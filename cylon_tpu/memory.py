"""HBM accounting + comm-buffer budget.

The reference's memory layer (reference: cpp/src/cylon/ctx/
memory_pool.hpp:25-66 `MemoryPool`, arrow_memory_pool_utils.hpp:25-63
`ProxyMemoryPool`/`ToArrowPool`) adapts a user pool into Arrow allocations.
On TPU the allocator is the XLA runtime's HBM arena, so the pool's role
becomes *accounting and budgeting*: report live/peak HBM per device and
hand the shuffle a comm-buffer budget so blockwise exchange sizes its
rounds to fit (the reference's analog: ArrowAllocator feeding receive
buffers from the pool, arrow_all_to_all.cpp:234-247).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

# the knob registry is the one sanctioned telemetry import for base
# leaves: knobs.py itself imports only the stdlib, and nothing in
# telemetry imports memory, so no cycle. Note the carve-out is a
# DEPENDENCY statement, not an import-cost one — binding the submodule
# still executes the telemetry package __init__ (spans/metrics/etc.),
# which is fine because cylon_tpu/__init__ pulls all of that on any
# entry into the package anyway.
from .telemetry.knobs import default as _knob_default, get as _knob_get

# HBM per chip when the runtime hides memory_stats (tunneled backends —
# the axon platform returns None): v5e carries 16 GiB. Overridable via
# CYLON_HBM_BYTES. Without this fallback the >HBM routing guards
# (join_blocked auto-engage, shuffle comm budget) silently disarm and a
# beyond-memory join OOMs instead of chunking.
DEFAULT_TPU_HBM_BYTES = _knob_default("CYLON_HBM_BYTES")


class MemoryPool:
    """Per-context HBM accounting over the mesh's local devices.

    ``comm_fraction`` bounds the portion of free HBM the shuffle may spend
    on in-flight exchange buffers (see parallel/shuffle.exchange)."""

    def __init__(self, devices, comm_fraction: float = 0.25):
        self._devices = [d for d in devices
                         if _stats(d) is not None]
        self.comm_fraction = comm_fraction
        self._fallback_limit = None
        # monotonic high-water mark over snapshot() observations — the
        # only peak signal on backends that hide memory_stats (axon
        # tunnels, the CPU test platform): without it, span hbm_peak
        # attrs and crash-dump watermarks silently read 0 there
        self._peak_seen = 0
        # external live-bytes source (duck-typed zero-arg callable —
        # the telemetry ledger's tracked-table total; memory.py stays a
        # base-layer leaf and never imports telemetry). Consulted only
        # when no local device exposes memory_stats.
        self._external_live: Optional[Callable[[], int]] = None
        if not self._devices and any(
                getattr(d, "platform", "") in ("tpu", "axon")
                for d in devices):
            self._fallback_limit = int(_knob_get("CYLON_HBM_BYTES"))

    def set_external_source(self, fn: Optional[Callable[[], int]]) -> None:
        """Register a fallback live-bytes provider (the telemetry
        ledger's ``live_bytes``) used when the runtime hides per-device
        memory stats — self-accounting instead of blindness."""
        self._external_live = fn

    def snapshot(self) -> Tuple[int, int, int]:
        """``(bytes_in_use, peak_bytes, bytes_limit)`` summed over local
        devices, with ONE ``memory_stats`` call per device (the
        bytes_allocated/peak_bytes/bytes_limit trio used to pay three).
        When every device hides its stats, ``bytes_in_use`` falls back
        to the external (ledger) source and ``peak_bytes`` to the
        pool's own monotonic high-water mark over those observations —
        the fix for hbm_peak reading 0 on tunneled backends."""
        used = peak = limit = 0
        seen = False
        for d in self._devices:
            s = _stats(d)
            if s is None:
                continue
            seen = True
            used += s.get("bytes_in_use", 0) or 0
            peak += s.get("peak_bytes_in_use", 0) or 0
            limit += s.get("bytes_limit", 0) or 0
        if not seen:
            if self._external_live is not None:
                try:
                    used = int(self._external_live())
                except Exception:  # pragma: no cover - defensive  # cylint: disable=errors/broad-swallow — broken external source reads as 0 live bytes
                    used = 0
            limit = self._fallback_limit or 0
        self._peak_seen = max(self._peak_seen, used)
        return used, max(peak, self._peak_seen), limit

    def bytes_allocated(self) -> int:
        """Live HBM across local mesh devices; ledger-tracked bytes when
        the backend hides memory_stats (0 with no external source)."""
        return self.snapshot()[0]

    def peak_bytes(self) -> int:
        return self.snapshot()[1]

    def bytes_limit(self) -> int:
        return self.snapshot()[2]

    def available_bytes(self) -> Optional[int]:
        """Free HBM on the tightest local device; the static chip limit
        when the backend hides stats (live usage unknowable there, so
        routing guards compare against the full chip); None when not a
        TPU at all."""
        per = []
        for d in self._devices:
            s = _stats(d)
            if s is None:
                continue
            limit, used = s.get("bytes_limit"), s.get("bytes_in_use")
            if limit:
                per.append(limit - (used or 0))
        if per:
            return min(per)
        return self._fallback_limit

    def comm_budget_bytes(self) -> Optional[int]:
        """Per-device byte budget for in-flight shuffle buffers."""
        avail = self.available_bytes()
        return None if avail is None else int(avail * self.comm_fraction)


def _stats(device) -> Optional[Dict]:
    try:
        return device.memory_stats()
    except Exception:  # cylint: disable=errors/broad-swallow — stats-hidden device: None IS the answer
        return None
