"""HBM accounting + comm-buffer budget.

The reference's memory layer (reference: cpp/src/cylon/ctx/
memory_pool.hpp:25-66 `MemoryPool`, arrow_memory_pool_utils.hpp:25-63
`ProxyMemoryPool`/`ToArrowPool`) adapts a user pool into Arrow allocations.
On TPU the allocator is the XLA runtime's HBM arena, so the pool's role
becomes *accounting and budgeting*: report live/peak HBM per device and
hand the shuffle a comm-buffer budget so blockwise exchange sizes its
rounds to fit (the reference's analog: ArrowAllocator feeding receive
buffers from the pool, arrow_all_to_all.cpp:234-247).
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

# HBM per chip when the runtime hides memory_stats (tunneled backends —
# the axon platform returns None): v5e carries 16 GiB. Overridable via
# CYLON_HBM_BYTES. Without this fallback the >HBM routing guards
# (join_blocked auto-engage, shuffle comm budget) silently disarm and a
# beyond-memory join OOMs instead of chunking.
DEFAULT_TPU_HBM_BYTES = 16 * (1 << 30)


class MemoryPool:
    """Per-context HBM accounting over the mesh's local devices.

    ``comm_fraction`` bounds the portion of free HBM the shuffle may spend
    on in-flight exchange buffers (see parallel/shuffle.exchange)."""

    def __init__(self, devices, comm_fraction: float = 0.25):
        self._devices = [d for d in devices
                         if _stats(d) is not None]
        self.comm_fraction = comm_fraction
        self._fallback_limit = None
        if not self._devices and any(
                getattr(d, "platform", "") in ("tpu", "axon")
                for d in devices):
            self._fallback_limit = int(os.environ.get(
                "CYLON_HBM_BYTES", DEFAULT_TPU_HBM_BYTES))

    def bytes_allocated(self) -> int:
        """Live HBM across local mesh devices (0 when the backend does not
        expose memory_stats, e.g. the CPU test platform)."""
        return sum(s.get("bytes_in_use", 0)
                   for d in self._devices if (s := _stats(d)) is not None)

    def peak_bytes(self) -> int:
        return sum(s.get("peak_bytes_in_use", 0)
                   for d in self._devices if (s := _stats(d)) is not None)

    def bytes_limit(self) -> int:
        return sum(s.get("bytes_limit", 0)
                   for d in self._devices if (s := _stats(d)) is not None)

    def available_bytes(self) -> Optional[int]:
        """Free HBM on the tightest local device; the static chip limit
        when the backend hides stats (live usage unknowable there, so
        routing guards compare against the full chip); None when not a
        TPU at all."""
        per = []
        for d in self._devices:
            s = _stats(d)
            if s is None:
                continue
            limit, used = s.get("bytes_limit"), s.get("bytes_in_use")
            if limit:
                per.append(limit - (used or 0))
        if per:
            return min(per)
        return self._fallback_limit

    def comm_budget_bytes(self) -> Optional[int]:
        """Per-device byte budget for in-flight shuffle buffers."""
        avail = self.available_bytes()
        return None if avail is None else int(avail * self.comm_fraction)


def _stats(device) -> Optional[Dict]:
    try:
        return device.memory_stats()
    except Exception:
        return None
