"""The concurrent query service tier — the TOP of the cylon_tpu stack.

Turns the one-blocking-``collect()``-at-a-time library into a service
(ROADMAP item 2): many LazyTable queries submitted at once, per-tenant
fair-share queueing (deficit round-robin), dispatch-time admission
against the ledger-tracked live HBM, typed backpressure before
enqueue, and a plan/fingerprint cache so repeated query shapes skip
optimization and re-hit the compiled-kernel memos.

* ``scheduler`` — :class:`QueryService` / :class:`QueryTicket`: the
  async submission surface and the single executor worker (device
  execution stays serialized; host-side optimize/preflight pipelines
  on the submitters' threads).
* ``plancache`` — the structural plan fingerprint and the bounded LRU
  of optimized plans, shared between the service and library mode.
* ``obs_http`` — the live operational surface: a stdlib HTTP endpoint
  (``CYLON_OBS_PORT``) serving /metrics (Prometheus scrape), /healthz
  (worker liveness + queue depths + pool watermarks), /queries (the
  structured query-log ring) and /slo (per-tenant SLO state).

Importing this package wires the plan cache into ``plan.lazy``'s
late-bound optimize memo (the hook keeps plan/ from importing
service/ — the ``below-service`` layering contract), so even plain
``LazyTable.collect()`` loops skip re-optimizing repeated shapes.

Layering (analysis/layering.py ``service-top``): this package imports
only plan/, resilience/, telemetry/ and status — never device
machinery (ops/parallel/data/io); execution goes through plan/'s
executor seam. Nothing below service may import it back.

Full semantics: docs/service.md.
"""
from __future__ import annotations

from . import obs_http, plancache, scheduler
from .obs_http import ObsServer
from .plancache import PlanCache, fingerprint, global_cache
from .scheduler import QueryService, QueryTicket

# library-mode wiring: LazyTable.optimized()/execute() memoize through
# the global fingerprint cache from the moment the package imports
plancache.install()

__all__ = [
    "ObsServer", "PlanCache", "QueryService", "QueryTicket",
    "fingerprint", "global_cache", "obs_http", "plancache",
    "scheduler",
]
