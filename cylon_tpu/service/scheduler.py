"""Concurrent query scheduler: multi-tenant fair-share queueing over
LazyTable queries.

Everything below this module runs ONE blocking ``collect()`` at a
time; this is the tier that turns the library into a service (ROADMAP
item 2, the "millions of users" tier). Submitted queries enter
per-tenant FIFO queues; a **deficit-round-robin** sweep over tenants
picks the next query (cost = the planner's pre-flight byte estimate,
so one tenant's huge joins cannot starve another's cheap lookups);
a single executor worker thread drains the pick.

Pipelining discipline: **device execution stays serialized** — JAX
dispatch through one mesh is not concurrency-safe, and interleaving
two queries' collectives would deadlock the virtual mesh — but the
expensive HOST work pipelines around it: ``submit()`` runs
optimization (through the plan/fingerprint cache, service/plancache)
and the pre-flight estimates on the CALLER's thread, concurrently with
whatever the worker is executing. Admission is decided by the worker
at DISPATCH time, so it sees the ledger-tracked live HBM of the
queries that actually ran before it (the pool's ``comm_budget_bytes``
nets out ``ledger.live_bytes()`` — held results shrink the budget the
next query is admitted against), not a static snapshot from submit
time.

Backpressure before queueing: once the total queue depth reaches
``CYLON_SERVICE_QUEUE_MAX`` (default 256), ``submit()`` raises a typed
:class:`CylonResourceExhausted` BEFORE enqueue and records the
rejection — with its tenant — in the flight recorder's admission ring,
so a load-shedding service leaves the same forensic trail as an
admission-controller shed.

Every query's fate is observable:

* ``cylon_service_queue_depth{tenant=}``   live queue depth gauges
* ``cylon_service_wait_seconds``           submit→dispatch histogram
* ``cylon_queries_total{tenant=,outcome=}`` ok / shed / error / timeout
* the tenant (+ query id + service name) rides every ROOT span the
  query opens (``telemetry.root_attrs``), so EXPLAIN ANALYZE trees,
  flight-ring entries and crash dumps all say whose query it was;
* admission decisions are recorded with the tenant label
  (``resilience.admission.record(decision, tenant=)``).

Env knobs: ``CYLON_SERVICE_QUEUE_MAX`` (queue bound),
``CYLON_SERVICE_QUANTUM_BYTES`` (DRR quantum, default 1 MiB). See
docs/service.md for the full catalog and semantics.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import OrderedDict, deque
from typing import Deque, Dict, Optional

from ..plan import ir
from ..plan.executor import (execute as _execute,
                             execute_analyzed as _execute_analyzed)
from ..plan.report import calibrate_estimates, preflight_estimates
from ..resilience import admission as _admission
from ..resilience import retry as _retry
from ..status import (Code, CylonPlanError, CylonResourceExhausted,
                      CylonTimeoutError)
from ..telemetry import flight as _flight
from ..telemetry import knobs as _knobs
from ..telemetry import logger as _logger
from ..telemetry import metrics as _metrics
from ..telemetry import root_attrs as _root_attrs
from ..telemetry import stats as _stats
from . import plancache as _plancache

DEFAULT_QUEUE_MAX = _knobs.default("CYLON_SERVICE_QUEUE_MAX")
DEFAULT_QUANTUM_BYTES = _knobs.default("CYLON_SERVICE_QUANTUM_BYTES")

# submit→dispatch wait histogram bounds, in SECONDS (the default
# bucket set is ms-scaled for span latencies; queue waits span
# sub-millisecond drains to multi-second backlogs)
WAIT_BUCKETS_S = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0,
                  5.0, 30.0, 120.0)

OUTCOMES = ("ok", "shed", "error", "timeout")

_query_ids = itertools.count(1)


def queue_max() -> int:
    return _knobs.get("CYLON_SERVICE_QUEUE_MAX")


def quantum_bytes() -> int:
    return _knobs.get("CYLON_SERVICE_QUANTUM_BYTES")


class QueryTicket:
    """Future-style handle for one submitted query.

    ``result()`` blocks until the worker finishes the query and either
    returns its Table or re-raises the query's TYPED error (a shed
    raises :class:`CylonResourceExhausted`, a deadline expiry
    :class:`CylonTimeoutError` — the same taxonomy a direct
    ``collect()`` surfaces). ``outcome`` is one of ``ok | shed |
    error | timeout`` once done; ``wait_s`` the measured submit→
    dispatch queue wait; ``dispatch_seq`` the service-wide dispatch
    order (the scheduler-fairness observable the DRR tests pin)."""

    def __init__(self, query_id: int, tenant: str):
        self.query_id = query_id
        self.tenant = tenant
        self.outcome: Optional[str] = None
        self.wait_s: Optional[float] = None
        self.dispatch_seq: Optional[int] = None
        self._done = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None
        self._report = None

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise CylonTimeoutError(
                f"query {self.query_id} (tenant {self.tenant!r}) not "
                f"finished within {timeout} s")
        if self._error is not None:
            raise self._error
        return self._result

    def report(self, timeout: Optional[float] = None):
        """The EXPLAIN ANALYZE ``PlanReport`` (``analyze=True``
        submissions only; None otherwise). Blocks like ``result`` but
        never raises the query error — forensics stay readable for
        failed queries too."""
        self._done.wait(timeout)
        return self._report

    def _finish(self, outcome: str, result=None, error=None,
                report=None) -> None:
        self.outcome = outcome
        self._result = result
        self._error = error
        self._report = report
        self._done.set()

    def __repr__(self):
        state = self.outcome or ("queued" if not self._done.is_set()
                                 else "done")
        return (f"QueryTicket(id={self.query_id}, "
                f"tenant={self.tenant!r}, {state})")


class _Job:
    __slots__ = ("ticket", "tenant", "root", "stats", "est", "cost",
                 "ctx", "analyze", "deadline_s", "t_submit",
                 "cache_doc")

    def __init__(self, ticket, tenant, root, stats, est, cost, ctx,
                 analyze, deadline_s, cache_doc=None):
        self.ticket = ticket
        self.tenant = tenant
        self.root = root
        self.stats = stats
        self.est = est
        self.cost = cost
        self.ctx = ctx
        self.analyze = analyze
        self.deadline_s = deadline_s
        self.t_submit = time.monotonic()
        # plan-cache fate from the submit thread's optimize() —
        # {"plan_fp", "plan_cache"} — stamped onto the query's root
        # span for the structured query log
        self.cache_doc = cache_doc or {}


def _job_cost(est: dict, root: ir.PlanNode) -> int:
    """A query's DRR cost: the sum of its ALLOCATING node estimates
    (Scans excluded — borrowed inputs are history, not work), floored
    at 1 so estimate-free plans still round-robin."""
    total = 0
    for n in ir.walk(root):
        if n.kind == "scan":
            continue
        b = est.get(id(n), {}).get("bytes")
        if b:
            total += int(b)
    return max(total, 1)


class QueryService:
    """The concurrent query service: submit many LazyTable queries,
    get :class:`QueryTicket` futures back; one worker thread drains
    the per-tenant queues under deficit round-robin.

    ``start=False`` builds the service paused (submissions queue but
    nothing executes) — the chaos drill uses it to make dispatch order
    a pure function of the submission sequence. ``close()`` drains the
    remaining queue and joins the worker; the service is also a
    context manager (``with QueryService() as svc: ...``)."""

    def __init__(self, name: str = "cylon", start: bool = True):
        self.name = name
        self._cv = threading.Condition()
        self._queues: "OrderedDict[str, Deque[_Job]]" = OrderedDict()
        self._deficit: Dict[str, float] = {}
        self._last_served: Optional[str] = None
        self._depth = 0
        self._dispatched = 0
        self._active: Optional[_Job] = None
        self._closed = False
        self._worker: Optional[threading.Thread] = None
        self._obs = None               # obs_http.ObsServer when armed
        if start:
            self.start()

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        """Start the executor worker (idempotent) — and, when
        ``CYLON_OBS_PORT`` is nonzero, the observability HTTP endpoint
        (``service/obs_http.py``) serving this service's /metrics,
        /healthz, /queries, /slo and /stats on a daemon thread. When
        ``CYLON_STATS_PATH`` names a saved statistics snapshot, the
        warehouse warm-starts from it BEFORE the first dispatch, so a
        fresh replica's repeat-shape queries get measured-calibrated
        admission from query 1 (a corrupt snapshot is quarantined —
        never blocks startup)."""
        with self._cv:
            if self._worker is not None or self._closed:
                return
        # warm-start outside the lock (file IO must not block
        # submitters); the worker is not running yet, so no dispatch
        # precedes the load — and load() merges via setdefault, so a
        # racing second start() loading again is harmless
        _stats.load()
        obs = None
        with self._cv:
            if self._worker is not None or self._closed:
                return
            self._worker = threading.Thread(
                target=self._run, name=f"cylon-service-{self.name}",
                daemon=True)
            self._worker.start()
            port = _knobs.get("CYLON_OBS_PORT")
            if port and self._obs is None:
                from . import obs_http as _obs_http

                obs = self._obs = _obs_http.ObsServer(service=self,
                                                      port=port)
        if obs is not None:
            # bind+serve OUTSIDE the lock: a bad port must not wedge
            # the scheduler, and the obs thread scrapes health() which
            # takes this same lock
            try:
                obs.start()
            except OSError:
                _logger.exception(
                    "service %s: observability endpoint failed to "
                    "bind port %s — continuing without it",
                    self.name, obs.requested_port)
                with self._cv:
                    self._obs = None
                return
            # a close() may have raced this start() and discarded the
            # handle before the bind — it had nothing to stop then, so
            # stop the now-live endpoint here or it outlives close()
            with self._cv:
                leaked = obs if self._closed or self._obs is not obs \
                    else None
            if leaked is not None:
                leaked.close()

    def close(self, timeout: Optional[float] = None) -> None:
        """Drain the remaining queue, stop the worker, reject further
        submissions. Closing a PAUSED service (built with
        ``start=False``, never started) has no worker to drain the
        queue — its still-queued tickets finish typed
        (:class:`CylonPlanError`, outcome ``error``) instead of
        hanging their waiters forever."""
        orphans = []
        with self._cv:
            already_closed = self._closed
            self._closed = True
            worker = self._worker
            obs, self._obs = self._obs, None
            if worker is None:
                for t, q in self._queues.items():
                    orphans.extend(q)
                    q.clear()
                    self._depth_gauge(t).set(0)
                self._depth = 0
            self._cv.notify_all()
        for job in orphans:
            self._count_outcome(job.tenant, "error")
            job.ticket._finish("error", error=CylonPlanError(
                f"service {self.name!r} closed before query "
                f"{job.ticket.query_id} (tenant {job.tenant!r}) was "
                f"dispatched", code=Code.Invalid))
        if worker is not None:
            worker.join(timeout)
        if obs is not None:
            # after the worker: the endpoint stays scrapeable while
            # the drain finishes, then shuts down with its thread
            # joined (no leaked obs thread past close())
            obs.close(timeout)
        # snapshot the statistics warehouse AFTER the drain: every
        # query this service ran has fed its digest by now, so the
        # file the next replica warm-starts from carries the full run
        # (no-op unless CYLON_STATS_PATH is set; never raises). Only
        # a STARTED service saves — start() is what merged the
        # existing snapshot into the store, so a never-started (or
        # re-)close() must not rotate a learned warm-start file aside
        # and replace it with a near-empty one
        if worker is not None and not already_closed:
            _stats.save()

    def __enter__(self) -> "QueryService":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- submission -----------------------------------------------------

    def submit(self, query, tenant: str = "default",
               analyze: bool = False,
               deadline_s: Optional[float] = None) -> QueryTicket:
        """Queue one LazyTable query for the ``tenant``; returns its
        ticket immediately.

        The host-side heavy lifting happens HERE, on the caller's
        thread — optimization through the plan/fingerprint cache and
        the pre-flight byte estimates — pipelined against whatever the
        worker is executing. Raises :class:`CylonResourceExhausted`
        (typed backpressure) when the service queue is full, BEFORE
        the query is queued or any device work happens."""
        if not hasattr(query, "optimized"):
            raise CylonPlanError(
                f"submit() takes a LazyTable-style query (got "
                f"{type(query).__name__})")
        with self._cv:
            if self._closed:
                raise CylonPlanError(
                    f"service {self.name!r} is closed",
                    code=Code.Invalid)
        qid = next(_query_ids)
        ticket = QueryTicket(qid, tenant)
        # host-side prepare (no lock, no device work): optimize via the
        # fingerprint cache + pre-flight estimates over the result.
        # The cache fate (fp, hit/miss) is read back thread-locally —
        # this thread's optimize, not a racing submitter's — and rides
        # the job into the query-log digest.
        _plancache.clear_last_event()
        root, stats = query.optimized()
        cache_doc = dict(_plancache.last_event() or {})
        if not cache_doc.get("plan_fp"):
            # cache disabled/bypassed: derive the LOGICAL-plan
            # fingerprint directly so the digest and the statistics
            # warehouse still key this query (same key space as the
            # cache — drift eviction must match it)
            fp_fn = getattr(query, "plan_fingerprint", None)
            if fp_fn is not None:
                cache_doc["plan_fp"] = fp_fn()
        est = preflight_estimates(root)
        cost = _job_cost(est, root)
        ctx = getattr(query, "context", None)
        job = _Job(ticket, tenant, root, stats, est, cost, ctx,
                   analyze, deadline_s, cache_doc=cache_doc)
        with self._cv:
            if self._closed:
                raise CylonPlanError(
                    f"service {self.name!r} is closed",
                    code=Code.Invalid)
            cap = queue_max()
            if self._depth >= cap:
                # typed backpressure BEFORE enqueue — and the same
                # forensic trail as an admission shed, tenant included
                _flight.record_admission({
                    "action": "shed", "tenant": tenant,
                    "query_id": qid, "est_bytes": cost,
                    "budget": None,
                    "reason": f"service queue full (depth "
                              f"{self._depth} >= "
                              f"CYLON_SERVICE_QUEUE_MAX {cap})"})
                self._count_outcome(tenant, "shed")
                raise CylonResourceExhausted(
                    f"service {self.name!r} queue full: depth "
                    f"{self._depth} >= CYLON_SERVICE_QUEUE_MAX {cap} "
                    f"(tenant {tenant!r}, query {qid})")
            q = self._queues.get(tenant)
            if q is None:
                q = self._queues[tenant] = deque()
                self._deficit.setdefault(tenant, 0.0)
            q.append(job)
            self._depth += 1
            self._depth_gauge(tenant).set(len(q))
            self._cv.notify_all()
        return ticket

    def drain(self, timeout: Optional[float] = None) -> None:
        """Block until every queued query has been dispatched AND
        finished; raises :class:`CylonTimeoutError` on timeout. Starts
        the worker if the service was built paused."""
        self.start()
        deadline = None if timeout is None else \
            time.monotonic() + timeout
        with self._cv:
            while self._depth > 0 or self._active is not None:
                rem = None if deadline is None else \
                    deadline - time.monotonic()
                if rem is not None and rem <= 0:
                    raise CylonTimeoutError(
                        f"service drain timed out with {self._depth} "
                        f"queued + "
                        f"{1 if self._active is not None else 0} "
                        f"running")
                self._cv.wait(rem)

    def depth(self, tenant: Optional[str] = None) -> int:
        with self._cv:
            if tenant is None:
                return self._depth
            q = self._queues.get(tenant)
            return len(q) if q is not None else 0

    def health(self) -> dict:
        """One lock-consistent liveness snapshot — the observability
        endpoint's ``/healthz`` payload: worker liveness, total and
        per-tenant queue depths, the in-flight query, dispatch
        count."""
        with self._cv:
            worker = self._worker
            active = self._active
            doc = {
                "service": self.name,
                "closed": self._closed,
                "worker_alive": worker is not None and
                worker.is_alive(),
                "queue_depth": self._depth,
                "queue_depth_by_tenant": {
                    t: len(q) for t, q in self._queues.items()},
                "dispatched": self._dispatched,
                "active": None if active is None else {
                    "query_id": active.ticket.query_id,
                    "tenant": active.tenant},
            }
        return doc

    # -- scheduling (deficit round-robin) -------------------------------

    def _depth_gauge(self, tenant: str):
        return _metrics.REGISTRY.gauge("cylon_service_queue_depth",
                                       {"tenant": tenant})

    def _count_outcome(self, tenant: str, outcome: str) -> None:
        _metrics.REGISTRY.counter(
            "cylon_queries_total",
            {"tenant": tenant, "outcome": outcome}).inc()

    def _pick_locked(self) -> Optional[_Job]:
        """One DRR pick (caller holds the lock): sweep active tenants
        cyclically starting after the last-served one; each visit adds
        a quantum to the tenant's deficit; the first tenant whose
        deficit covers its head query's cost is served. Computed in
        closed form (no per-round loop), so a pathological byte
        estimate cannot spin the scheduler. An emptied queue forfeits
        its residual deficit — the classic DRR anti-hoarding rule."""
        active = [t for t, q in self._queues.items() if q]
        if not active:
            return None
        # rotation: continue AFTER the tenant served last
        if self._last_served in active:
            i = active.index(self._last_served) + 1
            active = active[i:] + active[:i]
        q = float(quantum_bytes())
        best = None  # ((rounds, order_idx), tenant)
        for idx, t in enumerate(active):
            need = self._queues[t][0].cost - self._deficit[t]
            rounds = 1 if need <= q else -int(-need // q)  # ceil, >= 1
            key = (rounds, idx)
            if best is None or key < best[0]:
                best = (key, t)
        (r_serve, i_serve), serve = best
        # fast-forward every tenant's deficit by the visits it received
        # before the serving visit in the cyclic sweep
        for idx, t in enumerate(active):
            visits = r_serve if idx <= i_serve else r_serve - 1
            if visits > 0:
                self._deficit[t] += visits * q
        job = self._queues[serve].popleft()
        self._deficit[serve] = max(
            self._deficit[serve] - job.cost, 0.0)
        if not self._queues[serve]:
            self._deficit[serve] = 0.0
        self._last_served = serve
        self._depth -= 1
        self._depth_gauge(serve).set(len(self._queues[serve]))
        return job

    # -- the executor worker --------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cv:
                job = self._pick_locked()
                while job is None:
                    if self._closed:
                        return
                    self._cv.wait()
                    job = self._pick_locked()
                self._active = job
                self._dispatched += 1
                job.ticket.dispatch_seq = self._dispatched
            try:
                self._dispatch(job)
            finally:
                with self._cv:
                    self._active = None
                    self._cv.notify_all()

    def _dispatch(self, job: _Job) -> None:
        """Admit, then execute, one query; deliver its fate to the
        ticket. Never raises — the worker must survive every query."""
        ticket = job.ticket
        wait_s = time.monotonic() - job.t_submit
        ticket.wait_s = wait_s
        _metrics.REGISTRY.histogram(
            "cylon_service_wait_seconds",
            buckets=WAIT_BUCKETS_S).observe(wait_s)
        # dispatch-time admission: the budget is live-HBM aware (the
        # pool nets out ledger-tracked bytes), so queries admitted now
        # see the memory the PREVIOUS queries' held results still pin
        pool = getattr(job.ctx, "memory_pool", None) \
            if job.ctx is not None else None
        budget = _admission.effective_budget(pool)
        world = job.ctx.get_world_size() \
            if job.ctx is not None and job.ctx.is_distributed() else 1
        # calibrate at DISPATCH time, not submit time: a queued query
        # admitted now sees the statistics the queries ahead of it
        # just taught the warehouse (idempotent — the executor's
        # _preflight skips nodes already calibrated)
        calibrate_estimates(job.root, job.est, world)
        decision = _admission.decide(list(ir.walk(job.root)), job.est,
                                     budget, world)
        outcome, result, report, error = "error", None, None, None
        try:
            with _root_attrs(tenant=job.tenant,
                             query_id=ticket.query_id,
                             service=self.name,
                             wait_s=round(wait_s, 6),
                             admission=decision.action,
                             est_bytes=decision.est_bytes,
                             est_source=decision.est_source,
                             **job.cache_doc):
                # inside root_attrs so the non-admit plan.admission
                # marker span record() emits carries the tenant label
                _admission.record(decision, tenant=job.tenant)
                _admission.enforce(decision)
                with _retry.query_deadline(job.deadline_s):
                    if job.analyze:
                        result, report = _execute_analyzed(
                            job.root, job.ctx, stats=job.stats,
                            decision=decision, est=job.est)
                    else:
                        result = _execute(job.root, job.ctx,
                                          decision=decision,
                                          est=job.est)
            outcome = "ok"
        except CylonTimeoutError as e:
            outcome, error = "timeout", e
            _logger.warning("service %s: query %d (tenant %s) timed "
                            "out: %s", self.name, ticket.query_id,
                            job.tenant, e)
        except CylonResourceExhausted as e:
            outcome, error = "shed", e
            _logger.warning("service %s: query %d (tenant %s) shed: "
                            "%s", self.name, ticket.query_id,
                            job.tenant, e)
        except Exception as e:
            outcome, error = "error", e
            _logger.warning("service %s: query %d (tenant %s) failed: "
                            "%s: %s", self.name, ticket.query_id,
                            job.tenant, type(e).__name__, e)
        self._count_outcome(job.tenant, outcome)
        ticket._finish(outcome, result=result, error=error,
                       report=report)
