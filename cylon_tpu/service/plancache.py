"""Plan/fingerprint cache: repeated query shapes skip the optimizer.

A production service absorbing traffic from many users sees the same
HANDFUL of query shapes over and over — dashboards refresh, API
endpoints re-issue the same join+aggregate with fresh parameters. The
optimizer (plan/optimizer.py: four rewrite passes plus, in debug mode,
the witness verifier) re-derives the same physical plan every time;
worse, nothing memoizes it, so "millions of users" pay host-side plan
work per request. This module keys a bounded LRU of OPTIMIZED plans on
a **structural fingerprint** of the logical IR tree:

* **what the fingerprint covers** — node kinds, column schemas
  (names, dtypes, widths), join keys/type/algorithm, groupby
  keys/aggregates, sort keys/order, set-op kind, projection
  positions, the full filter expression (op + literal), each Scan's
  hash-placement witness *shape* (positions + dtypes + world — the
  one Scan fact the optimizer's elision pass keys on), and the world
  size. Names are included so a hit can never render ANOTHER query's
  column names in EXPLAIN trees or admission forensics.
* **what it deliberately excludes** — table IDENTITIES (object ids,
  registry ids, row contents). Two equal-shape queries over different
  tables fingerprint identically: positions were bound at
  construction, so the cached physical plan is correct for BOTH.

Cache entries are stored as **stripped templates**: every Scan's table
reference and registry id is nulled before insertion, so the cache
never pins device buffers (the ledger/leak discipline of PR 5 holds).
A hit deep-copies the template and REBINDS the incoming query's Scan
tables in walk order (the optimizer never reorders or duplicates
scans, so the order is stable by construction).

Verification discipline: a cache must never launder an unverified
plan. Inserts go through ``optimizer.optimize``, whose
``CYLON_TPU_VERIFY_PLANS=1`` debug assert verifies the plan at insert
time; hits RE-verify the rebound plan under the same flag, so a
hand-poisoned (or future-bug-corrupted) entry is rejected with a typed
:class:`CylonPlanError` — and evicted — instead of silently executing
an unsound elision.

Adaptive staleness (PR 15): each entry records the statistics-warehouse
EPOCH and the optimizer's adaptive DECISION VECTOR (broadcast/salt
choices, plan/optimizer.decision_vector) it was optimized under. A hit
whose epoch moved re-checks the vector against the live warehouse:
unchanged decisions refresh the entry (still a hit); changed ones —
a drift event, a newly-qualified build side, a flipped knob — evict
and re-optimize (``cylon_plan_cache_stale_total``), so a cached
template can never replay an algorithm choice its evidence no longer
supports.

Metrics: ``cylon_plan_cache_{hits,misses,evictions}_total``. Because a
hit re-fires the same lowerings, the same ``counted_cache`` kernel
factories re-hit their memo — the PR-4 profiler's
``cylon_kernel_compile_seconds`` shows exactly which compilations the
cache amortizes.

Library-mode wiring: :func:`install` registers :func:`memo_optimize`
as ``plan.lazy``'s late-bound optimize hook (the same leaf-hook
pattern as ``metrics.set_factory_fault_hook``) — plan/ never imports
service/, the ``below-service`` layering contract holds, and even a
bare ``LazyTable.collect()`` loop skips re-optimization on repeated
shapes. ``CYLON_PLAN_CACHE_MAX`` bounds the cache (default 64);
``0`` disables it entirely.
"""
from __future__ import annotations

import copy
import threading
from collections import OrderedDict
from contextlib import contextmanager
from dataclasses import replace as _dc_replace
from typing import Optional, Tuple

from ..plan import ir
# the structural fingerprint moved to plan/fingerprint.py (the
# statistics warehouse keys by the same function, from below the
# service tier); re-exported here unchanged — this module remains the
# semantics owner of what the key covers (docstring above)
from ..plan.fingerprint import FP_VERSION, fingerprint  # noqa: F401
from ..plan.optimizer import PlanStats, adaptive_knobs as _adaptive_knobs, \
    decision_vector as _decision_vector, optimize as _optimize
from ..plan.verify import check_plan as _check_plan
from ..telemetry import knobs as _knobs
from ..telemetry import metrics as _metrics
from ..telemetry import spans as _spans
from ..telemetry import stats as _stats

DEFAULT_CACHE_MAX = _knobs.default("CYLON_PLAN_CACHE_MAX")


def cache_max() -> int:
    return _knobs.get("CYLON_PLAN_CACHE_MAX")


# ---------------------------------------------------------------------------
# the bounded LRU of optimized-plan templates
# ---------------------------------------------------------------------------


def _scans(root: ir.PlanNode):
    return [n for n in ir.walk(root) if isinstance(n, ir.Scan)]


def _strip_template(root: ir.PlanNode) -> ir.PlanNode:
    """Deep-copy an optimized plan and null every Scan's table handle —
    a cached entry must never pin device buffers or registry ids."""
    tmpl = copy.deepcopy(root)
    for s in _scans(tmpl):
        s.table = None
        s.table_id = None
    return tmpl


class PlanCache:
    """Fingerprint → (optimized-plan template, PlanStats), bounded LRU.

    ``optimize(root, world)`` is the one entry point: a hit rebinds the
    template's scans to ``root``'s tables (and re-verifies under
    ``CYLON_TPU_VERIFY_PLANS=1``); a miss runs the real optimizer and
    inserts a stripped template. Thread-safe — service submitters
    prepare plans concurrently with the executor worker."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, tuple]" = OrderedDict()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def _counter(self, event: str):
        return _metrics.REGISTRY.counter(
            f"cylon_plan_cache_{event}_total")

    def optimize(self, root: ir.PlanNode, world: int
                 ) -> Tuple[ir.PlanNode, PlanStats]:
        cap = cache_max()
        if cap <= 0 or _bypassed():
            _set_last_event(None, "bypass")
            return _optimize(root, world)
        fp = fingerprint(root, world)
        with self._lock:
            hit = self._entries.get(fp)
            if hit is not None:
                self._entries.move_to_end(fp)
        if hit is not None and self._fresh(fp, hit, world):
            out = self._rebind(fp, hit, root, world)
            if out is not None:
                self._counter("hits").inc()
                _set_last_event(fp, "hit")
                return out
            # structural mismatch (defensive — the fingerprint covers
            # scan layout, so this means a corrupted entry): drop it
            # and fall through to a fresh optimize
            self.invalidate(fp)
        self._counter("misses").inc()
        _set_last_event(fp, "miss")
        opt_root, stats = _optimize(root, world)
        # the template records the statistics EPOCH and the adaptive
        # decision vector it was optimized under — the staleness
        # signal (_fresh) that keeps a cached algorithm choice from
        # outliving its evidence
        epoch = _stats.epoch()
        vec = _decision_vector(opt_root, world)
        with self._lock:
            self._entries[fp] = (_strip_template(opt_root), stats,
                                 epoch, vec)
            self._entries.move_to_end(fp)
            while len(self._entries) > cap:
                self._entries.popitem(last=False)
                self._counter("evictions").inc()
        return opt_root, stats

    def _fresh(self, fp: str, entry: tuple, world: int) -> bool:
        """Is a cached template's ADAPTIVE shape still what the
        warehouse would decide today? Fast path: the stats epoch (and
        the adaptive knobs) have not moved since the template was
        optimized — hit without recomputing anything. Otherwise
        recompute the decision vector over the template (decision
        fingerprints are algorithm-invariant, so the rewritten
        template resolves identically to the pre-rewrite tree): equal
        means the epoch bump concerned OTHER shapes — refresh the
        entry's epoch and hit; different means this template's
        algorithm choices are stale — evict, miss, re-optimize. A
        drift event therefore re-optimizes instead of replaying the
        stale choice, and a newly-qualified build side flips a warmed
        shape to broadcast without waiting for an LRU eviction."""
        tmpl, stats, epoch, vec = entry
        now_epoch = _stats.epoch()
        knobs_now = ("knobs",) + _adaptive_knobs()
        if epoch == now_epoch and vec and vec[0] == knobs_now:
            return True
        try:
            vec_now = _decision_vector(tmpl, world)
        except Exception:  # pragma: no cover - defensive
            _spans.logger.exception(
                "plan-cache staleness check failed for %s — evicting",
                fp[:12])
            self.invalidate(fp)
            self._counter("stale").inc()
            return False
        if vec_now == vec:
            with self._lock:
                cur = self._entries.get(fp)
                if cur is not None and cur[0] is tmpl:
                    self._entries[fp] = (tmpl, stats, now_epoch, vec)
            return True
        self.invalidate(fp)
        self._counter("stale").inc()
        return False

    def invalidate(self, fp: str) -> bool:
        """Drop one entry; True when something was actually removed."""
        with self._lock:
            return self._entries.pop(fp, None) is not None

    def _rebind(self, fp: str, entry: tuple, root: ir.PlanNode,
                world: int) -> Optional[Tuple[ir.PlanNode, PlanStats]]:
        """Instantiate a cached template for ``root``: deep-copy,
        rebind scan tables in walk order, and (in debug mode) re-run
        the witness verifier so a poisoned entry is rejected — evicted
        and raised as :class:`CylonPlanError` — never executed."""
        tmpl, stats = entry[0], entry[1]
        plan = copy.deepcopy(tmpl)
        dst, src = _scans(plan), _scans(root)
        if len(dst) != len(src):
            return None
        for d, s in zip(dst, src):
            d.table = s.table
            d.table_id = s.table_id
        if _knobs.get("CYLON_TPU_VERIFY_PLANS"):
            try:
                _check_plan(plan, world)
            except Exception:
                # a cache must never launder an unverified plan: drop
                # the poisoned entry, then surface the typed error
                self.invalidate(fp)
                raise
        return plan, _dc_replace(stats, notes=list(stats.notes))


# per-thread record of the most recent optimize()'s cache fate —
# (fingerprint, "hit" | "miss" | "bypass"). Thread-local, not global:
# service submitters optimize concurrently, and each needs ITS query's
# fate to stamp into the query-log digest (counter deltas would race).
_last_event = threading.local()


def _set_last_event(fp: Optional[str], cache: str) -> None:
    _last_event.doc = {"plan_fp": fp, "plan_cache": cache}


def last_event() -> Optional[dict]:
    """The calling thread's most recent optimize() cache fate
    (``{"plan_fp", "plan_cache"}``), or None — the scheduler reads it
    right after ``query.optimized()`` on the submit thread and stamps
    it onto the query's root attrs."""
    return getattr(_last_event, "doc", None)


def clear_last_event() -> None:
    _last_event.doc = None


# the process-global cache the library-mode memo and every
# QueryService share — one fingerprint space per process
_global = PlanCache()

# bypass depth (plancache.disabled()): bench baselines measure the
# uncached optimizer without disturbing the global cache's contents
_bypass = 0
_bypass_lock = threading.Lock()


def global_cache() -> PlanCache:
    return _global


def _bypassed() -> bool:
    return _bypass > 0  # cylint: disable=concurrency/lock-discipline — advisory GIL-atomic int read on the per-optimize fast path; the bench bypass tolerates one racing query either way


@contextmanager
def disabled():
    """Temporarily bypass the cache (hits AND inserts) — the bench's
    sequential-eager baseline measures the uncached optimizer cost."""
    global _bypass
    with _bypass_lock:
        _bypass += 1
    try:
        yield
    finally:
        with _bypass_lock:
            _bypass -= 1


def memo_optimize(root: ir.PlanNode, world: int
                  ) -> Tuple[ir.PlanNode, PlanStats]:
    """The ``plan.lazy`` optimize hook: route every LazyTable
    optimization through the global fingerprint cache."""
    return _global.optimize(root, world)


def _evict_on_drift(fp: str) -> None:
    """The statistics warehouse's drift-eviction hook: a measured
    distribution shift on a fingerprint means the cached optimized
    template was learned against a world that no longer exists — drop
    it so the next submission re-optimizes (and the store re-learns
    from fresh measurements). Counted only when an entry was actually
    removed — a disabled cache, an already-LRU-evicted entry, or a
    second drifted node of the same plan must not inflate the
    evictions series."""
    if _global.invalidate(fp):
        _metrics.REGISTRY.counter(
            "cylon_plan_cache_evictions_total").inc()


def install() -> None:
    """Register the global cache as plan/'s late-bound optimize memo
    and as the statistics warehouse's drift-eviction target
    (idempotent; called by ``cylon_tpu.service`` at import)."""
    from ..plan import lazy as _lazy

    _lazy.set_plan_memo(memo_optimize)
    _stats.set_plan_evict_hook(_evict_on_drift)
