"""The observability HTTP endpoint: /metrics, /healthz, /queries, /slo.

Everything PRs 3–7 measure — the metrics registry, the query flight
ring, per-tenant accounting — was reachable only in-process or
post-mortem; an operator of a running service had no way to scrape a
counter or ask "is tenant A inside its SLO" without attaching a
debugger. This module is the live surface: a stdlib ``http.server``
on a daemon thread (zero new dependencies, read-only by construction)
serving four routes:

* ``GET /metrics``  — the Prometheus v0.0.4 text dump
  (``export.prometheus_text``) over a lock-consistent registry
  snapshot (``MetricsRegistry.series`` materializes under the
  registry lock; histograms read their count group under each
  metric's own lock) — scrape-ready for a real Prometheus;
* ``GET /healthz``  — JSON liveness: scheduler worker alive, total and
  per-tenant queue depths (``QueryService.health()``), memory-pool
  watermarks; HTTP 200 while healthy, 503 once the worker is dead or
  the service closed (load balancers read the status code alone);
* ``GET /queries``  — the structured query log's in-memory digest ring
  (``telemetry/querylog.py``), newest last — ``tail -f`` for
  completed queries;
* ``GET /slo``      — per-tenant SLO state (``telemetry/slo.py``):
  latency quantile estimates, declared objective, remaining error
  budget;
* ``GET /stats``    — the query statistics warehouse
  (``telemetry/stats.py``): top-N plan/node fingerprints with
  observation counts and EWMAs, per-node-kind q-error p50/p95
  (estimate accuracy), recent drift events, live knob config —
  "what has admission learned, and is it still true".

Lifecycle: ``QueryService.start()`` arms it when ``CYLON_OBS_PORT`` is
nonzero (0 — the default — disables it); ``ObsServer`` can also be
started standalone against any service-like object (or none: the
telemetry routes work without a scheduler). ``close()`` shuts the
server down and JOINS the serve thread, so a closed service leaves no
thread behind.

Threading: requests are served on ``ThreadingHTTPServer`` daemon
threads, concurrent with submitters, the executor worker, GC
finalizers — everything. The routes therefore only READ, through
already-locked surfaces, and the handler entry points are declared in
the concurrency checker's domain catalog
(``analysis/concurrency.DECLARED_ENTRIES``) so the race detector
closes over them like any other thread domain.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..telemetry import export as _export
from ..telemetry import knobs as _knobs
from ..telemetry import logger as _logger
from ..telemetry import metrics as _metrics
from ..telemetry import querylog as _querylog
from ..telemetry import slo as _slo
from ..telemetry import stats as _stats

DEFAULT_OBS_PORT = _knobs.default("CYLON_OBS_PORT")

ROUTES = ("/metrics", "/healthz", "/queries", "/slo", "/stats")


def render_metrics() -> str:
    """The /metrics payload: the Prometheus text dump over a
    lock-consistent registry snapshot."""
    return _export.prometheus_text()


def render_healthz(service=None) -> dict:
    """The /healthz payload: scheduler liveness + queue depths (when a
    service is attached) and memory-pool watermarks. ``ok`` is the
    single field a probe needs."""
    doc: dict = {"ok": True}
    if service is not None:
        sh = service.health()
        doc["service"] = sh
        doc["ok"] = bool(sh["worker_alive"]) and not sh["closed"]
    pool = _metrics.get_memory_pool()
    if pool is not None:
        try:
            used, peak, limit = pool.snapshot()
            doc["pool"] = {"bytes_in_use": int(used),
                           "peak_bytes": int(peak),
                           "bytes_limit": int(limit)}
        except Exception:  # pragma: no cover - defensive  # cylint: disable=errors/broad-swallow — watermarks are optional health detail
            pass
    return doc


def render_queries() -> list:
    """The /queries payload: the query log's digest ring, oldest
    first."""
    return _querylog.recent()


def render_slo() -> dict:
    """The /slo payload: per-tenant SLO state."""
    return _slo.state()


def render_stats() -> dict:
    """The /stats payload: the statistics warehouse's state — top
    fingerprints, q-error quantiles, drift history."""
    return _stats.state()


class _ObsHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer carrying the service handle for the
    handler; request threads are daemons so a hung scrape can never
    block interpreter exit."""

    daemon_threads = True
    allow_reuse_address = True
    service = None


class _Handler(BaseHTTPRequestHandler):
    # requests are read-only GETs; every route renders through
    # already-locked telemetry surfaces (see module docstring)

    def do_GET(self) -> None:  # noqa: N802 - http.server API
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            if path == "/metrics":
                body = render_metrics().encode("utf-8")
                ctype = "text/plain; version=0.0.4; charset=utf-8"
                status = 200
            elif path == "/healthz":
                doc = render_healthz(self.server.service)
                body = json.dumps(doc, default=str,
                                  sort_keys=True).encode("utf-8")
                ctype = "application/json"
                status = 200 if doc["ok"] else 503
            elif path == "/queries":
                body = json.dumps(render_queries(), default=str,
                                  sort_keys=True).encode("utf-8")
                ctype = "application/json"
                status = 200
            elif path == "/slo":
                body = json.dumps(render_slo(), default=str,
                                  sort_keys=True).encode("utf-8")
                ctype = "application/json"
                status = 200
            elif path == "/stats":
                body = json.dumps(render_stats(), default=str,
                                  sort_keys=True).encode("utf-8")
                ctype = "application/json"
                status = 200
            else:
                body = json.dumps(
                    {"error": "unknown route",
                     "routes": list(ROUTES)}).encode("utf-8")
                ctype = "application/json"
                status = 404
        except Exception:
            _logger.exception("obs endpoint: %s failed", path)
            body = b'{"error": "internal"}'
            ctype = "application/json"
            status = 500
        try:
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            # scraper hung up mid-response — routine, not a failure
            _logger.debug("obs endpoint: client disconnected on %s",
                          path)

    def log_message(self, fmt, *args) -> None:
        # route http.server's per-request stderr lines to our logger
        # at DEBUG — a 1 Hz scraper must not spam a service's stderr
        _logger.debug("obs endpoint: " + fmt, *args)


class ObsServer:
    """The observability endpoint: bind, serve on a daemon thread,
    close. ``port=0`` asks the OS for an ephemeral port (``.port``
    reports the bound one) — the knob's 0 means *disabled* and is the
    caller's check (``QueryService.start`` never constructs one for
    port 0)."""

    def __init__(self, service=None, port: Optional[int] = None,
                 host: str = "127.0.0.1"):
        self.requested_port = _knobs.get("CYLON_OBS_PORT") \
            if port is None else int(port)
        self.host = host
        self._lock = threading.RLock()
        self._service = service
        self._server: Optional[_ObsHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        """The actually-bound TCP port (None before start())."""
        with self._lock:
            srv = self._server
        return srv.server_address[1] if srv is not None else None

    def url(self, route: str = "") -> str:
        return f"http://{self.host}:{self.port}{route}"

    def start(self) -> "ObsServer":
        """Bind and serve (idempotent). Raises OSError when the port
        cannot be bound — the caller decides whether that is fatal."""
        with self._lock:
            if self._server is not None:
                return self
            srv = _ObsHTTPServer((self.host, self.requested_port),
                                 _Handler)
            srv.service = self._service
            self._server = srv
            # the serve thread gets the server as an ARGUMENT, never
            # re-read through self: a close() racing this start()
            # nulls self._server, and a _serve that then skipped
            # serve_forever would leave close() blocked forever in
            # srv.shutdown() (which waits on an event only
            # serve_forever sets)
            self._thread = threading.Thread(
                target=self._serve, args=(srv,), name="cylon-obs",
                daemon=True)
            self._thread.start()
        _logger.info("obs endpoint serving on %s (routes: %s)",
                     self.url(), ", ".join(ROUTES))
        return self

    def _serve(self, srv: _ObsHTTPServer) -> None:
        srv.serve_forever(poll_interval=0.1)

    def close(self, timeout: Optional[float] = None) -> None:
        """Stop serving and JOIN the serve thread — after close() the
        concurrency domain sweep sees no live obs thread."""
        with self._lock:
            srv, self._server = self._server, None
            th, self._thread = self._thread, None
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        if th is not None:
            th.join(timeout)

    def __enter__(self) -> "ObsServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
