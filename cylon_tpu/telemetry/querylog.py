"""Structured query log: one digest per completed root query span.

Traces answer "what happened inside this query"; metrics answer "how
is the fleet doing"; crash dumps answer "why did it die". What an
operator tails day to day is the line BETWEEN them: one compact,
structured record per completed query, carrying the identifiers that
join the three worlds together — the query id and tenant (the trace
and crash-dump labels), the plan fingerprint (the plan-cache key), and
the aggregate signals a single query contributes to the metrics
(shuffle bytes/rows, retries, peak HBM, worst skew).

Implementation: a root-span close hook (``spans.add_root_hook``) that
fires for ``plan.query`` roots only — the plan executor wraps BOTH
execute paths in that root span, so every query produces exactly one
digest whether it ran through the service, a bare ``collect()``, or
``explain(analyze=True)``. Eager top-level ops (a direct
``distributed_join`` call) are operator phases, not queries, and stay
out of the log. The digest is assembled from the completed span tree —
which head sampling (telemetry/sampling.py) deliberately keeps in
memory — so a sampled-OUT query still logs a complete digest; the
``sampled`` field says whether its full trace was exported.

Two carriers:

* an **in-memory ring** (always on; ``recent()``) sized at
  ``RING_FACTOR×`` the flight ring — the observability endpoint's
  ``/queries`` route serves it;
* an optional **JSONL file** (``enable(path)``) — one
  ``json.dumps(digest)`` line per query, size-bounded through the
  shared rotating writer (``CYLON_SPAN_LOG_MAX_BYTES``, keep-N
  generations) so a long-lived service can tail it forever.

The digest also feeds the per-tenant SLO tracker (telemetry/slo.py) —
latency observation, objective evaluation, burn accounting — making
this hook the single choke point where a finished query becomes
operator-visible state.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import List, Optional

from . import export as _export
from . import knobs as _knobs
from . import slo as _slo
from . import spans as _spans
from . import stats as _stats

# root span names that ARE queries (everything else a root hook sees —
# eager op roots, marker spans — is not a query digest)
QUERY_ROOT_NAMES = ("plan.query",)

# the digest ring holds this multiple of CYLON_FLIGHT_RING entries:
# digests are ~200 B dicts where flight-ring entries are whole span
# trees, so /queries can afford deeper history than forensics
RING_FACTOR = 4

# v2: + est_bytes / est_source (PR 12)
# v3: + join_algorithms / salted_exchanges (PR 15 — "which queries
#      went broadcast, and did they win" is joinable offline from the
#      JSONL alone against exec_ms / shuffle_bytes)
DIGEST_SCHEMA_VERSION = 3


def _ring_size() -> int:
    return _knobs.get("CYLON_FLIGHT_RING") * RING_FACTOR


_lock = threading.RLock()
_ring: deque = deque(maxlen=_ring_size())
_writer: Optional[_export.RotatingJsonlWriter] = None


def digest(root) -> dict:
    """Reduce one completed root query span tree to its flat digest
    record — the query-log line and the ``/queries`` entry."""
    a = root.attrs
    shuffle_bytes = 0
    shuffle_rows = 0
    shuffles = 0
    retries = 0
    peak_hbm: Optional[int] = None
    skew_max: Optional[float] = None
    join_algos = set()
    salted = 0
    for node in root.walk():
        at = node.attrs
        if node.name.startswith("shuffle.exchange"):
            shuffle_bytes += int(at.get("bytes_moved") or 0)
            shuffle_rows += int(at.get("rows") or 0)
        if node.name.startswith("plan.shuffle"):
            shuffles += 1
        retries += int(at.get("retries") or 0)
        hp = at.get("hbm_peak")
        if hp is not None:
            peak_hbm = max(peak_hbm or 0, int(hp))
        si = at.get("skew_imbalance")
        if si is not None:
            skew_max = max(skew_max or 0.0, float(si))
        ja = at.get("join_algorithm")
        if ja is not None:
            join_algos.add(str(ja))
        if at.get("salted"):
            salted += 1
    return {
        "v": DIGEST_SCHEMA_VERSION,
        "time_unix": round(time.time(), 3),
        "query_id": a.get("query_id", root.span_id),
        "tenant": a.get("tenant", "default"),
        "service": a.get("service"),
        "root": root.label,
        "outcome": "error" if root.error else "ok",
        "exec_ms": round(root.elapsed_ms, 3)
        if root.elapsed_ms is not None else None,
        "wait_s": a.get("wait_s"),
        "admission": a.get("admission"),
        # the admission estimate + its provenance (static width x row
        # bound vs measured-EWMA calibration): with these two fields
        # beside the measured aggregates below, estimated-vs-actual is
        # joinable OFFLINE from the JSONL alone — before them only the
        # in-memory flight admission ring carried the estimate
        "est_bytes": a.get("est_bytes"),
        "est_source": a.get("est_source"),
        "plan_cache": a.get("plan_cache"),
        "plan_fp": a.get("plan_fp"),
        "shuffles": shuffles,
        "shuffle_bytes": shuffle_bytes,
        "shuffle_rows": shuffle_rows,
        # the algorithms this query's joins actually RAN (runtime-
        # honest, from the lowering's span attrs) and how many of its
        # exchanges took the hot-key salted path
        "join_algorithms": sorted(join_algos),
        "salted_exchanges": salted,
        "retries": retries,
        "peak_hbm_bytes": peak_hbm,
        "skew_imbalance_max": skew_max,
        "sampled": bool(a.get("sampled", True)),
        "sampled_promoted": bool(a.get("sampled_promoted", False)),
    }


def _on_root_close(root) -> None:
    if root.name not in QUERY_ROOT_NAMES:
        return
    try:
        d = digest(root)
    except Exception:  # pragma: no cover - defensive
        _spans.logger.exception("querylog digest failed")
        return
    global _ring
    with _lock:
        # knob reads are LIVE everywhere else (telemetry/knobs.py
        # contract) — honor a resized CYLON_FLIGHT_RING here too
        # instead of latching the import-time maxlen forever
        size = _ring_size()
        if _ring.maxlen != size:
            _ring = deque(_ring, maxlen=size)
        _ring.append(d)
        w = _writer
        if w is not None:
            try:
                # flushed per line: digests land at query rate, and an
                # operator tail -f'ing the log must see a query the
                # moment it completes
                w.write_line(json.dumps(d, default=str,
                                        sort_keys=True), flush=True)
            except Exception:  # pragma: no cover - defensive
                _spans.logger.exception("querylog write failed")
    # the digest is the SLO tracker's feed: per-tenant latency,
    # objective evaluation, burn accounting (outside our lock — slo
    # has its own)
    _slo.observe(d["tenant"], d["exec_ms"] or 0.0,
                 error=root.error)
    # ... and the statistics warehouse's: measured per-fingerprint
    # truth (q-error, drift, stats-informed admission) accumulates at
    # the same choke point where a finished query becomes operator-
    # visible state (outside our lock — the store has its own)
    try:
        _stats.record_root(root, d)
    except Exception:  # pragma: no cover - defensive
        _spans.logger.exception("stats observation failed")


# always on, like the flight recorder: the ring costs one deque append
# per completed query; the file carrier is armed via enable()
_spans.add_root_hook(_on_root_close)


def recent(n: Optional[int] = None) -> List[dict]:
    """The most recent query digests, oldest first (``n`` caps the
    tail) — the ``/queries`` payload."""
    with _lock:
        out = [dict(d) for d in _ring]
    return out if n is None else out[-n:]


def enable(path: str, max_bytes: Optional[int] = None,
           keep: int = _export.SPAN_LOG_KEEP) -> None:
    """Start appending one JSONL digest line per completed query to
    ``path`` (truncates; size-bounded via the shared rotating writer).
    Re-enabling swaps the file atomically under the log lock."""
    w = _export.RotatingJsonlWriter(path, max_bytes=max_bytes,
                                    keep=keep).open()
    with _lock:
        global _writer
        old, _writer = _writer, w
    if old is not None:
        old.close()


def disable() -> None:
    """Stop the file carrier (the ring stays on)."""
    with _lock:
        global _writer
        w, _writer = _writer, None
    if w is not None:
        w.close()


def lines_written() -> int:
    """Digest lines written to the enabled file so far (0 when
    disabled) — the smoke gate's completeness check."""
    with _lock:
        return _writer.lines_written if _writer is not None else 0


def reset() -> None:
    """Clear the digest ring (test isolation); re-reads the ring-size
    knob. The file carrier, if enabled, is untouched."""
    with _lock:
        global _ring
        _ring = deque(maxlen=_ring_size())
