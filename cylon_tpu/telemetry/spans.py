"""Hierarchical spans + the phase-timer back-compat surface.

The reference's observability is pervasive manual wall-clock timing with
glog at every operator phase (reference: cpp/src/cylon/table.cpp:320-335
shuffle timing; join/join.cpp:101-253 per-phase logs; arrow_hash_kernels.hpp
:120,163 build/probe timers). Here the same discipline rides three carriers:

* a ``logging`` logger named ``cylon_tpu`` — every span logs its
  host-side elapsed time at INFO on exit. JAX dispatch is async: unless
  a span ends in a host sync (the count→materialize scalar fetches do),
  the time logged is dispatch+trace cost, not device time. That is
  exactly what the phase discipline is for — spotting recompiles and
  host round-trips, the things the host can see.
* ``jax.profiler.TraceAnnotation`` — the same label appears in
  TensorBoard / Perfetto traces captured with ``jax.profiler.trace``,
  where the DEVICE time lives. ``seq`` carries the context's op
  sequence number, the moral heir of the reference's MPI edge/tag id
  (ctx/cylon_context.cpp:94-99).
* a contextvar-scoped `Span` TREE — spans opened inside another span
  become its children, carry typed attributes (``rows_in``/``rows_out``,
  ``bytes_moved``, ``world``, ``mode``, error flag), and feed the
  registered sinks (export.JsonlSpanSink) and the per-phase latency
  histogram (metrics) on completion. The plan executor's per-query
  EXPLAIN ANALYZE report (plan/report.py) is built on this tree.

``phase(name, seq)`` is the original module's API, now a thin wrapper
over ``span`` — all pre-package call sites keep their exact semantics
(label format ``name#seq``, one INFO line per span, collect_phases
label counting). New in the package: the body is wrapped in
try/finally, so a raising phase still records its elapsed time, gains
an ``error=True`` attribute, logs, and re-raises (the old module
silently dropped the measurement on the floor).

Enable host-side logs with ``logging.getLogger("cylon_tpu").setLevel(
logging.INFO)`` plus a handler, or ``cylon_tpu.telemetry.log_to_stderr()``.
"""
from __future__ import annotations

import itertools
import logging
import time
from contextlib import contextmanager, nullcontext
from contextvars import ContextVar
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Optional

import jax

from . import knobs as _knobs
from . import metrics as _metrics
from . import sampling as _sampling

logger = logging.getLogger("cylon_tpu")

# active phase collectors (collect_phases contexts) — every entered
# span appends its label AND the Span object to each, so callers can
# COUNT events (e.g. a query plan's shuffles) without wiring a logging
# handler, and the plan recorder can read back typed attributes (the
# exchange skew stats) by the same indices
_collectors: list = []

# completed-span sinks (add_sink/remove_sink); each is called with every
# Span as it CLOSES — the JSONL exporter registers here
_sinks: List[Callable] = []

# root-span close hooks: called with every span that closes with NO
# parent (a whole query tree / top-level eager op). The flight recorder
# (telemetry/flight.py) registers here to keep its completed-query ring
# and to write crash dumps when a root span closes errored. Exceptions
# are logged, never raised.
_root_hooks: List[Callable] = []

_span_ids = itertools.count(1)

# per-span HBM sampling (hbm_delta/hbm_peak attrs): two pool snapshots
# per span — a refcounted-counter read on ledger-backed pools, one
# memory_stats runtime call per local device on stats-bearing
# backends. CYLON_HBM_SPAN_ATTRS=0 turns it off for latency-critical
# runs (read live through the knob registry, so it can be flipped at
# any time); the flight recorder's crash-time watermarks are
# unaffected (sampled at dump time).


def _hbm_attrs_on() -> bool:
    return _knobs.get("CYLON_HBM_SPAN_ATTRS")


# innermost open span of the current (async/thread) context, or None
_current: ContextVar[Optional["Span"]] = ContextVar(
    "cylon_tpu_current_span", default=None)

# attributes stamped onto every ROOT span opened in this context (the
# service tier sets tenant/query_id here, so EXPLAIN ANALYZE trees,
# flight-ring entries and crash dumps all say whose query they were) —
# root-only keeps attr volume flat however deep the query tree is
_root_attrs: ContextVar[Optional[dict]] = ContextVar(
    "cylon_tpu_root_attrs", default=None)


@dataclass
class Span:
    """One timed operation with typed attributes and child spans.

    ``elapsed_ms`` is None while the span is open; ``attrs`` holds the
    attribute catalog documented in docs/telemetry.md (``rows_in``,
    ``rows_out``, ``bytes_moved``, ``world``, ``mode``, ``error``...).
    """

    name: str
    seq: Optional[int] = None
    attrs: Dict[str, object] = field(default_factory=dict)
    children: List["Span"] = field(default_factory=list)
    span_id: int = 0
    parent_id: int = 0
    root_id: int = 0               # the enclosing tree's root span_id
    elapsed_ms: Optional[float] = None
    error: bool = False
    # head-sampling decision (telemetry/sampling.py): decided at the
    # ROOT from the query_id hash, inherited by every child. False =
    # this span skips trace sinks + device-trace annotation; the tree
    # itself is still built (crash dumps / error promotion need it)
    sampled: bool = True
    _t0: float = 0.0
    _hbm0: Optional[int] = None    # pool bytes_in_use at span enter

    @property
    def label(self) -> str:
        return f"{self.name}#{self.seq}" if self.seq is not None \
            else self.name

    def set(self, **attrs) -> "Span":
        """Attach/overwrite attributes on this span."""
        self.attrs.update(attrs)
        return self

    def walk(self) -> Iterator["Span"]:
        yield self
        for c in self.children:
            yield from c.walk()

    def walk_postorder(self) -> Iterator["Span"]:
        """Children before parents — the order spans CLOSE in, and the
        order the JSONL exporter promises its lines (error promotion
        replays a sampled-out tree through the sinks in this order)."""
        for c in self.children:
            yield from c.walk_postorder()
        yield self

    def to_dict(self, nested: bool = False) -> dict:
        """Flat JSON-able record (parent_id links the tree); pass
        ``nested=True`` to embed children instead."""
        d = {"span_id": self.span_id, "parent_id": self.parent_id,
             "root_id": self.root_id, "name": self.name, "seq": self.seq,
             "elapsed_ms": self.elapsed_ms, "error": self.error,
             "attrs": dict(self.attrs)}
        if nested:
            d["children"] = [c.to_dict(nested=True) for c in self.children]
        return d


def current_span() -> Optional[Span]:
    """The innermost open span of this context, or None."""
    return _current.get()


def annotate(**attrs) -> None:
    """Attach attributes to the innermost open span (no-op outside any
    span) — lets deep helpers report ``rows``/``bytes`` without
    threading the Span object through every signature."""
    s = _current.get()
    if s is not None:
        s.attrs.update(attrs)


@contextmanager
def root_attrs(**attrs) -> Iterator[None]:
    """Stamp ``attrs`` onto every ROOT span opened inside the context
    (contextvar-scoped, so concurrent submitters/threads never leak
    labels into each other's queries). Explicit span attrs win on key
    collision. The service scheduler threads ``tenant``/``query_id``
    through here — one context manager instead of touching every
    execute path."""
    outer = _root_attrs.get()
    merged = {**outer, **attrs} if outer else dict(attrs)
    token = _root_attrs.set(merged)
    try:
        yield
    finally:
        _root_attrs.reset(token)


def add_sink(sink: Callable) -> None:
    """Register a completed-span sink: ``sink(span)`` runs as each span
    closes (innermost first). Exceptions are logged, never raised."""
    _sinks.append(sink)


def add_root_hook(hook: Callable) -> None:
    """Register a root-span close hook: ``hook(span)`` runs when a span
    with no parent closes — the whole tree is complete at that point
    (children closed first). The flight recorder lives here."""
    _root_hooks.append(hook)


def remove_root_hook(hook: Callable) -> None:
    for i, h in enumerate(_root_hooks):
        if h is hook:
            del _root_hooks[i]
            break


def remove_sink(sink: Callable) -> None:
    for i, s in enumerate(_sinks):
        if s is sink:
            del _sinks[i]
            break


def _emit_to_sinks(s: "Span") -> None:
    for sink in list(_sinks):
        try:
            sink(s)
        except Exception:  # pragma: no cover - defensive
            logger.exception("span sink failed")


class collect_phases:
    """Collect every span label entered inside the context — the
    programmatic mirror of the INFO log stream. ``count(prefix)``
    answers questions like "how many shuffles did this plan run?"
    (prefix="plan.shuffle"); labels keep their ``name#seq`` form.
    ``spans[i]`` is the Span whose label is ``labels[i]`` — attributes
    set later in the span body (skew stats, rows_out) are visible
    after it closes, which is how the EXPLAIN ANALYZE recorder reads
    per-exchange skew without re-threading the objects."""

    def __init__(self):
        self.labels: list = []
        self.spans: list = []

    def __enter__(self) -> "collect_phases":
        _collectors.append(self)
        return self

    def __exit__(self, *exc):
        # remove by IDENTITY: list.remove compares by ==, and two nested
        # collectors with equal contents would remove each other
        for i, c in enumerate(_collectors):
            if c is self:
                del _collectors[i]
                break
        return False

    def count(self, prefix: str) -> int:
        return sum(1 for l in self.labels if l.startswith(prefix))


def log_to_stderr(level: int = logging.INFO) -> None:
    """Convenience: route cylon_tpu phase logs to stderr (idempotent)."""
    if not any(getattr(h, "_cylon_tpu", False) for h in logger.handlers):
        handler = logging.StreamHandler()
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(name)s %(message)s"))
        handler._cylon_tpu = True
        logger.addHandler(handler)
    logger.setLevel(level)


@contextmanager
def span(name: str, seq: Optional[int] = None, **attrs) -> Iterator[Span]:
    """Open one span: time it, nest it under the current span, annotate
    device traces with the same label, feed sinks and the per-phase
    latency histogram on close. Yields the Span so the body can
    ``s.set(rows_out=...)``. Exceptions re-raise after the span records
    ``error=True`` and its elapsed time (the fixed phase() bug)."""
    parent = _current.get()
    sid = next(_span_ids)
    if parent is None:
        ra = _root_attrs.get()
        if ra:
            attrs = {**ra, **attrs}
        # head sampling decided HERE, once per tree: deterministic on
        # the stamped query_id (the service scheduler's monotonic id;
        # this root's span_id outside the service — replayable either
        # way, never an RNG)
        sampled = _sampling.decide(attrs.get("query_id", sid))
        _sampling.record_decision(sampled)
        if not sampled:
            attrs = {**attrs, "sampled": False}
    else:
        sampled = parent.sampled
    s = Span(name, seq, dict(attrs), span_id=sid,
             parent_id=parent.span_id if parent is not None else 0,
             sampled=sampled)
    s.root_id = parent.root_id if parent is not None else s.span_id
    label = s.label
    for c in _collectors:
        c.labels.append(label)
        c.spans.append(s)
    if parent is not None:
        parent.children.append(s)
    # per-span HBM accounting: snapshot the registered pool (duck-typed
    # — metrics.set_memory_pool) at enter and exit so every span carries
    # hbm_delta/hbm_peak attrs. On backends that hide memory_stats the
    # pool reads the ledger's tracked bytes, so the attrs stay live
    # through the axon tunnel and on the CPU test mesh.
    pool = _metrics.get_memory_pool() if _hbm_attrs_on() else None
    if pool is not None:
        try:
            s._hbm0 = int(pool.snapshot()[0])
        except Exception:  # pragma: no cover - defensive  # cylint: disable=errors/broad-swallow — pool snapshot failure disables hbm attrs
            s._hbm0 = None
    token = _current.set(s)
    s._t0 = time.perf_counter()
    try:
        # sampled-out trees skip the device-trace annotation too — the
        # Perfetto label volume is part of the per-span cost the head
        # decision bounds
        with jax.profiler.TraceAnnotation(f"cylon:{label}") \
                if s.sampled else nullcontext():
            yield s
    except BaseException:
        s.error = True
        s.attrs["error"] = True
        raise
    finally:
        s.elapsed_ms = (time.perf_counter() - s._t0) * 1e3
        _current.reset(token)
        if s._hbm0 is not None:
            try:
                used, peak, _limit = pool.snapshot()
                s.attrs["hbm_delta"] = int(used) - s._hbm0
                s.attrs["hbm_peak"] = int(peak)
            except Exception:  # pragma: no cover - defensive  # cylint: disable=errors/broad-swallow — pool snapshot failure drops hbm attrs
                pass
        _metrics.observe_phase(s.name, s.elapsed_ms, error=s.error)
        if s.sampled:
            _emit_to_sinks(s)
        if parent is None:
            if s.error and not s.sampled:
                # error promotion: the whole tree is complete (children
                # closed first) and still in memory — record it to the
                # sinks post-hoc, children before parents, so the JSONL
                # trace AND the crash dump read like a fully sampled
                # query. Forensics never degrade under sampling.
                s.sampled = True
                # the sampled attr means "a full trace was exported":
                # after promotion that is TRUE — the query log's
                # digest must not tell an operator that the one class
                # of query GUARANTEED to have a trace has none
                s.attrs["sampled"] = True
                s.attrs["sampled_promoted"] = True
                _sampling.record_promotion()
                for node in s.walk_postorder():
                    node.sampled = True
                    _emit_to_sinks(node)
            for hook in list(_root_hooks):
                try:
                    hook(s)
                except Exception:  # pragma: no cover - defensive
                    logger.exception("root-span hook failed")
        if logger.isEnabledFor(logging.INFO):
            logger.info("%s %.3f ms%s", label, s.elapsed_ms,
                        " error=True" if s.error else "")


def phase(name: str, seq: Optional[int] = None):
    """Time one operator phase; annotate device traces with the same
    label. The original telemetry.py API — now a span with no
    attributes, so every pre-package call site participates in the
    span tree unchanged."""
    return span(name, seq)
