"""Overhead-bounded head sampling for root query spans.

At high QPS the span TREE dominates observability cost — every child
span serializes one JSONL line, annotates the device trace, and logs.
Counters and histograms are O(1) per event and stay cheap forever;
traces are O(spans) per query. Head sampling keeps the aggregate
signals complete while bounding the per-query trace cost:

* the sampling decision is made ONCE, when a ROOT span opens
  (``CYLON_TRACE_SAMPLE_RATE``, default 1.0 = record everything), and
  every child span inherits it;
* it is a **pure function of the query id** — sha256 of the stamped
  ``query_id`` root attribute (the service scheduler's monotonic id;
  the root's own span_id outside the service) mapped to [0, 1) and
  compared against the rate. No RNG: the same query id samples the
  same way in every process, so a drill or a bug report replays
  byte-identically (``decide(query_id)`` answers "was this recorded?"
  offline);
* a sampled-out query still FEEDS everything aggregate — phase-latency
  histograms, counters, the query-log digest, the SLO tracker, the
  flight ring — but its spans skip the trace sinks (JSONL lines) and
  the ``jax.profiler.TraceAnnotation`` carrier;
* **errored queries are always promoted to fully recorded**: the span
  tree is kept in memory until the root closes (it must be — the
  flight recorder's crash dump serializes it), so when a sampled-out
  root closes errored, spans.span walks the completed tree through the
  sinks post-hoc (children before parents, the JSONL invariant) and
  the crash dump never degrades. ``cylon_trace_promotions_total``
  counts those late recordings.

What stays ON for sampled-out queries, by design: span objects are
still constructed and linked (the crash-dump/promotion contract and
the EXPLAIN ANALYZE recorder depend on the tree), per-span HBM attrs
follow their own knob (``CYLON_HBM_SPAN_ATTRS``), and INFO logging
follows the logger level. What sampling bounds is the per-span EXPORT
work — serialization and device-trace annotation — which is where the
volume cost lives.
"""
from __future__ import annotations

import hashlib
from typing import Optional

from . import knobs as _knobs
from . import metrics as _metrics

DEFAULT_RATE = _knobs.default("CYLON_TRACE_SAMPLE_RATE")


def rate() -> float:
    """The live sampling rate, clamped to [0, 1]."""
    return min(float(_knobs.get("CYLON_TRACE_SAMPLE_RATE")), 1.0)


def fraction(key) -> float:
    """Map a query id to a stable fraction in [0, 1): the first 8
    bytes of sha256(str(key)) as a big-endian integer over 2**64.
    Pure — no process seed, no RNG state — so the same id lands on
    the same side of any rate everywhere, forever."""
    digest = hashlib.sha256(str(key).encode("utf-8")).digest()
    return int.from_bytes(digest[:8], "big") / float(1 << 64)


def decide(key, sample_rate: Optional[float] = None) -> bool:
    """True when the query identified by ``key`` is head-sampled into
    full trace recording at ``sample_rate`` (default: the live knob)."""
    r = rate() if sample_rate is None else min(float(sample_rate), 1.0)
    if r >= 1.0:
        return True
    if r <= 0.0:
        return False
    return fraction(key) < r


# the decision counters, resolved once — record_decision runs on every
# root span, and reset_metrics() zeroes in place so the references
# stay live across test resets
_recorded = _metrics.REGISTRY.counter(
    "cylon_trace_sampled_total", {"decision": "recorded"})
_sampled_out = _metrics.REGISTRY.counter(
    "cylon_trace_sampled_total", {"decision": "sampled_out"})
_promotions = _metrics.REGISTRY.counter("cylon_trace_promotions_total")


def record_decision(sampled: bool) -> None:
    """Count one root-span head decision —
    ``cylon_trace_sampled_total{decision=recorded|sampled_out}``."""
    (_recorded if sampled else _sampled_out).inc()


def record_promotion() -> None:
    """Count one errored sampled-out root promoted to fully recorded
    (``cylon_trace_promotions_total``)."""
    _promotions.inc()
