"""The query statistics warehouse: measured truth per plan fingerprint.

Every completed query already leaves a digest (telemetry/querylog.py)
carrying its plan fingerprint and measured aggregates, and every
executed shuffle/join/groupby span carries the node's sub-fingerprint
beside its pre-flight estimate and its measured output size — but
until this module nothing REMEMBERED any of it: pre-flight estimates
stayed stat-free width x row upper bounds, so admission kept shedding
or degrading repeat queries it had already watched fit in budget. The
warehouse closes that loop (ROADMAP item 1's substrate):

* **store** — thread-safe, keyed two ways: whole-plan fingerprints
  (``plan/fingerprint.fingerprint`` — the plan-cache key) map query-
  level metrics (exec_ms, shuffle_bytes, peak_hbm), and per-node
  SUB-fingerprints (``node_fingerprint`` over shuffle/join/groupby
  subtrees) map measured output ``bytes``/``rows``. Every metric keeps
  EWMA / min / max / count. Node keys are subtree shapes, so the same
  join appearing in two plans shares one measured history.
* **estimate-accuracy observatory** — each observation with both an
  estimate and a measurement feeds a per-node-kind q-error histogram
  ``cylon_estimate_qerror{kind=}`` (``max(est/meas, meas/est)`` — the
  standard cardinality-estimation accuracy measure, always >= 1); the
  estimate measured is the one admission actually USED (calibrated
  when stats qualified, static otherwise), so the series shows the
  loop tightening as measurements accumulate. EXPLAIN ANALYZE renders
  the calibrated estimate beside ``est=`` (plan/report.py).
* **drift detection** — a new measurement deviating more than
  ``CYLON_STATS_DRIFT_FACTOR`` (ratio, either direction) from an
  established EWMA fires ``cylon_stats_drift_total``, records a
  ``stats_drift`` event in the flight admission ring (it rides crash
  dumps), EVICTS the plan-cache entry through a late-bound hook
  (``set_plan_evict_hook`` — service/plancache registers, telemetry
  stays below the service tier), and resets the learned entry so
  admission falls back to static estimates until the new regime is
  re-learned. Self-correction, not self-confidence.
* **stats-informed admission** — ``effective_bytes(node_fp, static)``
  returns ``min(static, ewma x CYLON_STATS_SAFETY)`` once a node
  fingerprint has >= ``CYLON_STATS_MIN_OBS`` successful observations
  (and ``"measured"`` as the source), else the static bound
  unchanged. Soundness is structural: the effective estimate is never
  ABOVE the static bound, and a genuinely-over-budget measured EWMA
  still sheds — the min() only ever relaxes false alarms, never
  waves through real ones.
* **persistence** — ``save()`` writes one JSONL line per entry through
  the shared rotating writer (``CYLON_STATS_PATH``); ``load()``
  rebuilds the store so a fresh replica warm-starts its estimates
  (the first piece of ROADMAP item 3c). A corrupt or truncated file
  is QUARANTINED: renamed to ``<path>.quarantine``, recorded as a
  typed :class:`CylonDataError` event in the flight admission ring,
  and the store starts fresh — startup is never blocked by forensics.
  ``QueryService.start()/close()`` drive both ends.

Fed by the querylog root hook (``record_root`` — one call per
completed ``plan.query`` root); only successful queries count as
observations (a shed or errored query measured nothing trustworthy).
``state()`` is the observability endpoint's ``/stats`` payload.

Layering: a telemetry submodule (imports telemetry siblings + the
stdlib-only error taxonomy ``status.py`` — the ``telemetry-leaf``
contract sanctions exactly that pair); plan/ computes the fingerprints
and stamps them onto spans, service/ registers the eviction hook.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ..status import CylonDataError
from . import export as _export
from . import flight as _flight
from . import knobs as _knobs
from . import metrics as _metrics
from . import spans as _spans

STATS_SCHEMA_VERSION = 1

# EWMA smoothing: alpha 0.3 weights the last ~5 observations with >80%
# of the mass — reactive enough for a dashboard workload, smooth
# enough that one noisy run does not whipsaw admission
EWMA_ALPHA = 0.3

# q-error histogram buckets (q >= 1 by construction; log-ish spacing —
# under 2 is a good estimator, 10+ is the planning disaster zone)
QERROR_BUCKETS = (1.1, 1.25, 1.5, 2.0, 3.0, 5.0, 10.0, 30.0, 100.0,
                  1000.0)

# bounded ring of recent drift events the /stats route serves (the
# flight admission ring carries them too, but shares its budget with
# admission decisions)
DRIFT_RING = 32

DEFAULT_MIN_OBS = _knobs.default("CYLON_STATS_MIN_OBS")
DEFAULT_SAFETY = _knobs.default("CYLON_STATS_SAFETY")
DEFAULT_DRIFT_FACTOR = _knobs.default("CYLON_STATS_DRIFT_FACTOR")


def min_obs() -> int:
    return _knobs.get("CYLON_STATS_MIN_OBS")


def safety() -> float:
    return _knobs.get("CYLON_STATS_SAFETY")


def drift_factor() -> float:
    return _knobs.get("CYLON_STATS_DRIFT_FACTOR")


def stats_path() -> Optional[str]:
    return _knobs.get("CYLON_STATS_PATH")


def qerror(est: float, measured: float) -> Optional[float]:
    """The q-error of one estimate: ``max(est/meas, meas/est)`` — 1.0
    is perfect, symmetric in over/under-estimation. None when either
    side is non-positive (no ratio exists)."""
    if est is None or measured is None or est <= 0 or measured <= 0:
        return None
    return max(est / measured, measured / est)


class MetricStats:
    """EWMA / min / max / count for one metric of one fingerprint."""

    __slots__ = ("ewma", "min", "max", "count")

    def __init__(self):
        self.ewma: Optional[float] = None
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        self.ewma = v if self.ewma is None else \
            EWMA_ALPHA * v + (1.0 - EWMA_ALPHA) * self.ewma
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        self.count += 1

    def reset(self) -> None:
        self.ewma = self.min = self.max = None
        self.count = 0

    def to_dict(self) -> dict:
        return {"ewma": self.ewma, "min": self.min, "max": self.max,
                "count": self.count}

    @classmethod
    def from_dict(cls, d: dict) -> "MetricStats":
        m = cls()
        m.ewma = None if d["ewma"] is None else float(d["ewma"])
        m.min = None if d["min"] is None else float(d["min"])
        m.max = None if d["max"] is None else float(d["max"])
        m.count = int(d["count"])
        if m.count < 0 or (m.count > 0 and m.ewma is None):
            raise ValueError(f"inconsistent metric stats: {d}")
        return m


class _Entry:
    """All metrics of one fingerprint (plan- or node-level)."""

    __slots__ = ("kind", "metrics", "last_unix")

    def __init__(self, kind: Optional[str] = None):
        self.kind = kind            # node kind for node entries
        self.metrics: Dict[str, MetricStats] = {}
        self.last_unix: Optional[float] = None

    def metric(self, name: str) -> MetricStats:
        m = self.metrics.get(name)
        if m is None:
            m = self.metrics[name] = MetricStats()
        return m

    def obs_count(self) -> int:
        return max((m.count for m in self.metrics.values()), default=0)

    def to_dict(self) -> dict:
        return {"kind": self.kind, "last_unix": self.last_unix,
                "metrics": {k: m.to_dict()
                            for k, m in sorted(self.metrics.items())}}


# metrics drift-checked on node entries: the measured output size and
# cardinality — the two signals admission consumes. Query-level wall
# time is NOT drift-checked (warm-up and host-load variance would
# false-fire it); it is stored for the observatory only.
_DRIFT_METRICS = ("bytes", "rows")

# metrics drift-checked on join_input (decision) entries: the measured
# per-side input sizes the broadcast-join rewrite consumes — a drifted
# build side is exactly the mis-learned-broadcast signal that must
# evict and revert
_JOIN_INPUT_METRICS = ("left_bytes", "right_bytes")

# metrics whose qualification (count crossing CYLON_STATS_MIN_OBS) can
# CHANGE an optimizer decision — broadcast build-side sizes and
# exchange skew. Crossing (or drifting) any of these bumps the stats
# EPOCH, which is what tells the plan cache a cached template's
# algorithm choices may be stale (service/plancache.py re-checks the
# decision vector instead of replaying the template blindly)
_ADAPTIVE_METRICS = frozenset(_JOIN_INPUT_METRICS) | {"skew"}


class StatsStore:
    """The thread-safe two-level store. One process-global instance
    (module functions below) is what the querylog hook feeds and the
    admission path reads; tests may build private ones."""

    def __init__(self):
        # RLock: record_root runs in the root-span hook domain (on
        # whichever thread closed the query) while /stats scrapes and
        # admission reads race it
        self._lock = threading.RLock()
        self._plans: Dict[str, _Entry] = {}
        self._nodes: Dict[str, _Entry] = {}
        self._drift: deque = deque(maxlen=DRIFT_RING)
        self._loaded_from: Optional[str] = None
        # monotonic counter of "an adaptive decision input changed":
        # qualification crossings and drift resets of _ADAPTIVE_METRICS
        # entries, plus warm-start loads. The plan cache records the
        # epoch each template was optimized under; a mismatch makes a
        # hit re-check its decision vector instead of replaying a
        # possibly-stale algorithm choice.
        self._epoch = 0

    # -- feeding ------------------------------------------------------

    def record_root(self, root, digest: dict) -> None:
        """One completed ``plan.query`` root: fold its measured truth
        into the store. Only successful queries observe — a shed or
        errored query measured nothing trustworthy."""
        if digest.get("outcome") != "ok":
            return
        plan_fp = digest.get("plan_fp")
        if not plan_fp:
            return
        now = time.time()
        with self._lock:
            entry = self._plans.get(plan_fp)
            if entry is None:
                entry = self._plans[plan_fp] = _Entry()
            entry.last_unix = now
            for name in ("exec_ms", "shuffle_bytes", "peak_hbm_bytes"):
                v = digest.get(name)
                if v is not None:
                    entry.metric(name).observe(float(v))
        for node in root.walk():
            at = node.attrs
            fp = at.get("stats_fp")
            if fp:
                self._observe_node(
                    plan_fp, fp, str(at.get("stats_kind") or "node"),
                    {"bytes": at.get("bytes_out"),
                     "rows": at.get("rows_out")},
                    _DRIFT_METRICS, at.get("est_bytes"), now)
            dfp = at.get("stats_decision_fp")
            if dfp and at.get("left_in_bytes") is not None:
                # the join's measured per-side INPUT sizes, keyed by
                # the algorithm-invariant decision fingerprint — the
                # broadcast rewrite's evidence base, fed by shuffle
                # and broadcast executions alike
                self._observe_node(
                    plan_fp, dfp, "join_input",
                    {"left_bytes": at.get("left_in_bytes"),
                     "right_bytes": at.get("right_in_bytes")},
                    _JOIN_INPUT_METRICS, None, now)
            elif dfp and at.get("skew_max") is not None:
                # a standalone exchange's pre-mitigation skew, keyed
                # by the SAME rewrite-invariant normalization (the
                # salted path records the RAW count matrix, so the
                # salting decision never oscillates on its own
                # mitigation, and elision below the shuffle never
                # forks the evidence away from the decision's key)
                self._observe_node(
                    plan_fp, dfp, "exchange",
                    {"skew": at.get("skew_max")}, (), None, now)

    def _observe_node(self, plan_fp: str, node_fp: str, kind: str,
                      measured: dict, drift_names, est_bytes,
                      now: float) -> None:
        q = qerror(est_bytes, measured.get("bytes"))
        if q is not None:
            _metrics.REGISTRY.histogram(
                "cylon_estimate_qerror", {"kind": kind},
                buckets=QERROR_BUCKETS).observe(q)
        with self._lock:
            entry = self._nodes.get(node_fp)
            if entry is None:
                entry = self._nodes[node_fp] = _Entry(kind=kind)
            entry.last_unix = now
            floor = min_obs()
            factor = drift_factor()
            drifted = None
            for name, v in measured.items():
                if v is None:
                    continue
                m = entry.metric(name)
                ratio = qerror(m.ewma, float(v)) \
                    if name in drift_names and m.count >= floor \
                    else None
                if ratio is not None and ratio > factor:
                    drifted = {"metric": name, "ewma": m.ewma,
                               "measured": float(v),
                               "factor": round(ratio, 2)}
                    break
                warn = _knobs.get("CYLON_SKEW_WARN_FACTOR")
                was_hot = name == "skew" and m.count >= floor \
                    and m.ewma is not None and m.ewma >= warn
                m.observe(float(v))
                if m.count == floor and name in _ADAPTIVE_METRICS:
                    # a decision input just QUALIFIED: cached plan
                    # templates may now choose differently
                    self._epoch += 1
                elif name == "skew" and m.count > floor \
                        and (m.ewma >= warn) != was_hot:
                    # the qualified skew EWMA crossed the warning
                    # threshold (either direction): the salting
                    # decision flips, so cached templates must
                    # re-decide — skew is deliberately NOT
                    # drift-checked (a shifting key distribution is a
                    # salting trigger, not a reason to forget the
                    # output-size history), so this crossing is its
                    # epoch signal
                    self._epoch += 1
            if drifted is not None:
                # the learned regime is gone: reset EVERY metric of
                # this entry and seed fresh from the new measurements
                # (count 1 < CYLON_STATS_MIN_OBS => admission falls
                # back to the static bound until re-learned)
                for m in entry.metrics.values():
                    m.reset()
                for name, v in measured.items():
                    if v is not None:
                        entry.metric(name).observe(float(v))
                self._epoch += 1
                event = {"action": "stats_drift", "plan_fp": plan_fp,
                         "node_fp": node_fp, "kind": kind,
                         "time_unix": round(now, 3), **drifted}
                self._drift.append(event)
        if drifted is None:
            return
        # outside our lock: counter, flight ring and the plan-cache
        # eviction hook all take their own
        _metrics.REGISTRY.counter("cylon_stats_drift_total").inc()
        _flight.record_admission(event)
        _spans.logger.warning(
            "stats drift: %s %.3g vs ewma %.3g (%.1fx > %.1fx) on "
            "node %s — plan %s evicted, stats re-learning",
            drifted["metric"], drifted["measured"], drifted["ewma"],
            drifted["factor"], factor, node_fp[:12], plan_fp[:12])
        hook = _plan_evict_hook
        if hook is not None:
            try:
                hook(plan_fp)
            except Exception:  # pragma: no cover - defensive
                _spans.logger.exception("plan evict hook failed")

    # -- admission reads ----------------------------------------------

    def effective_bytes(self, node_fp: Optional[str],
                        static_bytes: Optional[int]
                        ) -> Tuple[Optional[int], str]:
        """The estimate admission should use for one node:
        ``(min(static, ewma x safety), "measured")`` once the node
        fingerprint has >= ``CYLON_STATS_MIN_OBS`` observations, else
        ``(static, "static")``. Never above the static bound."""
        if node_fp is None or static_bytes is None:
            return static_bytes, "static"
        with self._lock:
            entry = self._nodes.get(node_fp)
            if entry is None:
                return static_bytes, "static"
            m = entry.metrics.get("bytes")
            if m is None or m.count < min_obs() or m.ewma is None:
                return static_bytes, "static"
            ewma = m.ewma
        eff = min(int(static_bytes), int(ewma * safety()) + 1)
        return eff, "measured"

    def node_obs(self, node_fp: str) -> int:
        """Qualified observation count for one node fingerprint."""
        with self._lock:
            entry = self._nodes.get(node_fp)
            m = entry.metrics.get("bytes") if entry is not None else None
            return m.count if m is not None else 0

    def _qualified_ewma(self, node_fp: str, metric: str
                        ) -> Optional[float]:
        """One metric's EWMA, or None until it has >=
        ``CYLON_STATS_MIN_OBS`` observations (caller holds no lock)."""
        with self._lock:
            entry = self._nodes.get(node_fp)
            m = entry.metrics.get(metric) if entry is not None else None
            if m is None or m.count < min_obs() or m.ewma is None:
                return None
            return m.ewma

    def join_input_bytes(self, decision_fp: Optional[str]
                         ) -> Tuple[Optional[float], Optional[float]]:
        """The measured (left, right) input-size EWMAs of one join
        decision fingerprint — each None until qualified. What the
        broadcast-join rewrite consumes."""
        if decision_fp is None:
            return None, None
        return (self._qualified_ewma(decision_fp, "left_bytes"),
                self._qualified_ewma(decision_fp, "right_bytes"))

    def node_skew(self, node_fp: Optional[str]) -> Optional[float]:
        """The measured exchange-skew EWMA (pre-mitigation imbalance
        factor) of one node fingerprint, or None until qualified.
        What the hot-key salting rewrite consumes."""
        if node_fp is None:
            return None
        return self._qualified_ewma(node_fp, "skew")

    def epoch(self) -> int:
        """Monotonic adaptive-decision epoch: bumps whenever a
        decision input qualifies, drifts, or warm-starts — the plan
        cache's staleness signal (see service/plancache.py)."""
        with self._lock:
            return self._epoch

    # -- observatory --------------------------------------------------

    def recent_drift(self) -> List[dict]:
        with self._lock:
            return [dict(d) for d in self._drift]

    def state(self, top_n: int = 20) -> dict:
        """The ``/stats`` payload: top-N fingerprints by observation
        count with their EWMAs, per-kind q-error quantiles, recent
        drift events, and the live knob values."""
        with self._lock:
            plans = sorted(self._plans.items(),
                           key=lambda kv: -kv[1].obs_count())[:top_n]
            nodes = sorted(self._nodes.items(),
                           key=lambda kv: -kv[1].obs_count())[:top_n]
            doc = {
                "plans": [{"fp": fp, "obs": e.obs_count(),
                           **e.to_dict()} for fp, e in plans],
                "nodes": [{"fp": fp, "obs": e.obs_count(),
                           **e.to_dict()} for fp, e in nodes],
                "plan_count": len(self._plans),
                "node_count": len(self._nodes),
                "drift_events": [dict(d) for d in self._drift],
                "loaded_from": self._loaded_from,
            }
        doc["qerror"] = qerror_quantiles()
        doc["config"] = {"min_obs": min_obs(), "safety": safety(),
                         "drift_factor": drift_factor(),
                         "path": stats_path()}
        return doc

    # -- persistence --------------------------------------------------

    def save(self, path: Optional[str] = None) -> Optional[str]:
        """Snapshot the store as JSONL (header line + one line per
        entry) through the shared rotating writer. ``path`` defaults
        to ``CYLON_STATS_PATH``; None/unset means no persistence (a
        no-op, not an error). Never raises — a failing snapshot must
        not turn a clean shutdown into a crash."""
        path = path or stats_path()
        if not path:
            return None
        with self._lock:
            lines = [json.dumps({"rec": "header",
                                 "v": STATS_SCHEMA_VERSION,
                                 "time_unix": round(time.time(), 3)},
                                sort_keys=True)]
            for table, name in ((self._plans, "plan"),
                                (self._nodes, "node")):
                for fp, e in table.items():
                    lines.append(json.dumps(
                        {"rec": name, "fp": fp, **e.to_dict()},
                        sort_keys=True))
        try:
            # generation rotation happens BEFORE the write (the last
            # snapshot survives as path.1), and the write itself is
            # unbounded: a snapshot split mid-write by the size-based
            # in-line rotation would read as a truncated file — and be
            # quarantined — at the next warm start
            if os.path.exists(path):
                _export.rotate_file(path)
            w = _export.RotatingJsonlWriter(path, max_bytes=0).open()
            try:
                for line in lines:
                    w.write_line(line)
            finally:
                w.close()
        except OSError:
            _spans.logger.exception("stats save failed for %s", path)
            return None
        _spans.logger.info("stats: %d entries saved to %s",
                           len(lines) - 1, path)
        return path

    def _parse_snapshot(self, path: str
                        ) -> Tuple[Dict[str, _Entry], Dict[str, _Entry]]:
        """Parse one snapshot file into fresh tables; raises
        :class:`CylonDataError` on ANY malformation (the caller
        quarantines — a half-trusted statistics file is worse than
        none)."""
        plans: Dict[str, _Entry] = {}
        nodes: Dict[str, _Entry] = {}
        try:
            with open(path, encoding="utf-8") as f:
                raw = f.read().splitlines()
        except OSError as e:
            raise CylonDataError(f"stats file unreadable: {e}")
        if not raw:
            raise CylonDataError("empty stats file")
        try:
            head = json.loads(raw[0])
        except ValueError as e:
            raise CylonDataError(f"corrupt stats header: {e}")
        # a bare scalar/array is valid JSON too — isinstance first, or
        # .get() raises AttributeError past the quarantine net
        if not isinstance(head, dict) or head.get("rec") != "header" \
                or head.get("v") != STATS_SCHEMA_VERSION:
            raise CylonDataError(
                f"unrecognized stats header/version: {raw[0][:200]}")
        for i, line in enumerate(raw[1:], start=2):
            try:
                doc = json.loads(line)
                rec, fp = doc["rec"], doc["fp"]
                e = _Entry(kind=doc.get("kind"))
                e.last_unix = doc.get("last_unix")
                for name, md in (doc.get("metrics") or {}).items():
                    e.metrics[str(name)] = MetricStats.from_dict(md)
            except (ValueError, KeyError, TypeError,
                    AttributeError) as err:
                raise CylonDataError(
                    f"corrupt stats line {i}: {type(err).__name__}: "
                    f"{err}")
            if rec == "plan":
                plans[fp] = e
            elif rec == "node":
                nodes[fp] = e
            else:
                raise CylonDataError(
                    f"unknown stats record kind {rec!r} (line {i})")
        return plans, nodes

    def load(self, path: Optional[str] = None) -> int:
        """Warm-start the store from a saved snapshot; returns the
        entry count loaded (0 when the path is unset or absent). A
        corrupt or truncated file — unparseable line, bad schema,
        wrong version — is QUARANTINED: renamed to
        ``<path>.quarantine``, recorded as a typed
        :class:`CylonDataError` event in the flight admission ring,
        and the store stays fresh. Startup is never blocked on
        forensics."""
        path = path or stats_path()
        if not path or not os.path.exists(path):
            return 0
        try:
            plans, nodes = self._parse_snapshot(path)
        except CylonDataError as e:
            self._quarantine(path, e)
            return 0
        with self._lock:
            # loaded entries never clobber LIVE measurements: a store
            # that already observed this process's own queries keeps
            # its fresher truth, the snapshot fills the gaps
            for fp, e in plans.items():
                self._plans.setdefault(fp, e)
            for fp, e in nodes.items():
                self._nodes.setdefault(fp, e)
            self._loaded_from = path
            # warm-started evidence can change adaptive choices
            self._epoch += 1
        n = len(plans) + len(nodes)
        _spans.logger.info("stats: warm-started %d entries from %s",
                           n, path)
        return n

    def _quarantine(self, path: str, err: CylonDataError) -> None:
        """Move a corrupt snapshot aside and record the typed event —
        the file stays on disk for a post-mortem, the store starts
        fresh, and startup proceeds."""
        qpath = path + ".quarantine"
        try:
            os.replace(path, qpath)
        except OSError:  # pragma: no cover - raced deletion
            qpath = None
        event = {"action": "stats_quarantine",
                 "error": f"{type(err).__name__}: {err}",
                 "path": path, "quarantined_to": qpath,
                 "time_unix": round(time.time(), 3)}
        _flight.record_admission(event)
        _metrics.REGISTRY.counter("cylon_stats_quarantine_total").inc()
        _spans.logger.error(
            "stats: corrupt snapshot %s quarantined to %s (%s) — "
            "starting with a fresh store", path, qpath, event["error"])

    def reset(self) -> None:
        """Drop every learned entry and drift event (test isolation).
        The epoch BUMPS (never rewinds): cached templates optimized
        against the dropped evidence are stale, not fresh."""
        with self._lock:
            self._plans.clear()
            self._nodes.clear()
            self._drift.clear()
            self._loaded_from = None
            self._epoch += 1


def qerror_quantiles() -> Dict[str, dict]:
    """Per-node-kind q-error p50/p95 + observation count, read back
    from the registry histograms — the observatory summary the /stats
    route and the bench artifact share."""
    out: Dict[str, dict] = {}
    for name, labels, m in _metrics.REGISTRY.series():
        if name != "cylon_estimate_qerror" or m.kind != "histogram":
            continue
        kind = dict(labels).get("kind", "")
        st = m.stats()
        if st["count"] == 0:
            continue
        out[kind] = {"count": st["count"],
                     "p50": round(m.quantile(0.50), 3),
                     "p95": round(m.quantile(0.95), 3),
                     "max": round(st["max"], 3)}
    return out


# Late-bound plan-cache eviction hook (the metrics.set_factory_*_hook
# pattern): service/plancache registers its invalidate here at import,
# so drift eviction reaches the cache while telemetry stays below the
# service tier. Last registration wins; None disarms.
_plan_evict_hook: Optional[Callable[[str], None]] = None


def set_plan_evict_hook(hook: Optional[Callable[[str], None]]) -> None:
    global _plan_evict_hook
    _plan_evict_hook = hook


# the process-global warehouse — the querylog hook feeds it, the
# admission path reads it, QueryService.start()/close() persist it
STORE = StatsStore()


def record_root(root, digest: dict) -> None:
    """Querylog-hook entry point: fold one completed query into the
    global store."""
    STORE.record_root(root, digest)


def effective_bytes(node_fp: Optional[str], static_bytes: Optional[int]
                    ) -> Tuple[Optional[int], str]:
    return STORE.effective_bytes(node_fp, static_bytes)


def node_obs(node_fp: str) -> int:
    return STORE.node_obs(node_fp)


def join_input_bytes(decision_fp: Optional[str]
                     ) -> Tuple[Optional[float], Optional[float]]:
    return STORE.join_input_bytes(decision_fp)


def node_skew(node_fp: Optional[str]) -> Optional[float]:
    return STORE.node_skew(node_fp)


def epoch() -> int:
    return STORE.epoch()


def recent_drift() -> List[dict]:
    return STORE.recent_drift()


def state(top_n: int = 20) -> dict:
    return STORE.state(top_n)


def save(path: Optional[str] = None) -> Optional[str]:
    return STORE.save(path)


def load(path: Optional[str] = None) -> int:
    return STORE.load(path)


def reset() -> None:
    STORE.reset()


def _dump_section() -> dict:
    """Crash-dump section: the warehouse's shape at failure time (top
    entries + drift history) — a mis-calibrated admission shows its
    evidence in the same file as the crash it caused."""
    return STORE.state(top_n=8)


_flight.add_dump_section("stats", _dump_section)
