"""Metrics registry: counters, histograms, gauges.

The quantitative half of the observability layer — where spans answer
"what ran and how long", the registry accumulates the signals the
reference logs and then drops (rows exchanged, shuffle bytes, HBM
watermarks, program builds). Everything is process-local, cheap
(plain attribute adds under the GIL), and exported either as a plain
dict (``snapshot()`` — the BENCH artifact form) or Prometheus text
(export.prometheus_text).

Well-known series (full catalog: docs/telemetry.md):

* ``cylon_shuffle_bytes_total``       payload bytes through exchanges
* ``cylon_rows_exchanged_total``      live rows moved by exchanges
* ``cylon_collective_launches_total`` compiled collective dispatches
* ``cylon_kernel_factory_builds_total{factory=...}`` jit program builds
  (each miss of a ``counted_cache`` kernel factory is one new XLA
  compilation — the recompile counter)
* ``cylon_phase_latency_ms{phase=...}`` per-span latency histogram
  (fed by spans.span on every close)
* ``cylon_hbm_*_bytes`` / ``cylon_comm_budget_bytes`` gauges sampled
  from a ``memory.MemoryPool`` via ``sample_memory`` (duck-typed —
  telemetry stays a base-layer leaf and never imports memory.py)
"""
from __future__ import annotations

import functools
import threading
from typing import Callable, Dict, Optional, Tuple

LabelKey = Tuple[Tuple[str, str], ...]


class Counter:
    """Monotonically increasing value.

    ``inc`` is a read-modify-write: submitter threads, the service
    worker and GC finalizers all increment concurrently, so it runs
    under a per-metric RLock (reentrant — a weakref callback firing
    mid-``inc`` on the same thread must never deadlock)."""

    kind = "counter"
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0
        self._lock = threading.RLock()

    def inc(self, n=1) -> None:
        with self._lock:
            self.value += n

    def zero(self) -> None:
        with self._lock:
            self.value = 0


class Gauge:
    """Last-sampled value."""

    kind = "gauge"
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def set(self, v) -> None:
        self.value = v

    def zero(self) -> None:
        self.value = 0


# latency bucket bounds in ms (log-ish spacing spanning one kernel
# dispatch to one axon-tunnel round trip and beyond)
DEFAULT_BUCKETS_MS = (0.1, 0.5, 1.0, 5.0, 10.0, 50.0, 100.0, 500.0,
                      1000.0, 5000.0)


class Histogram:
    """Cumulative-bucket histogram with sum/count/min/max.

    ``observe`` updates six fields; the per-metric RLock keeps the
    group consistent under concurrent observers (every thread that
    closes a span feeds the phase-latency series)."""

    kind = "histogram"
    __slots__ = ("buckets", "counts", "count", "sum", "min", "max",
                 "_lock")

    def __init__(self, buckets=DEFAULT_BUCKETS_MS):
        self.buckets = tuple(buckets)
        self._lock = threading.RLock()
        self.zero()

    def zero(self) -> None:
        with self._lock:
            self.counts = [0] * (len(self.buckets) + 1)  # last = +Inf
            self.count = 0
            self.sum = 0.0
            self.min = None
            self.max = None

    def observe(self, v: float) -> None:
        i = 0
        for i, b in enumerate(self.buckets):
            if v <= b:
                break
        else:
            i = len(self.buckets)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)

    def stats(self) -> dict:
        """Consistent read of the six-field group under the same lock
        the writers hold — a reader interleaving a half-applied
        observe() would see count/sum disagree (and a _count line
        disagreeing with the cumulative +Inf bucket in the Prometheus
        dump)."""
        with self._lock:
            return {"count": self.count, "sum": self.sum,
                    "min": self.min, "max": self.max,
                    "counts": list(self.counts)}

    def quantile(self, q: float) -> Optional[float]:
        """Estimate the q-quantile (0..1) by linear interpolation
        WITHIN the bucket holding the target rank (the
        histogram_quantile estimator): the bucket's observations are
        assumed uniform over (lower, upper]. The first bucket
        interpolates from ``min`` (0 when unknown), the +Inf bucket
        cannot interpolate and reports ``max``. Returns None on an
        empty histogram. Reads the count group under the per-metric
        RLock, so a concurrent observe() never tears the estimate."""
        st = self.stats()
        if st["count"] == 0:
            return None
        if q <= 0.0:
            return st["min"]
        if q >= 1.0:
            return st["max"]
        rank = q * st["count"]
        cum = 0
        lo = st["min"] if st["min"] is not None else 0.0
        for bound, c in zip(self.buckets, st["counts"]):
            if cum + c >= rank and c > 0:
                lo_eff = min(lo, bound)
                return lo_eff + (bound - lo_eff) * (rank - cum) / c
            cum += c
            lo = bound
        return st["max"]


def _series_key(name: str, labels: Optional[Dict[str, str]]) -> tuple:
    return name, tuple(sorted((labels or {}).items()))


def format_series(name: str, labels: LabelKey) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return f"{name}{{{inner}}}"


class MetricsRegistry:
    """Name+labels → metric instance. ``reset()`` zeroes IN PLACE so
    references held by instrumented code (counted_cache closures, span
    histograms) stay live across test resets."""

    def __init__(self):
        self._metrics: Dict[tuple, object] = {}
        # RLock, not Lock: the ledger's weakref-retire callback reaches
        # gauge() from GC, which can fire on a thread ALREADY inside
        # _get's critical section (metric construction allocates) — a
        # non-reentrant lock would deadlock that thread against itself
        self._lock = threading.RLock()

    def _get(self, cls, name: str, labels=None, **kw):
        key = _series_key(name, labels)
        m = self._metrics.get(key)
        if m is None:
            with self._lock:
                m = self._metrics.setdefault(key, cls(**kw))
        if not isinstance(m, cls):
            raise TypeError(f"metric {name!r} already registered as "
                            f"{type(m).__name__}, not {cls.__name__}")
        return m

    def counter(self, name: str, labels=None) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, labels=None) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, labels=None,
                  buckets=DEFAULT_BUCKETS_MS) -> Histogram:
        return self._get(Histogram, name, labels, buckets=buckets)

    def series(self):
        """Sorted [(name, labels, metric)] — the exporters' view.
        Materialized under the registry lock: a concurrent scrape
        (the obs endpoint's /metrics) must never iterate the metric
        dict while a submitter thread is registering a new series
        (RuntimeError: dict changed size during iteration)."""
        with self._lock:
            items = list(self._metrics.items())
        return [(n, l, m)
                for (n, l), m in sorted(items, key=lambda kv: kv[0])]

    def snapshot(self) -> dict:
        """Plain JSON-able dict keyed by the rendered series name —
        counters/gauges map to their value, histograms to
        {count, sum, min, max}. The BENCH artifact form."""
        out = {}
        for name, labels, m in self.series():
            key = format_series(name, labels)
            if m.kind == "histogram":
                st = m.stats()
                out[key] = {"count": st["count"],
                            "sum": round(st["sum"], 3),
                            "min": st["min"], "max": st["max"]}
            else:
                out[key] = m.value
        return out

    def reset(self) -> None:
        for m in self._metrics.values():
            m.zero()


# the process-global default registry — module-level helpers below and
# the instrumented call sites (parallel/shuffle.py, spans.py) all feed it
REGISTRY = MetricsRegistry()


def counter(name: str, labels=None) -> Counter:
    return REGISTRY.counter(name, labels)


def gauge(name: str, labels=None) -> Gauge:
    return REGISTRY.gauge(name, labels)


def histogram(name: str, labels=None) -> Histogram:
    return REGISTRY.histogram(name, labels)


def metrics_snapshot() -> dict:
    return REGISTRY.snapshot()


def reset_metrics() -> None:
    REGISTRY.reset()


def observe_phase(name: str, elapsed_ms: float, error: bool = False
                  ) -> None:
    """Per-span latency histogram feed (called by spans.span on close;
    the seq suffix is already stripped — label cardinality stays the
    static set of span names)."""
    REGISTRY.histogram("cylon_phase_latency_ms",
                       {"phase": name}).observe(elapsed_ms)
    if error:
        REGISTRY.counter("cylon_phase_errors_total",
                         {"phase": name}).inc()


def record_host_sync(site: str, n: int = 1) -> None:
    """One device→host round trip at a named choke point — feeds
    ``cylon_host_syncs_total{site=...}``. The sites are the
    ``jax.device_get`` calls the hostsync analysis already classifies
    as host-side-legal (count fetches, splitter samples, plan-capacity
    reads); this counter makes the round trips per query VISIBLE (each
    one costs ~100 ms through the axon tunnel). ``site`` labels must be
    static strings at the call site — label cardinality is the fixed
    set of choke points, never data."""
    REGISTRY.counter("cylon_host_syncs_total", {"site": site}).inc(n)


# Process-global MemoryPool handle (duck-typed — telemetry never
# imports memory.py): CylonContext registers its pool here so the span
# layer can sample per-span HBM deltas and the flight recorder can dump
# watermarks without threading the pool through every call site. Last
# registration wins (one pool per process in practice).
_memory_pool = None


def set_memory_pool(pool) -> None:
    global _memory_pool
    _memory_pool = pool


def get_memory_pool():
    return _memory_pool


# Build hook for the compile-cost profiler (telemetry/profiler.py):
# when installed, every counted_cache factory build passes its result
# through ``hook(factory_name, built)`` so the profiler can wrap the
# jitted program with compile-time capture. Kept as a late-bound module
# attribute so metrics (a leaf of the leaf) never imports profiler.
_factory_build_hook: Optional[Callable] = None

# Fault hook for the chaos injector (resilience/inject.py): when
# installed, ``hook(factory_name)`` runs BEFORE each counted_cache
# build and may raise a typed error — the deterministic stand-in for a
# compile OOM. lru_cache never caches exceptions, so a faulted build
# rebuilds cleanly on retry. Duck-typed like the build hook: telemetry
# stays a base-layer leaf and never imports resilience.
_factory_fault_hook: Optional[Callable] = None


def set_factory_build_hook(hook: Optional[Callable]) -> None:
    global _factory_build_hook
    _factory_build_hook = hook


def set_factory_fault_hook(hook: Optional[Callable]) -> None:
    global _factory_fault_hook
    _factory_fault_hook = hook


def counted_cache(fn: Callable) -> Callable:
    """``lru_cache(maxsize=None)`` plus a build counter — the drop-in
    decorator for the jit kernel-factory memo layer. Every cache miss
    builds (and on first call compiles) a new XLA program, so
    ``cylon_kernel_factory_builds_total{factory=...}`` IS the
    jit-recompile counter: a hot loop that grows it is paying
    compilation, not compute."""
    c = REGISTRY.counter("cylon_kernel_factory_builds_total",
                         {"factory": fn.__name__})

    def _build(*args, **kwargs):
        fault = _factory_fault_hook
        if fault is not None:
            fault(fn.__name__)  # chaos: may raise an injected error
        c.inc()
        out = fn(*args, **kwargs)
        hook = _factory_build_hook
        if hook is not None:
            out = hook(fn.__name__, out)
        return out

    cached = functools.lru_cache(maxsize=None)(_build)
    try:
        functools.update_wrapper(cached, fn)
    except Exception:  # pragma: no cover - exotic callables  # cylint: disable=errors/broad-swallow — exotic callable keeps its bare wrapper
        pass
    return cached


def sample_memory(pool, registry: Optional[MetricsRegistry] = None
                  ) -> dict:
    """Sample a ``memory.MemoryPool`` into gauges; returns the sampled
    values as a dict. Duck-typed (bytes_allocated/peak_bytes/
    bytes_limit/available_bytes/comm_budget_bytes) so the base-leaf
    layering contract holds — telemetry never imports memory.py.
    ``available``/``comm_budget`` may be None off-TPU; their gauges are
    then left untouched and the dict carries None."""
    r = registry or REGISTRY
    vals = {
        "hbm_live_bytes": int(pool.bytes_allocated()),
        "hbm_peak_bytes": int(pool.peak_bytes()),
        "hbm_limit_bytes": int(pool.bytes_limit()),
        "hbm_available_bytes": pool.available_bytes(),
        "comm_budget_bytes": pool.comm_budget_bytes(),
    }
    for key, v in vals.items():
        if v is not None:
            r.gauge(f"cylon_{key}").set(int(v))
    r.gauge("cylon_hbm_stats_available").set(
        int(vals["hbm_available_bytes"] is not None))
    return vals
