"""Kernel compile-cost profiler: what the ~24 jitted factories cost.

The engine's eager discipline (host-picked pow2 capacities, counted
``@counted_cache`` factories) bounds the number of distinct XLA
programs — but each one still pays a compile, and on a tunneled TPU
backend a recompile storm is the classic way a "fast" pipeline goes
slow. ``cylon_kernel_factory_builds_total`` counts the builds; this
module, when enabled, measures what each build's programs actually
COST:

* **compile wall time** — the program is lowered and compiled
  explicitly (``jitted.lower(*args).compile()``), the wall clock around
  ``compile()`` feeding ``cylon_kernel_compile_seconds{factory=...}``;
* **XLA cost analysis** — ``compiled.cost_analysis()`` FLOPs and bytes
  accessed, when the backend reports them (TPU does; CPU may not —
  every probe degrades gracefully to ``None``, never an error).

Mechanics: ``enable()`` installs a build hook into
``metrics.counted_cache``; every factory built afterwards returns a
``_ProfiledProgram`` proxy instead of the bare jit callable. The proxy
keeps its own (shape, dtype)-keyed executable cache: the FIRST call
with a new signature lowers + compiles + measures, then runs the
compiled executable; repeat signatures dispatch the cached executable
directly, so profiling never compiles the same program twice. Anything
unexpected (non-lowerable callable, aval mismatch, exotic backend)
falls back to calling the original jit object — profiling is strictly
additive, never a correctness risk.

Factories already memoized before ``enable()`` keep their unwrapped
programs (the lru_cache holds them); enable the profiler before first
use — bench.py does, so BENCH artifacts embed ``summary()`` under
``detail.compile_profile``.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional

from . import metrics as _metrics

# compile wall-time buckets, seconds (an elementwise program to a
# many-minute Mosaic build)
COMPILE_SECONDS_BUCKETS = (0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0,
                           60.0, 300.0)

_enabled = False
_records: List[dict] = []
_lock = threading.Lock()


def _cost_analysis(compiled):
    """(flops, bytes_accessed) from an XLA Compiled, or (None, None)
    when the backend hides them — cost_analysis may raise, return a
    list, or return a dict missing either key depending on backend and
    jax version."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # cylint: disable=errors/broad-swallow — cost_analysis is best-effort
        return None, None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None, None

    def _num(key):
        v = ca.get(key)
        return float(v) if isinstance(v, (int, float)) else None

    return _num("flops"), _num("bytes accessed")


def _signature(args):
    """Hashable (treedef, leaf aval) key for one call's inputs."""
    import jax

    leaves, treedef = jax.tree.flatten(args)
    return (str(treedef),
            tuple((getattr(x, "shape", None), str(getattr(x, "dtype", type(x))))
                  for x in leaves))


class _ProfiledProgram:
    """Proxy over one factory's jitted program: compile-on-first-call
    per input signature, with measurement. Falls back to the wrapped
    callable whenever the explicit lower/compile path cannot apply."""

    def __init__(self, factory: str, fn):
        self._factory = factory
        self._fn = fn
        self._compiled = {}

    def __call__(self, *args, **kwargs):
        if kwargs:  # factories here are positional; don't guess
            return self._fn(*args, **kwargs)
        try:
            import jax

            leaves = jax.tree.leaves(args)
            if any(isinstance(x, jax.core.Tracer) for x in leaves):
                # being traced (make_jaxpr, an enclosing jit): the
                # proxy must be transparent, not AOT-compile
                return self._fn(*args)
            key = _signature(args)
        except Exception:  # cylint: disable=errors/broad-swallow — non-lowerable program falls back to bare jit
            return self._fn(*args)
        hit = self._compiled.get(key)
        if hit is not None:
            try:
                return hit(*args)
            except Exception:  # cylint: disable=errors/broad-swallow — cost_analysis is best-effort
                # evict: a signature whose executable rejects dispatch
                # (sharding/commitment drift) must not pay a failed
                # AOT call on every subsequent exchange
                del self._compiled[key]
                return self._fn(*args)
        if not _enabled:
            return self._fn(*args)
        try:
            lowered = self._fn.lower(*args)
            t0 = time.perf_counter()
            compiled = lowered.compile()
            dt = time.perf_counter() - t0
        except Exception:  # cylint: disable=errors/broad-swallow — compile() unsupported: bare jit fallback
            # tracers (make_jaxpr/abstract eval), non-jit callables,
            # backends without AOT support: profiling bows out
            return self._fn(*args)
        flops, nbytes = _cost_analysis(compiled)
        _record(self._factory, dt, flops, nbytes)
        self._compiled[key] = compiled
        try:
            return compiled(*args)
        except Exception:  # cylint: disable=errors/broad-swallow — cost dict shape varies by backend
            # aval/sharding subtleties the signature key missed: the
            # jit object remains the source of truth
            del self._compiled[key]
            return self._fn(*args)


def _record(factory: str, seconds: float, flops, nbytes) -> None:
    _metrics.REGISTRY.histogram(
        "cylon_kernel_compile_seconds", {"factory": factory},
        buckets=COMPILE_SECONDS_BUCKETS).observe(seconds)
    if flops is not None:
        _metrics.REGISTRY.counter(
            "cylon_kernel_compile_flops_total",
            {"factory": factory}).inc(int(flops))
    if nbytes is not None:
        _metrics.REGISTRY.counter(
            "cylon_kernel_compile_bytes_accessed_total",
            {"factory": factory}).inc(int(nbytes))
    with _lock:
        _records.append({"factory": factory,
                         "compile_s": round(seconds, 6),
                         "flops": flops, "bytes_accessed": nbytes})


def _build_hook(factory: str, built):
    if not callable(built):
        return built
    return _ProfiledProgram(factory, built)


def enable() -> None:
    """Install the counted_cache build hook; factories built from now
    on capture compile cost. Idempotent."""
    global _enabled
    _enabled = True
    _metrics.set_factory_build_hook(_build_hook)


def disable() -> None:
    """Stop profiling NEW programs. Already-wrapped factories keep
    dispatching their cached executables (no re-measurement)."""
    global _enabled
    _enabled = False
    _metrics.set_factory_build_hook(None)


def enabled() -> bool:
    return _enabled


def records() -> List[dict]:
    """Every measured compile, in order: {factory, compile_s, flops,
    bytes_accessed} (cost fields None where the backend hides them)."""
    with _lock:
        return [dict(r) for r in _records]


def reset() -> None:
    with _lock:
        _records.clear()


def summary() -> dict:
    """Per-factory aggregate — the BENCH artifact form:
    {factory: {programs, compile_s, flops, bytes_accessed}} with cost
    totals None when no program reported them."""
    out: dict = {}
    for r in records():
        agg = out.setdefault(r["factory"], {
            "programs": 0, "compile_s": 0.0,
            "flops": None, "bytes_accessed": None})
        agg["programs"] += 1
        agg["compile_s"] = round(agg["compile_s"] + r["compile_s"], 6)
        for k in ("flops", "bytes_accessed"):
            if r[k] is not None:
                agg[k] = (agg[k] or 0.0) + r[k]
    return out
