"""Shuffle skew statistics from the host-fetched send-count matrices.

Every distributed operator here is *local kernel + hash-partition +
all-to-all + local kernel*, so whole-query time is dominated by the
exchanges — and an exchange is only as fast as its HOTTEST destination
shard. The count phase already fetches the full per-(src, dst) matrix
``counts[s, t]`` to the host (it picks the block geometry), so skew
observability is FREE: no extra device→host transfer, just arithmetic
over a [world, world] numpy array the host holds anyway.

``SkewStats.from_counts`` reduces that matrix to the signals that
matter:

* ``recv_rows[t] = counts[:, t].sum()`` — what shard t must absorb;
  the padded/compact capacity and the per-shard local-kernel time both
  track the WORST entry.
* ``imbalance = recv_max / recv_mean`` — 1.0 is a perfectly uniform
  hash placement; the padded route's PADDED_WASTE_FACTOR admission and
  the EXPLAIN ANALYZE skew warning both read in these units.
* min/median/max shard rows and per-shard received bytes.

The stats ride two carriers (parallel/shuffle.py attaches both):

* span attributes on ``shuffle.exchange*`` spans (``skew_imbalance``,
  ``shard_rows_min/med/max``, ``skew_warn``) — per-exchange, in the
  JSONL trace, and surfaced per Shuffle node by plan/report.py in
  ``LazyTable.explain(analyze=True)``;
* registry metrics — ``cylon_shuffle_imbalance_factor`` (histogram:
  max/mean over the run), ``cylon_shuffle_shard_rows`` and
  ``cylon_shuffle_shard_bytes`` (per-shard histograms).

The warning threshold is ``CYLON_SKEW_WARN_FACTOR`` (default 2.0 —
matching shuffle.PADDED_WASTE_FACTOR, the point where the exchange
stops routing padded and starts paying blockwise rounds).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import numpy as np

from . import knobs as _knobs
from . import metrics as _metrics

# imbalance (recv_max/recv_mean) above this renders a [SKEW] warning in
# EXPLAIN ANALYZE; aligned with shuffle.PADDED_WASTE_FACTOR by default
DEFAULT_WARN_FACTOR = _knobs.default("CYLON_SKEW_WARN_FACTOR")

# per-shard row-count histogram buckets (rows, log-spaced: one sublane
# to a full HBM-scale shard)
SHARD_ROWS_BUCKETS = (1e2, 1e3, 1e4, 1e5, 1e6, 1e7, 1e8, 1e9)

# per-shard received-bytes histogram buckets (1 KiB .. 16 GiB)
SHARD_BYTES_BUCKETS = tuple(float(1 << s)
                            for s in (10, 14, 17, 20, 23, 26, 28, 30,
                                      32, 34))

# imbalance-factor buckets: 1.0 = uniform, >= warn factor = skewed
IMBALANCE_BUCKETS = (1.0, 1.1, 1.25, 1.5, 2.0, 3.0, 5.0, 10.0, 100.0)

# full per-shard vectors ride span attrs only up to this mesh width
SPAN_ATTR_MAX_WORLD = 16


def warn_factor() -> float:
    """The configurable skew-warning threshold (env override)."""
    return _knobs.get("CYLON_SKEW_WARN_FACTOR")


@dataclass
class SkewStats:
    """Key-distribution skew of ONE exchange, reduced from its
    [world, world] send-count matrix (rows: source shard, cols:
    destination shard)."""

    world: int
    send_rows: List[int]           # counts.sum(axis=1) — per source
    recv_rows: List[int]           # counts.sum(axis=0) — per destination
    bytes_per_row: int             # payload row width (0 = unknown)

    @classmethod
    def from_counts(cls, counts, bytes_per_row: int = 0
                    ) -> Optional["SkewStats"]:
        """Reduce a host count matrix; None when there is nothing to
        measure (empty matrix or a 1-wide mesh, where every row lands
        on the only shard and skew is undefined)."""
        c = np.asarray(counts)
        if c.ndim != 2 or c.shape[0] < 2 or c.size == 0:
            return None
        return cls(world=int(c.shape[0]),
                   send_rows=[int(v) for v in c.sum(axis=1)],
                   recv_rows=[int(v) for v in c.sum(axis=0)],
                   bytes_per_row=int(bytes_per_row))

    # -- derived signals ------------------------------------------------

    @property
    def total_rows(self) -> int:
        return sum(self.recv_rows)

    @property
    def recv_bytes(self) -> List[int]:
        return [r * self.bytes_per_row for r in self.recv_rows]

    @property
    def imbalance(self) -> float:
        """max/mean of per-destination rows; 1.0 = uniform. An empty
        exchange (0 live rows) reports 1.0 — nothing is hot."""
        mean = self.total_rows / self.world
        if mean <= 0:
            return 1.0
        return max(self.recv_rows) / mean

    @property
    def rows_min(self) -> int:
        return min(self.recv_rows)

    @property
    def rows_med(self) -> int:
        return int(np.median(self.recv_rows))

    @property
    def rows_max(self) -> int:
        return max(self.recv_rows)

    @property
    def warn(self) -> bool:
        return self.imbalance >= warn_factor()

    # -- carriers -------------------------------------------------------

    def span_attrs(self) -> dict:
        """The attribute form attached to ``shuffle.exchange*`` spans
        (and read back by plan/report.py for EXPLAIN ANALYZE). Full
        per-shard send/recv vectors ride along up to
        SPAN_ATTR_MAX_WORLD — a pod slice's trace stays readable, a
        wide mesh keeps the summary (the histograms carry the
        distribution either way)."""
        attrs = {
            "skew_imbalance": round(self.imbalance, 3),
            "shard_rows_min": self.rows_min,
            "shard_rows_med": self.rows_med,
            "shard_rows_max": self.rows_max,
            "skew_warn": self.warn,
        }
        if self.world <= SPAN_ATTR_MAX_WORLD:
            attrs["shard_send_rows"] = list(self.send_rows)
            attrs["shard_recv_rows"] = list(self.recv_rows)
            if self.bytes_per_row:
                attrs["shard_recv_bytes"] = list(self.recv_bytes)
        return attrs

    def record(self, registry: Optional["_metrics.MetricsRegistry"] = None
               ) -> None:
        """Feed the registry histograms — one imbalance observation per
        exchange, one rows/bytes observation per destination shard."""
        r = registry or _metrics.REGISTRY
        r.histogram("cylon_shuffle_imbalance_factor",
                    buckets=IMBALANCE_BUCKETS).observe(self.imbalance)
        rows_h = r.histogram("cylon_shuffle_shard_rows",
                             buckets=SHARD_ROWS_BUCKETS)
        bytes_h = r.histogram("cylon_shuffle_shard_bytes",
                              buckets=SHARD_BYTES_BUCKETS)
        for rows, nbytes in zip(self.recv_rows, self.recv_bytes):
            rows_h.observe(rows)
            if self.bytes_per_row:
                bytes_h.observe(nbytes)


def observe_exchange(counts, bytes_per_row: int = 0,
                     registry=None) -> Optional[SkewStats]:
    """One-call form for the exchange sites: reduce + record; returns
    the stats (for span attachment) or None on a 1-wide mesh."""
    stats = SkewStats.from_counts(counts, bytes_per_row)
    if stats is not None:
        stats.record(registry)
    return stats
