"""Structured tracing + metrics for the TPU dataframe engine.

The reference's observability is per-phase wall-clock logging at every
operator (cpp/src/cylon/table.cpp:320-335 shuffle timers, join/join.cpp
per-phase logs). This package keeps that discipline — every label the
old flat telemetry module emitted is still emitted, byte-identical —
and grows it into a measurement layer:

* ``spans``   — hierarchical, contextvar-nested spans with typed
  attributes (rows/bytes/world/mode/error); ``phase``/``collect_phases``
  are thin back-compat wrappers over it, so every pre-existing call
  site participates in the span tree unchanged.
* ``metrics`` — process-local counters (shuffle bytes, rows exchanged,
  collective launches, kernel-factory builds = jit recompiles),
  per-phase latency histograms, and HBM gauges sampled from
  ``memory.MemoryPool`` (duck-typed; telemetry stays a base-layer
  leaf).
* ``export``  — JSONL span sink and Prometheus text dump; the
  ``jax.profiler.TraceAnnotation`` carrier stays inside ``span`` so
  Perfetto labels work with no exporter configured.
* ``skew``    — key-distribution skew stats reduced from the exchange
  count matrices the host already fetches (zero extra syncs): per-shard
  send/recv rows+bytes histograms, imbalance factor, EXPLAIN ANALYZE
  warning threshold.
* ``profiler`` — opt-in kernel compile-cost capture hooked into
  ``counted_cache``: compile wall time + XLA cost analysis per factory
  program (``cylon_kernel_compile_seconds{factory=...}``).
* ``ledger``  — buffer lifetime ledger: materializing ops register
  alloc/free events with owner labels
  (``cylon_live_table_bytes{owner=...}``), per-span HBM deltas ride
  every span as ``hbm_delta``/``hbm_peak`` attrs, and the plan
  executor renders an end-of-query leak report.
* ``flight``  — query flight recorder: a bounded ring of recent root
  span trees plus, on any exception crossing a root span, a JSON
  crash dump (span stack, metrics snapshot, pool watermarks, ledger
  outstanding set) written to ``CYLON_FLIGHT_DIR``.
* ``querylog`` — structured query log: one digest per completed root
  query span (id, tenant, plan fingerprint, outcome, shuffle/retry/
  HBM aggregates) in an in-memory ring + optional rotating JSONL
  file — the join key between traces, metrics and crash dumps.
* ``slo``     — per-tenant latency objectives: fixed-bucket latency
  histograms with p50/p95/p99 estimation, error-budget accounting
  (``CYLON_SLO_P95_MS`` / ``CYLON_SLO_TARGET``), burn events into the
  flight admission ring.
* ``stats``   — the query statistics warehouse: per-fingerprint
  measured EWMAs fed by the querylog hook, per-node-kind q-error
  histograms (estimate accuracy), drift detection with plan-cache
  eviction, stats-informed admission estimates
  (``min(static, ewma x CYLON_STATS_SAFETY)``), JSONL warm-start
  persistence (``CYLON_STATS_PATH``).
* ``sampling`` — overhead-bounded head sampling for root query spans
  (``CYLON_TRACE_SAMPLE_RATE``, deterministic on the query-id hash):
  sampled-out queries keep counters/histograms/querylog but skip
  trace-sink writes; errored queries always promote to fully
  recorded.

The plan executor builds per-query EXPLAIN ANALYZE reports
(plan/report.py) on this layer; docs/telemetry.md documents the span
model, the attribute catalog and both exporter formats.

Layering: this package is a BASE-LAYER LEAF (analysis/layering.py
``telemetry-leaf`` contract) — it imports nothing from the package but
its own submodules, and its underscore names are module-private
(``layering/private-internals``).
"""
from __future__ import annotations

from .spans import (Span, annotate, collect_phases, current_span,
                    log_to_stderr, logger, phase, root_attrs, span,
                    add_sink, remove_sink, add_root_hook,
                    remove_root_hook)
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                      REGISTRY, counted_cache, counter, gauge, histogram,
                      metrics_snapshot, record_host_sync, reset_metrics,
                      sample_memory, set_memory_pool, get_memory_pool)
from .export import JsonlSpanSink, prometheus_text, span_to_json
from . import knobs, ledger, profiler, sampling, skew
from . import flight
from . import stats
from . import querylog, slo
from .skew import SkewStats

__all__ = [
    # spans
    "Span", "annotate", "collect_phases", "current_span", "log_to_stderr",
    "logger", "phase", "root_attrs", "span", "add_sink", "remove_sink",
    "add_root_hook", "remove_root_hook",
    # metrics
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "counted_cache", "counter", "gauge", "histogram", "metrics_snapshot",
    "record_host_sync", "reset_metrics", "sample_memory",
    "set_memory_pool", "get_memory_pool",
    # exporters
    "JsonlSpanSink", "prometheus_text", "span_to_json",
    # skew + compile-cost + memory-lifetime + failure observability
    "profiler", "skew", "SkewStats", "ledger", "flight",
    # live-service observability: query digests, per-tenant SLOs,
    # overhead-bounded trace sampling
    "querylog", "slo", "sampling",
    # the query statistics warehouse: measured per-fingerprint stats,
    # q-error observatory, drift detection, stats-informed admission
    "stats",
    # the declared CYLON_* environment-knob registry
    "knobs",
]
