"""Query flight recorder: a ring of recent query trees + crash dumps.

An OOM or collective failure on an 8-wide mesh usually kills the whole
controller process; the log line that would have explained it was never
written. The flight recorder makes failures diagnosable post-mortem,
the way an aircraft recorder does — always on, bounded, and dumped to
disk the moment something goes wrong:

* **ring** — the last ``CYLON_FLIGHT_RING`` (default 16) completed ROOT
  span trees (whole queries / top-level eager ops), kept in memory via
  a root-span close hook (spans.add_root_hook). ``recent()`` returns
  them for interactive post-hoc inspection.
* **crash dump** — when a root span closes with ``error=True`` and
  ``CYLON_FLIGHT_DIR`` is set, ONE JSON file is written there
  containing everything a post-mortem needs:

  - the full span tree of the failed query (attrs included — the
    ``hbm_delta``/``hbm_peak`` trail shows where memory went);
  - the **error path**: root → deepest errored span, i.e. the exact
    in-flight span stack at the moment the exception crossed each
    frame (inner spans close first on a raise, each marked
    ``error=True``);
  - the metrics-registry snapshot (counters, per-phase latencies,
    host-sync counts — everything docs/telemetry.md catalogs);
  - MemoryPool watermarks (``snapshot()`` + available/comm budget —
    ledger-backed on stats-hidden backends, so never blindly zero);
  - the ledger's outstanding allocation set (which tables were live,
    who allocated them, under which span);
  - CYLON/JAX/XLA environment and the jax backend.

Dumps are written only when ``CYLON_FLIGHT_DIR`` names a directory
(checked at crash time, so tests/operators can arm it dynamically);
the ring is always on and costs one deque append per root span. The
dump directory is BOUNDED: after each write the oldest dumps beyond
``CYLON_FLIGHT_MAX_DUMPS`` (default 32) are rotated out, so a
crash-looping service cannot fill the disk with forensics.

The resilience layer records into two extension points here:

* **admission ring** — ``record_admission()`` keeps the last ring-size
  admission-controller decisions (admit/degrade/shed); a shed query
  leaves the same forensic trail as a crashed one. The ring doubles as
  the operational event journal: the SLO tracker's ``slo_burn``
  events and the statistics warehouse's ``stats_drift`` /
  ``stats_quarantine`` events (telemetry/stats.py) land here too, so
  every admission-adjacent incident rides crash dumps.
* **dump sections** — ``add_dump_section(name, provider)`` registers a
  zero-arg provider whose result is embedded in every crash dump (the
  fault injector registers its armed-plan/fired-events state, so a
  chaos dump names its own cause). Providers that raise contribute an
  error note, never mask the dump.
"""
from __future__ import annotations

import itertools
import json
import os
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from . import knobs as _knobs
from . import ledger as _ledger
from . import metrics as _metrics
from . import spans as _spans

DUMP_SCHEMA_VERSION = 2

DEFAULT_RING_SIZE = _knobs.default("CYLON_FLIGHT_RING")

DEFAULT_MAX_DUMPS = _knobs.default("CYLON_FLIGHT_MAX_DUMPS")


def _ring_size() -> int:
    return _knobs.get("CYLON_FLIGHT_RING")


def _max_dumps() -> int:
    return _knobs.get("CYLON_FLIGHT_MAX_DUMPS")


_ring: deque = deque(maxlen=_ring_size())
_admissions: deque = deque(maxlen=_ring_size())
# itertools.count: dump sequence allocation is atomic — root spans can
# close errored on several threads at once, and a racy `+= 1` would
# hand two dumps the same filename (the second silently overwrites the
# first crash's forensics)
_dump_seq = itertools.count(1)

# crash-dump section providers: name -> zero-arg callable returning a
# JSON-able value (resilience/inject registers its fault state here)
_dump_sections: Dict[str, Callable[[], object]] = {}


def recent() -> List[object]:
    """The most recent completed root spans, oldest first."""
    return list(_ring)


def last_dump_path() -> Optional[str]:
    """Path of the most recent crash dump this process wrote, or None."""
    return getattr(_on_root_close, "_last_dump", None)


def record_admission(doc: dict) -> None:
    """Append one admission-controller decision to the admission ring
    (bounded like the query ring; included in every crash dump)."""
    _admissions.append(dict(doc))


def admissions() -> List[dict]:
    """The most recent admission decisions, oldest first."""
    return [dict(d) for d in _admissions]


def add_dump_section(name: str, provider: Callable[[], object]) -> None:
    """Register a named crash-dump section: ``provider()`` runs at dump
    time and its result is embedded under ``sections[name]``. Last
    registration per name wins."""
    _dump_sections[name] = provider


def remove_dump_section(name: str) -> None:
    _dump_sections.pop(name, None)


def error_path(root) -> List[object]:
    """Root → deepest errored descendant: the in-flight span stack at
    failure time (on a raise, inner spans close first with error=True,
    so the errored chain IS the stack the exception unwound)."""
    out = []
    node = root
    while node is not None:
        out.append(node)
        nxt = None
        for c in node.children:
            if c.error:
                nxt = c   # last errored child = innermost at unwind
        node = nxt
    return out


def _pool_watermarks() -> dict:
    pool = _metrics.get_memory_pool()
    if pool is None:
        return {}
    try:
        used, peak, limit = pool.snapshot()
        return {"bytes_in_use": int(used), "peak_bytes": int(peak),
                "bytes_limit": int(limit),
                "available_bytes": pool.available_bytes(),
                "comm_budget_bytes": pool.comm_budget_bytes()}
    except Exception:  # pragma: no cover - defensive  # cylint: disable=errors/broad-swallow — watermarks are optional forensics
        return {}


def _environment() -> dict:
    import jax

    env = {k: v for k, v in os.environ.items()
           if k.startswith(("CYLON", "JAX_", "XLA_"))}
    try:
        backend = jax.default_backend()
        n_devices = jax.device_count()
    except Exception:  # pragma: no cover - defensive  # cylint: disable=errors/broad-swallow — environment probe is optional forensics
        backend, n_devices = None, None
    return {"env": env, "backend": backend, "device_count": n_devices,
            "pid": os.getpid()}


def crash_dump_doc(root) -> dict:
    """The crash-dump document for one errored root span (pure —
    write_crash_dump serializes it; tests inspect it directly)."""
    sections = {}
    for name, provider in list(_dump_sections.items()):
        try:
            sections[name] = provider()
        except Exception as e:  # pragma: no cover - defensive  # cylint: disable=errors/broad-swallow — a failing section provider must not mask the dump
            sections[name] = {"error": f"{type(e).__name__}: {e}"}
    return {
        "kind": "cylon-flight-crash-dump",
        "version": DUMP_SCHEMA_VERSION,
        "time_unix": time.time(),
        "root_label": root.label,
        "query": root.to_dict(nested=True),
        "error_path": [s.to_dict() for s in error_path(root)],
        "metrics": _metrics.metrics_snapshot(),
        "pool": _pool_watermarks(),
        "ledger_outstanding": _ledger.outstanding(),
        "recent_queries": [s.label for s in _ring],
        "admissions": list(admissions()),
        "sections": sections,
        "environment": _environment(),
    }


def write_crash_dump(root, directory: Optional[str] = None
                     ) -> Optional[str]:
    """Serialize one errored root span tree to a single JSON file in
    ``directory`` (default ``CYLON_FLIGHT_DIR``); returns the path, or
    None when no directory is configured. Never raises — a failing
    forensics path must not mask the original error."""
    directory = directory or _knobs.get("CYLON_FLIGHT_DIR")
    if not directory:
        return None
    try:
        os.makedirs(directory, exist_ok=True)
        seq = next(_dump_seq)
        name = (f"cylon-crash-{os.getpid()}-{seq:03d}-"
                f"{root.name.replace('/', '_')}.json")
        path = os.path.join(directory, name)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(crash_dump_doc(root), f, default=str, indent=2,
                      sort_keys=True)
        _spans.logger.warning("flight recorder: crash dump written to %s",
                              path)
        _on_root_close._last_dump = path
        _rotate_dumps(directory)
        return path
    except Exception:  # pragma: no cover - defensive
        _spans.logger.exception("flight recorder: crash dump failed")
        return None


def _rotate_dumps(directory: str) -> None:
    """Bound the dump directory to ``CYLON_FLIGHT_MAX_DUMPS`` files:
    delete the oldest ``cylon-crash-*.json`` beyond the cap (by mtime,
    name as the tiebreak) so a crash-looping service cannot fill the
    disk with forensics. Never raises — rotation is best-effort."""
    try:
        cap = _max_dumps()
        dumps = []
        for name in os.listdir(directory):
            if name.startswith("cylon-crash-") and \
                    name.endswith(".json"):
                p = os.path.join(directory, name)
                try:
                    dumps.append((os.path.getmtime(p), name, p))
                except OSError:  # pragma: no cover - raced deletion
                    continue
        if len(dumps) <= cap:
            return
        dumps.sort()
        for _mtime, _name, p in dumps[:len(dumps) - cap]:
            try:
                os.remove(p)
            except OSError:  # pragma: no cover - raced deletion
                continue
        _spans.logger.warning(
            "flight recorder: rotated %d old crash dump(s) "
            "(CYLON_FLIGHT_MAX_DUMPS=%d)", len(dumps) - cap, cap)
    except Exception:  # pragma: no cover - defensive
        _spans.logger.exception("flight recorder: dump rotation failed")


def _on_root_close(root) -> None:
    if root.error:
        # dump BEFORE ring insertion so recent_queries lists the
        # queries that PRECEDED the failure
        write_crash_dump(root)
    if root.name in ("plan.preflight", "plan.admission"):
        # the default execute() path emits these warning/decision
        # markers as parentless spans; they are not query trees —
        # letting them into the ring would evict the real query
        # history the forensics depend on (admission decisions have
        # their own ring: record_admission)
        return
    _ring.append(root)


# always on: the hook costs one deque append per root span; dumps are
# gated on CYLON_FLIGHT_DIR at crash time
_spans.add_root_hook(_on_root_close)


def reset() -> None:
    """Clear the query + admission rings (test isolation); re-reads the
    ring-size env."""
    global _ring, _admissions
    _ring = deque(maxlen=_ring_size())
    _admissions = deque(maxlen=_ring_size())
