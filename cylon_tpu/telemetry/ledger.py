"""Buffer lifetime ledger: who owns which table's HBM, and for how long.

The reference routes every allocation through a ``MemoryPool``
(reference: cpp/src/cylon/ctx/memory_pool.hpp:25-66), so the runtime
always knows who holds which buffer. On TPU the allocator is XLA's HBM
arena and the pool became passive accounting (memory.py) — which left
the observability stack able to say a query was slow, but not WHERE the
HBM went or which table leaked it. The ledger closes that gap with
explicit lifetime events:

* **alloc** — every materializing ``distributed_*`` op and every plan
  executor lowering registers its output via ``track(table, owner)``
  (the ``ledger-coverage`` analysis family enforces the coverage, the
  way ``span-coverage`` enforces spans). The entry records the owner
  label, device bytes (``Table.nbytes`` — shape math, no sync), the
  enclosing root span, and a weakref to the table.
* **free** — ``Table.clear()`` (and therefore ``_free_if_unretained``
  and ``finalize``) reports the release; a table collected by the
  garbage collector reports through its weakref callback. Either way
  the entry retires and the gauge drops.

What this buys:

* ``cylon_live_table_bytes{owner=...}`` gauges — live tracked bytes per
  owner label, in every Prometheus dump and BENCH artifact;
* ``live_bytes()`` — the pool's fallback live-HBM source on backends
  that hide ``memory_stats`` (memory.MemoryPool.set_external_source),
  so span ``hbm_delta``/``hbm_peak`` attrs and crash-dump watermarks
  stay nonzero even through the axon tunnel (and on the CPU test mesh);
* ``leak_report(root_id)`` — the end-of-query leak report: tables
  allocated under the query's root span and never freed
  (plan/executor.execute_analyzed renders it into EXPLAIN ANALYZE);
* ``outstanding()`` — the crash-dump "what was in flight" set
  (telemetry/flight.py).

Entries are weakref-anchored, so the ledger never extends a table's
lifetime; owner labels must be static strings at the call site (label
cardinality is the fixed set of operators, never data). ``borrowed=True``
marks tables the engine did not allocate (plan Scan inputs): they count
toward ``live_bytes`` but are excluded from leak reports — the user
holds them by design.

Accounting granularity: an ENTRY's ``nbytes`` is its table's full
buffer footprint (what a leak pins), while ``live_bytes()`` sums
DISTINCT live buffers — zero-copy views (project/filter outputs share
their input's columns) refcount the shared buffers instead of
double-counting them, so the pool's fallback watermark tracks real
memory, not table-object multiplicity.
"""
from __future__ import annotations

import itertools
import threading
import time
import weakref
from typing import Dict, List, Optional

import numpy as np

from . import metrics as _metrics
from . import spans as _spans

# RLock: weakref-retire callbacks can fire at any allocation point,
# including on a thread already inside a ledger critical section
_lock = threading.RLock()
_entries: Dict[int, "_Entry"] = {}   # id(table) -> live entry
_buffers: Dict[int, list] = {}       # id(buffer) -> [refcount, bytes]
_live_total = 0                      # sum of DISTINCT live buffer bytes
_event_ids = itertools.count(1)


class _Entry:
    __slots__ = ("event_id", "owner", "nbytes", "root_id", "label",
                 "borrowed", "t0", "wr", "buf_ids")

    def __init__(self, event_id, owner, nbytes, root_id, label, borrowed):
        self.event_id = event_id
        self.owner = owner
        self.nbytes = nbytes
        self.root_id = root_id
        self.label = label
        self.borrowed = borrowed
        self.wr = None           # set by track()
        self.buf_ids = ()        # id() of every referenced buffer
        self.t0 = time.monotonic()

    def to_dict(self) -> dict:
        return {"event_id": self.event_id, "owner": self.owner,
                "nbytes": self.nbytes, "root_id": self.root_id,
                "span": self.label, "borrowed": self.borrowed,
                "age_s": round(time.monotonic() - self.t0, 3)}


def _gauge(owner: str):
    return _metrics.REGISTRY.gauge("cylon_live_table_bytes",
                                   {"owner": owner})


def _buffer_bytes(arr) -> int:
    try:
        return int(np.dtype(arr.dtype).itemsize) * \
            int(np.prod(arr.shape))
    except Exception:  # pragma: no cover - exotic leaf  # cylint: disable=errors/broad-swallow — exotic leaf contributes 0 bytes
        return 0


def _charge_buffers(table) -> tuple:
    """Refcount every buffer of ``table`` into the distinct-buffer map
    (adding unseen ones to the live total); returns their ids. Tables
    without a ``buffers()`` enumeration contribute nothing distinct —
    their entry still carries the footprint. Caller holds _lock; a
    tracked entry's buffers stay alive exactly as long as the entry
    (clear() releases BEFORE dropping columns), so raw ids cannot be
    recycled while held here."""
    global _live_total
    try:
        bufs = table.buffers()
    except Exception:  # cylint: disable=errors/broad-swallow — no buffers() enumeration: nothing distinct
        return ()
    ids = []
    for b in bufs:
        k = id(b)
        ids.append(k)
        rec = _buffers.get(k)
        if rec is not None:
            rec[0] += 1
        else:
            nb = _buffer_bytes(b)
            _buffers[k] = [1, nb]
            _live_total += nb
    return tuple(ids)


def _discharge_buffers(buf_ids) -> None:
    """Caller holds _lock."""
    global _live_total
    for k in buf_ids:
        rec = _buffers.get(k)
        if rec is None:  # pragma: no cover - defensive
            continue
        rec[0] -= 1
        if rec[0] <= 0:
            _live_total -= rec[1]
            del _buffers[k]


def track(table, owner: str, borrowed: bool = False):
    """Register one table's buffers under ``owner`` and return the
    table (so call sites can wrap return expressions). Re-tracking a
    live table re-attributes it to the NEW owner — the plan executor's
    ``plan.*`` label supersedes the distributed op's, so leak reports
    name the query node that allocated, not just the mechanism."""
    if table is None:
        return table
    try:
        nbytes = int(table.nbytes)
    except Exception:  # pragma: no cover - defensive (cleared tables)  # cylint: disable=errors/broad-swallow — cleared table tracks at 0 bytes
        nbytes = 0
    cur = _spans.current_span()
    root_id = cur.root_id if cur is not None else 0
    label = cur.label if cur is not None else None
    key = id(table)
    with _lock:
        old = _entries.get(key)
        if old is not None and old.wr() is table:
            # same live object: move the bytes between owner gauges and
            # refresh the attribution; the weakref (and its callback)
            # stays — one retire per table, however many tracks
            g_old = _gauge(old.owner)
            g_old.set(g_old.value - old.nbytes)
            old.owner = owner
            old.nbytes = nbytes
            old.root_id = root_id or old.root_id
            old.label = label or old.label
            # borrowed is STICKY once set: a prior query's result
            # re-entering as a Scan input is user-held — re-rooting it
            # under the new query must not turn it into a false leak
            old.borrowed = borrowed or old.borrowed
            g = _gauge(owner)
            g.set(g.value + nbytes)
            return table
        entry = _Entry(next(_event_ids), owner, nbytes, root_id, label,
                       borrowed)
        entry.wr = weakref.ref(table, lambda _wr, k=key: _retire(k))
        entry.buf_ids = _charge_buffers(table)
        _entries[key] = entry
        g = _gauge(owner)
        g.set(g.value + nbytes)
    return table


def release(table) -> bool:
    """Explicit free event (Table.clear / _free_if_unretained). Returns
    True when a live entry retired; unknown tables are a no-op."""
    if table is None:
        return False
    key = id(table)
    with _lock:
        entry = _entries.get(key)
        if entry is None or entry.wr() is not table:
            return False
    _retire(key)
    return True


def _retire(key: int) -> None:
    with _lock:
        entry = _entries.pop(key, None)
        if entry is None:
            return
        _discharge_buffers(entry.buf_ids)
        g = _gauge(entry.owner)
        g.set(g.value - entry.nbytes)


def live_bytes() -> int:
    """Total DISTINCT tracked live buffer bytes (shared-buffer views
    refcount, never double-count) — the MemoryPool's external fallback
    source on backends that hide memory_stats."""
    return _live_total  # cylint: disable=concurrency/lock-discipline — single int read under the GIL; the watermark fallback tolerates momentary staleness, and taking _lock here would serialize every pool snapshot


def outstanding(include_borrowed: bool = True) -> List[dict]:
    """Every live entry (oldest first) — the crash dump's in-flight
    allocation set."""
    with _lock:
        out = [e.to_dict() for e in _entries.values()
               if include_borrowed or not e.borrowed]
    out.sort(key=lambda d: d["event_id"])
    return out


def leak_report(root_id: int, exclude: Optional[set] = None
                ) -> List[dict]:
    """Tables allocated under ``root_id``'s span tree and never freed —
    the end-of-query leak report. ``exclude`` holds id(table) values
    that are legitimate survivors (the query's own result). Borrowed
    (Scan-input) entries never count: the user holds them by design."""
    exclude = exclude or set()
    with _lock:
        out = [e.to_dict() for k, e in _entries.items()
               if e.root_id == root_id and not e.borrowed
               and k not in exclude]
    out.sort(key=lambda d: d["event_id"])
    return out


def leak_count() -> int:
    """Live non-borrowed entries, any root — the BENCH artifact's
    whole-run leak signal."""
    with _lock:
        return sum(1 for e in _entries.values() if not e.borrowed)


def reset() -> None:
    """Drop every entry and zero the owner gauges (test isolation)."""
    global _live_total
    with _lock:
        owners = {e.owner for e in _entries.values()}
        _entries.clear()
        _buffers.clear()
        _live_total = 0
        for o in owners:
            _gauge(o).set(0)
