"""The declared ``CYLON_*`` environment-knob registry.

Every tunable the engine reads from the environment is declared HERE —
name, type, default, floor, one-line doc — and read through
:func:`get`. That single chokepoint buys three things the old ad-hoc
``os.environ.get`` sprawl could not:

* **one parse policy** — unset or malformed values read as the declared
  default, ``lo`` floors numeric knobs (absorbing the old
  ``metrics.env_number``); a future policy change (logging malformed
  values, say) lands everywhere at once;
* **a generated reference** — :func:`render_table` emits the
  docs/telemetry.md knob table (``python -m cylon_tpu.telemetry.knobs``
  regenerates it), so the docs can never silently drift from the code;
* **lintability** — the ``envknobs`` analysis family rejects any
  ``CYLON_*`` read of ``os.environ``/``os.getenv`` outside this module
  and any :func:`get` of an undeclared name, so a new knob cannot ship
  undeclared or undocumented.

Reads are LIVE (each :func:`get` consults ``os.environ``), so tests and
operators can flip a knob at any time — nothing is latched at import.

Layering: this module is the leaf of the telemetry leaf — it imports
nothing but the stdlib, so even the base-layer modules (``memory.py``)
may read their knobs through it (the ``base-leaf`` contract carves out
exactly ``telemetry.knobs``).
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional

_TRUTHY = ("1", "true", "yes", "on")
_FALSY = ("0", "false", "no", "off")


def parse_number(raw: Optional[str], default, lo=None,
                 as_int: bool = False):
    """Pure numeric parse — THE policy behind every numeric knob:
    ``None`` or malformed reads as ``default``, ``lo`` floors the
    result."""
    if raw is None:
        return default
    try:
        v = int(raw) if as_int else float(raw)
    except ValueError:
        return default
    return max(v, lo) if lo is not None else v


def env_number(name: str, default, lo=None, as_int: bool = False):
    """:func:`parse_number` over a live ``os.environ`` read. Exposed
    for the rare caller that needs the raw policy; everything in-tree
    goes through a declared :class:`Knob` and :func:`get`."""
    return parse_number(os.environ.get(name), default, lo=lo,
                        as_int=as_int)


@dataclass(frozen=True)
class Knob:
    """One declared environment knob.

    ``kind`` is ``int`` / ``float`` / ``bool`` / ``str``; ``default``
    is returned when the variable is unset or malformed; ``lo`` floors
    numeric values; ``doc`` is the one-line description the generated
    docs table renders."""

    name: str
    default: object
    kind: str
    doc: str
    lo: Optional[float] = None

    def parse(self, raw: Optional[str]):
        if raw is None:
            return self.default
        if self.kind == "str":
            return raw
        if self.kind == "bool":
            v = raw.strip().lower()
            if v in _TRUTHY:
                return True
            if v in _FALSY:
                return False
            return self.default
        return parse_number(raw, self.default, lo=self.lo,
                            as_int=self.kind == "int")

    def get(self):
        return self.parse(os.environ.get(self.name))

    def default_str(self) -> str:
        if self.default is None:
            return "unset"
        if self.kind == "bool":
            return "1" if self.default else "0"
        return str(self.default)


# name -> Knob, in declaration order (the docs-table order)
KNOBS: "Dict[str, Knob]" = {}


def declare(name: str, default, kind: str, doc: str,
            lo: Optional[float] = None) -> Knob:
    """Register one knob; re-declaring a name is a programming error
    (two owners would disagree about defaults)."""
    if kind not in ("int", "float", "bool", "str"):
        raise ValueError(f"knob {name!r}: unknown kind {kind!r}")
    if name in KNOBS:
        raise ValueError(f"knob {name!r} already declared")
    k = Knob(name, default, kind, doc, lo)
    KNOBS[name] = k
    return k


def _require(name: str) -> Knob:
    k = KNOBS.get(name)
    if k is None:
        raise KeyError(
            f"{name!r} is not a declared knob (telemetry/knobs.py); "
            f"declared: {sorted(KNOBS)}")
    return k


def get(name: str):
    """The current value of a declared knob (live ``os.environ``
    read; unset/malformed -> the declared default)."""
    return _require(name).get()


def default(name: str):
    """A declared knob's default — the single source the per-module
    ``DEFAULT_*`` re-exports bind to."""
    return _require(name).default


def render_table() -> str:
    """The markdown knob-reference table embedded in docs/telemetry.md
    (``python -m cylon_tpu.telemetry.knobs`` regenerates it; the
    ``envknobs`` analysis family checks every declared name appears)."""
    lines = ["| knob | type | default | description |",
             "|---|---|---|---|"]
    for k in KNOBS.values():
        lines.append(f"| `{k.name}` | {k.kind} | `{k.default_str()}` "
                     f"| {k.doc} |")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# the catalog — every CYLON_* tunable in the package, one row each.
# Grouped by owner; the owning module re-exports its DEFAULT_* via
# default() so there is exactly one copy of each value.
# ---------------------------------------------------------------------------

# memory.py
declare("CYLON_HBM_BYTES", 16 * (1 << 30), "int",
        "per-chip HBM fallback when the runtime hides memory_stats "
        "(tunneled backends); sizes the >HBM routing guards and the "
        "shuffle comm budget", lo=1)

# telemetry/
declare("CYLON_TRACE_SAMPLE_RATE", 1.0, "float",
        "head-sampling rate for root query spans (0..1), decided "
        "deterministically from the query_id hash; sampled-out queries "
        "keep counters/histograms/querylog but skip trace-sink writes, "
        "and errored queries are always promoted to fully recorded",
        lo=0.0)
declare("CYLON_SPAN_LOG_MAX_BYTES", 0, "int",
        "size bound for file-backed JSONL sinks (span trace and query "
        "log): past it the file rotates (keep-3 .1/.2/.3 suffixes); "
        "0 = unbounded", lo=0)
declare("CYLON_HBM_SPAN_ATTRS", True, "bool",
        "sample the registered MemoryPool at span enter/exit for "
        "hbm_delta/hbm_peak attrs; 0 skips the two per-span snapshots "
        "on latency-critical runs")
declare("CYLON_SKEW_WARN_FACTOR", 2.0, "float",
        "exchange imbalance factor (max/mean destination rows) beyond "
        "which spans gain skew_warn and EXPLAIN ANALYZE marks [SKEW]",
        lo=1.0)
declare("CYLON_FLIGHT_RING", 16, "int",
        "completed root-span trees (and admission decisions) the "
        "flight recorder keeps in memory", lo=1)
declare("CYLON_FLIGHT_DIR", None, "str",
        "directory for crash dumps when a root span closes errored; "
        "unset disables dumps (the ring stays on)")
declare("CYLON_FLIGHT_MAX_DUMPS", 32, "int",
        "crash-dump files kept in CYLON_FLIGHT_DIR before oldest-first "
        "rotation", lo=1)

# parallel/shuffle.py (the chunked, double-buffered exchange)
declare("CYLON_EXCHANGE_OVERLAP", True, "bool",
        "chunk the padded-mode exchange and pipeline chunk N+1's "
        "all_to_all against chunk N's compaction (async dispatch + "
        "donated double buffers); 0 falls back to the single-shot "
        "monolithic exchange program")
declare("CYLON_EXCHANGE_CHUNK_BYTES", 1 << 26, "int",
        "target payload bytes per exchange chunk and per shard "
        "(across all destinations); the chunk block is pow2-floored "
        "from it and the chunk count is capped at MAX_CHUNKS per "
        "exchange", lo=1 << 12)
declare("CYLON_PARTITION_KERNEL", "auto", "str",
        "partition path of the padded exchange: auto routes to the "
        "fused Pallas histogram+scatter kernel on TPU (small worlds) "
        "and the XLA stable sort elsewhere; sort forces the sort "
        "everywhere (the exact pre-kernel program); pallas forces the "
        "kernel (Pallas interpreter off-TPU — tests). Bit-identical "
        "on every live row either way")

# plan/
declare("CYLON_TPU_VERIFY_PLANS", False, "bool",
        "debug assert: re-derive partitioning witnesses over every "
        "optimized (and cache-hit) plan via plan/verify.py, raising on "
        "unjustified elisions (tests/conftest.py enables it)")

# resilience/
declare("CYLON_RETRY_MAX", 3, "int",
        "total attempts per retryable stage (exchange dispatch, "
        "ingest reads)", lo=1)
declare("CYLON_RETRY_BACKOFF_S", 0.05, "float",
        "base backoff before attempt 2, doubling per retry — "
        "deterministic, no jitter", lo=0.0)
declare("CYLON_QUERY_DEADLINE_S", None, "float",
        "per-query wall-clock budget; expiry raises CylonTimeoutError "
        "at the next node/retry boundary")
declare("CYLON_SHED_FACTOR", 8.0, "float",
        "admission controller sheds when the worst node estimate "
        "exceeds this multiple of the byte budget", lo=1.0)
declare("CYLON_FAULT_PLAN", None, "str",
        "armed chaos fault plan (site:trigger:kind[,...]) — see "
        "docs/resilience.md for the grammar")

# service/
declare("CYLON_SERVICE_QUEUE_MAX", 256, "int",
        "total service queue bound; beyond it submit() raises typed "
        "backpressure before enqueue", lo=1)
declare("CYLON_SERVICE_QUANTUM_BYTES", 1 << 20, "int",
        "deficit-round-robin quantum added per sweep visit (the "
        "fair-share byte unit)", lo=1)
declare("CYLON_PLAN_CACHE_MAX", 64, "int",
        "plan/fingerprint cache entries (0 disables the cache)", lo=0)
declare("CYLON_OBS_PORT", 0, "int",
        "TCP port for the observability HTTP endpoint (/metrics, "
        "/healthz, /queries, /slo, /stats) the QueryService starts "
        "on a daemon thread; 0 disables it", lo=0)

# telemetry/slo.py (per-tenant service-level objectives)
declare("CYLON_SLO_P95_MS", None, "float",
        "declared per-tenant latency objective: the p95 query latency "
        "(ms) the service promises; unset = no objective, SLO "
        "evaluation reports latency quantiles only", lo=0.0)
declare("CYLON_SLO_TARGET", 0.99, "float",
        "fraction of queries that must meet the latency objective "
        "(the SLO target); the error budget is the allowed 1-target "
        "violation share, and burn events land in the flight "
        "admission ring", lo=0.0)

# telemetry/stats.py (the query statistics warehouse)
declare("CYLON_STATS_MIN_OBS", 3, "int",
        "successful observations a fingerprint needs before its "
        "measured EWMA informs admission estimates (below it the "
        "static upper bound rules); also the drift-detection floor",
        lo=1)
declare("CYLON_STATS_SAFETY", 1.5, "float",
        "headroom multiplier on the measured EWMA when it replaces a "
        "static estimate: effective = min(static, ewma x safety) — "
        "never above the static bound", lo=1.0)
declare("CYLON_STATS_DRIFT_FACTOR", 4.0, "float",
        "a new measurement deviating beyond this ratio from the EWMA "
        "(either direction) fires cylon_stats_drift_total, records a "
        "flight-ring event, evicts the plan-cache entry and resets "
        "the learned stats to re-learn from the new regime", lo=1.0)
declare("CYLON_STATS_PATH", None, "str",
        "JSONL persistence path for the statistics warehouse: saved "
        "on QueryService.close(), loaded on start() so a fresh "
        "replica warm-starts its estimates; a corrupt file is "
        "quarantined (renamed aside), never fatal")

# plan/optimizer.py (adaptive join execution — stats-driven rewrites)
declare("CYLON_JOIN_ALGORITHM", "auto", "str",
        "distributed-join algorithm policy: auto lets the optimizer "
        "rewrite shuffle joins to broadcast-hash joins from measured "
        "build-side statistics; shuffle disables every adaptive "
        "rewrite (the exact pre-adaptive program); broadcast forces "
        "the broadcast path on every eligible join shape")
declare("CYLON_BROADCAST_MAX_BYTES", 1 << 22, "int",
        "broadcast-hash-join budget: a join side whose MEASURED size "
        "(EWMA x CYLON_STATS_SAFETY) fits under this many bytes may "
        "be replicated to every shard instead of hash-exchanged "
        "(requires CYLON_STATS_MIN_OBS successful observations and a "
        "probe side measured at least BROADCAST_MIN_RATIO x larger); "
        "0 disables the rewrite", lo=0)
declare("CYLON_SALT_FACTOR", 4, "int",
        "hot-key salting spread: a standalone exchange whose measured "
        "skew crossed CYLON_SKEW_WARN_FACTOR splits each hot "
        "destination's rows across this many sub-buckets (consecutive "
        "shards; pow2-floored — the factor keys one compiled program "
        "per octave), bounding the max shard under Zipfian keys; 0 or "
        "1 disables salting", lo=0)


if __name__ == "__main__":  # pragma: no cover - doc regeneration
    print(render_table())
