"""Per-tenant service-level objectives over query latency.

A multi-tenant service needs to answer "is tenant A inside its latency
SLO right now" without grepping traces. This module keeps, per tenant:

* a **fixed-bucket latency histogram**
  (``cylon_slo_query_latency_ms{tenant=}``, buckets spanning 1 ms to
  one minute) fed with every completed query's wall time — p50/p95/p99
  are estimated by linear interpolation within the bucket
  (``metrics.Histogram.quantile``);
* the **declared objective**: ``CYLON_SLO_P95_MS`` is the p95 latency
  the service promises, ``CYLON_SLO_TARGET`` (default 0.99) the
  fraction of queries that must meet it. A query *violates* when it
  errors or exceeds the objective latency;
* the **error budget**: with target t, the budget is the allowed
  ``1 - t`` violation share; ``error_budget_remaining`` is the
  fraction of that allowance still unspent
  (``1 - violations / (count * (1 - t))``, clamped to [0, 1]).

Exported state (updated on every observation):

* ``cylon_slo_latency_p95_ms{tenant=}`` gauge — the live p95 estimate;
* ``cylon_slo_error_budget_remaining{tenant=}`` gauge — 1.0 = pristine,
  0.0 = budget exhausted (only while an objective is declared);
* **burn events** — each violation under a declared objective lands in
  the flight recorder's admission ring (``action: "slo_burn"``, with
  tenant, latency, objective and remaining budget), so an SLO breach
  leaves the same forensic trail as an admission shed and rides every
  crash dump.

Fed by the query log's root hook (telemetry/querylog.py) — one
observation per completed query, tenant read from the root span's
stamped attrs (``default`` outside the service). ``state()`` is the
observability endpoint's ``/slo`` payload. Counts are process-lifetime
(reset() for tests); the budget is an all-time ratio, not a sliding
window — honest for a v1, documented in docs/service.md.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

from . import flight as _flight
from . import knobs as _knobs
from . import metrics as _metrics

# query-latency buckets in ms: one kernel dispatch to a minute-long
# analytical query (finer than DEFAULT_BUCKETS_MS in the 100ms..10s
# band where interactive SLOs live)
SLO_BUCKETS_MS = (1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
                  1000.0, 2500.0, 5000.0, 10000.0, 30000.0, 60000.0)

DEFAULT_TARGET = _knobs.default("CYLON_SLO_TARGET")


def objective_ms() -> Optional[float]:
    """The declared p95 latency objective, or None (no SLO)."""
    return _knobs.get("CYLON_SLO_P95_MS")


def target() -> float:
    """The declared SLO target (fraction of queries that must meet
    the objective), clamped to [0, 1]."""
    return min(float(_knobs.get("CYLON_SLO_TARGET")), 1.0)


def error_budget_remaining(count: int, violations: int,
                           t: Optional[float] = None) -> float:
    """Pure budget math: the unspent fraction of the allowed
    ``1 - target`` violation share, clamped to [0, 1]. A target of
    1.0 allows zero violations — the budget is binary."""
    t = target() if t is None else t
    if count <= 0:
        return 1.0
    allowed = count * (1.0 - t)
    if allowed <= 0.0:
        return 1.0 if violations == 0 else 0.0
    return max(0.0, 1.0 - violations / allowed)


_lock = threading.RLock()
# tenant -> {"count", "violations", "burns"} (process-lifetime)
_tenants: Dict[str, dict] = {}


def _hist(tenant: str) -> _metrics.Histogram:
    return _metrics.REGISTRY.histogram(
        "cylon_slo_query_latency_ms", {"tenant": tenant},
        buckets=SLO_BUCKETS_MS)


def observe(tenant: str, latency_ms: float, error: bool = False
            ) -> None:
    """Record one completed query for ``tenant``: feed its latency
    histogram, update the p95/budget gauges, and record a burn event
    when the query violates a declared objective."""
    h = _hist(tenant)
    h.observe(float(latency_ms))
    obj = objective_ms()
    violated = obj is not None and (error or latency_ms > obj)
    with _lock:
        st = _tenants.setdefault(
            tenant, {"count": 0, "violations": 0, "burns": 0})
        st["count"] += 1
        if violated:
            st["violations"] += 1
            st["burns"] += 1
        count, violations = st["count"], st["violations"]
    p95 = h.quantile(0.95)
    if p95 is not None:
        _metrics.REGISTRY.gauge("cylon_slo_latency_p95_ms",
                                {"tenant": tenant}).set(round(p95, 3))
    if obj is None:
        return
    remaining = error_budget_remaining(count, violations)
    _metrics.REGISTRY.gauge(
        "cylon_slo_error_budget_remaining",
        {"tenant": tenant}).set(round(remaining, 4))
    if violated:
        # the burn event rides the flight admission ring (and so every
        # crash dump): an SLO breach leaves the same forensic trail as
        # an admission shed
        _flight.record_admission({
            "action": "slo_burn", "tenant": tenant,
            "latency_ms": round(float(latency_ms), 3),
            "objective_p95_ms": obj, "error": bool(error),
            "budget_remaining": round(remaining, 4)})


def state() -> Dict[str, dict]:
    """Per-tenant SLO state — the ``/slo`` payload: latency quantile
    estimates, declared objective, violation counts and remaining
    error budget (budget fields None while no objective is
    declared)."""
    obj = objective_ms()
    t = target()
    with _lock:
        snap = {tenant: dict(st) for tenant, st in _tenants.items()}
    out: Dict[str, dict] = {}
    for tenant, st in snap.items():
        h = _hist(tenant)
        doc = {
            "count": st["count"],
            "p50_ms": h.quantile(0.50),
            "p95_ms": h.quantile(0.95),
            "p99_ms": h.quantile(0.99),
            "objective_p95_ms": obj,
            "target": t if obj is not None else None,
            "violations": st["violations"] if obj is not None else None,
            "burn_events": st["burns"] if obj is not None else None,
            "error_budget_remaining": error_budget_remaining(
                st["count"], st["violations"]) if obj is not None
            else None,
            "ok": (h.quantile(0.95) or 0.0) <= obj
            if obj is not None else None,
        }
        out[tenant] = doc
    return out


def reset() -> None:
    """Clear per-tenant counts (test isolation). Registry histograms
    and gauges are zeroed by ``telemetry.reset_metrics()``."""
    with _lock:
        _tenants.clear()
