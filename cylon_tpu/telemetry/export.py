"""Exporters: JSONL span sink + Prometheus text metrics dump.

Two wire formats, both deliberately boring:

* **JSONL trace** — one JSON object per COMPLETED span, written as
  spans close (innermost first, so a child's line precedes its
  parent's). ``parent_id`` links the tree; ``span_id`` 0 is "no
  parent". Every line is independently parseable — a crashed process
  leaves a valid prefix, and ``jq``/pandas ingest it directly.
* **Prometheus text exposition** — the v0.0.4 text format rendered
  from a MetricsRegistry: counters/gauges as single samples,
  histograms as cumulative ``_bucket{le=...}`` + ``_sum`` + ``_count``.
  Scrape-ready, and diff-able across BENCH rounds.

The ``jax.profiler.TraceAnnotation`` carrier is NOT here — it lives
inside spans.span itself, so Perfetto labels keep working with no
exporter configured at all.
"""
from __future__ import annotations

import json
import os
import threading
from typing import IO, List, Optional, Union

from . import knobs as _knobs
from . import spans as _spans
from .metrics import REGISTRY, MetricsRegistry, format_series

# rotated generations kept beside a size-bounded JSONL file
# (path.1 = most recent full generation .. path.KEEP = oldest)
SPAN_LOG_KEEP = 3


def span_to_json(span) -> str:
    """One flat JSONL record for a completed span."""
    return json.dumps(span.to_dict(), default=str, sort_keys=True)


def _span_log_max_bytes() -> int:
    return _knobs.get("CYLON_SPAN_LOG_MAX_BYTES")


def rotate_file(path: str, keep: int = SPAN_LOG_KEEP) -> None:
    """Shift ``path`` into numbered generations (``path.1`` newest,
    ``path.keep`` oldest — the PR-6 crash-dump discipline applied to a
    single growing file): the oldest generation is dropped, each
    survivor shifts up, ``path`` itself is renamed to ``path.1``. The
    caller reopens ``path`` fresh. Never raises — rotation is
    best-effort bookkeeping around the real write path."""
    try:
        for i in range(keep, 0, -1):
            src = path if i == 1 else f"{path}.{i - 1}"
            dst = f"{path}.{i}"
            if os.path.exists(src):
                os.replace(src, dst)
    except OSError:  # pragma: no cover - raced deletion/permissions
        _spans.logger.exception("jsonl rotation failed for %s", path)


class RotatingJsonlWriter:
    """Line-oriented writer over a path with size-based rotation: once
    the current file reaches ``max_bytes`` (default: the live
    ``CYLON_SPAN_LOG_MAX_BYTES`` knob; 0 = unbounded), it rotates
    through ``keep`` numbered generations and starts fresh — a
    long-lived service can stream spans or query digests forever
    without growing a file without bound. Thread-safe: spans close on
    whatever thread ran the query (submitters, the service worker),
    and rotation is a multi-step close/rename/reopen that must never
    interleave another thread's write against the just-closed handle —
    every write runs under the writer's RLock."""

    def __init__(self, path: str, max_bytes: Optional[int] = None,
                 keep: int = SPAN_LOG_KEEP):
        self.path = path
        self._max_bytes = max_bytes
        self.keep = keep
        self._lock = threading.RLock()
        self._file: Optional[IO] = None
        self.lines_written = 0
        self.rotations = 0

    def max_bytes(self) -> int:
        return self._max_bytes if self._max_bytes is not None \
            else _span_log_max_bytes()

    def open(self) -> "RotatingJsonlWriter":
        with self._lock:
            self._file = open(self.path, "w", encoding="utf-8")
        return self

    def write_line(self, line: str, flush: bool = False) -> None:
        with self._lock:
            self._file.write(line + "\n")
            self.lines_written += 1
            cap = self.max_bytes()
            if cap and self._file.tell() >= cap:
                self._file.close()
                rotate_file(self.path, self.keep)
                self._file = open(self.path, "w", encoding="utf-8")
                self.rotations += 1
            elif flush:
                self._file.flush()

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


class JsonlSpanSink:
    """Context manager that streams every completed span to a JSONL
    file (path or open file object) while active::

        with telemetry.JsonlSpanSink("/tmp/trace.jsonl"):
            pipe.execute()

    Nesting multiple sinks is fine — each sees every span. A PATH
    target is size-bounded: past ``max_bytes`` (default: the live
    ``CYLON_SPAN_LOG_MAX_BYTES`` knob, 0 = unbounded) the file rotates
    through keep-N numbered generations (``rotate_file``), so a
    long-lived service tracing at any sample rate cannot grow one
    file without limit. File-object targets are the caller's to
    bound."""

    def __init__(self, target: Union[str, IO],
                 max_bytes: Optional[int] = None,
                 keep: int = SPAN_LOG_KEEP):
        self._target = target
        self._file: Optional[IO] = None
        self._writer: Optional[RotatingJsonlWriter] = None
        self._max_bytes = max_bytes
        self._keep = keep
        self.spans_written = 0
        # registration handle: accessing self._write builds a FRESH
        # bound-method object on every attribute access, so the
        # identity-based remove_sink must be handed the exact object
        # add_sink saw
        self._registered = self._write

    @property
    def rotations(self) -> int:
        return self._writer.rotations if self._writer is not None else 0

    def _write(self, span) -> None:
        if self._writer is not None:
            self._writer.write_line(span_to_json(span))
        else:
            self._file.write(span_to_json(span) + "\n")
        self.spans_written += 1

    def __enter__(self) -> "JsonlSpanSink":
        if isinstance(self._target, str):
            self._writer = RotatingJsonlWriter(
                self._target, max_bytes=self._max_bytes,
                keep=self._keep).open()
        else:
            self._file = self._target
        _spans.add_sink(self._registered)
        return self

    def __exit__(self, *exc):
        _spans.remove_sink(self._registered)
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        else:
            self._file.flush()
        self._file = None
        return False


def _fmt(v) -> str:
    # prometheus floats: integers render bare, floats keep precision
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def prometheus_text(registry: Optional[MetricsRegistry] = None) -> str:
    """Render a registry in the Prometheus text exposition format.
    Series sort by (name, labels); one ``# TYPE`` line per metric
    name."""
    reg = registry or REGISTRY
    lines: List[str] = []
    typed = set()
    for name, labels, m in reg.series():
        if name not in typed:
            lines.append(f"# TYPE {name} {m.kind}")
            typed.add(name)
        if m.kind in ("counter", "gauge"):
            lines.append(f"{format_series(name, labels)} {_fmt(m.value)}")
            continue
        # histogram: cumulative buckets + sum + count, read as one
        # locked group so _count always agrees with the +Inf bucket
        st = m.stats()
        cum = 0
        for bound, c in zip(m.buckets, st["counts"]):
            cum += c
            lbl = labels + (("le", _fmt(bound)),)
            lines.append(f"{format_series(name + '_bucket', lbl)} {cum}")
        cum += st["counts"][-1]
        lbl = labels + (("le", "+Inf"),)
        lines.append(f"{format_series(name + '_bucket', lbl)} {cum}")
        lines.append(f"{format_series(name + '_sum', labels)} "
                     f"{_fmt(st['sum'])}")
        lines.append(f"{format_series(name + '_count', labels)} "
                     f"{st['count']}")
    return "\n".join(lines) + "\n"
