"""Exporters: JSONL span sink + Prometheus text metrics dump.

Two wire formats, both deliberately boring:

* **JSONL trace** — one JSON object per COMPLETED span, written as
  spans close (innermost first, so a child's line precedes its
  parent's). ``parent_id`` links the tree; ``span_id`` 0 is "no
  parent". Every line is independently parseable — a crashed process
  leaves a valid prefix, and ``jq``/pandas ingest it directly.
* **Prometheus text exposition** — the v0.0.4 text format rendered
  from a MetricsRegistry: counters/gauges as single samples,
  histograms as cumulative ``_bucket{le=...}`` + ``_sum`` + ``_count``.
  Scrape-ready, and diff-able across BENCH rounds.

The ``jax.profiler.TraceAnnotation`` carrier is NOT here — it lives
inside spans.span itself, so Perfetto labels keep working with no
exporter configured at all.
"""
from __future__ import annotations

import json
from typing import IO, List, Optional, Union

from . import spans as _spans
from .metrics import REGISTRY, MetricsRegistry, format_series


def span_to_json(span) -> str:
    """One flat JSONL record for a completed span."""
    return json.dumps(span.to_dict(), default=str, sort_keys=True)


class JsonlSpanSink:
    """Context manager that streams every completed span to a JSONL
    file (path or open file object) while active::

        with telemetry.JsonlSpanSink("/tmp/trace.jsonl"):
            pipe.execute()

    Nesting multiple sinks is fine — each sees every span."""

    def __init__(self, target: Union[str, IO]):
        self._target = target
        self._file: Optional[IO] = None
        self._owns_file = False
        self.spans_written = 0
        # registration handle: accessing self._write builds a FRESH
        # bound-method object on every attribute access, so the
        # identity-based remove_sink must be handed the exact object
        # add_sink saw
        self._registered = self._write

    def _write(self, span) -> None:
        self._file.write(span_to_json(span) + "\n")
        self.spans_written += 1

    def __enter__(self) -> "JsonlSpanSink":
        if isinstance(self._target, str):
            self._file = open(self._target, "w", encoding="utf-8")
            self._owns_file = True
        else:
            self._file = self._target
        _spans.add_sink(self._registered)
        return self

    def __exit__(self, *exc):
        _spans.remove_sink(self._registered)
        if self._owns_file:
            self._file.close()
        else:
            self._file.flush()
        self._file = None
        return False


def _fmt(v) -> str:
    # prometheus floats: integers render bare, floats keep precision
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def prometheus_text(registry: Optional[MetricsRegistry] = None) -> str:
    """Render a registry in the Prometheus text exposition format.
    Series sort by (name, labels); one ``# TYPE`` line per metric
    name."""
    reg = registry or REGISTRY
    lines: List[str] = []
    typed = set()
    for name, labels, m in reg.series():
        if name not in typed:
            lines.append(f"# TYPE {name} {m.kind}")
            typed.add(name)
        if m.kind in ("counter", "gauge"):
            lines.append(f"{format_series(name, labels)} {_fmt(m.value)}")
            continue
        # histogram: cumulative buckets + sum + count, read as one
        # locked group so _count always agrees with the +Inf bucket
        st = m.stats()
        cum = 0
        for bound, c in zip(m.buckets, st["counts"]):
            cum += c
            lbl = labels + (("le", _fmt(bound)),)
            lines.append(f"{format_series(name + '_bucket', lbl)} {cum}")
        cum += st["counts"][-1]
        lbl = labels + (("le", "+Inf"),)
        lines.append(f"{format_series(name + '_bucket', lbl)} {cum}")
        lines.append(f"{format_series(name + '_sum', labels)} "
                     f"{_fmt(st['sum'])}")
        lines.append(f"{format_series(name + '_count', labels)} "
                     f"{st['count']}")
    return "\n".join(lines) + "\n"
