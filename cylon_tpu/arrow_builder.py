"""Raw-buffer table assembly for bindings — arrow_builder parity.

Reference: cpp/src/cylon/arrow/arrow_builder.{hpp,cpp}:31-161 —
``BeginTable / AddColumn(type, counts, buffer addresses) / FinishTable``
assembles a *registered* table from raw Arrow-layout buffers so a
foreign runtime (the reference's JNI layer) can hand over memory by
address instead of objects. The TPU-native version reads the caller's
buffers once on the host (ctypes address + size → numpy view), converts
to device columns (fixed-width arrays, varbytes for STRING/BINARY via
the Arrow offsets+data layout), and registers the finished Table in the
same string-id registry the other bindings-facing ops use
(cylon_tpu.table_api).

Buffer conventions (Arrow layout):
* validity: LSB-ordered bitmap, 1 = valid; address 0 / size 0 = no nulls
* data: for fixed-width types, value_count items of the type's width;
  for STRING/BINARY this is the concatenated byte payload
* offsets (varlen only): int32[value_count + 1] byte offsets
"""
from __future__ import annotations

import ctypes
import threading
from typing import Dict, List, Tuple

import numpy as np

from . import table_api
from .data.column import Column
from .data.strings import VarBytes
from .dtypes import Type
from .status import Code, CylonError, Status

_staged: Dict[str, List[Column]] = {}
_lock = threading.Lock()

_FIXED_NP = {
    Type.BOOL: np.uint8,  # Arrow bools arrive as a bitmap; see below
    Type.UINT8: np.uint8, Type.INT8: np.int8,
    Type.UINT16: np.uint16, Type.INT16: np.int16,
    Type.UINT32: np.uint32, Type.INT32: np.int32,
    Type.UINT64: np.uint64, Type.INT64: np.int64,
    Type.HALF_FLOAT: np.float16, Type.FLOAT: np.float32,
    Type.DOUBLE: np.float64,
    Type.DATE32: np.int32, Type.DATE64: np.int64,
    Type.TIMESTAMP: np.int64, Type.TIME32: np.int32,
    Type.TIME64: np.int64,
}


def _read_buffer(address: int, size: int) -> bytes:
    if address == 0 or size == 0:
        return b""
    return ctypes.string_at(ctypes.c_void_p(address), int(size))


def _unpack_bitmap(raw: bytes, n: int) -> np.ndarray:
    bits = np.unpackbits(np.frombuffer(raw, np.uint8), bitorder="little")
    return bits[:n].astype(bool)


def begin_table(table_id: str) -> Status:
    """Reference: BeginTable (arrow_builder.cpp:31-38)."""
    with _lock:
        if table_id in _staged:
            raise CylonError(Code.AlreadyExists,
                             f"table {table_id!r} already being built")
        _staged[table_id] = []
    return Status.OK()


def add_column(table_id: str, col_name: str, type_code: int,
               value_count: int, null_count: int,
               validity_address: int, validity_size: int,
               data_address: int, data_size: int,
               offset_address: int = 0, offset_size: int = 0) -> Status:
    """Reference: AddColumn (arrow_builder.cpp:40-118) — the varlen
    overload is selected by passing offset buffers."""
    with _lock:
        if table_id not in _staged:
            raise CylonError(Code.KeyError,
                             f"BeginTable({table_id!r}) was never called")
    t = Type(type_code)
    validity = None
    if null_count and validity_size:
        validity = _unpack_bitmap(
            _read_buffer(validity_address, validity_size), value_count)

    if t in (Type.STRING, Type.BINARY):
        if not offset_size:
            raise CylonError(Code.Invalid,
                             f"{t.name} column needs offset buffers")
        offsets = np.frombuffer(
            _read_buffer(offset_address, offset_size),
            np.int32)[: value_count + 1]
        data = _read_buffer(data_address, data_size)
        vb = VarBytes.from_arrow_buffers(offsets, data)
        col = Column.from_varbytes(
            vb, None if validity is None else np.asarray(validity),
            col_name)
    elif t == Type.BOOL:
        vals = _unpack_bitmap(_read_buffer(data_address, data_size),
                              value_count)
        col = Column.from_numpy(vals, col_name, validity)
    else:
        np_t = _FIXED_NP.get(t)
        if np_t is None:
            raise CylonError(Code.NotImplemented,
                             f"arrow_builder: unsupported type {t.name}")
        vals = np.frombuffer(_read_buffer(data_address, data_size),
                             np_t)[:value_count].copy()
        col = Column.from_numpy(vals, col_name, validity)
    with _lock:
        _staged[table_id].append(col)
    return Status.OK()


def finish_table(table_id: str, ctx=None) -> Status:
    """Reference: FinishTable (arrow_builder.cpp:120-161) — the built
    table becomes visible through the table_api registry."""
    from .context import CylonContext
    from .data.table import Table

    with _lock:
        cols = _staged.pop(table_id, None)
    if cols is None:
        raise CylonError(Code.KeyError,
                         f"BeginTable({table_id!r}) was never called")
    table_api.put_table(table_id,
                        Table(cols, ctx or CylonContext.Init()))
    return Status.OK()
