"""Distributed relational operators: shuffle-composed, per-shard kernels.

The reference composes every distributed op as *local partition + all-to-all
+ local op* (reference: docs/docs/arch.md:48-52; DistributedJoin
table.cpp:656-696; set ops table.cpp:948-992; GroupBy
groupby/groupby.cpp:96-139). The same composition here, but each stage is a
compiled SPMD program over the mesh instead of per-rank C++:

  1. key prep runs on the GLOBAL sharded arrays (elementwise → no comms):
     dtype promotion / dictionary unification, order-preserving key bits,
     murmur-style partition targets;
  2. the shuffle is the two-phase count+exchange from parallel/shuffle.py;
  3. the local stage runs per shard inside `shard_map` — matching keys are
     co-located after the hash shuffle, so per-shard dense ranks + the same
     vectorized kernels as the local path produce the distributed result.

Data-dependent output sizes follow the framework-wide eager discipline:
a count kernel returns per-shard totals, the host picks a pow2 capacity
(bounding recompilation), a materialize kernel fills static-shape outputs
whose padding rows carry emit=False. Results stay sharded; nothing is
gathered to the host.
"""
from __future__ import annotations

from functools import lru_cache
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

try:  # jax>=0.8
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from .. import dtypes
from ..context import CylonContext
from ..data import table as table_mod
from ..data.column import Column, unify_dictionaries
from ..data.table import Table
from ..ops import groupby as _groupby
from ..ops import hash as _hash
from ..ops import join as _join
from ..ops import order as _order
from ..ops import setops as _setops
from ..status import Code, CylonError
from ..telemetry import phase as _phase
from . import shard
from ..util import capacity as _capacity
from .shuffle import exchange, replicated_gather


# ---------------------------------------------------------------------------
# payload plumbing
# ---------------------------------------------------------------------------

def _table_payload(t: Table) -> dict:
    p = {}
    for i, c in enumerate(t._columns):
        p[f"d{i}"] = c.data
        p[f"v{i}"] = c.valid_mask()
    return p


def _payload_tuples(p: dict, ncols: int) -> Tuple[Tuple, Tuple]:
    return (tuple(p[f"d{i}"] for i in range(ncols)),
            tuple(p[f"v{i}"] for i in range(ncols)))


def _rebuild_columns(dat: Sequence, val: Sequence, src: Table,
                     names: Sequence[str]) -> List[Column]:
    cols = []
    for d, v, c, name in zip(dat, val, src._columns, names):
        cols.append(Column(d, c.dtype, v, c.dictionary, name))
    return cols


def _all_valid(cols: Sequence[Column]) -> jnp.ndarray:
    v = cols[0].valid_mask()
    for c in cols[1:]:
        v = v & c.valid_mask()
    return v


# ---------------------------------------------------------------------------
# per-shard kernels (cached per mesh/static-shape signature)
# ---------------------------------------------------------------------------

@lru_cache(maxsize=None)
def _join_plan_fn(mesh, join_type: _join.JoinType):
    """Per-shard join plan: ONE fused sort per shard (join_plan_keys);
    match arrays stay sharded on device for the materialize phase, the
    [world, 2] count matrix is all_gather-REPLICATED so every controller
    process can fetch it (multi-host safe)."""
    axis = mesh.axis_names[0]
    spec = P(axis)

    def kernel(lbits, lkv, lemit, rbits, rkv, remit):
        counts2, lo, m, bperm, un_mask = _join.join_plan_keys(
            lbits, lkv, lemit, rbits, rkv, remit, join_type)
        world = mesh.devices.size
        return (replicated_gather(counts2, axis, world),
                lo, m, bperm, un_mask)

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * 6,
                             out_specs=(P(), spec, spec, spec, spec)))


_gather_side = _join.gather_columns


@lru_cache(maxsize=None)
def _join_mat_fn(mesh, join_type: _join.JoinType, cap_p: int, cap_u: int):
    spec = P(mesh.axis_names[0])

    def kernel(lo, m, bperm, un_mask, aemit, ldat, lval, rdat, rval):
        lidx, ridx, emit = _join.join_materialize_gids(
            lo, m, bperm, un_mask, aemit, join_type, cap_p, cap_u)
        lod, lov = _gather_side(ldat, lval, lidx)
        rod, rov = _gather_side(rdat, rval, ridx)
        return lod, lov, rod, rov, emit

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * 9,
                             out_specs=spec))


@lru_cache(maxsize=None)
def _setop_count_fn(mesh):
    spec = P(mesh.axis_names[0])

    def kernel(lbits, lemit, rbits, remit):
        gl, gr = _order.dense_ranks_two(list(lbits), list(rbits))
        c = _setops.setop_counts(gl, gr, lemit, remit)
        counts = jnp.stack([c["n_union"], c["n_subtract"],
                            c["n_intersect"]]).astype(jnp.int32)
        return replicated_gather(counts, mesh.axis_names[0],
                                 mesh.devices.size)

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * 4,
                             out_specs=P()))


@lru_cache(maxsize=None)
def _setop_mat_fn(mesh, op: _setops.SetOp, cap: int):
    spec = P(mesh.axis_names[0])

    def kernel(lbits, lemit, rbits, remit, ldat, lval, rdat, rval):
        gl, gr = _order.dense_ranks_two(list(lbits), list(rbits))
        idx = _setops.setop_indices(gl, gr, lemit, remit, op, cap)
        emit = idx >= 0
        # indices address the concatenated [left; right] per-shard table
        dat = tuple(jnp.concatenate([a, b]) for a, b in zip(ldat, rdat))
        val = tuple(jnp.concatenate([a, b]) for a, b in zip(lval, rval))
        od, ov = _gather_side(dat, val, idx)
        return od, ov, emit

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * 8,
                             out_specs=spec))


@lru_cache(maxsize=None)
def _groupby_fn(mesh, ops: Tuple[_groupby.AggregationOp, ...]):
    spec = P(mesh.axis_names[0])

    def kernel(kbits, kdat, kval, emit, vdat, vval):
        n = emit.shape[0]
        keys = list(kbits) + [v.astype(jnp.uint8) for v in kval]
        gid, _ = _order.dense_ranks(keys)
        rep, gvalid, results = _groupby.segment_aggregate(
            gid, vdat, vval, emit, n, ops)
        safe = jnp.minimum(rep, n - 1)
        kout = tuple(jnp.take(d, safe, axis=0) for d in kdat)
        kvout = tuple(jnp.take(v, safe) & gvalid for v in kval)
        agg = tuple((arr, av & gvalid) for arr, av in results)
        return kout, kvout, gvalid, agg

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * 6,
                             out_specs=spec))


# ---------------------------------------------------------------------------
# shuffle / partition public API
# ---------------------------------------------------------------------------

def shuffle(table: Table, hash_columns: Sequence) -> Table:
    """Repartition rows by key hash (reference: cylon::Shuffle,
    table.cpp:162-236). Tables already hash-placed on the same keys
    (a previous shuffle, or shard.distribute_by_key host ingest) pass
    through without an exchange."""
    ctx = table._ctx
    world = ctx.get_world_size()
    if world == 1:
        return table
    t = shard.distribute(table, ctx)
    idxs = [t._col_index(c) for c in hash_columns]
    sig = shard.partition_signature([t._columns[i] for i in idxs], idxs,
                                    world)
    if sig is not None and t._hash_partitioned == sig:
        return t
    targets = shard.pin(_hash.partition_targets(
        [t._columns[i] for i in idxs], world), ctx)
    emit = shard.pin(t.emit_mask(), ctx)
    payload = {k: shard.pin(v, ctx) for k, v in _table_payload(t).items()}
    out, new_emit, _cap = exchange(payload, targets, emit, ctx)
    dat, val = _payload_tuples(out, t.column_count)
    cols = _rebuild_columns(dat, val, t, t.column_names)
    result = Table(cols, ctx, new_emit)
    result._hash_partitioned = sig
    # reference parity: Shuffle frees non-retained inputs (table.cpp:207)
    table._free_if_unretained()
    return result


def hash_partition(table: Table, hash_columns: Sequence,
                   num_partitions: int) -> dict:
    """Split into a {partition_id: Table} map (reference: HashPartition,
    table.hpp:354, table.cpp:102-160 — C++ kernels there, the native host
    partitioner here: the result is host-resident per-partition tables,
    so one ct_row_hash + stable bucket order replaces num_partitions
    device filter passes)."""
    from ..data.column import Column

    idxs = [table._col_index(c) for c in hash_columns]
    t = table.compact()
    host, valids, counts, order, offs = shard.host_partition_arrays(
        t, idxs, num_partitions)
    out = {}
    for p in range(num_partitions):
        seg = order[offs[p]:offs[p + 1]]
        cols = []
        for ci, c in enumerate(t._columns):
            v = None if valids[ci] is None else jnp.asarray(valids[ci][seg])
            cols.append(Column(jnp.asarray(host[ci][seg]), c.dtype, v,
                               c.dictionary, c.name))
        out[p] = Table(cols, t._ctx)
    return out


def repartition(table: Table, ctx: CylonContext) -> Table:
    """Round-robin balance rows across shards (no key)."""
    t = shard.distribute(table, ctx)
    world = ctx.get_world_size()
    n = t.capacity
    targets = shard.pin(
        jnp.arange(n, dtype=jnp.int32) % world, ctx)
    payload = {k: shard.pin(v, ctx) for k, v in _table_payload(t).items()}
    out, new_emit, _ = exchange(payload, targets, shard.pin(t.emit_mask(), ctx),
                                ctx)
    dat, val = _payload_tuples(out, t.column_count)
    return Table(_rebuild_columns(dat, val, t, t.column_names), ctx, new_emit)


# ---------------------------------------------------------------------------
# distributed join (reference: DistributedJoin, table.cpp:656-696)
# ---------------------------------------------------------------------------

def distributed_join(left: Table, right: Table, config: _join.JoinConfig
                     ) -> Table:
    ctx = left._ctx
    world = ctx.get_world_size()
    if world == 1:
        # reference parity: world==1 short-circuits to the local join
        # (table.cpp:662-669)
        return table_mod.join(left, right, config)

    left_d = shard.distribute(left, ctx)
    right_d = shard.distribute(right, ctx)
    lidx, ridx = config.left_column_idx, config.right_column_idx
    lcols, rcols = table_mod.align_key_columns(left_d, right_d, lidx, ridx)

    seq = ctx.get_next_sequence()
    shuffled = []
    with _phase("distributed_join.shuffle", seq):
        for t, kcols, kidx in ((left_d, lcols, lidx), (right_d, rcols, ridx)):
            bits = _order.sort_keys(kcols)
            kv = _all_valid(kcols)
            sig = shard.partition_signature(kcols, kidx, world)
            if sig is not None and t._hash_partitioned == sig:
                # co-partitioned (prior shuffle or distribute_by_key host
                # ingest): rows are already hash-placed — skip the exchange
                dat = tuple(shard.pin(c.data, ctx) for c in t._columns)
                val = tuple(shard.pin(c.valid_mask(), ctx)
                            for c in t._columns)
                shuffled.append((tuple(shard.pin(b, ctx) for b in bits),
                                 shard.pin(kv, ctx),
                                 shard.pin(t.emit_mask(), ctx), dat, val))
                continue
            targets = shard.pin(_hash.partition_targets(kcols, world), ctx)
            payload = _table_payload(t)
            for j, b in enumerate(bits):
                payload[f"k{j}"] = b
            payload["kv"] = kv
            payload = {k: shard.pin(v, ctx) for k, v in payload.items()}
            out, emit, _cap = exchange(payload, targets,
                                       shard.pin(t.emit_mask(), ctx), ctx)
            kbits = tuple(out[f"k{j}"] for j in range(len(bits)))
            dat, val = _payload_tuples(out, t.column_count)
            shuffled.append((kbits, out["kv"], emit, dat, val))

    (lkb, lkv, lemit, ldat, lval), (rkb, rkv, remit, rdat, rval) = shuffled

    jt = config.type
    with _phase("distributed_join.plan", seq):
        counts2, lo, m, bperm, un_mask = _join_plan_fn(ctx.mesh, jt)(
            lkb, lkv, lemit, rkb, rkv, remit)
        aemit = remit if jt == _join.JoinType.RIGHT else lemit
        # counts2 is the replicated [world, 2] matrix of per-shard
        # [n_primary, n_unmatched_b]; capacity = worst shard (all shards
        # share one program)
        counts = np.asarray(jax.device_get(counts2)).reshape(world, 2)
    cap_p = _capacity(int(counts[:, 0].max()))
    cap_u = _capacity(int(counts[:, 1].max())) \
        if jt == _join.JoinType.FULL_OUTER else 0

    with _phase("distributed_join.materialize", seq):
        lod, lov, rod, rov, emit = _join_mat_fn(ctx.mesh, jt, cap_p, cap_u)(
            lo, m, bperm, un_mask, aemit, ldat, lval, rdat, rval)

    nl = left_d.column_count
    cols = _rebuild_columns(lod, lov, left_d,
                            [f"lt-{i}" for i in range(nl)])
    cols += _rebuild_columns(rod, rov, right_d,
                             [f"rt-{nl + j}" for j in range(right_d.column_count)])
    result = Table(cols, ctx, emit)
    left._free_if_unretained()
    right._free_if_unretained()
    return result


# ---------------------------------------------------------------------------
# streaming / overlapped ring join (reference: ArrowJoin, arrow_join.hpp:
# 50-198 — the streaming alternative to the barrier shuffle: two
# ArrowAllToAlls drained incrementally while local joins run).
#
# TPU-native form: the BUILD side rotates around the mesh ring via
# `lax.ppermute` while every shard joins its RESIDENT probe shard against
# the visiting block — XLA's async collective-permute overlaps the next
# block's transfer with the current block's join. The probe side is never
# repartitioned at all, so total bytes on the ring ≈ size(build), vs
# size(probe+build) through the all-to-all — the win when the build side
# is small or the probe side is large and already resident.
# ---------------------------------------------------------------------------


def _varying(axis, tree):
    """Mark a pytree as mesh-varying so fori_loop carries type-match the
    ppermute/per-shard values produced inside the loop body."""
    pc = getattr(jax.lax, "pcast", None)
    if pc is not None:
        return jax.tree.map(lambda x: jax.lax.pcast(x, axis, to="varying"),
                            tree)
    return jax.tree.map(lambda x: jax.lax.pvary(x, (axis,)), tree)  # pragma: no cover


@lru_cache(maxsize=None)
def _ring_count_fn(mesh, emit_unmatched_a: bool, nkeys: int):
    axis = mesh.axis_names[0]
    world = mesh.devices.size
    spec = P(axis)
    perm = [(i, (i + 1) % world) for i in range(world)]

    def kernel(lbits, lkv, lemit, rbits, rkv, remit):
        def rot(t):
            return jax.tree.map(lambda x: jax.lax.ppermute(x, axis, perm), t)

        def step(k, carry):
            (rb, rkvc, remc), pairs, amatched = carry
            _, _, m, _, _ = _join.join_plan_keys(
                lbits, lkv, lemit, rb, rkvc, remc, _join.JoinType.INNER)
            pairs = pairs.at[k].set(m.sum(dtype=jnp.int32))
            amatched = amatched | (m > 0)
            return rot((rb, rkvc, remc)), pairs, amatched

        pairs0, amatched0 = _varying(axis, (
            jnp.zeros(world, jnp.int32), jnp.zeros(lemit.shape[0], bool)))
        _, pairs, amatched = jax.lax.fori_loop(
            0, world, step, ((rbits, rkv, remit), pairs0, amatched0))
        n_extra = (lemit & ~amatched).sum(dtype=jnp.int32) \
            if emit_unmatched_a else jnp.zeros((), jnp.int32)
        counts = jnp.concatenate([pairs, n_extra[None]])
        return replicated_gather(counts, axis, world)

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * 6,
                             out_specs=P()))


@lru_cache(maxsize=None)
def _ring_mat_fn(mesh, emit_unmatched_a: bool, cap_step: int, cap_extra: int,
                 nkeys: int):
    axis = mesh.axis_names[0]
    world = mesh.devices.size
    spec = P(axis)
    perm = [(i, (i + 1) % world) for i in range(world)]
    cap_total = world * cap_step + cap_extra

    def kernel(lbits, lkv, lemit, rbits, rkv, remit, adat, aval, bdat, bval):
        def rot(t):
            return jax.tree.map(lambda x: jax.lax.ppermute(x, axis, perm), t)

        def slab_like(x):
            return jnp.zeros((cap_total,) + x.shape[1:], x.dtype)

        slabs_a = tuple(slab_like(d) for d in adat)
        slabs_av = tuple(jnp.zeros(cap_total, bool) for _ in adat)
        slabs_b = tuple(slab_like(d) for d in bdat)
        slabs_bv = tuple(jnp.zeros(cap_total, bool) for _ in bdat)
        emit0 = jnp.zeros(cap_total, bool)
        slabs_a, slabs_av, slabs_b, slabs_bv, emit0 = _varying(
            axis, (slabs_a, slabs_av, slabs_b, slabs_bv, emit0))

        def step(k, carry):
            visit, slabs, amatched = carry
            rb, rkvc, remc, bdat_v, bval_v = visit
            sa, sav, sb, sbv, emit = slabs
            _, lo, m, bperm, _ = _join.join_plan_keys(
                lbits, lkv, lemit, rb, rkvc, remc, _join.JoinType.INNER)
            lidx, ridx, e = _join.join_materialize_gids(
                lo, m, bperm, jnp.zeros(remc.shape[0], bool), lemit,
                _join.JoinType.INNER, cap_step, 0)
            ad, av = _gather_side(adat, aval, lidx)
            bd, bv = _gather_side(bdat_v, bval_v, ridx)
            off = k * cap_step

            def put(slab, block):
                return jax.lax.dynamic_update_slice_in_dim(slab, block,
                                                           off, 0)

            slabs = (tuple(put(s, d) for s, d in zip(sa, ad)),
                     tuple(put(s, v) for s, v in zip(sav, av)),
                     tuple(put(s, d) for s, d in zip(sb, bd)),
                     tuple(put(s, v) for s, v in zip(sbv, bv)),
                     put(emit, e))
            amatched = amatched | (m > 0)
            return rot((rb, rkvc, remc, bdat_v, bval_v)), slabs, amatched

        visit0 = (rbits, rkv, remit, bdat, bval)
        amatched0 = _varying(axis, jnp.zeros(lemit.shape[0], bool))
        _, slabs, amatched = jax.lax.fori_loop(
            0, world, step,
            (visit0, (slabs_a, slabs_av, slabs_b, slabs_bv, emit0),
             amatched0))
        sa, sav, sb, sbv, emit = slabs

        if emit_unmatched_a:
            un = _join._masked_indices(lemit & ~amatched, cap_extra)
            ad, av = _gather_side(adat, aval, un)
            hole = jnp.full(cap_extra, -1, jnp.int32)
            bd, bv = _gather_side(bdat, bval, hole)
            off = world * cap_step

            def put(slab, block):
                return jax.lax.dynamic_update_slice_in_dim(slab, block,
                                                           off, 0)

            sa = tuple(put(s, d) for s, d in zip(sa, ad))
            sav = tuple(put(s, v) for s, v in zip(sav, av))
            sb = tuple(put(s, d) for s, d in zip(sb, bd))
            sbv = tuple(put(s, v) for s, v in zip(sbv, bv))
            emit = put(emit, un >= 0)
        return sa, sav, sb, sbv, emit

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * 10,
                             out_specs=spec))


def distributed_join_ring(left: Table, right: Table,
                          config: _join.JoinConfig) -> Table:
    """Streaming ring join (ArrowJoin analog). INNER/LEFT/RIGHT; the
    resident (probe) side is the left table (right for RIGHT joins) and
    the other side rotates. FULL_OUTER falls back to the shuffle path.

    Memory note: the per-shard output slab is world*cap_step + cap_extra
    rows where cap_step covers the worst (shard, step) block — heavy key
    skew inflates it; the shuffle path degrades more gracefully there.
    """
    ctx = left._ctx
    world = ctx.get_world_size()
    jt = config.type
    if world == 1 or jt == _join.JoinType.FULL_OUTER:
        return distributed_join(left, right, config)

    left_d = shard.distribute(left, ctx)
    right_d = shard.distribute(right, ctx)
    lidx, ridx = config.left_column_idx, config.right_column_idx
    lcols, rcols = table_mod.align_key_columns(left_d, right_d, lidx, ridx)

    if jt == _join.JoinType.RIGHT:
        a_t, a_cols, b_t, b_cols = right_d, rcols, left_d, lcols
    else:
        a_t, a_cols, b_t, b_cols = left_d, lcols, right_d, rcols
    emit_un_a = jt != _join.JoinType.INNER

    def prep(t, cols):
        bits = tuple(shard.pin(b, ctx) for b in _order.sort_keys(cols))
        kv = shard.pin(_all_valid(cols), ctx)
        emit = shard.pin(t.emit_mask(), ctx)
        dat = tuple(shard.pin(c.data, ctx) for c in t._columns)
        val = tuple(shard.pin(c.valid_mask(), ctx) for c in t._columns)
        return bits, kv, emit, dat, val

    abits, akv, aemit, adat, aval = prep(a_t, a_cols)
    bbits, bkv, bemit, bdat, bval = prep(b_t, b_cols)

    seq = ctx.get_next_sequence()
    with _phase("ring_join.count", seq):
        counts = np.asarray(jax.device_get(_ring_count_fn(
            ctx.mesh, emit_un_a, len(abits))(
            abits, akv, aemit, bbits, bkv, bemit)))
    pairs, extra = counts[:, :world], counts[:, world]
    cap_step = _capacity(int(pairs.max())) if pairs.size else 1
    cap_extra = _capacity(int(extra.max())) if emit_un_a else 0

    with _phase("ring_join.materialize", seq):
        sa, sav, sb, sbv, emit = _ring_mat_fn(
            ctx.mesh, emit_un_a, cap_step, cap_extra, len(abits))(
            abits, akv, aemit, bbits, bkv, bemit, adat, aval, bdat, bval)

    na = a_t.column_count
    a_cols_out = _rebuild_columns(sa, sav, a_t,
                                  [f"a-{i}" for i in range(na)])
    b_cols_out = _rebuild_columns(
        sb, sbv, b_t, [f"b-{j}" for j in range(b_t.column_count)])
    if jt == _join.JoinType.RIGHT:
        cols = b_cols_out + a_cols_out
        nl = b_t.column_count
    else:
        cols = a_cols_out + b_cols_out
        nl = na
    cols = [c.rename(f"lt-{i}" if i < nl else f"rt-{i}")
            for i, c in enumerate(cols)]
    result = Table(cols, ctx, emit)
    left._free_if_unretained()
    right._free_if_unretained()
    return result


# ---------------------------------------------------------------------------
# distributed set ops (reference: DistributedUnion/Subtract/Intersect,
# table.cpp:948-1010 — ShuffleTwoTables on ALL columns + local set op)
# ---------------------------------------------------------------------------

def distributed_set_op(left: Table, right: Table, op: _setops.SetOp) -> Table:
    ctx = left._ctx
    world = ctx.get_world_size()
    if world == 1:
        return table_mod.set_op(left, right, op)
    if left.column_count != right.column_count:
        raise CylonError(Code.Invalid, "set ops need equal schemas")

    left_d = shard.distribute(left, ctx)
    right_d = shard.distribute(right, ctx)
    all_idx = list(range(left_d.column_count))
    lcols, rcols = table_mod.align_key_columns(left_d, right_d, all_idx, all_idx)

    has_validity = [a.validity is not None or b.validity is not None
                    for a, b in zip(lcols, rcols)]

    seq = ctx.get_next_sequence()
    shuffled = []
    with _phase("distributed_set_op.shuffle", seq):
        for cols in (lcols, rcols):
            t_emit = (left_d if cols is lcols else right_d).emit_mask()
            targets = shard.pin(_hash.partition_targets(cols, world), ctx)
            payload = {}
            nbits = 0
            for ci, c in enumerate(cols):
                payload[f"d{ci}"] = c.data
                payload[f"v{ci}"] = c.valid_mask()
                payload[f"k{nbits}"] = _order.sort_keys([c])[0]
                nbits += 1
                if has_validity[ci]:
                    # validity participates in the row key (nulls compare
                    # equal, matching the reference's set-distinct semantics)
                    payload[f"k{nbits}"] = c.valid_mask().astype(jnp.uint8)
                    nbits += 1
            payload = {k: shard.pin(v, ctx) for k, v in payload.items()}
            out, emit, _cap = exchange(payload, targets,
                                       shard.pin(t_emit, ctx), ctx)
            kbits = tuple(out[f"k{j}"] for j in range(nbits))
            dat, val = _payload_tuples(out, len(cols))
            shuffled.append((kbits, emit, dat, val))

    (lkb, lemit, ldat, lval), (rkb, remit, rdat, rval) = shuffled

    with _phase("distributed_set_op.count", seq):
        counts = np.asarray(jax.device_get(_setop_count_fn(ctx.mesh)(
            lkb, lemit, rkb, remit))).reshape(world, 3)
    total = counts[:, int(op)]
    cap = _capacity(int(total.max()))

    with _phase("distributed_set_op.materialize", seq):
        od, ov, emit = _setop_mat_fn(ctx.mesh, op, cap)(
            lkb, lemit, rkb, remit, ldat, lval, rdat, rval)

    cols = []
    for d, v, a in zip(od, ov, lcols):
        cols.append(Column(d, a.dtype, v, a.dictionary, a.name))
    return Table(cols, ctx, emit)


# ---------------------------------------------------------------------------
# distributed groupby (reference: GroupBy, groupby/groupby.cpp:96-139;
# the reference pre-aggregates then re-applies the same op — which makes
# distributed COUNT wrong (SURVEY §3.2). Here the shuffle co-locates all
# rows of a key first, so ONE aggregation pass is both correct and simple;
# pre-aggregation is a future bandwidth optimization.)
# ---------------------------------------------------------------------------

def distributed_groupby(table: Table, index_col, aggregate_cols: List,
                        aggregate_ops: List[_groupby.AggregationOp]) -> Table:
    ctx = table._ctx
    world = ctx.get_world_size()
    if world == 1:
        return table_mod.groupby_local(table, index_col, aggregate_cols,
                                       aggregate_ops)

    t = shard.distribute(table, ctx)
    idx_cols = index_col if isinstance(index_col, (list, tuple)) else [index_col]
    idx_cols = [t._col_index(c) for c in idx_cols]
    val_cols = [t._col_index(c) for c in aggregate_cols]
    key_columns = [t._columns[i] for i in idx_cols]

    seq = ctx.get_next_sequence()
    with _phase("distributed_groupby.shuffle", seq):
        targets = shard.pin(_hash.partition_targets(key_columns, world), ctx)
        payload = {}
        for j, c in enumerate(key_columns):
            payload[f"kb{j}"] = _order.sort_keys([c])[0]
            payload[f"kd{j}"] = c.data
            payload[f"kv{j}"] = c.valid_mask()
        for j, vi in enumerate(val_cols):
            payload[f"d{j}"] = t._columns[vi].data
            payload[f"v{j}"] = t._columns[vi].valid_mask()
        payload = {k: shard.pin(v, ctx) for k, v in payload.items()}
        out, emit, _cap = exchange(payload, targets,
                                   shard.pin(t.emit_mask(), ctx), ctx)

    nk, nv = len(idx_cols), len(val_cols)
    kbits = tuple(out[f"kb{j}"] for j in range(nk))
    kdat = tuple(out[f"kd{j}"] for j in range(nk))
    kval = tuple(out[f"kv{j}"] for j in range(nk))
    vdat = tuple(out[f"d{j}"] for j in range(nv))
    vval = tuple(out[f"v{j}"] for j in range(nv))

    ops = tuple(aggregate_ops)
    with _phase("distributed_groupby.aggregate", seq):
        kout, kvout, gvalid, agg = _groupby_fn(ctx.mesh, ops)(
            kbits, kdat, kval, emit, vdat, vval)

    cols = []
    for d, v, src_i in zip(kout, kvout, idx_cols):
        src = t._columns[src_i]
        cols.append(Column(d, src.dtype, v, src.dictionary, src.name))
    for (arr, av), vi, op in zip(agg, val_cols, aggregate_ops):
        src = t._columns[vi]
        keep_dict = (op in (_groupby.AggregationOp.MIN,
                            _groupby.AggregationOp.MAX) and src.is_string)
        cols.append(Column(arr, table_mod._agg_dtype(src, op), av,
                           src.dictionary if keep_dict else None, src.name))
    return Table(cols, ctx, gvalid)


# ---------------------------------------------------------------------------
# distributed sort (reference has local Sort only, table.hpp:365; here a
# GLOBAL sort over the sharded arrays — XLA lowers the cross-shard sort/
# gather itself. Stays on device: dead rows sort to the tail via an emit
# key instead of host-side compaction.)
# ---------------------------------------------------------------------------

def distributed_sort(table: Table, order_by, ascending=True) -> Table:
    ctx = table._ctx
    t = shard.distribute(table, ctx) if ctx.is_distributed() else table
    by = order_by if isinstance(order_by, (list, tuple)) else [order_by]
    idxs = [t._col_index(c) for c in by]
    asc = list(ascending) if isinstance(ascending, (list, tuple)) \
        else [ascending] * len(idxs)
    with _phase("distributed_sort", ctx.get_next_sequence()):
        keys = _order.sort_keys([t._columns[i] for i in idxs], asc)
        emit = t.emit_mask()
        # live rows first, padding at the tail
        dead_last = (~emit).astype(jnp.uint8)
        perm = _order.lexsort_indices([dead_last] + keys)
        cols = []
        for c in t._columns:
            g = c.take(perm)
            validity = None if g.validity is None \
                else shard.pin(g.validity, ctx)
            cols.append(Column(shard.pin(g.data, ctx), g.dtype, validity,
                               g.dictionary, g.name))
        return Table(cols, ctx, shard.pin(jnp.take(emit, perm), ctx))
