"""Distributed relational operators: shuffle-composed, per-shard kernels.

The reference composes every distributed op as *local partition + all-to-all
+ local op* (reference: docs/docs/arch.md:48-52; DistributedJoin
table.cpp:656-696; set ops table.cpp:948-992; GroupBy
groupby/groupby.cpp:96-139). The same composition here, but each stage is a
compiled SPMD program over the mesh instead of per-rank C++:

  1. key prep runs on the GLOBAL sharded arrays (elementwise → no comms):
     dtype promotion / dictionary unification, order-preserving key bits,
     murmur-style partition targets;
  2. the shuffle is the two-phase count+exchange from parallel/shuffle.py;
  3. the local stage runs per shard inside `shard_map` — matching keys are
     co-located after the hash shuffle, so per-shard dense ranks + the same
     vectorized kernels as the local path produce the distributed result.

Data-dependent output sizes follow the framework-wide eager discipline:
a count kernel returns per-shard totals, the host picks a pow2 capacity
(bounding recompilation), a materialize kernel fills static-shape outputs
whose padding rows carry emit=False. Results stay sharded; nothing is
gathered to the host.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

try:  # jax>=0.8
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from .. import dtypes
from ..context import CylonContext
from ..data import table as table_mod
from ..data.column import Column, unify_dictionaries
from ..data.strings import pair_k_words as _pair_k
from ..data.table import Table
from ..ops import groupby as _groupby
from ..ops import hash as _hash
from ..ops import join as _join
from ..ops import order as _order
from ..ops import setops as _setops
from ..status import Code, CylonPlanError
from ..telemetry import annotate as _annotate, counted_cache, \
    counter as _counter, ledger as _ledger, phase as _phase, \
    record_host_sync as _host_sync, span as _span
from . import shard
from ..benchutils import bucket_cap as _bucket_cap
from ..util import capacity as _capacity, pow2_floor as _pow2_floor
from .shuffle import count_pair, exchange, exchange_pair, \
    replicated_gather


# ---------------------------------------------------------------------------
# payload plumbing
# ---------------------------------------------------------------------------

def _table_payload(t: Table) -> dict:
    p = {}
    for i, c in enumerate(t._columns):
        p[f"d{i}"] = c.data
        p[f"v{i}"] = c.valid_mask()
    return p


# ---------------------------------------------------------------------------
# varbytes (device-native strings) distributed plumbing. A sharded
# varbytes column is a SELF-CONTAINED per-shard layout (shard-relative
# starts), so all content kernels run per shard; moving rows moves their
# words through a SECOND exchange whose "rows" are words — the byte-count
# matrix the reference's ArrowAllToAll length headers carry
# (arrow_all_to_all.cpp:96-107) is exactly this word exchange's count
# phase.
# ---------------------------------------------------------------------------


@counted_cache
def _string_hash_fn(mesh, max_words: int):
    """Per-shard content hashes (h1, h2, h3, len-as-u32) for a sharded
    varbytes column — strings._hash_rows under shard_map (shard-relative
    starts make the per-shard call exact)."""
    from ..data import strings as _strings

    spec = P(mesh.axis_names[0])

    def kernel(words, starts, lengths):
        h1, h2, h3 = _strings._hash_rows(words, starts, lengths, max_words)
        return h1, h2, h3, lengths.astype(jnp.uint32)

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * 3,
                             out_specs=spec))


def _dist_string_keys(ctx: CylonContext, col: Column):
    """(h1, h2, h3, len) sharded key arrays for one varbytes column."""
    vb = col.varbytes
    return _string_hash_fn(ctx.mesh, vb.max_words)(
        shard.pin(vb.words, ctx), shard.pin(vb.starts, ctx),
        shard.pin(vb.lengths, ctx))


@counted_cache
def _word_lanes_fn(mesh, k_lim: int):
    """Per-shard word-lane lift of a sharded varbytes column
    (shard-relative starts make each shard's gather self-contained —
    no cross-shard indexing escapes the shard_map)."""
    spec = P(mesh.axis_names[0])

    def kernel(words, starts, lengths):
        nw = (lengths + 3) >> 2
        wcap = words.shape[0]
        outs = []
        for k in range(k_lim):
            pos = jnp.clip(starts + k, 0, wcap - 1)
            outs.append(jnp.where(k < nw, jnp.take(words, pos),
                                  jnp.uint32(0)))
        return tuple(outs)

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * 3,
                             out_specs=(spec,) * k_lim))


def _dist_word_lanes(ctx: CylonContext, col: Column, k_lim: int) -> list:
    vb = col.varbytes
    return list(_word_lanes_fn(ctx.mesh, k_lim)(
        shard.pin(vb.words, ctx), shard.pin(vb.starts, ctx),
        shard.pin(vb.lengths, ctx)))


def _lanes_hash(lanes: Sequence[jnp.ndarray], ln_u32) -> jnp.ndarray:
    """Elementwise partition hash of word lanes + length — the exact-key
    analog of the content-hash h1 (both sides of a join call this with
    the SAME lane count, so equal bytes land on equal shards)."""
    h = ln_u32 * np.uint32(0x9E3779B1)
    for l in lanes:
        h = h * np.uint32(31) + _hash.fmix32(l)
    return _hash.fmix32(h)


def _dist_col_keys(ctx: CylonContext, c: Column, k_words: int = None):
    """One column's (key bit arrays, partition hash). Short varbytes
    (≤ EXACT_KEY_WORDS words, the pair max when ``k_words`` is passed)
    use raw word lanes + length — byte-exact; longer rows use the
    content-hash quad. Plain columns use ordered bits."""
    from ..data.strings import EXACT_KEY_WORDS

    if c.is_varbytes:
        vb = c.varbytes
        k = vb.max_words if k_words is None else max(int(k_words),
                                                     vb.max_words)
        if k <= EXACT_KEY_WORDS:
            lanes = _dist_word_lanes(ctx, c, k)
            ln = vb.lengths.astype(jnp.uint32)
            h1 = _lanes_hash(lanes, ln)
            if c.validity is not None:
                h1 = jnp.where(c.validity, h1, jnp.uint32(0x9E3779B9))
            return lanes + [ln], h1
        q = _dist_string_keys(ctx, c)
        h1 = q[0]
        if c.validity is not None:
            h1 = jnp.where(c.validity, h1, jnp.uint32(0x9E3779B9))
        return list(q), h1
    return [_order.sort_keys([c])[0]], _hash.hash_column(c)


def _dist_key_bits(ctx: CylonContext, cols: Sequence[Column],
                   paired: Sequence[Column] = None):
    """Key bit arrays, combined key-validity, and per-column partition
    hashes for per-shard join/group kernels. ``paired``: the other
    side's aligned key columns (joins) so both sides emit matching lane
    counts and partition hashes."""
    bits: list = []
    h1s: list = []
    kv = None
    for j, c in enumerate(cols):
        kw = _pair_k(c, paired[j]) if paired is not None else None
        b, h1 = _dist_col_keys(ctx, c, kw)
        bits.extend(b)
        h1s.append(h1)
        v = c.valid_mask()
        kv = v if kv is None else (kv & v)
    return tuple(bits), kv, h1s


def _targets_from_hashes(ctx: CylonContext, h1s: Sequence[jnp.ndarray]
                         ) -> jnp.ndarray:
    """Combine per-column row hashes into a shard target (the
    ops/hash.hash_columns combine scheme)."""
    world = ctx.get_world_size()
    h = None
    for hc in h1s:
        h = hc if h is None else h * np.uint32(31) + hc
    h = _hash.fmix32(h)
    return (h % np.uint32(world)).astype(jnp.int32)


def _partition_targets_dist(ctx: CylonContext, cols: Sequence[Column],
                            paired: Sequence[Column] = None
                            ) -> jnp.ndarray:
    """Per-row target shard for mixed plain/varbytes key columns. Plain
    columns use the elementwise hash (sharding-transparent); varbytes
    hash per shard. ``paired``: the other side's aligned key columns so
    both sides hash with matching lane counts."""
    h1s = []
    for j, c in enumerate(cols):
        kw = _pair_k(c, paired[j]) if paired is not None else None
        h1s.append(_dist_col_keys(ctx, c, kw)[1])
    return _targets_from_hashes(ctx, h1s)


@counted_cache
def _word_targets_fn(mesh):
    """Word-level (targets, emit) from row-level (targets, emit): every
    word inherits its row's shuffle target; words of dead rows and slack
    slots are dropped."""
    from ..data import strings as _strings

    spec = P(mesh.axis_names[0])

    def kernel(words, starts, lengths, targets, emit):
        W = words.shape[0]
        nw = (lengths + 3) >> 2
        row, p = _strings._word_row_map(starts, nw, W)
        wt = jnp.take(targets, row)
        wemit = jnp.take(emit, row) & (p >= 0) & (p < jnp.take(nw, row))
        return wt.astype(jnp.int32), wemit

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * 5,
                             out_specs=spec))


@counted_cache
def _starts_reconcile_fn(mesh, row_block: int, word_block: int):
    """Rebuild shard-relative varbytes starts after a row+word exchange
    pair, for ANY combination of padded/compact layouts (block=0 means
    compact). Both exchanges keep each source's items contiguous and in
    matching order, so row (source s, j)'s words sit at that source's
    word-segment offset plus the within-source word prefix."""
    axis = mesh.axis_names[0]
    world = mesh.devices.size
    spec = P(axis)

    def kernel(lengths, row_ci, word_ci):
        n = lengths.shape[0]
        nw = (lengths + 3) >> 2
        cs = jnp.cumsum(nw)
        if row_block:
            row_off = jnp.arange(world, dtype=jnp.int32) * row_block
        else:
            row_off = jnp.cumsum(row_ci) - row_ci
        if word_block:
            word_off = jnp.arange(world, dtype=jnp.int32) * word_block
        else:
            word_off = jnp.cumsum(word_ci) - word_ci
        pos = jnp.arange(n, dtype=jnp.int32)
        sid = jnp.zeros(n, jnp.int32)
        for s in range(1, world):
            sid = sid + (pos >= row_off[s]).astype(jnp.int32)
        head = jnp.where(row_off > 0,
                         jnp.take(cs, jnp.maximum(row_off - 1, 0)), 0)
        return jnp.take(word_off, sid) + (cs - nw) - jnp.take(head, sid)

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * 3,
                             out_specs=spec))


def _exchange_varbytes_words(ctx: CylonContext, vb, targets, emit,
                             new_lengths, row_meta: dict):
    """The word-leg of a varbytes shuffle: words ride their own exchange
    (stability of the bucket sort keeps word order == row order), then
    shard-relative starts reconcile the two layouts."""
    from ..data.strings import VarBytes

    world = ctx.get_world_size()
    wt, wemit = _word_targets_fn(ctx.mesh)(
        shard.pin(vb.words, ctx), shard.pin(vb.starts, ctx),
        shard.pin(vb.lengths, ctx), targets, emit)
    wout, _wemit2, _wcap, wmeta = exchange(
        {"w": shard.pin(vb.words, ctx)}, wt, wemit, ctx)
    new_starts = _starts_reconcile_fn(
        ctx.mesh, row_meta["block"], wmeta["block"])(
        new_lengths, row_meta["counts_in"], wmeta["counts_in"])
    return VarBytes(wout["w"], new_starts, new_lengths, vb.max_words,
                    int(wout["w"].shape[0]),
                    shard_geom=(int(new_lengths.shape[0]) // world,
                                int(wout["w"].shape[0]) // world))


@counted_cache
def _lanes_interleave_fn(mesh, K: int):
    """Per-shard (lengths, lanes…) → (interleaved words, shard-relative
    starts): the strided-layout assembly stays local to each shard (a
    global reshape over the sharded row axis would re-layout)."""
    spec = P(mesh.axis_names[0])

    def kernel(lengths, *lanes):
        n = lengths.shape[0]
        nw = (lengths + 3) >> 2
        masked = [jnp.where(k < nw, l, jnp.uint32(0))
                  for k, l in enumerate(lanes)]
        flat = jnp.stack(masked, axis=1).reshape(-1)
        starts = jnp.arange(n, dtype=jnp.int32) * jnp.int32(K)
        return flat, starts

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * (1 + K),
                             out_specs=(spec, spec)))


def _from_lanes_sharded(ctx: CylonContext, lanes, lengths):
    """Strided sharded VarBytes from exchanged word lanes: each shard's
    rows occupy [r_local*K, r_local*K + nw) of its own word segment —
    shard-relative starts, shard_geom rows*K word stride."""
    from ..data.strings import VarBytes

    K = max(len(lanes), 1)
    n = int(lengths.shape[0])
    world = ctx.get_world_size()
    rows = n // world
    flat, starts = _lanes_interleave_fn(ctx.mesh, K)(lengths, *lanes)
    return VarBytes(flat, starts, lengths, K, n * K,
                    shard_geom=(rows, rows * K), stride=K)


def _build_exchange_payload(t: Table, ctx: CylonContext,
                            extra: Optional[dict]):
    """Payload leaves for a table shuffle. Short varbytes columns
    (≤ LANE_WORDS_MAX words) ride the ROW exchange as fixed word lanes —
    no second word-level exchange, no extra count sync, no starts
    reconcile. All-valid columns skip the mask leaf entirely (validity
    None round-trips as None — one less sort operand per column)."""
    from ..data.strings import LANE_WORDS_MAX

    payload = dict(extra or {})
    lane_cols = {}
    for i, c in enumerate(t._columns):
        payload[f"d{i}"] = c.data  # byte lengths for varbytes columns
        if c.validity is not None:
            payload[f"v{i}"] = c.valid_mask()
        if c.is_varbytes and c.varbytes.max_words <= LANE_WORDS_MAX:
            vb = c.varbytes
            lanes = _word_lanes_fn(ctx.mesh, vb.max_words)(
                shard.pin(vb.words, ctx), shard.pin(vb.starts, ctx),
                shard.pin(vb.lengths, ctx))
            lane_cols[i] = vb.max_words
            for k, l in enumerate(lanes):
                payload[f"d{i}w{k}"] = l
    payload = {k: shard.pin(v, ctx) for k, v in payload.items()}
    return payload, lane_cols


def _finish_exchange_table(t: Table, ctx: CylonContext, targets, emit,
                           out, new_emit, meta, lane_cols,
                           extra: Optional[dict]):
    cols = []
    for i, c in enumerate(t._columns):
        d, v = out[f"d{i}"], out.get(f"v{i}")
        if c.is_varbytes:
            # the padded-mode exchange over-reads neighbor rows into dead
            # slots, so dead rows can carry live rows' byte lengths; the
            # lane masking and every later _word_row_map pass need dead
            # rows at nw=0 to keep the monotone-starts invariant
            # (strings.py _word_row_map), so zero them first
            d = jnp.where(new_emit, d, jnp.zeros((), d.dtype))
            if i in lane_cols:
                vb = _from_lanes_sharded(
                    ctx, [out[f"d{i}w{k}"] for k in range(lane_cols[i])],
                    d)
            else:
                vb = _exchange_varbytes_words(ctx, c.varbytes, targets,
                                              emit, d, meta)
            cols.append(Column(vb.lengths, c.dtype, v, None, c.name,
                               varbytes=vb))
        else:
            cols.append(Column(d, c.dtype, v, c.dictionary, c.name))
    extra_out = {k: out[k] for k in (extra or {})}
    return cols, new_emit, extra_out


def _exchange_table(t: Table, targets, emit, ctx: CylonContext,
                    extra: Optional[dict] = None, counts=None,
                    dense: bool = False):
    """Shuffle a whole table's columns (fixed-width AND varbytes) plus
    optional extra per-row arrays. Returns (columns, new_emit,
    extra_out). ``dense``: caller asserts ``emit`` is all-live (enables
    the count-free fused world-1 route)."""
    payload, lane_cols = _build_exchange_payload(t, ctx, extra)
    out, new_emit, _cap, meta = exchange(payload, targets, emit, ctx,
                                         counts=counts, dense=dense)
    return _finish_exchange_table(t, ctx, targets, emit, out, new_emit,
                                  meta, lane_cols, extra)


def _exchange_table_pair(t1: Table, tg1, e1, c1, t2: Table, tg2, e2, c2,
                         ctx: CylonContext, dense: bool = False):
    """Two-table shuffle in ONE compiled program when both sides route
    padded (exchange_pair) — the distributed join/set-op composition."""
    p1, lc1 = _build_exchange_payload(t1, ctx, None)
    p2, lc2 = _build_exchange_payload(t2, ctx, None)
    r1, r2 = exchange_pair(p1, tg1, e1, c1, p2, tg2, e2, c2, ctx,
                           dense=dense)
    out1, ne1, _cap1, m1 = r1
    out2, ne2, _cap2, m2 = r2
    return (_finish_exchange_table(t1, ctx, tg1, e1, out1, ne1, m1, lc1,
                                   None),
            _finish_exchange_table(t2, ctx, tg2, e2, out2, ne2, m2, lc2,
                                   None))


# -- per-shard varlen gather (count → take at worst-shard capacity) --


@counted_cache
def _varlen_count_fn(mesh, replicated: bool = False):
    """Output-word count for a per-shard varlen gather. ``replicated``:
    the length source is a replicated (vocab) array, idx stays sharded."""
    axis = mesh.axis_names[0]
    spec = P(axis)

    def kernel(lengths, idx):
        safe = jnp.maximum(idx, 0)
        nw = (jnp.take(lengths, safe) + 3) >> 2
        total = jnp.where(idx >= 0, nw, 0).sum().astype(jnp.int32)
        return replicated_gather(total[None], axis, mesh.devices.size)

    src = P() if replicated else spec
    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(src, spec),
                             out_specs=P()))


@counted_cache
def _varlen_take_fn(mesh, cap_w: int, replicated: bool = False):
    """Per-shard varlen gather (strings._take_program under shard_map).
    ``replicated``: gather FROM a replicated source (dict vocab lift)."""
    from ..data import strings as _strings

    spec = P(mesh.axis_names[0])

    def kernel(words, starts, lengths, idx):
        return _strings._take_program(words, starts, lengths, idx, cap_w)

    src = P() if replicated else spec
    return jax.jit(shard_map(kernel, mesh=mesh,
                             in_specs=(src, src, src, spec),
                             out_specs=spec))


def _varlen_take_sharded(ctx: CylonContext, vb, idx) -> "object":
    """Distributed analog of VarBytes.take: per-shard varlen gather with
    ONE host sync for the worst shard's output word count."""
    from ..data.strings import VarBytes

    words = shard.pin(vb.words, ctx)
    starts = shard.pin(vb.starts, ctx)
    lengths = shard.pin(vb.lengths, ctx)
    idx = shard.pin(idx, ctx)
    counts = np.asarray(jax.device_get(
        _varlen_count_fn(ctx.mesh)(lengths, idx)))
    _host_sync("varlen.count")
    cap_w = _bucket_cap(int(counts.max()))
    w, s, ln = _varlen_take_fn(ctx.mesh, cap_w)(words, starts, lengths, idx)
    world = ctx.get_world_size()
    return VarBytes(w, s, ln, vb.max_words, int(w.shape[0]),
                    shard_geom=(int(idx.shape[0]) // world, cap_w))


def _dist_as_varbytes(ctx: CylonContext, col: Column) -> Column:
    """Sharding-aware as_varbytes: dictionary codes stay sharded; the
    (small) vocab VarBytes is replicated and each shard gathers its own
    self-contained layout."""
    from ..data.strings import VarBytes

    if col.is_varbytes:
        return col
    vocab_vb = VarBytes.from_host(col.dictionary)
    max_words = vocab_vb.max_words
    codes = shard.pin(col.data, ctx)
    counts = np.asarray(jax.device_get(
        _varlen_count_fn(ctx.mesh, replicated=True)(
            jax.device_put(vocab_vb.lengths), codes)))
    _host_sync("varlen.count")
    cap_w = _bucket_cap(int(counts.max()))
    w, s, ln = _varlen_take_fn(ctx.mesh, cap_w, replicated=True)(
        vocab_vb.words, vocab_vb.starts, vocab_vb.lengths, codes)
    world = ctx.get_world_size()
    vb = VarBytes(w, s, ln, max_words, int(w.shape[0]),
                  shard_geom=(int(codes.shape[0]) // world, cap_w))
    return Column(vb.lengths, col.dtype, col.validity, None, col.name,
                  varbytes=vb)


def _align_key_columns_dist(ctx: CylonContext, left_d: Table,
                            right_d: Table, lidx, ridx):
    """Distribution-aware align_key_columns: mixed string storages lift
    through the replicated-vocab kernel (the eager lift in
    data/column.align_string_columns would collapse per-shard layouts)."""
    lcols, rcols = [], []
    for li, ri in zip(lidx, ridx):
        a, b = left_d._columns[li], right_d._columns[ri]
        if a.is_string != b.is_string:
            raise CylonPlanError(
                f"join key type mismatch: {a.name} vs {b.name}",
                code=Code.TypeError)
        if a.is_string:
            if a.is_varbytes or b.is_varbytes:
                a = _dist_as_varbytes(ctx, a)
                b = _dist_as_varbytes(ctx, b)
            else:
                a, b = unify_dictionaries(a, b)
        elif a.data.dtype != b.data.dtype:
            common = jnp.promote_types(a.data.dtype, b.data.dtype)
            a = Column(a.data.astype(common), a.dtype, a.validity, None,
                       a.name)
            b = Column(b.data.astype(common), b.dtype, b.validity, None,
                       b.name)
        lcols.append(a)
        rcols.append(b)
    return lcols, rcols


def _payload_tuples(p: dict, ncols: int) -> Tuple[Tuple, Tuple]:
    return (tuple(p[f"d{i}"] for i in range(ncols)),
            tuple(p[f"v{i}"] for i in range(ncols)))


def _rebuild_columns(dat: Sequence, val: Sequence, src,
                     names: Sequence[str]) -> List[Column]:
    src_cols = src._columns if isinstance(src, Table) else src
    cols = []
    for d, v, c, name in zip(dat, val, src_cols, names):
        cols.append(Column(d, c.dtype, v, c.dictionary, name))
    return cols


def _all_valid(cols: Sequence[Column]) -> jnp.ndarray:
    v = cols[0].valid_mask()
    for c in cols[1:]:
        v = v & c.valid_mask()
    return v


# ---------------------------------------------------------------------------
# per-shard kernels (cached per mesh/static-shape signature)
# ---------------------------------------------------------------------------

@counted_cache
def _join_plan_fn(mesh, join_type: _join.JoinType):
    """Per-shard join plan: ONE fused sort per shard (join_plan_keys);
    match arrays stay sharded on device for the materialize phase, the
    [world, 2] count matrix is all_gather-REPLICATED so every controller
    process can fetch it (multi-host safe)."""
    axis = mesh.axis_names[0]
    spec = P(axis)

    def kernel(lbits, lkv, lemit, rbits, rkv, remit):
        counts2, lo, m, bperm, un_mask = _join.join_plan_keys(
            lbits, lkv, lemit, rbits, rkv, remit, join_type)
        world = mesh.devices.size
        return (replicated_gather(counts2, axis, world),
                lo, m, bperm, un_mask)

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * 6,
                             out_specs=(P(), spec, spec, spec, spec)))


_gather_side = _join.gather_columns


@counted_cache
def _join_plan_stream_fn(mesh, join_type: _join.JoinType, nk: int,
                         a_desc, b_desc, block_rows: int, hash_mode: bool):
    """Per-shard Pallas streaming join plan under shard_map — the same
    kernel chain the local join uses (ops/join.plan_program_stream),
    which the XLA per-shard plan was measured ~5x slower than at bench
    scale. TPU-only (the interpreter inside jit is prohibitive)."""
    axis = mesh.axis_names[0]
    world = mesh.devices.size
    spec = P(axis)

    def kernel(lkb, lkv, lemit, rkb, rkv, remit, ldat, lval, rdat, rval):
        counts, a_streams, b_streams = _join._plan_program_stream_impl(
            lkb, tuple([lkv] + [None] * (nk - 1)), lemit,
            rkb, tuple([rkv] + [None] * (nk - 1)), remit,
            ldat, lval, rdat, rval, (False,) * nk, join_type,
            a_desc=a_desc, b_desc=b_desc, block_rows=block_rows,
            hash_mode=hash_mode, interpret=False)
        return (replicated_gather(counts, axis, world), counts,
                a_streams, b_streams)

    # check_vma off: pallas_call outputs carry no varying-mesh-axes
    # annotation for the checker
    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * 10,
                             out_specs=(P(), spec, spec, spec),
                             check_vma=False))


@counted_cache
def _join_mat_stream_fn(mesh, join_type: _join.JoinType, cap_e: int,
                        a_desc, b_desc, block_rows: int):
    spec = P(mesh.axis_names[0])

    def kernel(counts, a_streams, b_streams, ldat, lval, rdat, rval):
        return _join._materialize_program_stream_impl(
            counts, a_streams, b_streams, ldat, lval, rdat, rval,
            join_type, cap_e, a_desc=a_desc, b_desc=b_desc,
            block_rows=block_rows, interpret=False)

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * 7,
                             out_specs=spec, check_vma=False))


def _dist_stream_mode(lkb, rkb, join_type: _join.JoinType, world: int):
    """None (XLA plan), or (hash_mode, block_rows) when the per-shard
    Pallas stream join applies (same applicability shape as the local
    join's router, on the post-exchange key-bit arrays)."""
    if jax.default_backend() != "tpu" or _join.STREAM_PLAN is False:
        return None
    if join_type == _join.JoinType.FULL_OUTER:
        return None
    na = int(lkb[0].shape[0]) // world
    nb = int(rkb[0].shape[0]) // world
    if na == 0 or nb == 0 or na + nb >= (1 << 29):
        return None
    if len(lkb) == 1 and lkb[0].dtype.itemsize == 4 \
            and lkb[0].dtype != jnp.bool_:
        return (False, _join.stream_block_rows(na, nb))
    lanes = sum(2 if b.dtype.itemsize == 8 else 1 for b in lkb)
    if lanes <= _join.MAX_HASH_KEY_LANES:
        return (True, _join.stream_block_rows(na, nb))
    return None


@counted_cache
def _join_mat_fn(mesh, join_type: _join.JoinType, cap_p: int, cap_u: int):
    spec = P(mesh.axis_names[0])

    def kernel(lo, m, bperm, un_mask, aemit, ldat, lval, rdat, rval):
        lidx, ridx, emit = _join.join_materialize_gids(
            lo, m, bperm, un_mask, aemit, join_type, cap_p, cap_u)
        lod, lov = _gather_side(ldat, lval, lidx)
        rod, rov = _gather_side(rdat, rval, ridx)
        return lod, lov, rod, rov, emit, lidx, ridx

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * 9,
                             out_specs=spec))


@counted_cache
def _setop_count_fn(mesh):
    spec = P(mesh.axis_names[0])

    def kernel(lbits, lemit, rbits, remit):
        gl, gr = _order.dense_ranks_two(list(lbits), list(rbits))
        c = _setops.setop_counts(gl, gr, lemit, remit)
        counts = jnp.stack([c["n_union"], c["n_subtract"],
                            c["n_intersect"]]).astype(jnp.int32)
        return replicated_gather(counts, mesh.axis_names[0],
                                 mesh.devices.size)

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * 4,
                             out_specs=P()))


@counted_cache
def _setop_mat_fn(mesh, op: _setops.SetOp, cap: int):
    spec = P(mesh.axis_names[0])

    def kernel(lbits, lemit, rbits, remit, ldat, lval, rdat, rval):
        gl, gr = _order.dense_ranks_two(list(lbits), list(rbits))
        idx = _setops.setop_indices(gl, gr, lemit, remit, op, cap)
        emit = idx >= 0
        # indices address the concatenated [left; right] per-shard table
        dat = tuple(jnp.concatenate([a, b]) for a, b in zip(ldat, rdat))
        val = tuple(jnp.concatenate([a, b]) for a, b in zip(lval, rval))
        od, ov = _gather_side(dat, val, idx)
        return od, ov, emit, idx

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * 8,
                             out_specs=spec))


@counted_cache
def _varlen_take_concat_count_fn(mesh):
    """Word count for a gather over the per-shard concat [left; right]
    varbytes pair."""
    axis = mesh.axis_names[0]
    spec = P(axis)

    def kernel(ll, lr, idx):
        lens = jnp.concatenate([ll, lr])
        safe = jnp.maximum(idx, 0)
        nw = (jnp.take(lens, safe) + 3) >> 2
        total = jnp.where(idx >= 0, nw, 0).sum().astype(jnp.int32)
        return replicated_gather(total[None], axis, mesh.devices.size)

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * 3,
                             out_specs=P()))


@counted_cache
def _varlen_take_concat_fn(mesh, cap_w: int):
    """Varlen gather over the per-shard concat of two varbytes columns.
    The source concat needs NO repacking: right starts shift by the
    (static) left word-buffer length — the hash/take range sums are
    gap-immune (data/strings.py)."""
    from ..data import strings as _strings

    spec = P(mesh.axis_names[0])

    def kernel(lw, ls, ll, rw, rs, rl, idx):
        words = jnp.concatenate([lw, rw])
        starts = jnp.concatenate([ls, rs + lw.shape[0]])
        lens = jnp.concatenate([ll, rl])
        return _strings._take_program(words, starts, lens, idx, cap_w)

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * 7,
                             out_specs=spec))


@counted_cache
def _groupby_fn(mesh, ops: Tuple[_groupby.AggregationOp, ...],
                col_ids: Tuple[int, ...], all_valid: Tuple[bool, ...]):
    spec = P(mesh.axis_names[0])

    def kernel(kbits, kdat, kval, emit, vdat, vval):
        n = emit.shape[0]
        keys = tuple(kbits) + tuple(v.astype(jnp.uint8) for v in kval)
        vdat_s, vval_s, emit_s, iota_s, gid_s, _ng = \
            _groupby.presort_groups(keys, emit, vdat, vval)
        rep, gvalid, results = _groupby.sorted_segment_aggregate(
            gid_s, emit_s, iota_s, vdat_s, vval_s, n, ops, col_ids,
            all_valid)
        safe = jnp.minimum(rep, n - 1)
        kout = tuple(jnp.take(d, safe, axis=0) for d in kdat)
        kvout = tuple(jnp.take(v, safe) & gvalid for v in kval)
        agg = tuple((arr, av & gvalid) for arr, av in results)
        return kout, kvout, gvalid, agg, safe

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * 6,
                             out_specs=spec))


# ---------------------------------------------------------------------------
# shuffle / partition public API
# ---------------------------------------------------------------------------

def shuffle(table: Table, hash_columns: Sequence,
            salted: bool = False) -> Table:
    """Repartition rows by key hash (reference: cylon::Shuffle,
    table.cpp:162-236). Tables already hash-placed on the same keys
    (a previous shuffle, or shard.distribute_by_key host ingest) pass
    through without an exchange.

    ``salted``: the hot-key load-balancing variant (adaptive
    execution): the salted-targets program decides on device which
    destinations are hot (receive total past CYLON_SKEW_WARN_FACTOR x
    the mean, from the true global count matrix) and spreads exactly
    those destinations' rows across CYLON_SALT_FACTOR consecutive
    shards — bounding the max shard under Zipfian keys. The salt is
    routing-only (nothing to strip on the receive side), but the
    output carries NO placement witness: salted placement is
    positional, and every downstream consumer must re-establish
    placement itself. Skew observability records the RAW
    (pre-mitigation) count matrix, so the planner's salting decision
    reads true key skew, never its own mitigation."""
    from .shuffle import salted_exchange_targets
    from ..telemetry import knobs as _knobs
    from ..telemetry import skew as _skew

    ctx = table._ctx
    world = ctx.get_world_size()
    if world == 1:
        return table
    t = shard.distribute(table, ctx)
    idxs = [t._col_index(c) for c in hash_columns]
    sig = shard.partition_signature([t._columns[i] for i in idxs], idxs,
                                    world)
    # pow2_floor: the salt factor keys the compiled salted-targets
    # program (1 per octave, specialization-clean); the effective
    # spread is therefore the pow2 floor of CYLON_SALT_FACTOR
    salt = _pow2_floor(max(int(_knobs.get("CYLON_SALT_FACTOR")), 1)) \
        if salted else 0
    salted = salted and salt >= 2
    if sig is not None and t._hash_partitioned == sig and not salted:
        return t
    targets = shard.pin(_partition_targets_dist(
        ctx, [t._columns[i] for i in idxs]), ctx)
    emit = shard.pin(t.emit_mask(), ctx)
    if salted:
        warn = float(_knobs.get("CYLON_SKEW_WARN_FACTOR"))
        targets, counts, raw = salted_exchange_targets(
            targets, emit, ctx, salt, warn)
        targets = shard.pin(targets, ctx)
        _counter("cylon_salted_exchanges_total").inc()
        raw_stats = _skew.SkewStats.from_counts(raw)
        _annotate(salted=True, salt_factor=salt,
                  skew_raw=round(raw_stats.imbalance, 3)
                  if raw_stats is not None else None)
        cols, new_emit, _x = _exchange_table(t, targets, emit, ctx,
                                             counts=counts)
        result = Table(cols, ctx, new_emit)
        # NO witness: hot keys are spread positionally across shards
        table._free_if_unretained()
        return _ledger.track(result, "shuffle")
    cols, new_emit, _x = _exchange_table(t, targets, emit, ctx,
                                         dense=t.row_mask is None)
    result = Table(cols, ctx, new_emit)
    result._hash_partitioned = sig
    # reference parity: Shuffle frees non-retained inputs (table.cpp:207)
    table._free_if_unretained()
    return _ledger.track(result, "shuffle")


def hash_partition(table: Table, hash_columns: Sequence,
                   num_partitions: int) -> dict:
    """Split into a {partition_id: Table} map (reference: HashPartition,
    table.hpp:354, table.cpp:102-160). DEVICE-RESIDENT: one fused
    stable sort by target bucket carries every column as an operand
    (the same trick the exchange's bucket sort uses), then each
    partition is a contiguous device slice — rows never leave HBM
    (round-3 verdict: the old host-numpy round trip was wrong for a
    device table mid-pipeline). Long varbytes columns (> LANE_WORDS_MAX
    words) fall back to the native host partitioner."""
    from ..data.column import Column
    from ..data.strings import LANE_WORDS_MAX, VarBytes

    idxs = [table._col_index(c) for c in hash_columns]
    if any(c.is_varbytes and c.varbytes.max_words > LANE_WORDS_MAX
           for c in table._columns):
        return _hash_partition_host(table, idxs, num_partitions)

    t = table
    ctx = t._ctx
    emit = t.emit_mask()
    targets = _hash.partition_targets(
        [t._columns[i] for i in idxs], num_partitions)
    # varbytes key columns need content hashes, not length hashes —
    # partition_targets handles them via hash_column internally; short
    # varbytes PAYLOADS ride the sort as word lanes below
    tkey = jnp.where(emit, targets, jnp.int32(num_partitions))
    leaves = []
    desc = []  # (col_idx, kind) per leaf, kind in d/v/w
    for ci, c in enumerate(t._columns):
        leaves.append(c.data)
        desc.append((ci, "d"))
        if c.validity is not None:
            leaves.append(c.valid_mask())
            desc.append((ci, "v"))
        if c.is_varbytes:
            for l in c.varbytes.word_lanes():
                leaves.append(l)
                desc.append((ci, "w"))
    res = jax.lax.sort((tkey,) + tuple(leaves), num_keys=1,
                       is_stable=True)
    sorted_leaves = list(res[1:])
    counts = np.asarray(jax.device_get(jax.ops.segment_sum(
        jnp.ones(tkey.shape[0], jnp.int32), tkey,
        num_segments=num_partitions + 1)))[:num_partitions]
    _host_sync("hash_partition.counts")
    offs = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)

    out = {}
    for p in range(num_partitions):
        lo, hi = int(offs[p]), int(offs[p + 1])
        cols = []
        by_col = {}
        for (ci, kind), leaf in zip(desc, sorted_leaves):
            by_col.setdefault(ci, {}).setdefault(kind, []).append(
                leaf[lo:hi])
        for ci, c in enumerate(t._columns):
            parts = by_col[ci]
            d = parts["d"][0]
            v = parts.get("v", [None])[0]
            if c.is_varbytes:
                vb = VarBytes.from_lanes(parts["w"], d)
                cols.append(Column(vb.lengths, c.dtype, v, None, c.name,
                                   varbytes=vb))
            else:
                cols.append(Column(d, c.dtype, v, c.dictionary, c.name))
        out[p] = Table(cols, ctx)
    return out


def _hash_partition_host(table: Table, idxs, num_partitions: int) -> dict:
    """Host partitioner (native ct_row_hash) — the long-varbytes path."""
    from ..data.column import Column
    from ..data.strings import VarBytes

    t = table.compact()
    host, valids, counts, order, offs = shard.host_partition_arrays(
        t, idxs, num_partitions)
    out = {}
    for p in range(num_partitions):
        seg = order[offs[p]:offs[p + 1]]
        cols = []
        for ci, c in enumerate(t._columns):
            v = None if valids[ci] is None else jnp.asarray(valids[ci][seg])
            if c.is_varbytes:
                vb = VarBytes.from_host(host[ci][seg])
                cols.append(Column(vb.lengths, c.dtype, v, None, c.name,
                                   varbytes=vb))
            else:
                cols.append(Column(jnp.asarray(host[ci][seg]), c.dtype, v,
                                   c.dictionary, c.name))
        out[p] = Table(cols, t._ctx)
    return out


def repartition(table: Table, ctx: CylonContext) -> Table:
    """Round-robin balance rows across shards (no key)."""
    t = shard.distribute(table, ctx)
    world = ctx.get_world_size()
    n = t.capacity
    targets = shard.pin(
        jnp.arange(n, dtype=jnp.int32) % world, ctx)
    cols, new_emit, _x = _exchange_table(
        t, targets, shard.pin(t.emit_mask(), ctx), ctx,
        dense=t.row_mask is None)
    return _ledger.track(Table(cols, ctx, new_emit), "repartition")


# ---------------------------------------------------------------------------
# distributed join (reference: DistributedJoin, table.cpp:656-696)
# ---------------------------------------------------------------------------

def distributed_join(left: Table, right: Table, config: _join.JoinConfig,
                     force_exchange: bool = False) -> Table:
    """``force_exchange``: run the full shuffle+join composition even on
    a 1-wide mesh / co-partitioned inputs (the all_to_all still executes)
    — used by bench.py to time the honest distributed path on one chip."""
    ctx = left._ctx
    world = ctx.get_world_size()
    if world == 1 and not (force_exchange and ctx.is_distributed()):
        # reference parity: world==1 short-circuits to the local join
        # (table.cpp:662-669)
        _counter("cylon_join_algorithm_total", {"algo": "local"}).inc()
        return _ledger.track(table_mod.join(left, right, config),
                             "distributed_join")
    # the runtime-honest algorithm census (adaptive execution: which
    # joins actually went broadcast — see broadcast_hash_join)
    _counter("cylon_join_algorithm_total", {"algo": "shuffle"}).inc()
    exact_pairs = []
    if getattr(config, "exact", False):
        from ..data.strings import EXACT_KEY_WORDS

        for li, rj in zip(config.left_column_idx, config.right_column_idx):
            a, b = left._columns[li], right._columns[rj]
            kw = _pair_k(a, b)
            if kw is not None and kw > EXACT_KEY_WORDS:
                # long keys join on the 96-bit content hash; exact=True
                # byte-verifies AFTER the exchange (both key columns are
                # row-aligned in the output) — INNER filters false
                # matches, outer joins redo on dictionary codes
                # (round-5: VERDICT r04 #8 closed the old rejection)
                exact_pairs.append((li, rj))

    left_d = shard.distribute(left, ctx)
    right_d = shard.distribute(right, ctx)
    lidx, ridx = config.left_column_idx, config.right_column_idx
    lcols, rcols = _align_key_columns_dist(ctx, left_d, right_d, lidx, ridx)

    seq = ctx.get_next_sequence()
    shuffled = []
    with _span("distributed_join.shuffle", seq, world=world,
               rows_in=left_d.capacity + right_d.capacity) as _sp:
        plan = []
        for t, kcols, kidx, other in ((left_d, lcols, lidx, rcols),
                                      (right_d, rcols, ridx, lcols)):
            sig = shard.partition_signature(kcols, kidx, world)
            if sig is not None and t._hash_partitioned == sig \
                    and not force_exchange:
                # co-partitioned (prior shuffle or distribute_by_key host
                # ingest): rows are already hash-placed — skip the exchange
                plan.append(("skip", t, None, None))
                continue
            # targets need only the partition hashes; key BITS are
            # recomputed from the shuffled columns below (elementwise /
            # per-shard work), so the exchange moves ~2/3 fewer lanes —
            # measured 813 ms -> the bare-columns exchange cost at 16M
            targets = shard.pin(
                _partition_targets_dist(ctx, kcols, other), ctx)
            emit = shard.pin(t.emit_mask(), ctx)
            plan.append(("exchange", t, targets, emit))
        # both sides exchanging: ONE fused count program + ONE host sync
        # covers both shuffles (the reference pays a header phase per
        # table per peer, mpi_channel.cpp:211-225; here the axon tunnel
        # charges ~100 ms per round trip, so fusing halves the fixed
        # cost of the composition)
        ex = [p for p in plan if p[0] == "exchange"]
        _sp.set(sides_exchanged=len(ex), sides_skipped=2 - len(ex))
        results = {}
        if len(ex) == 2:
            # 1-wide mesh + dense emits: skip the count sync entirely —
            # the fused padded body computes counts in-program (round-5)
            dense = (ex[0][1].row_mask is None
                     and ex[1][1].row_mask is None)
            cl = cr = None
            if world > 1 or not dense:
                cl, cr = count_pair(ex[0][2], ex[0][3], ex[1][2],
                                    ex[1][3], ctx)
            r1, r2 = _exchange_table_pair(
                ex[0][1], ex[0][2], ex[0][3], cl,
                ex[1][1], ex[1][2], ex[1][3], cr, ctx, dense=dense)
            results[id(ex[0])] = r1
            results[id(ex[1])] = r2
        for p in plan:
            kind, t, targets, emit = p
            if kind == "skip":
                shuffled.append((t._columns, t.row_mask,
                                 shard.pin(t.emit_mask(), ctx)))
                continue
            if id(p) in results:
                cols, emit_s, _x = results[id(p)]
            else:
                cols, emit_s, _x = _exchange_table(
                    t, targets, emit, ctx, dense=t.row_mask is None)
            shuffled.append((cols, emit_s, emit_s))

    # rebuild key bits from the SHUFFLED columns (word lanes reshape out
    # of the strided layout; plain columns are elementwise ordered-bits)
    (lcols_all, lmask, lemit), (rcols_all, rmask, remit) = shuffled
    left_s = Table(list(lcols_all), ctx, lmask)
    right_s = Table(list(rcols_all), ctx, rmask)
    lcols2, rcols2 = _align_key_columns_dist(ctx, left_s, right_s,
                                             lidx, ridx)
    lkb, lkv, _h1s_l = _dist_key_bits(ctx, lcols2, rcols2)
    rkb, rkv, _h1s_r = _dist_key_bits(ctx, rcols2, lcols2)
    lkb = tuple(shard.pin(b, ctx) for b in lkb)
    rkb = tuple(shard.pin(b, ctx) for b in rkb)
    lkv = shard.pin(lkv, ctx)
    rkv = shard.pin(rkv, ctx)
    lcols_s, rcols_s = lcols_all, rcols_all
    lvb = [i for i, c in enumerate(lcols_s) if c.is_varbytes]
    rvb = [i for i, c in enumerate(rcols_s) if c.is_varbytes]
    ldat = tuple(shard.pin(c.data, ctx) for c in lcols_s)
    lval = tuple(shard.pin(c.valid_mask(), ctx) for c in lcols_s)
    rdat = tuple(shard.pin(c.data, ctx) for c in rcols_s)
    rval = tuple(shard.pin(c.valid_mask(), ctx) for c in rcols_s)

    jt = config.type
    res = None
    mode = _dist_stream_mode(lkb, rkb, jt, world)
    if mode is not None:
        hash_mode, br = mode
        a_desc, b_desc = _join.plan_lane_descs(ldat, lval, rdat, rval, jt)
        with _phase("distributed_join.plan", seq):
            rep_counts, counts_dev, a_streams, b_streams = \
                _join_plan_stream_fn(ctx.mesh, jt, len(lkb), a_desc,
                                     b_desc, br, hash_mode)(
                    lkb, lkv, lemit, rkb, rkv, remit,
                    ldat, lval, rdat, rval)
            # the plan program's replicated counts-gather is a real
            # collective dispatch — counted, so the adaptive bench's
            # launch comparison is honest on both algorithms
            _counter("cylon_collective_launches_total").inc()
            cm = np.asarray(jax.device_get(rep_counts)).reshape(world, -1)
            _host_sync("join.plan")
        if not (hash_mode and int(cm[:, 3].sum()) > 0):
            cap_e = _join.stream_expand_capacity(int(cm[:, 0].max()), br)
            with _phase("distributed_join.materialize", seq):
                res = _join_mat_stream_fn(
                    ctx.mesh, jt, cap_e, a_desc, b_desc, br)(
                    counts_dev, a_streams, b_streams,
                    ldat, lval, rdat, rval)
        # else: 64-bit hash collision — recompute via the exact XLA plan

    if res is not None:
        lod, lov, rod, rov, emit, lidx_o, ridx_o = res
    else:
        with _phase("distributed_join.plan", seq):
            counts2, lo, m, bperm, un_mask = _join_plan_fn(ctx.mesh, jt)(
                lkb, lkv, lemit, rkb, rkv, remit)
            # replicated counts-gather: a counted collective dispatch
            # (see the stream-plan branch above)
            _counter("cylon_collective_launches_total").inc()
            aemit = remit if jt == _join.JoinType.RIGHT else lemit
            # counts2 is the replicated [world, 2] matrix of per-shard
            # [n_primary, n_unmatched_b]; capacity = worst shard (all
            # shards share one program)
            counts = np.asarray(jax.device_get(counts2)).reshape(world, 2)
            _host_sync("join.plan")
            _annotate(rows_out=int(counts[:, 0].sum()))
        # bucket_cap, not util.capacity: these caps are cache-key
        # parameters of _join_mat_fn — 1 bucket per octave bounds the
        # recompile count under varied cardinalities (specialization
        # analysis); padding rows are masked by emit, results identical
        cap_p = _bucket_cap(int(counts[:, 0].max()))
        cap_u = _bucket_cap(int(counts[:, 1].max())) \
            if jt == _join.JoinType.FULL_OUTER else 0

        with _span("distributed_join.materialize", seq, world=world,
                   capacity=cap_p + cap_u):
            lod, lov, rod, rov, emit, lidx_o, ridx_o = _join_mat_fn(
                ctx.mesh, jt, cap_p, cap_u)(
                lo, m, bperm, un_mask, aemit, ldat, lval, rdat, rval)

    nl = left_d.column_count
    cols = _rebuild_columns(lod, lov, lcols_s,
                            [f"lt-{i}" for i in range(nl)])
    cols += _rebuild_columns(rod, rov, rcols_s,
                             [f"rt-{nl + j}" for j in range(right_d.column_count)])
    # varbytes payload columns: per-shard varlen gather by the
    # materialized indices (fixed-width lanes carried only the lengths)
    for i in lvb:
        vb = _varlen_take_sharded(ctx, lcols_s[i].varbytes, lidx_o)
        cols[i] = Column(vb.lengths, lcols_s[i].dtype, cols[i].validity,
                         None, cols[i].name, varbytes=vb)
    for j in rvb:
        vb = _varlen_take_sharded(ctx, rcols_s[j].varbytes, ridx_o)
        cols[nl + j] = Column(vb.lengths, rcols_s[j].dtype,
                              cols[nl + j].validity, None,
                              cols[nl + j].name, varbytes=vb)
    result = Table(cols, ctx, emit)
    if exact_pairs:
        result, collided = _exact_post_verify(result, nl, exact_pairs,
                                              config)
        if collided:
            # rare path (an actual 96-bit collision): skip the frees —
            # the encoded tables share payload columns with the inputs
            return _exact_dict_redo(left, right, config, exact_pairs,
                                    force_exchange)
    # co-partitioning witness on the OUTPUT: every emitted row sits on
    # the shard its join-key hash routed it to, so a later shuffle /
    # pre-partitioned groupby on the same keys can skip its exchange
    # (the plan optimizer's shuffle-elision hook). Key positions map
    # straight through (left columns first); dtypes come from the
    # ALIGNED columns — if alignment promoted, the signature's dtype
    # string won't match the output column's and the witness correctly
    # never fires. Outer sides with unmatched null keys invalidate the
    # witness for that side.
    if jt in (_join.JoinType.INNER, _join.JoinType.LEFT):
        result._hash_partitioned = shard.partition_signature(
            lcols2, tuple(lidx), world)
    elif jt == _join.JoinType.RIGHT:
        result._hash_partitioned = shard.partition_signature(
            rcols2, tuple(nl + j for j in ridx), world)
    left._free_if_unretained()
    right._free_if_unretained()
    return _ledger.track(result, "distributed_join")


def _exact_post_verify(res: Table, nl: int, pairs, config):
    """Post-exchange byte verification for exact=True long varbytes keys
    (round-5, VERDICT r04 #8 — the old path rejected these outright).
    Both key columns sit row-aligned in the join output, so verification
    is one ``VarBytes.equals_rows`` per key pair: INNER joins filter the
    false matches out of the row mask; outer joins report any collision
    so the caller can redo on exact dictionary codes. Reference bar:
    arrow_hash_kernels.hpp:110-185 verifies true keys inline."""
    emit = res.row_mask
    if emit is None:
        emit = jnp.ones(res.capacity, bool)
    bad = jnp.zeros(res.capacity, bool)
    for li, rj in pairs:
        a, b = res._columns[li], res._columns[nl + rj]
        if not (a.is_varbytes and b.is_varbytes):
            continue
        both = a.valid_mask() & b.valid_mask()
        bad = bad | (emit & both & ~a.varbytes.equals_rows(b.varbytes))
    if config.type == _join.JoinType.INNER:
        return Table(res._columns, res._ctx, emit & ~bad), False
    collided = bool(jax.device_get(bad.any()))
    _host_sync("join.exact_verify")
    return res, collided


def _exact_dict_redo(left: Table, right: Table, config: _join.JoinConfig,
                     pairs, force_exchange: bool) -> Table:
    """Collision recovery for exact outer joins on long varbytes keys:
    re-encode each colliding key pair over ONE shared sorted vocabulary
    (host round trip — paid only when a collision was actually detected,
    i.e. ~never) and redo the distributed join on the exact int32
    codes (same mechanism as the local `_exact_dict_fallback_join`).
    The redo's dictionary-coded key columns are re-materialized as
    varbytes so the recovery path's output schema matches the normal
    path, and the unretained originals are freed once the redo no
    longer shares their buffers (ADVICE r5 low — this path used to
    leak retain=False inputs and leak the storage change)."""
    from ..data.table import _dict_encode_pair

    ctx = left._ctx
    nl = left.column_count
    lcols2, rcols2 = list(left._columns), list(right._columns)
    for li, rj in pairs:
        lcols2[li], rcols2[rj] = _dict_encode_pair(left._columns[li],
                                                   right._columns[rj])
    cfg = _join.JoinConfig(config.type, config.left_column_idx,
                           config.right_column_idx, config.algorithm,
                           exact=False)
    res = distributed_join(Table(lcols2, left._ctx, left.row_mask),
                           Table(rcols2, right._ctx, right.row_mask),
                           cfg, force_exchange=force_exchange)
    # decode the redone key columns back through the shared vocab so the
    # output carries varbytes storage exactly like the collision-free path
    from ..data.column import as_varbytes

    out_cols = list(res._columns)
    for li, rj in pairs:
        for pos in (li, nl + rj):
            c = out_cols[pos]
            if c.dictionary is not None:
                vb_col = _dist_as_varbytes(ctx, c) \
                    if ctx.is_distributed() and ctx.get_world_size() > 1 \
                    else as_varbytes(c)
                out_cols[pos] = vb_col.rename(c.name)
    res = Table(out_cols, res._ctx, res.row_mask)
    # the redo is fully materialized now — nothing shares the originals'
    # buffers except via XLA refcounts, so the deferred frees are safe
    left._free_if_unretained()
    right._free_if_unretained()
    return res


# ---------------------------------------------------------------------------
# streaming / overlapped ring join (reference: ArrowJoin, arrow_join.hpp:
# 50-198 — the streaming alternative to the barrier shuffle: two
# ArrowAllToAlls drained incrementally while local joins run).
#
# TPU-native form: the BUILD side rotates around the mesh ring via
# `lax.ppermute` while every shard joins its RESIDENT probe shard against
# the visiting block — XLA's async collective-permute overlaps the next
# block's transfer with the current block's join. The probe side is never
# repartitioned at all, so total bytes on the ring ≈ size(build), vs
# size(probe+build) through the all-to-all — the win when the build side
# is small or the probe side is large and already resident.
# ---------------------------------------------------------------------------


def _prep_join_side(ctx: CylonContext, t: Table, cols, other_cols):
    """One join side's per-shard kernel operands: key bit arrays +
    combined key validity + emit, plus the payload data/validity lanes
    with every (short) varbytes column's word lanes APPENDED as extra
    fixed-width lanes (the ArrowJoin trick — strings ride the
    fixed-width machinery; ``lane_slots`` maps column -> (first lane
    index, lane count) for the rebuild). Shared by the ring join
    (lanes rotate with the visiting block) and the broadcast join
    (lanes gather with the replicated build side)."""
    bits, kv, _h = _dist_key_bits(ctx, cols, other_cols)
    bits = tuple(shard.pin(b, ctx) for b in bits)
    kv = shard.pin(kv, ctx)
    emit = shard.pin(t.emit_mask(), ctx)
    dat = [shard.pin(c.data, ctx) for c in t._columns]
    val = [shard.pin(c.valid_mask(), ctx) for c in t._columns]
    lane_slots = {}
    for i, c in enumerate(t._columns):
        if c.is_varbytes:
            vb = c.varbytes
            lanes = _word_lanes_fn(ctx.mesh, vb.max_words)(
                shard.pin(vb.words, ctx), shard.pin(vb.starts, ctx),
                shard.pin(vb.lengths, ctx))
            lane_slots[i] = (len(dat), vb.max_words)
            dat.extend(lanes)
            val.extend([shard.pin(c.valid_mask(), ctx)] * vb.max_words)
    return bits, kv, emit, tuple(dat), tuple(val), lane_slots


def _rebuild_join_side(ctx: CylonContext, slabs_d, slabs_v, t: Table,
                       lane_slots, prefix: str):
    """Columns back out of one side's materialized slabs: varbytes
    columns reassemble from their word lanes (unmatched/dead/null slab
    rows carry garbage lanes — their lengths zero via the hit-AND-valid
    mask; never-written slab rows are zero-initialized)."""
    cols = []
    for i, c in enumerate(t._columns):
        d, v = slabs_d[i], slabs_v[i]
        if c.is_varbytes:
            off, k = lane_slots[i]
            lens = jnp.where(v, d, 0)
            vb = _from_lanes_sharded(
                ctx, [slabs_d[off + q] for q in range(k)], lens)
            cols.append(Column(vb.lengths, c.dtype, v, None,
                               f"{prefix}-{i}", varbytes=vb))
        else:
            cols.append(Column(d, c.dtype, v, c.dictionary,
                               f"{prefix}-{i}"))
    return cols


def _varying(axis, tree):
    """Mark a pytree as mesh-varying so fori_loop carries type-match the
    ppermute/per-shard values produced inside the loop body."""
    pc = getattr(jax.lax, "pcast", None)
    if pc is not None:
        return jax.tree.map(lambda x: jax.lax.pcast(x, axis, to="varying"),
                            tree)
    if hasattr(jax.lax, "pvary"):  # pragma: no cover
        return jax.tree.map(lambda x: jax.lax.pvary(x, (axis,)), tree)
    return tree  # old jax: no varying-mesh-axes checker to satisfy


@counted_cache
def _ring_count_fn(mesh, emit_unmatched_a: bool, nkeys: int):
    axis = mesh.axis_names[0]
    world = mesh.devices.size
    spec = P(axis)
    perm = [(i, (i + 1) % world) for i in range(world)]

    def kernel(lbits, lkv, lemit, rbits, rkv, remit):
        def rot(t):
            return jax.tree.map(lambda x: jax.lax.ppermute(x, axis, perm), t)

        def step(k, carry):
            (rb, rkvc, remc), pairs, amatched = carry
            _, _, m, _, _ = _join.join_plan_keys(
                lbits, lkv, lemit, rb, rkvc, remc, _join.JoinType.INNER)
            pairs = pairs.at[k].set(m.sum(dtype=jnp.int32))
            amatched = amatched | (m > 0)
            return rot((rb, rkvc, remc)), pairs, amatched

        pairs0, amatched0 = _varying(axis, (
            jnp.zeros(world, jnp.int32), jnp.zeros(lemit.shape[0], bool)))
        _, pairs, amatched = jax.lax.fori_loop(
            0, world, step, ((rbits, rkv, remit), pairs0, amatched0))
        n_extra = (lemit & ~amatched).sum(dtype=jnp.int32) \
            if emit_unmatched_a else jnp.zeros((), jnp.int32)
        counts = jnp.concatenate([pairs, n_extra[None]])
        return replicated_gather(counts, axis, world)

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * 6,
                             out_specs=P()))


@counted_cache
def _ring_mat_fn(mesh, emit_unmatched_a: bool, cap_step: int, cap_extra: int,
                 nkeys: int):
    axis = mesh.axis_names[0]
    world = mesh.devices.size
    spec = P(axis)
    perm = [(i, (i + 1) % world) for i in range(world)]
    cap_total = world * cap_step + cap_extra

    def kernel(lbits, lkv, lemit, rbits, rkv, remit, adat, aval, bdat, bval):
        def rot(t):
            return jax.tree.map(lambda x: jax.lax.ppermute(x, axis, perm), t)

        def slab_like(x):
            return jnp.zeros((cap_total,) + x.shape[1:], x.dtype)

        slabs_a = tuple(slab_like(d) for d in adat)
        slabs_av = tuple(jnp.zeros(cap_total, bool) for _ in adat)
        slabs_b = tuple(slab_like(d) for d in bdat)
        slabs_bv = tuple(jnp.zeros(cap_total, bool) for _ in bdat)
        emit0 = jnp.zeros(cap_total, bool)
        slabs_a, slabs_av, slabs_b, slabs_bv, emit0 = _varying(
            axis, (slabs_a, slabs_av, slabs_b, slabs_bv, emit0))

        def step(k, carry):
            visit, slabs, amatched = carry
            rb, rkvc, remc, bdat_v, bval_v = visit
            sa, sav, sb, sbv, emit = slabs
            _, lo, m, bperm, _ = _join.join_plan_keys(
                lbits, lkv, lemit, rb, rkvc, remc, _join.JoinType.INNER)
            lidx, ridx, e = _join.join_materialize_gids(
                lo, m, bperm, jnp.zeros(remc.shape[0], bool), lemit,
                _join.JoinType.INNER, cap_step, 0)
            ad, av = _gather_side(adat, aval, lidx)
            bd, bv = _gather_side(bdat_v, bval_v, ridx)
            off = k * cap_step

            def put(slab, block):
                return jax.lax.dynamic_update_slice_in_dim(slab, block,
                                                           off, 0)

            slabs = (tuple(put(s, d) for s, d in zip(sa, ad)),
                     tuple(put(s, v) for s, v in zip(sav, av)),
                     tuple(put(s, d) for s, d in zip(sb, bd)),
                     tuple(put(s, v) for s, v in zip(sbv, bv)),
                     put(emit, e))
            amatched = amatched | (m > 0)
            return rot((rb, rkvc, remc, bdat_v, bval_v)), slabs, amatched

        visit0 = (rbits, rkv, remit, bdat, bval)
        amatched0 = _varying(axis, jnp.zeros(lemit.shape[0], bool))
        _, slabs, amatched = jax.lax.fori_loop(
            0, world, step,
            (visit0, (slabs_a, slabs_av, slabs_b, slabs_bv, emit0),
             amatched0))
        sa, sav, sb, sbv, emit = slabs

        if emit_unmatched_a:
            un = _join._masked_indices(lemit & ~amatched, cap_extra)
            ad, av = _gather_side(adat, aval, un)
            hole = jnp.full(cap_extra, -1, jnp.int32)
            bd, bv = _gather_side(bdat, bval, hole)
            off = world * cap_step

            def put(slab, block):
                return jax.lax.dynamic_update_slice_in_dim(slab, block,
                                                           off, 0)

            sa = tuple(put(s, d) for s, d in zip(sa, ad))
            sav = tuple(put(s, v) for s, v in zip(sav, av))
            sb = tuple(put(s, d) for s, d in zip(sb, bd))
            sbv = tuple(put(s, v) for s, v in zip(sbv, bv))
            emit = put(emit, un >= 0)
        return sa, sav, sb, sbv, emit

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * 10,
                             out_specs=spec))


def distributed_join_ring(left: Table, right: Table,
                          config: _join.JoinConfig) -> Table:
    """Streaming ring join (ArrowJoin analog). INNER/LEFT/RIGHT; the
    resident (probe) side is the left table (right for RIGHT joins) and
    the other side rotates. FULL_OUTER falls back to the shuffle path.

    Memory note: the per-shard output slab is world*cap_step + cap_extra
    rows where cap_step covers the worst (shard, step) block — heavy key
    skew inflates it; the shuffle path degrades more gracefully there.
    """
    from ..data.strings import LANE_WORDS_MAX

    ctx = left._ctx
    world = ctx.get_world_size()
    jt = config.type
    if world == 1 or jt == _join.JoinType.FULL_OUTER or \
            any(c.is_varbytes and c.varbytes.max_words > LANE_WORDS_MAX
                for c in left._columns + right._columns):
        # long varbytes payload can't ride the ring's fixed-width
        # rotation (short rows ride as word lanes below)
        return distributed_join(left, right, config)
    if getattr(config, "exact", False):
        from ..data.strings import EXACT_KEY_WORDS

        for li, rj in zip(config.left_column_idx,
                          config.right_column_idx):
            kw = _pair_k(left._columns[li], right._columns[rj])
            if kw is not None and kw > EXACT_KEY_WORDS:
                # the ring can't byte-verify mid-rotation; the shuffle
                # path post-verifies (round-5) — route there rather
                # than reject (keys <= EXACT_KEY_WORDS*4 bytes are
                # byte-exact on the ring by construction)
                return distributed_join(left, right, config)

    left_d = shard.distribute(left, ctx)
    right_d = shard.distribute(right, ctx)
    lidx, ridx = config.left_column_idx, config.right_column_idx
    lcols, rcols = table_mod.align_key_columns(left_d, right_d, lidx, ridx)

    if jt == _join.JoinType.RIGHT:
        a_t, a_cols, b_t, b_cols = right_d, rcols, left_d, lcols
    else:
        a_t, a_cols, b_t, b_cols = left_d, lcols, right_d, rcols
    emit_un_a = jt != _join.JoinType.INNER

    # varbytes keys become per-shard word lanes (byte-exact) or the
    # content-hash quad; either way the bit arrays rotate like any
    # fixed lane. Short varbytes PAYLOADS ride as appended word lanes
    # (the ArrowJoin analog now streams whole tables incl. strings,
    # reference arrow_join.hpp:50-198).
    abits, akv, aemit, adat, aval, a_lane_slots = _prep_join_side(
        ctx, a_t, a_cols, b_cols)
    bbits, bkv, bemit, bdat, bval, b_lane_slots = _prep_join_side(
        ctx, b_t, b_cols, a_cols)

    seq = ctx.get_next_sequence()
    with _phase("ring_join.count", seq):
        counts = np.asarray(jax.device_get(_ring_count_fn(
            ctx.mesh, emit_un_a, len(abits))(
            abits, akv, aemit, bbits, bkv, bemit)))
        _host_sync("ring.count")
    pairs, extra = counts[:, :world], counts[:, world]
    cap_step = _bucket_cap(int(pairs.max())) if pairs.size else 1
    cap_extra = _bucket_cap(int(extra.max())) if emit_un_a else 0
    # skew guard: the output slab is world*cap_step rows per shard, with
    # cap_step set by the WORST (shard, step) block — a hot key inflates
    # every shard's slab. When the slab overshoots the actual worst
    # per-shard output by more than RING_SKEW_FACTOR (or blows the HBM
    # budget), the shuffle join's blockwise machinery degrades more
    # gracefully — route there.
    worst_total = int(pairs.sum(axis=1).max()) if pairs.size else 0
    slab = world * cap_step
    budget = ctx.memory_pool.comm_budget_bytes()
    row_bytes = sum(
        int(np.dtype(c.data.dtype).itemsize) + 1
        + (5 * c.varbytes.max_words if c.is_varbytes else 0)
        for c in a_t._columns + b_t._columns)
    over_budget = bool(budget) and slab * row_bytes > budget
    # absolute floor: tiny slabs are free regardless of ratio — without
    # it, sparse-output joins (cap_step ~ a few rows) would always
    # misroute off the ring
    skewed = slab > (1 << 16) and \
        slab > RING_SKEW_FACTOR * _capacity(max(worst_total, 1))
    if skewed or over_budget:
        return distributed_join(left, right, config)

    _counter("cylon_join_algorithm_total", {"algo": "ring"}).inc()
    with _phase("ring_join.materialize", seq):
        sa, sav, sb, sbv, emit = _ring_mat_fn(
            ctx.mesh, emit_un_a, cap_step, cap_extra, len(abits))(
            abits, akv, aemit, bbits, bkv, bemit, adat, aval, bdat, bval)

    na = a_t.column_count
    a_cols_out = _rebuild_join_side(ctx, sa, sav, a_t, a_lane_slots, "a")
    b_cols_out = _rebuild_join_side(ctx, sb, sbv, b_t, b_lane_slots, "b")
    if jt == _join.JoinType.RIGHT:
        cols = b_cols_out + a_cols_out
        nl = b_t.column_count
    else:
        cols = a_cols_out + b_cols_out
        nl = na
    cols = [c.rename(f"lt-{i}" if i < nl else f"rt-{i}")
            for i, c in enumerate(cols)]
    result = Table(cols, ctx, emit)
    left._free_if_unretained()
    right._free_if_unretained()
    return _ledger.track(result, "distributed_join_ring")


# ---------------------------------------------------------------------------
# broadcast-hash join (adaptive execution, ROADMAP item 1): when the
# planner has MEASURED one side small (stats warehouse, see
# plan/optimizer.adapt_from_stats), the all-to-all that dominates every
# distributed op per PAPER.md's local/shuffle/local composition is
# elided entirely — the build side is replicated to every shard via the
# counted-gather discipline (`replicated_gather`, the same psum one-hot
# trick `_join_plan_fn` uses for its counts) INSIDE the per-shard join
# program, and every shard probes its RESIDENT rows against the full
# build table with the same local join kernels. Zero payload
# all-to-all, zero probe-side movement: the probe side's
# `_hash_partitioned` witness survives the join unchanged.
# ---------------------------------------------------------------------------


def _gather_full(x, axis, world):
    """Per-shard [n, ...] leaf -> the FULL [world*n, ...] array
    replicated on every shard, rows in global (shard-major) order.
    psum-of-one-hot (replicated_gather) so shard_map's replication
    checker can statically prove the result replicated; bools ride as
    u8 (psum has no bool reduction)."""
    if x.dtype == jnp.bool_:
        g = replicated_gather(x.astype(jnp.uint8), axis, world)
        return g.reshape((-1,) + x.shape[1:]).astype(jnp.bool_)
    g = replicated_gather(x, axis, world)
    return g.reshape((-1,) + x.shape[1:])


@counted_cache
def _bcast_join_plan_fn(mesh, join_type: _join.JoinType):
    """Broadcast-join plan program: all_gather the (small) build
    side's key bits inside the shard_map, then run the SAME fused-sort
    join plan every shuffle join uses — probe rows per shard vs the
    full build table. Counts come back replicated (every controller
    process can fetch them, multi-host safe); the match arrays stay
    sharded for the materialize program."""
    axis = mesh.axis_names[0]
    world = mesh.devices.size
    spec = P(axis)

    def kernel(abits, akv, aemit, bbits, bkv, bemit):
        bb = tuple(_gather_full(x, axis, world) for x in bbits)
        bkv_f = _gather_full(bkv, axis, world)
        bemit_f = _gather_full(bemit, axis, world)
        counts2, lo, m, bperm, un_mask = _join.join_plan_keys(
            abits, akv, aemit, bb, bkv_f, bemit_f, join_type)
        return (replicated_gather(counts2, axis, world),
                lo, m, bperm, un_mask)

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * 6,
                             out_specs=(P(), spec, spec, spec, spec)))


@counted_cache
def _bcast_join_mat_fn(mesh, join_type: _join.JoinType, cap_p: int):
    """Broadcast-join materialize program: re-gather the build side's
    payload lanes (replication is recomputed, never cached — the build
    side is small by the planner's measured evidence), expand the
    match runs at the host-chosen capacity, and gather both sides.
    Probe gathers stay shard-local; build gathers index the replicated
    table."""
    axis = mesh.axis_names[0]
    world = mesh.devices.size
    spec = P(axis)

    def kernel(lo, m, bperm, un_mask, aemit, adat, aval, bdat, bval):
        bdat_f = tuple(_gather_full(x, axis, world) for x in bdat)
        bval_f = tuple(_gather_full(x, axis, world) for x in bval)
        # join_type is INNER or LEFT here (probe is always the a side),
        # so (lidx, ridx) == (aidx, bidx)
        aidx, bidx, emit = _join.join_materialize_gids(
            lo, m, bperm, un_mask, aemit, join_type, cap_p, 0)
        aod, aov = _gather_side(adat, aval, aidx)
        bod, bov = _gather_side(bdat_f, bval_f, bidx)
        return aod, aov, bod, bov, emit

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * 9,
                             out_specs=spec))


# sides a broadcast join may legally replicate, per join type: the
# probe must cover every row the join can emit unmatched. THREE
# deliberately-independent copies of this invariant exist — here (the
# runtime gate), plan/optimizer._BROADCAST_SIDES (the rewrite's choice,
# in preference order) and plan/verify._BROADCAST_SIDES (the
# optimizer-independent soundness check) — because the layering
# contracts forbid sharing (parallel never imports plan/, and the
# verifier must not share code with the optimizer). Their agreement is
# PINNED by tests/test_adaptive_join.py::test_broadcast_side_tables_agree;
# change one, change all three.
_BCAST_LEGAL_SIDES = {_join.JoinType.INNER: (0, 1),
                      _join.JoinType.LEFT: (1,),
                      _join.JoinType.RIGHT: (0,)}


def _broadcast_eligible(left: Table, right: Table,
                        config: _join.JoinConfig,
                        build_side: int) -> Optional[str]:
    """None when the broadcast path can run this join; otherwise the
    reason it must fall back to the shuffle composition."""
    from ..data.strings import EXACT_KEY_WORDS, LANE_WORDS_MAX

    jt = config.type
    legal = _BCAST_LEGAL_SIDES.get(jt, ())
    if build_side not in legal:
        return f"build_side={build_side} not replicable under {jt.name}"
    if any(c.is_varbytes and c.varbytes.max_words > LANE_WORDS_MAX
           for c in left._columns + right._columns):
        return "long varbytes payload cannot ride fixed word lanes"
    if getattr(config, "exact", False):
        for li, rj in zip(config.left_column_idx,
                          config.right_column_idx):
            kw = _pair_k(left._columns[li], right._columns[rj])
            if kw is not None and kw > EXACT_KEY_WORDS:
                # the shuffle path byte-verifies exact long keys
                # post-exchange; the broadcast path has no equivalent
                return "exact long varbytes keys need post-verification"
    return None


def broadcast_hash_join(left: Table, right: Table,
                        config: _join.JoinConfig,
                        build_side: int = 1) -> Table:
    """Replicate ``build_side`` (0=left, 1=right) to every shard and
    probe locally — the zero-all-to-all join for a measured-small
    build side. INNER may replicate either side; LEFT only its right
    input, RIGHT only its left (the probe must cover every row the
    join can emit unmatched). Ineligible shapes fall back to
    `distributed_join` (correct, just exchanged), annotating the open
    span with ``broadcast_fallback``. The output carries the PROBE
    side's placement witness unchanged: probe rows (and their
    duplicate expansions) never leave their shard."""
    ctx = left._ctx
    world = ctx.get_world_size()
    if world == 1:
        # a 1-wide mesh replicates nothing: the local join IS the
        # broadcast join (reference parity with distributed_join)
        _counter("cylon_join_algorithm_total", {"algo": "local"}).inc()
        return _ledger.track(table_mod.join(left, right, config),
                             "distributed_join")
    reason = _broadcast_eligible(left, right, config, build_side)
    if reason is not None:
        _annotate(join_algorithm="shuffle", broadcast_fallback=reason)
        return distributed_join(left, right, config)

    left_d = shard.distribute(left, ctx)
    right_d = shard.distribute(right, ctx)
    lidx, ridx = config.left_column_idx, config.right_column_idx
    lcols, rcols = _align_key_columns_dist(ctx, left_d, right_d, lidx,
                                           ridx)
    if build_side == 1:
        a_t, a_cols, b_t, b_cols = left_d, lcols, right_d, rcols
    else:
        a_t, a_cols, b_t, b_cols = right_d, rcols, left_d, lcols
    # the probe is always the a side, so LEFT/RIGHT both lower to the
    # local LEFT plan (emit unmatched probe rows)
    jt_local = _join.JoinType.INNER \
        if config.type == _join.JoinType.INNER else _join.JoinType.LEFT

    abits, akv, aemit, adat, aval, a_lane_slots = _prep_join_side(
        ctx, a_t, a_cols, b_cols)
    bbits, bkv, bemit, bdat, bval, b_lane_slots = _prep_join_side(
        ctx, b_t, b_cols, a_cols)

    seq = ctx.get_next_sequence()
    _counter("cylon_join_algorithm_total", {"algo": "broadcast"}).inc()
    with _span("broadcast_join.plan", seq, world=world,
               rows_in=a_t.capacity + b_t.capacity,
               build_rows=b_t.capacity, build_bytes=int(b_t.nbytes)):
        rep_counts, lo, m, bperm, un_mask = _bcast_join_plan_fn(
            ctx.mesh, jt_local)(abits, akv, aemit, bbits, bkv, bemit)
        # the gather program is this join's only collective transport
        _counter("cylon_collective_launches_total").inc()
        cm = np.asarray(jax.device_get(rep_counts)).reshape(world, 2)
        _host_sync("join.plan")
        _annotate(rows_out=int(cm[:, 0].sum()))
    cap_p = _bucket_cap(int(cm[:, 0].max()))

    with _span("broadcast_join.materialize", seq, world=world,
               capacity=cap_p):
        aod, aov, bod, bov, emit = _bcast_join_mat_fn(
            ctx.mesh, jt_local, cap_p)(lo, m, bperm, un_mask, aemit,
                                       adat, aval, bdat, bval)
        _counter("cylon_collective_launches_total").inc()

    a_cols_out = _rebuild_join_side(ctx, aod, aov, a_t, a_lane_slots,
                                    "a")
    b_cols_out = _rebuild_join_side(ctx, bod, bov, b_t, b_lane_slots,
                                    "b")
    if build_side == 1:
        cols = a_cols_out + b_cols_out
        nl = a_t.column_count
    else:
        cols = b_cols_out + a_cols_out
        nl = b_t.column_count
    cols = [c.rename(f"lt-{i}" if i < nl else f"rt-{i}")
            for i, c in enumerate(cols)]
    result = Table(cols, ctx, emit)
    # probe rows never moved (and duplicate expansions stay on their
    # source shard), so the probe table's placement witness survives —
    # position-mapped when the probe is the right side
    probe_t = left_d if build_side == 1 else right_d
    sig = probe_t._hash_partitioned
    if sig is not None:
        pos, dts, w = sig
        if build_side == 0:
            pos = tuple(nl + int(p) for p in pos)
        result._hash_partitioned = (tuple(int(p) for p in pos),
                                    tuple(dts), int(w))
    left._free_if_unretained()
    right._free_if_unretained()
    return _ledger.track(result, "distributed_join")


# ---------------------------------------------------------------------------
# distributed set ops (reference: DistributedUnion/Subtract/Intersect,
# table.cpp:948-1010 — ShuffleTwoTables on ALL columns + local set op)
# ---------------------------------------------------------------------------

def distributed_set_op(left: Table, right: Table, op: _setops.SetOp,
                       force_exchange: bool = False) -> Table:
    """``force_exchange``: run the full shuffle+set-op composition even
    on a 1-wide mesh (bench contract, same as distributed_join)."""
    ctx = left._ctx
    world = ctx.get_world_size()
    if world == 1 and not (force_exchange and ctx.is_distributed()):
        return _ledger.track(table_mod.set_op(left, right, op),
                             "distributed_set_op")
    if left.column_count != right.column_count:
        raise CylonPlanError("set ops need equal schemas")

    left_d = shard.distribute(left, ctx)
    right_d = shard.distribute(right, ctx)
    all_idx = list(range(left_d.column_count))
    lcols, rcols = _align_key_columns_dist(ctx, left_d, right_d,
                                           all_idx, all_idx)

    has_validity = [a.validity is not None or b.validity is not None
                    for a, b in zip(lcols, rcols)]

    seq = ctx.get_next_sequence()
    shuffled = []
    with _span("distributed_set_op.shuffle", seq, world=world,
               rows_in=left_d.capacity + right_d.capacity,
               op=str(op)):
        # exchange ONLY the aligned columns; key bits (word lanes /
        # hash quads / ordered bits) and validity key lanes are
        # recomputed per shard from the shuffled columns — the exchange
        # stops double-shipping the lanes (round-4 review finding).
        # Both counts fuse into one program + one host sync.
        sides = []
        for cols, t, other in ((lcols, left_d, rcols),
                               (rcols, right_d, lcols)):
            view = Table(list(cols), ctx, t.row_mask)
            targets = shard.pin(
                _partition_targets_dist(ctx, cols, other), ctx)
            emit = shard.pin(t.emit_mask(), ctx)
            sides.append((view, targets, emit))
        # 1-wide mesh + dense emits: count-free fused route (round-5)
        dense = (world == 1 and left_d.row_mask is None
                 and right_d.row_mask is None)
        cl = cr = None
        if not dense:
            cl, cr = count_pair(sides[0][1], sides[0][2],
                                sides[1][1], sides[1][2], ctx)
        for (view, targets, emit), cnt in zip(sides, (cl, cr)):
            out_cols, emit_s, _x = _exchange_table(view, targets, emit,
                                                   ctx, counts=cnt,
                                                   dense=dense)
            shuffled.append((emit_s, out_cols))

    (lemit, lcols_s), (remit, rcols_s) = shuffled
    lcols_s2, rcols_s2 = _align_key_columns_dist(
        ctx, Table(list(lcols_s), ctx, lemit),
        Table(list(rcols_s), ctx, remit), all_idx, all_idx)

    def rebits(cols, other, emit):
        bits = []
        for ci, c in enumerate(cols):
            b, _h1 = _dist_col_keys(ctx, c, _pair_k(c, other[ci]))
            bits.extend(b)
            if has_validity[ci]:
                # validity participates in the row key (nulls compare
                # equal, matching the reference's set-distinct semantics)
                bits.append(c.valid_mask().astype(jnp.uint8))
        return tuple(shard.pin(b, ctx) for b in bits)

    lkb = rebits(lcols_s2, rcols_s2, lemit)
    rkb = rebits(rcols_s2, lcols_s2, remit)
    ldat = tuple(shard.pin(c.data, ctx) for c in lcols_s)
    lval = tuple(shard.pin(c.valid_mask(), ctx) for c in lcols_s)
    rdat = tuple(shard.pin(c.data, ctx) for c in rcols_s)
    rval = tuple(shard.pin(c.valid_mask(), ctx) for c in rcols_s)

    with _phase("distributed_set_op.count", seq):
        counts = np.asarray(jax.device_get(_setop_count_fn(ctx.mesh)(
            lkb, lemit, rkb, remit))).reshape(world, 3)
        _host_sync("setop.count")
    total = counts[:, int(op)]
    cap = _bucket_cap(int(total.max()))

    with _phase("distributed_set_op.materialize", seq):
        od, ov, emit, idx = _setop_mat_fn(ctx.mesh, op, cap)(
            lkb, lemit, rkb, remit, ldat, lval, rdat, rval)

    from ..data.strings import VarBytes

    cols = []
    for ci, (d, v, a) in enumerate(zip(od, ov, lcols_s)):
        if a.is_varbytes:
            bvb = rcols_s[ci].varbytes
            wcounts = np.asarray(jax.device_get(
                _varlen_take_concat_count_fn(ctx.mesh)(
                    shard.pin(a.varbytes.lengths, ctx),
                    shard.pin(bvb.lengths, ctx), idx)))
            _host_sync("varlen.count")
            cap_w = _bucket_cap(int(wcounts.max()))
            w, s, ln = _varlen_take_concat_fn(ctx.mesh, cap_w)(
                shard.pin(a.varbytes.words, ctx),
                shard.pin(a.varbytes.starts, ctx),
                shard.pin(a.varbytes.lengths, ctx),
                shard.pin(bvb.words, ctx), shard.pin(bvb.starts, ctx),
                shard.pin(bvb.lengths, ctx), idx)
            vb = VarBytes(w, s, ln,
                          max(a.varbytes.max_words, bvb.max_words),
                          int(w.shape[0]),
                          shard_geom=(int(idx.shape[0]) // world, cap_w))
            cols.append(Column(vb.lengths, a.dtype, v, None, a.name,
                               varbytes=vb))
        else:
            cols.append(Column(d, a.dtype, v, a.dictionary, a.name))
    return _ledger.track(Table(cols, ctx, emit), "distributed_set_op")


# ---------------------------------------------------------------------------
# distributed groupby (reference: GroupBy, groupby/groupby.cpp:96-139 —
# local partial aggregation BEFORE the shuffle so exchanged bytes scale
# with groups, not rows; unlike the reference, partials merge with the
# CORRECT second-phase op — COUNT partials SUM, MEAN carries (sum, count)
# pairs — fixing the reference's COUNT-of-partials bug, SURVEY §3.2.)
# ---------------------------------------------------------------------------


def _groupby_shuffle_agg(ctx: CylonContext, key_columns, value_columns,
                         ops: Tuple, emit, seq, col_ids: Tuple = None,
                         dense: bool = False, skip_exchange: bool = False):
    """Shuffle rows by key hash, then aggregate per shard. Returns
    (key_out_cols, agg list of (arr, valid), gvalid). ``col_ids``: static
    source-column names for the aggregate's sub-reduction dedup (repeated
    (column, op) pairs compute once — see sorted_segment_aggregate).
    ``skip_exchange``: caller asserts every key's rows are already
    co-located on one shard (a co-partitioning witness from a prior
    shuffle/join on the same keys) — the per-shard aggregation is then
    globally exact with NO exchange at all (the plan optimizer's elided
    groupby-after-join path)."""
    if skip_exchange:
        out_cols = list(key_columns) + list(value_columns)
        emit_s = emit
        _annotate(exchange_skipped=True)
    else:
        with _span("distributed_groupby.shuffle", seq,
                   world=ctx.get_world_size(),
                   rows_in=int(emit.shape[0])):
            view = Table(list(key_columns) + list(value_columns), ctx,
                         None)
            targets = shard.pin(
                _partition_targets_dist(ctx, key_columns), ctx)
            out_cols, emit_s, _x = _exchange_table(view, targets, emit,
                                                   ctx, dense=dense)

    nk = len(key_columns)
    kcols_s = out_cols[:nk]
    vcols_s = out_cols[nk:]
    # key bits recompute per shard from the shuffled key columns —
    # recomputable lanes never cross the exchange (round-4 review)
    kbits = []
    for c in kcols_s:
        b, _h1 = _dist_col_keys(ctx, c)
        kbits.extend(b)
    kbits = tuple(shard.pin(b, ctx) for b in kbits)
    kdat = tuple(shard.pin(c.data, ctx) for c in kcols_s)
    kval = tuple(shard.pin(c.valid_mask(), ctx) for c in kcols_s)
    vdat = tuple(shard.pin(c.data, ctx) for c in vcols_s)
    vval = tuple(None if c.validity is None
                 else shard.pin(c.valid_mask(), ctx) for c in vcols_s)

    with _phase("distributed_groupby.aggregate", seq):
        if col_ids is None:
            col_ids = tuple(range(len(vcols_s)))
        all_valid = tuple(c.validity is None for c in vcols_s)
        kout, kvout, gvalid, agg, safe = _groupby_fn(
            ctx.mesh, ops, col_ids, all_valid)(
            kbits, kdat, kval, emit_s, vdat, vval)

    key_out = []
    for d, v, kc in zip(kout, kvout, kcols_s):
        if kc.is_varbytes:
            vb = _varlen_take_sharded(ctx, kc.varbytes, safe)
            key_out.append(Column(vb.lengths, kc.dtype, v, None, kc.name,
                                  varbytes=vb))
        else:
            key_out.append(Column(d, kc.dtype, v, kc.dictionary, kc.name))
    return key_out, list(agg), gvalid


def distributed_groupby(table: Table, index_col, aggregate_cols: List,
                        aggregate_ops: List[_groupby.AggregationOp],
                        pre_aggregate: bool = True,
                        pre_partitioned: bool = False) -> Table:
    """``pre_partitioned``: caller asserts the table's rows are already
    hash-placed by the groupby keys (e.g. the output of a join/shuffle
    on the same keys, witnessed by ``_hash_partitioned``) — the whole
    exchange is skipped and ONE per-shard aggregation pass produces the
    exact global result. The plan executor verifies the witness before
    setting this; a false assertion would split groups across shards."""
    ctx = table._ctx
    world = ctx.get_world_size()
    if world == 1:
        return _ledger.track(
            table_mod.groupby_local(table, index_col, aggregate_cols,
                                    aggregate_ops),
            "distributed_groupby")

    t = shard.distribute(table, ctx)
    idx_cols = index_col if isinstance(index_col, (list, tuple)) else [index_col]
    idx_cols = [t._col_index(c) for c in idx_cols]
    val_cols = [t._col_index(c) for c in aggregate_cols]
    key_columns = [t._columns[i] for i in idx_cols]
    for vi, op in zip(val_cols, aggregate_ops):
        if t._columns[vi].is_varbytes and \
                op != _groupby.AggregationOp.COUNT:
            raise CylonPlanError(
                "varbytes value columns support COUNT only",
                code=Code.NotImplemented)

    seq = ctx.get_next_sequence()
    ops = list(aggregate_ops)
    emit = shard.pin(t.emit_mask(), ctx)
    MEAN = _groupby.AggregationOp.MEAN
    SUM = _groupby.AggregationOp.SUM
    COUNT = _groupby.AggregationOp.COUNT

    if pre_partitioned or not pre_aggregate:
        value_columns = [t._columns[vi] for vi in val_cols]
        key_out, agg, gvalid = _groupby_shuffle_agg(
            ctx, key_columns, value_columns, tuple(ops), emit, seq,
            col_ids=tuple(val_cols), dense=t.row_mask is None,
            skip_exchange=pre_partitioned)
        cols = list(key_out)
        for (arr, av), vi, op in zip(agg, val_cols, ops):
            src = t._columns[vi]
            keep_dict = (op in (_groupby.AggregationOp.MIN,
                                _groupby.AggregationOp.MAX)
                         and src.is_string)
            cols.append(Column(arr, table_mod._agg_dtype(src, op), av,
                               src.dictionary if keep_dict else None,
                               src.name))
        out = Table(cols, ctx, gvalid)
        # output keys stay hash-placed (rows never moved / moved by key
        # hash): witness lets a further same-key stage skip its shuffle
        out._hash_partitioned = shard.partition_signature(
            key_out, tuple(range(len(key_out))), world)
        return _ledger.track(out, "distributed_groupby")

    # ---- phase A: per-shard partial aggregation (shuffle bytes then
    # scale with per-shard GROUPS, not rows). MEAN expands to
    # (f64 SUM, COUNT) partial pairs; phase B merges with the correct
    # second-phase op (COUNT partials are SUMmed).
    a_entries = []   # (orig_pos, opA, cast_f64)
    b_ops = []
    out_map = []     # per original op: ("d", a_idx) | ("mean", si, ci)
    for j, op in enumerate(ops):
        if op == MEAN:
            out_map.append(("mean", len(a_entries), len(a_entries) + 1))
            a_entries += [(j, SUM, True), (j, COUNT, False)]
            b_ops += [SUM, SUM]
        else:
            out_map.append(("d", len(a_entries)))
            a_entries.append((j, op, False))
            b_ops.append(_groupby.second_phase_op(op))

    with _phase("distributed_groupby.pre_aggregate", seq):
        kbitsA = []
        for c in key_columns:
            b, _h1 = _dist_col_keys(ctx, c)
            kbitsA.extend(b)
        kbitsA = tuple(shard.pin(b, ctx) for b in kbitsA)
        kdatA = tuple(shard.pin(c.data, ctx) for c in key_columns)
        kvalA = tuple(shard.pin(c.valid_mask(), ctx) for c in key_columns)
        vdatA, vvalA = [], []
        for j, _opA, cast in a_entries:
            src = t._columns[val_cols[j]]
            d = src.data.astype(jnp.float64) if cast else src.data
            vdatA.append(shard.pin(d, ctx))
            vvalA.append(None if src.validity is None
                         else shard.pin(src.valid_mask(), ctx))
        opsA = tuple(opA for _j, opA, _c in a_entries)
        cidsA = tuple((val_cols[j], cast) for j, _opA, cast in a_entries)
        avA = tuple(t._columns[val_cols[j]].validity is None
                    for j, _opA, _c in a_entries)
        koutA, kvoutA, gvalidA, aggA, safeA = _groupby_fn(
            ctx.mesh, opsA, cidsA, avA)(kbitsA, kdatA, kvalA, emit,
                                        tuple(vdatA), tuple(vvalA))

    pkey_cols = []
    for d, v, kc in zip(koutA, kvoutA, key_columns):
        if kc.is_varbytes:
            vb = _varlen_take_sharded(ctx, kc.varbytes, safeA)
            pkey_cols.append(Column(vb.lengths, kc.dtype, v, None, kc.name,
                                    varbytes=vb))
        else:
            pkey_cols.append(Column(d, kc.dtype, v, kc.dictionary, kc.name))
    pval_cols = []
    for (arr, av), (j, opA, cast) in zip(aggA, a_entries):
        src = t._columns[val_cols[j]]
        dt = dtypes.Double() if cast else table_mod._agg_dtype(src, opA)
        keep_dict = (opA in (_groupby.AggregationOp.MIN,
                             _groupby.AggregationOp.MAX)
                     and src.is_string)
        pval_cols.append(Column(arr, dt, av,
                                src.dictionary if keep_dict else None,
                                src.name))

    # ---- phase B: shuffle the partials, merge with second-phase ops
    key_out, aggB, gvalid = _groupby_shuffle_agg(
        ctx, pkey_cols, pval_cols, tuple(b_ops), gvalidA, seq)

    cols = list(key_out)
    for op, vi, m in zip(ops, val_cols, out_map):
        src = t._columns[vi]
        if m[0] == "mean":
            s_arr, s_av = aggB[m[1]]
            c_arr, c_av = aggB[m[2]]
            data = s_arr / jnp.maximum(c_arr.astype(jnp.float64), 1)
            av = s_av & c_av & (c_arr > 0)
            cols.append(Column(data, table_mod._agg_dtype(src, op), av,
                               None, src.name))
        else:
            arr, av = aggB[m[1]]
            keep_dict = (op in (_groupby.AggregationOp.MIN,
                                _groupby.AggregationOp.MAX)
                         and src.is_string)
            cols.append(Column(arr, table_mod._agg_dtype(src, op), av,
                               src.dictionary if keep_dict else None,
                               src.name))
    out = Table(cols, ctx, gvalid)
    # phase B placed every group on its key-hash shard: witness the
    # partitioning so later same-key stages can elide their shuffles
    out._hash_partitioned = shard.partition_signature(
        key_out, tuple(range(len(key_out))), world)
    return _ledger.track(out, "distributed_groupby")


# ---------------------------------------------------------------------------
# distributed sort. The reference has local Sort only (table.hpp:365);
# this extension is splitter-based: sample keys → agree global range
# splitters → range-partition through the SAME exchange the joins use →
# fused per-shard sort. Nothing ever all-gathers; shard i's rows all
# precede shard i+1's, so global order = (shard, position). Multi-key
# and varbytes ORDER columns use the XLA global-sort fallback /
# local-sort path.
# ---------------------------------------------------------------------------

# per-shard sample count for splitter estimation (total = world * this)
SORT_SAMPLES_PER_SHARD = 4096

# ring join routes to the shuffle join when its output slab overshoots
# the worst per-shard output by this factor (hot-key skew)
RING_SKEW_FACTOR = 4


@counted_cache
def _shard_sort_fn(mesh, nd: int, nv: int, nk: int = 1):
    """Per-shard fused sort by (dead-last, key lanes…): every payload
    column rides as a sort operand; returns sorted dat/val/emit plus the
    permutation (for varbytes content takes). ``nk``: number of key
    lanes (multi-key / varbytes-prefix sorts pass several)."""
    spec = P(mesh.axis_names[0])

    def kernel(bits, emit, dat, val):
        n = bits[0].shape[0]
        dead = (~emit).astype(jnp.uint8)
        iota = jnp.arange(n, dtype=jnp.int32)
        ops = (dead,) + tuple(bits) + tuple(dat) + tuple(val) + (emit, iota)
        res = jax.lax.sort(ops, num_keys=1 + nk, is_stable=True)
        o = 1 + nk
        return (res[o:o + nd], res[o + nd:o + nd + nv], res[-2], res[-1])

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * 4,
                             out_specs=spec))


def _range_splitters(ctx: CylonContext, lanes, emit):
    """Host-side splitter agreement over COMPOSITE keys: gather a small
    random sample of every key lane, keep live rows, take world-1
    lexicographic quantiles. Deterministic seed keeps every controller
    process agreeing (multi-host: same computation on the replicated
    sample). Returns a list of world-1 key TUPLES."""
    world = ctx.get_world_size()
    n = int(lanes[0].shape[0])
    rng = np.random.default_rng(0xC11)
    k = min(n, SORT_SAMPLES_PER_SHARD * world)
    pos = jnp.asarray(np.sort(rng.integers(0, n, k)).astype(np.int32))
    # ONE device_get for all lanes + emit (round-5: was len(lanes)+1
    # sequential fetches at ~100 ms/round-trip through the axon tunnel —
    # ~0.4 s of fixed cost on a 2-key sort). Samples pack into a single
    # matrix of the widest unsigned lane type; unsigned casts round-trip
    # each lane's values exactly. uint64 packing only arises under x64
    # (TPU mode keeps lanes <=32-bit, so the cast never narrows).
    wide = jnp.uint64 if max(l.dtype.itemsize for l in lanes) == 8 \
        else jnp.uint32
    packed = jnp.stack(
        [jnp.take(l, pos).astype(wide) for l in lanes]
        + [jnp.take(emit, pos).astype(wide)])
    host = np.asarray(jax.device_get(packed))
    _host_sync("sort.splitters")
    live = host[-1].astype(bool)
    samples = [host[i].astype(l.dtype)[live]
               for i, l in enumerate(lanes)]
    if samples[0].size == 0:
        return [tuple(s.dtype.type(0) for s in samples)] * (world - 1)
    order = np.lexsort(tuple(reversed(samples)))
    q = (np.arange(1, world) * samples[0].size) // world
    return [tuple(s[order[qi]] for s in samples) for qi in q]


def _splitter_targets(lanes, splitters):
    """target = #splitter-tuples lexicographically <= the row's key
    tuple: (world-1) * n_lanes vector compares, no searchsorted."""
    targets = jnp.zeros(lanes[0].shape[0], jnp.int32)
    for tup in splitters:
        ge = jnp.zeros(lanes[0].shape[0], bool)
        eq = jnp.ones(lanes[0].shape[0], bool)
        for lane, sv in zip(lanes, tup):
            v = jnp.asarray(sv)
            ge = ge | (eq & (lane > v))
            eq = eq & (lane == v)
        targets = targets + (ge | eq).astype(jnp.int32)
    return targets


def _dist_order_lanes(ctx: CylonContext, c: Column, a: bool):
    """Bit lanes whose lexicographic tuple order equals column c's sort
    order (ascending=a, nulls last) — the distributed analog of
    table._sort_keys_mixed. Varbytes columns use per-shard big-endian
    prefix word lanes + length (exact up to SORT_PREFIX_WORDS*4 bytes;
    beyond that returns None → host path). Reference: sort kernels incl.
    strings, arrow_kernels.cpp:136-317."""
    if c.is_varbytes:
        from ..data.strings import SORT_PREFIX_WORDS, _bswap32

        vb = c.varbytes
        if not vb.sortable_on_device:
            return None
        k_lim = min(vb.max_words, SORT_PREFIX_WORDS)
        lanes = [_bswap32(l) for l in _dist_word_lanes(ctx, c, k_lim)]
        lanes.append(vb.lengths.astype(jnp.uint32))
        if not a:
            lanes = [l ^ jnp.uint32(0xFFFFFFFF) for l in lanes]
        if c.validity is not None:
            ext = jnp.uint32(0xFFFFFFFF)
            lanes = [jnp.where(c.validity, l, ext) for l in lanes]
        return lanes
    return list(_order.sort_keys([c], [a]))


def distributed_sort(table: Table, order_by, ascending=True,
                     force_exchange: bool = False) -> Table:
    """Splitter-based distributed sort over ANY key combination: sample
    composite key-lane tuples, agree range splitters, range-partition
    through the same exchange the joins use, per-shard fused sort. No
    global gather for multi-key or (short) varbytes ORDER columns; rows
    beyond the device prefix bound (> SORT_PREFIX_WORDS*4-byte strings)
    take the host path. Reference: Sort + sort kernels incl. strings
    (table.hpp:365, arrow_kernels.cpp:136-317).

    ``force_exchange``: run the full sample+partition+exchange+sort
    composition even on a 1-wide mesh (bench.py times the honest
    distributed path on one chip — same contract as distributed_join)."""
    ctx = table._ctx
    t = shard.distribute(table, ctx) if ctx.is_distributed() else table
    by = order_by if isinstance(order_by, (list, tuple)) else [order_by]
    idxs = [t._col_index(c) for c in by]
    asc = list(ascending) if isinstance(ascending, (list, tuple)) \
        else [ascending] * len(idxs)
    world = ctx.get_world_size()
    order_cols = [t._columns[i] for i in idxs]

    if not (ctx.is_distributed() and (world > 1 or force_exchange)):
        return t.sort(by, ascending)

    per_col = [_dist_order_lanes(ctx, c, a)
               for c, a in zip(order_cols, asc)]
    if any(l is None for l in per_col):
        # >SORT_PREFIX_WORDS varbytes keys: host sort of the SORT
        # columns only, then redistribute (the reference's string sort
        # is a host-memory Arrow kernel too, arrow_kernels.cpp:136-230)
        return shard.distribute(t.compact().sort(by, ascending), ctx)
    lanes = [l for col_lanes in per_col for l in col_lanes]

    seq = ctx.get_next_sequence()
    with _span("distributed_sort.partition", seq, world=world,
               rows_in=t.capacity):
        lanes = [shard.pin(l, ctx) for l in lanes]
        emit = shard.pin(t.emit_mask(), ctx)
        # splitter memoization (the count-cache pattern, weakref-keyed
        # on the SOURCE column buffers): repeat sorts of the same table
        # skip the ~100 ms sample fetch — the lanes themselves are fresh
        # derived arrays every call, so the key is the source data
        from .shuffle import _count_cached

        # memo key/refs span data + validity + varbytes buffers (ADVICE
        # r5 low — data ids alone could alias columns differing only in
        # validity or string content), same discipline as the join memos
        src_ids, src_refs = table_mod._memo_refs(order_cols)
        if t.row_mask is not None:
            src_ids = src_ids + (id(t.row_mask),)
            src_refs = src_refs + (t.row_mask,)
        splitters = _count_cached(
            ("splitters", id(ctx.mesh), tuple(asc), world) + src_ids,
            src_refs, lambda: _range_splitters(ctx, lanes, emit))
        targets = _splitter_targets(lanes, splitters)
        cols_s, emit_s, _x = _exchange_table(
            t, shard.pin(targets, ctx), emit, ctx,
            dense=t.row_mask is None)

    with _phase("distributed_sort.local", seq):
        # key lanes recompute per shard from the shuffled columns —
        # recomputable lanes never cross the exchange (same pattern as
        # the join/set-op/groupby shuffles)
        t_s = Table(list(cols_s), ctx, emit_s)
        order_cols_s = [t_s._columns[i] for i in idxs]
        per_col_s = [_dist_order_lanes(ctx, c, a)
                     for c, a in zip(order_cols_s, asc)]
        sbits = tuple(shard.pin(l, ctx)
                      for col_lanes in per_col_s for l in col_lanes)
        dat = tuple(shard.pin(c.data, ctx) for c in cols_s)
        val = tuple(shard.pin(c.valid_mask(), ctx) for c in cols_s)
        sdat, sval, semit, perm = _shard_sort_fn(
            ctx.mesh, len(dat), len(val), len(sbits))(
            sbits, emit_s, dat, val)
    out_cols = []
    for d, v, c in zip(sdat, sval, cols_s):
        if c.is_varbytes:
            vb = _varlen_take_sharded(ctx, c.varbytes, perm)
            out_cols.append(Column(vb.lengths, c.dtype, v, None, c.name,
                                   varbytes=vb))
        else:
            out_cols.append(Column(d, c.dtype, v, c.dictionary, c.name))
    return _ledger.track(Table(out_cols, ctx, semit), "distributed_sort")


