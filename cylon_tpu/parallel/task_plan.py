"""RETIRED — absorbed into the plan subsystem as `cylon_tpu.plan.tasks`
(the task overlay always belonged next to the logical plan it serves;
reference: arrow_task_all_to_all.h:9-57). This shim keeps existing
import sites working."""
from ..plan.tasks import LogicalTaskPlan, task_exchange  # noqa: F401

__all__ = ["LogicalTaskPlan", "task_exchange"]
