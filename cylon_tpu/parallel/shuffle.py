"""The shuffle: hash-partition + blockwise all-to-all on XLA collectives.

This is the TPU-native replacement for the reference's entire four-layer
communication stack (reference: cpp/src/cylon/net/mpi/mpi_channel.cpp:30-247
two-phase header+body MPI protocol with per-peer FSMs; net/ops/
all_to_all.cpp:26-178 queue/FIN machinery; arrow/arrow_all_to_all.cpp:24-264
per-buffer Arrow serialization). None of that machinery is translated:
inside one compiled SPMD program, `jax.lax.all_to_all` over the mesh axis IS
the transport, XLA program order replaces MPI tags/edges, and program
completion replaces the FIN handshake.

The reference's variable-length problem (its 8-int length header preceding
every body message) maps to the static-shape world as a TWO-PHASE exchange:

  phase 1 ("header"): a tiny compiled program computes the per-(src,dst)
     send-count matrix — one [W] vector per shard, gathered to the host;
  phase 2 ("body"):   a BLOCKWISE exchange. The host picks a pow2 block
     size B (capped at MAX_BLOCK) and a round count K with K*B >= the
     largest single (src,dst) transfer; the compiled program bucket-sorts
     rows by target once, then loops K rounds, each round moving one [W,B]
     block per payload leaf through `all_to_all` and compacting received
     rows into a [cap_out] output at running per-source offsets.

The blockwise loop is the TPU analog of the reference's incremental
buffer-at-a-time streaming (arrow_all_to_all.cpp:83-135): peak comm-buffer
memory is bounded by W*MAX_BLOCK rows per leaf regardless of skew, and the
output capacity tracks the worst RECEIVE TOTAL over shards
(pow2(max_t sum_s C[s,t])) instead of W*pow2(max C[s,t]) — up to W× smaller
when one (src,dst) pair is hot. Receivers place each source's rows
contiguously, so shuffle output is COMPACT (emit = leading prefix).

Rows whose emit mask is False (table padding, filtered rows) are dropped in
transit — the shuffle doubles as a compaction step.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

try:  # jax>=0.8
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from ..context import CylonContext
from ..ops import hash as _hash
from ..ops import tpu_kernels as _tpuk
from ..resilience import inject as _inject
from ..resilience import retry as _retry
from ..telemetry import REGISTRY as _REGISTRY
from ..telemetry import counted_cache, counter as _counter, \
    phase as _phase, record_host_sync as _host_sync, span as _span
from ..telemetry import knobs as _knobs
from ..telemetry import skew as _skew
from ..util import pow2 as _pow2, pow2_floor as _pow2_floor

# Upper bound on the per-round block (rows per (src,dst) pair per round).
# Comm/scratch memory per leaf is 2*W*MAX_BLOCK rows; the memory-pool
# budget (comm_budget_bytes, real HBM stats on TPU) shrinks the block to
# fit, so this cap only matters where stats are unavailable. Skew beyond
# the budgeted block degrades into more rounds, not bigger buffers.
# (1<<16 was measured 64 rounds = 5x slower than one round at 4M rows on
# a 1-wide v5e mesh — round count, not block memory, was the binding
# constraint.)
MAX_BLOCK = 1 << 22

# Chunk-count ceiling for the overlapped (chunked) padded exchange: the
# chunk block is floored so one exchange never fans out into more than
# this many pipeline programs — past ~64 the per-dispatch fixed cost
# dwarfs any remaining overlap win (the 1<<16 MAX_BLOCK measurement
# above is the same lesson: round count, not block memory, binds).
MAX_CHUNKS = 64

# cylon_exchange_overlap_ratio buckets: fraction of an exchange's
# programs issued while earlier chunk work was still in flight
# ((programs-1)/programs) — 0.0 is single-shot, ->1.0 is a deep pipeline
OVERLAP_BUCKETS = (0.0, 0.25, 0.5, 0.75, 0.875, 0.9375, 1.0)


def _shard_map_for(part, kernel, mesh, in_specs, out_specs):
    """jitted shard_map builder for the padded exchange programs: the
    sort path keeps the varying-mesh-axes replication check (the exact
    pre-kernel program); the Pallas partition path disables it —
    shard_map has no replication rule for pallas_call, and the kernel
    is purely per-shard (no collectives inside)."""
    if part == "sort":
        return jax.jit(shard_map(kernel, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs))
    try:
        sm = shard_map(kernel, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_rep=False)
    except TypeError:  # pragma: no cover - jax>=0.8 spelling
        sm = shard_map(kernel, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_vma=False)
    return jax.jit(sm)


def replicated_gather(x, axis: str, world: int):
    """Per-shard [..] value → [world, ..] matrix REPLICATED on every shard.

    psum of a one-hot row scatter rather than `all_gather`: shard_map's
    varying-mesh-axes check can statically prove a psum result is
    replicated (out_specs=P() legal), which it cannot for all_gather.
    Replication matters on multi-host meshes — the host fetch of a
    *sharded* count array would not be addressable from other controller
    processes."""
    row = jax.lax.axis_index(axis)
    mat = jnp.zeros((world,) + x.shape, x.dtype).at[row].set(x)
    return jax.lax.psum(mat, axis)


def _payload_nbytes(payload) -> int:
    """Host-computable byte size of a payload pytree (shape × itemsize;
    no device sync) — the ``bytes_moved`` span attribute and the
    ``cylon_shuffle_bytes_total`` counter feed."""
    return sum(int(np.dtype(x.dtype).itemsize) * int(np.prod(x.shape))
               for x in jax.tree.leaves(payload))


def _record_exchange(rows: int, nbytes: int, programs: int = 1) -> None:
    """Metrics for one exchange dispatch: payload bytes through the
    collective, live rows moved, compiled-program launches."""
    _counter("cylon_shuffle_bytes_total").inc(nbytes)
    _counter("cylon_rows_exchanged_total").inc(rows)
    _counter("cylon_collective_launches_total").inc(programs)


def _launch_exchange(fn):
    """One exchange program dispatch under the resilience policy: the
    chaos injector's ``exchange`` choke point fires first (so every
    retry attempt is one arrival — a persistent fault plan keeps
    failing), then the dispatch runs under bounded retry-with-backoff.
    Re-dispatching is safe: the compiled program is a pure function of
    its device inputs, and a faulted kernel-factory build is not
    cached, so retries rebuild it. Runs INSIDE the exchange span, so a
    recovered stage carries the ``retries`` attr EXPLAIN ANALYZE
    renders as ``[RETRY×n]``."""
    def attempt():
        _inject.fire("exchange")
        return fn()

    return _retry.run_retryable("exchange", attempt)


def _payload_row_bytes(payload) -> int:
    """Host-computable bytes per ROW of a payload pytree — the
    per-shard byte-histogram feed (skew.observe_exchange)."""
    return sum(int(np.dtype(x.dtype).itemsize) * int(np.prod(x.shape[1:]))
               for x in jax.tree.leaves(payload))


# beyond this world size, per-target compare-sum passes cost more than
# one scatter-class segment_sum
_COUNT_COMPARE_MAX_W = 64


def _target_counts(t, world):
    """counts[w] = #rows with target w. Compare-sum for small W (W cheap
    vector passes; segment_sum's scatter costs ~15-30 ns/element on TPU
    and was measured at ~0.3 s per 16M-row count phase)."""
    if world <= _COUNT_COMPARE_MAX_W:
        return jnp.stack(
            [(t == w).sum(dtype=jnp.int32) for w in range(world)])
    return jax.ops.segment_sum(jnp.ones(t.shape[0], jnp.int32), t,
                               num_segments=world + 1)[:world]


@counted_cache
def _count_fn(mesh):
    """Send-count matrix counts[s, t] = live rows shard s sends to shard t,
    REPLICATED on every shard (an in-program all_gather) so the host fetch
    is valid on every controller process — a sharded output would not be
    addressable from the other hosts of a multi-host mesh.

    The moral equivalent of the reference's header phase
    (mpi_channel.cpp:211-225 sendHeader)."""
    axis = mesh.axis_names[0]
    world = mesh.devices.size
    spec = P(axis)

    def kernel(targets, emit):
        t = jnp.where(emit, targets.astype(jnp.int32), world)
        return replicated_gather(_target_counts(t, world), axis, world)

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec, spec),
                             out_specs=P()))


def _to_varying_fn(axis):  # cylint: disable=collectives/uncataloged-factory — returns a plain host callable, not a jitted program
    _vary = getattr(jax.lax, "pcast", None)
    if _vary is not None:
        return lambda x: jax.lax.pcast(x, axis, to="varying")
    if hasattr(jax.lax, "pvary"):  # pragma: no cover
        return lambda x: jax.lax.pvary(x, (axis,))
    return lambda x: x  # old jax: no varying-mesh-axes checker to satisfy


def _bucket_sort(payload, targets, emit, world):
    """Stable bucket sort by target: ONE fused device sort carries every
    1-D payload leaf as a sort OPERAND (the reference's per-dtype split
    kernels, arrow_kernels.cpp:24-134, collapse into this sort). Payload
    operands ride the sort at near-memcpy bandwidth; a per-leaf
    take(perm) gather costs ~15-30 ns/element on TPU and was measured
    dominating the whole exchange. Non-1-D leaves (rare) fall back to
    the gather. Returns (sorted leaves, counts_out, start offsets)."""
    n = targets.shape[0]
    t = jnp.where(emit, targets.astype(jnp.int32), world)
    leaves, treedef = jax.tree.flatten(payload)
    ride = [x.ndim == 1 for x in leaves]
    ops = tuple(x for x, r in zip(leaves, ride) if r)
    need_perm = not all(ride)
    # stability is load-bearing: the varbytes word/row exchanges must
    # keep matching within-source order (previously via an iota tiebreak)
    if need_perm:
        iota = jnp.arange(n, dtype=jnp.int32)
        res = jax.lax.sort((t,) + ops + (iota,), num_keys=1,
                           is_stable=True)
        perm = res[-1]
        sorted_ops = list(res[1:-1])
    else:
        res = jax.lax.sort((t,) + ops, num_keys=1, is_stable=True)
        sorted_ops = list(res[1:])
    out_leaves = []
    k = 0
    for x, r in zip(leaves, ride):
        if r:
            out_leaves.append(sorted_ops[k])
            k += 1
        else:
            out_leaves.append(jnp.take(x, perm, axis=0))
    counts_out = _target_counts(t, world)
    start = jnp.cumsum(counts_out) - counts_out
    return jax.tree.unflatten(treedef, out_leaves), counts_out, start


def _send_block(xs, start, o, block, world):
    """[world, block] send stack via ONE contiguous dynamic slice per
    target — rows are target-bucket-sorted, so sends are slices, never
    gathers (XLA gathers cost ~15-30 ns/element; slices are memcpys).
    ``xs`` must be pre-padded by ``block`` so slices stay in range;
    over-read rows belong to other targets and are dropped receive-side."""
    outs = []
    for t in range(world):
        pos = jnp.clip(start[t] + o, 0, xs.shape[0] - block)
        outs.append(jax.lax.dynamic_slice_in_dim(xs, pos, block, axis=0))
    return jnp.stack(outs)


def _padded_body_w1(axis, block, payload, targets, emit):
    """1-wide-mesh padded body: there is exactly one target, so the
    all_to_all is the identity and the bucket sort's only job is
    pushing dead rows to the tail. A device-side cond skips even that
    when every row is live (stable sort by a constant key IS the
    identity) — the common all-live case costs one pad memcpy, the way
    the reference's world-1 MPI path degenerates to memcpy
    (mpi_channel.cpp:30-247 moves bytes at wire speed). Fused count:
    counts_in computes in-program, so the caller never needs the host
    count round trip (~100 ms through the axon tunnel) on a 1-wide
    mesh."""
    leaves, treedef = jax.tree.flatten(payload)
    n = targets.shape[0]

    def pad(x):
        if block <= x.shape[0]:
            return x[:block]
        return jnp.concatenate(
            [x, jnp.zeros((block - x.shape[0],) + x.shape[1:], x.dtype)])

    _to_varying = _to_varying_fn(axis)

    def live_path(ls):
        # constants must be cast varying to type-match the sort branch
        # under shard_map's varying-mesh-axes check
        return (tuple(pad(x) for x in ls),
                _to_varying(jnp.full((1,), n, jnp.int32)))

    def sort_path(ls):
        sorted_ls, counts_out, _start = _bucket_sort(
            list(ls), targets, emit, 1)
        return tuple(pad(x) for x in sorted_ls), counts_out

    outs, counts_in = jax.lax.cond(emit.all(), live_path, sort_path,
                                   tuple(leaves))
    new_emit = jnp.arange(block, dtype=jnp.int32) < counts_in[0]
    return jax.tree.unflatten(treedef, list(outs)), new_emit, counts_in


# ---------------------------------------------------------------------------
# the fused partition kernel (ROADMAP item 2 close-out, SURVEY §7): the
# padded-mode partition — a stable bucket sort by target — is the one
# spot the survey reserves Pallas for. CYLON_PARTITION_KERNEL routes it:
# "auto" picks the two-pass histogram+scatter kernel on TPU (up to
# _PARTITION_MAX_WORLD targets — past that the scatter's per-bucket
# passes cost more than the sort), "sort" forces the XLA stable sort
# everywhere (the exact pre-kernel program — the path string is part of
# every factory cache key), "pallas" forces the kernel (interpreter
# off-TPU; tests pin bit-identity through it). Both paths return the
# identical (sorted_leaves, counts_out, start) triple, so everything
# downstream — chunk pipeline, skew attrs, ledger, admission — is
# partition-path-oblivious.
# ---------------------------------------------------------------------------

# beyond this world size the scatter pass's per-bucket input streaming
# (~world+2 elementwise-priced passes) loses to the one stable sort
_PARTITION_MAX_WORLD = 16


def _partition_eligible(payload) -> bool:
    """Every leaf must split into u32 legs: 1-D/2-D, 1/2/4/8-byte."""
    return all(
        x.ndim in (1, 2) and np.dtype(x.dtype).itemsize in (1, 2, 4, 8)
        for x in jax.tree.leaves(payload))


def _partition_path(mesh, world: int, payload) -> str:
    """Resolve the partition path for one exchange dispatch — "sort",
    "pallas" (compiled kernel) or "interp" (interpreter, tests). The
    result keys the exchange factory caches, so flipping the knob can
    never reuse a program built for the other path."""
    mode = _knobs.get("CYLON_PARTITION_KERNEL")
    if mode not in ("auto", "pallas", "sort"):
        mode = "auto"
    # world+1 buckets (dead rows included) must fit one histogram lane
    # row — past that even a forced knob falls back to the sort
    if mode == "sort" or world < 2 or world + 1 > _tpuk.LANES \
            or not _partition_eligible(payload):
        return "sort"
    on_tpu = mesh.devices.flat[0].platform == "tpu"
    if mode == "pallas":
        return "pallas" if on_tpu else "interp"
    return "pallas" if on_tpu and world <= _PARTITION_MAX_WORLD \
        else "sort"


def partition_path_label(part: str) -> str:
    """The PUBLIC spelling of a partition path: "interp" is the
    interpreter form of the kernel — one label, ``pallas``."""
    return "sort" if part == "sort" else "pallas"


def _record_partition(sp, *parts: str) -> None:
    """Observability for the partition-path decisions of one dispatch
    (one per exchange, two for a fused pair): the
    cylon_partition_path_total counter per side, and ONE
    partition_path span attr EXPLAIN ANALYZE folds per node ("mixed"
    when a pair's sides differ)."""
    paths = [partition_path_label(p) for p in parts]
    sp.set(partition_path=paths[0] if len(set(paths)) == 1 else "mixed")
    for p in paths:
        _counter("cylon_partition_path_total", {"path": p}).inc()


def _leg_split(x):
    """One payload leaf → (u32 (n,) legs, join(legs) -> leaf).

    The partition kernel moves 32-bit lanes; wider dtypes ride as
    word legs (the varbytes trick applied to every column), narrower
    ones widen value-exactly, 2-D leaves split per column. Round trips
    are bit-exact: bitcasts for 4/8-byte, value casts for 1/2-byte
    (lossless by range)."""
    if x.ndim == 2:
        subs = [_leg_split(x[:, j]) for j in range(x.shape[1])]
        legs = [leg for sub_legs, _ in subs for leg in sub_legs]

        def join2d(ls, subs=subs):
            outs, i = [], 0
            for sub_legs, sub_join in subs:
                outs.append(sub_join(ls[i:i + len(sub_legs)]))
                i += len(sub_legs)
            return jnp.stack(outs, axis=1)

        return legs, join2d
    dt = x.dtype
    size = np.dtype(dt).itemsize
    if size == 4:
        if dt == jnp.uint32:
            return [x], lambda ls: ls[0]
        return ([jax.lax.bitcast_convert_type(x, jnp.uint32)],
                lambda ls: jax.lax.bitcast_convert_type(ls[0], dt))
    if size == 8:
        pair = jax.lax.bitcast_convert_type(x, jnp.uint32)  # (n, 2)
        return ([pair[:, 0], pair[:, 1]],
                lambda ls: jax.lax.bitcast_convert_type(
                    jnp.stack(ls, axis=1), dt))
    if dt == jnp.bool_:
        return ([x.astype(jnp.uint32)],
                lambda ls: ls[0].astype(jnp.bool_))
    narrow = jnp.uint16 if size == 2 else jnp.uint8
    return ([jax.lax.bitcast_convert_type(x, narrow).astype(jnp.uint32)],
            lambda ls: jax.lax.bitcast_convert_type(
                ls[0].astype(narrow), dt))


def _kernel_partition(payload, targets, emit, world, interpret: bool):
    """The Pallas twin of `_bucket_sort`: identical contract — stable
    by target, dead rows (emit False) keyed ``world`` to the tail,
    (sorted leaves, counts_out, start) — via one histogram pass and one
    counting-scatter pass instead of an O(n log n) multi-operand sort.
    Bit-for-bit the same permutation: the scatter's sequential
    bucket-major appends ARE the stable sort order."""
    t = jnp.where(emit, targets.astype(jnp.int32), world)
    leaves, treedef = jax.tree.flatten(payload)
    splits = [_leg_split(x) for x in leaves]
    flat_legs = [leg for legs, _ in splits for leg in legs]
    hist = _tpuk.partition_hist(t, world + 1, interpret=interpret)
    counts_out = hist[:, :world].sum(axis=0, dtype=jnp.int32)
    start = jnp.cumsum(counts_out) - counts_out
    outs = _tpuk.partition_scatter(t, flat_legs, world + 1,
                                   interpret=interpret)
    out_leaves, i = [], 0
    for legs, join in splits:
        out_leaves.append(join(list(outs[i:i + len(legs)])))
        i += len(legs)
    return jax.tree.unflatten(treedef, out_leaves), counts_out, start


def _padded_partition(axis, world, block, payload, targets, emit,
                      part: str = "sort"):
    """The shared partition prefix of BOTH padded-mode bodies (the
    single-shot program and the chunked pipeline): stable partition by
    target (`part` picks the XLA bucket sort or the fused Pallas
    kernel — bit-identical layouts), device counts exchange, per-target
    start offsets and the final emit mask. ONE copy on purpose — the
    chunked path's bit-identity with the single-shot program is
    structural, not two texts kept in sync."""
    cap_out = world * block
    if part == "sort":
        sorted_leaves, counts_out, start = _bucket_sort(
            payload, targets, emit, world)
    else:
        sorted_leaves, counts_out, start = _kernel_partition(
            payload, targets, emit, world, interpret=part == "interp")
    counts_in = jax.lax.all_to_all(counts_out, axis, split_axis=0,
                                   concat_axis=0, tiled=True)
    pos = jnp.arange(cap_out, dtype=jnp.int32)
    new_emit = (pos % block) < jnp.take(counts_in, pos // block)
    return sorted_leaves, counts_in, start, new_emit


def _padded_body(axis, world, block, payload, targets, emit,
                 part: str = "sort"):
    """The padded-mode exchange as a pure function of per-shard values —
    shared by the single and the PAIR program builders. ``part`` picks
    the partition path (world-1 keeps the cond-gated sort: a 1-bucket
    counting sort buys nothing over the identity fast path)."""
    if world == 1:
        return _padded_body_w1(axis, block, payload, targets, emit)
    cap_out = world * block
    sorted_leaves, counts_in, start, new_emit = _padded_partition(
        axis, world, block, payload, targets, emit, part)

    def one(xs):
        pad = jnp.zeros((block,) + xs.shape[1:], xs.dtype)
        xp = jnp.concatenate([xs, pad])
        send = _send_block(xp, start, 0, block, world)
        recv = jax.lax.all_to_all(send, axis, split_axis=0,
                                  concat_axis=0, tiled=False)
        return recv.reshape((cap_out,) + xs.shape[1:])

    outs = jax.tree.map(one, sorted_leaves)
    return outs, new_emit, counts_in


@counted_cache
def _exchange_padded_fn(mesh, block: int, part: str = "sort"):
    """Scatter-free single-shot exchange: every (src,dst) pair moves ONE
    [block] slice and lands at the STATIC slot dst_out[src*block:...] —
    no receive scatter at all. Output is PADDED per source (emit mask
    marks each source's live prefix), capacity world*block; the host
    routes here when that padding is acceptable (see exchange()).
    ``part`` (the partition path — see _partition_path) is part of the
    cache key: a knob flip can never reuse the other path's program."""
    axis = mesh.axis_names[0]
    world = mesh.devices.size
    spec = P(axis)

    def kernel(payload, targets, emit):
        return _padded_body(axis, world, block, payload, targets, emit,
                            part)

    return _shard_map_for(part, kernel, mesh, (spec, spec, spec),
                          spec)


# ---------------------------------------------------------------------------
# the chunked, double-buffered padded exchange (overlapped blockwise
# pipeline): the padded payload splits into CYLON_EXCHANGE_CHUNK_BYTES-
# sized blocks, and chunk N+1's all_to_all is dispatched while chunk N's
# received rows are still being compacted into the output — JAX async
# dispatch is the overlap engine, so the host never waits between
# chunks. Peak comm-buffer HBM per leaf drops from 2*W*block (the
# single-shot send+recv stacks) to 2*W*chunk_block: the live pair is
# one in-flight chunk's buffers plus the (donated, reused) accumulator.
# Chunk geometry derives from the count matrix the host already fetched
# for block geometry — zero new host syncs.
# ---------------------------------------------------------------------------


def _chunk_plan(block: int, world: int, bytes_per_row: int):
    """(chunk_block, chunks) for a padded exchange with per-(src,dst)
    ``block``; chunks == 1 means single-shot. Pure host arithmetic over
    already-known geometry. The chunk block is pow2-floored (its value
    keys compiled chunk programs — 1 per octave, specialization-clean)
    and floored again so the pipeline never exceeds MAX_CHUNKS
    programs."""
    if not _knobs.get("CYLON_EXCHANGE_OVERLAP"):
        return block, 1
    target = int(_knobs.get("CYLON_EXCHANGE_CHUNK_BYTES"))
    per_slot = max(int(bytes_per_row), 1) * max(world, 1)
    cb = _pow2_floor(max(target // per_slot, 1))
    cb = max(cb, _pow2_floor(max(block // MAX_CHUNKS, 1)))
    if cb >= block:
        return block, 1
    return cb, -(-block // cb)


def _chunk_write(axis, world, block, cb, xs, start, out, o):
    """Move ONE chunk of one leaf: slice rows [start[t]+o, +cb) per
    target (contiguous — the payload is bucket-sorted), all_to_all,
    land source s's rows at the STATIC padded slot s*block + o. When
    the chunk block divides the block the landing is a memcpy-class
    dynamic_update_slice; a remainder chunk (non-pow2 geometry, only
    reachable through forced test plans) falls back to a dropping
    scatter so out-of-block rows vanish instead of wrapping."""
    send = _send_block(xs, start, o, cb, world)
    recv = jax.lax.all_to_all(send, axis, split_axis=0,
                              concat_axis=0, tiled=False)
    if block % cb == 0:
        out2d = out.reshape((world, block) + xs.shape[1:])
        out2d = jax.lax.dynamic_update_slice_in_dim(out2d, recv, o,
                                                    axis=1)
        return out2d.reshape((world * block,) + xs.shape[1:])
    biota = jnp.arange(cb, dtype=jnp.int32)
    pos = (jnp.arange(world, dtype=jnp.int32) * block)[:, None] \
        + o + biota[None, :]
    valid = (o + biota) < block
    psafe = jnp.where(valid[None, :], pos, world * block).reshape(-1)
    flat = recv.reshape((world * cb,) + xs.shape[1:])
    return out.at[psafe].set(flat, mode="drop")


def _partition_body(axis, world, block, cb, payload, targets, emit,
                    first_chunk: bool, part: str = "sort"):
    """The partition phase of the chunked exchange as a pure per-shard
    function: stable partition (``part``-routed), device counts
    exchange, chunk-padded sorted leaves, zeroed output accumulators
    and the final emit mask — everything the per-chunk programs
    consume. ``first_chunk`` folds chunk 0's exchange+compaction in
    (the fused form)."""
    cap_out = world * block
    sorted_leaves, counts_in, start, new_emit = _padded_partition(
        axis, world, block, payload, targets, emit, part)
    padded = jax.tree.map(
        lambda x: jnp.concatenate(
            [x, jnp.zeros((cb,) + x.shape[1:], x.dtype)]),
        sorted_leaves)
    _to_varying = _to_varying_fn(axis)
    out0 = jax.tree.map(
        lambda x: _to_varying(jnp.zeros((cap_out,) + x.shape[1:],
                                        x.dtype)), payload)
    if first_chunk:
        out0 = jax.tree.map(
            lambda xs, ob: _chunk_write(axis, world, block, cb, xs,
                                        start, ob, 0),
            padded, out0)
    return padded, start, counts_in, new_emit, out0


@counted_cache
def _exchange_partition_fn(mesh, block: int, chunk_block: int,
                           part: str = "sort"):
    """UNFUSED partition program of the chunked exchange (no chunk 0):
    kept as a real dispatchable program so the profiler and the
    shuffle_pipeline bench can measure the fusion win of
    `_exchange_chunk_first_fn` against it — with fusion a C-chunk
    exchange costs C program launches, without it C+1."""
    axis = mesh.axis_names[0]
    world = mesh.devices.size
    spec = P(axis)

    def kernel(payload, targets, emit):
        return _partition_body(axis, world, block, chunk_block,
                               payload, targets, emit,
                               first_chunk=False, part=part)

    return _shard_map_for(part, kernel, mesh, (spec,) * 3, spec)


@counted_cache
def _exchange_chunk_first_fn(mesh, block: int, chunk_block: int,
                             part: str = "sort"):
    """FUSED partition+exchange program — the single-table analog of
    the `_exchange_padded_pair_fn` trick (two stages in ONE compiled
    program, one dispatch where two would do): the partition body with
    chunk 0's all_to_all+compaction folded in, so XLA schedules the
    bucket sort, the counts exchange and the first payload collective
    together and `cylon_collective_launches_total` drops by one per
    chunked exchange."""
    axis = mesh.axis_names[0]
    world = mesh.devices.size
    spec = P(axis)

    def kernel(payload, targets, emit):
        return _partition_body(axis, world, block, chunk_block,
                               payload, targets, emit,
                               first_chunk=True, part=part)

    return _shard_map_for(part, kernel, mesh, (spec,) * 3, spec)


@counted_cache
def _exchange_chunk_fn(mesh, block: int, chunk_block: int):
    """One pipeline chunk: slice, all_to_all, compact at the static
    padded slots. The chunk index ``k`` rides as a DEVICE operand
    (replicated scalar), so every chunk of every exchange with this
    geometry shares ONE compiled program — chunk count never enters a
    cache key. The output accumulator is donated on TPU: the pipeline's
    live buffers are the in-flight chunk's send/recv stacks plus one
    accumulator (the double buffer), not one fresh [cap_out] copy per
    chunk. (Donation is a no-op on host backends, which do not
    implement it.)"""
    axis = mesh.axis_names[0]
    world = mesh.devices.size
    spec = P(axis)

    def kernel(padded, start, out, k):
        o = k.astype(jnp.int32) * chunk_block
        return jax.tree.map(
            lambda xs, ob: _chunk_write(axis, world, block, chunk_block,
                                        xs, start, ob, o),
            padded, out)

    donate = (2,) if mesh.devices.flat[0].platform == "tpu" else ()
    return jax.jit(shard_map(kernel, mesh=mesh,
                             in_specs=(spec, spec, spec, P()),
                             out_specs=spec),
                   donate_argnums=donate)


def _dispatch_chunked(ctx: CylonContext, block: int, cb: int,
                      chunks: int, payload, targets, emit, fuse: bool,
                      part: str = "sort"):
    """Launch the chunked pipeline: one partition program (with chunk 0
    folded in when ``fuse``), then one chunk program per remaining
    chunk — dispatched back to back WITHOUT waiting, so chunk N+1's
    all_to_all runs while chunk N's received rows are compacted (and
    while the consumer's local kernels on already-landed rows queue
    behind them). Every dispatch runs under the per-chunk retry policy;
    re-dispatch is idempotent because the chaos injector fires BEFORE
    the program consumes its (donated) buffers. Returns (outs,
    new_emit, counts_in, programs_launched)."""
    mesh = ctx.mesh
    if fuse:
        padded, start, counts_in, new_emit, outs = _launch_exchange(
            lambda: _exchange_chunk_first_fn(mesh, block, cb, part)(
                payload, targets, emit))
        k0, programs = 1, chunks
    else:
        padded, start, counts_in, new_emit, outs = _launch_exchange(
            lambda: _exchange_partition_fn(mesh, block, cb, part)(
                payload, targets, emit))
        k0, programs = 0, chunks + 1
    step = _exchange_chunk_fn(mesh, block, cb)
    for k in range(k0, chunks):
        karr = np.int32(k)

        def attempt(karr=karr, k=k):
            # donation caveat: a faulted dispatch that already consumed
            # the donated accumulator (possible only on TPU — donation
            # is a no-op on host backends) would make a plain
            # re-dispatch fail hard on a deleted buffer; a retry
            # attempt therefore rebuilds the pipeline state from the
            # (never-donated) payload and replays the landed chunks
            # before re-dispatching — idempotent recovery either way
            nonlocal padded, start, counts_in, new_emit, outs
            leaf = next(iter(jax.tree.leaves(outs)), None)
            if leaf is not None and \
                    getattr(leaf, "is_deleted", lambda: False)():
                padded, start, counts_in, new_emit, outs = \
                    _exchange_partition_fn(mesh, block, cb, part)(
                        payload, targets, emit)
                for j in range(k):
                    outs = step(padded, start, outs, np.int32(j))
            return step(padded, start, outs, karr)

        outs = _launch_exchange(attempt)
    return outs, new_emit, counts_in, programs


def _record_chunked(sp, chunks: int, cb: int, programs: int) -> None:
    """Chunk-pipeline observability: per-exchange span attrs plus the
    cylon_exchange_chunks_total counter and the overlap-ratio histogram
    ((programs-1)/programs — the fraction of the pipeline's programs
    issued while earlier chunk work was still in flight)."""
    ratio = (programs - 1) / programs if programs else 0.0
    sp.set(chunks=chunks, chunk_block=cb,
           overlap_ratio=round(ratio, 4))
    _counter("cylon_exchange_chunks_total").inc(chunks)
    _REGISTRY.histogram("cylon_exchange_overlap_ratio",
                        buckets=OVERLAP_BUCKETS).observe(ratio)


@counted_cache
def _exchange_padded_pair_fn(mesh, block1: int, block2: int,
                             part1: str = "sort", part2: str = "sort"):
    """BOTH sides of a two-table shuffle in ONE compiled program — one
    dispatch instead of two, and XLA schedules the two bucket sorts and
    collective pairs together (the distributed join's composition cost
    is dominated by fixed per-program cost through the axon tunnel)."""
    axis = mesh.axis_names[0]
    world = mesh.devices.size
    spec = P(axis)

    def kernel(p1, t1, e1, p2, t2, e2):
        o1 = _padded_body(axis, world, block1, p1, t1, e1, part1)
        o2 = _padded_body(axis, world, block2, p2, t2, e2, part2)
        return o1 + o2

    # any pallas side forces the unchecked shard_map build (a mixed
    # sort+pallas pair still contains a pallas_call)
    part = part1 if part1 != "sort" else part2
    return _shard_map_for(part, kernel, mesh, (spec,) * 6, spec)


def exchange_pair(payload1, targets1, emit1, counts1,
                  payload2, targets2, emit2, counts2, ctx: CylonContext,
                  dense: bool = False):
    """Two shuffles in one program when both route to padded mode
    (the uniform-hash common case); otherwise two sequential
    exchanges. Returns (result1, result2) where each result is the
    exchange() 4-tuple. ``counts1``/``counts2`` may be None on a 1-wide
    mesh when ``dense`` (both emits all-live): the fused world-1 padded
    body computes counts in-program (no host count sync at all for the
    whole two-table shuffle)."""
    world = ctx.get_world_size()
    budget = ctx.memory_pool.comm_budget_bytes()
    if world == 1 and counts1 is None and counts2 is None and dense:
        b1 = _pow2(int(targets1.shape[0]))
        b2 = _pow2(int(targets2.shape[0]))
        mb1 = _budget_block_cap(payload1, 1, budget, b1, 8)
        mb2 = _budget_block_cap(payload2, 1, budget, b2, 8)
        if b1 <= mb1 and b2 <= mb2:
            seq = ctx.get_next_sequence()
            rows = int(targets1.shape[0]) + int(targets2.shape[0])
            nbytes = _payload_nbytes(payload1) + _payload_nbytes(payload2)
            with _span("shuffle.exchange_pair", seq, world=1,
                       mode="padded", rows=rows, bytes_moved=nbytes):
                res = _launch_exchange(
                    lambda: _exchange_padded_pair_fn(ctx.mesh, b1, b2)(
                        payload1, targets1, emit1, payload2, targets2,
                        emit2))
            _record_exchange(rows, nbytes)
            out1, emit1_o, ci1, out2, emit2_o, ci2 = res
            return ((out1, emit1_o, b1,
                     {"mode": "padded", "block": b1, "counts_in": ci1}),
                    (out2, emit2_o, b2,
                     {"mode": "padded", "block": b2, "counts_in": ci2}))
        return (exchange(payload1, targets1, emit1, ctx, dense=dense),
                exchange(payload2, targets2, emit2, ctx, dense=dense))
    # buffer_factor=8: the pair program holds BOTH tables' comm buffers
    ok1, b1, _mb1 = _padded_route(counts1, payload1, world, budget,
                                  buffer_factor=8)
    ok2, b2, _mb2 = _padded_route(counts2, payload2, world, budget,
                                  buffer_factor=8)
    if ok1 and ok2 and (
            _chunk_plan(b1, world, _payload_row_bytes(payload1))[1] > 1
            or _chunk_plan(b2, world,
                           _payload_row_bytes(payload2))[1] > 1):
        # either side is big enough to chunk: the overlapped pipeline
        # (each side chunked through exchange(), counts already fetched)
        # beats the monolithic pair program whose send+recv stacks for
        # BOTH tables would be live at once
        return (exchange(payload1, targets1, emit1, ctx, counts=counts1),
                exchange(payload2, targets2, emit2, ctx, counts=counts2))
    if ok1 and ok2:
        seq = ctx.get_next_sequence()
        rows = (int(counts1.sum()) if counts1 is not None else 0) \
            + (int(counts2.sum()) if counts2 is not None else 0)
        nbytes = _payload_nbytes(payload1) + _payload_nbytes(payload2)
        # per-side histograms carry each table's own row width; the
        # span attributes carry the COMBINED per-destination totals
        # (what each shard actually absorbs from the fused program)
        _skew.observe_exchange(counts1, _payload_row_bytes(payload1))
        _skew.observe_exchange(counts2, _payload_row_bytes(payload2))
        pair_stats = _skew.SkewStats.from_counts(
            np.asarray(counts1) + np.asarray(counts2)) \
            if counts1 is not None and counts2 is not None else None
        part1 = _partition_path(ctx.mesh, world, payload1)
        part2 = _partition_path(ctx.mesh, world, payload2)
        with _span("shuffle.exchange_pair", seq, world=world,
                   mode="padded", rows=rows, bytes_moved=nbytes) as sp:
            if pair_stats is not None:
                sp.set(**pair_stats.span_attrs())
            # one decision per side; the fused program partitions both
            _record_partition(sp, part1, part2)
            res = _launch_exchange(
                lambda: _exchange_padded_pair_fn(ctx.mesh, b1, b2,
                                                 part1, part2)(
                    payload1, targets1, emit1, payload2, targets2,
                    emit2))
        _record_exchange(rows, nbytes)
        out1, emit1_o, ci1, out2, emit2_o, ci2 = res
        return ((out1, emit1_o, world * b1,
                 {"mode": "padded", "block": b1, "counts_in": ci1}),
                (out2, emit2_o, world * b2,
                 {"mode": "padded", "block": b2, "counts_in": ci2}))
    return (exchange(payload1, targets1, emit1, ctx, counts=counts1),
            exchange(payload2, targets2, emit2, ctx, counts=counts2))


@counted_cache
def _exchange_fn(mesh, block: int, rounds: int, cap_out: int):
    """The blockwise body phase (skew fallback): K rounds, each moving
    one [W,B] block per leaf and compacting received rows at running
    per-source offsets — bounded comm memory under any skew."""
    axis = mesh.axis_names[0]
    world = mesh.devices.size
    spec = P(axis)

    def kernel(payload, targets, emit):
        sorted_leaves, counts_out, start = _bucket_sort(
            payload, targets, emit, world)
        # the header exchange, on device: each shard learns how many rows
        # every source will send it, and writes source s's rows at offset
        # S[s] — arrivals are contiguous per source, output is compact
        counts_in = jax.lax.all_to_all(counts_out, axis, split_axis=0,
                                       concat_axis=0, tiled=True)
        S = jnp.cumsum(counts_in) - counts_in
        total_in = counts_in.sum()

        biota = jnp.arange(block, dtype=jnp.int32)[None, :]      # [1,B]
        padded = jax.tree.map(
            lambda x: jnp.concatenate(
                [x, jnp.zeros((block,) + x.shape[1:], x.dtype)]),
            sorted_leaves)
        _to_varying = _to_varying_fn(axis)
        out0 = jax.tree.map(
            lambda x: _to_varying(jnp.zeros((cap_out,) + x.shape[1:],
                                            x.dtype)), payload)

        def round_body(k, outs):
            o = k * block
            # receive slots: S[s] + [o, o+B), dropped past counts_in[s]
            pos = S[:, None] + o + biota
            pvalid = (o + biota) < counts_in[:, None]
            psafe = jnp.where(pvalid, pos, cap_out).reshape(-1)

            def one(xs, out):
                send = _send_block(xs, start, o, block, world)
                recv = jax.lax.all_to_all(send, axis, split_axis=0,
                                          concat_axis=0, tiled=False)
                flat = recv.reshape((world * block,) + xs.shape[1:])
                return out.at[psafe].set(flat, mode="drop")

            return jax.tree.map(one, padded, outs)

        outs = jax.lax.fori_loop(0, rounds, round_body, out0) if rounds > 1 \
            else round_body(0, out0)
        new_emit = jnp.arange(cap_out, dtype=jnp.int32) < total_in
        counts_in_out = counts_in
        return outs, new_emit, counts_in_out

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec, spec, spec),
                             out_specs=spec))


# padded-mode acceptance: worst-case capacity blowup over the compact
# layout before the blockwise (skew) path takes over. Uniform hash
# placement gives W*pow2(max_pair) <= 2*pow2(recv_max); a hot (src,dst)
# pair blows past 2 and routes to the blockwise path.
PADDED_WASTE_FACTOR = 2


@counted_cache
def _count2_fn(mesh):
    """Both sides' send-count matrices in ONE compiled program (one
    host sync for a two-table shuffle instead of two — the axon tunnel
    charges ~100 ms per round trip)."""
    axis = mesh.axis_names[0]
    world = mesh.devices.size
    spec = P(axis)

    def kernel(t1, e1, t2, e2):
        a = jnp.where(e1, t1.astype(jnp.int32), world)
        b = jnp.where(e2, t2.astype(jnp.int32), world)
        both = jnp.stack([_target_counts(a, world),
                          _target_counts(b, world)])
        return replicated_gather(both, axis, world)

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec,) * 4,
                             out_specs=P()))


# ---------------------------------------------------------------------------
# hot-key salting (adaptive execution, ROADMAP item 1): under a Zipfian
# key column every row of the hot key hashes to ONE destination, so the
# receiving shard's local kernel does most of the query's work however
# fast the exchange itself runs. The salted variant of the partition
# decides, ON DEVICE and from the true global count matrix, which
# destinations are hot (receive total beyond the warn factor x the
# mean), and spreads exactly those destinations' rows across
# CYLON_SALT_FACTOR consecutive shards — the salt is a per-row value
# folded into the routing (fmix32(iota) % S), never into the payload,
# so receive-side rows are already "un-salted": downstream kernels see
# the original keys, and the caller withholds the placement witness
# (salted placement is positional, not key-hash). One program, one
# host sync: the salted targets, the salted count matrix AND the raw
# (pre-mitigation) matrix come back together — skew observability and
# the warehouse's salting decision read the RAW skew, so the decision
# never oscillates on its own mitigation.
# ---------------------------------------------------------------------------


@counted_cache
def _salted_targets_fn(mesh, salt: int):
    """(targets, emit, warn_factor) -> (salted targets [sharded],
    stacked [2, W, W] salted+raw count matrices [replicated]). ``salt``
    is the declared CYLON_SALT_FACTOR (>= 2, structural — a tiny
    finite set of compiled programs)."""
    axis = mesh.axis_names[0]
    world = mesh.devices.size
    spec = P(axis)

    def kernel(targets, emit, warn):
        t = jnp.where(emit, targets.astype(jnp.int32), world)
        raw = replicated_gather(_target_counts(t, world), axis, world)
        recv = raw.sum(axis=0)
        total = jnp.maximum(recv.sum(), 1)
        # hot destination: receive total > warn x mean = warn x total/W
        hot = recv.astype(jnp.float32) * np.float32(world) \
            > warn * total.astype(jnp.float32)
        iota = jnp.arange(targets.shape[0], dtype=jnp.uint32)
        sub = (_hash.fmix32(iota) % np.uint32(salt)).astype(jnp.int32)
        safe = jnp.clip(targets.astype(jnp.int32), 0, world - 1)
        spread = (safe + sub) % np.int32(world)
        t2 = jnp.where(jnp.take(hot, safe) & emit, spread, safe)
        t2d = jnp.where(emit, t2, world)
        salted = replicated_gather(_target_counts(t2d, world), axis,
                                   world)
        return t2, jnp.stack([salted, raw])

    return jax.jit(shard_map(kernel, mesh=mesh,
                             in_specs=(spec, spec, P()),
                             out_specs=(spec, P())))


def salted_exchange_targets(targets, emit, ctx: CylonContext,
                            salt: int, warn_factor: float):
    """Host wrapper: run the salted-targets program, fetch BOTH count
    matrices in one sync, and return (salted targets, salted counts,
    raw counts) — the caller feeds the salted counts to exchange()
    (no second count round trip) and observes skew from the raw ones."""
    def compute():
        t2, both = _salted_targets_fn(ctx.mesh, salt)(
            targets, emit, jnp.float32(warn_factor))
        host = np.asarray(jax.device_get(both))
        _host_sync("shuffle.salt")
        _counter("cylon_collective_launches_total").inc()
        return t2, host[0], host[1]

    return _retry.run_retryable("exchange.count", compute)


# Repeat-shuffle count cache (round-5, VERDICT r04 #4a): jax Arrays are
# immutable, so identical (targets, emit) OBJECTS imply identical counts
# — iterative pipelines that re-shuffle the same key column (and bench
# timing loops) skip the ~100 ms count round trip on every repeat.
# WEAK refs only: entries die with their arrays (no HBM pinned beyond
# the caller's own lifetime), and a hit additionally verifies object
# identity so a recycled id can never alias a dead entry.
_COUNT_CACHE: "dict[tuple, tuple]" = {}
_COUNT_CACHE_CAP = 8


def _count_cached(ids_key, refs, compute):
    import weakref

    hit = _COUNT_CACHE.get(ids_key)
    if hit is not None:
        wrs, val = hit
        if all(w() is r for w, r in zip(wrs, refs)):
            return val
        del _COUNT_CACHE[ids_key]
    val = compute()
    if len(_COUNT_CACHE) >= _COUNT_CACHE_CAP:
        _COUNT_CACHE.pop(next(iter(_COUNT_CACHE)))
    try:
        wrs = tuple(weakref.ref(r) for r in refs)
    except TypeError:  # pragma: no cover - non-weakref-able array impl
        return val  # skip caching rather than pin device memory
    _COUNT_CACHE[ids_key] = (wrs, val)
    return val


def count_pair(targets1, emit1, targets2, emit2, ctx: CylonContext):
    """Host (countsL, countsR) for two shuffles, one program + one sync.
    Feed the results to exchange(..., counts=...)."""
    def compute():
        # result is [src, 2, dst] (replicated_gather stacks per source)
        with _span("shuffle.count", ctx.get_next_sequence(),
                   world=ctx.get_world_size(), tables=2):
            both = np.asarray(jax.device_get(
                _count2_fn(ctx.mesh)(targets1, emit1, targets2, emit2)))
        _host_sync("shuffle.count_pair")
        _counter("cylon_collective_launches_total").inc()
        return both[:, 0, :], both[:, 1, :]

    # the count program is part of the exchange stage: transient
    # failures (and injected compile faults in its factory build)
    # retry under the same policy as the body dispatch
    return _count_cached(
        ("pair", id(ctx.mesh), id(targets1), id(emit1), id(targets2),
         id(emit2)),
        (targets1, emit1, targets2, emit2),
        lambda: _retry.run_retryable("exchange.count", compute))


def _budget_block_cap(payload, world: int, budget, mb: int,
                      buffer_factor: int) -> int:
    """Shrink the per-round block cap so buffer_factor * world * block *
    row_bytes fits the comm budget (pow2-floored) — the Allocator analog
    feeding receive buffers from the pool
    (arrow_all_to_all.cpp:234-247)."""
    bytes_per_row = sum(
        int(np.dtype(x.dtype).itemsize) * int(np.prod(x.shape[1:]))
        for x in jax.tree.leaves(payload)) or 4
    if budget:
        while mb > 1024 and buffer_factor * world * mb * bytes_per_row                 > budget:
            mb //= 2
    # pow2_floor: the cap feeds block sizes that key compiled exchange
    # programs — keep them 1-per-octave (specialization analysis)
    return _pow2_floor(mb)


def _padded_route(counts, payload, world: int, budget,
                  buffer_factor: int = 4, max_block: int = None):
    """(padded_ok, block) — ONE routing rule shared by exchange() and
    exchange_pair() so the two paths can never silently diverge."""
    max_pair = int(counts.max()) if counts.size else 0
    recv_max = int(counts.sum(axis=0).max()) if counts.size else 0
    block_p = _pow2(max_pair)
    mb = _budget_block_cap(payload, world, budget,
                           MAX_BLOCK if max_block is None else max_block,
                           buffer_factor)
    ok = (world * block_p
          <= PADDED_WASTE_FACTOR * max(_pow2(recv_max), 1)
          and block_p <= mb)
    return ok, block_p, mb


def exchange(payload: Dict[str, jnp.ndarray], targets: jnp.ndarray,
             emit: jnp.ndarray, ctx: CylonContext,
             max_block: Optional[int] = None,
             counts: Optional[np.ndarray] = None,
             dense: bool = False, fuse: bool = True
             ) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray, int, dict]:
    """Shuffle a pytree of row-sharded per-row arrays to their target shards.

    Returns (exchanged payload, new emit mask, per-shard capacity, meta).
    All outputs are row-sharded with each source's rows CONTIGUOUS and
    in stable order; live rows are marked by the emit mask. Two layouts,
    host-selected from the count matrix:

    * "padded" (the fast path): every (src,dst) pair moves one slice and
      lands at a static slot — no receive scatter. Source s's rows start
      at s*block; capacity world*block. Picked when that padding stays
      within PADDED_WASTE_FACTOR of the compact capacity (uniform-ish
      distributions, which hash placement makes the common case).
    * "compact" (skew fallback): blockwise rounds with bounded comm
      buffers; live rows form a leading prefix, capacity pow2 of the
      worst receive total.

    meta = {"mode", "block", "counts_in"} — counts_in is the [world*W]
    sharded per-source receive-count matrix (each shard's own [W] slice),
    consumed by the varbytes word/row layout reconciliation. Padded-mode
    exchanges whose payload exceeds CYLON_EXCHANGE_CHUNK_BYTES run as
    the chunked, double-buffered pipeline (meta gains ``chunks``;
    ``CYLON_EXCHANGE_OVERLAP=0`` restores the single-shot program, and
    the two paths are bit-identical on every live row). ``fuse`` folds
    the partition program into chunk 0 (on by default; the bench's
    launch-count comparison is the only caller that turns it off).
    ``max_block`` caps the per-round blockwise block size.
    """
    world = ctx.get_world_size()
    seq = ctx.get_next_sequence()
    budget0 = ctx.memory_pool.comm_budget_bytes()
    if world == 1 and counts is None and dense:
        # fused count+exchange (round-5, VERDICT r04 #4b): on a 1-wide
        # mesh the padded route with block = pow2(n) is always exact, so
        # the host count round trip is pure overhead — counts_in
        # computes inside the exchange program itself. Gated on the
        # caller asserting a dense emit (``dense``): for sparse-emit
        # tables the counted route's pow2(live) capacity beats saving
        # one sync. MAX_BLOCK (a per-ROUND comm-buffer cap) does not
        # bind here: there are no rounds, only the memory budget
        block1 = _pow2(int(targets.shape[0]))
        mb1 = _budget_block_cap(payload, 1, budget0, block1
                                if max_block is None else max_block, 4)
        if block1 <= mb1:
            rows = int(targets.shape[0])
            nbytes = _payload_nbytes(payload)
            with _span("shuffle.exchange", seq, world=1, mode="padded",
                       rows=rows, bytes_moved=nbytes):
                out, new_emit, counts_in = _launch_exchange(
                    lambda: _exchange_padded_fn(
                        ctx.mesh, block1)(payload, targets, emit))
            _record_exchange(rows, nbytes)
            return out, new_emit, block1, {
                "mode": "padded", "block": block1, "counts_in": counts_in}
    if counts is None:
        def compute():
            with _span("shuffle.count", seq, world=world, tables=1):
                res = np.asarray(jax.device_get(
                    _count_fn(ctx.mesh)(targets, emit)))
            _host_sync("shuffle.count")
            _counter("cylon_collective_launches_total").inc()
            return res

        counts = _count_cached(
            ("one", id(ctx.mesh), id(targets), id(emit)),
            (targets, emit),
            lambda: _retry.run_retryable("exchange.count", compute))
    max_pair = int(counts.max()) if counts.size else 0
    recv_max = int(counts.sum(axis=0).max()) if counts.size else 0
    budget = ctx.memory_pool.comm_budget_bytes()
    padded_ok, block_p, mb = _padded_route(counts, payload, world, budget,
                                           buffer_factor=4,
                                           max_block=max_block)
    cap_padded = world * block_p
    cap_compact = _pow2(recv_max)
    rows_live = int(counts.sum()) if counts.size else 0
    nbytes = _payload_nbytes(payload)
    row_bytes = _payload_row_bytes(payload)
    # skew observability rides the ALREADY-FETCHED count matrix: zero
    # extra device→host transfers (None on a 1-wide mesh)
    skew_stats = _skew.observe_exchange(counts, row_bytes)
    with _span("shuffle.exchange", seq, world=world,
               mode="padded" if padded_ok else "compact",
               rows=rows_live, bytes_moved=nbytes) as sp:
        if skew_stats is not None:
            sp.set(**skew_stats.span_attrs())
        if padded_ok:
            part = _partition_path(ctx.mesh, world, payload)
            _record_partition(sp, part)
            cb, chunks = _chunk_plan(block_p, world, row_bytes)
            if chunks > 1:
                out, new_emit, counts_in, programs = _dispatch_chunked(
                    ctx, block_p, cb, chunks, payload, targets, emit,
                    fuse, part)
                _record_chunked(sp, chunks, cb, programs)
                _record_exchange(rows_live, nbytes, programs)
                return out, new_emit, cap_padded, {
                    "mode": "padded", "block": block_p,
                    "counts_in": counts_in, "chunks": chunks}
            out, new_emit, counts_in = _launch_exchange(
                lambda: _exchange_padded_fn(
                    ctx.mesh, block_p, part)(payload, targets, emit))
            _record_exchange(rows_live, nbytes)
            return out, new_emit, cap_padded, {
                "mode": "padded", "block": block_p, "counts_in": counts_in}
        block = min(block_p, mb)
        # pow2 round count bounds the compile cache to O(log^3) programs
        rounds = _pow2(-(-max(max_pair, 1) // block))
        sp.set(block=block, rounds=rounds)
        out, new_emit, counts_in = _launch_exchange(
            lambda: _exchange_fn(
                ctx.mesh, block, rounds, cap_compact)(payload, targets,
                                                      emit))
    _record_exchange(rows_live, nbytes)
    return out, new_emit, cap_compact, {
        "mode": "compact", "block": 0, "counts_in": counts_in}
