"""The shuffle: hash-partition + blockwise all-to-all on XLA collectives.

This is the TPU-native replacement for the reference's entire four-layer
communication stack (reference: cpp/src/cylon/net/mpi/mpi_channel.cpp:30-247
two-phase header+body MPI protocol with per-peer FSMs; net/ops/
all_to_all.cpp:26-178 queue/FIN machinery; arrow/arrow_all_to_all.cpp:24-264
per-buffer Arrow serialization). None of that machinery is translated:
inside one compiled SPMD program, `jax.lax.all_to_all` over the mesh axis IS
the transport, XLA program order replaces MPI tags/edges, and program
completion replaces the FIN handshake.

The reference's variable-length problem (its 8-int length header preceding
every body message) maps to the static-shape world as a TWO-PHASE exchange:

  phase 1 ("header"): a tiny compiled program computes the per-(src,dst)
     send-count matrix — one [W] vector per shard, gathered to the host;
  phase 2 ("body"):   a BLOCKWISE exchange. The host picks a pow2 block
     size B (capped at MAX_BLOCK) and a round count K with K*B >= the
     largest single (src,dst) transfer; the compiled program bucket-sorts
     rows by target once, then loops K rounds, each round moving one [W,B]
     block per payload leaf through `all_to_all` and compacting received
     rows into a [cap_out] output at running per-source offsets.

The blockwise loop is the TPU analog of the reference's incremental
buffer-at-a-time streaming (arrow_all_to_all.cpp:83-135): peak comm-buffer
memory is bounded by W*MAX_BLOCK rows per leaf regardless of skew, and the
output capacity tracks the worst RECEIVE TOTAL over shards
(pow2(max_t sum_s C[s,t])) instead of W*pow2(max C[s,t]) — up to W× smaller
when one (src,dst) pair is hot. Receivers place each source's rows
contiguously, so shuffle output is COMPACT (emit = leading prefix).

Rows whose emit mask is False (table padding, filtered rows) are dropped in
transit — the shuffle doubles as a compaction step.
"""
from __future__ import annotations

from functools import lru_cache
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

try:  # jax>=0.8
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from ..context import CylonContext
from ..telemetry import phase as _phase
from ..util import pow2 as _pow2

# Upper bound on the per-round block (rows per (src,dst) pair per round).
# Comm/scratch memory per leaf is 2*W*MAX_BLOCK rows; skew beyond this
# degrades into more rounds, not bigger buffers.
MAX_BLOCK = 1 << 16


def replicated_gather(x, axis: str, world: int):
    """Per-shard [..] value → [world, ..] matrix REPLICATED on every shard.

    psum of a one-hot row scatter rather than `all_gather`: shard_map's
    varying-mesh-axes check can statically prove a psum result is
    replicated (out_specs=P() legal), which it cannot for all_gather.
    Replication matters on multi-host meshes — the host fetch of a
    *sharded* count array would not be addressable from other controller
    processes."""
    row = jax.lax.axis_index(axis)
    mat = jnp.zeros((world,) + x.shape, x.dtype).at[row].set(x)
    return jax.lax.psum(mat, axis)


@lru_cache(maxsize=None)
def _count_fn(mesh):
    """Send-count matrix counts[s, t] = live rows shard s sends to shard t,
    REPLICATED on every shard (an in-program all_gather) so the host fetch
    is valid on every controller process — a sharded output would not be
    addressable from the other hosts of a multi-host mesh.

    The moral equivalent of the reference's header phase
    (mpi_channel.cpp:211-225 sendHeader)."""
    axis = mesh.axis_names[0]
    world = mesh.devices.size
    spec = P(axis)

    def kernel(targets, emit):
        t = jnp.where(emit, targets.astype(jnp.int32), world)
        counts = jax.ops.segment_sum(jnp.ones(t.shape[0], jnp.int32), t,
                                     num_segments=world + 1)
        return replicated_gather(counts[:world], axis, world)

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec, spec),
                             out_specs=P()))


@lru_cache(maxsize=None)
def _exchange_fn(mesh, block: int, rounds: int, cap_out: int):
    """The body phase: bucket-sort by target once, then K blockwise
    `all_to_all` rounds compacting into a [cap_out] output per leaf."""
    axis = mesh.axis_names[0]
    world = mesh.devices.size
    spec = P(axis)

    def kernel(payload, targets, emit):
        n = targets.shape[0]
        iota = jnp.arange(n, dtype=jnp.int32)
        t = jnp.where(emit, targets.astype(jnp.int32), world)
        # stable bucket sort by target: one fused device sort yields the
        # permutation every column reuses (the reference's per-dtype split
        # kernels, arrow_kernels.cpp:24-134, collapse into this one sort)
        _, perm = jax.lax.sort((t, iota), num_keys=1)
        counts_out = jax.ops.segment_sum(jnp.ones(n, jnp.int32), t,
                                         num_segments=world + 1)[:world]
        start = jnp.cumsum(counts_out) - counts_out
        # the header exchange, on device: each shard learns how many rows
        # every source will send it, and writes source s's rows at offset
        # S[s] — arrivals are contiguous per source, output is compact
        counts_in = jax.lax.all_to_all(counts_out, axis, split_axis=0,
                                       concat_axis=0, tiled=True)
        S = jnp.cumsum(counts_in) - counts_in
        total_in = counts_in.sum()

        biota = jnp.arange(block, dtype=jnp.int32)[None, :]      # [1,B]
        sorted_leaves = jax.tree.map(
            lambda x: jnp.take(x, perm, axis=0), payload)
        # the carry must be typed as mesh-varying, like the all_to_all
        # outputs accumulated into it
        _vary = getattr(jax.lax, "pcast", None)
        if _vary is not None:
            def _to_varying(x):
                return jax.lax.pcast(x, axis, to="varying")
        else:  # pragma: no cover - older jax
            def _to_varying(x):
                return jax.lax.pvary(x, (axis,))
        out0 = jax.tree.map(
            lambda x: _to_varying(jnp.zeros((cap_out,) + x.shape[1:],
                                            x.dtype)), payload)

        def round_body(k, outs):
            o = k * block
            # send slots: rows [o, o+B) of each target's bucket
            gsafe = jnp.clip(start[:, None] + o + biota, 0, max(n - 1, 0))
            # receive slots: S[s] + [o, o+B), dropped past counts_in[s]
            pos = S[:, None] + o + biota
            pvalid = (o + biota) < counts_in[:, None]
            psafe = jnp.where(pvalid, pos, cap_out).reshape(-1)

            def one(xs, out):
                send = jnp.take(xs, gsafe.reshape(-1), axis=0)
                send = send.reshape((world, block) + xs.shape[1:])
                recv = jax.lax.all_to_all(send, axis, split_axis=0,
                                          concat_axis=0, tiled=False)
                flat = recv.reshape((world * block,) + xs.shape[1:])
                return out.at[psafe].set(flat, mode="drop")

            return jax.tree.map(one, sorted_leaves, outs)

        outs = jax.lax.fori_loop(0, rounds, round_body, out0) if rounds > 1 \
            else round_body(0, out0)
        new_emit = jnp.arange(cap_out, dtype=jnp.int32) < total_in
        return outs, new_emit

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec, spec, spec),
                             out_specs=spec))


def exchange(payload: Dict[str, jnp.ndarray], targets: jnp.ndarray,
             emit: jnp.ndarray, ctx: CylonContext,
             max_block: Optional[int] = None
             ) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray, int]:
    """Shuffle a pytree of row-sharded per-row arrays to their target shards.

    Returns (exchanged payload, new emit mask, per-shard capacity). All
    outputs are row-sharded and COMPACT per shard (live rows form a
    leading prefix). Capacity = pow2 of the worst per-shard receive total.
    ``max_block`` caps the per-round block size (default MAX_BLOCK).
    """
    world = ctx.get_world_size()
    seq = ctx.get_next_sequence()
    with _phase("shuffle.count", seq):
        counts = np.asarray(jax.device_get(
            _count_fn(ctx.mesh)(targets, emit)))
    max_pair = int(counts.max()) if counts.size else 0
    recv_max = int(counts.sum(axis=0).max()) if counts.size else 0
    mb = max_block if max_block is not None else MAX_BLOCK
    # the memory pool bounds in-flight comm buffers (2*W*block rows per
    # leaf both directions); shrink the block cap to fit the HBM budget —
    # the reference's analog is the Allocator feeding receive buffers from
    # the pool (arrow_all_to_all.cpp:234-247)
    budget = ctx.memory_pool.comm_budget_bytes()
    if budget:
        bytes_per_row = sum(
            int(np.dtype(x.dtype).itemsize) * int(np.prod(x.shape[1:]))
            for x in jax.tree.leaves(payload)) or 4
        while mb > 1024 and 4 * world * mb * bytes_per_row > budget:
            mb //= 2
    # floor-pow2 the cap so the documented memory bound is never exceeded
    mb = 1 << (max(int(mb), 1).bit_length() - 1)
    block = min(_pow2(max_pair), mb)
    # pow2 round count bounds the compile cache to O(log^3) programs
    rounds = _pow2(-(-max(max_pair, 1) // block))
    cap_out = _pow2(recv_max)
    with _phase("shuffle.exchange", seq):
        out, new_emit = _exchange_fn(ctx.mesh, block, rounds, cap_out)(
            payload, targets, emit)
    return out, new_emit, cap_out
