"""The shuffle: hash-partition + all-to-all exchange on XLA collectives.

This is the TPU-native replacement for the reference's entire four-layer
communication stack (reference: cpp/src/cylon/net/mpi/mpi_channel.cpp:30-247
two-phase header+body MPI protocol with per-peer FSMs; net/ops/
all_to_all.cpp:26-178 queue/FIN machinery; arrow/arrow_all_to_all.cpp:24-264
per-buffer Arrow serialization). None of that machinery is translated:
inside one compiled SPMD program, `jax.lax.all_to_all` over the mesh axis IS
the transport, XLA program order replaces MPI tags/edges, and program
completion replaces the FIN handshake.

The reference's variable-length problem (its 8-int length header preceding
every body message) maps to the static-shape world as a TWO-PHASE exchange:

  phase 1 ("header"): a tiny compiled program computes the per-(src,dst)
     send-count matrix — one [W] vector per shard, gathered to the host;
  phase 2 ("body"):   the host picks a pow2 block size B = max count (this
     bounds recompilation to O(log) distinct programs), and a second
     compiled program bucket-sorts rows by target shard, scatters them into
     a [W, B] send buffer per column, and runs ONE `all_to_all` per column
     over ICI. Padding slots carry emit=False.

Rows whose emit mask is False (table padding, filtered rows) are dropped in
transit — the shuffle doubles as a compaction step.
"""
from __future__ import annotations

from functools import lru_cache, partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

try:  # jax>=0.8
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from ..context import CylonContext
from ..telemetry import phase as _phase
from ..util import pow2 as _pow2
from .shard import row_sharding


@lru_cache(maxsize=None)
def _count_fn(mesh):
    """Per-shard send-count vector: counts[t] = live rows headed to shard t.

    The moral equivalent of the reference's header phase
    (mpi_channel.cpp:211-225 sendHeader)."""
    axis = mesh.axis_names[0]
    world = mesh.devices.size
    spec = P(axis)

    def kernel(targets, emit):
        t = jnp.where(emit, targets.astype(jnp.int32), world)
        counts = jax.ops.segment_sum(jnp.ones(t.shape[0], jnp.int32), t,
                                     num_segments=world + 1)
        return counts[:world]

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec, spec),
                             out_specs=spec))


@lru_cache(maxsize=None)
def _exchange_fn(mesh, block: int):
    """The body phase: bucket-sort by target, scatter to [W, B] blocks,
    one `all_to_all` per payload leaf, flatten back to [W*B] rows."""
    axis = mesh.axis_names[0]
    world = mesh.devices.size
    spec = P(axis)

    def kernel(payload, targets, emit):
        n = targets.shape[0]
        iota = jnp.arange(n, dtype=jnp.int32)
        t = jnp.where(emit, targets.astype(jnp.int32), world)
        # stable bucket sort by target: one fused device sort yields the
        # permutation every column reuses (the reference's per-dtype split
        # kernels, arrow_kernels.cpp:24-134, collapse into this one sort)
        t_sorted, perm = jax.lax.sort((t, iota), num_keys=1)
        counts = jax.ops.segment_sum(jnp.ones(n, jnp.int32), t,
                                     num_segments=world + 1)[:world]
        start = jnp.cumsum(counts) - counts
        pos = iota - jnp.take(start, jnp.minimum(t_sorted, world - 1))
        flat = jnp.where(t_sorted < world, t_sorted * block + pos,
                         world * block)  # out-of-range -> dropped

        def exchange_leaf(x):
            xs = jnp.take(x, perm, axis=0)
            buf = jnp.zeros((world * block,) + x.shape[1:], x.dtype)
            buf = buf.at[flat].set(xs, mode="drop")
            buf = buf.reshape((world, block) + x.shape[1:])
            out = jax.lax.all_to_all(buf, axis, split_axis=0, concat_axis=0,
                                     tiled=False)
            return out.reshape((world * block,) + x.shape[1:])

        return jax.tree.map(exchange_leaf, payload)

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(spec, spec, spec),
                             out_specs=spec))


def exchange(payload: Dict[str, jnp.ndarray], targets: jnp.ndarray,
             emit: jnp.ndarray, ctx: CylonContext
             ) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray, int]:
    """Shuffle a pytree of row-sharded per-row arrays to their target shards.

    Returns (exchanged payload, new emit mask, per-shard capacity). All
    outputs are row-sharded; capacity = W * B where B is the pow2 block.
    """
    world = ctx.get_world_size()
    if "__emit__" in payload:
        raise ValueError("__emit__ is a reserved payload key")
    seq = ctx.get_next_sequence()
    with _phase("shuffle.count", seq):
        counts = np.asarray(jax.device_get(_count_fn(ctx.mesh)(targets,
                                                               emit)))
    block = _pow2(int(counts.max()) if counts.size else 1)
    full = dict(payload)
    full["__emit__"] = emit
    with _phase("shuffle.exchange", seq):
        out = _exchange_fn(ctx.mesh, block)(full, targets, emit)
    new_emit = out.pop("__emit__")
    return out, new_emit, world * block
