"""Row-sharding of tables over the device mesh.

The reference's distribution model is "one ragged Arrow table per MPI rank"
(reference: cpp/src/cylon/ctx/cylon_context.hpp:29 — rank/world_size; every
distributed op is a collective all ranks enter). The TPU-native model keeps
ONE global Table whose column arrays carry a `jax.sharding.NamedSharding`
over the 1-D mesh axis: shard i of every array is partition i. Raggedness
is expressed by padding every shard to one common capacity and masking the
padding rows via the table's ``row_mask`` — XLA requires static, equal
shapes per shard; the mask is the moral equivalent of Arrow's per-rank row
counts.

`distribute` is the entry point: pad → device_put with the row sharding.
It is a no-op for tables already laid out on the context's mesh, so eager
op pipelines don't re-transfer.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..context import CylonContext
from ..data.column import Column
from ..data.table import Table
from ..status import Code, CylonPlanError
from ..telemetry import record_host_sync as _host_sync

# Per-shard capacities are rounded to a multiple of 8 (TPU sublane quantum)
_ROW_QUANTUM = 8


def row_sharding(ctx: CylonContext) -> NamedSharding:
    """The canonical row-partitioned sharding for this context's mesh."""
    return NamedSharding(ctx.mesh, P(ctx.axis))


def is_row_sharded(arr, ctx: CylonContext) -> bool:
    sh = getattr(arr, "sharding", None)
    if not isinstance(sh, NamedSharding):
        return False
    return sh.mesh == ctx.mesh and sh.spec == P(ctx.axis)


def is_distributed_table(table: Table, ctx: CylonContext) -> bool:
    if not table._columns:
        return False
    n = table.capacity
    if n % ctx.get_world_size() != 0:
        return False
    return all(is_row_sharded(c.data, ctx) for c in table._columns)


def pin(arr, ctx: CylonContext):
    """Force an array onto the row sharding (no-op when already there).

    Eager elementwise ops usually preserve sharding, but host-built or
    gather-produced arrays may not carry it — pin before entering a
    shard_map kernel."""
    if is_row_sharded(arr, ctx):
        return arr
    return jax.device_put(arr, row_sharding(ctx))


def shard_capacity(n: int, world: int) -> int:
    """Per-shard padded capacity for n global rows."""
    c = -(-max(n, 1) // world)
    return -(-c // _ROW_QUANTUM) * _ROW_QUANTUM


def _pad_to(arr: jnp.ndarray, total: int, fill):
    n = arr.shape[0]
    if n == total:
        return arr
    pad = jnp.full((total - n,) + arr.shape[1:], fill, arr.dtype)
    return jnp.concatenate([arr, pad])


def distribute(table: Table, ctx: CylonContext) -> Table:
    """Shard a table's rows over the context mesh (pad + device_put).

    Already-distributed tables pass through untouched. The result's
    ``row_mask`` marks padding rows dead; real rows keep their validity.
    """
    if is_distributed_table(table, ctx):
        return table
    world = ctx.get_world_size()
    n = table.capacity
    cap = shard_capacity(n, world)
    total = world * cap
    sharding = row_sharding(ctx)

    cols = []
    for c in table._columns:
        if c.is_varbytes:
            cols.append(_distribute_varbytes(c, n, cap, world, sharding))
            continue
        data = jax.device_put(_pad_to(c.data, total, 0), sharding)
        validity = None
        if c.validity is not None:
            validity = jax.device_put(_pad_to(c.validity, total, False), sharding)
        cols.append(Column(data, c.dtype, validity, c.dictionary, c.name))
    if table.row_mask is None and total == n:
        # no padding, all rows live: preserve mask-None — downstream
        # routing reads "row_mask is None" as the dense invariant (the
        # count-free fused world-1 exchange keys on it)
        mask = None
    else:
        mask = jax.device_put(_pad_to(table.emit_mask(), total, False),
                              sharding)
    return Table(cols, ctx, mask)


def _distribute_varbytes(c: Column, n: int, cap: int, world: int,
                         sharding) -> Column:
    """Shard a varbytes column: each shard gets a SELF-CONTAINED local
    (words, starts, lengths) layout — starts are shard-relative word
    indices, so per-shard kernels (hash, take) run with no cross-shard
    word addressing. Shards' word buffers pad to a common capacity."""
    from ..data.strings import VarBytes
    from ..util import capacity as _capacity

    vb = c.varbytes
    # one device_get + numpy slicing + one device_put: each shard's rows
    # are a CONTIGUOUS row range, so its words are a contiguous slice of
    # the source buffer (monotone starts) — no per-shard device gathers
    words_h = np.asarray(jax.device_get(vb.words))
    starts_h = np.asarray(jax.device_get(vb.eff_starts()))
    lens_h = np.asarray(jax.device_get(vb.lengths))
    _host_sync("distribute.varbytes", 3)
    nw_h = (lens_h.astype(np.int64) + 3) // 4
    slices = []
    for s in range(world):
        lo, hi = s * cap, min((s + 1) * cap, n)
        if lo >= hi:
            slices.append((0, 0, lo, hi))
            continue
        w_lo = int(starts_h[lo])
        w_hi = int(starts_h[hi - 1] + nw_h[hi - 1])
        slices.append((w_lo, w_hi, lo, hi))
    wc = _capacity(max(max(w_hi - w_lo for w_lo, w_hi, _l, _h in slices), 1))
    words = np.zeros(world * wc, np.uint32)
    starts = np.zeros(world * cap, np.int32)
    lengths = np.zeros(world * cap, np.int32)
    for s, (w_lo, w_hi, lo, hi) in enumerate(slices):
        words[s * wc: s * wc + (w_hi - w_lo)] = words_h[w_lo:w_hi]
        starts[s * cap: s * cap + (hi - lo)] = starts_h[lo:hi] - w_lo
        lengths[s * cap: s * cap + (hi - lo)] = lens_h[lo:hi]
    out_vb = VarBytes(jax.device_put(jnp.asarray(words), sharding),
                      jax.device_put(jnp.asarray(starts), sharding),
                      jax.device_put(jnp.asarray(lengths), sharding),
                      vb.max_words, world * wc, shard_geom=(cap, wc))
    validity = None
    if c.validity is not None:
        validity = jax.device_put(
            _pad_to(c.validity, world * cap, False), sharding)
    return Column(out_vb.lengths, c.dtype, validity, None, c.name,
                  varbytes=out_vb)


def distribute_array(arr, n_src_rows: int, ctx: CylonContext,
                     fill=0) -> jnp.ndarray:
    """Shard an auxiliary per-row array with the same padding geometry a
    table of ``n_src_rows`` rows gets from `distribute`."""
    world = ctx.get_world_size()
    cap = shard_capacity(n_src_rows, world)
    return jax.device_put(_pad_to(jnp.asarray(arr), world * cap, fill),
                          row_sharding(ctx))


def partition_signature(key_cols, idxs, world: int):
    """Hashable co-partitioning witness: a table whose rows were placed
    by hash of these key columns can skip a later shuffle on the same
    keys — but only when the key dtypes at join time match the dtypes
    hashed at placement time (align_key_columns may promote), and never
    for strings (vocabulary unification re-codes them)."""
    if any(c.is_string for c in key_cols):
        return None
    return (tuple(int(i) for i in idxs),
            tuple(str(c.data.dtype) for c in key_cols), int(world))


def host_partition_arrays(t: Table, idxs, world: int):
    """Shared host-side partition preamble: pull a COMPACTED table's
    columns to host, run the native partitioner over its key columns,
    and return (host_cols, valids, counts, order, offsets). Used by both
    distribute_by_key and dist_ops.hash_partition so placement logic
    lives in exactly one place.

    Varbytes columns come to host as object arrays; varbytes KEY columns
    hash their actual BYTES through the host mirror of the device
    content hash (native.np_varbytes_hash == strings._hash_rows h1), so
    placement is a pure function of key VALUES — equal keys in two
    independently built tables land on the same partition, and the host
    fallback agrees with the device hash_partition path. (ADVICE r5
    medium: the previous table-local np.unique dictionary codes made
    placement depend on each table's whole key set.)"""
    from .. import native as _native
    from ..dtypes import Type

    host = []
    for c in t._columns:
        if c.is_varbytes:
            host.append(c.varbytes.to_host(
                as_str=c.dtype.type != Type.BINARY))
        else:
            host.append(np.asarray(jax.device_get(c.data)))
    valids = [None if c.validity is None
              else np.asarray(jax.device_get(c.valid_mask()))
              for c in t._columns]
    _host_sync("ingest.host_partition",
               len(host) + sum(v is not None for v in valids))
    keys = []
    pre = []
    for i in idxs:
        if t._columns[i].is_varbytes:
            keys.append(_native.np_varbytes_hash(host[i]))
            pre.append(True)
        else:
            keys.append(host[i])
            pre.append(False)
    flags = [False if p else t._columns[i].is_string
             for i, p in zip(idxs, pre)]
    _targets, counts, order = _native.hash_partition(
        keys, [valids[i] for i in idxs], world, is_string=flags,
        prehashed=pre)
    offs = np.concatenate([[0], np.cumsum(counts)])
    return host, valids, counts, order, offs


def distribute_by_key(table: Table, ctx: CylonContext, key_columns) -> Table:
    """Host-side pre-partitioned ingest: place every row on the shard its
    key HASHES to (the placement a device shuffle would produce), using
    the native partitioner (native/cylon_host.cpp ct_row_hash /
    ct_partition_order — bit-identical to ops/hash.partition_targets).

    The result carries a co-partitioning witness, so `shuffle` on the
    same keys is a no-op and `distributed_join` skips that side's
    exchange — the ingest-time analog of the reference shuffling inside
    DistributedJoin (table.cpp:656-696), moved off the device entirely.
    """
    world = ctx.get_world_size()
    idxs = [table._col_index(c) for c in key_columns]
    t = table.compact()
    key_cols = [t._columns[i] for i in idxs]
    host, valids, counts, order, offs = host_partition_arrays(t, idxs, world)

    cap = shard_capacity(int(counts.max()), 1)
    total = world * cap
    sharding = row_sharding(ctx)

    def build(arr, fill, dtype=None):
        a = np.asarray(arr)
        g = a[order]
        out = np.full((total,) + a.shape[1:], fill,
                      a.dtype if dtype is None else dtype)
        for s in range(world):
            out[s * cap:s * cap + counts[s]] = g[offs[s]:offs[s + 1]]
        return jax.device_put(jnp.asarray(out), sharding)

    if any(c.is_varbytes for c in t._columns):
        # varbytes rows can't lift through the fixed-width build():
        # materialize each shard's rows as a host table (VarBytes
        # rebuilt from the partitioned object arrays) and assemble —
        # shard i of the result holds partition i, same placement
        from ..data.strings import VarBytes

        if ctx.get_process_count() > 1:
            raise CylonPlanError(
                "multi-host distribute_by_key with varbytes columns: "
                "use per-rank file placement (read_csv_per_rank)",
                code=Code.NotImplemented)

        shard_tables = []
        for s in range(world):
            seg = order[offs[s]:offs[s + 1]]
            cols = []
            for ci, c in enumerate(t._columns):
                v = None if valids[ci] is None \
                    else jnp.asarray(valids[ci][seg])
                if c.is_varbytes:
                    vb = VarBytes.from_host(host[ci][seg])
                    cols.append(Column(vb.lengths, c.dtype, v, None,
                                       c.name, varbytes=vb))
                else:
                    cols.append(Column(jnp.asarray(host[ci][seg]),
                                       c.dtype, v, c.dictionary, c.name))
            shard_tables.append(Table(cols, ctx))
        out = assemble_process_local(shard_tables, ctx)
        out._hash_partitioned = partition_signature(key_cols, idxs, world)
        return out

    cols = []
    for ci, c in enumerate(t._columns):
        data = build(host[ci], 0)
        validity = None if valids[ci] is None else build(valids[ci], False)
        cols.append(Column(data, c.dtype, validity, c.dictionary, c.name))
    emit = np.zeros(total, np.bool_)
    for s in range(world):
        emit[s * cap:s * cap + counts[s]] = True
    out = Table(cols, ctx, jax.device_put(jnp.asarray(emit), sharding))
    out._hash_partitioned = partition_signature(key_cols, idxs, world)
    return out


def assemble_process_local(tables, ctx: CylonContext) -> Table:
    """Build ONE global distributed Table from per-shard host tables, one
    per shard this process owns (the multi-host ingest path: the
    reference's per-rank CSV convention, cpp/test/join_test.cpp:22-24,
    maps to per-shard files read by the owning controller).

    Every process calls this collectively with its own local shard list
    (len == len(ctx.local_shard_indices())). Per-shard row counts may be
    ragged; shards are padded to the global max (agreed via a tiny
    all-gathered count exchange) and the padding is masked dead.

    String columns are lifted to device-native varbytes storage
    (data/strings.py): content hashes need NO global vocabulary, so
    every process ingests its strings independently — the reference's
    per-rank binary columns (arrow_partition_kernels.hpp:94) with zero
    cross-process coordination beyond the word-capacity agreement.
    """
    from jax.experimental import multihost_utils

    from ..data.column import as_varbytes
    from ..util import capacity as _capacity

    local = ctx.local_shard_indices()
    if len(tables) != len(local):
        raise CylonPlanError(
            f"need one table per local shard ({len(local)}), "
            f"got {len(tables)}")
    tables = [t.compact() for t in tables]

    first = tables[0]
    vb_cols = [ci for ci in range(first.column_count)
               if any(t._columns[ci].is_string for t in tables)]
    # lift once; the counts matrix AND the buffer assembly reuse these
    lifted = {ci: [as_varbytes(t._columns[ci]) for t in tables]
              for ci in vb_cols}

    # rows AND per-string-column word counts agree via one allgather
    counts = np.array(
        [[t.capacity for t in tables]]
        + [[c.varbytes.total_words for c in lifted[ci]]
           for ci in vb_cols], np.int64)
    if ctx.get_process_count() > 1:
        all_counts = np.asarray(multihost_utils.process_allgather(
            counts.T.copy())).reshape(-1, counts.shape[0]).T
    else:
        all_counts = counts
    cap = -(-int(all_counts[0].max()) // _ROW_QUANTUM) * _ROW_QUANTUM
    cap = max(cap, _ROW_QUANTUM)
    word_caps = {ci: _capacity(max(int(all_counts[1 + k].max()), 1))
                 for k, ci in enumerate(vb_cols)}

    sharding = row_sharding(ctx)
    world = ctx.get_world_size()

    def build(arrays, fill, pad_len=None):
        """Pad each local shard's array to a common length, stack, and
        lift to the global sharded array."""
        tgt = cap if pad_len is None else pad_len
        blocks = []
        for arr in arrays:
            a = np.asarray(arr)
            if a.shape[0] < tgt:
                pad = np.full((tgt - a.shape[0],) + a.shape[1:], fill,
                              a.dtype)
                a = np.concatenate([a, pad])
            blocks.append(a)
        local_np = np.ascontiguousarray(np.concatenate(blocks))
        if ctx.get_process_count() == 1:
            return jax.device_put(jnp.asarray(local_np), sharding)
        return jax.make_array_from_process_local_data(
            sharding, local_np, (world * tgt,) + local_np.shape[1:])

    cols = []
    for ci in range(first.column_count):
        ref = first._columns[ci]
        if ci in vb_cols:
            from ..data.strings import VarBytes

            parts = [c.varbytes for c in lifted[ci]]
            wc = word_caps[ci]
            words = build([np.asarray(jax.device_get(
                p.words[:p.total_words])) for p in parts], 0, pad_len=wc)
            starts = build([np.asarray(jax.device_get(p.starts))
                            for p in parts], 0)
            lengths = build([np.asarray(jax.device_get(p.lengths))
                             for p in parts], 0)
            max_words = max(p.max_words for p in parts)
            if ctx.get_process_count() > 1:
                max_words = int(np.asarray(multihost_utils.process_allgather(
                    np.array([max_words]))).max())
            vb = VarBytes(words, starts, lengths, max_words, world * wc,
                          shard_geom=(cap, wc))
            validity = None
            if any(t._columns[ci].validity is not None for t in tables):
                validity = build(
                    [jax.device_get(t._columns[ci].valid_mask())
                     for t in tables], False)
            cols.append(Column(vb.lengths, ref.dtype, validity, None,
                               ref.name, varbytes=vb))
            continue
        data = build([jax.device_get(t._columns[ci].data) for t in tables],
                     0)
        validity = None
        if any(t._columns[ci].validity is not None for t in tables):
            validity = build(
                [jax.device_get(t._columns[ci].valid_mask())
                 for t in tables], False)
        cols.append(Column(data, ref.dtype, validity, None, ref.name))
    emit = build([np.ones(t.capacity, np.bool_) for t in tables], False)
    return Table(cols, ctx, emit)


def _local_blocks(arr) -> list:
    """This process's shards of a row-sharded array, as numpy blocks in
    global shard order."""
    shards = sorted(arr.addressable_shards,
                    key=lambda s: (s.index[0].start or 0) if s.index else 0)
    return [np.asarray(s.data) for s in shards]


def extract_process_local(table: Table, ctx: CylonContext) -> dict:
    """Host numpy dict of THIS process's shards' live rows — the
    per-process handoff out of a distributed table (the export mirror of
    `assemble_process_local`). Each controller process of a multi-host
    mesh gets exactly its own shards, so a DDP training loop can feed
    its accelerator without any global gather (reference:
    demo_pytorch_distributed.py:1-50 feeds each rank its pycylon
    partition; python/examples/cylon_sequential_mnist.py).

    Varbytes columns decode per shard: their starts are SHARD-RELATIVE
    by invariant (strings.py shard_geom), so each addressable word block
    pairs with its row block with no global gather."""
    from ..dtypes import Type

    t = table
    n_local = None
    out = {}
    for name, c in zip(t._unique_names(), t._columns):
        if c.is_varbytes:
            vb = c.varbytes
            vals = []
            for wb, sb, lb in zip(_local_blocks(vb.words),
                                  _local_blocks(vb.starts),
                                  _local_blocks(vb.lengths)):
                raw = np.ascontiguousarray(wb).view(np.uint8).tobytes()
                for s, ln in zip(sb.tolist(), lb.tolist()):
                    b = raw[4 * s: 4 * s + ln]
                    vals.append(b if c.dtype.type == Type.BINARY
                                else b.decode("utf-8", errors="replace"))
            vals = np.array(vals, dtype=object)
            n_local = vals.shape[0]
            if c.validity is not None:
                m = np.concatenate(_local_blocks(c.validity))
                vals[~m] = None
            out[name] = vals
            continue
        d = np.concatenate(_local_blocks(c.data))
        n_local = d.shape[0]
        vals = c.dictionary[d].astype(object) if c.is_string else d
        if c.validity is not None:
            m = np.concatenate(_local_blocks(c.validity))
            if vals.dtype.kind == "f":
                vals = vals.copy()
                vals[~m] = np.nan
            else:
                vals = vals.astype(object)
                vals[~m] = None
        out[name] = vals
    if t.row_mask is not None:
        em = np.concatenate(_local_blocks(t.row_mask))
    else:
        em = np.ones(n_local if n_local is not None else 0, bool)
    return {k: v[em] for k, v in out.items()}
