"""Distributed layer: sharding, shuffle (XLA all_to_all), distributed ops.

Replaces the reference's net/ + arrow-comm stack (reference:
cpp/src/cylon/net/, cpp/src/cylon/arrow/arrow_all_to_all.cpp) with compiled
SPMD programs over a `jax.sharding.Mesh`.
"""
from . import dist_ops, shard, shuffle
from .dist_ops import (distributed_groupby, distributed_join,
                       distributed_set_op, distributed_sort, hash_partition,
                       repartition)
from .dist_ops import shuffle as shuffle_table
from .shard import (distribute, distribute_by_key, is_distributed_table,
                    row_sharding)

__all__ = [
    "dist_ops", "distribute", "distribute_by_key", "distributed_groupby",
    "distributed_join", "distributed_set_op", "distributed_sort",
    "hash_partition", "is_distributed_table", "repartition", "row_sharding",
    "shard", "shuffle", "shuffle_table",
]
