"""CylonContext — the entry point object.

Mirrors the reference's CylonContext (reference: cpp/src/cylon/ctx/
cylon_context.hpp:29-146 — Init/InitDistributed, GetRank/GetWorldSize,
GetNextSequence, Barrier, string config map) re-designed for the TPU
execution model:

* an MPI *world of W processes* becomes a *1-D device mesh of W chips*
  driven by one controller process per host (SPMD via shard_map/pjit);
* ``rank``/``world_size`` become mesh coordinates; on multi-host meshes the
  controller's ``jax.process_index()`` plays the reference's node-rank role
  for file IO placement;
* ``Barrier`` becomes a device synchronization (block_until_ready on a tiny
  psum) — program order inside XLA replaces MPI tag ordering;
* ``GetNextSequence`` survives as the op-sequence counter used to key
  shuffle "edges" for tracing/profiling (the reference used it as the MPI
  tag: cylon_context.cpp:94-99).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .config import CommConfig, CommType, LocalConfig, TPUConfig, MultiHostConfig
from .status import Code, CylonError

_AXIS = "p"  # the canonical 1-D mesh axis name for row partitioning


def _distributed_initialized() -> bool:
    """True when jax.distributed.initialize has already run (idempotence
    guard that — unlike jax.process_count() — does not itself initialise
    the XLA backend)."""
    try:
        from jax._src import distributed as _jd

        return getattr(_jd.global_state, "client", None) is not None
    except Exception:  # pragma: no cover - jax internals moved  # cylint: disable=errors/broad-swallow — jax internals moved: treat as uninitialized
        return False


class CylonContext:
    """Holds the device mesh, distributed flag and op sequence counter."""

    def __init__(self, config: Optional[CommConfig] = None, distributed: bool = False):
        # pycylon parity: CylonContext(config=MPIConfig(), distributed=True)
        # (python/pycylon/ctx/context.pyx:29-75)
        self._config_map: Dict[str, str] = {}
        self._sequence = 0
        self._lock = threading.Lock()
        self._finalized = False

        if config is None and not distributed:
            config = LocalConfig()
        elif config is None:
            config = TPUConfig()

        self.comm_config = config
        ct = config.comm_type()
        self.distributed = distributed and ct != CommType.LOCAL

        if ct == CommType.MULTIHOST:
            cfg: MultiHostConfig = config  # type: ignore[assignment]
            if cfg.num_processes not in (None, 1) \
                    and not _distributed_initialized():
                # must run before ANY backend-initialising jax call
                # (jax.process_count() itself would initialise it)
                jax.distributed.initialize(
                    coordinator_address=cfg.coordinator_address,
                    num_processes=cfg.num_processes,
                    process_id=cfg.process_id,
                )
            devices = jax.devices()
        elif ct == CommType.TPU:
            cfg2: TPUConfig = config  # type: ignore[assignment]
            devices = list(cfg2.devices) if cfg2.devices is not None else jax.devices()
            if cfg2.world_size is not None:
                if cfg2.world_size > len(devices):
                    raise CylonError(
                        Code.Invalid,
                        f"world_size {cfg2.world_size} > available devices {len(devices)}")
                devices = devices[: cfg2.world_size]
        else:
            devices = [jax.devices()[0]]

        if not self.distributed:
            devices = devices[:1]

        self.devices: List = devices
        self.mesh = jax.sharding.Mesh(np.array(devices), (_AXIS,))

        from .memory import MemoryPool
        from . import telemetry as _telemetry

        self.memory_pool = MemoryPool(
            [d for d in devices
             if d.process_index == jax.process_index()])
        # observability wiring: on backends that hide memory_stats the
        # pool falls back to the ledger's tracked-table bytes (self-
        # accounting instead of blindness), and the span layer samples
        # this pool for per-span hbm_delta/hbm_peak attrs + the flight
        # recorder's crash-dump watermarks
        self.memory_pool.set_external_source(_telemetry.ledger.live_bytes)
        _telemetry.set_memory_pool(self.memory_pool)

    # -- reference API (cylon_context.hpp) --

    @staticmethod
    def Init() -> "CylonContext":
        """Local (single-device) context. Reference: CylonContext::Init."""
        return CylonContext(LocalConfig(), distributed=False)

    @staticmethod
    def InitDistributed(config: Optional[CommConfig] = None) -> "CylonContext":
        """Distributed context over the device mesh.

        Reference: CylonContext::InitDistributed (cylon_context.cpp:32-43).
        """
        return CylonContext(config or TPUConfig(), distributed=True)

    def get_world_size(self) -> int:
        """Number of mesh devices (reference: GetWorldSize = MPI world size).

        An MPI rank maps to a mesh SHARD here, so world = shard count, not
        process count (one controller process drives many chips)."""
        return len(self.devices)

    def get_rank(self) -> int:
        """This controller's first shard index in the mesh (shard space —
        consistent with `get_neighbours`). Single-controller meshes always
        return 0; on multi-host meshes each process owns a contiguous run
        of shards and `get_rank` is the first of them. For file placement
        use `get_process_rank`/`local_shard_indices`."""
        local = self.local_shard_indices()
        return local[0] if local else 0

    def get_process_rank(self) -> int:
        """Controller process index (the reference's node-rank role for
        per-rank file IO; reference: cpp/test/join_test.cpp:22-24)."""
        return jax.process_index()

    def get_process_count(self) -> int:
        return jax.process_count()

    def local_shard_indices(self) -> List[int]:
        """Shard indices whose device is addressable from this process."""
        me = jax.process_index()
        return [i for i, d in enumerate(self.devices)
                if d.process_index == me]

    def get_neighbours(self, include_self: bool = False) -> List[int]:
        """All other shard indices, optionally including this controller's
        own (reference: GetNeighbours, cylon_context.cpp:77-86)."""
        w = self.get_world_size()
        me = self.get_rank()
        return [i for i in range(w) if include_self or i != me]

    def get_next_sequence(self) -> int:
        """Monotonic op id — the reference used it as the MPI comm tag
        (cylon_context.cpp:94-99); we key profiler annotations with it."""
        with self._lock:
            self._sequence += 1
            return self._sequence

    def barrier(self) -> None:
        """Synchronize all devices (reference: MPI_Barrier). Runs one tiny
        SPMD program over the whole mesh — multi-host safe (a per-device
        device_put would fail on non-addressable devices)."""
        if self._finalized:
            return
        from jax.sharding import NamedSharding, PartitionSpec as P

        out = jax.jit(lambda: jnp.zeros((), jnp.int32) + 1,
                      out_shardings=NamedSharding(self.mesh, P()))()
        jax.block_until_ready(out)

    def finalize(self) -> None:
        self._finalized = True

    def is_distributed(self) -> bool:
        return self.distributed

    # string config map (cylon_context.hpp:31)
    def add_config(self, key: str, value: str) -> None:
        self._config_map[key] = value

    def get_config(self, key: str, default: str = "") -> str:
        return self._config_map.get(key, default)

    # -- TPU-native additions --

    @property
    def axis(self) -> str:
        return _AXIS

    # PascalCase aliases for reference-style call sites
    GetRank = get_rank
    GetWorldSize = get_world_size
    GetNextSequence = get_next_sequence
    Barrier = barrier
    Finalize = finalize
