"""CylonContext — the entry point object.

Mirrors the reference's CylonContext (reference: cpp/src/cylon/ctx/
cylon_context.hpp:29-146 — Init/InitDistributed, GetRank/GetWorldSize,
GetNextSequence, Barrier, string config map) re-designed for the TPU
execution model:

* an MPI *world of W processes* becomes a *1-D device mesh of W chips*
  driven by one controller process per host (SPMD via shard_map/pjit);
* ``rank``/``world_size`` become mesh coordinates; on multi-host meshes the
  controller's ``jax.process_index()`` plays the reference's node-rank role
  for file IO placement;
* ``Barrier`` becomes a device synchronization (block_until_ready on a tiny
  psum) — program order inside XLA replaces MPI tag ordering;
* ``GetNextSequence`` survives as the op-sequence counter used to key
  shuffle "edges" for tracing/profiling (the reference used it as the MPI
  tag: cylon_context.cpp:94-99).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

import jax
import numpy as np

from .config import CommConfig, CommType, LocalConfig, TPUConfig, MultiHostConfig
from .status import Code, CylonError

_AXIS = "p"  # the canonical 1-D mesh axis name for row partitioning


class CylonContext:
    """Holds the device mesh, distributed flag and op sequence counter."""

    def __init__(self, config: Optional[CommConfig] = None, distributed: bool = False):
        # pycylon parity: CylonContext(config=MPIConfig(), distributed=True)
        # (python/pycylon/ctx/context.pyx:29-75)
        self._config_map: Dict[str, str] = {}
        self._sequence = 0
        self._lock = threading.Lock()
        self._finalized = False

        if config is None and not distributed:
            config = LocalConfig()
        elif config is None:
            config = TPUConfig()

        self.comm_config = config
        ct = config.comm_type()
        self.distributed = distributed and ct != CommType.LOCAL

        if ct == CommType.MULTIHOST:
            cfg: MultiHostConfig = config  # type: ignore[assignment]
            if jax.process_count() == 1 and cfg.num_processes not in (None, 1):
                jax.distributed.initialize(
                    coordinator_address=cfg.coordinator_address,
                    num_processes=cfg.num_processes,
                    process_id=cfg.process_id,
                )
            devices = jax.devices()
        elif ct == CommType.TPU:
            cfg2: TPUConfig = config  # type: ignore[assignment]
            devices = list(cfg2.devices) if cfg2.devices is not None else jax.devices()
            if cfg2.world_size is not None:
                if cfg2.world_size > len(devices):
                    raise CylonError(
                        Code.Invalid,
                        f"world_size {cfg2.world_size} > available devices {len(devices)}")
                devices = devices[: cfg2.world_size]
        else:
            devices = [jax.devices()[0]]

        if not self.distributed:
            devices = devices[:1]

        self.devices: List = devices
        self.mesh = jax.sharding.Mesh(np.array(devices), (_AXIS,))

    # -- reference API (cylon_context.hpp) --

    @staticmethod
    def Init() -> "CylonContext":
        """Local (single-device) context. Reference: CylonContext::Init."""
        return CylonContext(LocalConfig(), distributed=False)

    @staticmethod
    def InitDistributed(config: Optional[CommConfig] = None) -> "CylonContext":
        """Distributed context over the device mesh.

        Reference: CylonContext::InitDistributed (cylon_context.cpp:32-43).
        """
        return CylonContext(config or TPUConfig(), distributed=True)

    def get_world_size(self) -> int:
        """Number of mesh devices (reference: GetWorldSize = MPI world size)."""
        return len(self.devices)

    def get_rank(self) -> int:
        """Controller process index. In the reference every rank is a process;
        here one controller drives all local chips, so `rank` is only
        meaningful for multi-host file placement."""
        return jax.process_index()

    def get_neighbours(self, include_self: bool = False) -> List[int]:
        """All other shard indices, optionally including this controller's
        own (reference: GetNeighbours, cylon_context.cpp:77-86)."""
        w = self.get_world_size()
        me = self.get_rank()
        return [i for i in range(w) if include_self or i != me]

    def get_next_sequence(self) -> int:
        """Monotonic op id — the reference used it as the MPI comm tag
        (cylon_context.cpp:94-99); we key profiler annotations with it."""
        with self._lock:
            self._sequence += 1
            return self._sequence

    def barrier(self) -> None:
        """Synchronize all devices (reference: MPI_Barrier)."""
        if self._finalized:
            return
        xs = [jax.device_put(np.zeros((), np.int32), d) for d in self.devices]
        jax.block_until_ready([x + 1 for x in xs])

    def finalize(self) -> None:
        self._finalized = True

    def is_distributed(self) -> bool:
        return self.distributed

    # string config map (cylon_context.hpp:31)
    def add_config(self, key: str, value: str) -> None:
        self._config_map[key] = value

    def get_config(self, key: str, default: str = "") -> str:
        return self._config_map.get(key, default)

    # -- TPU-native additions --

    @property
    def axis(self) -> str:
        return _AXIS

    # PascalCase aliases for reference-style call sites
    GetRank = get_rank
    GetWorldSize = get_world_size
    GetNextSequence = get_next_sequence
    Barrier = barrier
    Finalize = finalize
