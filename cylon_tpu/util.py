"""Small shared helpers."""
from __future__ import annotations


def pow2(n: int) -> int:
    """Round up to a power of two (≥1). All data-dependent capacities are
    pow2-rounded so the count→materialize discipline compiles O(log n)
    distinct programs instead of one per size."""
    return 1 if n <= 1 else 1 << (int(n) - 1).bit_length()
