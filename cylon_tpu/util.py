"""Small shared helpers."""
from __future__ import annotations


def pow2(n: int) -> int:
    """Round up to a power of two (≥1). All data-dependent capacities are
    pow2-rounded so the count→materialize discipline compiles O(log n)
    distinct programs instead of one per size."""
    return 1 if n <= 1 else 1 << (int(n) - 1).bit_length()


def pow2_floor(n: int) -> int:
    """Round DOWN to a power of two (≥1) — the budget-shrink direction:
    a comm-buffer cap halved to fit stays a pow2, so the block sizes it
    feeds into kernel-factory cache keys keep 1-per-octave cardinality
    (the specialization analysis recognizes this helper)."""
    return 1 << (max(int(n), 1).bit_length() - 1)


def capacity(n: int) -> int:
    """Static-capacity rounding with a 4-bit mantissa: the smallest
    s * 2^e ≥ n with s ∈ [17, 32]. Overshoot ≤ 6.25% (vs up to 100% for
    pow2) while still bounding distinct compiled programs to 16 per
    octave. Used for OUTPUT capacities on the hot path, where every
    padded row costs real gather/scan work."""
    n = max(int(n), 1)
    if n <= 16:
        return pow2(n)
    e = max((n - 1).bit_length() - 5, 0)
    s = -(-n // (1 << e))
    return s << e
