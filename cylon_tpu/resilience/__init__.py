"""Resilient query execution: fault injection, retry, admission.

The reference Cylon has no resilience story at all — an MPI rank
failure aborts the whole job (reference: any `MPI_Abort` path). On a
real TPU pod every distributed op is partition + all-to-all + local
kernel (PAPER.md layer map), and each stage can fail transiently: a
preempted ICI collective, a compile OOM, HBM exhaustion. This package
makes those failures survivable AND provable:

* ``inject``    — deterministic fault injection: seeded, env-driven
  fault plans (``CYLON_FAULT_PLAN="exchange:2:transient"``) fire typed
  errors at named choke points (exchange launch, kernel-factory build,
  admission budget, ingest), so every chaos run replays by seed
  (scripts/chaos.py is the drill driver).
* ``retry``     — bounded retry-with-backoff around retryable stages
  (``cylon_retries_total{site=}`` counter, ``retries`` span attr so
  EXPLAIN ANALYZE renders ``[RETRY×n]``) and the per-query deadline
  (``CYLON_QUERY_DEADLINE_S`` → :class:`CylonTimeoutError`).
* ``admission`` — the admission controller: before execution, the
  planner's pre-flight estimate is compared against the pool's budget
  (ledger ``live_bytes`` aware, chaos-clampable) and the query is
  admitted, degraded to the blocked/chunked join path, or shed with
  :class:`CylonResourceExhausted`. Every decision lands in the flight
  recorder's admission ring.

Retryability itself is a property of the error (status.py taxonomy:
``CylonTransientError`` et al.), never a guess at the catch site.

Layering: resilience sits between the base leaves (status/telemetry)
and the execution layers — ``parallel/``, ``plan/`` and ``io/`` call
into it; it never imports them (``layering/resilience-below-exec``).
"""
from __future__ import annotations

from . import admission, inject, retry
from .retry import check_deadline, query_deadline, run_retryable

__all__ = ["admission", "inject", "retry", "run_retryable",
           "query_deadline", "check_deadline"]
