"""The admission controller: admit, degrade, or shed — before running.

PR-5 built the raw material (planner pre-flight estimates + the
ledger-backed ``live_bytes`` pool fallback) but only used it for a
warning span; ROADMAP item 2 calls for turning it into a real
controller with backpressure/shed paths. This module is that
controller: the plan executor hands it the pre-flight estimate map and
the pool, and gets back one of three decisions —

* **admit**   — the worst node estimate fits the budget (or no budget
  is knowable — stats-hidden backend with no ledger history): run
  unchanged.
* **degrade** — a Join's estimate exceeds the budget and the blocked/
  chunked join path can bound the working set (ROADMAP item 4's
  planner-visible blocked mode): the executor lowers the join with
  ``probe_block_rows`` sized so one block's working set fits. Only
  single-shard (world==1) joins degrade today — the distributed join's
  exchange already bounds its comm buffers via the blockwise path, and
  its post-exchange working set has no chunked lowering yet.
* **shed**    — the estimate is beyond ``CYLON_SHED_FACTOR`` (default
  8×) of the budget: raise :class:`CylonResourceExhausted` BEFORE
  burning device time the query cannot finish with. Checked before
  degrade — the blocked path bounds the join's WORKING SET, but the
  estimate is the OUTPUT size, which degrade still materializes in
  full. Over budget but under the factor with no degradable node
  admits with the pre-flight warning.

Budget: ``pool.comm_budget_bytes()`` (live-HBM aware — the pool's
``available_bytes`` nets out ``live_bytes`` on stats-bearing backends
and the ledger feeds it on hidden ones), clamped by the fault
injector's ``pool`` site so chaos drills exercise both paths
deterministically.

Every decision is recorded: a ``cylon_admission_total{decision=}``
counter, a log line, and an entry in the flight recorder's admission
ring (``flight.admissions()``, included in crash dumps) — a shed query
leaves the same forensic trail as a crashed one.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..status import CylonResourceExhausted
from ..telemetry import flight as _flight
from ..telemetry import knobs as _knobs
from ..telemetry import logger as _logger
from ..telemetry import metrics as _metrics
from ..telemetry import span as _span
from . import inject as _inject

DEFAULT_SHED_FACTOR = _knobs.default("CYLON_SHED_FACTOR")

# degraded joins never chunk below this many probe rows per block —
# sub-1k blocks pay more per-dispatch overhead than they save memory
MIN_BLOCK_ROWS = 1 << 10


def shed_factor() -> float:
    return _knobs.get("CYLON_SHED_FACTOR")


def effective_budget(pool) -> Optional[int]:
    """The byte budget admission decisions run against: the pool's comm
    budget (duck-typed — admission never imports memory.py), clamped by
    an armed ``pool`` fault spec. None = unknowable, admit."""
    budget = None
    if pool is not None:
        try:
            budget = pool.comm_budget_bytes()
        except Exception:  # cylint: disable=errors/broad-swallow — a broken pool must not veto admission
            budget = None
    clamp = _inject.budget_clamp()
    if clamp is not None:
        budget = clamp if budget is None else min(budget, clamp)
    return budget


@dataclass
class Decision:
    """One admission decision over one plan."""

    action: str                    # "admit" | "degrade" | "shed"
    budget: Optional[int] = None
    est_bytes: Optional[int] = None   # worst node EFFECTIVE estimate
    worst_node: Optional[str] = None
    reason: str = ""
    # provenance of the worst-node estimate the decision acted on:
    # "static" (width x row upper bound) or "measured" (the statistics
    # warehouse's EWMA-calibrated value, telemetry/stats.py). Rides
    # the admission ring and the query-log digest, so a forensic
    # record always says WHICH estimator admitted or shed the query.
    est_source: str = "static"
    # id(join node) -> probe_block_rows for degraded lowerings
    degrade_blocks: Dict[int, int] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"action": self.action, "budget": self.budget,
                "est_bytes": self.est_bytes,
                "est_source": self.est_source,
                "worst_node": self.worst_node, "reason": self.reason,
                "degraded_nodes": len(self.degrade_blocks)}


def _node_desc(node) -> str:
    return f"{type(node).__name__}({node.args_repr()})"


def _effective(e: dict):
    """(effective bytes, source) for one estimate entry: the
    statistics-warehouse calibration when plan/report.py stamped one
    (``calibrated_bytes`` = min(static, ewma x safety) — never above
    the static bound), the static width x row estimate otherwise.
    Duck-typed dict read: admission never imports plan/ or the
    warehouse — calibration happened upstream."""
    cb = e.get("calibrated_bytes")
    if cb is not None:
        return cb, e.get("est_source", "measured")
    return e.get("bytes"), "static"


def decide(nodes: List[object], est: Dict[int, dict],
           budget: Optional[int], world: int) -> Decision:
    """The pure decision function: ``nodes`` is the plan's node list
    (duck-typed — ``kind``/``args_repr``; admission never imports
    plan/), ``est`` the (possibly stats-calibrated) pre-flight
    estimate map keyed by id(node). Every comparison runs against the
    EFFECTIVE estimate — measured EWMA x safety once a fingerprint has
    enough observations, static bound otherwise — so a repeat query
    the warehouse has watched fit in budget is admitted, while the
    min() with the static bound keeps the decision sound (a measured
    estimate still over budget sheds exactly like a static one).
    Raises nothing; the executor enforces a shed decision."""
    # Scans are excluded: their bytes are ALREADY resident (borrowed
    # user inputs) — admission controls the allocations a query is
    # about to make, not history it cannot undo
    allocating = [(n, *_effective(est.get(id(n), {}))) for n in nodes
                  if n.kind != "scan"]
    allocating = [(n, b, src) for n, b, src in allocating
                  if b is not None]
    if not budget:
        # no budget to enforce, but the forensic record still carries
        # the worst allocating estimate + its provenance — the digest
        # and admission ring stay joinable against measured truth even
        # on budget-hidden backends
        worst = max(allocating, key=lambda p: p[1], default=None)
        if worst is None:
            return Decision("admit", budget=budget,
                            reason="no budget knowable")
        return Decision("admit", budget=budget, est_bytes=worst[1],
                        est_source=worst[2],
                        reason="no budget knowable")
    over = [(n, b, src) for n, b, src in allocating if b > budget]
    if not over:
        # worst ALLOCATING estimate only — a huge borrowed Scan input
        # must not make an admitted query's forensic record look like
        # a waved-through 500x overrun
        worst = max(allocating, key=lambda p: p[1], default=None)
        if worst is None:
            return Decision("admit", budget=budget,
                            reason="within budget")
        return Decision("admit", budget=budget, est_bytes=worst[1],
                        est_source=worst[2],
                        reason="within budget"
                        + (" (stats-calibrated)"
                           if worst[2] == "measured" else ""))
    worst_node, worst_bytes, worst_src = max(over, key=lambda p: p[1])
    factor = worst_bytes / budget
    if factor > shed_factor():
        # beyond the shed factor NOTHING saves the query — the blocked
        # path bounds the join's WORKING SET, but the estimate is the
        # OUTPUT size, which degrade still materializes in full. A
        # MEASURED estimate this far over budget sheds identically:
        # the warehouse relaxes false alarms, never real ones.
        return Decision(
            "shed", budget=budget, est_bytes=worst_bytes,
            est_source=worst_src,
            worst_node=_node_desc(worst_node),
            reason=f"{worst_src} estimate {factor:.1f}x over budget "
                   f"(shed factor {shed_factor():.1f}, "
                   f"world={world})")
    # degrade: an over-budget JOIN can chunk its probe side so one
    # block's working set fits. Only when EVERY over-budget node is a
    # degradable join — degrading the join while a downstream node
    # still blows the budget helps nothing.
    over_joins = [(n, b) for n, b, _src in over if n.kind == "join"]
    degradable = world == 1 and over_joins \
        and all(n.kind == "join" for n, _b, _src in over)
    if degradable:
        blocks: Dict[int, int] = {}
        for n, b in over_joins:
            rows = est[id(n)].get("rows") or 0
            if rows <= 0:
                continue
            blocks[id(n)] = max(int(rows * budget / b),
                                MIN_BLOCK_ROWS)
        if blocks:
            return Decision(
                "degrade", budget=budget, est_bytes=worst_bytes,
                est_source=worst_src,
                worst_node=_node_desc(worst_node),
                degrade_blocks=blocks,
                reason=f"{len(blocks)} join(s) over budget -> "
                       f"blocked/chunked probe")
    # moderately over budget with no chunked lowering available: admit
    # — the exchange bounds its own comm buffers against this budget,
    # and the pre-flight warning span already flags the risk
    return Decision("admit", budget=budget, est_bytes=worst_bytes,
                    est_source=worst_src,
                    worst_node=_node_desc(worst_node),
                    reason=f"{worst_src} estimate {factor:.1f}x over "
                           f"budget, under shed factor — admitted "
                           f"with warning")


def record(decision: Decision, tenant: Optional[str] = None
           ) -> Decision:
    """Publish one decision (counter + log + flight admission ring +
    the ``plan.admission`` marker span for non-admit decisions);
    returns it for chaining. ``tenant`` (the service scheduler's
    multi-tenant label) rides the admission-ring entry — a shed
    query's forensic record says WHOSE query was shed."""
    _metrics.REGISTRY.counter("cylon_admission_total",
                              {"decision": decision.action}).inc()
    # which estimator is steering admission — the closed-loop health
    # signal (bench surfaces the measured-admit count as
    # service_pipeline.stats_informed_admits)
    _metrics.REGISTRY.counter(
        "cylon_admission_est_source_total",
        {"source": decision.est_source}).inc()
    doc = decision.to_dict()
    if tenant is not None:
        doc["tenant"] = tenant
    _flight.record_admission(doc)
    if decision.action == "admit":
        _logger.debug("admission: %s (%s)", decision.action,
                      decision.reason)
    else:
        _logger.warning("admission: %s — %s (worst %s, est %s B vs "
                        "budget %s B)", decision.action,
                        decision.reason, decision.worst_node,
                        decision.est_bytes, decision.budget)
        # the trace-visible marker (docs/telemetry.md): every non-admit
        # decision — executor-internal OR service-dispatch — emits one
        # plan.admission span before execution (or the shed raise)
        with _span("plan.admission", decision=decision.action,
                   est_bytes=decision.est_bytes,
                   budget=decision.budget,
                   worst_node=decision.worst_node or ""):
            pass
    return decision


def enforce(decision: Decision) -> Decision:
    """Raise the typed shed error for a shed decision; pass everything
    else through."""
    if decision.action == "shed":
        raise CylonResourceExhausted(
            f"query shed by admission controller: {decision.reason}; "
            f"worst node {decision.worst_node} estimated at "
            f"{decision.est_bytes} B vs budget {decision.budget} B")
    return decision
