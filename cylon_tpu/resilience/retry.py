"""Bounded retry-with-backoff + the per-query deadline.

Every retryable stage in the engine funnels through
:func:`run_retryable`: the exchange dispatch in `parallel/shuffle.py`
(which transitively covers the kernel-factory builds the dispatch
triggers — `functools.lru_cache` does not cache exceptions, so a
failed build rebuilds on retry) and the io ingest readers. Stages are
pure functions of device arrays (the jax execution model), so re-
dispatching a failed program is always safe.

Policy, all env-tunable (docs/resilience.md):

* ``CYLON_RETRY_MAX``        total attempts per stage (default 3);
* ``CYLON_RETRY_BACKOFF_S``  base backoff before attempt 2 (default
  0.05 s), doubling per retry — deterministic, no jitter: two chaos
  replays of the same seed take the same path;
* ``CYLON_QUERY_DEADLINE_S`` per-query wall-clock budget. The plan
  executor opens :func:`query_deadline` around each query; retry
  loops, backoff sleeps and node boundaries all check it, raising
  :class:`CylonTimeoutError` — which crosses the query's root span and
  triggers the flight recorder's crash dump like any other failure.

Observability: each retry increments ``cylon_retries_total{site=}``
and, on eventual success, the enclosing span gains a ``retries`` attr
— EXPLAIN ANALYZE renders it as ``[RETRY×n]`` (plan/report.py). Only
:func:`status.is_retryable` errors retry; raw backend errors are first
mapped through ``status.classify`` so retryability is decided by type.
"""
from __future__ import annotations

import time
from contextlib import contextmanager
from contextvars import ContextVar
from typing import Callable, Iterator, Optional, TypeVar

from ..status import CylonTimeoutError, classify
from ..telemetry import annotate as _annotate
from ..telemetry import current_span as _current_span
from ..telemetry import knobs as _knobs
from ..telemetry import logger as _logger
from ..telemetry import metrics as _metrics

T = TypeVar("T")

DEFAULT_MAX_ATTEMPTS = _knobs.default("CYLON_RETRY_MAX")
DEFAULT_BACKOFF_S = _knobs.default("CYLON_RETRY_BACKOFF_S")


def max_attempts() -> int:
    return _knobs.get("CYLON_RETRY_MAX")


def backoff_base_s() -> float:
    return _knobs.get("CYLON_RETRY_BACKOFF_S")


# ---------------------------------------------------------------------------
# per-query deadline
# ---------------------------------------------------------------------------

# absolute time.monotonic() deadline of the enclosing query, or None
_deadline: ContextVar[Optional[float]] = ContextVar(
    "cylon_tpu_query_deadline", default=None)


def _env_deadline_s() -> Optional[float]:
    s = _knobs.get("CYLON_QUERY_DEADLINE_S")
    return s if s is not None and s > 0 else None


@contextmanager
def query_deadline(seconds: Optional[float] = None) -> Iterator[None]:
    """Scope a wall-clock budget over a query (``seconds`` default:
    ``CYLON_QUERY_DEADLINE_S``; no-op when neither is set). Nested
    scopes keep the TIGHTER deadline — an outer budget can never be
    extended by an inner one."""
    s = seconds if seconds is not None else _env_deadline_s()
    if s is None:
        yield
        return
    new = time.monotonic() + s
    outer = _deadline.get()
    token = _deadline.set(min(new, outer) if outer is not None else new)
    try:
        yield
    finally:
        _deadline.reset(token)


def remaining_s() -> Optional[float]:
    """Seconds left on the enclosing query's deadline, or None."""
    d = _deadline.get()
    return None if d is None else d - time.monotonic()


def check_deadline(site: str = "") -> None:
    """Raise :class:`CylonTimeoutError` when the enclosing query's
    deadline has passed. Called at stage boundaries (executor node
    lowerings) and inside every retry loop."""
    rem = remaining_s()
    if rem is not None and rem <= 0:
        _metrics.REGISTRY.counter("cylon_deadline_exceeded_total").inc()
        raise CylonTimeoutError(
            f"query deadline exceeded ({-rem:.3f} s past budget"
            f"{', at ' + site if site else ''})")


# ---------------------------------------------------------------------------
# retry loop
# ---------------------------------------------------------------------------


def run_retryable(site: str, fn: Callable[[], T]) -> T:
    """Run ``fn`` with bounded retry-with-backoff on transient errors.

    Non-retryable failures re-raise immediately — mapped onto the typed
    taxonomy when ``classify`` recognizes them, so a raw XLA
    RESOURCE_EXHAUSTED leaves this function as
    :class:`CylonResourceExhausted`. On success after n retries the
    current span gains ``retries=n`` and a warning is logged (a stage
    that needed retries is worth a human's glance even when it
    recovered)."""
    attempts = max_attempts()
    base = backoff_base_s()
    retries = 0
    while True:
        check_deadline(site)
        try:
            out = fn()
        except Exception as e:
            typed = classify(e)   # the one classification per failure
            retryable = typed is not None and typed.retryable
            if not retryable or retries + 1 >= attempts:
                if typed is not None and typed is not e:
                    raise typed from e
                raise
            retries += 1
            _metrics.REGISTRY.counter("cylon_retries_total",
                                      {"site": site}).inc()
            delay = base * (2 ** (retries - 1))
            rem = remaining_s()
            if rem is not None:
                delay = min(delay, max(rem, 0.0))
            _logger.warning(
                "retry %d/%d at %s after %s (backoff %.3f s)",
                retries, attempts - 1, site, e, delay)
            if delay > 0:
                time.sleep(delay)
            continue
        if retries:
            # ACCUMULATE into the enclosing span: two retried stages
            # under one node span (count + dispatch) must sum, so the
            # [RETRY×n] marker agrees with cylon_retries_total
            cur = _current_span()
            prior = int(cur.attrs.get("retries", 0)) \
                if cur is not None else 0
            _annotate(retries=prior + retries)
            _logger.warning("stage %s succeeded after %d retr%s",
                            site, retries,
                            "y" if retries == 1 else "ies")
        return out
