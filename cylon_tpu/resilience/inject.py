"""Deterministic fault injection at named choke points.

Chaos testing only proves anything when the chaos is REPLAYABLE: a
fault that fires "sometimes" produces unreproducible red builds, so
every fault here is a pure function of the armed plan and the arrival
counter — run the same plan against the same pipeline and the same
attempt fails, every time.

Fault-plan grammar (``CYLON_FAULT_PLAN`` or ``arm(plan)``)::

    plan    := spec ("," spec)*
    spec    := site ":" trigger ":" kind
    site    := "exchange" | "compile" | "ingest" | "pool"
    trigger := N        fire on the Nth arrival only (1-based)
             | N "+"    fire on every arrival from the Nth on
                        (a PERSISTENT fault — retries keep failing)
             | "*"      fire on every arrival (same as "1+")
    kind    := "transient"  -> CylonTransientError  (retryable)
             | "oom"        -> CylonResourceExhausted
             | "data"       -> CylonDataError

    exchange:2:transient      second exchange launch fails once
    exchange:1+:transient     every exchange launch fails (persistent)
    compile:1:oom             first kernel-factory build OOMs
    ingest:1:data             first file read returns garbage

The ``pool`` site is different: it does not raise — it CLAMPS the
budget the admission controller sees (``budget_clamp()``), simulating
HBM exhaustion deterministically. Its trigger field is the clamp in
BYTES: ``pool:4096:oom`` makes every admission decision run against a
4 KiB budget, driving the shed/degrade paths.

Choke points call :func:`fire` (a near-free no-op when nothing is
armed); arming happens explicitly via :func:`arm` or lazily from the
environment on first fire. ``state()`` (armed plan, per-site arrival
counts, fired events) is registered as a crash-dump section, so a
chaos failure's dump names the fault that caused it.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..status import (CylonDataError, CylonPlanError,
                      CylonResourceExhausted, CylonTransientError)
from ..telemetry import flight as _flight
from ..telemetry import knobs as _knobs
from ..telemetry import metrics as _metrics

PLAN_ENV = "CYLON_FAULT_PLAN"

SITES = ("exchange", "compile", "ingest", "pool")

_KINDS = {
    "transient": CylonTransientError,
    "oom": CylonResourceExhausted,
    "data": CylonDataError,
}


@dataclass(frozen=True)
class FaultSpec:
    """One armed fault: fire ``kind`` at ``site`` per ``trigger``."""

    site: str
    nth: int            # 1-based arrival index (pool: clamp bytes)
    persistent: bool    # fire on every arrival >= nth
    kind: str

    def matches(self, arrival: int) -> bool:
        return arrival >= self.nth if self.persistent \
            else arrival == self.nth

    def spec_str(self) -> str:
        trig = f"{self.nth}+" if self.persistent else str(self.nth)
        return f"{self.site}:{trig}:{self.kind}"


def parse_plan(text: str) -> List[FaultSpec]:
    """Parse the fault-plan grammar; a malformed plan is a
    :class:`CylonPlanError` (a typo'd chaos config must fail loudly,
    not silently arm nothing)."""
    specs: List[FaultSpec] = []
    for raw in text.split(","):
        raw = raw.strip()
        if not raw:
            continue
        parts = raw.split(":")
        if len(parts) != 3:
            raise CylonPlanError(
                f"fault spec {raw!r} is not site:trigger:kind")
        site, trig, kind = (p.strip() for p in parts)
        if site not in SITES:
            raise CylonPlanError(
                f"unknown fault site {site!r} (one of {SITES})")
        if kind not in _KINDS:
            raise CylonPlanError(
                f"unknown fault kind {kind!r} "
                f"(one of {tuple(_KINDS)})")
        persistent = trig == "*" or trig.endswith("+")
        num = "1" if trig == "*" else trig.rstrip("+")
        try:
            nth = int(num)
        except ValueError:
            raise CylonPlanError(
                f"fault trigger {trig!r} is not N, N+ or *")
        if nth < 1:
            raise CylonPlanError(
                f"fault trigger {trig!r} must be >= 1")
        specs.append(FaultSpec(site, nth, persistent, kind))
    return specs


@dataclass
class _State:
    plan_str: str
    specs: List[FaultSpec]
    arrivals: Dict[str, int] = field(default_factory=dict)
    fired: List[dict] = field(default_factory=list)


_lock = threading.Lock()
_state: Optional[_State] = None
_env_checked = False


def arm(plan: Optional[str] = None) -> List[FaultSpec]:
    """Arm a fault plan (default: ``CYLON_FAULT_PLAN``); resets arrival
    counters. Returns the parsed specs (empty when nothing to arm)."""
    global _state, _env_checked
    text = plan if plan is not None else \
        (_knobs.get(PLAN_ENV) or "")
    with _lock:
        _env_checked = True
        if not text:
            _state = None
            _metrics.set_factory_fault_hook(None)
            return []
        # publish via a local so the return below never re-reads the
        # global outside the lock (a concurrent disarm() between the
        # critical section and the return would None it out from under
        # us — the concurrency checker's lock-discipline rule)
        st = _State(text, parse_plan(text))
        _state = st
        if any(s.site == "compile" for s in st.specs):
            _metrics.set_factory_fault_hook(_compile_fault_hook)
        else:
            _metrics.set_factory_fault_hook(None)
    return list(st.specs)


def disarm() -> None:
    """Drop the armed plan and counters (test isolation)."""
    global _state, _env_checked
    with _lock:
        _state = None
        _env_checked = True
        _metrics.set_factory_fault_hook(None)


def active() -> bool:
    return _current() is not None


def _current() -> Optional[_State]:
    """The armed state, lazily arming from the environment exactly once
    (so env-driven chaos needs no import-order ceremony)."""
    global _env_checked
    if _state is None and not _env_checked:  # cylint: disable=concurrency/lock-discipline — double-checked lazy arm: reference reads are GIL-atomic; two racers at worst both run arm(), which is locked and idempotent
        if _knobs.get(PLAN_ENV):
            arm()
        else:
            with _lock:
                _env_checked = True
    return _state  # cylint: disable=concurrency/lock-discipline — GIL-atomic reference read is the fire() fast path; all mutation of the returned _State happens under _lock


def fire(site: str, detail: str = "") -> None:
    """One arrival at a choke point: increments the site counter and
    raises the armed typed error when a spec matches this arrival.
    Near-free when nothing is armed."""
    st = _current()
    if st is None:
        return
    with _lock:
        arrival = st.arrivals.get(site, 0) + 1
        st.arrivals[site] = arrival
        spec = next((s for s in st.specs
                     if s.site == site and s.matches(arrival)), None)
        if spec is None:
            return
        st.fired.append({"site": site, "arrival": arrival,
                         "kind": spec.kind, "spec": spec.spec_str(),
                         "detail": detail})
        _metrics.REGISTRY.counter("cylon_faults_injected_total",
                                  {"site": site}).inc()
    raise _KINDS[spec.kind](
        f"injected {spec.kind} fault at {site} "
        f"(arrival {arrival}, spec {spec.spec_str()}"
        f"{', ' + detail if detail else ''})")


def _compile_fault_hook(factory_name: str) -> None:
    """Installed as the counted_cache fault hook while a ``compile``
    spec is armed — every kernel-factory build is one arrival."""
    fire("compile", detail=f"factory {factory_name}")


def budget_clamp() -> Optional[int]:
    """The armed ``pool`` clamp in bytes, or None. The admission
    controller takes ``min(real budget, clamp)`` — a deterministic
    stand-in for a pod whose HBM is already spoken for."""
    st = _current()
    if st is None:
        return None
    clamps = [s.nth for s in st.specs if s.site == "pool"]
    return min(clamps) if clamps else None


def state() -> dict:
    """Armed plan + arrival counters + fired events — the crash dump's
    ``faults`` section, so a chaos dump names its own cause."""
    st = _state  # cylint: disable=concurrency/lock-discipline — GIL-atomic snapshot; the lock below guards the captured state's fields, a racing disarm just yields a stale (consistent) report
    if st is None:
        return {"armed": None, "arrivals": {}, "fired": []}
    with _lock:
        return {"armed": st.plan_str,
                "specs": [s.spec_str() for s in st.specs],
                "arrivals": dict(st.arrivals),
                "fired": [dict(f) for f in st.fired]}


# a chaos failure's crash dump must name the fault that caused it
_flight.add_dump_section("faults", state)
