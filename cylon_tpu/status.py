"""Status/error model for cylon_tpu.

Mirrors the reference's return-value error propagation (reference:
cpp/src/cylon/status.hpp:21-63, cpp/src/cylon/code.cpp) but exposes it
Python-idiomatically: every public op raises :class:`CylonError` carrying a
:class:`Code`, and a :class:`Status` object is available for call sites that
prefer the reference's non-throwing style.

Error taxonomy (docs/resilience.md): the resilience layer needs
retryability to be a PROPERTY of the error, not a guess made at the
catch site, so :class:`CylonError` grew four operational subclasses —

* :class:`CylonTransientError`   — a stage that may succeed on retry
  (preempted ICI collective, transient runtime failure). The ONLY
  retryable class; ``resilience.retry`` keys off ``retryable``.
* :class:`CylonResourceExhausted` — HBM/compile memory exhausted, or a
  query shed by the admission controller. Not retryable as-is: the
  same attempt would exhaust the same memory — degrade or shrink.
* :class:`CylonPlanError`        — the plan/query itself is invalid
  (unknown lowering, bad fault-plan grammar). Never retryable.
* :class:`CylonDataError`        — malformed input data (truncated
  parquet, garbage CSV). Never retryable; re-reading won't fix bytes.
* :class:`CylonTimeoutError`     — the per-query deadline
  (``CYLON_QUERY_DEADLINE_S``) expired. Never retryable — the budget
  is spent.

``classify()`` maps raw backend exceptions (XLA RESOURCE_EXHAUSTED,
preemption/unavailable collectives) onto this taxonomy at the
resilience layer's catch sites, so retry policy is decided by type,
never by string-matching in operator code.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Optional


class Code(enum.IntEnum):
    """Error codes (reference: cpp/src/cylon/code.cpp)."""

    OK = 0
    OutOfMemory = 1
    KeyError = 2
    TypeError = 3
    Invalid = 4
    IOError = 5
    CapacityError = 6
    IndexError = 7
    UnknownError = 8
    NotImplemented = 9
    SerializationError = 10
    RError = 11
    CodeGenError = 12
    ExpressionValidationError = 13
    ExecutionError = 14
    AlreadyExists = 15


@dataclass(frozen=True)
class Status:
    """Reference: cpp/src/cylon/status.hpp:21-63 (`Status::OK/is_ok/get_code/get_msg`)."""

    code: Code = Code.OK
    msg: str = ""

    @staticmethod
    def OK() -> "Status":
        return Status(Code.OK, "")

    def is_ok(self) -> bool:
        return self.code == Code.OK

    def get_code(self) -> Code:
        return self.code

    def get_msg(self) -> str:
        return self.msg

    def raise_if_error(self) -> None:
        if not self.is_ok():
            raise CylonError(self.code, self.msg)


class CylonError(Exception):
    """Exception carrying a :class:`Code`; the Python-native face of Status.

    ``retryable`` is the class-level contract the resilience layer's
    retry policy reads: only :class:`CylonTransientError` sets it."""

    retryable = False

    def __init__(self, code: Code, msg: str):
        super().__init__(f"[{code.name}] {msg}")
        self.code = code
        self.msg = msg

    def status(self) -> Status:
        return Status(self.code, self.msg)


class CylonTransientError(CylonError):
    """A stage failure that may succeed on retry (preempted collective,
    transient runtime error, injected chaos fault). The only retryable
    error class."""

    retryable = True

    def __init__(self, msg: str, code: Code = Code.ExecutionError):
        super().__init__(code, msg)


class CylonResourceExhausted(CylonError):
    """HBM/compile memory exhausted, or a query shed by the admission
    controller. Retrying the identical attempt exhausts the identical
    memory — the recovery is degrade (blocked/chunked execution) or
    shrink, never blind retry."""

    def __init__(self, msg: str, code: Code = Code.OutOfMemory):
        super().__init__(code, msg)


class CylonPlanError(CylonError):
    """The plan/query itself is invalid (no lowering for a node, bad
    fault-plan grammar, malformed configuration). Never retryable."""

    def __init__(self, msg: str, code: Code = Code.Invalid):
        super().__init__(code, msg)


class CylonDataError(CylonError):
    """Malformed input data (truncated parquet footer, garbage CSV,
    invalid UTF-8). Never retryable — re-reading won't fix the bytes."""

    def __init__(self, msg: str, code: Code = Code.SerializationError):
        super().__init__(code, msg)


class CylonTimeoutError(CylonError):
    """The per-query deadline (``CYLON_QUERY_DEADLINE_S``) expired.
    Never retryable — the time budget is spent; the flight recorder
    dumps the in-flight span stack for the post-mortem."""

    def __init__(self, msg: str, code: Code = Code.ExecutionError):
        super().__init__(code, msg)


def is_retryable(exc: BaseException) -> bool:
    """True when retrying the failed stage could succeed: a typed
    transient error, or a raw backend error ``classify()`` maps to
    one."""
    if isinstance(exc, CylonError):
        return exc.retryable
    mapped = classify(exc)
    return mapped is not None and mapped.retryable


# substrings (lowercased) in raw backend error text that identify the
# failure class when the exception TYPE carries no information (XLA
# surfaces everything as XlaRuntimeError / RuntimeError)
_TRANSIENT_MARKERS = ("preempt", "unavailable", "aborted",
                      "connection reset", "transient", "cancelled",
                      "socket closed")
_OOM_MARKERS = ("resource_exhausted", "resource exhausted",
                "out of memory", "failed to allocate")


def classify(exc: BaseException) -> Optional[CylonError]:
    """Map a raw (non-Cylon) exception onto the typed taxonomy, or None
    when it carries no recognizable operational signature. Typed errors
    pass through unchanged — classification never re-wraps."""
    if isinstance(exc, CylonError):
        return exc
    text = f"{type(exc).__name__}: {exc}".lower()
    if any(m in text for m in _OOM_MARKERS):
        return CylonResourceExhausted(
            f"backend out of memory: {exc}")
    if any(m in text for m in _TRANSIENT_MARKERS):
        return CylonTransientError(
            f"transient backend failure: {exc}")
    return None
