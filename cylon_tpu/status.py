"""Status/error model for cylon_tpu.

Mirrors the reference's return-value error propagation (reference:
cpp/src/cylon/status.hpp:21-63, cpp/src/cylon/code.cpp) but exposes it
Python-idiomatically: every public op raises :class:`CylonError` carrying a
:class:`Code`, and a :class:`Status` object is available for call sites that
prefer the reference's non-throwing style.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass


class Code(enum.IntEnum):
    """Error codes (reference: cpp/src/cylon/code.cpp)."""

    OK = 0
    OutOfMemory = 1
    KeyError = 2
    TypeError = 3
    Invalid = 4
    IOError = 5
    CapacityError = 6
    IndexError = 7
    UnknownError = 8
    NotImplemented = 9
    SerializationError = 10
    RError = 11
    CodeGenError = 12
    ExpressionValidationError = 13
    ExecutionError = 14
    AlreadyExists = 15


@dataclass(frozen=True)
class Status:
    """Reference: cpp/src/cylon/status.hpp:21-63 (`Status::OK/is_ok/get_code/get_msg`)."""

    code: Code = Code.OK
    msg: str = ""

    @staticmethod
    def OK() -> "Status":
        return Status(Code.OK, "")

    def is_ok(self) -> bool:
        return self.code == Code.OK

    def get_code(self) -> Code:
        return self.code

    def get_msg(self) -> str:
        return self.msg

    def raise_if_error(self) -> None:
        if not self.is_ok():
            raise CylonError(self.code, self.msg)


class CylonError(Exception):
    """Exception carrying a :class:`Code`; the Python-native face of Status."""

    def __init__(self, code: Code, msg: str):
        super().__init__(f"[{code.name}] {msg}")
        self.code = code
        self.msg = msg

    def status(self) -> Status:
        return Status(self.code, self.msg)
