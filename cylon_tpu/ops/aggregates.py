"""Scalar column aggregates (table-level Sum/Count/Min/Max).

Reference: cpp/src/cylon/compute/aggregates.cpp:113-339 — local Arrow
compute reduction followed by `mpi::AllReduce` on the scalar
(mpi_operations.cpp:61-78). Here the local reduction is a jnp reduction and
the cross-device combine is free: when the column is sharded over the mesh,
XLA lowers the same reduction to per-shard partials + an ICI all-reduce.
Null handling matches Arrow: nulls are skipped; Count counts non-null rows.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..data.column import Column
from ..dtypes import Type
from ..status import Code, CylonError
from .groupby import _max_of, _min_of


@jax.jit
def _sum(data, valid):
    return jnp.where(valid, data, 0).sum()


@jax.jit
def _count(valid):
    return valid.sum()


@jax.jit
def _min(data, valid):
    return jnp.where(valid, data, _max_of(data.dtype)).min()


@jax.jit
def _max(data, valid):
    return jnp.where(valid, data, _min_of(data.dtype)).max()


def agg_scalar(col: Column, op: str):
    """Compute one scalar aggregate of a column; returns a Python scalar."""
    if col.is_string and op in ("sum", "mean"):
        raise CylonError(Code.TypeError, f"{op} unsupported for string column")
    valid = col.valid_mask()
    if op == "count":
        return int(_count(valid))
    if col.is_varbytes:
        # lexicographic min/max: one device sort of the prefix keys picks
        # the winning ROW; only that row's bytes are decoded
        vb = col.varbytes
        if not vb.sortable_on_device:  # >64-byte rows: host fallback
            vals = [v for v in col.to_numpy() if v is not None]
            if not vals:
                return None
            return min(vals) if op == "min" else max(vals)
        from .order import lexsort_indices

        keys = vb.sort_prefix_keys()
        if op == "max":
            keys = [k ^ jnp.uint32(0xFFFFFFFF) for k in keys]
        ext = jnp.uint32(0xFFFFFFFF)  # nulls lose either direction
        keys = [jnp.where(valid, k, ext) for k in keys]
        win = lexsort_indices(keys)[:1]
        if not bool(jax.device_get(valid.any())):
            return None
        # BINARY columns return bytes (a str() decode would corrupt
        # non-UTF-8 payloads — round-3 advisor finding)
        as_str = col.dtype.type != Type.BINARY
        v = vb.take(win).to_host(as_str=as_str)[0]
        return str(v) if as_str else bytes(v)
    if col.is_string:
        # min/max by dictionary order -> decode the code
        code = (_min if op == "min" else _max)(col.data, valid)
        return str(col.dictionary[int(code)])
    if op == "sum":
        return _py(_sum(col.data, valid))
    if op == "min":
        return _py(_min(col.data, valid))
    if op == "max":
        return _py(_max(col.data, valid))
    if op == "mean":
        s = _sum(col.data.astype(jnp.float64), valid)
        c = _count(valid)
        return float(s) / max(int(c), 1)
    raise CylonError(Code.Invalid, f"unknown aggregate {op}")


def _py(x):
    v = x.item()
    return v
