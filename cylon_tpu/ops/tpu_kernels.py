"""Pallas TPU kernel library — streaming relational primitives.

XLA's gather/scatter on TPU costs ~15-30 ns/element regardless of index
locality (measured on v5e: 33M-element random gather 509 ms, *sorted*
gather 963 ms, scatter 250 ms — vs 96-192 ms for a full multi-operand
sort, ~17 ms for a cumsum and ~10 ms for an elementwise pass). The
relational hot paths are therefore rebuilt as streaming Pallas kernels
that touch HBM sequentially and resolve indirection on-chip:

- ``sweep_gather``  — in-kernel VMEM window gather out[i] = win[o[i]]:
  sublane sweep of native (rows,128) lane gathers (`take_along_axis`
  along lanes is a Mosaic primitive; wider windows sweep row-by-row
  with compare+select).
- ``block_cumsum``  — in-kernel flat inclusive scan of a (R,128) block
  (`jnp.cumsum` has no Mosaic lowering).
- ``inverse_monotone`` — o[q] = #{j : P[j] <= q} for a non-decreasing
  block P: binary search over sweep_gather probes.
- ``stream_compact`` — compact masked elements of K parallel u32 streams
  into dense prefixes, writing element-exact output via row-aligned DMA
  with a write pointer and partial-row tail carried in SMEM/VMEM across
  the (sequential) TPU grid.

Storage convention: 1-D streams are reshaped (n/128, 128) so windows can
be DMA'd at dynamic *row* offsets (Mosaic rejects arbitrary-offset 1-D
HBM slices; row-granular 2-D slices work).

These replace the reference's builder-append materialization (reference:
cpp/src/cylon/join/join_utils.cpp:131-196 `build_final_table`,
cpp/src/cylon/util/copy_arrray.cpp `copy_array_by_indices`) with
TPU-streaming equivalents. Off-TPU every wrapper accepts
``interpret=True`` and runs under the Pallas interpreter (used by the
CPU test suite; the XLA kernels in ops/join.py remain the portable
default path).
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):  # pre-0.5 jax naming
    def _compiler_params_compat(has_side_effects: bool = False):
        # TPUCompilerParams grew has_side_effects later; the dict form
        # ({"mosaic": {...}}) is the spelling old pallas_call accepts
        return {"mosaic": {"has_side_effects": bool(has_side_effects)}}

    pltpu.CompilerParams = _compiler_params_compat

LANES = 128
_I32MAX = jnp.iinfo(jnp.int32).max


def _x32_trace():
    """Context: trace kernel bodies with x64 disabled. Under
    jax_enable_x64, jnp.take_along_axis promotes its indices to int64 and
    Mosaic's int64 convert_element_type rule recurses forever; every
    kernel here is 32-bit by construction, so the promotion is never
    wanted."""
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(False)
    from jax.experimental import enable_x64 as _e64  # pre-0.5 jax home

    return _e64(False)


def _roll(x, k, axis, interpret=False):
    # pltpu.roll is Mosaic-only; the interpreter needs jnp.roll
    if interpret:
        return jnp.roll(x, k, axis)
    return pltpu.roll(x, k, axis)


def rows_for(n: int) -> int:
    return max(-(-n // LANES), 1)


def pad_rows(x: jnp.ndarray, rows: int, fill=0) -> jnp.ndarray:
    """1-D (n,) -> (rows, 128), zero/fill-padded."""
    n = x.shape[0]
    pad = rows * LANES - n
    if pad:
        x = jnp.concatenate([x, jnp.full(pad, fill, x.dtype)])
    return x.reshape(rows, LANES)


# ---------------------------------------------------------------------------
# in-kernel building blocks (pure functions of VMEM values)
# ---------------------------------------------------------------------------


# np scalar, not a bare python int: weak literals in kernel jaxprs are
# re-canonicalized (i64 under jax_enable_x64) when the interpret
# lowering discharges inside an enclosing jit — see block_cumsum
_L32 = np.int32(LANES)


def flat_iota(shape) -> jnp.ndarray:
    return (jax.lax.broadcasted_iota(jnp.int32, shape, 0) * _L32
            + jax.lax.broadcasted_iota(jnp.int32, shape, 1))


def block_cumsum(x: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """Inclusive scan of a (R,128) int32 block in flat row-major order.

    Scalar where-branches carry STRONG dtypes (``x.dtype.type(0)``, not
    a bare ``0``): a weak python literal in the kernel jaxpr is
    re-canonicalized when the interpret lowering discharges inside an
    enclosing jit — under jax_enable_x64 it comes back i64 and fails
    select_n's strict dtype check. Same rule for every kernel helper
    below."""
    R = x.shape[0]
    zero = x.dtype.type(0)
    lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    v = x
    k = 1
    while k < LANES:
        v = v + jnp.where(lane >= k, _roll(v, k, 1, interpret), zero)
        k <<= 1
    if R == 1:
        return v
    tot = jnp.broadcast_to(v[:, LANES - 1:LANES], (R, LANES))
    riota = jax.lax.broadcasted_iota(jnp.int32, (R, LANES), 0)
    inc = tot
    k = 1
    while k < R:
        inc = inc + jnp.where(riota >= k, _roll(inc, k, 0, interpret),
                              zero)
        k <<= 1
    return v + (inc - tot)


def block_cummax(x: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """Inclusive running MAX of a (R,128) int32 block in flat row-major
    order (same log-shift structure as block_cumsum)."""
    R = x.shape[0]
    lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    neg = x.dtype.type(jnp.iinfo(x.dtype).min)  # strong: see block_cumsum
    v = x
    k = 1
    while k < LANES:
        v = jnp.maximum(v, jnp.where(lane >= k, _roll(v, k, 1, interpret),
                                     neg))
        k <<= 1
    if R == 1:
        return v
    tot = jnp.broadcast_to(v[:, LANES - 1:LANES], (R, LANES))
    riota = jax.lax.broadcasted_iota(jnp.int32, (R, LANES), 0)
    inc = tot
    k = 1
    while k < R:
        inc = jnp.maximum(inc, jnp.where(riota >= k,
                                         _roll(inc, k, 0, interpret), neg))
        k <<= 1
    prev_rows = jnp.where(riota > 0, _roll(inc, 1, 0, interpret), neg)
    return jnp.maximum(v, prev_rows)


def flat_shift(x: jnp.ndarray, s, fill=0, interpret: bool = False
               ) -> jnp.ndarray:
    """Shift a (R,128) block DOWN by s (dynamic, 0 <= s < 128) in flat
    order; vacated head gets `fill`. Elements pushed past the end are
    dropped (callers append a spill row first if they need them)."""
    lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    ra = _dyn_roll_lanes(x, s)
    rb = _roll(ra, 1, 0, interpret)  # rows down by one
    shifted = jnp.where(lane >= s, ra, rb)
    fi = flat_iota(x.shape)
    return jnp.where(fi >= s, shifted,
                     jnp.asarray(fill, x.dtype))  # strong: block_cumsum


def _dyn_roll_lanes(x, s):
    """Roll lanes by dynamic s using take_along_axis (Mosaic-native)."""
    lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    src = (lane - s) % _L32
    return jnp.take_along_axis(x, src, axis=1)


def flat_shift_up(x: jnp.ndarray, k: int, fill=0, interpret: bool = False
                  ) -> jnp.ndarray:
    """Shift a (R,128) block UP (toward index 0) by static k in flat
    order; vacated tail gets `fill`."""
    R = x.shape[0]
    span = R * LANES
    rows_k, q = k // LANES, k % LANES
    lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    a = _roll(x, (R - rows_k) % R, 0, interpret)  # pltpu.roll: shift >= 0
    if q == 0:
        shifted = a
    else:
        b = _roll(x, (R - rows_k - 1) % R, 0, interpret)
        ra = _roll(a, LANES - q, 1, interpret)
        rb = _roll(b, LANES - q, 1, interpret)
        shifted = jnp.where(lane < np.int32(LANES - q), ra, rb)
    fi = flat_iota(x.shape)
    return jnp.where(fi < np.int32(span - k), shifted,
                     jnp.asarray(fill, x.dtype))  # strong: block_cumsum


def sweep_gather(win: jnp.ndarray, o: jnp.ndarray, fill=0) -> jnp.ndarray:
    """out[i] = win.flat[o[i]] for window (W,128) and flat offsets o
    (B,128); offsets outside [0, W*128) yield `fill`. Cost O(W) vops."""
    W = win.shape[0]
    orow = o // LANES
    olane = jnp.where((o >= 0) & (orow < W), o % LANES, 0)
    out = jnp.full(o.shape, fill, win.dtype)
    for r in range(W):
        bc = jnp.broadcast_to(win[r:r + 1, :], o.shape)
        g = jnp.take_along_axis(bc, olane, axis=1)
        out = jnp.where(orow == r, g, out)
    return out


def inverse_monotone(P: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """o[·] = #{j : P.flat[j] <= q[·]} for non-decreasing (R,128) P.
    Binary search; q any int32 block shape."""
    span = P.shape[0] * LANES
    width = 1
    while width < span:
        width <<= 1
    lo = jnp.zeros(q.shape, jnp.int32)
    step = width
    while step:
        mid = lo + step
        pv = sweep_gather(P, jnp.minimum(mid, span) - 1, fill=_I32MAX)
        pv = jnp.where(mid <= span, pv, _I32MAX)
        lo = jnp.where(pv <= q, mid, lo)
        step >>= 1
    return lo


# ---------------------------------------------------------------------------
# stream_compact
# ---------------------------------------------------------------------------


def stream_compact(mask: jnp.ndarray, streams: Sequence[jnp.ndarray],
                   block_rows: int = 32, interpret: bool = False
                   ) -> Tuple[Tuple[jnp.ndarray, ...], jnp.ndarray]:
    """Compact ``streams[k][mask]`` into dense zero-padded prefixes.

    mask: (n,) bool/int; streams: 1-D 32-bit arrays of length n.
    Returns (tuple of compacted (n_pad,) arrays, count int32). n_pad =
    n rounded up to a block multiple (tail beyond `count` is zeros).
    """
    nstreams = len(streams)
    n = mask.shape[0]
    BR = block_rows
    # DMA windows must cover whole (8,128) sublane tiles — a copy of a
    # non-multiple-of-8 row count hard-faults the chip (observed on v5e)
    assert BR % 8 == 0 and BR >= 8
    blocks = max(-(-n // (BR * LANES)), 1)
    rows = blocks * BR
    m2 = pad_rows(mask.astype(jnp.int32), rows)
    # BITCAST (not value-cast) to u32: the outputs are bit-reinterpreted
    # back via .view(s.dtype), so the round trip must be bit-exact
    for s in streams:
        assert s.dtype.itemsize == 4, \
            f"stream_compact takes 32-bit streams, got {s.dtype}"
    s2 = [pad_rows(s if s.dtype == jnp.uint32 else s.view(jnp.uint32),
                   rows) for s in streams]

    out_rows = rows + BR + 8  # dynamic write window may extend past rows

    scratch = ([pltpu.SMEM((1,), jnp.int32),
                pltpu.VMEM((nstreams, LANES), jnp.uint32)]
               + [pltpu.VMEM((BR + 8, LANES), jnp.uint32)
                  for _ in range(nstreams)]
               + [pltpu.SemaphoreType.DMA((nstreams,))])

    out_shapes = ([jax.ShapeDtypeStruct((out_rows, LANES), jnp.uint32)
                   for _ in range(nstreams)]
                  + [jax.ShapeDtypeStruct((1,), jnp.int32)])

    def kernel(mask_ref, *rest):
        srefs = rest[:nstreams]
        outs = rest[nstreams:2 * nstreams]
        cnt_ref = rest[2 * nstreams]
        wptr = rest[2 * nstreams + 1]
        tails = rest[2 * nstreams + 2]
        bufs = list(rest[2 * nstreams + 3:2 * nstreams + 3 + nstreams])
        sems = rest[2 * nstreams + 3 + nstreams]
        _compact_streams(nstreams, BR, mask_ref, srefs, outs, cnt_ref,
                         wptr, tails, bufs, sems, interpret)

    res = pl.pallas_call(
        kernel,
        out_shape=out_shapes,
        grid=(blocks,),
        in_specs=([pl.BlockSpec((BR, LANES), lambda i: (i, 0),
                                memory_space=pltpu.VMEM)] * (1 + nstreams)),
        out_specs=([pl.BlockSpec(memory_space=pl.ANY)] * nstreams
                   + [pl.BlockSpec(memory_space=pltpu.SMEM)]),
        scratch_shapes=scratch,
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
        interpret=interpret,
    )
    with _x32_trace():
        res = res(m2, *s2)
    outs, count = res[:nstreams], res[nstreams][0]
    flat = tuple(
        o.reshape(-1)[:rows * LANES].view(s.dtype)
        if s.dtype != jnp.uint32 else o.reshape(-1)[:rows * LANES]
        for o, s in zip(outs, streams))
    return flat, count


# ---------------------------------------------------------------------------
# join_plan_stream — the streaming join planner
# ---------------------------------------------------------------------------


def join_plan_stream(bits_s: jnp.ndarray, tag_s: jnp.ndarray, na: int,
                     nb: int, emit_unmatched_a: bool,
                     lanes: Sequence[jnp.ndarray] = (),
                     n_a_lanes: Optional[int] = None,
                     n_b_lanes: Optional[int] = None,
                     bits2_s: Optional[jnp.ndarray] = None,
                     verify_lanes: Sequence[jnp.ndarray] = (),
                     block_rows: int = 64, interpret: bool = False):
    """ONE sequential pass over the key-sorted row stream that computes the
    whole join plan — the Pallas replacement for the XLA scatter/gather
    chain in ops/join.join_plan_keys (profiled ~2 s of latency-bound
    random HBM passes at 33M rows; this pass is bandwidth-bound streaming).

    Inputs (key-sorted together, see ops/join.plan_program_stream):
      bits_s: u32 order-normalized key bits; dead rows forced to ~0.
      tag_s:  u32 ``side<<31 | emit<<30 | live<<29 | iota`` — probe (a)
              rows carry side=1 and sort after build (b) rows within a run.
      lanes:  u32 payload streams that rode the SAME sort (slot s holds
              a-side column s at a rows, b-side column s at b rows) —
              they are compacted into both groups so the expansion kernel
              never has to random-gather payload from HBM.
      bits2_s: optional SECOND run-boundary stream — the hash-join path
              sorts on a 2x32-bit row hash, so runs are (bits, bits2)
              equality classes.
      verify_lanes: u32 key-bit streams checked for equality WITHIN each
              run; any difference between adjacent live rows bumps the
              collision counter (counts[3]) — the hash-join path treats
              a nonzero count as "hash collision, recompute exactly".

    Per element the pass derives, with SMEM carries across the sequential
    grid: the live-b prefix count (block_cumsum), run boundaries (shifted
    compare), the run-head live-b prefix via a running MAX broadcast
    (head values are non-decreasing in key order, so cummax IS the
    broadcast — no scatter), match count m, output offsets (cumsum of
    per-row multiplicity), and stream-compacts two groups:
      group A (emitting probe rows): {orig index, packed delta2,
              output start, payload lanes…} — the expansion plan;
      group B (live build rows):     {orig index, payload lanes…} — the
              key-ordered build permutation (bperm analog).

    Returns (counts i32[4] = [n_out, n_emit, n_blive, n_collisions],
    a_streams, b_streams) where a_streams = (elist, delc, startsc,
    a_lane…) and b_streams = (blist, b_lane…), each a PADDED (rows,
    LANES) u32 block array; entries beyond their count are garbage —
    consumers mask by the counts (join_expand_stream).
    """
    n = bits_s.shape[0]
    BR = block_rows
    L = len(lanes)
    # lane slot s holds a-side column s at a rows and b-side column s at b
    # rows; when the sides pack unequal lane counts, the narrow side's
    # group only compacts ITS lanes (the tail slots are the other side's)
    La = L if n_a_lanes is None else n_a_lanes
    Lb = L if n_b_lanes is None else n_b_lanes
    nA, nB = 3 + La, 1 + Lb
    has_b2 = bits2_s is not None
    nv = len(verify_lanes)
    assert BR % 8 == 0 and BR >= 8
    assert n < (1 << 29)
    blocks = max(-(-n // (BR * LANES)), 1)
    rows = blocks * BR
    allones = jnp.uint32(0xFFFFFFFF)
    b2 = pad_rows(bits_s, rows, fill=allones)
    t2 = pad_rows(tag_s, rows, fill=0)  # side=0, live=0 → inert
    b2b = pad_rows(bits2_s, rows, fill=allones) if has_b2 else None
    v2 = [pad_rows(x, rows, fill=0) for x in verify_lanes]
    l2 = [pad_rows(x, rows, fill=0) for x in lanes]

    rows_a = rows_for(max(na, 1))
    rows_b = rows_for(max(nb, 1))
    out_rows_a = rows_a + BR + 8
    out_rows_b = rows_b + BR + 8

    out_shapes = (
        [jax.ShapeDtypeStruct((out_rows_a, LANES), jnp.uint32)] * nA
        + [jax.ShapeDtypeStruct((out_rows_b, LANES), jnp.uint32)] * nB
        + [jax.ShapeDtypeStruct((4,), jnp.int32)])

    # tails rows: [0,nA) A partial-row carries, [nA,nA+nB) B carries,
    # then prev-element carries: bits, tag, bits2?, verify lanes…
    t_prev = nA + nB
    n_tails = t_prev + 2 + (1 if has_b2 else 0) + nv
    scratch = ([pltpu.SMEM((8,), jnp.int32),
                pltpu.VMEM((n_tails, LANES), jnp.uint32)]
               + [pltpu.VMEM((BR + 8, LANES), jnp.uint32)
                  for _ in range(nA + nB)]
               + [pltpu.SemaphoreType.DMA((nA + nB,))])

    def kernel(bits_ref, tag_ref, *rest):
        k = 0
        bits2_ref = rest[k] if has_b2 else None
        k += 1 if has_b2 else 0
        vrefs = rest[k:k + nv]
        k += nv
        lane_refs = rest[k:k + L]
        k += L
        outsA = rest[k:k + nA]
        outsB = rest[k + nA:k + nA + nB]
        cnt_ref = rest[k + nA + nB]
        carr = rest[k + nA + nB + 1]
        tails = rest[k + nA + nB + 2]
        bufsA = list(rest[k + nA + nB + 3:k + nA + nB + 3 + nA])
        bufsB = list(rest[k + nA + nB + 3 + nA:k + nA + nB + 3 + nA + nB])
        sems = rest[k + nA + nB + 3 + nA + nB]
        i = pl.program_id(0)
        bits = bits_ref[:]
        tag = tag_ref[:]
        lane_vals = [r[:] for r in lane_refs]

        @pl.when(i == 0)
        def _():
            carr[0] = 0  # inclusive live-b count so far
            carr[1] = 0  # inclusive output offset so far
            carr[2] = 0  # running max of head b_before (monotone ≥ 0)
            carr[4] = 0  # group A write pointer (n_emit)
            carr[5] = 0  # group B write pointer (n_blive)
            carr[6] = 0  # within-run key-mismatch (hash collision) count
            tails[:] = jnp.zeros((n_tails, LANES), jnp.uint32)

        def prev_of(x, trow, fill0):
            """x shifted down by one in flat order, the vacated head
            filled from the carried last element of the previous block
            (prev-element carries live in tails rows — Mosaic has no
            scalar bitcast, so an SMEM i32 slot can't hold a u32)."""
            pf = jnp.where(i == 0, fill0, tails[trow, LANES - 1])
            return flat_shift(x, jnp.int32(1), fill=pf,
                              interpret=interpret)

        # at i==0 any value ≠ bits[0,0] forces the first run head
        pb = prev_of(bits, t_prev, bits[0, 0] + jnp.uint32(1))
        neq = bits != pb
        if has_b2:
            bits2 = bits2_ref[:]
            neq = neq | (bits2 != prev_of(bits2, t_prev + 2,
                                          bits2[0, 0] + jnp.uint32(1)))
        side = (tag >> 31) == 1
        emit = ((tag >> 30) & 1) == 1
        live = ((tag >> 29) & 1) == 1
        idx_u = tag & jnp.uint32((1 << 29) - 1)

        if nv:
            # hash-collision audit: adjacent LIVE rows inside one run
            # must agree on every true-key lane (prev tag carried for the
            # cross-block boundary; tag fill 0 → prev dead → no flag)
            ptag = prev_of(tag, t_prev + 1, jnp.uint32(0))
            prev_live = ((ptag >> 29) & 1) == 1
            coll = jnp.zeros(bits.shape, bool)
            vbase = t_prev + 2 + (1 if has_b2 else 0)
            for vi in range(nv):
                vl = vrefs[vi][:]
                coll = coll | (vl != prev_of(vl, vbase + vi, jnp.uint32(0)))
            # a live row BELOW a dead row in one run means a live key
            # hashed to the dead rows' forced all-ones slot — its verify
            # chain is interrupted, so that also counts as a collision
            coll = (coll | ~prev_live) & (~neq) & live
            carr[6] = carr[6] + jnp.sum(coll.astype(jnp.int32))

        ib = ((~side) & live).astype(jnp.int32)
        cumb = block_cumsum(ib, interpret) + carr[0]
        bb_at = cumb - ib
        # run-head b_before values are non-decreasing in key order, so a
        # running max of (head ? value : 0) IS the per-run broadcast
        headv = jnp.where(neq, bb_at, 0)
        bb = jnp.maximum(block_cummax(headv, interpret), carr[2])
        m_at = cumb - bb
        eff_m = jnp.where(live, m_at, 0)
        if emit_unmatched_a:
            mm = jnp.where(side & emit, jnp.maximum(eff_m, 1), 0)
        else:
            mm = jnp.where(side & live, eff_m, 0)
        offv = block_cumsum(mm, interpret) + carr[1]
        start = offv - mm
        delta2 = (bb - start) * 2 + (eff_m > 0).astype(jnp.int32)

        # carries must update before the compaction writes bump wptrs
        carr[0] = cumb[BR - 1, LANES - 1]
        carr[1] = offv[BR - 1, LANES - 1]
        carr[2] = bb[BR - 1, LANES - 1]
        tails[t_prev:t_prev + 1, :] = bits[BR - 1:BR, :]
        tails[t_prev + 1:t_prev + 2, :] = tag[BR - 1:BR, :]
        if has_b2:
            tails[t_prev + 2:t_prev + 3, :] = bits2[BR - 1:BR, :]
        for vi in range(nv):
            vb = t_prev + 2 + (1 if has_b2 else 0) + vi
            tails[vb:vb + 1, :] = vrefs[vi][BR - 1:BR, :]

        mA = (mm > 0).astype(jnp.int32)
        valsA = [idx_u,
                 jax.lax.bitcast_convert_type(delta2, jnp.uint32),
                 jax.lax.bitcast_convert_type(start, jnp.uint32)] \
            + lane_vals[:La]
        _compact_write(BR, mA, valsA, list(outsA), carr, 4, tails, 0,
                       bufsA, sems, 0, interpret)
        valsB = [idx_u - jnp.uint32(na)] + lane_vals[:Lb]
        _compact_write(BR, ib, valsB, list(outsB), carr, 5, tails, nA,
                       bufsB, sems, nA, interpret)

        @pl.when(i == pl.num_programs(0) - 1)
        def _():
            cnt_ref[0] = offv[BR - 1, LANES - 1]  # n_out
            cnt_ref[1] = carr[4]                  # n_emit
            cnt_ref[2] = carr[5]                  # n_blive
            cnt_ref[3] = carr[6]                  # hash collisions

    extra_in = ([b2b] if has_b2 else []) + v2 + l2
    res = pl.pallas_call(
        kernel,
        out_shape=out_shapes,
        grid=(blocks,),
        in_specs=[pl.BlockSpec((BR, LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)] * (2 + len(extra_in)),
        out_specs=([pl.BlockSpec(memory_space=pl.ANY)] * (nA + nB)
                   + [pl.BlockSpec(memory_space=pltpu.SMEM)]),
        scratch_shapes=scratch,
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
        interpret=interpret,
    )
    with _x32_trace():
        res = res(b2, t2, *extra_in)
    return res[nA + nB], tuple(res[:nA]), tuple(res[nA:nA + nB])


# ---------------------------------------------------------------------------
# setop_stream — streaming set operations (union/subtract/intersect)
# ---------------------------------------------------------------------------


def setop_stream(bits_s: jnp.ndarray, bits2_s: jnp.ndarray,
                 tag_s: jnp.ndarray, lanes: Sequence[jnp.ndarray],
                 op: int, block_rows: int = 64, interpret: bool = False):
    """ONE sequential pass over the full-row-hash-sorted stream that
    computes a distinct set operation and compacts its output rows —
    replacing the XLA path's ~8 full sorts + scatters (dense ranks,
    first-occurrence, membership, masked-indices; reference semantics:
    table.cpp:729-942 hash-set union/subtract/intersect).

    Inputs sorted together by (bits, bits2, tag): bits/bits2 = 2x32-bit
    full-row hash (dead rows forced all-ones), tag = ``side<<31 |
    live<<29 | iota`` with side=1 for the LEFT table — so within a run
    all right rows precede all left rows, and at any left element the
    inclusive right-prefix count IS the run's right total. lanes carry
    the canonicalized row payload; they double as hash-verify lanes
    (within-run mismatch => counts[1] collision, caller recomputes
    exactly) and as the compacted output.

    op: 0=UNION (first live element of each run, either side),
    1=SUBTRACT (first live left of runs with no live right),
    2=INTERSECT (first live left of runs with at least one live right).

    Returns (counts i32[2] = [n_out, n_collisions], out_streams) with
    out_streams = (idx, lane…) compacted at emitted rows; idx addresses
    the concatenated [left; right] row space.
    """
    n = bits_s.shape[0]
    BR = block_rows
    L = len(lanes)
    nO = 1 + L
    assert BR % 8 == 0 and BR >= 8
    assert n < (1 << 29)
    blocks = max(-(-n // (BR * LANES)), 1)
    rows = blocks * BR
    allones = jnp.uint32(0xFFFFFFFF)
    b1 = pad_rows(bits_s, rows, fill=allones)
    b2 = pad_rows(bits2_s, rows, fill=allones)
    t2 = pad_rows(tag_s, rows, fill=0)
    l2 = [pad_rows(x, rows, fill=0) for x in lanes]

    out_rows = rows_for(n) + BR + 8
    out_shapes = ([jax.ShapeDtypeStruct((out_rows, LANES), jnp.uint32)] * nO
                  + [jax.ShapeDtypeStruct((2,), jnp.int32)])

    # tails: [0,nO) output-group partial rows, then prev carries:
    # bits, bits2, tag, lanes…
    t_prev = nO
    n_tails = t_prev + 3 + L
    scratch = ([pltpu.SMEM((8,), jnp.int32),
                pltpu.VMEM((n_tails, LANES), jnp.uint32)]
               + [pltpu.VMEM((BR + 8, LANES), jnp.uint32)
                  for _ in range(nO)]
               + [pltpu.SemaphoreType.DMA((nO,))])

    def kernel(b1_ref, b2_ref, tag_ref, *rest):
        lane_refs = rest[:L]
        outs = rest[L:L + nO]
        cnt_ref = rest[L + nO]
        carr = rest[L + nO + 1]
        tails = rest[L + nO + 2]
        bufs = list(rest[L + nO + 3:L + nO + 3 + nO])
        sems = rest[L + nO + 3 + nO]
        i = pl.program_id(0)
        bits = b1_ref[:]
        bits2 = b2_ref[:]
        tag = tag_ref[:]
        lane_vals = [r[:] for r in lane_refs]

        @pl.when(i == 0)
        def _():
            carr[0] = 0  # inclusive live-left count
            carr[1] = 0  # inclusive live-right count
            carr[2] = 0  # running max of head left-before
            carr[3] = 0  # running max of head right-before
            carr[4] = 0  # output write pointer
            carr[6] = 0  # collision count
            tails[:] = jnp.zeros((n_tails, LANES), jnp.uint32)

        def prev_of(x, trow, fill0):
            pf = jnp.where(i == 0, fill0, tails[trow, LANES - 1])
            return flat_shift(x, jnp.int32(1), fill=pf,
                              interpret=interpret)

        neq = (bits != prev_of(bits, t_prev, bits[0, 0] + jnp.uint32(1))) \
            | (bits2 != prev_of(bits2, t_prev + 1,
                                bits2[0, 0] + jnp.uint32(1)))
        side = (tag >> 31) == 1
        live = ((tag >> 29) & 1) == 1
        idx_u = tag & jnp.uint32((1 << 29) - 1)

        ptag = prev_of(tag, t_prev + 2, jnp.uint32(0))
        prev_live = ((ptag >> 29) & 1) == 1
        coll = jnp.zeros(bits.shape, bool)
        for vi in range(L):
            coll = coll | (lane_vals[vi] != prev_of(
                lane_vals[vi], t_prev + 3 + vi, jnp.uint32(0)))
        coll = (coll | ~prev_live) & (~neq) & live
        carr[6] = carr[6] + jnp.sum(coll.astype(jnp.int32))

        ill = (side & live).astype(jnp.int32)
        ibr = ((~side) & live).astype(jnp.int32)
        cum_l = block_cumsum(ill, interpret) + carr[0]
        cum_r = block_cumsum(ibr, interpret) + carr[1]
        # run-head prefix broadcast via running max (heads non-decreasing)
        l_before = jnp.maximum(
            block_cummax(jnp.where(neq, cum_l - ill, 0), interpret),
            carr[2])
        r_before = jnp.maximum(
            block_cummax(jnp.where(neq, cum_r - ibr, 0), interpret),
            carr[3])
        l_at = cum_l - l_before  # inclusive live-left count within run
        r_at = cum_r - r_before  # inclusive live-right count within run

        if op == 0:      # UNION: first live element of the run
            emitm = live & ((l_at + r_at) == 1)
        elif op == 1:    # SUBTRACT: first live left, no live right in run
            emitm = (ill == 1) & (l_at == 1) & (r_at == 0)
        else:            # INTERSECT: first live left, some live right
            emitm = (ill == 1) & (l_at == 1) & (r_at > 0)

        carr[0] = cum_l[BR - 1, LANES - 1]
        carr[1] = cum_r[BR - 1, LANES - 1]
        carr[2] = l_before[BR - 1, LANES - 1]
        carr[3] = r_before[BR - 1, LANES - 1]
        tails[t_prev:t_prev + 1, :] = bits[BR - 1:BR, :]
        tails[t_prev + 1:t_prev + 2, :] = bits2[BR - 1:BR, :]
        tails[t_prev + 2:t_prev + 3, :] = tag[BR - 1:BR, :]
        for vi in range(L):
            tails[t_prev + 3 + vi:t_prev + 4 + vi, :] = \
                lane_vals[vi][BR - 1:BR, :]

        _compact_write(BR, emitm.astype(jnp.int32), [idx_u] + lane_vals,
                       list(outs), carr, 4, tails, 0, bufs, sems, 0,
                       interpret)

        @pl.when(i == pl.num_programs(0) - 1)
        def _():
            cnt_ref[0] = carr[4]
            cnt_ref[1] = carr[6]

    res = pl.pallas_call(
        kernel,
        out_shape=out_shapes,
        grid=(blocks,),
        in_specs=[pl.BlockSpec((BR, LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)] * (3 + L),
        out_specs=([pl.BlockSpec(memory_space=pl.ANY)] * nO
                   + [pl.BlockSpec(memory_space=pltpu.SMEM)]),
        scratch_shapes=scratch,
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
        interpret=interpret,
    )
    with _x32_trace():
        res = res(b1, b2, t2, *l2)
    return res[nO], tuple(res[:nO])


# ---------------------------------------------------------------------------
# join_expand_stream — the streaming join materializer
# ---------------------------------------------------------------------------


def join_expand_stream(counts: jnp.ndarray,
                       a_streams: Sequence[jnp.ndarray],
                       b_streams: Sequence[jnp.ndarray],
                       cap_e: int, block_rows: int = 64,
                       interpret: bool = False):
    """Expand a compacted join plan into the output rows — the streaming
    replacement for the XLA scatter+cumsum+row-gather chain that dominated
    the join at ~30 ns/row (profiled: ordx 228 ms + two output-sized row
    gathers ~1.1 s at 17M output rows on v5e).

    The key structural facts the kernel exploits:
      * group A's output starts are STRICTLY increasing over emitting
        runs, so the covering-run ordinal of output j is monotone — each
        output block needs only a (BR+8)-row window of group A at the
        carried run pointer, searched with `inverse_monotone`;
      * within a run, b positions are CONSECUTIVE (bpos = j + delta), and
        run lo's are non-decreasing, so each block's b reads live in a
        short span walked with a windowed loop whose TOTAL work across
        blocks is bounded by one streaming pass over group B (plus one
        window per duplicate-key reset).

    counts: i32[4] from join_plan_stream. a_streams: (elist, delc,
    startsc, a_lane…); b_streams: (blist, b_lane…) — padded (rows, LANES)
    u32 blocks as returned by join_plan_stream. cap_e: static output
    capacity, must be a multiple of block_rows*LANES.

    Returns (aidx, bidx, a_lane_outs, b_lane_outs): i32/u32 (cap_e,)
    arrays; aidx = −1 beyond n_out, bidx = −1 where the row has no build
    match; lanes are zeroed where their side's index is −1.
    """
    BR = block_rows
    assert BR % 8 == 0 and BR >= 8
    assert cap_e % (BR * LANES) == 0 and cap_e > 0
    nA, nB = len(a_streams), len(b_streams)
    La, Lb = nA - 3, nB - 1
    nblocks = cap_e // (BR * LANES)
    W = BR + 8  # window rows; DMA row counts must be multiples of 8
    tot_a = a_streams[0].shape[0]
    tot_b = b_streams[0].shape[0]
    assert tot_a >= W and tot_b >= W, "plan streams carry BR+8 slack rows"

    out_shapes = ([jax.ShapeDtypeStruct((nblocks * BR, LANES), jnp.int32)] * 2
                  + [jax.ShapeDtypeStruct((nblocks * BR, LANES), jnp.uint32)]
                  * (La + Lb))

    scratch = ([pltpu.SMEM((2,), jnp.int32)]
               + [pltpu.VMEM((W, LANES), jnp.uint32)
                  for _ in range(nA + nB)]
               + [pltpu.SemaphoreType.DMA((nA + nB,))])

    def kernel(cnt_ref, *rest):
        a_refs = rest[:nA]
        b_refs = rest[nA:nA + nB]
        o_aidx = rest[nA + nB]
        o_bidx = rest[nA + nB + 1]
        o_alane = rest[nA + nB + 2:nA + nB + 2 + La]
        o_blane = rest[nA + nB + 2 + La:nA + nB + 2 + La + Lb]
        carr = rest[nA + nB + 2 + La + Lb]
        bufsA = list(rest[nA + nB + 3 + La + Lb:
                          nA + nB + 3 + La + Lb + nA])
        bufsB = list(rest[nA + nB + 3 + La + Lb + nA:
                          nA + nB + 3 + La + Lb + nA + nB])
        sems = rest[nA + nB + 3 + La + Lb + nA + nB]
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            carr[0] = 0  # run pointer: ordinal of prev block's last output

        n_out = cnt_ref[0]
        n_emit = cnt_ref[1]

        # --- group A window at the carried run pointer ---
        arow0 = jnp.minimum(carr[0] // LANES, tot_a - W)
        for k in range(nA):
            pltpu.make_async_copy(a_refs[k].at[pl.ds(arow0, W)], bufsA[k],
                                  sems.at[k]).start()
        for k in range(nA):
            pltpu.make_async_copy(a_refs[k].at[pl.ds(arow0, W)], bufsA[k],
                                  sems.at[k]).wait()
        base_e = arow0 * LANES
        ge = base_e + flat_iota((W, LANES))
        s_raw = jax.lax.bitcast_convert_type(bufsA[2][:], jnp.int32)
        s_win = jnp.where(ge < n_emit, s_raw, _I32MAX)
        j = i * (BR * LANES) + flat_iota((BR, LANES))
        # ordinal = #{r global : start[r] <= j} − 1; every pre-window run
        # starts at/before the carried pointer's covered output, so the
        # window count + base_e is the global count
        cnt_le = inverse_monotone(s_win, j)
        ordinal = base_e + cnt_le - 1
        woff = jnp.maximum(cnt_le - 1, 0)
        d2 = sweep_gather(
            jax.lax.bitcast_convert_type(bufsA[1][:], jnp.int32), woff)
        aidx = sweep_gather(
            jax.lax.bitcast_convert_type(bufsA[0][:], jnp.int32), woff)
        alanes = [sweep_gather(bufsA[3 + k][:], woff) for k in range(La)]
        valid = j < n_out
        has = ((d2 & 1) == 1) & valid
        bpos = j + (d2 >> 1)  # arithmetic shift: delta may be negative
        carr[0] = jnp.maximum(ordinal[BR - 1, LANES - 1], 0)

        # --- group B windowed walk over the block's bpos span ---
        bposv = jnp.where(has, bpos, _I32MAX)
        minb = jnp.min(bposv)
        maxb = jnp.max(jnp.where(has, bpos, -1))
        brow0 = jnp.clip(minb // LANES, 0, tot_b - W)
        nw = jnp.where(maxb >= 0,
                       (jnp.minimum(maxb // LANES, tot_b - 1) - brow0) // W
                       + 1, 0)
        outs0 = tuple(jnp.zeros((BR, LANES), jnp.uint32)
                      for _ in range(nB))

        def body(w, outs):
            brow = jnp.minimum(brow0 + w * W, tot_b - W)
            for k in range(nB):
                pltpu.make_async_copy(b_refs[k].at[pl.ds(brow, W)],
                                      bufsB[k], sems.at[nA + k]).start()
            for k in range(nB):
                pltpu.make_async_copy(b_refs[k].at[pl.ds(brow, W)],
                                      bufsB[k], sems.at[nA + k]).wait()
            off = bpos - brow * LANES
            inwin = has & (off >= 0) & (off < W * LANES)
            return tuple(
                jnp.where(inwin, sweep_gather(bufsB[k][:],
                                              jnp.where(inwin, off, -1)),
                          outs[k])
                for k in range(nB))

        outs = jax.lax.fori_loop(0, nw, body, outs0)

        o_aidx[:] = jnp.where(valid, aidx, -1)
        o_bidx[:] = jnp.where(
            has, jax.lax.bitcast_convert_type(outs[0], jnp.int32), -1)
        for k in range(La):
            o_alane[k][:] = jnp.where(valid, alanes[k], jnp.uint32(0))
        for k in range(Lb):
            o_blane[k][:] = jnp.where(has, outs[1 + k], jnp.uint32(0))

    res = pl.pallas_call(
        kernel,
        out_shape=out_shapes,
        grid=(nblocks,),
        in_specs=([pl.BlockSpec(memory_space=pltpu.SMEM)]
                  + [pl.BlockSpec(memory_space=pl.ANY)] * (nA + nB)),
        out_specs=[pl.BlockSpec((BR, LANES), lambda i: (i, 0))] * len(
            out_shapes),
        scratch_shapes=scratch,
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
        interpret=interpret,
    )
    with _x32_trace():
        res = res(counts, *a_streams, *b_streams)
    flat = [r.reshape(-1) for r in res]
    return (flat[0], flat[1], tuple(flat[2:2 + La]),
            tuple(flat[2 + La:2 + La + Lb]))


def _compact_write(BR, m, vals, out_refs, wptr, wslot, tails, trow0,
                   bufs, sems, srow0, interpret):
    """Compact the masked elements of `vals` (VMEM (BR,128) u32 values,
    mask m int32 0/1) onto `out_refs` at the running write pointer
    ``wptr[wslot]``, carrying the partial-row tail in rows trow0.. of
    `tails` and using semaphores srow0.. of `sems`.

    Staged-shift compaction: selected element at j must move UP by
    d[j] = #unselected before j (monotone non-decreasing). Moving by
    d's bits low-to-high is collision-free: for j1<j2 (both selected),
    (d2 mod 2^b) - (d1 mod 2^b) <= d2-d1 < j2-j1, so partial positions
    j - (d mod 2^b) stay strictly ordered. O(log span) cheap vector
    passes — no in-VMEM scatter, no O(rows) sweeps."""
    nstreams = len(vals)
    P = block_cumsum(m, interpret)
    cnt = P[BR - 1, LANES - 1]
    base = wptr[wslot]
    s = base % _L32

    one_u = np.uint32(1)
    q = flat_iota((BR, LANES))
    d = q + np.int32(1) - P  # unselected before j (exclusive, j selected)
    pack = ((d.astype(jnp.uint32) << one_u) | m.astype(jnp.uint32))
    vals = list(vals)
    span = BR * LANES
    k = 1
    b = 0
    while k < span:
        pa = flat_shift_up(pack, k, 0, interpret)
        bshift = np.uint32(b)
        take = ((pa & one_u) == one_u) \
            & (((pa >> one_u) >> bshift) & one_u == one_u)
        keep = ((pack & one_u) == one_u) \
            & (((pack >> one_u) >> bshift) & one_u == np.uint32(0))
        pack = jnp.where(take, pa, jnp.where(keep, pack, jnp.uint32(0)))
        vals = [jnp.where(take, flat_shift_up(v, k, 0, interpret),
                          jnp.where(keep, v, jnp.uint32(0)))
                for v in vals]
        k <<= 1
        b += 1

    valid = q < cnt
    lane1 = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
    for k in range(nstreams):
        v = jnp.where(valid, vals[k], jnp.uint32(0))
        ext = jnp.concatenate([v, jnp.zeros((8, LANES), v.dtype)])
        shifted = flat_shift(ext, s, 0, interpret)
        first = jnp.where(lane1 < s, tails[trow0 + k:trow0 + k + 1, :],
                          shifted[0:1, :])
        blk = jnp.concatenate([first, shifted[1:]])
        bufs[k][:] = blk
        pltpu.make_async_copy(
            bufs[k], out_refs[k].at[pl.ds(base // _L32, BR + 8)],
            sems.at[srow0 + k]).start()
    newp = base + cnt
    rel = newp // _L32 - base // _L32
    for k in range(nstreams):
        pltpu.make_async_copy(
            bufs[k], out_refs[k].at[pl.ds(base // _L32, BR + 8)],
            sems.at[srow0 + k]).wait()
        tails[trow0 + k:trow0 + k + 1, :] = bufs[k][pl.ds(rel, 1), :]
    wptr[wslot] = newp
    return newp


def _compact_streams(nstreams, BR, mask_ref, streams, out_refs, cnt_ref,
                     wptr, tails, bufs, sems, interpret=False):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        wptr[0] = 0
        for k in range(nstreams):
            tails[k:k + 1, :] = jnp.zeros((1, LANES), jnp.uint32)

    m = (mask_ref[:] != 0).astype(jnp.int32)
    vals = [st[:] for st in streams]
    base = wptr[0]  # write pointer before this block's compaction
    newp = _compact_write(BR, m, vals, out_refs, wptr, 0, tails, 0,
                          bufs, sems, 0, interpret)

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        cnt_ref[0] = newp
        # The documented contract zero-pads the tail; real HBM outputs are
        # not zero-initialized, so sweep zero windows over whatever lies
        # beyond the final write window.
        total_rows = pl.num_programs(0) * BR + BR + 8
        start = base // LANES + BR + 8
        nwin = (total_rows - start + (BR + 8) - 1) // (BR + 8)
        for k in range(nstreams):
            bufs[k][:] = jnp.zeros((BR + 8, LANES), jnp.uint32)

        def zero_one(w, _):
            for k in range(nstreams):
                pltpu.make_async_copy(
                    bufs[k],
                    out_refs[k].at[pl.ds(jnp.minimum(
                        start + w * (BR + 8),
                        total_rows - (BR + 8)), BR + 8)],
                    sems.at[k]).start()
            for k in range(nstreams):
                pltpu.make_async_copy(
                    bufs[k],
                    out_refs[k].at[pl.ds(jnp.minimum(
                        start + w * (BR + 8),
                        total_rows - (BR + 8)), BR + 8)],
                    sems.at[k]).wait()
            return _

        jax.lax.fori_loop(0, nwin, zero_one, 0)


# ---------------------------------------------------------------------------
# partition_hist / partition_scatter — the fused shuffle partitioner
# ---------------------------------------------------------------------------
# The counted padded exchange (parallel/shuffle._padded_partition) needs a
# STABLE partition of every payload leaf into <= W+1 contiguous buckets
# (W live targets + the dead-row tail). The XLA route is a full stable
# multi-operand `jax.lax.sort` by target — a comparison network priced
# O(n log n) (96-192 ms for a 33M-row multi-operand sort on v5e) where
# the problem only needs a counting sort. These two kernels replace it
# with the SURVEY §7 shape: one histogram pass and one scatter pass,
# both bandwidth-bound sequential HBM streams.
#
# * ``partition_hist``   — pass 1: streams the target-id blocks once and
#   emits the per-block × per-bucket histogram. Summed over blocks it is
#   the counts vector (replacing W compare-sum passes of
#   shuffle._target_counts); exclusively scanned it is the bucket start
#   offsets. Zero extra passes over payload.
# * ``partition_scatter`` — pass 2: a (nbuckets, blocks) grid, bucket-
#   major. TPU grid order is sequential, so appending each block's
#   bucket-w rows (staged-shift compaction, `_compact_write`'s
#   partial-row-tail discipline, ONE global write pointer) IS a stable
#   counting sort: bucket 0's rows land first in block order, then
#   bucket 1's, … — bit-for-bit the permutation `jax.lax.sort(…,
#   is_stable=True)` by target produces. Every payload leaf rides the
#   same pass as a u32 leg, so one kernel materializes the whole
#   partition (varbytes word legs included).
#
# Traffic: pass 2 re-streams the input once per bucket (blocked
# prefetch), so the pair costs ~(W+2) elementwise-priced passes — a win
# over the sort up to W≈16 (shuffle routes by world size; empty-bucket
# appends skip their DMA entirely, so clustered/skewed inputs pay less).
# ---------------------------------------------------------------------------


def partition_hist(t_s: jnp.ndarray, nbuckets: int, block_rows: int = 32,
                   interpret: bool = False) -> jnp.ndarray:
    """Per-block bucket histogram of a target-id stream.

    t_s: (n,) int32 bucket ids in [0, nbuckets); out-of-range ids are
    never counted (padding uses id nbuckets). Returns (blocks,
    nbuckets) int32 with blocks = ceil(n / (block_rows*128)):
    ``out[b, w]`` = #rows of block b with id w. ``out.sum(0)`` is the
    counts vector; an exclusive scan of it the bucket starts.
    Requires nbuckets <= 128 (one lane row carries a block's histogram).
    """
    n = t_s.shape[0]
    BR = block_rows
    assert BR % 8 == 0 and BR >= 8
    assert 1 <= nbuckets <= LANES
    blocks = max(-(-n // (BR * LANES)), 1)
    rows = blocks * BR
    t2 = pad_rows(t_s.astype(jnp.int32), rows, fill=nbuckets)

    def kernel(t_ref, hist_ref):
        tv = t_ref[:]
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
        row = jnp.zeros((1, LANES), jnp.int32)
        for w in range(nbuckets):
            c = jnp.sum((tv == w).astype(jnp.int32))
            row = jnp.where(lane == w, c, row)
        hist_ref[:] = row

    res = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((blocks, LANES), jnp.int32),
        grid=(blocks,),
        in_specs=[pl.BlockSpec((BR, LANES), lambda b: (b, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, LANES), lambda b: (b, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )
    with _x32_trace():
        res = res(t2)
    return res[:, :nbuckets]


def partition_scatter(t_s: jnp.ndarray, streams: Sequence[jnp.ndarray],
                      nbuckets: int, block_rows: int = 32,
                      interpret: bool = False
                      ) -> Tuple[jnp.ndarray, ...]:
    """Stable counting scatter of u32 streams into bucket-contiguous
    layout — the partition permutation applied to every leg at once.

    t_s: (n,) int32 bucket ids in [0, nbuckets); streams: (n,) u32 legs
    (callers bitcast/split wider dtypes). Returns one (n,) u32 array
    per leg holding ``leg[perm]`` where perm is the stable sort by
    bucket id — identical to ``jax.lax.sort((t,)+legs, num_keys=1,
    is_stable=True)`` including rows of the last (dead) bucket.

    Grid is (nbuckets, blocks), bucket-major; grid order on TPU is
    sequential, so the single carried write pointer makes the appends a
    stable counting sort. A (bucket, block) pair with no matching rows
    skips its compaction and DMA entirely.
    """
    n = t_s.shape[0]
    BR = block_rows
    L = len(streams)
    assert BR % 8 == 0 and BR >= 8
    assert 1 <= nbuckets <= LANES
    assert L >= 1
    for s in streams:
        assert s.dtype == jnp.uint32, \
            f"partition_scatter takes u32 legs, got {s.dtype}"
        assert s.shape == (n,)
    blocks = max(-(-n // (BR * LANES)), 1)
    rows = blocks * BR
    # pad id nbuckets: matches NO grid bucket, so padding is never
    # scattered and the write pointer ends exactly at n
    t2 = pad_rows(t_s.astype(jnp.int32), rows, fill=nbuckets)
    s2 = [pad_rows(s, rows) for s in streams]

    out_rows = rows + BR + 8  # append windows may extend past rows

    scratch = ([pltpu.SMEM((1,), jnp.int32),
                pltpu.VMEM((L, LANES), jnp.uint32)]
               + [pltpu.VMEM((BR + 8, LANES), jnp.uint32)
                  for _ in range(L)]
               + [pltpu.SemaphoreType.DMA((L,))])

    out_shapes = [jax.ShapeDtypeStruct((out_rows, LANES), jnp.uint32)
                  for _ in range(L)]

    def kernel(t_ref, *rest):
        srefs = rest[:L]
        outs = list(rest[L:2 * L])
        wptr = rest[2 * L]
        tails = rest[2 * L + 1]
        bufs = list(rest[2 * L + 2:2 * L + 2 + L])
        sems = rest[2 * L + 2 + L]
        w = pl.program_id(0)
        b = pl.program_id(1)

        @pl.when((w == 0) & (b == 0))
        def _():
            # jnp.int32, not a bare 0: a weak python literal survives
            # into the kernel jaxpr and is re-canonicalized to int64
            # when the interpret lowering runs under jax_enable_x64 —
            # the store then fails the dynamic_update_slice dtype check
            wptr[0] = jnp.int32(0)
            tails[:] = jnp.zeros((L, LANES), jnp.uint32)

        m = (t_ref[:] == w).astype(jnp.int32)

        @pl.when(jnp.sum(m) > 0)
        def _():
            _compact_write(BR, m, [r[:] for r in srefs], outs, wptr, 0,
                           tails, 0, bufs, sems, 0, interpret)

    res = pl.pallas_call(
        kernel,
        out_shape=out_shapes,
        grid=(nbuckets, blocks),
        in_specs=[pl.BlockSpec((BR, LANES), lambda w, b: (b, 0),
                               memory_space=pltpu.VMEM)] * (1 + L),
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * L,
        scratch_shapes=scratch,
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
        interpret=interpret,
    )
    with _x32_trace():
        res = res(t2, *s2)
    return tuple(o.reshape(-1)[:n] for o in res)


# ---------------------------------------------------------------------------
# groupby_stream — streaming grouped aggregation
# ---------------------------------------------------------------------------
# A groupby_stream kernel (segmented-scan grouped aggregation) lived
# here through rounds 2-3; it measured 10-11M rows/s vs the XLA segment
# path's 13-19M on v5e and was removed per the round-3 review rather
# than shipped as a slower parallel implementation (see git history).
# ---------------------------------------------------------------------------
