"""Ordering primitives: order-preserving bit normalization, multi-key
lexicographic argsort, and dense group-rank computation.

This replaces the reference's comparator/sort-kernel layer (reference:
cpp/src/cylon/arrow/arrow_comparator.hpp/.cpp `ArrowComparator`/
`TableRowComparator`; arrow_kernels.hpp:132-275 sort kernels;
util/sort.hpp quicksort) with a TPU-idiomatic design: every comparable
column is mapped to an unsigned integer array whose natural ordering equals
the column's value ordering ("ordered bits"), so ALL multi-column
comparisons become vectorized integer sorts — no per-row callbacks, no
branching, everything XLA-fusible.

Dense ranks are the workhorse: two tables' key columns are concatenated,
lexsorted once, and each distinct key row gets a dense integer id. Joins,
set ops and group-bys then operate on these int32 ids — one representation
for numeric, string (dictionary codes), temporal and multi-column keys.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..data.column import Column
from ..status import Code, CylonError

_WIDTH_UINT = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}


def ordered_bits(col: Column, descending: bool = False) -> jnp.ndarray:
    """Column wrapper over `ordered_bits_raw`."""
    if col.is_varbytes:
        # loud guard: a varbytes column has no single ordered-bits array —
        # order needs sort_prefix_keys, equality needs hash_keys
        raise CylonError(Code.TypeError,
                         "varbytes columns need sort_prefix_keys/hash_keys, "
                         "not ordered_bits")
    return ordered_bits_raw(col.data, col.is_string, descending)


def ordered_bits_raw(x: jnp.ndarray, is_string: bool = False,
                     descending: bool = False) -> jnp.ndarray:
    """Map values to unsigned ints preserving value order (traceable —
    usable inside jit/shard_map programs).

    * unsigned ints: identity
    * signed ints: flip the sign bit
    * floats: IEEE total-order trick (flip all bits for negatives, sign bit
      for positives); -0.0 is normalized to +0.0 first so equality matches
      IEEE semantics
    * bool: widen to uint8
    * strings: dictionary codes are already rank-preserving (sorted vocab)

    Nulls are NOT handled here — callers combine with ``valid_mask``.
    """
    if is_string:
        out = x.astype(jnp.uint32)
    else:
        dt = x.dtype
        if dt == jnp.bool_:
            out = x.astype(jnp.uint8)
        elif jnp.issubdtype(dt, jnp.unsignedinteger):
            out = x
        elif jnp.issubdtype(dt, jnp.signedinteger):
            w = dt.itemsize
            u = _WIDTH_UINT[w]
            out = x.astype(u) ^ jnp.asarray(np.uint64(1) << (8 * w - 1), u)
        elif jnp.issubdtype(dt, jnp.floating):
            w = dt.itemsize
            u = _WIDTH_UINT[w]
            xz = jnp.where(x == 0, jnp.zeros((), dt), x)  # -0.0 -> +0.0
            bits = xz.view(u)
            sign = (bits >> (8 * w - 1)).astype(bool)
            allones = jnp.asarray(~np.uint64(0) >> (64 - 8 * w), u)
            signbit = jnp.asarray(np.uint64(1) << (8 * w - 1), u)
            out = jnp.where(sign, ~bits & allones, bits ^ signbit)
        else:
            raise CylonError(Code.TypeError, f"unorderable dtype {dt}")
    if descending:
        allones = jnp.asarray(~np.uint64(0) >> (64 - 8 * out.dtype.itemsize),
                              out.dtype)
        out = out ^ allones
    return out


def sort_keys(cols: Sequence[Column],
              ascending: Optional[Sequence[bool]] = None,
              nulls_last: bool = True) -> List[jnp.ndarray]:
    """Per-column ordered-bit arrays with nulls pushed to one end.

    Null placement: each column's keys are widened by nothing — instead the
    null rows get the extreme value of the column's bit domain, and ties are
    broken by later keys, matching "nulls last/first" sort semantics.
    """
    out = []
    for i, c in enumerate(cols):
        desc = bool(ascending is not None and not ascending[i])
        k = ordered_bits(c, descending=desc)
        if c.validity is not None:
            w = k.dtype.itemsize
            extreme = jnp.asarray(~np.uint64(0) >> (64 - 8 * w), k.dtype) \
                if nulls_last else jnp.zeros((), k.dtype)
            k = jnp.where(c.validity, k, extreme)
        out.append(k)
    return out


def lexsort_indices(keys: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """Stable argsort by keys[0] (primary) then keys[1], ... (numpy lexsort
    convention reversed). Single fused `lax.sort` call — XLA sorts all
    operands together, so this is one O(n log n) device sort regardless of
    key count."""
    n = keys[0].shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    import jax.lax as lax

    res = lax.sort(tuple(keys) + (iota,), num_keys=len(keys))
    return res[-1]


def row_neq_sorted(sorted_keys: Sequence[jnp.ndarray],
                   sorted_valid: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Boolean array: row i differs from row i-1 (row 0 = True)."""
    n = sorted_keys[0].shape[0]
    neq = jnp.zeros(n, dtype=bool).at[0].set(True)
    for k in sorted_keys:
        d = jnp.zeros(n, dtype=bool).at[1:].set(k[1:] != k[:-1])
        neq = neq | d
    if sorted_valid is not None:
        d = jnp.zeros(n, dtype=bool).at[1:].set(
            sorted_valid[1:] != sorted_valid[:-1])
        neq = neq | d
    return neq


def dense_ranks(keys: Sequence[jnp.ndarray]) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dense group ids for each row (0-based, ordered by key order).

    Returns (gid, perm) where gid[i] is the rank of row i's key among the
    distinct keys and perm is the stable lexsort permutation.
    """
    perm = lexsort_indices(keys)
    sk = [k[perm] for k in keys]
    neq = row_neq_sorted(sk)
    gid_sorted = jnp.cumsum(neq.astype(jnp.int32)) - 1
    gid = jnp.zeros_like(gid_sorted).at[perm].set(gid_sorted)
    return gid, perm


def dense_ranks_two(keys_l: Sequence[jnp.ndarray],
                    keys_r: Sequence[jnp.ndarray]
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dense ranks over the UNION of two key sets: returns (gid_l, gid_r)
    on a shared id space, so cross-table equality is integer equality.

    This is the TPU replacement for the reference's hash-multimap build/
    probe (arrow_hash_kernels.hpp:48-225): instead of pointer-chasing a
    multimap, one fused sort of the concatenated keys yields ids that both
    sides share.
    """
    nl = keys_l[0].shape[0]
    cat = [jnp.concatenate([a, b]) for a, b in zip(keys_l, keys_r)]
    gid, _ = dense_ranks(cat)
    return gid[:nl], gid[nl:]
