"""Row hashing for partitioning — vectorized murmur-style finalizers.

Replaces the reference's per-row MurmurHash3 partition kernels (reference:
cpp/src/cylon/arrow/arrow_partition_kernels.hpp:29-226, util/murmur3.cpp)
with whole-column integer mixing on the VPU: every lane is hashed in
parallel with the murmur3 fmix32/fmix64 avalanche, and multi-column row
hashes combine per-column hashes with the same `31*h + h_col` scheme the
reference uses (arrow_partition_kernels.cpp:90-99) so partition placement
stays deterministic across column counts.

String columns hash their dictionary codes — consistent within one
shuffle because vocabularies are unified before partitioning.
"""
from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import numpy as np

from ..data.column import Column
from .order import ordered_bits

_C1 = np.uint32(0x85EBCA6B)
_C2 = np.uint32(0xC2B2AE35)


def fmix32(h: jnp.ndarray) -> jnp.ndarray:
    """murmur3 32-bit finalizer (avalanche)."""
    h = h ^ (h >> 16)
    h = h * _C1
    h = h ^ (h >> 13)
    h = h * _C2
    h = h ^ (h >> 16)
    return h


def fmix32b(h: jnp.ndarray) -> jnp.ndarray:
    """Second, independent 32-bit avalanche ("lowbias32" constants) — the
    hash-join path pairs it with fmix32 so a row carries 2x32 independent
    hash bits; a pair collision needs BOTH to collide (~2^-64 per pair),
    and the plan kernel's verify lanes catch even those exactly."""
    h = h ^ (h >> 16)
    h = h * np.uint32(0x7FEB352D)
    h = h ^ (h >> 15)
    h = h * np.uint32(0x846CA68B)
    h = h ^ (h >> 16)
    return h


def fmix64(h: jnp.ndarray) -> jnp.ndarray:
    """murmur3/splitmix 64-bit finalizer."""
    h = h ^ (h >> 33)
    h = h * np.uint64(0xFF51AFD7ED558CCD)
    h = h ^ (h >> 33)
    h = h * np.uint64(0xC4CEB9FE1A85EC53)
    h = h ^ (h >> 33)
    return h


def hash_column(col: Column) -> jnp.ndarray:
    """Per-row uint32 hash of one column. Equal values hash equal (floats
    use the same -0.0-normalized bits as ordering; nulls hash to a fixed
    tag). Varbytes strings hash their full byte content on device
    (strings.VarBytes.hash_keys — the reference's BinaryHashPartitionKernel
    analog, arrow_partition_kernels.hpp:94)."""
    if col.is_varbytes:
        h1, _h2, _h3, _ln = col.varbytes.hash_keys()
        if col.validity is not None:
            h1 = jnp.where(col.validity, h1, jnp.uint32(0x9E3779B9))
        return h1
    bits = ordered_bits(col)
    if bits.dtype.itemsize == 8:
        h = fmix64(bits.astype(jnp.uint64))
        h32 = (h ^ (h >> 32)).astype(jnp.uint32)
    else:
        h32 = fmix32(bits.astype(jnp.uint32))
    if col.validity is not None:
        h32 = jnp.where(col.validity, h32, jnp.uint32(0x9E3779B9))
    return h32


def hash_columns(cols: Sequence[Column]) -> jnp.ndarray:
    """Combined row hash over several columns (reference combine scheme)."""
    h = jnp.zeros(len(cols[0]), dtype=jnp.uint32)
    for c in cols:
        h = h * np.uint32(31) + hash_column(c)
    return fmix32(h)


def hash2_streams(lanes: Sequence[jnp.ndarray], live) -> "tuple":
    """The 2x32-bit row-hash pair shared by every hash-sorted stream
    path (hash join, wide-key groupby): combine u32 lanes with the
    31/33 schemes over independent avalanches, then force dead rows to
    all-ones so they sort to the tail."""
    n = lanes[0].shape[0]
    h1 = jnp.zeros(n, jnp.uint32)
    h2 = jnp.full(n, jnp.uint32(0x9E3779B9))
    for kl in lanes:
        h1 = h1 * np.uint32(31) + fmix32(kl)
        h2 = h2 * np.uint32(33) + fmix32b(kl)
    allones = jnp.uint32(0xFFFFFFFF)
    h1 = jnp.where(live, fmix32(h1), allones)
    h2 = jnp.where(live, fmix32b(h2), allones)
    return h1, h2


def partition_targets(cols: Sequence[Column], world_size: int) -> jnp.ndarray:
    """Per-row target partition in [0, world_size) — the reference's
    `HashPartitionArray` modulo placement (arrow_partition_kernels.cpp:61-72)."""
    return (hash_columns(cols) % np.uint32(world_size)).astype(jnp.int32)
