"""Group-by aggregation kernels — sort-based segmented reduction.

Replaces the reference's hash-map group-by (reference:
cpp/src/cylon/groupby/groupby_hash.hpp:28-359 — `unordered_map` with
compile-time `AggregateKernel<T,Op>{Init,Update,Finalize}`, and the
sorted-run pipeline variant groupby_pipeline.hpp:28-257) with the TPU
formulation: dense-rank the key column(s) (one device sort), then every
aggregation is a `jax.ops.segment_*` reduction — contiguous, vectorized,
fusible.

Distributed semantics (fixing the reference's re-aggregation subtlety noted
in SURVEY §3.2): partial aggregates are combined with the correct SECOND-
PHASE op — COUNT partials are SUMmed, MEAN carries (sum, count) pairs and
divides at the end. The reference re-applies the same op twice, which makes
distributed COUNT wrong when a key spans ranks (groupby/groupby.cpp:96-139).
"""
from __future__ import annotations

import enum
from functools import partial
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class AggregationOp(enum.IntEnum):
    """Reference: groupby/groupby_aggregate_ops.hpp `GroupByAggregationOp`
    (SUM/COUNT/MIN/MAX); MEAN added (the reference left it commented out,
    groupby_hash.hpp:118-138)."""

    SUM = 0
    COUNT = 1
    MIN = 2
    MAX = 3
    MEAN = 4


def second_phase_op(op: AggregationOp) -> AggregationOp:
    """The op used to merge per-shard partials (COUNT partials are summed)."""
    if op in (AggregationOp.COUNT,):
        return AggregationOp.SUM
    return op


def _identity_for(op: AggregationOp, dtype):
    if op in (AggregationOp.SUM, AggregationOp.COUNT, AggregationOp.MEAN):
        return jnp.zeros((), dtype)
    if op == AggregationOp.MIN:
        return jnp.asarray(_max_of(dtype), dtype)
    return jnp.asarray(_min_of(dtype), dtype)


def _max_of(dtype):
    d = np.dtype(dtype)
    if d.kind == "f":
        return np.inf
    if d.kind == "b":
        return True
    return np.iinfo(d).max


def _min_of(dtype):
    d = np.dtype(dtype)
    if d.kind == "f":
        return -np.inf
    if d.kind == "b":
        return False
    return np.iinfo(d).min


def presort_groups(keys: Tuple[jnp.ndarray, ...], emit: jnp.ndarray,
                   values: Tuple[jnp.ndarray, ...],
                   valids: Tuple[jnp.ndarray, ...]):
    """ONE fused stable sort carries the key bits, every value column,
    every validity mask, emit and iota as operands (dead rows last via a
    dead-flag primary key — the join/sort kernels' trick). Output rows
    are grouped contiguously, so the downstream segment reductions see
    SORTED ids (scatter fast path) and the dense-rank scatter-back the
    old path paid (a ~15-30 ns/element .at[perm].set at full row count)
    disappears entirely.

    ``valids`` entries may be None (all-valid column): None masks don't
    ride the sort — the aggregate reads them as "live row = valid".

    Returns (values_s, valids_s, emit_s, iota_s, gid_s, n_groups) where
    gid_s is the per-SORTED-row dense group id and n_groups a device
    scalar (the caller's single host sync)."""
    n = emit.shape[0]
    dead = (~emit).astype(jnp.uint8)
    iota = jnp.arange(n, dtype=jnp.int32)
    nk, nv = len(keys), len(values)
    real_v = [v for v in valids if v is not None]
    ops_in = (dead,) + tuple(keys) + tuple(values) + tuple(real_v) \
        + (emit, iota)
    res = jax.lax.sort(ops_in, num_keys=1 + nk, is_stable=True)
    ks = res[1:1 + nk]
    values_s = tuple(res[1 + nk:1 + nk + nv])
    it = iter(res[1 + nk + nv:1 + nk + nv + len(real_v)])
    valids_s = tuple(None if v is None else next(it) for v in valids)
    emit_s, iota_s = res[-2], res[-1]
    # row differs from its predecessor on any key lane (row 0 = True);
    # dead rows are all last, so live rows form a prefix and cumsum
    # yields dense 0-based ids in key order
    neq = jnp.zeros(n, dtype=bool).at[0].set(True)
    for k in ks:
        neq = neq | jnp.concatenate([jnp.ones(1, bool), k[1:] != k[:-1]])
    new_grp = neq & emit_s
    gid_s = jnp.cumsum(new_grp.astype(jnp.int32)) - 1
    return (values_s, valids_s, emit_s, iota_s, gid_s,
            new_grp.sum(dtype=jnp.int32))


def sorted_segment_aggregate(gid_s, emit_s, iota_s,
                             values_s: Tuple[jnp.ndarray, ...],
                             valids_s: Tuple[jnp.ndarray, ...],
                             num_segments: int,
                             ops: Tuple[AggregationOp, ...],
                             col_ids: Tuple[int, ...],
                             all_valid: Tuple[bool, ...]):
    """Aggregate presorted value columns into per-group slots.

    Everything rides ``indices_are_sorted=True`` segment ops, and
    duplicate sub-reductions dedup across the op list (static
    ``col_ids`` name each value's source column — the same traced array
    appears as distinct tracers per arg position, so identity can't):
    SUM/MIN/MAX/COUNT repeated on one column run once; MEAN reuses
    COUNT's tally; all-valid columns (``all_valid``) skip both the
    any-valid pass (it equals group_valid) and get one shared count.

    Returns (rep_idx, group_valid, list_of_(agg_array, agg_valid)):
      rep_idx[g] = first ORIGINAL row index holding group g,
      agg arrays have shape [num_segments].
    MEAN returns a float64 array; COUNT returns int64 of non-null values
    (Arrow count semantics)."""
    n = gid_s.shape[0]
    seg = jnp.where(emit_s, gid_s, num_segments)  # masked -> overflow slot

    def seg_sum(x):
        return jax.ops.segment_sum(x, seg, num_segments=num_segments + 1,
                                   indices_are_sorted=True)

    rep = jnp.full(num_segments + 1, n, jnp.int32).at[seg].min(
        jnp.where(emit_s, iota_s, n), indices_are_sorted=True)
    group_valid = rep[:num_segments] < n

    sub = {}

    def memo(key, compute):
        hit = sub.get(key)
        if hit is None:
            hit = sub[key] = compute()
        return hit

    results = []
    for arr, vmask, op, cid, av in zip(values_s, valids_s, ops, col_ids,
                                       all_valid):
        use = emit_s if vmask is None else (emit_s & vmask)
        vkey = "all" if av else cid
        count = lambda: memo(("count", vkey), lambda: seg_sum(
            use.astype(jnp.int64))[:num_segments])
        if op == AggregationOp.COUNT:
            results.append((count(), group_valid))
            continue
        if op == AggregationOp.MEAN:
            s = memo(("msum", cid), lambda: seg_sum(
                jnp.where(use, arr, 0).astype(jnp.float64))[:num_segments])
            c = count().astype(jnp.float64)
            results.append((s / jnp.maximum(c, 1),
                            group_valid & (c > 0)))
            continue
        ident = _identity_for(op, arr.dtype)
        x = jnp.where(use, arr, ident)
        if op == AggregationOp.SUM:
            out = memo(("sum", cid), lambda: seg_sum(x)[:num_segments])
        elif op == AggregationOp.MIN:
            out = memo(("min", cid), lambda: jax.ops.segment_min(
                x, seg, num_segments=num_segments + 1,
                indices_are_sorted=True)[:num_segments])
        else:
            out = memo(("max", cid), lambda: jax.ops.segment_max(
                x, seg, num_segments=num_segments + 1,
                indices_are_sorted=True)[:num_segments])
        if av:
            # all rows valid: a group exists iff it has a live row
            results.append((out, group_valid))
        else:
            anyv = memo(("anyv", cid), lambda: jax.ops.segment_max(
                use.astype(jnp.int32), seg,
                num_segments=num_segments + 1,
                indices_are_sorted=True)[:num_segments])
            results.append((out, group_valid & (anyv > 0)))
    return rep[:num_segments], group_valid, results


presort_groups_jit = jax.jit(presort_groups)

sorted_segment_aggregate_jit = partial(
    jax.jit, static_argnames=("num_segments", "ops", "col_ids",
                              "all_valid"))(sorted_segment_aggregate)


# ---------------------------------------------------------------------------
# A Pallas streaming groupby (ONE fused sort + ONE segmented-scan pass)
# was built and benchmarked in rounds 2-3: 10-11M rows/s vs the XLA
# segment path's 13-19M across 1K-1M group cardinalities on v5e — the
# segmented scans (3+ log-shift passes per block) cost more than the
# scatter they remove, unlike the join/setops kernels where one pass
# replaced several scatter chains. Per the round-3 review it was
# REMOVED rather than shipped as a slower parallel implementation
# (git history: rounds 2-3 carry the kernel and its tests).
# ---------------------------------------------------------------------------

