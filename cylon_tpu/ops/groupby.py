"""Group-by aggregation kernels — sort-based segmented reduction.

Replaces the reference's hash-map group-by (reference:
cpp/src/cylon/groupby/groupby_hash.hpp:28-359 — `unordered_map` with
compile-time `AggregateKernel<T,Op>{Init,Update,Finalize}`, and the
sorted-run pipeline variant groupby_pipeline.hpp:28-257) with the TPU
formulation: dense-rank the key column(s) (one device sort), then every
aggregation is a `jax.ops.segment_*` reduction — contiguous, vectorized,
fusible.

Distributed semantics (fixing the reference's re-aggregation subtlety noted
in SURVEY §3.2): partial aggregates are combined with the correct SECOND-
PHASE op — COUNT partials are SUMmed, MEAN carries (sum, count) pairs and
divides at the end. The reference re-applies the same op twice, which makes
distributed COUNT wrong when a key spans ranks (groupby/groupby.cpp:96-139).
"""
from __future__ import annotations

import enum
from functools import partial
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class AggregationOp(enum.IntEnum):
    """Reference: groupby/groupby_aggregate_ops.hpp `GroupByAggregationOp`
    (SUM/COUNT/MIN/MAX); MEAN added (the reference left it commented out,
    groupby_hash.hpp:118-138)."""

    SUM = 0
    COUNT = 1
    MIN = 2
    MAX = 3
    MEAN = 4


def second_phase_op(op: AggregationOp) -> AggregationOp:
    """The op used to merge per-shard partials (COUNT partials are summed)."""
    if op in (AggregationOp.COUNT,):
        return AggregationOp.SUM
    return op


def _identity_for(op: AggregationOp, dtype):
    if op in (AggregationOp.SUM, AggregationOp.COUNT, AggregationOp.MEAN):
        return jnp.zeros((), dtype)
    if op == AggregationOp.MIN:
        return jnp.asarray(_max_of(dtype), dtype)
    return jnp.asarray(_min_of(dtype), dtype)


def _max_of(dtype):
    d = np.dtype(dtype)
    if d.kind == "f":
        return np.inf
    if d.kind == "b":
        return True
    return np.iinfo(d).max


def _min_of(dtype):
    d = np.dtype(dtype)
    if d.kind == "f":
        return -np.inf
    if d.kind == "b":
        return False
    return np.iinfo(d).min


@partial(jax.jit, static_argnames=("num_segments", "ops"))
def segment_aggregate(gid, values: Tuple[jnp.ndarray, ...],
                      valids: Tuple[jnp.ndarray, ...],
                      emit: jnp.ndarray,
                      num_segments: int,
                      ops: Tuple[AggregationOp, ...]):
    """Aggregate each value column into per-group slots.

    gid: int32 group id per row (any id for non-emitted rows — masked).
    Returns (rep_idx, group_valid, list_of_(agg_array, agg_valid)):
      rep_idx[g] = first row index holding group g (for key materialization),
      agg arrays have shape [num_segments].

    MEAN returns a float64 array; COUNT returns int64 of non-null values
    (Arrow count semantics).
    """
    n = gid.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    seg = jnp.where(emit, gid, num_segments)  # masked rows -> overflow slot
    rep = jnp.full(num_segments + 1, n, jnp.int32).at[seg].min(iota)
    group_valid = rep[:num_segments] < n

    results = []
    for arr, vmask, op in zip(values, valids, ops):
        use = emit & vmask
        if op == AggregationOp.COUNT:
            out = jax.ops.segment_sum(use.astype(jnp.int64), seg,
                                      num_segments=num_segments + 1)
            results.append((out[:num_segments], group_valid))
            continue
        if op == AggregationOp.MEAN:
            x = jnp.where(use, arr, 0).astype(jnp.float64)
            s = jax.ops.segment_sum(x, seg, num_segments=num_segments + 1)
            c = jax.ops.segment_sum(use.astype(jnp.float64), seg,
                                    num_segments=num_segments + 1)
            out = s[:num_segments] / jnp.maximum(c[:num_segments], 1)
            results.append((out, group_valid & (c[:num_segments] > 0)))
            continue
        ident = _identity_for(op, arr.dtype)
        x = jnp.where(use, arr, ident)
        if op == AggregationOp.SUM:
            out = jax.ops.segment_sum(x, seg, num_segments=num_segments + 1)
        elif op == AggregationOp.MIN:
            out = jax.ops.segment_min(x, seg, num_segments=num_segments + 1)
        else:
            out = jax.ops.segment_max(x, seg, num_segments=num_segments + 1)
        any_valid = jax.ops.segment_max(use.astype(jnp.int32), seg,
                                        num_segments=num_segments + 1)
        results.append((out[:num_segments],
                        group_valid & (any_valid[:num_segments] > 0)))
    return rep[:num_segments], group_valid, results


# ---------------------------------------------------------------------------
# Streaming groupby path: ONE fused sort + ONE Pallas pass
# (tpu_kernels.groupby_stream) replacing dense-ranks + XLA segment
# reductions. Exact: runs are delimited by the TRUE key bits
# (multi-operand compare) for up to MAX_GROUP_KEY_LANES lanes; wider
# keys use 2x32 hash operands with verify lanes and an exact fallback
# on any collision.
#
# MEASURED (v5e, honest device_get timing, 16M rows): the kernel runs
# 10-11M rows/s vs the XLA segment path's 13-19M across 1K-1M group
# cardinalities and 1-3 aggregates — the segmented scans (3+ log-shift
# passes per block) cost more than the scatter they remove, unlike the
# join/setops kernels where ONE pass replaced several scatter chains.
# It is therefore OFF by default (STREAM_GROUPBY=True forces it; the
# interpreter test suite exercises it for correctness) and kept as
# tuned-kernel groundwork.
# ---------------------------------------------------------------------------

# True forces the streaming path; None/False use the XLA segment path
# (measured faster — see block comment)
STREAM_GROUPBY = None

# kernel block-rows override (None = stream_block_rows policy; BR=16
# measured best of {16,32,64,128,256} on v5e)
BLOCK_ROWS_OVERRIDE = None

MAX_GROUP_KEY_LANES = 4
MAX_HASH_VERIFY_LANES = 8

_KIND = {"float32": "f", "int32": "i", "uint32": "u"}


def _key_lanes(col):
    """(lanes, nullable): u32 equality lanes for one key column. Null
    rows are normalized to shared extreme bits (their raw data is
    arbitrary filler), with the validity lane separating them from
    genuine extreme values — the sort_keys null discipline."""
    import jax.numpy as jnp

    from .order import sort_keys

    if col.is_varbytes:
        from ..data.strings import EXACT_KEY_WORDS

        vb = col.varbytes
        if vb.max_words <= EXACT_KEY_WORDS:
            # byte-exact group identity: raw word lanes + length
            return (vb.word_lanes() + [vb.lengths.astype(jnp.uint32)],
                    col.validity is not None)
        # hash of the "" filler is shared by all nulls; the validity
        # lane (added by the caller) splits them from genuine ""
        return list(vb.hash_keys()), col.validity is not None
    bits = sort_keys([col])[0]
    w = bits.dtype.itemsize
    if w == 8:
        return [(bits >> 32).astype(jnp.uint32), bits.astype(jnp.uint32)], \
            col.validity is not None
    return [bits.astype(jnp.uint32) if w < 4 else
            (bits if bits.dtype == jnp.uint32 else bits.view(jnp.uint32))], \
        col.validity is not None


def stream_groupby_table(table, idx_cols, val_cols, ops):
    """Try the streaming groupby; returns the result Table or None
    (inapplicable / hash collision — caller uses the XLA path)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .. import dtypes as _dtypes
    from ..data.column import Column
    from ..data.table import Table, _agg_dtype
    from ..util import capacity as _capacity
    from . import hash as _hash_mod
    from . import tpu_kernels as tk

    n = table.capacity
    if STREAM_GROUPBY is not True or n == 0 or n >= (1 << 29):
        return None
    interpret = jax.default_backend() != "tpu"

    # value lanes: 4-byte numerics only (others fall back)
    val_cols_u = sorted(set(val_cols))
    kinds = {}
    for vi in val_cols_u:
        c = table._columns[vi]
        if c.is_string or c.data.ndim != 1:
            return None
        kind = _KIND.get(str(c.data.dtype))
        if kind is None:
            return None
        kinds[vi] = kind
    for vi, op in zip(val_cols, ops):
        if op == AggregationOp.MEAN and kinds[vi] != "f":
            # MEAN sums in the source lane dtype here; an int32 sum wraps
            # before the division (the XLA path accumulates in f64) —
            # integer MEAN falls back
            return None

    # key lanes (exact multi-operand mode, or hash mode when wide)
    klanes = []
    for i in idx_cols:
        lanes, nullable = _key_lanes(table._columns[i])
        klanes.extend(lanes)
        if nullable:
            klanes.append(
                table._columns[i].valid_mask().astype(jnp.uint32))
    hash_mode = len(klanes) > MAX_GROUP_KEY_LANES
    if hash_mode and len(klanes) > MAX_HASH_VERIFY_LANES:
        return None

    emit = table.emit_mask()
    iota = jnp.arange(n, dtype=jnp.uint32)
    tag = (emit.astype(jnp.uint32) << 29) | iota

    # specs: one scan per distinct (col, op) pair; COUNT/MEAN ride vcnt
    lane_of = {vi: k for k, vi in enumerate(val_cols_u)}
    spec_ix = {}
    specs = []
    for vi, op in zip(val_cols, ops):
        o = AggregationOp.SUM if op == AggregationOp.MEAN else op
        if o == AggregationOp.COUNT:
            continue
        key = (lane_of[vi], int(o))
        if key not in spec_ix:
            spec_ix[key] = len(specs)
            specs.append((lane_of[vi], int(o), kinds[vi]))

    val_lanes = []
    valid_lanes = []
    vvalid_idx = []
    for vi in val_cols_u:
        c = table._columns[vi]
        d = c.data
        val_lanes.append(d if d.dtype == jnp.uint32 else d.view(jnp.uint32))
        if c.validity is not None:
            vvalid_idx.append(len(valid_lanes))
            valid_lanes.append(c.validity.astype(jnp.uint32))
        else:
            vvalid_idx.append(-1)

    from .join import stream_block_rows

    # the groupby pass is standalone (no expand-window coupling like the
    # join kernels); BLOCK_ROWS_OVERRIDE exists for tuning experiments
    br = BLOCK_ROWS_OVERRIDE or stream_block_rows(n, 0)
    allones = jnp.uint32(0xFFFFFFFF)
    if hash_mode:
        # dead rows to the tail (pad fill is allones+live=0; forcing dead
        # hashes to allones groups them with the pad run harmlessly —
        # contributions are identity for dead rows)
        h1, h2 = _hash_mod.hash2_streams(klanes, emit)
        ops_in = (h1, h2, tag) + tuple(klanes) + tuple(val_lanes) \
            + tuple(valid_lanes)
        res = jax.lax.sort(ops_in, num_keys=2)
        keys_s = list(res[:2])
        tag_s = res[2]
        nv = len(klanes)
        verify_s = list(res[3:3 + nv])
        vals_s = list(res[3 + nv:3 + nv + len(val_lanes)])
        valids_s = list(res[3 + nv + len(val_lanes):])
    else:
        klanes_d = [jnp.where(emit, kl, allones) for kl in klanes]
        ops_in = tuple(klanes_d) + (tag,) + tuple(val_lanes) \
            + tuple(valid_lanes)
        res = jax.lax.sort(ops_in, num_keys=len(klanes))
        keys_s = list(res[:len(klanes)])
        tag_s = res[len(klanes)]
        verify_s = []
        vals_s = list(res[len(klanes) + 1:len(klanes) + 1 + len(val_lanes)])
        valids_s = list(res[len(klanes) + 1 + len(val_lanes):])

    counts, outs = tk.groupby_stream(
        keys_s, tag_s, verify_s, vals_s, valids_s, tuple(specs),
        tuple(vvalid_idx), block_rows=br, interpret=interpret)
    host = jax.device_get(counts)
    ng, ncoll = int(host[0]), int(host[1])
    if ncoll > 0:
        return None
    ncols_u = len(val_cols_u)
    cap = min(_capacity(max(ng, 1)), outs[0].size)
    emit_out = jnp.arange(cap, dtype=jnp.int32) < ng
    rep = jnp.where(emit_out,
                    outs[0].reshape(-1)[:cap].view(jnp.int32), 0)
    vcnts = {vi: outs[1 + k].reshape(-1)[:cap].view(jnp.int32)
             for k, vi in enumerate(val_cols_u)}
    aggs = {k: outs[1 + ncols_u + six].reshape(-1)[:cap]
            for k, six in spec_ix.items()}

    out_cols = []
    for i in idx_cols:
        g = table._columns[i].take(rep)
        validity = None if g.validity is None else g.validity & emit_out
        out_cols.append(Column(g.data, g.dtype, validity, g.dictionary,
                               g.name, varbytes=g.varbytes))
    for vi, op in zip(val_cols, ops):
        src = table._columns[vi]
        vcnt = vcnts[vi]
        if op == AggregationOp.COUNT:
            out_cols.append(Column(vcnt.astype(jnp.int64),
                                   _agg_dtype(src, op), emit_out, None,
                                   src.name))
            continue
        o = AggregationOp.SUM if op == AggregationOp.MEAN else op
        raw = aggs[(lane_of[vi], int(o))]
        data = raw if src.data.dtype == jnp.uint32 \
            else raw.view(src.data.dtype)
        validity = (vcnt > 0) & emit_out
        if op == AggregationOp.MEAN:
            data = data.astype(jnp.float64) / jnp.maximum(vcnt, 1)
            out_cols.append(Column(data, _agg_dtype(src, op), validity,
                                   None, src.name))
        else:
            out_cols.append(Column(data, _agg_dtype(src, op), validity,
                                   None, src.name))
    return Table(out_cols, table._ctx, emit_out)
