"""Group-by aggregation kernels — sort-based segmented reduction.

Replaces the reference's hash-map group-by (reference:
cpp/src/cylon/groupby/groupby_hash.hpp:28-359 — `unordered_map` with
compile-time `AggregateKernel<T,Op>{Init,Update,Finalize}`, and the
sorted-run pipeline variant groupby_pipeline.hpp:28-257) with the TPU
formulation: dense-rank the key column(s) (one device sort), then every
aggregation is a `jax.ops.segment_*` reduction — contiguous, vectorized,
fusible.

Distributed semantics (fixing the reference's re-aggregation subtlety noted
in SURVEY §3.2): partial aggregates are combined with the correct SECOND-
PHASE op — COUNT partials are SUMmed, MEAN carries (sum, count) pairs and
divides at the end. The reference re-applies the same op twice, which makes
distributed COUNT wrong when a key spans ranks (groupby/groupby.cpp:96-139).
"""
from __future__ import annotations

import enum
from functools import partial
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class AggregationOp(enum.IntEnum):
    """Reference: groupby/groupby_aggregate_ops.hpp `GroupByAggregationOp`
    (SUM/COUNT/MIN/MAX); MEAN added (the reference left it commented out,
    groupby_hash.hpp:118-138)."""

    SUM = 0
    COUNT = 1
    MIN = 2
    MAX = 3
    MEAN = 4


def second_phase_op(op: AggregationOp) -> AggregationOp:
    """The op used to merge per-shard partials (COUNT partials are summed)."""
    if op in (AggregationOp.COUNT,):
        return AggregationOp.SUM
    return op


def _identity_for(op: AggregationOp, dtype):
    if op in (AggregationOp.SUM, AggregationOp.COUNT, AggregationOp.MEAN):
        return jnp.zeros((), dtype)
    if op == AggregationOp.MIN:
        return jnp.asarray(_max_of(dtype), dtype)
    return jnp.asarray(_min_of(dtype), dtype)


def _max_of(dtype):
    d = np.dtype(dtype)
    if d.kind == "f":
        return np.inf
    if d.kind == "b":
        return True
    return np.iinfo(d).max


def _min_of(dtype):
    d = np.dtype(dtype)
    if d.kind == "f":
        return -np.inf
    if d.kind == "b":
        return False
    return np.iinfo(d).min


@partial(jax.jit, static_argnames=("num_segments", "ops"))
def segment_aggregate(gid, values: Tuple[jnp.ndarray, ...],
                      valids: Tuple[jnp.ndarray, ...],
                      emit: jnp.ndarray,
                      num_segments: int,
                      ops: Tuple[AggregationOp, ...]):
    """Aggregate each value column into per-group slots.

    gid: int32 group id per row (any id for non-emitted rows — masked).
    Returns (rep_idx, group_valid, list_of_(agg_array, agg_valid)):
      rep_idx[g] = first row index holding group g (for key materialization),
      agg arrays have shape [num_segments].

    MEAN returns a float64 array; COUNT returns int64 of non-null values
    (Arrow count semantics).
    """
    n = gid.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    seg = jnp.where(emit, gid, num_segments)  # masked rows -> overflow slot
    rep = jnp.full(num_segments + 1, n, jnp.int32).at[seg].min(iota)
    group_valid = rep[:num_segments] < n

    results = []
    for arr, vmask, op in zip(values, valids, ops):
        use = emit & vmask
        if op == AggregationOp.COUNT:
            out = jax.ops.segment_sum(use.astype(jnp.int64), seg,
                                      num_segments=num_segments + 1)
            results.append((out[:num_segments], group_valid))
            continue
        if op == AggregationOp.MEAN:
            x = jnp.where(use, arr, 0).astype(jnp.float64)
            s = jax.ops.segment_sum(x, seg, num_segments=num_segments + 1)
            c = jax.ops.segment_sum(use.astype(jnp.float64), seg,
                                    num_segments=num_segments + 1)
            out = s[:num_segments] / jnp.maximum(c[:num_segments], 1)
            results.append((out, group_valid & (c[:num_segments] > 0)))
            continue
        ident = _identity_for(op, arr.dtype)
        x = jnp.where(use, arr, ident)
        if op == AggregationOp.SUM:
            out = jax.ops.segment_sum(x, seg, num_segments=num_segments + 1)
        elif op == AggregationOp.MIN:
            out = jax.ops.segment_min(x, seg, num_segments=num_segments + 1)
        else:
            out = jax.ops.segment_max(x, seg, num_segments=num_segments + 1)
        any_valid = jax.ops.segment_max(use.astype(jnp.int32), seg,
                                        num_segments=num_segments + 1)
        results.append((out[:num_segments],
                        group_valid & (any_valid[:num_segments] > 0)))
    return rep[:num_segments], group_valid, results


# ---------------------------------------------------------------------------
# A Pallas streaming groupby (ONE fused sort + ONE segmented-scan pass)
# was built and benchmarked in rounds 2-3: 10-11M rows/s vs the XLA
# segment path's 13-19M across 1K-1M group cardinalities on v5e — the
# segmented scans (3+ log-shift passes per block) cost more than the
# scatter they remove, unlike the join/setops kernels where one pass
# replaced several scatter chains. Per the round-3 review it was
# REMOVED rather than shipped as a slower parallel implementation
# (git history: rounds 2-3 carry the kernel and its tests).
# ---------------------------------------------------------------------------

