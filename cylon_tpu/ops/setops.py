"""Set operations (distinct union / subtract / intersect) on full-row keys.

Replaces the reference's hash-set implementation (reference:
cpp/src/cylon/table.cpp:39-942 — `RowComparator` over an
`unordered_set<pair<tableIdx,rowIdx>>`, arrow_comparator.cpp) with sorted
dense ranks: both tables' rows map to shared integer ids (one fused device
sort), then membership is a segment-count gather and dedup is a
first-occurrence mask — no pointer-chasing hash sets, all vectorized.

Set semantics match the reference: results are DISTINCT rows; within-table
duplicates collapse. Null row-components compare equal to each other (ids
are built with validity as part of the key).

All kernels take "emit" masks so padded/invalid rows are ignored.
"""
from __future__ import annotations

import enum
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


class SetOp(enum.IntEnum):
    UNION = 0
    SUBTRACT = 1
    INTERSECT = 2


@jax.jit
def setop_counts(gl, gr, lemit, remit):
    """Counts for all three ops in one pass.

    gl/gr: int32 dense row ids on a shared space (full-row keys).
    Returns dict: n_union, n_subtract, n_intersect.
    """
    nl = gl.shape[0]
    gl_eff = jnp.where(lemit, gl, -1)
    gr_eff = jnp.where(remit, gr, -2)
    first_l = _first_occurrence(gl_eff) & lemit
    in_r = _isin(gl_eff, gr_eff, remit)
    n_subtract = (first_l & ~in_r).sum()
    n_intersect = (first_l & in_r).sum()
    # union: distinct over concat = distinct(left) + rows of right unseen in left
    first_r = _first_occurrence(gr_eff) & remit
    in_l = _isin(gr_eff, gl_eff, lemit)
    n_union = first_l.sum() + (first_r & ~in_l).sum()
    return {"n_union": n_union, "n_subtract": n_subtract,
            "n_intersect": n_intersect}


def _first_occurrence(g) -> jnp.ndarray:
    """True at the first row (in table order) holding each distinct id."""
    n = g.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    gs, idxs = jax.lax.sort((g, iota), num_keys=1)
    neq = jnp.zeros(n, dtype=bool).at[0].set(True)
    neq = neq.at[1:].set(gs[1:] != gs[:-1])
    # scatter-min: first index per run
    return jnp.zeros(n, dtype=bool).at[idxs].set(neq)


def _isin(g, other, other_emit) -> jnp.ndarray:
    """Membership of each id of ``g`` in ``other`` (emitted rows only).
    ``other`` must already carry a sentinel on non-emitted rows that can
    never appear in ``g``. Sort+scan match counting — no searchsorted, no
    duplicate-index scatters (both pathologically slow on TPU)."""
    del other_emit  # sentinel handling is done by the caller
    from .join import _match_lo_m

    _, m = _match_lo_m(g, other)
    return m > 0


@partial(jax.jit, static_argnames=("op", "out_size"))
def setop_indices(gl, gr, lemit, remit, op: SetOp, out_size: int
                  ) -> jnp.ndarray:
    """Row indices of the result, padded with -1 to ``out_size``.

    Indices address the CONCATENATED [left; right] table: i < nl selects a
    left row, i >= nl selects right row i-nl (only UNION emits those).
    """
    nl = gl.shape[0]
    if nl + gr.shape[0] == 0:
        return jnp.full(out_size, -1, jnp.int32)
    from .join import _masked_indices
    gl_eff = jnp.where(lemit, gl, -1)
    gr_eff = jnp.where(remit, gr, -2)
    first_l = _first_occurrence(gl_eff) & lemit
    if op == SetOp.UNION:
        first_r = _first_occurrence(gr_eff) & remit
        in_l = _isin(gr_eff, gl_eff, lemit)
        mask = jnp.concatenate([first_l, first_r & ~in_l])
    elif op == SetOp.SUBTRACT:
        in_r = _isin(gl_eff, gr_eff, remit)
        mask = jnp.concatenate([first_l & ~in_r,
                                jnp.zeros_like(remit)])
    else:  # INTERSECT
        in_r = _isin(gl_eff, gr_eff, remit)
        mask = jnp.concatenate([first_l & in_r, jnp.zeros_like(remit)])
    return _masked_indices(mask, out_size)


def setop_rows(gl, gr, lemit, remit, op: SetOp) -> np.ndarray:
    """Eager driver: count, materialize at pow2 capacity, slice."""
    counts = {k: int(v) for k, v in setop_counts(gl, gr, lemit, remit).items()}
    total = counts[{SetOp.UNION: "n_union", SetOp.SUBTRACT: "n_subtract",
                    SetOp.INTERSECT: "n_intersect"}[op]]
    from ..util import pow2

    cap = pow2(total)
    idx = setop_indices(gl, gr, lemit, remit, op, cap)
    return np.asarray(idx)[:total]
