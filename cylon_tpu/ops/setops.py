"""Set operations (distinct union / subtract / intersect) on full-row keys.

Replaces the reference's hash-set implementation (reference:
cpp/src/cylon/table.cpp:39-942 — `RowComparator` over an
`unordered_set<pair<tableIdx,rowIdx>>`, arrow_comparator.cpp) with sorted
dense ranks: both tables' rows map to shared integer ids (one fused device
sort), then membership is a segment-count gather and dedup is a
first-occurrence mask — no pointer-chasing hash sets, all vectorized.

Set semantics match the reference: results are DISTINCT rows; within-table
duplicates collapse. Null row-components compare equal to each other (ids
are built with validity as part of the key).

All kernels take "emit" masks so padded/invalid rows are ignored.
"""
from __future__ import annotations

import enum
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


class SetOp(enum.IntEnum):
    UNION = 0
    SUBTRACT = 1
    INTERSECT = 2


@jax.jit
def setop_counts(gl, gr, lemit, remit):
    """Counts for all three ops in one pass.

    gl/gr: int32 dense row ids on a shared space (full-row keys).
    Returns dict: n_union, n_subtract, n_intersect.
    """
    nl = gl.shape[0]
    gl_eff = jnp.where(lemit, gl, -1)
    gr_eff = jnp.where(remit, gr, -2)
    first_l = _first_occurrence(gl_eff) & lemit
    in_r = _isin(gl_eff, gr_eff, remit)
    n_subtract = (first_l & ~in_r).sum()
    n_intersect = (first_l & in_r).sum()
    # union: distinct over concat = distinct(left) + rows of right unseen in left
    first_r = _first_occurrence(gr_eff) & remit
    in_l = _isin(gr_eff, gl_eff, lemit)
    n_union = first_l.sum() + (first_r & ~in_l).sum()
    return {"n_union": n_union, "n_subtract": n_subtract,
            "n_intersect": n_intersect}


def _first_occurrence(g) -> jnp.ndarray:
    """True at the first row (in table order) holding each distinct id."""
    n = g.shape[0]
    if n == 0:
        return jnp.zeros(0, dtype=bool)
    iota = jnp.arange(n, dtype=jnp.int32)
    gs, idxs = jax.lax.sort((g, iota), num_keys=1)
    neq = jnp.zeros(n, dtype=bool).at[0].set(True)
    neq = neq.at[1:].set(gs[1:] != gs[:-1])
    # scatter-min: first index per run
    return jnp.zeros(n, dtype=bool).at[idxs].set(neq)


def _isin(g, other, other_emit) -> jnp.ndarray:
    """Membership of each id of ``g`` in ``other`` (emitted rows only).
    ``other`` must already carry a sentinel on non-emitted rows that can
    never appear in ``g``. Sort+scan match counting — no searchsorted, no
    duplicate-index scatters (both pathologically slow on TPU)."""
    del other_emit  # sentinel handling is done by the caller
    from .join import _match_lo_m

    _, m = _match_lo_m(g, other)
    return m > 0


@partial(jax.jit, static_argnames=("op", "out_size"))
def setop_indices(gl, gr, lemit, remit, op: SetOp, out_size: int
                  ) -> jnp.ndarray:
    """Row indices of the result, padded with -1 to ``out_size``.

    Indices address the CONCATENATED [left; right] table: i < nl selects a
    left row, i >= nl selects right row i-nl (only UNION emits those).
    """
    nl = gl.shape[0]
    if nl + gr.shape[0] == 0:
        return jnp.full(out_size, -1, jnp.int32)
    from .join import _masked_indices
    gl_eff = jnp.where(lemit, gl, -1)
    gr_eff = jnp.where(remit, gr, -2)
    first_l = _first_occurrence(gl_eff) & lemit
    if op == SetOp.UNION:
        first_r = _first_occurrence(gr_eff) & remit
        in_l = _isin(gr_eff, gl_eff, lemit)
        mask = jnp.concatenate([first_l, first_r & ~in_l])
    elif op == SetOp.SUBTRACT:
        in_r = _isin(gl_eff, gr_eff, remit)
        mask = jnp.concatenate([first_l & ~in_r,
                                jnp.zeros_like(remit)])
    else:  # INTERSECT
        in_r = _isin(gl_eff, gr_eff, remit)
        mask = jnp.concatenate([first_l & in_r, jnp.zeros_like(remit)])
    return _masked_indices(mask, out_size)


def setop_rows(gl, gr, lemit, remit, op: SetOp) -> np.ndarray:
    """Eager driver: count, materialize at pow2 capacity, slice."""
    counts = {k: int(v) for k, v in setop_counts(gl, gr, lemit, remit).items()}
    total = counts[{SetOp.UNION: "n_union", SetOp.SUBTRACT: "n_subtract",
                    SetOp.INTERSECT: "n_intersect"}[op]]
    from ..util import pow2

    cap = pow2(total)
    idx = setop_indices(gl, gr, lemit, remit, op, cap)
    return np.asarray(idx)[:total]


# ---------------------------------------------------------------------------
# Streaming set-op path: ONE fused sort on a 2x32-bit full-row hash + ONE
# Pallas pass (tpu_kernels.setop_stream) replaces the ~8 sorts + scatters
# above; the row payload rides the sort as u32 lanes, doubling as the
# hash-verify lanes and as the compacted output. Exact: any within-run
# lane mismatch (64-bit hash collision) makes the caller recompute via
# the dense-ranks path.
# ---------------------------------------------------------------------------

# None = auto (TPU only); False disables; True forces (interpreter tests)
STREAM_SETOP = None

# sort operands = 3 (h1, h2, tag) + lane budget
MAX_SETOP_LANES = 12


def setop_lane_descs(lcols, rcols):
    """Static lane plan over ALIGNED column pairs, or None when any
    column can't ride u32 lanes within budget. Per column: (kind,
    has_validity) with kind "d" (4-byte bit-exact), "n" (1/2-byte
    widened), "b" (bool), "w" (8-byte split hi/lo)."""
    descs = []
    total = 0
    for a, b in zip(lcols, rcols):
        has_v = a.validity is not None or b.validity is not None
        if a.is_varbytes or b.is_varbytes:
            # varlen content can't ride (or be reconstructed from) fixed
            # u32 lanes — dense-ranks path handles varbytes
            return None
        if a.is_string:
            kind, slots = "d", 1
        elif a.data.dtype == jnp.bool_:
            kind, slots = "b", 1
        elif a.data.ndim != 1:
            return None
        else:
            w = np.dtype(a.data.dtype).itemsize
            if w == 4:
                kind, slots = "d", 1
            elif w == 8:
                kind, slots = "w", 2
            elif w in (1, 2):
                kind, slots = "n", 1
            else:
                return None
        total += slots + (1 if has_v else 0)
        if total > MAX_SETOP_LANES:
            return None
        descs.append((kind, has_v))
    return tuple(descs)


def setop_stream_applicable(n_total: int, descs) -> bool:
    if STREAM_SETOP is False or descs is None:
        return False
    if n_total == 0 or n_total >= (1 << 29):
        return False
    if STREAM_SETOP:
        return True
    return jax.default_backend() == "tpu"


def _col_lanes(col, other_has_v, kind):
    """Canonical u32 lanes for one side's column: equal VALUES produce
    equal lane bits (floats: -0.0 normalized; null cells: forced 0 with
    the validity lane carrying the distinction)."""
    x = col.data
    if kind == "b":
        bits = [x.astype(jnp.uint32)]
    elif kind == "n":
        if jnp.issubdtype(x.dtype, jnp.floating):
            # float16: bitcast, not value-cast — a value cast truncates
            # distinct halves (1.25 vs 1.5) to the same integer.
            x = jnp.where(x == 0, jnp.zeros((), x.dtype), x)
            bits = [x.view(jnp.uint16).astype(jnp.uint32)]
        else:
            bits = [x.astype(jnp.uint32)]
    elif kind == "w":
        if jnp.issubdtype(x.dtype, jnp.floating):
            x = jnp.where(x == 0, jnp.zeros((), x.dtype), x)
        u = x.view(jnp.uint64)
        bits = [(u >> 32).astype(jnp.uint32), u.astype(jnp.uint32)]
    else:
        if x.dtype != jnp.bool_ and jnp.issubdtype(x.dtype, jnp.floating):
            x = jnp.where(x == 0, jnp.zeros((), x.dtype), x)
        bits = [x if x.dtype == jnp.uint32 else x.view(jnp.uint32)]
    has_v = col.validity is not None or other_has_v
    if has_v:
        vm = col.valid_mask()
        bits = [jnp.where(vm, b, jnp.uint32(0)) for b in bits]
        bits.append(vm.astype(jnp.uint32))
    return bits


@partial(jax.jit, static_argnames=("descs", "op", "block_rows",
                                   "interpret"))
def _setop_stream_program(lane_l, lane_r, lemit, remit, descs, op: SetOp,
                          block_rows: int, interpret: bool):
    from .hash import fmix32, fmix32b
    from . import tpu_kernels as tk

    nl = lemit.shape[0]
    nr = remit.shape[0]
    n = nl + nr
    lanes = [jnp.concatenate([a, b]) for a, b in zip(lane_l, lane_r)]
    live = jnp.concatenate([lemit, remit])
    iota = jnp.arange(n, dtype=jnp.uint32)
    tag = (jnp.concatenate([jnp.full(nl, jnp.uint32(1 << 31)),
                            jnp.zeros(nr, jnp.uint32)])
           | (live.astype(jnp.uint32) << 29) | iota)
    h1 = jnp.zeros(n, jnp.uint32)
    h2 = jnp.full(n, jnp.uint32(0x9E3779B9))
    for ln in lanes:
        h1 = h1 * jnp.uint32(31) + fmix32(ln)
        h2 = h2 * jnp.uint32(33) + fmix32b(ln)
    allones = jnp.uint32(0xFFFFFFFF)
    h1 = jnp.where(live, fmix32(h1), allones)
    h2 = jnp.where(live, fmix32b(h2), allones)
    res = jax.lax.sort((h1, h2, tag) + tuple(lanes), num_keys=3)
    return tk.setop_stream(res[0], res[1], res[2], res[3:], int(op),
                           block_rows=block_rows, interpret=interpret)


def setop_stream_table(left, right, lcols, rcols, op: SetOp):
    """Try the streaming set-op. Returns the result Table or None (not
    applicable / hash collision — caller uses the dense-ranks path).
    lcols/rcols: schema-ALIGNED columns (dtypes promoted, dictionaries
    unified)."""
    from ..data.column import Column
    from ..data.table import Table
    from ..util import capacity as _capacity
    from .join import stream_block_rows

    descs = setop_lane_descs(lcols, rcols)
    nl, nr = left.capacity, right.capacity
    if not setop_stream_applicable(nl + nr, descs):
        return None
    interpret = jax.default_backend() != "tpu"
    br = stream_block_rows(nl, nr)

    lane_l, lane_r = [], []
    for (kind, _), a, b in zip(descs, lcols, rcols):
        other_v_a = b.validity is not None
        lane_l.extend(_col_lanes(a, other_v_a, kind))
        lane_r.extend(_col_lanes(b, a.validity is not None, kind))
    lemit = left.emit_mask()
    remit = right.emit_mask()

    if interpret:
        counts, streams = _setop_stream_program.__wrapped__(
            tuple(lane_l), tuple(lane_r), lemit, remit, descs, op,
            br, True)
    else:
        counts, streams = _setop_stream_program(
            tuple(lane_l), tuple(lane_r), lemit, remit, descs, op,
            br, False)
    host = jax.device_get(counts)
    n_out, n_coll = int(host[0]), int(host[1])
    if n_coll > 0:
        return None
    # cap may overshoot the padded stream length when n_out is close to
    # n (capacity() rounds up ~6%); jnp slicing clamps silently, which
    # would leave columns shorter than the emit mask. Clamp to the
    # stream element count — it is always >= n_out.
    cap = min(_capacity(n_out), streams[1].size)
    flat = [s.reshape(-1)[:cap] for s in streams[1:]]  # drop idx stream

    cols = []
    k = 0
    emit = jnp.arange(cap, dtype=jnp.int32) < n_out
    for (kind, has_v), a in zip(descs, lcols):
        if kind == "w":
            hi, lo = flat[k], flat[k + 1]
            u = (hi.astype(jnp.uint64) << 32) | lo.astype(jnp.uint64)
            data = u.view(a.data.dtype)
            k += 2
        elif kind == "b":
            data = flat[k] != 0
            k += 1
        elif kind == "n":
            if jnp.issubdtype(jnp.dtype(a.data.dtype), jnp.floating):
                data = flat[k].astype(jnp.uint16).view(a.data.dtype)
            else:
                data = flat[k].astype(a.data.dtype)
            k += 1
        else:
            data = flat[k] if a.data.dtype == jnp.uint32 \
                else flat[k].view(a.data.dtype)
            k += 1
        validity = None
        if has_v:
            validity = (flat[k] != 0) & emit
            k += 1
        cols.append(Column(data, a.dtype, validity, a.dictionary, a.name))
    return Table(cols, left._ctx, emit)
