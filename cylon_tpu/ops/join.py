"""Local join kernels — vectorized sort-merge join with static shapes.

Replaces the reference's three local join paths (reference:
cpp/src/cylon/join/join.cpp:77-540 — `do_sorted_join`,
`do_inplace_sorted_join`, `do_hash_join` with the multimap kernel in
arrow_hash_kernels.hpp:48-225) with ONE TPU-idiomatic algorithm:

1. key columns of both tables are mapped to shared dense integer ids
   (ops/order.dense_ranks_two — a single fused device sort);
2. the right ids are sorted once; per-left-row match ranges come from two
   vectorized ``searchsorted`` calls; duplicate expansion uses prefix sums
   (the reference's `advance` duplicate-run loops become gathers);
3. output size is data-dependent, so materialization is two-phase
   (count → allocate static capacity → gather), the XLA static-shape
   discipline described in SURVEY §7.

`JoinConfig.algorithm` SORT and HASH both lower to this kernel today (they
are semantically identical); a Pallas VMEM hash-probe variant can slot in
behind the HASH enum later.

All kernels accept "emit" row-validity masks so padded rows (from pow2
capacity rounding or from sharded shuffles) flow through without host
round-trips.
"""
from __future__ import annotations

import enum
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class JoinType(enum.IntEnum):
    """Reference: join/join_config.hpp:22 `JoinType`."""

    INNER = 0
    LEFT = 1
    RIGHT = 2
    FULL_OUTER = 3


class JoinAlgorithm(enum.IntEnum):
    """Reference: join/join_config.hpp:25 `JoinAlgorithm`."""

    SORT = 0
    HASH = 1


class JoinConfig:
    """Reference: join/join_config.hpp:29-89. Accepts single ints or lists
    of column indices (multi-column keys are first-class here)."""

    def __init__(self, join_type: JoinType, left_column_idx, right_column_idx,
                 algorithm: JoinAlgorithm = JoinAlgorithm.SORT):
        self.type = join_type
        self.algorithm = algorithm
        self.left_column_idx = _as_list(left_column_idx)
        self.right_column_idx = _as_list(right_column_idx)

    @staticmethod
    def InnerJoin(l, r, algorithm: JoinAlgorithm = JoinAlgorithm.SORT):
        return JoinConfig(JoinType.INNER, l, r, algorithm)

    @staticmethod
    def LeftJoin(l, r, algorithm: JoinAlgorithm = JoinAlgorithm.SORT):
        return JoinConfig(JoinType.LEFT, l, r, algorithm)

    @staticmethod
    def RightJoin(l, r, algorithm: JoinAlgorithm = JoinAlgorithm.SORT):
        return JoinConfig(JoinType.RIGHT, l, r, algorithm)

    @staticmethod
    def FullOuterJoin(l, r, algorithm: JoinAlgorithm = JoinAlgorithm.SORT):
        return JoinConfig(JoinType.FULL_OUTER, l, r, algorithm)

    def GetType(self) -> JoinType:
        return self.type

    def GetAlgorithm(self) -> JoinAlgorithm:
        return self.algorithm

    def GetLeftColumnIdx(self):
        return self.left_column_idx

    def GetRightColumnIdx(self):
        return self.right_column_idx


def _as_list(v):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [int(v)]


# ---------------------------------------------------------------------------
# Kernels. Inputs:
#   gl, gr : int32 dense key ids on a shared id space (>= 0); rows whose key
#            must never match carry a negative sentinel (-1 left, -2 right).
#   lemit, remit : bool masks — rows eligible for emission (False for padding).
# ---------------------------------------------------------------------------

LEFT_NULL_GID = np.int32(-1)
RIGHT_NULL_GID = np.int32(-2)


def _match_ranges(gl, gr_sorted):
    lo = jnp.searchsorted(gr_sorted, gl, side="left")
    hi = jnp.searchsorted(gr_sorted, gl, side="right")
    return lo, hi - lo


@jax.jit
def join_counts(gl, gr, lemit, remit):
    """One pass computing every count any join type needs.

    Returns dict of int32 scalars: n_inner, n_left, n_right, n_full.
    """
    gr_sorted = jnp.sort(gr)
    _, m = _match_ranges(gl, gr_sorted)
    m = jnp.where(lemit, m, 0)
    gl_sorted = jnp.sort(gl)
    _, mr = _match_ranges(gr, gl_sorted)
    mr = jnp.where(remit, mr, 0)
    n_inner = m.sum()
    n_left = jnp.where(lemit, jnp.maximum(m, 1), 0).sum()
    n_right = jnp.where(remit, jnp.maximum(mr, 1), 0).sum()
    r_unmatched = (remit & (mr == 0)).sum()
    return {
        "n_inner": n_inner,
        "n_left": n_left,
        "n_right": n_right,
        "n_full": n_left + r_unmatched,
    }


@partial(jax.jit, static_argnames=("out_size", "emit_unmatched_left"))
def _expand_pairs(gl, gr, lemit, remit, out_size: int,
                  emit_unmatched_left: bool) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Emit (left_idx, right_idx) pairs for INNER (emit_unmatched_left=False)
    or LEFT join (True), padded to ``out_size`` with (-1, -1)."""
    nl, nr = gl.shape[0], gr.shape[0]
    if nl == 0:
        e = jnp.full(out_size, -1, jnp.int32)
        return e, e
    riota = jnp.arange(nr, dtype=jnp.int32)
    gr_sorted, rperm = jax.lax.sort((gr, riota), num_keys=1)
    lo, m = _match_ranges(gl, gr_sorted)
    m = jnp.where(lemit, m, 0)
    mm = jnp.where(lemit & emit_unmatched_left, jnp.maximum(m, 1), m)
    off = jnp.cumsum(mm)
    total = off[-1] if nl > 0 else jnp.int32(0)
    j = jnp.arange(out_size, dtype=jnp.int32)
    i = jnp.searchsorted(off, j, side="right").astype(jnp.int32)
    i = jnp.minimum(i, max(nl - 1, 0))
    start = off[i] - mm[i]
    k = j - start
    rpos = lo[i] + k
    if nr == 0:
        ridx = jnp.full(out_size, -1, jnp.int32)
    else:
        ridx = jnp.take(rperm, rpos, mode="fill", fill_value=0)
        ridx = jnp.where(m[i] > 0, ridx, -1)
    valid = j < total
    lidx = jnp.where(valid, i, -1)
    ridx = jnp.where(valid, ridx, -1)
    return lidx, ridx


@partial(jax.jit, static_argnames=("out_size",))
def _unmatched_right(gl, gr, lemit, remit, out_size: int) -> jnp.ndarray:
    """Right rows with no left match, padded to out_size with -1."""
    gl_sorted = jnp.sort(gl)
    _, mr = _match_ranges(gr, gl_sorted)
    un = remit & (mr == 0)
    (idx,) = jnp.nonzero(un, size=out_size, fill_value=-1)
    return idx.astype(jnp.int32)


def join_indices(gl, gr, lemit=None, remit=None,
                 join_type: JoinType = JoinType.INNER,
                 counts: Optional[dict] = None
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Eager driver: count on device, sync the scalar, materialize with a
    pow2-rounded static capacity (bounds recompilation), slice to the true
    size. Returns host int32 index arrays (−1 = null row, the reference's
    convention in join_utils.cpp:131-196)."""
    nl, nr = gl.shape[0], gr.shape[0]
    if lemit is None:
        lemit = jnp.ones(nl, dtype=bool)
    if remit is None:
        remit = jnp.ones(nr, dtype=bool)
    if counts is None:
        counts = {k: int(v) for k, v in join_counts(gl, gr, lemit, remit).items()}

    if join_type == JoinType.RIGHT:
        ridx, lidx = join_indices(gr, gl, remit, lemit, JoinType.LEFT,
                                  _swap_counts(counts))
        return lidx, ridx

    if join_type == JoinType.INNER:
        total = counts["n_inner"]
        cap = _pow2(total)
        lidx, ridx = _expand_pairs(gl, gr, lemit, remit, cap, False)
        return np.asarray(lidx)[:total], np.asarray(ridx)[:total]

    if join_type == JoinType.LEFT:
        total = counts["n_left"]
        cap = _pow2(total)
        lidx, ridx = _expand_pairs(gl, gr, lemit, remit, cap, True)
        return np.asarray(lidx)[:total], np.asarray(ridx)[:total]

    # FULL_OUTER = LEFT part + unmatched right
    n_left = counts["n_left"]
    n_un = counts["n_full"] - n_left
    lidx, ridx = _expand_pairs(gl, gr, lemit, remit, _pow2(n_left), True)
    un = _unmatched_right(gl, gr, lemit, remit, _pow2(n_un))
    lidx = np.concatenate([np.asarray(lidx)[:n_left],
                           np.full(n_un, -1, np.int32)])
    ridx = np.concatenate([np.asarray(ridx)[:n_left], np.asarray(un)[:n_un]])
    return lidx, ridx


def _swap_counts(c: dict) -> dict:
    # n_full = n_inner + unmatched_left + unmatched_right is side-symmetric.
    return {"n_inner": c["n_inner"], "n_left": c["n_right"],
            "n_right": c["n_left"], "n_full": c["n_full"]}


def _pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (int(n) - 1).bit_length()
