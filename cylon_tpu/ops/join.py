"""Local join kernels — vectorized sort-merge join with static shapes.

Replaces the reference's three local join paths (reference:
cpp/src/cylon/join/join.cpp:77-540 — `do_sorted_join`,
`do_inplace_sorted_join`, `do_hash_join` with the multimap kernel in
arrow_hash_kernels.hpp:48-225) with ONE TPU-idiomatic algorithm:

1. key columns of both tables are mapped to shared dense integer ids
   (ops/order.dense_ranks_two — a single fused device sort);
2. because the ids are DENSE, per-left-row match ranges come from one
   fused sort + prefix-scans (`_match_lo_m`) and duplicate expansion from
   run-head scatters + cumsum + gathers — no binary search, no
   duplicate-index scatter, no cumulative max (all three are TPU
   pathologies; see the kernel-block comment below);
3. output size is data-dependent, so materialization is two-phase
   (count → allocate static capacity → gather), the XLA static-shape
   discipline described in SURVEY §7.

`JoinConfig.algorithm` SORT lowers to the key-sort kernels;
HASH lowers to the hash-stream path (`hash_stream_applicable` /
`plan_program_stream(hash_mode=True)`): rows sort by a 2x32-bit row hash
— two operands regardless of key arity — with true key bits as verify
lanes and an exact XLA-plan fallback on any detected collision. A scalar
VMEM build/probe table was considered and rejected: random single-
element inserts/probes are scalar-unit work (~30 cycles/row — 0.5 s for
a 16M-row probe side, worse than the ENTIRE sort path), which is why the
reference's multimap design (arrow_hash_kernels.hpp:48-225) has no
profitable literal TPU translation.

All kernels accept "emit" row-validity masks so padded rows (from pow2
capacity rounding or from sharded shuffles) flow through without host
round-trips.
"""
from __future__ import annotations

import enum
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..util import pow2 as _pow2


class JoinType(enum.IntEnum):
    """Reference: join/join_config.hpp:22 `JoinType`."""

    INNER = 0
    LEFT = 1
    RIGHT = 2
    FULL_OUTER = 3


class JoinAlgorithm(enum.IntEnum):
    """Reference: join/join_config.hpp:25 `JoinAlgorithm` (SORT/HASH).
    AUTO is an extension: pick the fastest applicable path — sort-stream
    for single 4-byte keys, hash-stream for multi-column/wide keys
    (measured 7.3x over the XLA plan at 16M x 16M two-key rows on v5e),
    XLA plan otherwise."""

    SORT = 0
    HASH = 1
    AUTO = 2


class JoinConfig:
    """Reference: join/join_config.hpp:29-89. Accepts single ints or lists
    of column indices (multi-column keys are first-class here)."""

    def __init__(self, join_type: JoinType, left_column_idx, right_column_idx,
                 algorithm: JoinAlgorithm = JoinAlgorithm.SORT,
                 exact: bool = False):
        self.type = join_type
        self.algorithm = algorithm
        self.left_column_idx = _as_list(left_column_idx)
        self.right_column_idx = _as_list(right_column_idx)
        # opt-in byte-verification of hash-identified varbytes keys
        # (keys <= EXACT_KEY_WORDS are byte-exact by construction; long
        # keys join on the 96-bit content hash unless exact=True)
        self.exact = exact

    @staticmethod
    def InnerJoin(l, r, algorithm: JoinAlgorithm = JoinAlgorithm.SORT):
        return JoinConfig(JoinType.INNER, l, r, algorithm)

    @staticmethod
    def LeftJoin(l, r, algorithm: JoinAlgorithm = JoinAlgorithm.SORT):
        return JoinConfig(JoinType.LEFT, l, r, algorithm)

    @staticmethod
    def RightJoin(l, r, algorithm: JoinAlgorithm = JoinAlgorithm.SORT):
        return JoinConfig(JoinType.RIGHT, l, r, algorithm)

    @staticmethod
    def FullOuterJoin(l, r, algorithm: JoinAlgorithm = JoinAlgorithm.SORT):
        return JoinConfig(JoinType.FULL_OUTER, l, r, algorithm)

    def GetType(self) -> JoinType:
        return self.type

    def GetAlgorithm(self) -> JoinAlgorithm:
        return self.algorithm

    def GetLeftColumnIdx(self):
        return self.left_column_idx

    def GetRightColumnIdx(self):
        return self.right_column_idx


def _as_list(v):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [int(v)]


# ---------------------------------------------------------------------------
# Kernels. Inputs:
#   gl, gr : int32 dense key ids on a shared id space (>= 0); rows whose key
#            must never match carry a negative sentinel (-1 left, -2 right).
#   lemit, remit : bool masks — rows eligible for emission (False for padding).
#
# NO jnp.searchsorted anywhere: its binary-search lowering is pathologically
# slow on TPU (measured ~4 s per 16M×16M call vs 0.14 s for a full sort).
# Equally banned: duplicate-index scatters (segment_sum over gid buckets —
# minutes at 16M) and associative_scan(maximum) (215 s COMPILE at 2M).
# Everything below is sorts, cumsums, gathers and unique-index scatters.
# ---------------------------------------------------------------------------

def _match_lo_m(ga, gb) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-a-row match info against b: lo[i] = #b-rows with gid < ga[i]
    (= start of the equal-gid run in gid-sorted b order), m[i] = #b-rows
    with gid == ga[i].

    One fused 3-operand sort with b ordered BEFORE a inside each gid run,
    so at every a position the inclusive b-prefix count minus the count at
    the run start IS the run's b total. Scatter-backs hit unique
    destinations (TPU serializes duplicate-index scatters; segment_sum over
    a gid-sized bucket array was measured minutes-slow at 16M rows —
    everything here is sort/scan/gather/unique-scatter).
    Sentinel gids (negative, side-distinct) never match across sides."""
    na, nb = ga.shape[0], gb.shape[0]
    n = na + nb
    if n == 0 or na == 0:
        return jnp.zeros(na, jnp.int32), jnp.zeros(na, jnp.int32)
    g = jnp.concatenate([ga, gb])
    side = jnp.concatenate([jnp.ones(na, jnp.int32),
                            jnp.zeros(nb, jnp.int32)])
    iota = jnp.arange(n, dtype=jnp.int32)
    g_s, side_s, idx_s = jax.lax.sort((g, side, iota), num_keys=2)
    is_b = side_s == 0
    cum_b = jnp.cumsum(is_b.astype(jnp.int32))  # inclusive prefix b-count
    neq = jnp.zeros(n, bool).at[0].set(True)
    neq = neq.at[1:].set(g_s[1:] != g_s[:-1])
    # run_start[p] = position of p's run head. NOT a cumulative max —
    # associative_scan(maximum) compiles catastrophically slowly on TPU
    # (measured 215 s compile at 2M rows); run ids are cumsum(neq), run
    # heads scatter to unique slots, and a gather broadcasts them back.
    run_id = jnp.cumsum(neq.astype(jnp.int32)) - 1
    first_pos = jnp.zeros(n, jnp.int32).at[
        jnp.where(neq, run_id, n)].set(iota, mode="drop")
    run_start = jnp.take(first_pos, run_id)
    b_before = jnp.take(cum_b, run_start) - \
        jnp.take(is_b.astype(jnp.int32), run_start)
    m_at = cum_b - b_before  # valid at a positions: run b's all precede
    dest = jnp.where(is_b, na, idx_s)
    lo = jnp.zeros(na, jnp.int32).at[dest].set(b_before, mode="drop")
    m = jnp.zeros(na, jnp.int32).at[dest].set(m_at, mode="drop")
    return lo, m


def _masked_indices(mask, out_size: int) -> jnp.ndarray:
    """Positions of True values in order, padded with −1 to out_size.
    Sort-based (stable sort by ~mask) — jnp.nonzero's lowering is scatter-
    heavy and ignores fill_value on empty operands."""
    n = mask.shape[0]
    if n == 0:
        return jnp.full(out_size, -1, jnp.int32)
    iota = jnp.arange(n, dtype=jnp.int32)
    _, srt = jax.lax.sort(((~mask).astype(jnp.int32), iota), num_keys=1)
    cnt = mask.sum()
    j = jnp.arange(out_size, dtype=jnp.int32)
    idx = jnp.take(srt, j, mode="fill", fill_value=0)
    return jnp.where(j < cnt, idx, -1).astype(jnp.int32)


# ---------------------------------------------------------------------------
# plan / materialize. A join is TWO device programs separated by one
# 2-scalar host sync (the static-shape capacity decision):
#
#   plan:        key bits → match info (lo, m), key-sorted live-b
#                permutation, unmatched-b mask, output COUNTS — all from
#                ONE fused sort of the concatenated keys (see
#                `join_plan_keys`).
#   materialize: consumes the plan's DEVICE arrays — duplicate-run
#                expansion + payload gathers. No re-sorting: the expensive
#                match sort is computed once and reused across the phases.
#
# "A/B space": A is the probe side (left, or right for RIGHT joins so the
# same expansion kernel serves all types), B the build side.
# ---------------------------------------------------------------------------


def join_plan_keys(lbits, lkv, lemit, rbits, rkv, remit,
                   join_type: JoinType):
    """Traceable single-sort join plan.

    Replaces a dense-rank sort + match sort + b-permutation sort (three
    33M-element device sorts at bench scale) with ONE fused sort over the
    concatenated keys. Dead rows (not emitted, or null key) get their key
    bits FORCED to the all-ones maximum so they sink to the tail runs, and
    one packed u32 tag operand `side<<31 | live<<29 | iota` replaces the
    old (class, side, iota) triple — the sort moves 2 operands instead of
    4, and within a key run build (b) rows (tag bit31=0) sort before probe
    (a) rows, so at any a position the inclusive live-b prefix count minus
    the count at the run head IS the run's match count. Live rows whose
    keys are genuinely all-ones share the dead run harmlessly: match
    counts only ever count LIVE opposite-side rows, and dead rows' m is
    zeroed in a-space after the scatter.

    Profiling note (TPU v5e): XLA gathers/scatters cost ~10-15 ns/element
    regardless of locality, so this plan's cost model counts them — it
    spends 1 sort + 2 cumsums + 1 gather + 4 scatters (FULL_OUTER adds 2
    gathers + 1 scatter).

    Returns (counts2, lo, m, bperm, un_mask): counts2 = [n_primary,
    n_unmatched_b] (int64 under x64, else int32); lo[i]/m[i] = start and
    length of probe row i's match run inside `bperm` (the key-ordered
    compaction of live build rows, original indices); un_mask marks
    emitted build rows with no match (FULL_OUTER only).
    """
    if join_type == JoinType.RIGHT:
        abits, akv, aemit = rbits, rkv, remit
        bbits, bkv, bemit = lbits, lkv, lemit
    else:
        abits, akv, aemit = lbits, lkv, lemit
        bbits, bkv, bemit = rbits, rkv, remit
    na, nb = aemit.shape[0], bemit.shape[0]
    n = na + nb
    cdt = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32

    if na == 0 or n == 0:
        if join_type == JoinType.FULL_OUTER:
            un_mask = bemit
            n_un = un_mask.sum(dtype=cdt)
        else:
            un_mask = jnp.zeros(nb, bool)
            n_un = jnp.zeros((), cdt)
        counts2 = jnp.stack([jnp.zeros((), cdt), n_un])
        z = jnp.zeros(na, jnp.int32)
        return counts2, z, z, jnp.zeros(nb, jnp.int32), un_mask

    assert n < (1 << 29), "per-shard row count must fit the 29-bit tag"
    live_a = aemit & akv
    live_b = bemit & bkv
    live = jnp.concatenate([live_a, live_b])
    iota = jnp.arange(n, dtype=jnp.uint32)
    tag = (jnp.concatenate([jnp.full(na, jnp.uint32(1 << 31)),
                            jnp.zeros(nb, jnp.uint32)])
           | (live.astype(jnp.uint32) << 29) | iota)
    bits = []
    for x, y in zip(abits, bbits):
        b = jnp.concatenate([x, y])
        allones = jnp.asarray(~np.uint64(0) >> (64 - 8 * b.dtype.itemsize),
                              b.dtype)
        bits.append(jnp.where(live, b, allones))
    res = jax.lax.sort(tuple(bits) + (tag,), num_keys=1 + len(bits))
    bits_s, tag_s = res[:-1], res[-1]

    is_a = (tag_s >> 31) == 1
    live_s = (tag_s >> 29) & 1
    idx_s = (tag_s & jnp.uint32((1 << 29) - 1)).astype(jnp.int32)
    ib = jnp.where(~is_a, live_s, 0).astype(jnp.int32)
    cum_b = jnp.cumsum(ib)
    neq_tail = jnp.zeros(n - 1, bool)
    for k in bits_s:
        neq_tail = neq_tail | (k[1:] != k[:-1])
    neq = jnp.concatenate([jnp.ones(1, bool), neq_tail])
    run_id = jnp.cumsum(neq.astype(jnp.int32)) - 1
    # live-b count before each run, broadcast via run heads (scatter to
    # unique head slots + gather by run id — never a cumulative max)
    head_b = jnp.zeros(n, jnp.int32).at[
        jnp.where(neq, run_id, n)].set(cum_b - ib, mode="drop")
    b_before = jnp.take(head_b, run_id)
    m_at = cum_b - b_before  # valid at a positions: run b's all precede

    dest_a = jnp.where(is_a, idx_s, na)
    lo = jnp.zeros(na, jnp.int32).at[dest_a].set(b_before, mode="drop")
    m = jnp.zeros(na, jnp.int32).at[dest_a].set(m_at, mode="drop")
    # dead a rows sharing the all-ones run with live max-key b rows must
    # not match them
    m = jnp.where(live_a, m, 0)
    bperm = jnp.zeros(nb, jnp.int32).at[
        jnp.where(ib == 1, cum_b - 1, nb)].set(idx_s - na, mode="drop")

    # accumulate counts in int64 (where x64 is enabled) so >2^31-pair
    # outputs don't silently wrap before the host capacity decision
    if join_type == JoinType.INNER:
        n_primary = m.sum(dtype=cdt)
    else:
        n_primary = jnp.where(aemit, jnp.maximum(m, 1), 0).sum(dtype=cdt)
    if join_type == JoinType.FULL_OUTER:
        ia = jnp.where(is_a, live_s, 0).astype(jnp.int32)
        cum_a = jnp.cumsum(ia)
        head_a = jnp.zeros(n + 1, jnp.int32).at[
            jnp.where(neq, run_id, n + 1)].set(cum_a - ia, mode="drop")
        nruns = run_id[-1] + 1
        head_a = head_a.at[nruns].set(cum_a[-1], mode="drop")
        # live-a total of each run = next run's prefix minus this run's
        m_b_at = jnp.take(head_a, run_id + 1) - jnp.take(head_a, run_id)
        dest_b = jnp.where(is_a, nb, idx_s - na)
        mb = jnp.zeros(nb, jnp.int32).at[dest_b].set(m_b_at, mode="drop")
        # dead b rows in the shared all-ones run are unmatched by fiat
        un_mask = bemit & (jnp.where(live_b, mb, 0) == 0)
        n_un = un_mask.sum(dtype=cdt)
    else:
        un_mask = jnp.zeros(nb, bool)
        n_un = jnp.zeros((), cdt)
    counts2 = jnp.stack([n_primary, n_un])
    return counts2, lo, m, bperm, un_mask


def join_plan_gids(gl, gr, lemit, remit, join_type: JoinType):
    """Plan from precomputed shared dense key ids (compat wrapper over
    `join_plan_keys`): negative gids are null sentinels that never match."""
    sb = jnp.uint32(1 << 31)
    return join_plan_keys(
        (gl.astype(jnp.uint32) ^ sb,), gl >= 0, lemit,
        (gr.astype(jnp.uint32) ^ sb,), gr >= 0, remit, join_type)


def _expand_from_match(lo, m, aemit, bperm, out_size: int,
                       emit_unmatched_a: bool
                       ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Emit (a_idx, b_idx) pairs from precomputed match info, padded to
    ``out_size`` with (-1, -1).

    B rows of a key occupy a contiguous run of the key-sorted b permutation
    starting at lo; a row i's j-th output picks run slot j − first_output_i.
    The j→i map: scatter a 1 at each emitting run's start (unique slots),
    cumsum ranks positions into ordinal runs, and a gather through the
    compacted emitting-row list recovers i — no cumulative max (215 s
    COMPILE at 2M) and no binary search.

    Per-row plan values (a-row index, packed lo − starts & has-match) are
    compacted into one (na, 2) matrix so the output-sized re-gather is ONE
    packed row gather, not three scalar gathers — gathers cost ~10-15
    ns/element on TPU regardless of width and dominate this kernel."""
    na, nb = lo.shape[0], bperm.shape[0]
    if na == 0:
        e = jnp.full(out_size, -1, jnp.int32)
        return e, e
    mm = jnp.where(aemit & emit_unmatched_a, jnp.maximum(m, 1), m)
    off = jnp.cumsum(mm)
    total = off[-1]
    starts = off - mm
    # bpos = lo[i] + (j - starts[i]) = j + delta[i]; two's-complement
    # arithmetic keeps (x*2+bit)>>1 == x for negative deltas. The *2
    # packing halves the int32 range, so past 2^30 output rows fall back
    # to separate (delta, has) gathers instead of silently wrapping.
    pack_ok = out_size < (1 << 30) and nb < (1 << 30)

    aiota = jnp.arange(na, dtype=jnp.int32)
    erank = jnp.cumsum((mm > 0).astype(jnp.int32))  # inclusive
    slot = jnp.where(mm > 0, erank - 1, na)
    emit_list = jnp.zeros(na, jnp.int32).at[slot].set(aiota, mode="drop")
    z = jnp.zeros(out_size, jnp.int32)
    z = z.at[jnp.where(mm > 0, starts, out_size)].set(1, mode="drop")
    c = jnp.cumsum(z)  # 1-based ordinal of the run covering position j
    ord_safe = jnp.maximum(c - 1, 0)

    j = jnp.arange(out_size, dtype=jnp.int32)
    if pack_ok:
        delta2 = (lo - starts) * 2 + (m > 0)
        # compact delta2 alongside emit_list (two unique-slot scatters —
        # a packed 2-column scatter is slow on TPU, packed GATHER is fast)
        delc = jnp.zeros(na, jnp.int32).at[slot].set(delta2, mode="drop")
        pair = jnp.stack([emit_list, delc], axis=1)  # (na, 2)
        g = jnp.take(pair, ord_safe, axis=0, mode="clip")
        i, d2 = g[:, 0], g[:, 1]
        has = (d2 & 1) == 1
        d = d2 >> 1
    else:
        delta = lo - starts
        has_m = m > 0
        i = jnp.take(emit_list, ord_safe, mode="clip")
        d = jnp.take(delta, i)
        has = jnp.take(has_m, i)
    if nb == 0:
        bidx = jnp.full(out_size, -1, jnp.int32)
    else:
        bpos = j + d
        bidx = jnp.take(bperm, bpos, mode="fill", fill_value=0)
        bidx = jnp.where(has, bidx, -1)
    valid = j < total
    aidx = jnp.where(valid, i, -1)
    bidx = jnp.where(valid, bidx, -1)
    return aidx, bidx


def join_materialize_gids(lo, m, bperm, un_mask, aemit,
                          join_type: JoinType, cap_p: int, cap_u: int
                          ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Traceable (lidx, ridx, emit) at static capacity from a plan's
    arrays. emit marks live output rows; padding carries (-1, -1, False)."""
    aidx, bidx = _expand_from_match(lo, m, aemit, bperm, cap_p,
                                    join_type != JoinType.INNER)
    if join_type == JoinType.FULL_OUTER:
        un = _masked_indices(un_mask, cap_u)
        aidx = jnp.concatenate([aidx, jnp.full(cap_u, -1, jnp.int32)])
        bidx = jnp.concatenate([bidx, un])
    if join_type == JoinType.RIGHT:
        lidx, ridx = bidx, aidx
    else:
        lidx, ridx = aidx, bidx
    return lidx, ridx, (lidx >= 0) | (ridx >= 0)


# ---------------------------------------------------------------------------
# Pallas streaming plan path. The XLA plan above spends ~2 s at 33M rows in
# latency-bound scatter/gather passes (head broadcast + a/b-space
# scatter-backs); the streaming kernel (ops/tpu_kernels.join_plan_stream)
# fuses everything after the key sort into ONE sequential HBM pass and
# emits the expansion plan directly in compacted form. Applicability:
# single u32 key, INNER/LEFT/RIGHT (FULL_OUTER needs a backward pass —
# falls back to the XLA plan), per-shard rows < 2^29.
# ---------------------------------------------------------------------------

# None = auto (TPU backend, or interpreter off-TPU when forced True);
# False disables; True forces (tests force it with the interpreter).
STREAM_PLAN: Optional[bool] = None


def stream_plan_applicable(lkeys, rkeys, str_flags,
                           join_type: JoinType) -> bool:
    """Host-side check over key arrays (pre-ordered-bits): single 4-byte
    (or dictionary-string) key, INNER/LEFT/RIGHT, both sides non-empty."""
    if STREAM_PLAN is False or join_type == JoinType.FULL_OUTER:
        return False
    if len(lkeys) != 1:
        return False

    def width(x, is_str):
        return 4 if is_str else np.dtype(x.dtype).itemsize

    if width(lkeys[0], str_flags[0]) != 4 \
            or width(rkeys[0], str_flags[0]) != 4 \
            or (not str_flags[0] and lkeys[0].dtype == jnp.bool_):
        return False
    na, nb = lkeys[0].shape[0], rkeys[0].shape[0]
    if na == 0 or nb == 0 or na + nb >= (1 << 29):
        return False
    if STREAM_PLAN:
        return True
    return jax.default_backend() == "tpu"


# sort-operand budget for the hash path: 2 hash keys + tag + key-verify
# lanes + shared payload lanes
MAX_HASH_KEY_LANES = 6


def _key_lane_count(x, is_str) -> int:
    if is_str:
        return 1
    if x.dtype == jnp.bool_:
        return 1
    return 2 if np.dtype(x.dtype).itemsize == 8 else 1


def hash_stream_applicable(lkeys, rkeys, str_flags,
                           join_type: JoinType) -> bool:
    """The hash-join stream path covers what the single-key path can't:
    multi-column and wide keys. Rows sort by a 2x32-bit row hash (2
    operands however many key columns), true key bits ride as verify
    lanes, and the plan kernel counts within-run key mismatches — a
    nonzero count means a 64-bit hash collision and the caller recomputes
    via the exact XLA plan (reference hash join: arrow_hash_kernels.hpp
    :48-225, where the multimap probe re-checks true keys the same way).
    """
    if STREAM_PLAN is False or join_type == JoinType.FULL_OUTER:
        return False
    na, nb = lkeys[0].shape[0], rkeys[0].shape[0]
    if na == 0 or nb == 0 or na + nb >= (1 << 29):
        return False
    kl = sum(_key_lane_count(x, s) for x, s in zip(lkeys, str_flags))
    if kl > MAX_HASH_KEY_LANES:
        return False
    if STREAM_PLAN:
        return True
    return jax.default_backend() == "tpu"


# Shared sort-payload slot budget: each slot adds one u32 operand to the
# fused plan sort (measured on v5e at 33M rows: +2 operands free, +5 ≈
# +100 ms). Columns beyond the budget fall back to aidx/bidx gathers.
MAX_SHARED_LANES = 8


def plan_lane_descs(ldat, lval, rdat, rval, join_type: JoinType):
    """Static lane packing for the stream path: which columns ride the
    plan sort as u32 payload lanes. Slot s carries the probe side's lane
    s at probe rows and the build side's lane s at build rows, so the
    operand count is max(a, b) lanes, not the sum.

    Returns hashable (a_desc, b_desc): tuples of (col_idx, kind) with
    kind "d" (data, bit-exact u32 reinterpret) or "v" (validity widened
    to u32). 4-byte 1-D non-bool columns qualify; the rest (8-byte,
    bool) use the index-gather fallback in materialize."""
    if join_type == JoinType.RIGHT:
        adat, aval, bdat, bval = rdat, rval, ldat, lval
    else:
        adat, aval, bdat, bval = ldat, lval, rdat, rval

    def side(dat, val):
        desc = []
        for ci, (d, v) in enumerate(zip(dat, val)):
            need = 1 + (1 if v is not None else 0)
            if (d.ndim == 1 and d.dtype.itemsize == 4
                    and d.dtype != jnp.bool_
                    and len(desc) + need <= MAX_SHARED_LANES):
                desc.append((ci, "d"))
                if v is not None:
                    desc.append((ci, "v"))
        return tuple(desc)

    return side(adat, aval), side(bdat, bval)


def stream_block_rows(na: int, nb: int) -> int:
    """ONE Pallas block-rows choice for plan AND expand (the expansion
    window slack requires expand block_rows <= plan block_rows): small
    inputs use small blocks — the kernel graphs (log-shift compaction,
    window sweeps) scale with the block span, and small-block variants
    trace/compile ~3x faster, which dominates interpreter-mode tests."""
    return 8 if (na + nb) < (1 << 20) else 64


def stream_expand_capacity(n: int, block_rows: int):
    """cap_e for join_expand_stream: the pow2-bucketed capacity lifted
    to a whole number of expansion blocks. cap_e is a jit cache-key
    parameter on both the local and the distributed stream path, so it
    routes through benchutils.bucket_cap (1 bucket per octave) rather
    than the 16-per-octave mantissa rounding — the specialization
    analysis recognizes this helper as bucketing."""
    blk = block_rows * 128
    from ..benchutils import bucket_cap as _bucket_cap

    return -(-_bucket_cap(n) // blk) * blk


def _side_lanes(dat, val, desc):
    lanes = []
    for ci, kind in desc:
        if kind == "d":
            d = dat[ci]
            lanes.append(d if d.dtype == jnp.uint32 else d.view(jnp.uint32))
        else:
            lanes.append(val[ci].astype(jnp.uint32))
    return lanes


def _plan_program_stream_impl(lkeys, lkvalid, lemit, rkeys, rkvalid, remit,
                              ldat, lval, rdat, rval,
                              str_flags, join_type: JoinType,
                              a_desc=(), b_desc=(), block_rows: int = 64,
                              hash_mode: bool = False,
                              interpret: bool = False):
    """Phase 1 (stream path): raw key columns → sorted stream (payload
    lanes riding along) → Pallas plan pass that compacts the plan AND the
    payload into groups A/B. Only counts[4] crosses to the host.

    hash_mode (the honest JoinAlgorithm.HASH): rows sort by a 2x32-bit
    row hash instead of raw key bits, so ANY key shape costs two sort
    operands; the true key bits ride as verify lanes and counts[3]
    reports within-run mismatches (64-bit hash collisions) for the
    caller's exact fallback."""
    from . import tpu_kernels as tk
    from .hash import hash2_streams

    lbits, lkv, rbits, rkv = _keys_to_bits(lkeys, lkvalid, rkeys, rkvalid,
                                           str_flags)
    lemit = _vm(lemit, lkv.shape[0])
    remit = _vm(remit, rkv.shape[0])
    if join_type == JoinType.RIGHT:
        abits, akv, aemit = rbits, rkv, remit
        bbits, bkv, bemit = lbits, lkv, lemit
        adat, aval, bdat, bval = rdat, rval, ldat, lval
    else:
        abits, akv, aemit = lbits, lkv, lemit
        bbits, bkv, bemit = rbits, rkv, remit
        adat, aval, bdat, bval = ldat, lval, rdat, rval
    na, nb = aemit.shape[0], bemit.shape[0]
    n = na + nb

    live = jnp.concatenate([aemit & akv, bemit & bkv])
    emit = jnp.concatenate([aemit, bemit])
    iota = jnp.arange(n, dtype=jnp.uint32)
    tag = (jnp.concatenate([jnp.full(na, jnp.uint32(1 << 31)),
                            jnp.zeros(nb, jnp.uint32)])
           | (emit.astype(jnp.uint32) << 30)
           | (live.astype(jnp.uint32) << 29) | iota)

    a_lanes = _side_lanes(adat, aval, a_desc)
    b_lanes = _side_lanes(bdat, bval, b_desc)
    lanes = []
    for s in range(max(len(a_lanes), len(b_lanes))):
        al = a_lanes[s] if s < len(a_lanes) else jnp.zeros(na, jnp.uint32)
        bl = b_lanes[s] if s < len(b_lanes) else jnp.zeros(nb, jnp.uint32)
        lanes.append(jnp.concatenate([al, bl]))

    allones = jnp.uint32(0xFFFFFFFF)
    if hash_mode:
        # flatten every key column into u32 lanes (8-byte bits split
        # hi/lo) and hash them into two independent 32-bit streams.
        # KNOWN trade-off: 4-byte key columns ride the sort twice (verify
        # lane here + payload lane from plan_lane_descs, ~+30 ms/lane at
        # 33M rows) — deduplicating needs static key→column maps and a
        # bits→value inverse at unpack, deferred until the hash path
        # shows up in a profile again
        kb_lanes = []
        for a, b in zip(abits, bbits):
            cat = jnp.concatenate([a, b])
            if cat.dtype.itemsize == 8:
                kb_lanes.append((cat >> 32).astype(jnp.uint32))
                kb_lanes.append(cat.astype(jnp.uint32))
            else:
                kb_lanes.append(cat.astype(jnp.uint32))
        h1, h2 = hash2_streams(kb_lanes, live)
        res = jax.lax.sort((h1, h2, tag) + tuple(kb_lanes) + tuple(lanes),
                           num_keys=3)
        nk = len(kb_lanes)
        return tk.join_plan_stream(
            res[0], res[2], na, nb,
            emit_unmatched_a=join_type != JoinType.INNER,
            lanes=res[3 + nk:], n_a_lanes=len(a_lanes),
            n_b_lanes=len(b_lanes), bits2_s=res[1],
            verify_lanes=res[3:3 + nk],
            block_rows=block_rows, interpret=interpret)

    bits = jnp.concatenate([abits[0], bbits[0]])
    bits = jnp.where(live, bits, allones)
    res = jax.lax.sort((bits, tag) + tuple(lanes), num_keys=2)
    bits_s, tag_s, lanes_s = res[0], res[1], res[2:]
    return tk.join_plan_stream(bits_s, tag_s, na, nb,
                               emit_unmatched_a=join_type != JoinType.INNER,
                               lanes=lanes_s, n_a_lanes=len(a_lanes),
                               n_b_lanes=len(b_lanes),
                               block_rows=block_rows, interpret=interpret)


_plan_program_stream_jit = partial(
    jax.jit, static_argnames=("str_flags", "join_type", "a_desc", "b_desc",
                              "block_rows", "hash_mode",
                              "interpret"))(_plan_program_stream_impl)


def plan_program_stream(*args, interpret: bool = False, **kw):
    """Dispatch: compiled on TPU; EAGER under the interpreter (tests) —
    jitting the interpreted Pallas graph costs ~70 s of XLA CPU compile
    per shape variant, while eager execution of test-sized inputs is
    milliseconds."""
    if interpret:
        return _plan_program_stream_impl(*args, interpret=True, **kw)
    return _plan_program_stream_jit(*args, interpret=False, **kw)


def _materialize_program_stream_impl(counts, a_streams, b_streams,
                                     ldat, lval, rdat, rval,
                                     join_type: JoinType, cap_e: int,
                                     a_desc=(), b_desc=(),
                                     block_rows: int = 64,
                                     interpret: bool = False):
    """Phase 2 (stream path): compacted plan + payload lanes → output
    rows via the streaming expansion kernel. Returns (ldat', lval',
    rdat', rval', emit). Columns that rode sort lanes are unpacked from
    the kernel's lane outputs (zero output-sized XLA gathers); the rest
    gather by the materialized aidx/bidx."""
    from . import tpu_kernels as tk

    aidx, bidx, a_lane_outs, b_lane_outs = tk.join_expand_stream(
        counts, a_streams, b_streams, cap_e, block_rows=block_rows,
        interpret=interpret)
    valid = aidx >= 0
    bhit = bidx >= 0
    lidx, ridx = (bidx, aidx) if join_type == JoinType.RIGHT else (aidx, bidx)

    if join_type == JoinType.RIGHT:
        adat, aval, bdat, bval = rdat, rval, ldat, lval
    else:
        adat, aval, bdat, bval = ldat, lval, rdat, rval

    def unpack(dat, val, desc, lane_outs, hit, idx):
        od: list = [None] * len(dat)
        ov: list = [None] * len(dat)
        for (ci, kind), lane in zip(desc, lane_outs):
            if kind == "d":
                od[ci] = lane if dat[ci].dtype == jnp.uint32 \
                    else lane.view(dat[ci].dtype)
                if val[ci] is None:
                    ov[ci] = hit
            else:
                ov[ci] = (lane != 0) & hit
        fb = [ci for ci in range(len(dat)) if od[ci] is None]
        if fb:
            fbd, fbv = gather_columns(
                tuple(dat[ci] for ci in fb), tuple(val[ci] for ci in fb),
                idx)
            for k, ci in enumerate(fb):
                od[ci], ov[ci] = fbd[k], fbv[k]
        return tuple(od), tuple(ov)

    aod, aov = unpack(adat, aval, a_desc, a_lane_outs, valid, aidx)
    bod, bov = unpack(bdat, bval, b_desc, b_lane_outs, bhit, bidx)
    if join_type == JoinType.RIGHT:
        lod, lov, rod, rov = bod, bov, aod, aov
    else:
        lod, lov, rod, rov = aod, aov, bod, bov
    return lod, lov, rod, rov, valid, lidx, ridx


_materialize_program_stream_jit = partial(
    jax.jit, static_argnames=("join_type", "cap_e", "a_desc", "b_desc",
                              "block_rows",
                              "interpret"))(_materialize_program_stream_impl)


def materialize_program_stream(*args, interpret: bool = False, **kw):
    """Dispatch twin of plan_program_stream: compiled on TPU, eager under
    the interpreter."""
    if interpret:
        return _materialize_program_stream_impl(*args, interpret=True, **kw)
    return _materialize_program_stream_jit(*args, interpret=False, **kw)


def _vm(v, n):
    """validity-or-None → mask (None means all-valid; stays device-side)."""
    return jnp.ones(n, dtype=bool) if v is None else v


def _keys_to_bits(lkeys, lkvalid, rkeys, rkvalid, str_flags):
    from .order import ordered_bits_raw

    n_l, n_r = lkeys[0].shape[0], rkeys[0].shape[0]
    lbits = tuple(ordered_bits_raw(x, s) for x, s in zip(lkeys, str_flags))
    rbits = tuple(ordered_bits_raw(x, s) for x, s in zip(rkeys, str_flags))
    lkv = jnp.ones(n_l, bool)
    for v in lkvalid:
        if v is not None:
            lkv = lkv & v
    rkv = jnp.ones(n_r, bool)
    for v in rkvalid:
        if v is not None:
            rkv = rkv & v
    return lbits, lkv, rbits, rkv


@partial(jax.jit, static_argnames=("str_flags", "join_type"))
def plan_program(lkeys, lkvalid, lemit, rkeys, rkvalid, remit, str_flags,
                 join_type: JoinType):
    """Phase 1: raw key columns → plan (counts + match arrays), one
    compiled program. Only counts2 crosses to the host; the match arrays
    stay on device for phase 2."""
    lbits, lkv, rbits, rkv = _keys_to_bits(lkeys, lkvalid, rkeys, rkvalid,
                                           str_flags)
    return join_plan_keys(lbits, lkv, _vm(lemit, lkv.shape[0]),
                          rbits, rkv, _vm(remit, rkv.shape[0]), join_type)


@partial(jax.jit, static_argnames=("join_type", "cap_p", "cap_u"))
def materialize_program(lo, m, bperm, un_mask, aemit,
                        ldat, lval, rdat, rval,
                        join_type: JoinType, cap_p: int, cap_u: int):
    """Phase 2: plan arrays → index pairs → gather every payload column,
    one compiled program. Returns (ldat', lval', rdat', rval', emit)."""
    lidx, ridx, emit = join_materialize_gids(
        lo, m, bperm, un_mask, _vm(aemit, lo.shape[0]), join_type,
        cap_p, cap_u)
    lod, lov = gather_columns(ldat, lval, lidx)
    rod, rov = gather_columns(rdat, rval, ridx)
    return lod, lov, rod, rov, emit, lidx, ridx


def gather_columns(dat, val, idx):
    """Batch −1→null gather (traceable): new validity = src validity at the
    gathered row AND a real (non-negative) index. Empty sources produce
    all-null outputs (idx is guaranteed all −1 then).

    Columns are gathered individually: XLA fuses same-index gathers into
    one HBM pass on its own, so manual (n, C) bit-packing only adds stack
    copies (measured +200 ms at 17M rows — packing pays ONLY for gathers
    with *independent* index vectors, as in _expand_from_match)."""
    safe = jnp.maximum(idx, 0)
    hit = idx >= 0
    out_d, out_v = [], []
    for d, v in zip(dat, val):
        if d.shape[0] == 0:
            out_d.append(jnp.zeros(idx.shape + d.shape[1:], d.dtype))
            out_v.append(jnp.zeros(idx.shape, bool))
        else:
            out_d.append(jnp.take(d, safe, axis=0))
            out_v.append(hit if v is None else (jnp.take(v, safe) & hit))
    return tuple(out_d), tuple(out_v)




