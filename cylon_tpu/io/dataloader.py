"""Partitioned data loading for ML workloads — pycylon util.data parity.

Reference: python/pycylon/util/data/DataManager.py (`DataLoader` /
`Partition` feeding the PyTorch demo pipelines) and
util/data/generator.py. The reference loads per-rank CSV partitions
into Arrow tables and hands index-partitioned views to a DL framework;
here the loader builds cylon_tpu Tables (device-resident) and exports
dense numpy blocks for the training framework (see
examples/torch_dataloader_demo.py for the end-to-end flow).
"""
from __future__ import annotations

import os
from math import ceil
from typing import List, Optional, Sequence

import numpy as np

from ..context import CylonContext
from ..data.table import Table
from ..status import Code, CylonError


class Partition:
    """An index-partitioned view over a dense sample block (reference:
    DataManager.Partition)."""

    def __init__(self, data: np.ndarray, index: Sequence[int]):
        self.data = data
        self.index = list(index)

    def __len__(self) -> int:
        return len(self.index)

    def __getitem__(self, i: int):
        return self.data[self.index[i]]


class DataLoader:
    """Load per-rank partitioned CSV/Parquet files into Tables and
    partition the dense export across workers (reference:
    DataManager.DataLoader, re-based on the TPU-native Table)."""

    def __init__(self, ctx: CylonContext, source_dir: str,
                 source_files: Sequence[str], file_type: str = "csv"):
        if not os.path.isdir(source_dir):
            raise CylonError(Code.IOError, f"no such dir: {source_dir}")
        for f in source_files:
            if not os.path.exists(os.path.join(source_dir, f)):
                raise CylonError(Code.IOError, f"missing file: {f}")
        self._ctx = ctx
        self._dir = source_dir
        self._files = list(source_files)
        self._type = file_type
        self.tables: List[Table] = []

    def load(self) -> "DataLoader":
        from . import csv as _csv
        from . import parquet as _parquet

        reader = _csv.read_csv if self._type == "csv" \
            else _parquet.read_parquet
        self.tables = [reader(self._ctx, os.path.join(self._dir, f))
                       for f in self._files]
        return self

    def table(self, i: int = 0) -> Table:
        return self.tables[i]

    def to_numpy_blocks(self) -> List[np.ndarray]:
        return [t.to_numpy(order="C") for t in self.tables]

    def partitions(self, n_workers: int, seed: Optional[int] = 0,
                   table_index: int = 0) -> List[Partition]:
        """Shuffled, near-equal index partitions of one table's dense
        export — one per DL worker (reference: DataPartitioner)."""
        block = self.tables[table_index].to_numpy(order="C")
        n = block.shape[0]
        idx = np.arange(n)
        if seed is not None:
            np.random.default_rng(seed).shuffle(idx)
        per = ceil(n / max(n_workers, 1))
        return [Partition(block, idx[w * per:(w + 1) * per])
                for w in range(n_workers)]
