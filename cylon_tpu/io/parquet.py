"""Parquet IO (reference: io/arrow_io.cpp:64-113 + parquet.cpp, flag-gated
by BUILD_CYLON_PARQUET; always available here)."""
from __future__ import annotations

from typing import Optional, Sequence, Union

from ..config import ParquetOptions
from ..context import CylonContext
from ..data.table import Table, concat_tables
from ..resilience import inject as _inject
from ..resilience import retry as _retry
from ..status import Code, CylonDataError, CylonError


def _read_table(path: str):
    """One parquet file -> pyarrow table, with the error taxonomy
    applied: missing file / permissions = IOError, malformed bytes
    (truncated footer, bad magic, garbage) = typed
    :class:`CylonDataError` — never a raw backend traceback. Transient
    filesystem failures retry under the bounded policy."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    def attempt():
        _inject.fire("ingest", detail=f"parquet {path}")
        try:
            return pq.read_table(path)
        except OSError as e:
            # environment errors (missing file, permissions, disk)
            # are IOError — fixable without touching the bytes, NOT
            # bad data
            raise CylonError(Code.IOError, str(e))
        except (pa.ArrowInvalid, pa.ArrowException, ValueError) as e:
            raise CylonDataError(
                f"malformed parquet {path}: {e}") from e

    return _retry.run_retryable("ingest", attempt)


def read_parquet(ctx: CylonContext, path: Union[str, Sequence[str]],
                 options: Optional[ParquetOptions] = None) -> Table:
    if isinstance(path, (list, tuple)):
        return concat_tables([read_parquet(ctx, p, options) for p in path], ctx)
    return Table.from_arrow(ctx, _read_table(path))


def read_parquet_per_rank(ctx: CylonContext, path_pattern: str,
                          options: Optional[ParquetOptions] = None
                          ) -> Table:
    """Per-rank parquet placement — ``path_pattern`` contains ``{rank}``,
    substituted with each shard index (the per-rank file convention
    read_csv_per_rank implements for CSV; reference:
    cpp/test/join_test.cpp:22-24). Multi-host: each controller process
    reads only the shards it owns; collective, all processes must call
    it."""
    from ..parallel import shard as _shard

    tables = []
    for i in ctx.local_shard_indices():
        p = path_pattern.format(rank=i)
        tables.append(Table.from_arrow(ctx, _read_table(p)))
    return _shard.assemble_process_local(tables, ctx)


def write_parquet(table: Table, path: str,
                  options: Optional[ParquetOptions] = None) -> None:
    import pyarrow.parquet as pq

    options = options or ParquetOptions()
    pq.write_table(table.to_arrow(), path,
                   row_group_size=options._chunk_size,
                   compression=options._compression or "snappy")
