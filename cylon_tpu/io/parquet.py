"""Parquet IO (reference: io/arrow_io.cpp:64-113 + parquet.cpp, flag-gated
by BUILD_CYLON_PARQUET; always available here)."""
from __future__ import annotations

from typing import Optional, Sequence, Union

from ..config import ParquetOptions
from ..context import CylonContext
from ..data.table import Table, concat_tables
from ..status import Code, CylonError


def read_parquet(ctx: CylonContext, path: Union[str, Sequence[str]],
                 options: Optional[ParquetOptions] = None) -> Table:
    import pyarrow.parquet as pq

    if isinstance(path, (list, tuple)):
        return concat_tables([read_parquet(ctx, p, options) for p in path], ctx)
    try:
        pa_table = pq.read_table(path)
    except FileNotFoundError as e:
        raise CylonError(Code.IOError, str(e))
    return Table.from_arrow(ctx, pa_table)


def write_parquet(table: Table, path: str,
                  options: Optional[ParquetOptions] = None) -> None:
    import pyarrow.parquet as pq

    options = options or ParquetOptions()
    pq.write_table(table.to_arrow(), path,
                   row_group_size=options._chunk_size,
                   compression=options._compression or "snappy")
