"""Parquet IO (reference: io/arrow_io.cpp:64-113 + parquet.cpp, flag-gated
by BUILD_CYLON_PARQUET; always available here)."""
from __future__ import annotations

from typing import Optional, Sequence, Union

from ..config import ParquetOptions
from ..context import CylonContext
from ..data.table import Table, concat_tables
from ..status import Code, CylonError


def read_parquet(ctx: CylonContext, path: Union[str, Sequence[str]],
                 options: Optional[ParquetOptions] = None) -> Table:
    import pyarrow.parquet as pq

    if isinstance(path, (list, tuple)):
        return concat_tables([read_parquet(ctx, p, options) for p in path], ctx)
    try:
        pa_table = pq.read_table(path)
    except FileNotFoundError as e:
        raise CylonError(Code.IOError, str(e))
    return Table.from_arrow(ctx, pa_table)


def read_parquet_per_rank(ctx: CylonContext, path_pattern: str,
                          options: Optional[ParquetOptions] = None
                          ) -> Table:
    """Per-rank parquet placement — ``path_pattern`` contains ``{rank}``,
    substituted with each shard index (the per-rank file convention
    read_csv_per_rank implements for CSV; reference:
    cpp/test/join_test.cpp:22-24). Multi-host: each controller process
    reads only the shards it owns; collective, all processes must call
    it."""
    import pyarrow.parquet as pq

    from ..parallel import shard as _shard

    tables = []
    for i in ctx.local_shard_indices():
        p = path_pattern.format(rank=i)
        try:
            tables.append(Table.from_arrow(ctx, pq.read_table(p)))
        except FileNotFoundError as e:
            raise CylonError(Code.IOError, str(e))
    return _shard.assemble_process_local(tables, ctx)


def write_parquet(table: Table, path: str,
                  options: Optional[ParquetOptions] = None) -> None:
    import pyarrow.parquet as pq

    options = options or ParquetOptions()
    pq.write_table(table.to_arrow(), path,
                   row_group_size=options._chunk_size,
                   compression=options._compression or "snappy")
