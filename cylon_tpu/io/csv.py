"""CSV IO — pyarrow-backed read, host stringify write.

Reference: cpp/src/cylon/io/arrow_io.cpp:34-62 (Arrow CSV TableReader over
a memory-mapped file, options from the type-erased CSVConfigHolder) and
table.cpp:1019-1064 (multi-file concurrent read, one thread per file).
Here pyarrow's C++ CSV reader does the parsing (same engine family the
reference leans on), and the parsed host table is dictionary-encoded +
device_put into HBM.
"""
from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Sequence, Union

from ..config import CSVReadOptions, CSVWriteOptions
from ..context import CylonContext
from ..data.table import Table, concat_tables
from ..resilience import inject as _inject
from ..resilience import retry as _retry
from ..status import Code, CylonDataError, CylonError


def _arrow_options(options: CSVReadOptions):
    import pyarrow.csv as pacsv

    o = options
    read_opts = pacsv.ReadOptions(
        use_threads=o._use_threads,
        block_size=o._block_size,
        skip_rows=o._skip_rows,
        column_names=o._column_names,
        autogenerate_column_names=o._autogenerate_column_names,
    )
    parse_opts = pacsv.ParseOptions(
        delimiter=o._delimiter,
        quote_char=o._quote_char if o._quoting else '"',
        double_quote=o._double_quote,
        escape_char=o._escape_char if o._escaping else False,
        newlines_in_values=o._newlines_in_values,
        ignore_empty_lines=bool(o._ignore_empty_lines),
    )
    convert_kwargs = dict(
        check_utf8=True,
        strings_can_be_null=o._strings_can_be_null,
        include_columns=o._include_columns,
        include_missing_columns=o._include_missing_columns,
    )
    if o._null_values is not None:
        convert_kwargs["null_values"] = o._null_values
    if o._true_values is not None:
        convert_kwargs["true_values"] = o._true_values
    if o._false_values is not None:
        convert_kwargs["false_values"] = o._false_values
    if o._column_types is not None:
        import pyarrow as pa

        m = {}
        for name, dt in o._column_types.items():
            m[name] = pa.from_numpy_dtype(dt.np_dtype) \
                if not dt.is_var_width() else pa.string()
        convert_kwargs["column_types"] = m
    convert_opts = pacsv.ConvertOptions(**convert_kwargs)
    return read_opts, parse_opts, convert_opts


def read_csv(ctx: CylonContext, path: Union[str, Sequence[str]],
             options: Optional[CSVReadOptions] = None) -> Table:
    """Reference: FromCSV (table.cpp:367-386); multi-file variant spawns a
    reader per file then merges (table.cpp:1030-1064)."""
    options = options or CSVReadOptions()
    if isinstance(path, (list, tuple)):
        paths: List[str] = list(path)
        if options.IsConcurrentFileReads():
            with ThreadPoolExecutor(max_workers=len(paths)) as ex:
                tables = list(ex.map(lambda p: _read_one(ctx, p, options), paths))
        else:
            tables = [_read_one(ctx, p, options) for p in paths]
        return concat_tables(tables, ctx)
    return _read_one(ctx, path, options)


def read_csv_per_rank(ctx: CylonContext, path_pattern: str,
                      options: Optional[CSVReadOptions] = None) -> Table:
    """Per-rank file placement: ``path_pattern`` contains ``{rank}``,
    substituted with each shard index (the reference's per-rank CSV
    convention, cpp/test/join_test.cpp:22-24 ``csv1_<rank>.csv``).

    Single-controller: reads EVERY shard's file and assembles them
    shard-aligned (shard i of the result holds file i's rows). Multi-host:
    each controller process reads only the files of the shards it owns —
    collective, all processes must call it.
    """
    from ..parallel import shard as _shard

    options = options or CSVReadOptions()
    local = ctx.local_shard_indices()
    paths = [path_pattern.format(rank=i) for i in local]
    if options.IsConcurrentFileReads() and len(paths) > 1:
        with ThreadPoolExecutor(max_workers=len(paths)) as ex:
            tables = list(ex.map(lambda p: _read_one(ctx, p, options), paths))
    else:
        tables = [_read_one(ctx, p, options) for p in paths]
    return _shard.assemble_process_local(tables, ctx)


def _read_one(ctx: CylonContext, path: str, options: CSVReadOptions) -> Table:
    import pyarrow as pa
    import pyarrow.csv as pacsv

    read_opts, parse_opts, convert_opts = _arrow_options(options)

    def attempt():
        _inject.fire("ingest", detail=f"csv {path}")
        try:
            return pacsv.read_csv(path, read_options=read_opts,
                                  parse_options=parse_opts,
                                  convert_options=convert_opts)
        except OSError as e:
            # environment errors (missing file, permissions, disk) are
            # IOError — fixable without touching the bytes, NOT bad
            # data
            raise CylonError(Code.IOError, str(e))
        except (pa.ArrowInvalid, pa.ArrowException, ValueError) as e:
            # malformed bytes are a DATA error, typed and
            # non-retryable — the parser's traceback never reaches
            # the caller
            raise CylonDataError(f"malformed CSV {path}: {e}") from e

    # transient filesystem failures retry under the same bounded
    # policy as exchanges; IOError/DataError are non-retryable and
    # leave the loop on the first attempt
    return Table.from_arrow(ctx, _retry.run_retryable("ingest",
                                                      attempt))


def write_csv(table: Table, path: str,
              options: Optional[CSVWriteOptions] = None) -> None:
    """Reference: Table::WriteCSV via PrintToOStream (table.cpp:429-440,
    1091-1142 — native C++ row stringify there, native C++ here: all-
    numeric tables go through the multithreaded writer in
    native/cylon_host.cpp; strings/temporal/bool fall back to pandas)."""
    import jax
    import numpy as np

    options = options or CSVWriteOptions()
    names = options.GetColumnNames()
    t = table.compact() if table.row_mask is not None else table
    from .. import native as _native

    native_ok = (
        all(not c.is_string and not c.dtype.is_temporal()
            and np.dtype(c.data.dtype) in _native.SUPPORTED_CSV_DTYPES
            for c in t._columns)
        and (names is None or len(names) == t.column_count))
    if native_ok:
        cols = [np.asarray(jax.device_get(c.data)) for c in t._columns]
        valids = [c._host_mask() for c in t._columns]
        out_names = list(names) if names is not None else \
            [c.name or f"c{i}" for i, c in enumerate(t._columns)]
        if _native.write_csv_numeric(cols, valids, out_names, path,
                                     options.GetDelimiter()):
            return
    df = t.to_pandas()
    if names is not None:
        df.columns = names
    df.to_csv(path, sep=options.GetDelimiter(), index=False)
