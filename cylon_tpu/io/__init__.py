from .csv import read_csv, write_csv
from .parquet import read_parquet, write_parquet

__all__ = ["read_csv", "write_csv", "read_parquet", "write_parquet"]
