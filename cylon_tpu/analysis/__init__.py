"""cylon_tpu.analysis — pluggable static-analysis suite.

Ten checker families guard the invariants the paper's *local kernel +
shuffle + local kernel* decomposition rests on (SURVEY §1), each
registered in `core.CHECKERS` and runnable from one entry point:

* ``layering``      — declarative per-subsystem import contracts
                      (generalizes scripts/check_plan_imports.py);
* ``hostsync``      — AST detector for host transfers inside traced
                      (`jit`/`shard_map`/Pallas) code;
* ``collectives``   — jaxpr-level checks over the `parallel/` kernel
                      factories on a virtual mesh: collective axis
                      names, all_to_all split/concat discipline, no
                      implicit float64 promotion;
* ``witness``       — optimizer-independent re-derivation of
                      partitioning witnesses over optimized plans
                      (wraps plan/verify.py): every shuffle elision
                      must be justified or the plan is rejected;
* ``span-coverage`` — every public ``distributed_*`` op and every
                      executor lowering must run under a telemetry
                      span (the observability layer's coverage
                      contract — an unspanned operator is invisible
                      to shuffle counting and EXPLAIN ANALYZE);
* ``ledger-coverage`` — the memory analog: every materializing
                      ``distributed_*`` op and executor lowering must
                      register its output with the telemetry ledger,
                      or its HBM is unattributable to gauges, leak
                      reports and crash dumps;
* ``errors``        — no silent swallowing: bare ``except:`` and
                      broad ``except Exception`` handlers that
                      neither re-raise nor report (log call /
                      ``error=True`` span attr) are findings — a
                      fault dying in one never reaches the
                      resilience layer's retry or flight recorder;
* ``concurrency``   — thread-domain race detector over the service
                      tier: shared state written across the worker/
                      submitter/finalizer/hook domains must follow
                      the per-attribute lock discipline, no blocking
                      call may hold a lock, thread-entry code must
                      re-stamp the contextvars it reads, and GC
                      finalizers must never touch non-reentrant
                      locks or jax;
* ``envknobs``      — every ``CYLON_*`` environment read routes
                      through the declared knob registry
                      (telemetry/knobs.py) and every declared knob
                      appears in the generated docs table;
* ``specialization`` — kernel-specialization auditor: every
                      ``counted_cache`` factory cache-key argument is
                      classified (structural / schema-bound / bucketed
                      / data-dependent / unbounded) by tracing it from
                      the call site through the call graph; a runtime
                      count reaching a cache key without a recognized
                      bucketing helper is a finding — recompile
                      cardinality stays bounded by construction.

Run ``python -m cylon_tpu.analysis`` (see ``--help``); wired into
``scripts/check.sh`` ahead of tier-1. Rule catalog, suppression syntax
and extension guide: docs/analysis.md.
"""
from __future__ import annotations

from .core import (AnalysisContext, CHECKERS, Finding, RunResult,
                   SARIF_VERSION, SCHEMA_VERSION, register, run_checkers,
                   to_json_text, to_sarif, to_sarif_text)

# importing the checker modules registers them
from . import layering as _layering          # noqa: F401,E402
from . import hostsync as _hostsync          # noqa: F401,E402
from . import collectives as _collectives    # noqa: F401,E402
from . import witness as _witness            # noqa: F401,E402
from . import spancov as _spancov            # noqa: F401,E402
from . import ledgercov as _ledgercov        # noqa: F401,E402
from . import errors as _errors              # noqa: F401,E402
from . import concurrency as _concurrency    # noqa: F401,E402
from . import envknobs as _envknobs          # noqa: F401,E402
from . import specialization as _specialization  # noqa: F401,E402

__all__ = ["AnalysisContext", "CHECKERS", "Finding", "RunResult",
           "SARIF_VERSION", "SCHEMA_VERSION", "register", "run_checkers",
           "to_json_text", "to_sarif", "to_sarif_text"]
