"""Ledger-coverage lint: materializing code paths must register output.

The memory half of the observatory (telemetry/ledger.py) is only as
good as its coverage — a distributed operator that materializes a
result without registering it leaves HBM that no gauge, leak report or
crash dump can attribute, and the gap is silent because nothing fails.
This checker is the memory analog of ``span-coverage``:

* every public ``distributed_*`` function in ``parallel/dist_ops.py``
  must call ``ledger.track(...)`` (any alias — ``_ledger.track``,
  bare ``track``) somewhere in its body;
* every executor lowering (``_do_*`` method in ``plan/executor.py``)
  must do the same — the lowering's ``track`` is what gives
  ``cylon_live_table_bytes{owner="plan.*"}`` and the end-of-query leak
  report their per-node attribution.

A track "anywhere in the body" is deliberately the whole bar, for the
same reason span-coverage accepts it: several operators return early
on no-op paths (world-1 short circuits, witness-skipped shuffles) that
allocate nothing, and per-branch coverage would force tracking of
tables the op did not materialize. What the lint catches is the real
failure mode — a NEW operator or lowering whose output the ledger
never sees.

Fixture trees exercise it through ``options["ledger_scopes"]``.
"""
from __future__ import annotations

import ast
from typing import List, Tuple

from .core import AnalysisContext, Finding, register
from .spancov import _targets

# (package-relative file, kind, name-prefix); kind as in spancov
DEFAULT_SCOPES: Tuple[Tuple[str, str, str], ...] = (
    ("parallel/dist_ops.py", "function", "distributed_"),
    ("plan/executor.py", "method", "_do_"),
)

# call names that register with the ledger: telemetry.ledger.track
# under the repo's import aliases, as bare names or attributes
_TRACK_CALL_NAMES = frozenset({"track", "_track", "ledger_track"})


def _is_track_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    name = fn.id if isinstance(fn, ast.Name) else \
        fn.attr if isinstance(fn, ast.Attribute) else None
    return name in _TRACK_CALL_NAMES


def _has_track(fn_node: ast.FunctionDef) -> bool:
    return any(_is_track_call(n) for n in ast.walk(fn_node))


@register("ledger-coverage")
def check_ledger_coverage(ctx: AnalysisContext) -> List[Finding]:
    scopes = ctx.options.get("ledger_scopes", DEFAULT_SCOPES)
    by_rel = {f.rel: f for f in ctx.files()}
    findings: List[Finding] = []
    for rel, kind, prefix in scopes:
        f = by_rel.get(rel)
        if f is None:
            continue
        for fn in _targets(f.tree, kind, prefix):
            if not _has_track(fn):
                what = "executor lowering" if kind == "method" \
                    else "distributed op"
                findings.append(Finding(
                    rule="ledger-coverage/missing-ledger", path=rel,
                    line=fn.lineno,
                    message=f"{what} {fn.name}() materializes output "
                            f"the memory ledger never sees: no HBM "
                            f"gauge, leak report or crash dump can "
                            f"attribute it — register the result via "
                            f"telemetry.ledger.track(table, owner)"))
    return findings
