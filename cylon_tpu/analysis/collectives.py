"""Jaxpr collective checker: abstract evaluation of `parallel/` kernels.

Every distributed operator in this codebase is a `jax.jit(shard_map(
kernel, mesh...))` program built by an ``@lru_cache`` factory in
`parallel/shuffle.py` / `parallel/dist_ops.py`. This checker builds
each factory on a VIRTUAL mesh (forced host devices — no accelerator
needed), traces it with abstract `ShapeDtypeStruct` inputs via
``jax.make_jaxpr``, and walks the resulting jaxpr recursively:

* ``collectives/axis-name`` — every collective primitive (`psum`,
  `all_gather`, `all_to_all`, `ppermute`, `axis_index`, `pbroadcast`)
  must name an axis of the ENCLOSING `shard_map`'s mesh. A stray name
  is a program that only works by accident of a caller's axis naming.
* ``collectives/all-to-all-axes`` — `all_to_all` must use
  ``split_axis == concat_axis``: the repo-wide exchange discipline is
  "shard-major dimension 0 in, shard-major dimension 0 out" (the
  [world, block] send stacks); mismatched axes silently transpose the
  received blocks.
* ``collectives/f64-promotion`` — no equation may INTRODUCE a float64
  value from non-float64 inputs. On TPU an implicit f64 (a stray
  ``np.float64`` scalar, a numpy-promoting op) either fails Mosaic or
  silently doubles a kernel's bandwidth; tracing with x64 enabled makes
  the promotion visible in the jaxpr.
* ``collectives/trace-error`` — the factory fails to trace at all
  (e.g. a collective over an unbound axis name raises at trace time).

Entry points are DECLARED (factory + static args + input shapes) in
``default_entry_points`` — abstract evaluation needs concrete static
configuration. Any ``_*_fn`` factory in `parallel/` the catalog does
not cover is a REAL FINDING (``collectives/uncataloged-factory``), not
a note: an uncataloged factory is a collective program no axis-name /
all-to-all / f64 check ever sees, which is exactly how catalog drift
used to rot. Helpers that merely LOOK like factories (returning plain
host callables, not jitted programs) opt out explicitly with
``# cylint: disable=collectives/uncataloged-factory`` on their def
line — exclusion is a reviewable decision, never a hidden set. The
Pallas stream factories are TPU-only (the interpreter inside jit is
prohibitive) and are skipped with a note off-TPU. Option
``collectives_coverage_only`` runs just the catalog sweep (no tracing)
— the fast form the fixture tests drive.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

from .core import AnalysisContext, Finding, register

# collective primitive name -> param key holding the axis name(s)
_COLLECTIVES = {
    "psum": "axes", "psum2": "axes", "pmax": "axes", "pmin": "axes",
    "all_gather": "axis_name", "all_to_all": "axis_name",
    "ppermute": "axis_name", "axis_index": "axis_name",
    "pbroadcast": "axes", "pcast": "axes", "pvary": "axes",
    "reduce_scatter": "axis_name",
}


@dataclass
class EntryPoint:
    """One traced program: where it lives, how to build it, what to
    feed it. ``build(mesh, mod)`` returns the jitted callable;
    ``inputs(mesh)`` returns the abstract argument tuple."""

    name: str
    path: str                       # package-relative file, for findings
    build: Callable
    inputs: Callable
    factory: str = ""               # factory function name (coverage)
    tpu_only: bool = False


def _virtual_mesh(world: int = 4):
    """A 1-D mesh over host devices. Forcing the virtual CPU device
    count only works before the jax backend initializes — harmless when
    it already has (the checker then runs on whatever width exists;
    every check below is width-independent)."""
    os.environ.setdefault("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in \
            os.environ["XLA_FLAGS"]:
        os.environ["XLA_FLAGS"] += \
            " --xla_force_host_platform_device_count=8"
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devs = jax.devices()
    world = min(world, len(devs))
    return Mesh(np.array(devs[:world]), ("shards",))


def _walk_jaxpr(jaxpr, allowed_axes: Tuple[str, ...], sink):
    """Recurse through all nested jaxprs; ``sink(eqn, allowed_axes)``
    sees every equation with the axis names of its enclosing
    shard_map."""
    from jax.core import ClosedJaxpr, Jaxpr

    for eqn in jaxpr.eqns:
        inner_allowed = allowed_axes
        if eqn.primitive.name == "shard_map":
            mesh = eqn.params.get("mesh")
            names = getattr(mesh, "axis_names", None)
            if names:
                inner_allowed = tuple(names)
        sink(eqn, allowed_axes)
        for v in eqn.params.values():
            for sub in (v if isinstance(v, (list, tuple)) else (v,)):
                if isinstance(sub, ClosedJaxpr):
                    _walk_jaxpr(sub.jaxpr, inner_allowed, sink)
                elif isinstance(sub, Jaxpr):
                    _walk_jaxpr(sub, inner_allowed, sink)


def _check_jaxpr(jaxpr, entry: EntryPoint, line: int) -> List[Finding]:
    import numpy as np

    findings: List[Finding] = []

    def sink(eqn, allowed):
        prim = eqn.primitive.name
        if prim in _COLLECTIVES:
            axes = eqn.params.get(_COLLECTIVES[prim])
            axes = axes if isinstance(axes, (tuple, list)) else (axes,)
            for ax in axes:
                if isinstance(ax, str) and allowed and ax not in allowed:
                    findings.append(Finding(
                        rule="collectives/axis-name", path=entry.path,
                        line=line,
                        message=f"{entry.name}: {prim} over axis "
                                f"{ax!r}, but the enclosing shard_map "
                                f"mesh declares {allowed}"))
        if prim == "all_to_all":
            sa = eqn.params.get("split_axis")
            ca = eqn.params.get("concat_axis")
            if sa != ca:
                findings.append(Finding(
                    rule="collectives/all-to-all-axes", path=entry.path,
                    line=line,
                    message=f"{entry.name}: all_to_all split_axis="
                            f"{sa} != concat_axis={ca}: the exchange "
                            f"discipline is shard-major dim 0 both "
                            f"ways; a mismatch transposes received "
                            f"blocks"))
        # float64 introduction: an output is f64 while no input was.
        # Container primitives (pjit/shard_map/cond/...) re-surface
        # their body's dtypes — only the LEAF equation that performs
        # the promotion reports, or one finding would triple up
        if any(isinstance(v, (list, tuple)) or hasattr(v, "jaxpr")
               for v in eqn.params.values()) or \
                eqn.primitive.name in ("pjit", "shard_map", "closed_call",
                                       "core_call", "custom_jvp_call",
                                       "custom_vjp_call", "cond", "while",
                                       "scan", "remat"):
            return
        out_dts = [getattr(getattr(v, "aval", None), "dtype", None)
                   for v in eqn.outvars]
        if any(d == np.float64 for d in out_dts if d is not None):
            in_dts = [getattr(getattr(v, "aval", None), "dtype", None)
                      for v in eqn.invars]
            if not any(d == np.float64 for d in in_dts if d is not None):
                findings.append(Finding(
                    rule="collectives/f64-promotion", path=entry.path,
                    line=line,
                    message=f"{entry.name}: {prim} introduces float64 "
                            f"from non-f64 inputs (implicit promotion "
                            f"— a stray np.float64 scalar or numpy-"
                            f"promoting op entering the kernel)"))

    _walk_jaxpr(jaxpr, (), sink)
    return findings


# ---------------------------------------------------------------------------
# the declared entry-point catalog for cylon_tpu.parallel
# ---------------------------------------------------------------------------


def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(shape, dtype)


def default_entry_points() -> List[EntryPoint]:
    """Abstract-input catalog for every traceable kernel factory in
    `parallel/`. Geometry: world=4 shards, 16 rows/shard (n=64 global),
    varbytes word buffers 64 words/shard."""
    import jax.numpy as jnp

    N, W = (64,), (256,)        # global rows / words
    CI = (16,)                  # counts_in: world*world
    i32, u32, b = jnp.int32, jnp.uint32, jnp.bool_

    def rows(*dts):
        return tuple(_sds(N, d) for d in dts)

    def vb():
        # (words, starts, lengths)
        return (_sds(W, u32), _sds(N, i32), _sds(N, i32))

    def payload():
        return {"d0": _sds(N, i32), "v0": _sds(N, b)}

    sh = "parallel/shuffle.py"
    do = "parallel/dist_ops.py"

    def S(mesh):  # noqa: N802 - tiny catalog helpers
        from ..parallel import shuffle
        return shuffle

    def D(mesh):  # noqa: N802
        from ..parallel import dist_ops
        return dist_ops

    eps: List[EntryPoint] = [
        EntryPoint(
            "count", sh, lambda m: S(m)._count_fn(m),
            lambda m: rows(i32, b), factory="_count_fn"),
        EntryPoint(
            "count2", sh, lambda m: S(m)._count2_fn(m),
            lambda m: rows(i32, b, i32, b), factory="_count2_fn"),
        EntryPoint(
            "exchange_padded", sh,
            lambda m: S(m)._exchange_padded_fn(m, 16, "sort"),
            lambda m: (payload(),) + rows(i32, b),
            factory="_exchange_padded_fn"),
        EntryPoint(
            # the fused Pallas partition path, traced through the
            # interpreter so the axis/all-to-all/f64 checks cover the
            # kernel-routed program off-TPU too
            "exchange_padded_kernel", sh,
            lambda m: S(m)._exchange_padded_fn(m, 16, "interp"),
            lambda m: (payload(),) + rows(i32, b),
            factory="_exchange_padded_fn"),
        EntryPoint(
            "exchange_padded_pair", sh,
            lambda m: S(m)._exchange_padded_pair_fn(m, 16, 16,
                                                    "sort", "sort"),
            lambda m: (payload(),) + rows(i32, b)
            + (payload(),) + rows(i32, b),
            factory="_exchange_padded_pair_fn"),
        EntryPoint(
            "exchange_blockwise", sh,
            lambda m: S(m)._exchange_fn(m, 8, 2, 64),
            lambda m: (payload(),) + rows(i32, b),
            factory="_exchange_fn"),
        EntryPoint(
            "exchange_partition", sh,
            lambda m: S(m)._exchange_partition_fn(m, 16, 8, "sort"),
            lambda m: (payload(),) + rows(i32, b),
            factory="_exchange_partition_fn"),
        EntryPoint(
            "exchange_chunk_first", sh,
            lambda m: S(m)._exchange_chunk_first_fn(m, 16, 8, "sort"),
            lambda m: (payload(),) + rows(i32, b),
            factory="_exchange_chunk_first_fn"),
        EntryPoint(
            # chunked pipeline head with the Pallas partition folded in
            "exchange_chunk_first_kernel", sh,
            lambda m: S(m)._exchange_chunk_first_fn(m, 16, 8, "interp"),
            lambda m: (payload(),) + rows(i32, b),
            factory="_exchange_chunk_first_fn"),
        EntryPoint(
            # operands: chunk-padded sorted leaves (rows + world*cb),
            # per-shard start offsets, the [world*block] accumulator,
            # and the replicated chunk-index scalar
            "exchange_chunk", sh,
            lambda m: S(m)._exchange_chunk_fn(m, 16, 8),
            lambda m: ({"d0": _sds((96,), i32), "v0": _sds((96,), b)},
                       _sds(CI, i32),
                       {"d0": _sds((256,), i32), "v0": _sds((256,), b)},
                       _sds((), i32)),
            factory="_exchange_chunk_fn"),
        EntryPoint(
            # hot-key salted routing: targets+emit (+ the replicated
            # warn-factor scalar) -> salted targets + stacked count
            # matrices. salt=4 is the declared CYLON_SALT_FACTOR shape
            "salted_targets", sh,
            lambda m: S(m)._salted_targets_fn(m, 4),
            lambda m: rows(i32, b) + (_sds((), jnp.float32),),
            factory="_salted_targets_fn"),
        EntryPoint(
            "string_hash", do, lambda m: D(m)._string_hash_fn(m, 4),
            lambda m: vb(), factory="_string_hash_fn"),
        EntryPoint(
            "word_lanes", do, lambda m: D(m)._word_lanes_fn(m, 4),
            lambda m: vb(), factory="_word_lanes_fn"),
        EntryPoint(
            "word_targets", do, lambda m: D(m)._word_targets_fn(m),
            lambda m: vb() + rows(i32, b), factory="_word_targets_fn"),
        EntryPoint(
            "starts_reconcile", do,
            lambda m: D(m)._starts_reconcile_fn(m, 16, 64),
            lambda m: (_sds(N, i32), _sds(CI, i32), _sds(CI, i32)),
            factory="_starts_reconcile_fn"),
        EntryPoint(
            "lanes_interleave", do,
            lambda m: D(m)._lanes_interleave_fn(m, 2),
            lambda m: (_sds(N, i32), _sds(N, u32), _sds(N, u32)),
            factory="_lanes_interleave_fn"),
        EntryPoint(
            "varlen_count", do, lambda m: D(m)._varlen_count_fn(m),
            lambda m: rows(i32, i32), factory="_varlen_count_fn"),
        EntryPoint(
            "varlen_count_replicated", do,
            lambda m: D(m)._varlen_count_fn(m, replicated=True),
            lambda m: (_sds((32,), i32), _sds(N, i32)),
            factory="_varlen_count_fn"),
        EntryPoint(
            "varlen_take", do, lambda m: D(m)._varlen_take_fn(m, 64),
            lambda m: vb() + (_sds(N, i32),), factory="_varlen_take_fn"),
        EntryPoint(
            "join_plan_inner", do,
            lambda m: _join_factory(D(m), m, "INNER"),
            lambda m: ((_sds(N, u32),), _sds(N, b), _sds(N, b),
                       (_sds(N, u32),), _sds(N, b), _sds(N, b)),
            factory="_join_plan_fn"),
        EntryPoint(
            "join_plan_full_outer", do,
            lambda m: _join_factory(D(m), m, "FULL_OUTER"),
            lambda m: ((_sds(N, u32),), _sds(N, b), _sds(N, b),
                       (_sds(N, u32),), _sds(N, b), _sds(N, b)),
            factory="_join_plan_fn"),
        EntryPoint(
            "join_materialize", do,
            lambda m: _join_mat_factory(D(m), m),
            lambda m: (_sds(N, i32), _sds(N, i32), _sds(N, i32),
                       _sds(N, b), _sds(N, b),
                       rows(i32, jnp.float32), rows(b, b),
                       rows(i32,), rows(b,)),
            factory="_join_mat_fn"),
        EntryPoint(
            # broadcast-hash join (adaptive execution): the build
            # side's key bits all_gather inside the program, probe
            # rows plan per shard against the replicated table
            "bcast_join_plan", do,
            lambda m: _bcast_join_factory(D(m), m),
            lambda m: ((_sds(N, u32),), _sds(N, b), _sds(N, b),
                       (_sds(N, u32),), _sds(N, b), _sds(N, b)),
            factory="_bcast_join_plan_fn"),
        EntryPoint(
            # ...and its materialize program: build payload lanes
            # re-gathered, match runs expanded at host-chosen capacity
            "bcast_join_mat", do,
            lambda m: _bcast_join_mat_factory(D(m), m),
            lambda m: (_sds(N, i32), _sds(N, i32), _sds(N, i32),
                       _sds(N, b), _sds(N, b),
                       rows(i32, jnp.float32), rows(b, b),
                       rows(i32,), rows(b,)),
            factory="_bcast_join_mat_fn"),
        EntryPoint(
            "setop_count", do, lambda m: D(m)._setop_count_fn(m),
            lambda m: ((_sds(N, u32),), _sds(N, b),
                       (_sds(N, u32),), _sds(N, b)),
            factory="_setop_count_fn"),
        EntryPoint(
            "setop_materialize", do,
            lambda m: _setop_mat_factory(D(m), m),
            lambda m: ((_sds(N, u32),), _sds(N, b),
                       (_sds(N, u32),), _sds(N, b),
                       rows(i32,), rows(b,), rows(i32,), rows(b,)),
            factory="_setop_mat_fn"),
        EntryPoint(
            "varlen_take_concat_count", do,
            lambda m: D(m)._varlen_take_concat_count_fn(m),
            lambda m: rows(i32, i32, i32),
            factory="_varlen_take_concat_count_fn"),
        EntryPoint(
            "varlen_take_concat", do,
            lambda m: D(m)._varlen_take_concat_fn(m, 64),
            lambda m: vb() + vb() + (_sds(N, i32),),
            factory="_varlen_take_concat_fn"),
        EntryPoint(
            "groupby", do, lambda m: _groupby_factory(D(m), m),
            lambda m: ((_sds(N, u32),), (_sds(N, i32),), (_sds(N, b),),
                       _sds(N, b), (_sds(N, jnp.float32),),
                       (_sds(N, b),)),
            factory="_groupby_fn"),
        EntryPoint(
            "ring_count", do,
            lambda m: D(m)._ring_count_fn(m, True, 1),
            lambda m: ((_sds(N, u32),), _sds(N, b), _sds(N, b),
                       (_sds(N, u32),), _sds(N, b), _sds(N, b)),
            factory="_ring_count_fn"),
        EntryPoint(
            "ring_materialize", do,
            lambda m: D(m)._ring_mat_fn(m, True, 8, 8, 1),
            lambda m: ((_sds(N, u32),), _sds(N, b), _sds(N, b),
                       (_sds(N, u32),), _sds(N, b), _sds(N, b),
                       rows(i32, jnp.float32), rows(b, b),
                       rows(i32,), rows(b,)),
            factory="_ring_mat_fn"),
        EntryPoint(
            "shard_sort", do,
            lambda m: D(m)._shard_sort_fn(m, 2, 2, 1),
            lambda m: ((_sds(N, u32),), _sds(N, b),
                       rows(i32, jnp.float32), rows(b, b)),
            factory="_shard_sort_fn"),
        EntryPoint(
            "join_plan_stream", do, lambda m: None, lambda m: (),
            factory="_join_plan_stream_fn", tpu_only=True),
        EntryPoint(
            "join_mat_stream", do, lambda m: None, lambda m: (),
            factory="_join_mat_stream_fn", tpu_only=True),
    ]
    return eps


def _join_factory(dist_ops, mesh, jt_name):
    from ..ops import join as _join
    return dist_ops._join_plan_fn(mesh, getattr(_join.JoinType, jt_name))


def _join_mat_factory(dist_ops, mesh):
    from ..ops import join as _join
    return dist_ops._join_mat_fn(mesh, _join.JoinType.INNER, 16, 0)


def _bcast_join_factory(dist_ops, mesh):
    from ..ops import join as _join
    return dist_ops._bcast_join_plan_fn(mesh, _join.JoinType.INNER)


def _bcast_join_mat_factory(dist_ops, mesh):
    from ..ops import join as _join
    return dist_ops._bcast_join_mat_fn(mesh, _join.JoinType.LEFT, 16)


def _setop_mat_factory(dist_ops, mesh):
    from ..ops import setops as _setops
    return dist_ops._setop_mat_fn(mesh, _setops.SetOp.UNION, 32)


def _groupby_factory(dist_ops, mesh):
    from ..ops import groupby as _groupby
    return dist_ops._groupby_fn(
        mesh, (_groupby.AggregationOp.SUM,), (0,), (False,))


def _load_entry_module(path: str) -> List[EntryPoint]:
    """Load ENTRY_POINTS from a fixture module file (tests)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("_cylint_entries", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return list(mod.ENTRY_POINTS)


@dataclass
class _Notes:
    items: List[str] = field(default_factory=list)


@register("collectives")
def check_collectives(ctx: AnalysisContext) -> List[Finding]:
    entry_module = ctx.options.get("collectives_entry_module")
    if entry_module is None and ctx.options.get("skip_collectives"):
        return []
    if ctx.options.get("collectives_coverage_only"):
        covered = {(e.path, e.factory)
                   for e in default_entry_points() if e.factory}
        return _coverage_findings(ctx, covered)
    import jax

    # f64-promotion detection needs x64 on: with it off, jax silently
    # downgrades the very promotions we are hunting. RESTORED after the
    # trace loop — a read-only checker must not leak global config into
    # its host process (a later eager kernel would trace under x64)
    x64_before = bool(jax.config.jax_enable_x64)
    if not x64_before:
        jax.config.update("jax_enable_x64", True)

    try:
        mesh = _virtual_mesh(int(ctx.options.get("world", 4)))
        entries = _load_entry_module(entry_module) if entry_module \
            else default_entry_points()

        findings: List[Finding] = []
        notes: List[str] = ctx.options.setdefault("notes", [])
        on_tpu = jax.default_backend() == "tpu"
        covered = set()
        for e in entries:
            if e.factory:
                covered.add((e.path, e.factory))
            if e.tpu_only and not on_tpu:
                notes.append(f"collectives: {e.name} is TPU-only "
                             f"(Pallas) — skipped on "
                             f"{jax.default_backend()}")
                continue
            line = _factory_line(ctx, e)
            try:
                fn = e.build(mesh)
                closed = jax.make_jaxpr(fn)(*e.inputs(mesh))
            except Exception as exc:  # noqa: BLE001 - reported as finding  # cylint: disable=errors/broad-swallow — trace failure becomes a Finding below
                findings.append(Finding(
                    rule="collectives/trace-error", path=e.path,
                    line=line,
                    message=f"{e.name}: abstract evaluation failed: "
                            f"{type(exc).__name__}: {exc}"))
                continue
            findings.extend(_check_jaxpr(closed.jaxpr, e, line))
        if entry_module is None:
            findings.extend(_coverage_findings(ctx, covered))
        return findings
    finally:
        if not x64_before:
            jax.config.update("jax_enable_x64", False)


def _factory_line(ctx: AnalysisContext, e: EntryPoint) -> int:
    """def-line of the factory, for clickable findings."""
    import ast

    for f in ctx.files():
        if f.rel != e.path:
            continue
        for node in f.tree.body:
            if isinstance(node, ast.FunctionDef) and \
                    node.name == e.factory:
                return node.lineno
    return 1


def _coverage_findings(ctx: AnalysisContext, covered) -> List[Finding]:
    """One ``collectives/uncataloged-factory`` finding per `_*_fn` in
    `parallel/` the entry-point catalog misses. Intentional exclusions
    (helpers returning plain host callables rather than jitted
    programs) carry a per-line ``# cylint: disable=`` — suppression
    counting keeps them visible in the run summary."""
    import ast

    findings: List[Finding] = []
    for f in ctx.files():
        if not f.rel.startswith("parallel/"):
            continue
        for node in f.tree.body:
            if isinstance(node, ast.FunctionDef) and \
                    node.name.startswith("_") and \
                    node.name.endswith("_fn") and \
                    (f.rel, node.name) not in covered:
                findings.append(Finding(
                    rule="collectives/uncataloged-factory", path=f.rel,
                    line=node.lineno,
                    message=f"{node.name} is not in the collectives "
                            f"entry-point catalog: its collective "
                            f"program is never abstractly checked — "
                            f"add an EntryPoint (or disable this rule "
                            f"on the def line if it returns a plain "
                            f"host callable)"))
    return findings
