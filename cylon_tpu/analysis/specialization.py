"""Kernel-specialization auditor: bounds recompile cardinality.

Every distributed op is *local kernel + shuffle + local kernel*
(PAPER.md), and every local kernel comes from a ``counted_cache``
factory whose arguments ARE the jit cache key: each distinct key tuple
bakes a brand-new XLA program, and ``cylon_kernel_compile_seconds``
(docs/telemetry.md) bills the build. Whether that is fine or a
recompile storm depends on each key parameter's *cardinality class*:

* **structural** — mesh, join type, set op, bool mode flags: bounded by
  the operator surface. Always fine.
* **schema-bound** — dtype widths, lane counts, column counts,
  ``max_words``: bounded by schema diversity. Fine, but noted — this is
  the axis along which compile time scales with schema variety.
* **bucketed capacity** — a runtime count routed through a recognized
  bucketing helper (``benchutils.bucket_cap``, ``util.pow2``,
  ``util.pow2_floor``, ``ops.join.stream_expand_capacity``): bounded to
  ~1 bucket per octave of data size. Fine.
* **data-dependent** — a runtime count (``device_get`` fetch,
  ``.max()``/``.sum()`` reduction) reaching a cache key raw, or through
  the 16-buckets-per-octave ``util.capacity`` mantissa rounding: one
  compile per distinct value (or per 4-bit mantissa step). Finding.
* **unbounded** — cardinality not provable from the derivation chain at
  all. Finding.

The pass traces each factory call-site argument backwards through
assignments, tuple unpacks, dict literals and package-local calls
(reusing core.ModuleIndex — the same shared index the hostsync and
concurrency closures use), so the finding carries the derivation chain.

Rules:

* ``specialization/unbucketed-capacity`` — a data-dependent cache-key
  argument not routed through a recognized bucketing helper;
* ``specialization/unbounded-key`` — a cache-key argument whose
  cardinality the trace cannot bound (chain in the message);
* ``specialization/closure-capture`` — a ``jit``/``shard_map`` traced
  body closing over a value bound in an enclosing NON-factory function:
  nothing pins it in any cache key, so changing it silently retraces
  (or worse, silently does not). Inside a ``counted_cache`` factory
  every enclosing binding derives from the cache key and is exempt.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import (AnalysisContext, Finding, ModuleIndex, attr_chain,
                   build_module_index, register)

# classification lattice (join = max)
STRUCTURAL, SCHEMA, BUCKETED, DATA, UNBOUNDED = range(5)
CLASS_NAMES = ("structural", "schema-bound", "bucketed-capacity",
               "data-dependent", "unbounded")

# recognized bucketing helpers, by package-relative (module, name) and —
# for single-file fixture trees where imports do not resolve — bare name
BUCKET_HELPERS_QUAL = {
    ("benchutils", "bucket_cap"),
    ("util", "pow2"), ("util", "pow2_floor"),
    ("ops.join", "stream_expand_capacity"),
}
BUCKET_HELPER_NAMES = {"bucket_cap", "_bucket_cap", "pow2", "_pow2",
                       "pow2_floor", "_pow2_floor",
                       "stream_expand_capacity"}

# fine-grained mantissa rounding: bounded, but 16 buckets per octave —
# deliberately NOT recognized as bucketing for cache keys (the names are
# reserved: see docs/analysis.md)
FINE_ROUNDER_NAMES = {"capacity", "_capacity", "_cap"}

# package functions known to return schema descriptors (their bodies
# use nested defs the generic return-trace cannot follow)
SCHEMA_FUNCS_QUAL = {("ops.join", "plan_lane_descs"),
                     ("data.strings", "pair_k_words")}
SCHEMA_FUNC_NAMES = {"plan_lane_descs", "pair_k_words", "_pair_k"}

# attribute reads that are static schema/shape introspection
SCHEMA_ATTRS = {"max_words", "dtype", "itemsize", "ndim", "shape",
                "size", "column_count", "axis_names"}

# device→host runtime-count sources
DATA_CALL_CHAINS = {("jax", "device_get"), ("np", "asarray"),
                    ("np", "array"), ("numpy", "asarray"),
                    ("numpy", "array")}
DATA_METHODS = {"max", "sum", "min", "item", "tolist"}

# program-building wrap sites for the closure-capture rule (lax control
# flow combinators are NOT wrap sites: their bodies run under an outer
# trace whose operands/static args are already accounted for)
WRAP_CHAINS = {("jax", "jit"), ("jit",), ("shard_map",),
               ("jax", "experimental", "shard_map", "shard_map")}

_MAX_DEPTH = 24


def _own_scope_nodes(fn: ast.AST):
    """Walk fn's body without descending into nested defs/lambdas — a
    nested helper's ``return`` is not fn's return."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _is_counted_cache(fn: ast.AST) -> bool:
    for dec in fn.decorator_list:
        chain = attr_chain(dec)
        if chain is not None and chain[-1] == "counted_cache":
            return True
    return False


def _params(fn: ast.AST) -> List[ast.arg]:
    a = fn.args
    return list(a.posonlyargs) + list(a.args)


def _ann_name(ann: Optional[ast.AST]) -> Optional[str]:
    if isinstance(ann, ast.Name):
        return ann.id
    return None


class _Result(tuple):
    """(rank, why) with lattice join."""

    __slots__ = ()

    def __new__(cls, rank, why):
        return super().__new__(cls, (rank, why))

    @property
    def rank(self):
        return self[0]

    @property
    def why(self):
        return self[1]


def _join(results) -> Optional[_Result]:
    """Lattice join; None entries (cycle-pruned branches) are ignored,
    an all-None join is None (caller decides)."""
    best = None
    for r in results:
        if r is None:
            continue
        if best is None or r.rank > best.rank:
            best = r
    return best


class _Tracer:
    """Backward value trace over the shared ModuleIndex."""

    def __init__(self, modules: Dict[str, ModuleIndex], package: str):
        self.modules = modules
        self.package = package
        # callee (mod, qualname) -> [(caller ModuleIndex, caller fn def
        # or None, self_cls, Call node)]
        self.call_sites: Dict[Tuple[str, str], list] = {}
        # per-module external/import name set
        self._ext: Dict[str, Set[str]] = {}
        self._bind_cache: Dict[int, Dict[str, list]] = {}
        for mod in modules.values():
            self._index_module(mod)

    # -- indexing ---------------------------------------------------------

    def _index_module(self, mod: ModuleIndex):
        ext: Set[str] = set()
        for node in ast.walk(mod.sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    ext.add(a.asname or a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    ext.add(a.asname or a.name)
        self._ext[mod.modname] = ext
        # module-level statements EXCLUDING def/class bodies (those are
        # attributed to their own unit below — double attribution would
        # re-classify every in-function call in module scope, where its
        # locals resolve to nothing)
        units = [(None, None, stmt) for stmt in mod.sf.tree.body
                 if not isinstance(stmt, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.ClassDef))]
        units += [(q, None, fn) for q, fn in mod.functions.items()]
        units += [(q, q.split(".", 1)[0], fn)
                  for q, fn in mod.methods.items()]
        for qual, self_cls, body in units:
            for node in ast.walk(body):
                if not isinstance(node, ast.Call):
                    continue
                target = self.resolve_call(node, mod, self_cls)
                if target is not None:
                    self.call_sites.setdefault(target, []).append(
                        (mod, mod.lookup(qual) if qual else None,
                         self_cls, node))

    def resolve_call(self, call: ast.Call, mod: ModuleIndex,
                     self_cls: Optional[str]):
        chain = attr_chain(call.func)
        if chain is None:
            return None
        if len(chain) == 1:
            name = chain[0]
            if name in mod.functions:
                return (mod.modname, name)
            if name in mod.fn_imports:
                return mod.fn_imports[name]
        elif len(chain) == 2:
            head, fname = chain
            if head == "self" and self_cls is not None and \
                    f"{self_cls}.{fname}" in mod.methods:
                return (mod.modname, f"{self_cls}.{fname}")
            if head in mod.mod_aliases:
                return (mod.mod_aliases[head], fname)
        return None

    # -- binding tables ---------------------------------------------------

    def _bindings(self, body: ast.AST) -> Dict[str, list]:
        """name -> [(value expr | None, selectors)] over a function (or
        module) subtree. None value = bound but untraceable (loop/with
        targets)."""
        cached = self._bind_cache.get(id(body))
        if cached is not None:
            return cached
        out: Dict[str, list] = {}

        def bind_target(tgt, value, sel):
            if isinstance(tgt, ast.Name):
                out.setdefault(tgt.id, []).append((value, sel))
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                for i, elt in enumerate(tgt.elts):
                    if isinstance(elt, ast.Starred):
                        bind_target(elt.value, None, [])
                    else:
                        bind_target(elt, value, sel + [i])

        for node in ast.walk(body):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    bind_target(tgt, node.value, [])
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                bind_target(node.target, node.value, [])
            elif isinstance(node, ast.AugAssign):
                # x op= v: trace the increment only — the prior binding
                # of x contributes through its own entry
                bind_target(node.target, node.value, [])
            elif isinstance(node, ast.For):
                bind_target(node.target, None, [])
            elif isinstance(node, ast.With):
                for item in node.items:
                    if item.optional_vars is not None:
                        bind_target(item.optional_vars, None, [])
        self._bind_cache[id(body)] = out
        return out

    # -- classification ---------------------------------------------------

    def classify_arg(self, expr: ast.AST, mod: ModuleIndex,
                     fn: Optional[ast.AST], self_cls: Optional[str]
                     ) -> _Result:
        st = {"depth": 0, "visited": set()}
        r = self._value(expr, [], mod, fn, self_cls, st)
        return r if r is not None else _Result(UNBOUNDED,
                                               "cyclic derivation")

    def _value(self, expr, sel, mod, fn, self_cls, st
               ) -> Optional[_Result]:
        if st["depth"] > _MAX_DEPTH:
            return _Result(UNBOUNDED, "derivation deeper than trace "
                                      "limit")
        st["depth"] += 1
        try:
            return self._value_inner(expr, sel, mod, fn, self_cls, st)
        finally:
            st["depth"] -= 1

    def _value_inner(self, expr, sel, mod, fn, self_cls, st):
        if isinstance(expr, ast.Constant):
            return _Result(STRUCTURAL, f"constant {expr.value!r}")
        if isinstance(expr, ast.IfExp):
            return _join([self._value(expr.body, sel, mod, fn, self_cls,
                                      st),
                          self._value(expr.orelse, sel, mod, fn,
                                      self_cls, st)])
        if isinstance(expr, (ast.Tuple, ast.List)):
            if sel:
                i = sel[0]
                if isinstance(i, int) and i < len(expr.elts):
                    return self._value(expr.elts[i], sel[1:], mod, fn,
                                       self_cls, st)
                return _Result(UNBOUNDED, "selector out of range")
            return _join([self._value(e, [], mod, fn, self_cls, st)
                          for e in expr.elts]) \
                or _Result(STRUCTURAL, "empty tuple")
        if isinstance(expr, ast.Dict):
            if sel and isinstance(sel[0], str):
                for k, v in zip(expr.keys, expr.values):
                    if isinstance(k, ast.Constant) and k.value == sel[0]:
                        return self._value(v, sel[1:], mod, fn,
                                           self_cls, st)
                return _Result(UNBOUNDED, f"no dict key {sel[0]!r}")
            return _Result(UNBOUNDED, "dict value")
        if isinstance(expr, ast.Name):
            return self._name(expr.id, sel, mod, fn, self_cls, st)
        if isinstance(expr, ast.Subscript):
            if isinstance(expr.value, ast.Attribute) and \
                    expr.value.attr == "shape":
                return _Result(SCHEMA, "shape introspection")
            key = expr.slice
            if isinstance(key, ast.Constant) and \
                    isinstance(key.value, (str, int)):
                return self._value(expr.value, [key.value] + sel, mod,
                                   fn, self_cls, st)
            return _Result(UNBOUNDED, "non-constant subscript")
        if isinstance(expr, ast.Attribute):
            return self._attribute(expr, mod)
        if isinstance(expr, ast.Call):
            return self._call(expr, sel, mod, fn, self_cls, st)
        if isinstance(expr, ast.BinOp):
            if isinstance(expr.op, ast.LShift) and \
                    isinstance(expr.left, ast.Constant):
                return _Result(BUCKETED, "pow2 by construction "
                                         "(constant << e)")
            return _join([self._value(expr.left, [], mod, fn, self_cls,
                                      st),
                          self._value(expr.right, [], mod, fn, self_cls,
                                      st)]) \
                or _Result(UNBOUNDED, "cyclic arithmetic")
        if isinstance(expr, ast.UnaryOp):
            return self._value(expr.operand, [], mod, fn, self_cls, st)
        if isinstance(expr, (ast.BoolOp, ast.Compare)):
            return _Result(STRUCTURAL, "boolean expression")
        if isinstance(expr, ast.Lambda):
            return _Result(STRUCTURAL, "lambda")
        return _Result(UNBOUNDED,
                       f"untraceable {type(expr).__name__} expression")

    def _attribute(self, expr: ast.Attribute, mod: ModuleIndex
                   ) -> _Result:
        chain = attr_chain(expr)
        attr = expr.attr
        if attr == "mesh":
            return _Result(STRUCTURAL, "mesh handle")
        if attr in SCHEMA_ATTRS:
            return _Result(SCHEMA, f".{attr} schema introspection")
        if chain is not None and len(chain) >= 2 and attr.isupper():
            # Enum member access: JoinType.INNER, _setops.SetOp.UNION
            return _Result(STRUCTURAL,
                           f"enum/constant member {'.'.join(chain)}")
        return _Result(UNBOUNDED,
                       f"opaque attribute "
                       f"{'.'.join(chain) if chain else attr}")

    def _name(self, name, sel, mod, fn, self_cls, st):
        # 1. enclosing-function parameter → interprocedural
        if fn is not None:
            for p in _params(fn):
                if p.arg == name:
                    qual = self._qual_of(mod, fn)
                    return self._param(mod, fn, qual, p, sel, self_cls,
                                       st)
            binds = self._bindings(fn).get(name)
            if binds:
                results = []
                for value, bsel in binds:
                    if value is None:
                        results.append(_Result(
                            UNBOUNDED, f"'{name}' bound by loop/with "
                                       f"target"))
                    else:
                        key = (mod.modname, id(value), tuple(bsel),
                               tuple(sel))
                        if key in st["visited"]:
                            continue
                        st["visited"].add(key)
                        r = self._value(value, list(bsel) + sel, mod,
                                        fn, self_cls, st)
                        st["visited"].discard(key)
                        if r is not None:
                            r = _Result(r.rank, f"{name} = {r.why}")
                        results.append(r)
                return _join(results)
        # 2. module scope
        if name in mod.functions or name in mod.classes or \
                name in mod.objects:
            return _Result(STRUCTURAL, f"module-level callable {name}")
        if name.isupper():
            return _Result(STRUCTURAL, f"module constant {name}")
        mod_binds = self._bindings(mod.sf.tree).get(name)
        if mod_binds and fn is not None:
            # module-level assignment visible from the function
            return self._name(name, sel, mod, None, None, st)
        if mod_binds:
            results = []
            for value, bsel in mod_binds:
                if value is None:
                    results.append(_Result(UNBOUNDED,
                                           f"'{name}' loop target"))
                else:
                    results.append(self._value(value, list(bsel) + sel,
                                               mod, None, None, st))
            return _join(results)
        if name in mod.fn_imports:
            tmod, tname = mod.fn_imports[name]
            if tname.isupper():
                return _Result(STRUCTURAL,
                               f"imported constant {tmod}.{tname}")
            target = self.modules.get(tmod)
            if target is not None:
                if tname in target.functions or tname in target.classes:
                    return _Result(STRUCTURAL,
                                   f"imported callable {tmod}.{tname}")
                tbinds = self._bindings(target.sf.tree).get(tname)
                if tbinds:
                    return _join(
                        [self._value(v, list(bs) + sel, target, None,
                                     None, st) for v, bs in tbinds
                         if v is not None])
            return _Result(UNBOUNDED, f"unresolved import {name}")
        if name in mod.mod_aliases or name in self._ext.get(mod.modname,
                                                            ()):
            return _Result(STRUCTURAL, f"imported module/symbol {name}")
        if name in ("True", "False", "None"):
            return _Result(STRUCTURAL, name)
        return _Result(UNBOUNDED, f"unresolved name '{name}'")

    def _qual_of(self, mod: ModuleIndex, fn: ast.AST) -> Optional[str]:
        for q, node in mod.functions.items():
            if node is fn:
                return q
        for q, node in mod.methods.items():
            if node is fn:
                return q
        return None

    def _param(self, mod, fn, qual, p: ast.arg, sel, self_cls, st):
        ann = _ann_name(p.annotation)
        if ann in ("bool", "str", "float"):
            return _Result(STRUCTURAL, f"{p.arg}: {ann} parameter")
        if p.annotation is not None and ann != "int":
            # enum/config/tuple-typed parameter: bounded by the
            # operator/schema surface, not by data
            return _Result(SCHEMA, f"{p.arg}: annotated parameter")
        if qual is None:
            return _Result(UNBOUNDED,
                           f"parameter '{p.arg}' of unindexed function")
        key = (mod.modname, qual, p.arg, tuple(sel))
        if key in st["visited"]:
            return None  # cycle: this branch contributes nothing
        st["visited"].add(key)
        try:
            results = []
            default = self._param_default(fn, p)
            if default is not None:
                results.append(self._value(default, sel, mod, None,
                                           None, st))
            sites = self.call_sites.get((mod.modname, qual), [])
            pos = [q.arg for q in _params(fn)].index(p.arg)
            for cmod, cfn, ccls, call in sites:
                arg = self._site_arg(call, pos, p.arg)
                if arg is None:
                    continue
                r = self._value(arg, sel, cmod, cfn, ccls, st)
                if r is not None:
                    r = _Result(r.rank,
                                f"{p.arg}@{cmod.sf.rel}:{call.lineno} "
                                f"= {r.why}")
                results.append(r)
            joined = _join(results)
            if joined is None:
                return _Result(UNBOUNDED,
                               f"parameter '{p.arg}' of {qual} has no "
                               f"traceable package call site")
            return joined
        finally:
            st["visited"].discard(key)

    @staticmethod
    def _param_default(fn: ast.AST, p: ast.arg):
        params = _params(fn)
        defaults = fn.args.defaults
        if not defaults:
            return None
        offset = len(params) - len(defaults)
        idx = [q.arg for q in params].index(p.arg)
        if idx >= offset:
            return defaults[idx - offset]
        return None

    @staticmethod
    def _site_arg(call: ast.Call, pos: int, name: str):
        for kw in call.keywords:
            if kw.arg == name:
                return kw.value
        if pos < len(call.args) and not any(
                isinstance(a, ast.Starred) for a in call.args[:pos + 1]):
            return call.args[pos]
        return None

    def _call(self, expr: ast.Call, sel, mod, fn, self_cls, st):
        chain = attr_chain(expr.func)
        if chain is None:
            # no Name-rooted chain — but a runtime-reduction method on
            # ANY expression (np.asarray(...).max()) is still data
            if isinstance(expr.func, ast.Attribute) and \
                    expr.func.attr in DATA_METHODS and not expr.args:
                return _Result(DATA,
                               f".{expr.func.attr}() runtime reduction")
            return _Result(UNBOUNDED, "computed callee")
        target = self.resolve_call(expr, mod, self_cls)
        name = chain[-1]
        if (target in BUCKET_HELPERS_QUAL) or \
                (target is None and name in BUCKET_HELPER_NAMES) or \
                (target is not None and target[1] in BUCKET_HELPER_NAMES
                 and self.modules.get(target[0]) is None):
            return _Result(BUCKETED, f"{name}(...) bucketing helper")
        if target is not None and target in SCHEMA_FUNCS_QUAL or \
                name in SCHEMA_FUNC_NAMES:
            return _Result(SCHEMA, f"{name}(...) schema descriptor")
        if name in FINE_ROUNDER_NAMES or \
                (target is not None
                 and target[1] in FINE_ROUNDER_NAMES):
            return _Result(
                DATA, f"{name}(...) — util.capacity's 16-buckets-per-"
                      f"octave mantissa rounding is NOT a recognized "
                      f"bucketing helper for cache keys")
        if chain in DATA_CALL_CHAINS:
            return _Result(DATA, f"{'.'.join(chain)}() runtime fetch")
        if len(chain) >= 2 and name in DATA_METHODS and not expr.args:
            return _Result(DATA, f".{name}() runtime reduction")
        if chain == ("len",):
            return _Result(SCHEMA, "len() of a static container")
        if name in ("int", "abs", "round"):
            if expr.args:
                r = self._value(expr.args[0], [], mod, fn, self_cls, st)
                return r
            return _Result(STRUCTURAL, f"{name}()")
        if name in ("min", "max"):
            return _join([self._value(a, [], mod, fn, self_cls, st)
                          for a in expr.args]) \
                or _Result(UNBOUNDED, "cyclic min/max")
        if target is not None:
            tmod = self.modules.get(target[0])
            tdef = tmod.lookup(target[1]) if tmod is not None else None
            if tdef is not None:
                key = (target[0], target[1], "return", tuple(sel))
                if key in st["visited"]:
                    return None
                st["visited"].add(key)
                try:
                    tcls = target[1].split(".", 1)[0] \
                        if "." in target[1] else None
                    rets = [n for n in _own_scope_nodes(tdef)
                            if isinstance(n, ast.Return)
                            and n.value is not None]
                    if not rets:
                        return _Result(UNBOUNDED,
                                       f"{name}() returns nothing "
                                       f"traceable")
                    joined = _join([self._value(r.value, sel, tmod,
                                                tdef, tcls, st)
                                    for r in rets])
                    if joined is None:
                        return None
                    return _Result(joined.rank,
                                   f"{name}(...) -> {joined.why}")
                finally:
                    st["visited"].discard(key)
        return _Result(UNBOUNDED, f"unresolvable call {name}(...)")


# ---------------------------------------------------------------------------
# closure-capture scan
# ---------------------------------------------------------------------------


def _own_stores(fn: ast.AST) -> Set[str]:
    """Names bound in fn's OWN scope (params, assignments, imports, for/
    with targets, nested def names) — not descending into nested defs'
    bodies, so an inner scope's local never masks an outer capture."""
    out = {p.arg for p in _params(fn)}
    a = fn.args
    if a.vararg:
        out.add(a.vararg.arg)
    if a.kwarg:
        out.add(a.kwarg.arg)
    out |= {p.arg for p in a.kwonlyargs}

    def walk(node, top):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    out.add(child.name)
                continue
            if isinstance(child, ast.Name) and \
                    isinstance(child.ctx, (ast.Store, ast.Del)):
                out.add(child.id)
            elif isinstance(child, (ast.Import, ast.ImportFrom)):
                for al in child.names:
                    out.add(al.asname or al.name.split(".")[0])
            elif isinstance(child, ast.comprehension):
                for n in ast.walk(child.target):
                    if isinstance(n, ast.Name):
                        out.add(n.id)
            walk(child, False)

    walk(fn, True)
    return out


def _all_bound(fn: ast.AST) -> Set[str]:
    """Every name bound anywhere inside fn (incl. nested scopes)."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            out |= {p.arg for p in _params(node)}
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.add(node.name)
        elif isinstance(node, ast.Name) and \
                isinstance(node.ctx, (ast.Store, ast.Del)):
            out.add(node.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for al in node.names:
                out.add(al.asname or al.name.split(".")[0])
        elif isinstance(node, ast.comprehension):
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name):
                    out.add(n.id)
    return out


def _module_names(mod: ModuleIndex, ext: Set[str]) -> Set[str]:
    names = set(mod.functions) | set(mod.classes) | set(mod.objects)
    names |= set(mod.mod_aliases) | set(mod.fn_imports) | ext
    for node in mod.sf.tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                for n in ast.walk(tgt):
                    if isinstance(n, ast.Name):
                        names.add(n.id)
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name):
            names.add(node.target.id)
    return names


def _scan_closures(mod: ModuleIndex, ext: Set[str]) -> List[Finding]:
    import builtins

    findings: List[Finding] = []
    module_names = _module_names(mod, ext)
    builtin_names = set(dir(builtins))

    # collect (def/lambda node, enclosing def stack) and wrap calls
    def_stacks: Dict[int, tuple] = {}
    defs_by_name: List[tuple] = []  # (name, node, stack)
    wraps: List[tuple] = []         # (call node, stack)

    def visit(node, stack):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                def_stacks[id(child)] = stack
                defs_by_name.append((child.name, child, stack))
                visit(child, stack + (child,))
                continue
            if isinstance(child, ast.Lambda):
                def_stacks[id(child)] = stack
                visit(child, stack + (child,))
                continue
            if isinstance(child, ast.Call):
                chain = attr_chain(child.func)
                if chain in WRAP_CHAINS and child.args:
                    wraps.append((child, stack))
            visit(child, stack)

    visit(mod.sf.tree, ())

    for call, stack in wraps:
        target = call.args[0]
        if isinstance(target, ast.Lambda):
            tnode, tstack = target, stack
        elif isinstance(target, ast.Name):
            cands = [(n, d, s) for n, d, s in defs_by_name
                     if n == target.id
                     and s == stack[:len(s)]]
            if not cands:
                continue
            _n, tnode, tstack = max(cands, key=lambda c: len(c[2]))
        else:
            continue
        if not tstack:
            continue  # module-level traced def: no enclosing captures
        bound = _all_bound(tnode)
        own_by_frame = [(e, _own_stores(e)) for e in tstack
                        if isinstance(e, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))]
        reported: Set[str] = set()
        for node in ast.walk(tnode):
            if not (isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)):
                continue
            name = node.id
            if name in bound or name in reported:
                continue
            for enc, own in reversed(own_by_frame):  # innermost first
                if name not in own:
                    continue
                if _is_counted_cache(enc):
                    break  # cache-keyed closure: every binding derives
                    # from the factory's key tuple
                reported.add(name)
                label = getattr(tnode, "name", "<lambda>")
                findings.append(Finding(
                    rule="specialization/closure-capture",
                    path=mod.sf.rel, line=node.lineno,
                    message=f"traced body '{label}' closes over "
                            f"'{name}' bound in enclosing non-factory "
                            f"'{enc.name}' — no cache key pins it, so "
                            f"a changed value silently retraces (or "
                            f"stales); pass it as an operand or build "
                            f"through a counted_cache factory"))
                break
    return findings


# ---------------------------------------------------------------------------
# checker
# ---------------------------------------------------------------------------


@register("specialization")
def check_specialization(ctx: AnalysisContext) -> List[Finding]:
    modules = build_module_index(ctx)
    tracer = _Tracer(modules, ctx.package_name)
    findings: List[Finding] = []

    # counted_cache factories and their defs
    factories: Dict[Tuple[str, str], ast.AST] = {}
    for modname, mod in modules.items():
        for name, fndef in mod.functions.items():
            if _is_counted_cache(fndef):
                factories[(modname, name)] = fndef

    census = {c: 0 for c in CLASS_NAMES}
    audited_sites = 0
    for key, fndef in sorted(factories.items()):
        fmod, fname = key
        params = _params(fndef)
        sites = tracer.call_sites.get(key, [])
        for cmod, cfn, ccls, call in sites:
            audited_sites += 1
            for i, p in enumerate(params):
                arg = tracer._site_arg(call, i, p.arg)
                if arg is None:
                    continue
                if p.arg == "mesh":
                    census["structural"] += 1
                    continue
                ann = _ann_name(p.annotation)
                if ann in ("bool", "str", "float"):
                    census["structural"] += 1
                    continue
                if p.annotation is not None and ann != "int":
                    census["schema-bound"] += 1
                    continue
                res = tracer.classify_arg(arg, cmod, cfn, ccls)
                census[CLASS_NAMES[res.rank]] += 1
                if res.rank == DATA:
                    findings.append(Finding(
                        rule="specialization/unbucketed-capacity",
                        path=cmod.sf.rel, line=call.lineno,
                        message=f"cache-key parameter '{p.arg}' of "
                                f"{fname} is data-dependent and not "
                                f"routed through a recognized bucketing "
                                f"helper (benchutils.bucket_cap / "
                                f"util.pow2) — one compiled program per "
                                f"distinct value; derivation: "
                                f"{res.why}"))
                elif res.rank == UNBOUNDED:
                    findings.append(Finding(
                        rule="specialization/unbounded-key",
                        path=cmod.sf.rel, line=call.lineno,
                        message=f"cache-key parameter '{p.arg}' of "
                                f"{fname}: cardinality not provably "
                                f"bounded; derivation: {res.why}"))
        if not sites:
            ctx.options.setdefault("notes", []).append(
                f"specialization: factory {fmod or ctx.package_name}."
                f"{fname} has no package call site (dynamic use only)")

    # closure-capture sweep over every module
    for modname, mod in modules.items():
        findings.extend(_scan_closures(mod, tracer._ext.get(modname,
                                                            set())))

    ctx.options.setdefault("notes", []).append(
        "specialization: {} counted_cache factories, {} call sites; "
        "key args: {}".format(
            len(factories), audited_sites,
            ", ".join(f"{census[c]} {c}" for c in CLASS_NAMES)))
    return findings
