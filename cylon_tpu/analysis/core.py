"""Analysis framework core: findings, suppressions, checker registry.

A *checker* is a function ``(AnalysisContext) -> list[Finding]``
registered under a family name ("layering", "hostsync", ...). The CLI
(`python -m cylon_tpu.analysis`) runs every registered checker and
exits non-zero when any unsuppressed finding survives; tests drive the
same API directly against fixture trees with seeded violations.

Suppression syntax (mirrors the familiar linter discipline):

* ``# cylint: disable=<rule>[,<rule>...]`` on the offending line
  suppresses those rules for that line only;
* ``# cylint: disable-file=<rule>[,<rule>...]`` anywhere in a file
  (conventionally the top) suppresses for the whole file.

A ``<rule>`` is either a full rule id (``layering/plan-no-ops``), a
family name (``layering`` — every rule in the family), or ``all``.
Suppressions are deliberately per-rule: a bare ``# cylint: disable``
with no rule is ignored (and reported), so silencing is always an
explicit, reviewable decision.
"""
from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

# JSON output schema version — tests pin this; bump only with a
# deliberate, documented schema change (docs/analysis.md).
SCHEMA_VERSION = 1

_SUPPRESS_RE = re.compile(
    r"#\s*cylint:\s*(disable|disable-file)=([A-Za-z0-9_\-/,*]+)")


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location.

    ``rule`` is ``<family>/<name>``; ``path`` is repo/package-relative
    for display (checkers that analyze traced programs rather than
    files point at the factory's def line)."""

    rule: str
    path: str
    line: int
    message: str
    col: int = 0

    @property
    def family(self) -> str:
        return self.rule.split("/", 1)[0]

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_json(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message}


def _rule_matches(entry: str, rule: str) -> bool:
    if entry == "all" or entry == "*":
        return True
    if entry == rule:
        return True
    # family name, or explicit family wildcard ("layering/*")
    fam = entry[:-2] if entry.endswith("/*") else entry
    return "/" not in fam and rule.split("/", 1)[0] == fam


class Suppressions:
    """Per-file suppression index parsed straight from source text."""

    def __init__(self, source: str):
        self.line_rules: Dict[int, List[str]] = {}
        self.file_rules: List[str] = []
        for i, text in enumerate(source.splitlines(), start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            kind, rules = m.group(1), m.group(2).split(",")
            rules = [r.strip() for r in rules if r.strip()]
            if kind == "disable-file":
                self.file_rules.extend(rules)
            else:
                self.line_rules.setdefault(i, []).extend(rules)

    def is_suppressed(self, finding: Finding) -> bool:
        for entry in self.file_rules:
            if _rule_matches(entry, finding.rule):
                return True
        for entry in self.line_rules.get(finding.line, ()):
            if _rule_matches(entry, finding.rule):
                return True
        return False


@dataclass
class SourceFile:
    path: str         # absolute
    rel: str          # package-root-relative, '/'-separated
    source: str
    tree: ast.AST
    suppressions: Suppressions


class AnalysisContext:
    """Shared state for one analysis run.

    ``package_root`` is the directory whose layout defines subsystems
    (``ops/``, ``plan/``, ...) — the installed ``cylon_tpu`` package by
    default, a fixture tree with the same shape under test. ``options``
    carries checker-specific knobs (fixture entry-point modules, world
    size, ...).
    """

    def __init__(self, package_root: str, options: Optional[dict] = None):
        self.package_root = os.path.abspath(package_root)
        self.package_name = os.path.basename(self.package_root)
        self.options = dict(options or {})
        self._files: Optional[List[SourceFile]] = None
        self._module_index: Optional[Dict[str, "ModuleIndex"]] = None
        # how many times the index was BUILT (not fetched) — tests pin
        # this at 1 across a multi-family run: hostsync, concurrency,
        # envknobs and specialization all share one call-graph index
        self.index_builds = 0

    def module_index(self) -> Dict[str, "ModuleIndex"]:
        """The per-module symbol/call-graph index, built once per
        context and shared by every family that closes over the call
        graph (hostsync, concurrency, envknobs, specialization). The
        walk+index is the dominant cost the check.sh wall-clock budget
        guards, so a CLI invocation must never rebuild it per family."""
        if self._module_index is None:
            self.index_builds += 1
            self._module_index = {
                self.module_name(sf): ModuleIndex(sf,
                                                  self.module_name(sf),
                                                  self.package_name)
                for sf in self.files()}
        return self._module_index

    def files(self) -> List[SourceFile]:
        if self._files is None:
            out = []
            for root, dirs, names in os.walk(self.package_root):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", "_native"))
                for name in sorted(names):
                    if not name.endswith(".py"):
                        continue
                    path = os.path.join(root, name)
                    rel = os.path.relpath(path, self.package_root)
                    rel = rel.replace(os.sep, "/")
                    src = open(path, encoding="utf-8").read()
                    try:
                        tree = ast.parse(src, filename=path)
                    except SyntaxError as e:  # pragma: no cover
                        raise RuntimeError(f"cannot parse {path}: {e}")
                    out.append(SourceFile(path, rel, src, tree,
                                          Suppressions(src)))
            self._files = out
        return self._files

    def module_name(self, f: SourceFile) -> str:
        """Package-relative dotted module path ('' for __init__)."""
        mod = f.rel[:-3].replace("/", ".")
        if mod.endswith("__init__"):
            mod = mod[: -len("__init__")].rstrip(".")
        return mod


# ---------------------------------------------------------------------------
# shared import resolution (used by the layering and hostsync passes —
# ONE copy, so the two checkers can never disagree about what module an
# import statement targets)
# ---------------------------------------------------------------------------


def importer_package(rel: str, modname: str) -> str:
    """Package-relative dotted path of a file's PACKAGE — the anchor a
    level-1 relative import resolves against. For ``pkg/sub/x.py`` that
    is ``sub``; for ``pkg/sub/__init__.py`` it is also ``sub`` (a
    package's relative imports anchor at itself)."""
    if rel.endswith("__init__.py"):
        return modname
    return ".".join(modname.split(".")[:-1]) if modname else ""


def resolve_import(module: Optional[str], level: int, importer_pkg: str,
                   package: str) -> Optional[str]:
    """Resolve an import statement to a *package-relative* dotted path
    ('' = the package root), or None when it leaves the package.
    ``importer_pkg`` is the importing file's package (see
    importer_package); ``level`` is the ImportFrom relative level (0
    for absolute)."""
    if level == 0:
        name = module or ""
        if name == package:
            return ""
        if name.startswith(package + "."):
            return name[len(package) + 1:]
        return None
    # relative: level 1 anchors at the importer's own package, each
    # further level climbs one package
    parts = importer_pkg.split(".") if importer_pkg else []
    anchor = parts[: max(len(parts) - (level - 1), 0)]
    return ".".join(anchor + ([module] if module else []))


# ---------------------------------------------------------------------------
# shared call-graph machinery (hoisted from the hostsync pass in PR 8 so
# the concurrency checker reuses the SAME transitive-closure semantics —
# two checkers must never disagree about what a call statement targets)
# ---------------------------------------------------------------------------


def attr_chain(node: ast.AST):
    """('jax','lax','psum') for ``jax.lax.psum``; ('f',) for bare
    names; None when the chain does not bottom out in a Name."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


class ModuleIndex:
    """Per-file symbol tables for closure passes.

    ``functions`` maps module-level def names to their AST;
    ``methods`` maps ``Class.method`` qualnames (one level — the
    repo's universal shape); ``objects`` maps module-level
    ``NAME = Cls(...)`` singletons to their class so
    ``alias.OBJ.method()`` call chains resolve (the metrics REGISTRY
    pattern); ``mod_aliases``/``fn_imports`` resolve intra-package
    ``alias.fn(...)`` and ``from ..m import f`` calls."""

    def __init__(self, sf: SourceFile, modname: str, package: str):
        self.sf = sf
        self.modname = modname
        self.functions: Dict[str, ast.AST] = {}
        self.classes: Dict[str, ast.ClassDef] = {}
        self.methods: Dict[str, ast.AST] = {}     # "Cls.m" -> def node
        self.objects: Dict[str, tuple] = {}       # name -> (mod, Cls)
        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        self.methods[f"{node.name}.{sub.name}"] = sub
        # local alias -> package-relative module path, for call
        # resolution of `_join.join_plan_keys(...)`
        self.mod_aliases: Dict[str, str] = {}
        # local name -> (module path, name) from
        # `from ..ops.join import gather_columns as _gather`
        self.fn_imports: Dict[str, tuple] = {}
        pkg = importer_package(sf.rel, modname)
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    target = resolve_import(a.name, 0, pkg, package)
                    if target:  # intra-package, below the root
                        self.mod_aliases[a.asname
                                         or a.name.split(".")[-1]] = target
            elif isinstance(node, ast.ImportFrom):
                base = resolve_import(node.module or "", node.level, pkg,
                                      package)
                if base is None:
                    continue
                for a in node.names:
                    sub = (base + "." + a.name) if base else a.name
                    local = a.asname or a.name
                    # imported name could be a submodule or a function;
                    # record both interpretations, resolved lazily
                    self.mod_aliases.setdefault(local, sub)
                    self.fn_imports[local] = (base, a.name)
        # module-level singletons: NAME = Cls(...) where Cls is a local
        # class or an imported one
        for node in sf.tree.body:
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)):
                continue
            chain = attr_chain(node.value.func)
            if chain is None:
                continue
            name = node.targets[0].id
            if len(chain) == 1 and chain[0] in self.classes:
                self.objects[name] = (modname, chain[0])
            elif len(chain) == 1 and chain[0] in self.fn_imports:
                self.objects[name] = self.fn_imports[chain[0]]

    def lookup(self, qualname: str):
        """The def node for a module-level function OR a Class.method
        qualname, or None."""
        return self.functions.get(qualname) or self.methods.get(qualname)


def build_module_index(ctx: AnalysisContext) -> Dict[str, ModuleIndex]:
    return ctx.module_index()


def called_functions(body: ast.AST, mod: ModuleIndex,
                     modules: Optional[Dict[str, ModuleIndex]] = None,
                     self_cls: Optional[str] = None):
    """(module path, qualname) pairs ``body`` calls, resolved as far as
    syntax allows: same-module ``fn(...)``, imported ``fn(...)``,
    intra-package ``alias.fn(...)``, ``self.m(...)`` (when ``self_cls``
    names the enclosing class), ``Cls(...)`` construction (-> its
    ``__init__``), module-level singleton ``obj.m(...)``, and — given
    ``modules`` — the three-deep ``alias.OBJ.m(...)`` form."""
    out = set()
    for node in ast.walk(body):
        if not isinstance(node, ast.Call):
            continue
        chain = attr_chain(node.func)
        if chain is None:
            continue
        if len(chain) == 1:
            name = chain[0]
            if name in mod.functions:
                out.add((mod.modname, name))
            elif name in mod.classes:
                if f"{name}.__init__" in mod.methods:
                    out.add((mod.modname, f"{name}.__init__"))
            elif name in mod.fn_imports:
                base, fn = mod.fn_imports[name]
                target = modules.get(base) if modules else None
                if target is not None and fn in target.classes:
                    if f"{fn}.__init__" in target.methods:
                        out.add((base, f"{fn}.__init__"))
                else:
                    out.add(mod.fn_imports[name])
        elif len(chain) == 2:
            head, meth = chain
            if head == "self" and self_cls is not None:
                if f"{self_cls}.{meth}" in mod.methods:
                    out.add((mod.modname, f"{self_cls}.{meth}"))
            elif head in mod.objects:
                omod, ocls = mod.objects[head]
                out.add((omod, f"{ocls}.{meth}"))
            elif head in mod.mod_aliases:
                out.add((mod.mod_aliases[head], meth))
        elif len(chain) == 3 and modules is not None:
            alias, obj, meth = chain
            target = modules.get(mod.mod_aliases.get(alias, ""))
            if target is not None and obj in target.objects:
                omod, ocls = target.objects[obj]
                out.add((omod, f"{ocls}.{meth}"))
    return out


def call_closure(modules: Dict[str, ModuleIndex], seeds: Dict,
                 package: str) -> Dict:
    """Transitive closure over the call graph from ``seeds`` — a
    ``{(mod, qualname): chain description}`` map. Returns the closed
    map; each discovered callee's description extends its caller's
    (``root -> mod.callee``), so findings can print the whole chain."""
    closed = dict(seeds)
    work = list(seeds)
    while work:
        modname, fname = work.pop()
        mod = modules.get(modname)
        fn = mod.lookup(fname) if mod is not None else None
        if fn is None:
            continue
        desc = closed[(modname, fname)]
        self_cls = fname.split(".", 1)[0] if "." in fname else None
        for callee in called_functions(fn, mod, modules, self_cls):
            cmod, cfn = callee
            target = modules.get(cmod)
            if target is None or target.lookup(cfn) is None:
                continue
            if callee not in closed:
                closed[callee] = f"{desc} -> {cmod or package}.{cfn}"
                work.append(callee)
    return closed


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

CheckerFn = Callable[[AnalysisContext], List[Finding]]
CHECKERS: Dict[str, CheckerFn] = {}


def register(family: str):
    def deco(fn: CheckerFn) -> CheckerFn:
        CHECKERS[family] = fn
        return fn
    return deco


@dataclass
class RunResult:
    findings: List[Finding] = field(default_factory=list)
    suppressed: int = 0
    checkers: List[str] = field(default_factory=list)
    notes: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_json(self) -> dict:
        counts: Dict[str, int] = {}
        for f in self.findings:
            counts[f.family] = counts.get(f.family, 0) + 1
        return {
            "version": SCHEMA_VERSION,
            "ok": self.ok,
            "checkers": list(self.checkers),
            "counts": counts,
            "suppressed": self.suppressed,
            "notes": list(self.notes),
            "findings": [f.to_json() for f in self.findings],
        }

    def format_text(self) -> str:
        lines = []
        for f in self.findings:
            lines.append(f.format())
        lines.append(f"cylint: {len(self.findings)} finding(s), "
                     f"{self.suppressed} suppressed "
                     f"[{', '.join(self.checkers)}]")
        for n in self.notes:
            lines.append(f"note: {n}")
        return "\n".join(lines)


def run_checkers(ctx: AnalysisContext,
                 families: Optional[Sequence[str]] = None) -> RunResult:
    """Run the selected checker families (default: all registered) and
    apply suppressions. Findings sort by (path, line, rule) so output
    (and the JSON schema) is deterministic. Unknown family names raise:
    a typo in a CI config must not become an exit-0 gate that ran
    nothing."""
    if families is not None:
        unknown = sorted(set(families) - set(CHECKERS))
        if unknown:
            raise ValueError(
                f"unknown checker families {unknown}; registered: "
                f"{sorted(CHECKERS)}")
    res = RunResult()
    by_path = {f.rel: f for f in ctx.files()}
    for name in sorted(CHECKERS):
        if families is not None and name not in families:
            continue
        res.checkers.append(name)
        for finding in CHECKERS[name](ctx):
            sf = by_path.get(finding.path)
            if sf is not None and sf.suppressions.is_suppressed(finding):
                res.suppressed += 1
                continue
            res.findings.append(finding)
    res.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    # checkers accumulate informational notes (coverage gaps, skipped
    # TPU-only entries, corpus sizes) in ctx.options["notes"]
    res.notes.extend(ctx.options.pop("notes", []))
    return res


def to_json_text(res: RunResult) -> str:
    return json.dumps(res.to_json(), indent=2, sort_keys=True)


# SARIF v2.1.0 (OASIS) — the interchange format CI annotators consume;
# docs/analysis.md pins the envelope shape alongside JSON schema v1.
SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")


def to_sarif(res: RunResult) -> dict:
    """Render a run as a SARIF v2.1.0 log: one run, one driver
    ("cylint"), one rule entry per distinct rule id seen, one result
    per finding. Paths stay package-root-relative (the same strings
    the text/JSON outputs use), so CI resolves them against the
    package root it invoked the suite on."""
    rule_ids = sorted({f.rule for f in res.findings})
    results = [{
        "ruleId": f.rule,
        "ruleIndex": rule_ids.index(f.rule),
        "level": "error",
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path},
                "region": {"startLine": f.line,
                           "startColumn": max(f.col, 1)},
            },
        }],
    } for f in res.findings]
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "cylint",
                "informationUri":
                    "https://github.com/cylon-tpu/cylon-tpu"
                    "/blob/main/docs/analysis.md",
                "rules": [{"id": rid,
                           "shortDescription": {"text": rid}}
                          for rid in rule_ids],
            }},
            "invocations": [{"executionSuccessful": res.ok}],
            "properties": {
                "checkers": list(res.checkers),
                "suppressed": res.suppressed,
                "notes": list(res.notes),
            },
            "results": results,
        }],
    }


def to_sarif_text(res: RunResult) -> str:
    return json.dumps(to_sarif(res), indent=2, sort_keys=True)
