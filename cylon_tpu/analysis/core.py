"""Analysis framework core: findings, suppressions, checker registry.

A *checker* is a function ``(AnalysisContext) -> list[Finding]``
registered under a family name ("layering", "hostsync", ...). The CLI
(`python -m cylon_tpu.analysis`) runs every registered checker and
exits non-zero when any unsuppressed finding survives; tests drive the
same API directly against fixture trees with seeded violations.

Suppression syntax (mirrors the familiar linter discipline):

* ``# cylint: disable=<rule>[,<rule>...]`` on the offending line
  suppresses those rules for that line only;
* ``# cylint: disable-file=<rule>[,<rule>...]`` anywhere in a file
  (conventionally the top) suppresses for the whole file.

A ``<rule>`` is either a full rule id (``layering/plan-no-ops``), a
family name (``layering`` — every rule in the family), or ``all``.
Suppressions are deliberately per-rule: a bare ``# cylint: disable``
with no rule is ignored (and reported), so silencing is always an
explicit, reviewable decision.
"""
from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

# JSON output schema version — tests pin this; bump only with a
# deliberate, documented schema change (docs/analysis.md).
SCHEMA_VERSION = 1

_SUPPRESS_RE = re.compile(
    r"#\s*cylint:\s*(disable|disable-file)=([A-Za-z0-9_\-/,*]+)")


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location.

    ``rule`` is ``<family>/<name>``; ``path`` is repo/package-relative
    for display (checkers that analyze traced programs rather than
    files point at the factory's def line)."""

    rule: str
    path: str
    line: int
    message: str
    col: int = 0

    @property
    def family(self) -> str:
        return self.rule.split("/", 1)[0]

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_json(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message}


def _rule_matches(entry: str, rule: str) -> bool:
    if entry == "all" or entry == "*":
        return True
    if entry == rule:
        return True
    # family name, or explicit family wildcard ("layering/*")
    fam = entry[:-2] if entry.endswith("/*") else entry
    return "/" not in fam and rule.split("/", 1)[0] == fam


class Suppressions:
    """Per-file suppression index parsed straight from source text."""

    def __init__(self, source: str):
        self.line_rules: Dict[int, List[str]] = {}
        self.file_rules: List[str] = []
        for i, text in enumerate(source.splitlines(), start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            kind, rules = m.group(1), m.group(2).split(",")
            rules = [r.strip() for r in rules if r.strip()]
            if kind == "disable-file":
                self.file_rules.extend(rules)
            else:
                self.line_rules.setdefault(i, []).extend(rules)

    def is_suppressed(self, finding: Finding) -> bool:
        for entry in self.file_rules:
            if _rule_matches(entry, finding.rule):
                return True
        for entry in self.line_rules.get(finding.line, ()):
            if _rule_matches(entry, finding.rule):
                return True
        return False


@dataclass
class SourceFile:
    path: str         # absolute
    rel: str          # package-root-relative, '/'-separated
    source: str
    tree: ast.AST
    suppressions: Suppressions


class AnalysisContext:
    """Shared state for one analysis run.

    ``package_root`` is the directory whose layout defines subsystems
    (``ops/``, ``plan/``, ...) — the installed ``cylon_tpu`` package by
    default, a fixture tree with the same shape under test. ``options``
    carries checker-specific knobs (fixture entry-point modules, world
    size, ...).
    """

    def __init__(self, package_root: str, options: Optional[dict] = None):
        self.package_root = os.path.abspath(package_root)
        self.package_name = os.path.basename(self.package_root)
        self.options = dict(options or {})
        self._files: Optional[List[SourceFile]] = None

    def files(self) -> List[SourceFile]:
        if self._files is None:
            out = []
            for root, dirs, names in os.walk(self.package_root):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", "_native"))
                for name in sorted(names):
                    if not name.endswith(".py"):
                        continue
                    path = os.path.join(root, name)
                    rel = os.path.relpath(path, self.package_root)
                    rel = rel.replace(os.sep, "/")
                    src = open(path, encoding="utf-8").read()
                    try:
                        tree = ast.parse(src, filename=path)
                    except SyntaxError as e:  # pragma: no cover
                        raise RuntimeError(f"cannot parse {path}: {e}")
                    out.append(SourceFile(path, rel, src, tree,
                                          Suppressions(src)))
            self._files = out
        return self._files

    def module_name(self, f: SourceFile) -> str:
        """Package-relative dotted module path ('' for __init__)."""
        mod = f.rel[:-3].replace("/", ".")
        if mod.endswith("__init__"):
            mod = mod[: -len("__init__")].rstrip(".")
        return mod


# ---------------------------------------------------------------------------
# shared import resolution (used by the layering and hostsync passes —
# ONE copy, so the two checkers can never disagree about what module an
# import statement targets)
# ---------------------------------------------------------------------------


def importer_package(rel: str, modname: str) -> str:
    """Package-relative dotted path of a file's PACKAGE — the anchor a
    level-1 relative import resolves against. For ``pkg/sub/x.py`` that
    is ``sub``; for ``pkg/sub/__init__.py`` it is also ``sub`` (a
    package's relative imports anchor at itself)."""
    if rel.endswith("__init__.py"):
        return modname
    return ".".join(modname.split(".")[:-1]) if modname else ""


def resolve_import(module: Optional[str], level: int, importer_pkg: str,
                   package: str) -> Optional[str]:
    """Resolve an import statement to a *package-relative* dotted path
    ('' = the package root), or None when it leaves the package.
    ``importer_pkg`` is the importing file's package (see
    importer_package); ``level`` is the ImportFrom relative level (0
    for absolute)."""
    if level == 0:
        name = module or ""
        if name == package:
            return ""
        if name.startswith(package + "."):
            return name[len(package) + 1:]
        return None
    # relative: level 1 anchors at the importer's own package, each
    # further level climbs one package
    parts = importer_pkg.split(".") if importer_pkg else []
    anchor = parts[: max(len(parts) - (level - 1), 0)]
    return ".".join(anchor + ([module] if module else []))


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

CheckerFn = Callable[[AnalysisContext], List[Finding]]
CHECKERS: Dict[str, CheckerFn] = {}


def register(family: str):
    def deco(fn: CheckerFn) -> CheckerFn:
        CHECKERS[family] = fn
        return fn
    return deco


@dataclass
class RunResult:
    findings: List[Finding] = field(default_factory=list)
    suppressed: int = 0
    checkers: List[str] = field(default_factory=list)
    notes: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_json(self) -> dict:
        counts: Dict[str, int] = {}
        for f in self.findings:
            counts[f.family] = counts.get(f.family, 0) + 1
        return {
            "version": SCHEMA_VERSION,
            "ok": self.ok,
            "checkers": list(self.checkers),
            "counts": counts,
            "suppressed": self.suppressed,
            "notes": list(self.notes),
            "findings": [f.to_json() for f in self.findings],
        }

    def format_text(self) -> str:
        lines = []
        for f in self.findings:
            lines.append(f.format())
        lines.append(f"cylint: {len(self.findings)} finding(s), "
                     f"{self.suppressed} suppressed "
                     f"[{', '.join(self.checkers)}]")
        for n in self.notes:
            lines.append(f"note: {n}")
        return "\n".join(lines)


def run_checkers(ctx: AnalysisContext,
                 families: Optional[Sequence[str]] = None) -> RunResult:
    """Run the selected checker families (default: all registered) and
    apply suppressions. Findings sort by (path, line, rule) so output
    (and the JSON schema) is deterministic. Unknown family names raise:
    a typo in a CI config must not become an exit-0 gate that ran
    nothing."""
    if families is not None:
        unknown = sorted(set(families) - set(CHECKERS))
        if unknown:
            raise ValueError(
                f"unknown checker families {unknown}; registered: "
                f"{sorted(CHECKERS)}")
    res = RunResult()
    by_path = {f.rel: f for f in ctx.files()}
    for name in sorted(CHECKERS):
        if families is not None and name not in families:
            continue
        res.checkers.append(name)
        for finding in CHECKERS[name](ctx):
            sf = by_path.get(finding.path)
            if sf is not None and sf.suppressions.is_suppressed(finding):
                res.suppressed += 1
                continue
            res.findings.append(finding)
    res.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    # checkers accumulate informational notes (coverage gaps, skipped
    # TPU-only entries, corpus sizes) in ctx.options["notes"]
    res.notes.extend(ctx.options.pop("notes", []))
    return res


def to_json_text(res: RunResult) -> str:
    return json.dumps(res.to_json(), indent=2, sort_keys=True)
