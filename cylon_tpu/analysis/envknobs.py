"""Environment-knob discipline: every ``CYLON_*`` read is declared.

The engine grew ~15 ``CYLON_*`` tunables across seven modules (retry
budget, deadlines, shed factor, DRR quantum, queue bound, flight ring,
skew threshold, HBM fallback, ...). Each used to be an ad-hoc
``os.environ.get`` with its own inline default — undiscoverable,
undocumented, and trivially typo-able. PR 8 routes them all through the
declared registry (``telemetry/knobs.py``); this family keeps it that
way:

* ``envknobs/unregistered-read`` — an ``os.environ[...]`` /
  ``os.environ.get`` / ``os.getenv`` read of a ``CYLON_*`` name (or a
  raw ``env_number("CYLON_*", ...)`` parse) ANYWHERE outside
  ``telemetry/knobs.py``. Ad-hoc reads fork the default/parse policy
  and dodge the generated docs table.
* ``envknobs/undeclared-knob`` — ``knobs.get("CYLON_X")`` /
  ``knobs.default("CYLON_X")`` naming a knob the scanned tree's
  registry never ``declare``s: it would raise ``KeyError`` at runtime
  and documents nothing.
* ``envknobs/undocumented-knob`` — a declared knob whose name does not
  appear in ``docs/telemetry.md`` (the table ``render_table``
  generates; ``python -m cylon_tpu.telemetry.knobs`` re-emits it).
  Anchored at the ``declare(...)`` line. Skipped — with a note — when
  the scanned tree has no sibling ``docs/`` (fixture trees).

The checker is purely syntactic over string LITERALS: a knob name
built at runtime is invisible (and would be a finding-worthy design
smell on its own — names are the registry's keys).
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Tuple

from .core import (AnalysisContext, Finding, ModuleIndex, attr_chain,
                   build_module_index, register)

REGISTRY_REL = "telemetry/knobs.py"

_ENV_GET_CHAINS = {("os", "environ", "get"), ("environ", "get"),
                   ("os", "getenv"), ("getenv",)}


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _declared_knobs(tree: ast.AST) -> Dict[str, int]:
    """CYLON_* names passed to ``declare(...)`` in the registry module
    -> declaration line."""
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = attr_chain(node.func)
        if chain is None or chain[-1] != "declare":
            continue
        name = None
        if node.args:
            name = _const_str(node.args[0])
        for kw in node.keywords:
            if kw.arg == "name":
                name = _const_str(kw.value)
        if name is not None:
            out.setdefault(name, node.lineno)
    return out


def _knob_api_call(chain: Tuple[str, ...], mod: ModuleIndex
                   ) -> Optional[str]:
    """'get'/'default' when this call chain resolves to the knob
    registry's accessor (via import tables), else None."""
    if len(chain) == 1:
        target = mod.fn_imports.get(chain[0])
        if target is not None and target[0].endswith("telemetry.knobs") \
                and target[1] in ("get", "default"):
            return target[1]
    elif len(chain) == 2 and chain[1] in ("get", "default"):
        alias = mod.mod_aliases.get(chain[0], "")
        if alias == "telemetry.knobs" or alias.endswith(".knobs") or \
                alias == "knobs":
            return chain[1]
    return None


@register("envknobs")
def check_envknobs(ctx: AnalysisContext) -> List[Finding]:
    modules = build_module_index(ctx)
    findings: List[Finding] = []
    notes = ctx.options.setdefault("notes", [])

    registry_file = next((sf for sf in ctx.files()
                          if sf.rel == REGISTRY_REL), None)
    declared: Dict[str, int] = {}
    if registry_file is not None:
        declared = _declared_knobs(registry_file.tree)

    reads = 0
    for sf in ctx.files():
        if sf.rel == REGISTRY_REL:
            continue
        mod = modules[ctx.module_name(sf)]
        for node in ast.walk(sf.tree):
            # os.environ["CYLON_X"] subscript form — Load context only:
            # an env-var WRITE (os.environ["CYLON_X"] = v, the way
            # tests/operators flip a live knob) is not a read and has
            # no registry equivalent to route through
            if isinstance(node, ast.Subscript):
                if not isinstance(node.ctx, ast.Load):
                    continue
                chain = attr_chain(node.value)
                if chain in (("os", "environ"), ("environ",)):
                    key = _const_str(node.slice)
                    if key is not None and key.startswith("CYLON_"):
                        reads += 1
                        findings.append(Finding(
                            rule="envknobs/unregistered-read",
                            path=sf.rel, line=node.lineno,
                            message=f"os.environ[{key!r}] bypasses the "
                                    f"declared knob registry "
                                    f"(telemetry/knobs.py) — route "
                                    f"through knobs.get"))
                continue
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if chain is None:
                continue
            arg0 = _const_str(node.args[0]) if node.args else None
            if chain in _ENV_GET_CHAINS:
                if arg0 is not None and arg0.startswith("CYLON_"):
                    reads += 1
                    findings.append(Finding(
                        rule="envknobs/unregistered-read",
                        path=sf.rel, line=node.lineno,
                        message=f"{'.'.join(chain)}({arg0!r}) bypasses "
                                f"the declared knob registry "
                                f"(telemetry/knobs.py) — route "
                                f"through knobs.get"))
            elif chain[-1] == "env_number":
                if arg0 is not None and arg0.startswith("CYLON_"):
                    reads += 1
                    findings.append(Finding(
                        rule="envknobs/unregistered-read",
                        path=sf.rel, line=node.lineno,
                        message=f"env_number({arg0!r}) parses a CYLON_ "
                                f"knob outside the registry — its "
                                f"default/doc live nowhere; declare "
                                f"it and use knobs.get"))
            else:
                api = _knob_api_call(chain, mod)
                if api is not None and arg0 is not None and \
                        registry_file is not None and \
                        arg0 not in declared:
                    findings.append(Finding(
                        rule="envknobs/undeclared-knob",
                        path=sf.rel, line=node.lineno,
                        message=f"knobs.{api}({arg0!r}) names a knob "
                                f"telemetry/knobs.py never declares "
                                f"(KeyError at runtime)"))

    # docs check: every declared knob appears in docs/telemetry.md
    if registry_file is None:
        notes.append("envknobs: no telemetry/knobs.py in this tree — "
                     "registry/docs checks skipped")
    else:
        docs_path = os.path.join(os.path.dirname(ctx.package_root),
                                 "docs", "telemetry.md")
        if not os.path.isfile(docs_path):
            notes.append("envknobs: no sibling docs/telemetry.md — "
                         "documentation check skipped")
        else:
            text = open(docs_path, encoding="utf-8").read()
            for name, line in sorted(declared.items()):
                # backtick-delimited match: a bare substring test would
                # let a knob that is a PREFIX of a documented one
                # (CYLON_FLIGHT_MAX vs CYLON_FLIGHT_MAX_DUMPS) pass
                # undocumented
                if f"`{name}`" not in text and \
                        not re.search(rf"\b{re.escape(name)}\b", text):
                    findings.append(Finding(
                        rule="envknobs/undocumented-knob",
                        path=REGISTRY_REL, line=line,
                        message=f"declared knob {name} is missing from "
                                f"docs/telemetry.md — regenerate the "
                                f"table with `python -m "
                                f"cylon_tpu.telemetry.knobs`"))
        # "site(s)": the count is taken before core applies per-line
        # cylint suppressions, so a sanctioned suppressed read shows
        # here even when zero findings surface
        notes.append(f"envknobs: {len(declared)} declared knobs, "
                     f"{reads} unregistered read site(s)")
    return findings
