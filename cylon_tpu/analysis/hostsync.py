"""Host-sync detector: host transfers inside traced (device) code.

`np.asarray` / `jax.device_get` / `.item()` / `float()` on a traced
value forces a device→host round trip (or a trace-time
ConcretizationTypeError on a path no test exercises). The framework's
discipline is that host syncs happen at exactly the declared points —
the count→capacity fetches between kernel phases — and NEVER inside
code that runs under `jit` / `shard_map` / `pallas_call`.

The pass is purely syntactic (nothing is imported):

1. *Trace roots.* A function is traced when it is decorated with
   ``jax.jit`` (or ``partial(jax.jit, ...)``), or its NAME is passed to
   ``jax.jit`` / ``shard_map`` / ``pl.pallas_call`` / a ``jax.lax``
   control-flow combinator — the repo's universal kernel-factory shape
   (``def kernel(...)`` then ``jax.jit(shard_map(kernel, ...))``).
2. *Closure.* Calls from a traced body to module-level functions —
   directly (``_bucket_sort(...)``) or through an intra-package module
   alias (``_join.join_plan_keys(...)``, resolved via each module's
   import table) — mark the callee traced too, transitively across the
   package. Nested ``def``s and lambdas inside a traced body are
   covered by walking the whole body.
3. *Flag.* Within traced code: ``np.asarray`` / ``np.array`` /
   ``np.ascontiguousarray``, ``jax.device_get``, ``.item()`` /
   ``.tolist()``, and ``float()/int()/bool()`` on non-static arguments
   (shape/ndim/len() expressions are static under trace and stay
   legal).

Host-side call sites — the overwhelming majority of the ~120
`np.asarray`/`device_get` sites in the package — are by construction
never flagged: they live outside any traced closure. Each finding
reports the trace chain (root → callee) so a false positive is cheap
to triage; a justified one takes a per-line ``# cylint:
disable=hostsync/...`` with a comment.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import (AnalysisContext, Finding, ModuleIndex, attr_chain,
                   build_module_index, call_closure, register)

# call targets whose function-valued arguments become traced
_TRACING_CALLS = {
    ("jax", "jit"), ("jit",), ("shard_map",), ("jax", "vmap"),
    ("pl", "pallas_call"), ("pallas_call",),
    ("jax", "lax", "fori_loop"), ("jax", "lax", "while_loop"),
    ("jax", "lax", "cond"), ("jax", "lax", "scan"),
    ("jax", "lax", "switch"), ("lax", "fori_loop"), ("lax", "cond"),
    ("lax", "scan"), ("lax", "while_loop"), ("lax", "switch"),
    ("jax", "checkpoint"), ("jax", "remat"),
}

# attribute-call chains that ARE a host sync
_SYNC_CALLS = {
    ("np", "asarray"), ("np", "array"), ("np", "ascontiguousarray"),
    ("numpy", "asarray"), ("numpy", "array"),
    ("jax", "device_get"),
}

_SYNC_METHODS = {"item", "tolist"}

_CAST_BUILTINS = {"float", "int", "bool"}


# the attribute-chain resolver now lives in core (attr_chain) — one
# copy shared with the concurrency checker's call-graph pass
_attr_chain = attr_chain


def _is_jit_decorator(dec: ast.AST) -> bool:
    chain = _attr_chain(dec)
    if chain in (("jax", "jit"), ("jit",)):
        return True
    if isinstance(dec, ast.Call):
        inner = _attr_chain(dec.func)
        if inner in (("jax", "jit"), ("jit",)):
            return True
        # partial(jax.jit, static_argnames=...)
        if inner in (("partial",), ("functools", "partial")) and dec.args:
            return _attr_chain(dec.args[0]) in (("jax", "jit"), ("jit",))
    return False


def _static_params(fn: ast.AST) -> Set[str]:
    """Parameters of ``fn`` that are static under tracing: annotated as
    a scalar Python type (``max_e: int``), or named in the function's
    own ``jax.jit(static_argnames=...)`` decorator (enum/config args)."""
    out: Set[str] = set()
    args = fn.args
    all_args = list(args.posonlyargs) + list(args.args) \
        + list(args.kwonlyargs)
    for a in all_args:
        ann = a.annotation
        if isinstance(ann, ast.Name) and ann.id in ("int", "float",
                                                    "bool", "str"):
            out.add(a.arg)
    for dec in fn.decorator_list:
        if not (isinstance(dec, ast.Call) and _is_jit_decorator(dec)):
            continue
        for kw in dec.keywords:
            if kw.arg in ("static_argnames", "static_argnums"):
                names = kw.value.elts \
                    if isinstance(kw.value, (ast.Tuple, ast.List)) \
                    else [kw.value]
                for n in names:
                    if isinstance(n, ast.Constant):
                        if isinstance(n.value, str):
                            out.add(n.value)
                        elif isinstance(n.value, int) and \
                                n.value < len(all_args):
                            out.add(all_args[n.value].arg)
    return out


def _is_staticish(node: ast.AST, static_names: Set[str] = frozenset()
                  ) -> bool:
    """Expressions that stay concrete under tracing: literals, shape /
    ndim / size / itemsize introspection, len(), statically-annotated
    parameters, and arithmetic over those. Conservative: anything else
    is treated as possibly traced."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Name) and node.id in static_names:
        return True
    if isinstance(node, ast.Attribute):
        if node.attr in ("ndim", "size", "itemsize", "dtype"):
            return True
        if node.attr == "shape":
            return True
        return _is_staticish(node.value, static_names) and \
            node.attr.isidentifier()
    if isinstance(node, ast.Subscript):
        return isinstance(node.value, ast.Attribute) and \
            node.value.attr == "shape"
    if isinstance(node, ast.Call):
        chain = _attr_chain(node.func)
        if chain in (("len",), ("int",), ("float",), ("max",), ("min",)):
            return all(_is_staticish(a, static_names) for a in node.args)
        return False
    if isinstance(node, ast.BinOp):
        return _is_staticish(node.left, static_names) and \
            _is_staticish(node.right, static_names)
    if isinstance(node, ast.UnaryOp):
        return _is_staticish(node.operand, static_names)
    return False


# the per-file symbol tables now live in core (ModuleIndex) — the
# closure pass shares them with the concurrency checker
_Module = ModuleIndex


def _trace_roots(mod: _Module) -> Set[str]:
    """Names of this module's functions that enter tracing directly."""
    roots: Set[str] = set()
    for name, fn in mod.functions.items():
        if any(_is_jit_decorator(d) for d in fn.decorator_list):
            roots.add(name)
    for node in ast.walk(mod.sf.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if chain is None or chain not in _TRACING_CALLS:
            continue
        for arg in node.args:
            inner = _attr_chain(arg)
            if inner is not None and len(inner) == 1:
                roots.add(inner[0])
    return roots


def _scan_body(fn: ast.AST, mod: _Module, chain_desc: str
               ) -> List[Finding]:
    out: List[Finding] = []
    # static parameters of this function and every def nested in it
    # (kernel factories close over static config; a per-scope walk
    # would be more precise but name collisions are not a real risk)
    static_names: Set[str] = set()
    for sub in ast.walk(fn):
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
            static_names |= _static_params(sub)
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        where = f" [traced via {chain_desc}]" if chain_desc else ""
        if chain in _SYNC_CALLS:
            out.append(Finding(
                rule="hostsync/transfer", path=mod.sf.rel,
                line=node.lineno,
                message=f"{'.'.join(chain)}() inside traced code forces "
                        f"a device→host transfer{where}"))
        elif isinstance(node.func, ast.Attribute) and \
                node.func.attr in _SYNC_METHODS and not node.args:
            out.append(Finding(
                rule="hostsync/transfer", path=mod.sf.rel,
                line=node.lineno,
                message=f".{node.func.attr}() inside traced code forces "
                        f"a device→host transfer{where}"))
        elif chain is not None and len(chain) == 1 and \
                chain[0] in _CAST_BUILTINS and node.args:
            if not all(_is_staticish(a, static_names) for a in node.args):
                out.append(Finding(
                    rule="hostsync/concretize", path=mod.sf.rel,
                    line=node.lineno,
                    message=f"{chain[0]}() on a possibly-traced value "
                            f"inside traced code concretizes (host "
                            f"sync or trace error){where}"))
    return out


@register("hostsync")
def check_hostsync(ctx: AnalysisContext) -> List[Finding]:
    package = ctx.package_name
    modules = build_module_index(ctx)

    # seed with direct trace roots, then close over the call graph
    # (core.call_closure — the machinery shared with the concurrency
    # checker's thread-domain reachability)
    seeds: Dict[Tuple[str, str], str] = {}
    for modname, mod in modules.items():
        for name in _trace_roots(mod):
            if name in mod.functions:
                seeds[(modname, name)] = name
    traced = call_closure(modules, seeds, package)

    findings: List[Finding] = []
    for (modname, fname), desc in sorted(traced.items()):
        mod = modules[modname]
        fn = mod.lookup(fname)
        if fn is not None:
            findings.extend(_scan_body(fn, mod, desc))

    # classification summary: every host-transfer call site in the tree
    # is either inside a traced closure (flagged above) or host-side
    # (legal — the declared count→capacity syncs between kernel phases)
    total = 0
    for sf in ctx.files():
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call):
                chain = _attr_chain(node.func)
                if chain in _SYNC_CALLS or (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr in _SYNC_METHODS
                        and not node.args):
                    total += 1
    flagged = sum(1 for f in findings if f.rule == "hostsync/transfer")
    ctx.options.setdefault("notes", []).append(
        f"hostsync: {total} host-transfer call sites; {flagged} inside "
        f"traced closures (flagged), {total - flagged} host-side (legal); "
        f"{len(traced)} functions in the traced closure")
    return findings
