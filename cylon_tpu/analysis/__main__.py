"""CLI: ``python -m cylon_tpu.analysis`` — run the static-analysis
suite; exit 0 iff no unsuppressed finding.

Wired into scripts/check.sh ahead of tier-1. Typical invocations:

    python -m cylon_tpu.analysis                    # full suite
    python -m cylon_tpu.analysis --json             # machine-readable
    python -m cylon_tpu.analysis --format sarif     # SARIF v2.1.0 (CI)
    python -m cylon_tpu.analysis --families layering,hostsync
    python -m cylon_tpu.analysis --package-root tests/analysis_fixtures/pkg_bad
    python -m cylon_tpu.analysis --list-rules
"""
from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    # the collectives checker wants virtual host devices; the flag only
    # takes effect if the jax backend has not initialized yet, which is
    # the case here (importing cylon_tpu imports jax but touches no
    # device until a kernel runs)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "--xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
            " --xla_force_host_platform_device_count=8"

    p = argparse.ArgumentParser(
        prog="python -m cylon_tpu.analysis",
        description="cylon_tpu static-analysis suite "
                    "(docs/analysis.md)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output (stable schema, "
                        "docs/analysis.md); alias for --format json")
    p.add_argument("--format", choices=("text", "json", "sarif"),
                   default=None,
                   help="output format: text (default), json (stable "
                        "schema v1), or sarif (SARIF v2.1.0 for CI "
                        "inline annotation)")
    p.add_argument("--families",
                   help="comma-separated checker families to run "
                        "(default: all registered)")
    p.add_argument("--package-root",
                   help="package tree to scan (default: the installed "
                        "cylon_tpu package); fixture trees use this")
    p.add_argument("--collectives-entry-module",
                   help="fixture module file declaring ENTRY_POINTS "
                        "for the collectives checker")
    p.add_argument("--witness-plan-module",
                   help="fixture module file declaring build_plans() "
                        "for the witness checker")
    p.add_argument("--world", type=int, default=4,
                   help="virtual mesh width for semantic checkers")
    p.add_argument("--list-rules", action="store_true",
                   help="print registered checker families and exit")
    args = p.parse_args(argv)

    from . import AnalysisContext, CHECKERS, run_checkers, \
        to_json_text, to_sarif_text

    fmt = args.format or ("json" if args.json else "text")

    if args.list_rules:
        for name in sorted(CHECKERS):
            doc = (sys.modules[CHECKERS[name].__module__].__doc__ or
                   "").strip().splitlines()[0]
            print(f"{name:12s} {doc}")
        return 0

    if args.package_root:
        root = args.package_root
    else:
        import cylon_tpu

        root = os.path.dirname(os.path.abspath(cylon_tpu.__file__))

    options = {"world": args.world}
    if args.collectives_entry_module:
        options["collectives_entry_module"] = args.collectives_entry_module
    if args.witness_plan_module:
        options["witness_plan_module"] = args.witness_plan_module

    families = args.families.split(",") if args.families else None
    if args.package_root and families is None and \
            not (args.collectives_entry_module or
                 args.witness_plan_module):
        # scanning a fixture/foreign tree: the semantic checkers
        # (collectives/witness) are about the REAL package's kernels
        # and optimizer — run only the file-scanning families
        families = ["layering", "hostsync", "span-coverage",
                    "ledger-coverage", "errors", "concurrency",
                    "envknobs", "specialization"]

    ctx = AnalysisContext(root, options)
    try:
        res = run_checkers(ctx, families)
    except ValueError as e:  # unknown --families entry
        print(f"error: {e}", file=sys.stderr)
        return 2
    print({"json": to_json_text, "sarif": to_sarif_text}[fmt](res)
          if fmt != "text" else res.format_text())
    return 0 if res.ok else 1


if __name__ == "__main__":
    sys.exit(main())
