"""Error-handling lints: no silent swallowing of broad exceptions.

The resilience layer only works when failures actually REACH it: a
``try/except Exception: pass`` between a fault and the retry loop
converts a recoverable transient into silent data loss, and a bare
``except:`` even eats ``KeyboardInterrupt``. This family makes the
swallow-points static:

* ``errors/bare-except``   — a bare ``except:`` handler, anywhere.
* ``errors/broad-swallow`` — an ``except Exception`` /
  ``except BaseException`` handler that SWALLOWS: its body neither
  re-raises, nor reports through the telemetry error channel
  (``logger.exception/error/warning`` or an ``error=True`` span
  attribute).

"Swallow" is deliberately the bar, not "catch": catching broadly at a
defensive boundary is fine as long as the failure stays observable.
Handlers that ``raise`` (bare or a typed error), log through the
telemetry logger, or mark the enclosing span errored all pass. A
deliberate silent fallback (e.g. a memory-stats probe where failure
IS the answer) opts out per line with
``# cylint: disable=errors/broad-swallow`` — an explicit, reviewable
decision, never a hidden default.
"""
from __future__ import annotations

import ast
from typing import List, Optional

from .core import AnalysisContext, Finding, register

# exception names considered over-broad when caught
_BROAD = frozenset({"Exception", "BaseException"})

# attribute/function call names that count as REPORTING the failure
_REPORT_CALLS = frozenset({"exception", "error", "warning"})


def _exc_name(node: Optional[ast.expr]) -> Optional[str]:
    if node is None:
        return None
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return False  # the bare-except rule owns that case
    if isinstance(t, ast.Tuple):
        return any(_exc_name(e) in _BROAD for e in t.elts)
    return _exc_name(t) in _BROAD


def _reports_or_reraises(handler: ast.ExceptHandler) -> bool:
    """True when the handler body re-raises or reports through the
    telemetry error channel (log call or error=True span attr)."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else \
                fn.id if isinstance(fn, ast.Name) else None
            if name in _REPORT_CALLS:
                return True
            # span error marking: any call carrying error=True
            # (sp.set(error=True), annotate(error=True))
            for kw in node.keywords:
                if kw.arg == "error" and \
                        isinstance(kw.value, ast.Constant) and \
                        kw.value.value is True:
                    return True
    return False


@register("errors")
def check_errors(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for f in ctx.files():
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                findings.append(Finding(
                    rule="errors/bare-except", path=f.rel,
                    line=node.lineno,
                    message="bare `except:` catches everything "
                            "(KeyboardInterrupt/SystemExit included) "
                            "— name the exception class, at least "
                            "`Exception`"))
                continue
            if _is_broad(node) and not _reports_or_reraises(node):
                findings.append(Finding(
                    rule="errors/broad-swallow", path=f.rel,
                    line=node.lineno,
                    message="broad handler swallows the failure: "
                            "neither re-raises nor reports it "
                            "(logger.exception/error/warning or an "
                            "error=True span attr) — a fault dying "
                            "here never reaches the retry/flight-"
                            "recorder machinery; if the silent "
                            "fallback is deliberate, opt out with "
                            "`# cylint: disable=errors/broad-swallow`"))
    return findings
