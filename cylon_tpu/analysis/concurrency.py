"""Thread-domain race detector for the concurrent service tier.

PR 7 made the engine genuinely multi-threaded: submitter threads run
optimization/pre-flight concurrently with one executor worker, a shared
plan-cache LRU absorbs hits from every thread, ledger weakref
finalizers fire wherever GC happens to run, and flight-recorder hooks
run on whichever thread closes a root span. The reference Cylon
sidesteps all of this with one-MPI-rank-per-process; our service tier
cannot — and a race caught by lint is infinitely cheaper than one
caught under production load.

The pass reuses hostsync's transitive call-graph machinery
(``core.call_closure``) to compute what each **thread domain** reaches:

* ``worker:<fn>`` — every ``threading.Thread(target=...)`` target (the
  service executor's ``_run`` loop); serial with itself, concurrent
  with everything else.
* ``api`` — the public submitter surface: public methods of every
  top-level class in a thread-spawning module (``submit``/``close``/
  ``drain``/ticket accessors), public module functions there, plus the
  ``DECLARED_ENTRIES`` catalog below (plan cache, ledger surface, fault
  injector arm/disarm — entry points many user threads call at once).
  Concurrent with itself.
* ``finalizer`` — ``weakref.ref``/``weakref.finalize`` callbacks (the
  ledger's GC retire path): fire on ARBITRARY threads, mid-allocation,
  even inside another function's critical section. Concurrent with
  itself and everything else, and additionally **non-reentrant**: it
  may interrupt a thread that already holds a plain ``threading.Lock``
  the callback wants.
* ``hook`` — callbacks registered through ``atexit.register`` and the
  telemetry hook registrars (``add_root_hook``/``add_sink``/
  ``add_dump_section``/``set_factory_*_hook``/``set_plan_memo``): they
  run on whichever thread triggers them.

Rules (all package-relative, suppressible per line like every family):

* ``concurrency/unlocked-shared-write`` — instance-attribute or
  module-global state written (outside ``__init__``) with NO lock
  while its access sites span ≥2 domains or a self-concurrent domain.
* ``concurrency/lock-discipline`` — inferred per attribute: state ever
  written under a lock must hold that lock at EVERY access; an
  unlocked read of locked-write state is a torn-read/lost-update site.
* ``concurrency/blocking-under-lock`` — a blocking call (``time.sleep``,
  ``.result()``/``.join()``/``.acquire()``/foreign ``.wait()``,
  ``queue.get`` — the bare zero-arg form blocks indefinitely, the
  ``block=``/``timeout=`` forms bound it — or jax dispatch, directly
  or transitively through the call graph) made while holding a lock:
  the serialization/deadlock hazard class. ``held_cv.wait()`` is legal
  (Condition.wait releases its lock), as are the explicitly
  non-blocking spellings ``acquire(blocking=False)`` /
  ``get(block=False)``.
* ``concurrency/unstamped-contextvar`` — a contextvar ``.get()``
  reached from a thread-entry domain (worker/finalizer/hook) whose
  closure never ``set``s it: a fresh thread's context carries the
  DEFAULT, not the submitter's stamp — exactly the tenant-label /
  deadline bug class PR 7 hand-dodged with ``root_attrs``/
  ``query_deadline`` re-stamps.
* ``concurrency/finalizer-hazard`` — finalizer-domain code acquiring a
  NON-reentrant ``threading.Lock`` (same-thread GC re-entry deadlocks
  it; use RLock) or dispatching through jax (device work inside GC).

Static limits, by design: calls through local variables/parameters
(``ticket._finish(...)``) and container-method mutation
(``list.append``) are invisible — the checker trades recall for a
near-zero false-positive rate; the dynamic barrier-hammer test in
tests/test_service.py corroborates from the other side.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import (AnalysisContext, Finding, ModuleIndex, attr_chain,
                   build_module_index, call_closure, register)

# domains that can run concurrently with THEMSELVES (many user threads
# in the API; GC/hooks fire wherever)
SELF_CONCURRENT = ("api", "finalizer", "hook")

# the real package's declared entry-point catalog: public surfaces many
# threads call that no syntactic Thread/weakref scan can discover
# (documented in docs/service.md "Threading model"). Entries whose
# module/function are absent from the scanned tree are ignored, so
# fixture trees are unaffected.
DECLARED_ENTRIES: Tuple[Tuple[str, str, str], ...] = (
    # the plan/fingerprint cache: submitter threads race the LRU
    ("api", "service.plancache", "PlanCache.optimize"),
    ("api", "service.plancache", "PlanCache.clear"),
    ("api", "service.plancache", "PlanCache.invalidate"),
    ("api", "service.plancache", "memo_optimize"),
    ("api", "service.plancache", "disabled"),
    # the ledger's public surface: every executing thread tracks
    ("api", "telemetry.ledger", "track"),
    ("api", "telemetry.ledger", "release"),
    ("api", "telemetry.ledger", "live_bytes"),
    ("api", "telemetry.ledger", "outstanding"),
    ("api", "telemetry.ledger", "leak_report"),
    ("api", "telemetry.ledger", "leak_count"),
    # chaos arming happens from test/driver threads while workers fire
    ("api", "resilience.inject", "arm"),
    ("api", "resilience.inject", "disarm"),
    ("api", "resilience.inject", "state"),
    # the observability endpoint: ThreadingHTTPServer spawns one
    # daemon thread PER REQUEST inside the stdlib (no syntactic
    # Thread(...) for the scan to find), so the handler entry point
    # and the route renderers are declared concurrency domains —
    # scrapes race submitters, the worker, finalizers, everything
    ("api", "service.obs_http", "_Handler.do_GET"),
    ("api", "service.obs_http", "render_metrics"),
    ("api", "service.obs_http", "render_healthz"),
    ("api", "service.obs_http", "render_queries"),
    ("api", "service.obs_http", "render_slo"),
    ("api", "service.obs_http", "ObsServer.close"),
    # the structured query log: fed by the root-span hook, read by
    # scrape threads and test drivers
    ("api", "telemetry.querylog", "recent"),
    ("api", "telemetry.querylog", "enable"),
    ("api", "telemetry.querylog", "disable"),
    ("api", "telemetry.querylog", "lines_written"),
    ("api", "telemetry.querylog", "reset"),
    # the SLO tracker: observed from the hook domain, read by scrapes
    ("api", "telemetry.slo", "observe"),
    ("api", "telemetry.slo", "state"),
    ("api", "telemetry.slo", "reset"),
    # the statistics warehouse: fed by the querylog root hook, read by
    # the admission path (submitters + the executor worker), scraped
    # by /stats request threads, persisted from service lifecycle
    ("api", "service.obs_http", "render_stats"),
    ("api", "telemetry.stats", "record_root"),
    ("api", "telemetry.stats", "effective_bytes"),
    ("api", "telemetry.stats", "node_obs"),
    ("api", "telemetry.stats", "recent_drift"),
    ("api", "telemetry.stats", "state"),
    ("api", "telemetry.stats", "save"),
    ("api", "telemetry.stats", "load"),
    ("api", "telemetry.stats", "reset"),
)

# hook registrars: a function-valued argument to one of these becomes
# hook-domain code (runs on whichever thread triggers the hook)
HOOK_REGISTRARS = ("add_root_hook", "add_sink", "add_dump_section",
                   "set_factory_fault_hook", "set_factory_build_hook",
                   "set_plan_memo", "set_plan_evict_hook")

_LOCK_CTORS = {
    ("threading", "Lock"): False,      # reentrant? no
    ("threading", "RLock"): True,
    ("threading", "Condition"): True,  # default wraps an RLock
    ("Lock",): False,
    ("RLock",): True,
    ("Condition",): True,
}

_THREAD_CTORS = (("threading", "Thread"), ("Thread",))
_WEAKREF_CBS = (("weakref", "ref"), ("weakref", "finalize"),
                ("ref",), ("finalize",))
_CONTEXTVAR_CTORS = (("contextvars", "ContextVar"), ("ContextVar",))

LockKey = Tuple  # ("cls", mod, Cls, attr) | ("mod", mod, name)
FnKey = Tuple[str, str]  # (module, qualname)


# ---------------------------------------------------------------------------
# package inventory: locks + contextvars
# ---------------------------------------------------------------------------


class _Inventory:
    def __init__(self, modules: Dict[str, ModuleIndex]):
        # lock key -> reentrant?
        self.locks: Dict[LockKey, bool] = {}
        # (mod, name) of every module-level ContextVar
        self.contextvars: Set[Tuple[str, str]] = set()
        # module-level simple-assigned names (the global-state universe)
        self.globals: Dict[str, Set[str]] = {}
        for modname, mod in modules.items():
            g: Set[str] = set()
            for node in mod.sf.tree.body:
                if isinstance(node, ast.Assign) and \
                        len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name):
                    name = node.targets[0].id
                    g.add(name)
                    if isinstance(node.value, ast.Call):
                        chain = attr_chain(node.value.func)
                        if chain in _LOCK_CTORS:
                            self.locks[("mod", modname, name)] = \
                                _LOCK_CTORS[chain]
                        elif chain in _CONTEXTVAR_CTORS:
                            self.contextvars.add((modname, name))
                elif isinstance(node, ast.AnnAssign) and \
                        isinstance(node.target, ast.Name):
                    g.add(node.target.id)
                    if isinstance(node.value, ast.Call):
                        chain = attr_chain(node.value.func)
                        if chain in _CONTEXTVAR_CTORS:
                            self.contextvars.add((modname,
                                                  node.target.id))
            self.globals[modname] = g
            # instance locks: self.X = threading.Lock() in any method
            for qual, fn in mod.methods.items():
                cls = qual.split(".", 1)[0]
                for node in ast.walk(fn):
                    if not (isinstance(node, ast.Assign) and
                            len(node.targets) == 1):
                        continue
                    tgt = node.targets[0]
                    if not (isinstance(tgt, ast.Attribute) and
                            isinstance(tgt.value, ast.Name) and
                            tgt.value.id == "self" and
                            isinstance(node.value, ast.Call)):
                        continue
                    chain = attr_chain(node.value.func)
                    if chain in _LOCK_CTORS:
                        self.locks[("cls", modname, cls, tgt.attr)] = \
                            _LOCK_CTORS[chain]

    def lock_of(self, chain, modname: str, cls: Optional[str]
                ) -> Optional[LockKey]:
        """The lock key a with-item / receiver chain names, or None."""
        if chain is None:
            return None
        if len(chain) == 1:
            key = ("mod", modname, chain[0])
            return key if key in self.locks else None
        if len(chain) == 2 and chain[0] == "self" and cls is not None:
            key = ("cls", modname, cls, chain[1])
            return key if key in self.locks else None
        return None


# ---------------------------------------------------------------------------
# per-function lexical scan (lock regions, calls, accesses)
# ---------------------------------------------------------------------------


class _Access:
    __slots__ = ("name", "write", "line", "held")

    def __init__(self, name, write, line, held):
        self.name = name
        self.write = write
        self.line = line
        self.held = held


class _CallSite:
    __slots__ = ("node", "chain", "held", "line")

    def __init__(self, node, chain, held, line):
        self.node = node
        self.chain = chain
        self.held = held
        self.line = line


class _FnScan:
    """One function's lexical facts: every call site and every
    ``self.X`` / module-global access, each tagged with the lock set
    held at that point. Nested ``def``/``lambda`` bodies are separate
    execution scopes (they run LATER, not under the enclosing locks)
    and are skipped."""

    def __init__(self, fn: ast.AST, mod: ModuleIndex, inv: _Inventory,
                 qualname: str):
        self.mod = mod
        self.cls = qualname.split(".", 1)[0] if "." in qualname else None
        self.inv = inv
        self.calls: List[_CallSite] = []
        self.attr_acc: List[_Access] = []    # self.X accesses
        self.global_acc: List[_Access] = []  # module-global accesses
        self.with_locks: List[Tuple] = []    # (lockkey, line, held)
        self._globals_declared: Set[str] = set()
        self._locals: Set[str] = set()
        args = fn.args
        for a in (list(args.posonlyargs) + list(args.args) +
                  list(args.kwonlyargs) +
                  ([args.vararg] if args.vararg else []) +
                  ([args.kwarg] if args.kwarg else [])):
            self._locals.add(a.arg)
        # pre-pass: global decls + local assignments (name shadowing).
        # Own scope ONLY — a nested def binds its NAME here but its
        # body is a separate scope, and walking it would let a nested
        # function's local shadow a same-named module global, hiding
        # the outer function's global accesses from every shared-state
        # rule (a false negative in exactly the race class this
        # checker exists for).
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._locals.add(node.name)
                continue
            if isinstance(node, ast.Lambda):
                continue
            if isinstance(node, ast.Global):
                self._globals_declared.update(node.names)
            elif isinstance(node, ast.Name) and \
                    isinstance(node.ctx, (ast.Store,)):
                self._locals.add(node.id)
            stack.extend(ast.iter_child_nodes(node))
        self._locals -= self._globals_declared
        for stmt in fn.body:
            self._visit(stmt, frozenset())

    def _is_global(self, name: str) -> bool:
        if name in self._globals_declared:
            return True
        return name in self.inv.globals.get(self.mod.modname, ()) and \
            name not in self._locals

    def _visit(self, node: ast.AST, held: frozenset) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # separate execution scope
        if isinstance(node, (ast.With, ast.AsyncWith)):
            # items acquire left-to-right: item N's context expression
            # evaluates with items 1..N-1 already held, so scan each
            # against the ACCUMULATED set, not the outer one
            new = set(held)
            for item in node.items:
                key = self.inv.lock_of(attr_chain(item.context_expr),
                                       self.mod.modname, self.cls)
                if key is not None:
                    self.with_locks.append((key, node.lineno,
                                            frozenset(new)))
                    new.add(key)
                else:
                    self._visit(item.context_expr, frozenset(new))
            for sub in node.body:
                self._visit(sub, frozenset(new))
            return
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain is not None:
                self.calls.append(_CallSite(node, chain, held,
                                            node.lineno))
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self":
            self.attr_acc.append(_Access(
                node.attr, isinstance(node.ctx, (ast.Store, ast.Del)),
                node.lineno, held))
        if isinstance(node, ast.Name) and self._is_global(node.id):
            self.global_acc.append(_Access(
                node.id, isinstance(node.ctx, (ast.Store, ast.Del)),
                node.lineno, held))
        # container mutation through subscript: self.X[k] = v / X[k] = v
        if isinstance(node, ast.Subscript) and \
                isinstance(node.ctx, (ast.Store, ast.Del)):
            base = node.value
            if isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id == "self":
                self.attr_acc.append(_Access(base.attr, True,
                                             node.lineno, held))
            elif isinstance(base, ast.Name) and \
                    self._is_global(base.id):
                self.global_acc.append(_Access(base.id, True,
                                               node.lineno, held))
        for child in ast.iter_child_nodes(node):
            self._visit(child, held)


# ---------------------------------------------------------------------------
# domain discovery
# ---------------------------------------------------------------------------


def _fn_target(arg: ast.AST, mod: ModuleIndex, cls: Optional[str]
               ) -> List[FnKey]:
    """Resolve a function-valued argument (Name / self.X / lambda) to
    (module, qualname) keys; a lambda contributes its callees."""
    chain = attr_chain(arg)
    if chain is not None:
        if len(chain) == 1:
            name = chain[0]
            if name in mod.functions:
                return [(mod.modname, name)]
            if name in mod.fn_imports:
                return [mod.fn_imports[name]]
        elif len(chain) == 2 and chain[0] == "self" and cls is not None:
            return [(mod.modname, f"{cls}.{chain[1]}")]
    if isinstance(arg, ast.Lambda):
        from .core import called_functions
        return sorted(called_functions(arg.body, mod, None, cls))
    return []


def _discover_domains(modules: Dict[str, ModuleIndex]
                      ) -> Dict[str, Dict[FnKey, str]]:
    """Domain name -> seed map {(mod, qualname): description}."""
    domains: Dict[str, Dict[FnKey, str]] = {}

    def seed(domain: str, key: FnKey, desc: str) -> None:
        domains.setdefault(domain, {}).setdefault(key, desc)

    for modname, mod in modules.items():
        spawns_in_module = False
        # scan every function/method body AND module-level statements
        bodies = [(None, mod.sf.tree)] + \
            [(None, f) for f in mod.functions.values()] + \
            [(q.split(".", 1)[0], f) for q, f in mod.methods.items()]
        thread_targets: Set[FnKey] = set()
        for cls, body in bodies:
            for node in ast.walk(body):
                if not isinstance(node, ast.Call):
                    continue
                chain = attr_chain(node.func)
                if chain is None:
                    continue
                args = list(node.args)
                kwargs = {k.arg: k.value for k in node.keywords}
                if chain in _THREAD_CTORS:
                    tgt = kwargs.get("target") or \
                        (args[1] if len(args) > 1 else None)
                    if tgt is not None:
                        for key in _fn_target(tgt, mod, cls):
                            name = f"worker:{key[0] or 'pkg'}.{key[1]}"
                            seed(name, key, key[1])
                            thread_targets.add(key)
                            spawns_in_module = True
                elif chain in _WEAKREF_CBS:
                    cb = args[1] if len(args) > 1 else \
                        kwargs.get("callback")
                    if cb is not None:
                        for key in _fn_target(cb, mod, cls):
                            seed("finalizer", key,
                                 f"GC finalizer {key[1]}")
                elif chain == ("atexit", "register") and args:
                    for key in _fn_target(args[0], mod, cls):
                        seed("hook", key, f"atexit {key[1]}")
                elif chain[-1] in HOOK_REGISTRARS:
                    for a in list(args) + list(kwargs.values()):
                        for key in _fn_target(a, mod, cls):
                            seed("hook", key,
                                 f"{chain[-1]} callback {key[1]}")
        # public submitter surface of thread-spawning modules: public
        # methods of every public top-level class (minus the thread
        # targets) + public module functions
        if spawns_in_module:
            for qual, fn in mod.methods.items():
                cls, meth = qual.split(".", 1)
                if cls.startswith("_"):
                    continue
                public = not meth.startswith("_") or \
                    meth in ("__enter__", "__exit__", "__call__")
                if public and (modname, qual) not in thread_targets:
                    seed("api", (modname, qual), qual)
            for name in mod.functions:
                if not name.startswith("_"):
                    seed("api", (modname, name), name)

    # the declared catalog (real-tree entries; absent ones ignored)
    for domain, modname, qual in DECLARED_ENTRIES:
        mod = modules.get(modname)
        if mod is not None and mod.lookup(qual) is not None:
            seed(domain, (modname, qual), qual)
    return domains


# ---------------------------------------------------------------------------
# the checker
# ---------------------------------------------------------------------------


_BLOCKING_ATTRS = ("result", "join", "acquire", "wait", "wait_for")


def _kwarg_is_false(call: ast.Call, name: str) -> bool:
    """True when the call passes ``name=False`` literally — the
    explicit non-blocking spelling of acquire()/queue.get()."""
    for k in call.keywords:
        if k.arg == name and isinstance(k.value, ast.Constant) and \
                k.value.value is False:
            return True
    return False


def _blocking_primitive(site: _CallSite, inv: _Inventory,
                        mod: ModuleIndex, cls: Optional[str]
                        ) -> Optional[str]:
    """A human-readable description when this call site IS a blocking
    primitive (ignoring lock context), else None."""
    chain = site.chain
    if chain in (("time", "sleep"), ("sleep",)):
        return "time.sleep"
    if chain[0] == "jax" and (len(chain) < 2 or
                              chain[1] != "profiler"):
        return f"jax dispatch {'.'.join(chain)}"
    if len(chain) >= 2 and chain[-1] in _BLOCKING_ATTRS:
        if _kwarg_is_false(site.node, "blocking"):
            return None  # lock.acquire(blocking=False) never blocks
        if chain[-1] == "join":
            # disambiguate Thread.join from the string/os.path shapes:
            # a 2-chain non-os receiver (`worker.join(t)`) is treated
            # as a thread, and a longer chain only when it is
            # self-held (`self._worker.join()` — the canonical
            # shutdown-deadlock shape) or passes timeout= (str.join
            # has no kwargs). `sep.join(parts)` under a lock is the
            # residual false positive; per-line disable covers it.
            kw = {k.arg for k in site.node.keywords}
            threadish = (len(chain) == 2 and chain[0] != "os") or \
                chain[0] == "self" or "timeout" in kw
            if not threadish:
                return None
        return f"{'.'.join(chain)}()"
    if len(chain) >= 2 and chain[-1] == "get":
        if _kwarg_is_false(site.node, "block"):
            return None  # queue.get(block=False) never blocks
        kw = {k.arg for k in site.node.keywords}
        if kw & {"timeout", "block"}:
            return f"{'.'.join(chain)}(block/timeout)"
        # bare q.get() — no args at all — is the INDEFINITELY-blocking
        # queue shape (dict/os.environ .get always takes a key).
        # Zero-arg ContextVar.get() is the other common shape; exclude
        # receivers whose terminal name is a known module-level
        # ContextVar (name-level match — good enough for a lint).
        if not site.node.args and not site.node.keywords:
            cv_names = {n for _, n in inv.contextvars}
            if chain[-2] not in cv_names:
                return f"{'.'.join(chain)}() [bare queue-get shape]"
    return None


def _held_lock_wait(site: _CallSite, inv: _Inventory, mod: ModuleIndex,
                    cls: Optional[str], held: frozenset) -> bool:
    """``held_cv.wait()`` — Condition.wait RELEASES its lock, the one
    legal blocking call under that same lock. ``held`` must be the
    EFFECTIVE held set (lexical + caller-inherited), else refactoring a
    cv.wait into a helper only ever called under ``with self._cv:``
    would false-positive."""
    chain = site.chain
    if chain[-1] not in ("wait", "wait_for"):
        return False
    key = inv.lock_of(chain[:-1], mod.modname, cls)
    return key is not None and key in held


@register("concurrency")
def check_concurrency(ctx: AnalysisContext) -> List[Finding]:
    modules = build_module_index(ctx)
    package = ctx.package_name
    inv = _Inventory(modules)
    domains = _discover_domains(modules)
    if not domains:
        ctx.options.setdefault("notes", []).append(
            "concurrency: no thread domains discovered (no Thread/"
            "weakref/hook entry points)")
        return []

    # close each domain over the call graph
    closures: Dict[str, Dict[FnKey, str]] = {
        d: call_closure(modules, seeds, package)
        for d, seeds in domains.items()}
    fn_domains: Dict[FnKey, Set[str]] = {}
    fn_desc: Dict[FnKey, str] = {}
    for d, closed in closures.items():
        for key, desc in closed.items():
            fn_domains.setdefault(key, set()).add(d)
            fn_desc.setdefault(key, desc)

    # lexical scans for every domain function that resolves to source
    scans: Dict[FnKey, _FnScan] = {}
    for key in fn_domains:
        mod = modules.get(key[0])
        fn = mod.lookup(key[1]) if mod is not None else None
        if fn is not None:
            scans[key] = _FnScan(fn, mod, inv, key[1])

    # inherited locks: a function ALL of whose visible call sites hold
    # lock L runs under L (the _pick_locked "caller holds the lock"
    # idiom); entry-point seeds are externally invoked -> no locks.
    from .core import called_functions
    seeds_all: Set[FnKey] = set()
    for seed_map in domains.values():
        seeds_all.update(seed_map)
    inherited: Dict[FnKey, frozenset] = {k: frozenset() for k in scans}
    for _ in range(6):
        changed = False
        site_locks: Dict[FnKey, List[frozenset]] = {}
        for key, scan in scans.items():
            mod = modules[key[0]]
            self_cls = key[1].split(".", 1)[0] if "." in key[1] else None
            for site in scan.calls:
                for callee in called_functions(site.node, mod, modules,
                                               self_cls):
                    if callee in scans:
                        site_locks.setdefault(callee, []).append(
                            frozenset(site.held) |
                            inherited.get(key, frozenset()))
        for key in scans:
            if key in seeds_all:
                new = frozenset()
            else:
                sites = site_locks.get(key)
                new = frozenset.intersection(*sites) if sites \
                    else frozenset()
            if new != inherited[key]:
                inherited[key] = new
                changed = True
        if not changed:
            break

    def held_at(key: FnKey, site_held: frozenset) -> frozenset:
        return frozenset(site_held) | inherited.get(key, frozenset())

    findings: Set[Tuple] = set()  # (rule, path, line, message)

    def add(rule, key, line, message):
        findings.add((f"concurrency/{rule}",
                      modules[key[0]].sf.rel, line, message))

    # -- shared-state rules (attrs per class, globals per module) -------
    def _domains_str(dset: Set[str]) -> str:
        return "/".join(sorted(dset))

    # group accesses
    attr_sites: Dict[Tuple[str, str, str], List] = {}
    global_sites: Dict[Tuple[str, str], List] = {}
    for key, scan in scans.items():
        dset = fn_domains[key]
        in_init = key[1].endswith(".__init__") or \
            key[1].endswith(".__new__")
        cls = key[1].split(".", 1)[0] if "." in key[1] else None
        if cls is not None and not in_init:
            for acc in scan.attr_acc:
                attr_sites.setdefault((key[0], cls, acc.name),
                                      []).append((key, acc, dset))
        if not in_init:
            for acc in scan.global_acc:
                global_sites.setdefault((key[0], acc.name),
                                        []).append((key, acc, dset))

    def _check_shared(sites, desc):
        writes = [(k, a, d) for k, a, d in sites if a.write]
        if not writes:
            return
        union: Set[str] = set()
        for _k, _a, d in sites:
            union |= d
        if len(union) < 2 and not (union & set(SELF_CONCURRENT)):
            return
        locked_writes = [(k, a, d) for k, a, d in writes
                         if held_at(k, a.held)]
        if not locked_writes:
            seen = set()
            for k, a, _d in writes:
                if (k[0], a.line) not in seen:
                    seen.add((k[0], a.line))
                    add("unlocked-shared-write", k, a.line,
                        f"{desc} is written with no lock but is "
                        f"reachable from the {_domains_str(union)} "
                        f"thread domains ({fn_desc[k]})")
            return
        # the guard is the lock(s) held at EVERY locked write — the
        # intersection, not the union: two writers under two different
        # locks do not exclude each other, and a reader must hold the
        # common write lock, not just "some lock a writer once held"
        helds = [set(held_at(k, a.held)) for k, a, _d in locked_writes]
        guard = set.intersection(*helds)
        if not guard:
            seen = set()
            for k, a, _d in locked_writes:
                if (k[0], a.line) not in seen:
                    seen.add((k[0], a.line))
                    add("lock-discipline", k, a.line,
                        f"{desc} is written under inconsistent locks — "
                        f"no single lock covers every write, so the "
                        f"writers do not exclude each other (domains "
                        f"{_domains_str(union)}; via {fn_desc[k]})")
            return
        seen = set()
        for k, a, _d in sites:
            if not (held_at(k, a.held) & guard) and \
                    (k[0], a.line) not in seen:
                seen.add((k[0], a.line))
                kind = "written" if a.write else "read"
                add("lock-discipline", k, a.line,
                    f"{desc} is written under a lock elsewhere but "
                    f"{kind} here with no lock (domains "
                    f"{_domains_str(union)}; via {fn_desc[k]})")

    for (modname, cls, attr), sites in sorted(attr_sites.items()):
        _check_shared(sites, f"attribute {cls}.{attr}")
    for (modname, name), sites in sorted(global_sites.items()):
        if ("mod", modname, name) in inv.locks:
            continue  # the lock objects themselves
        _check_shared(sites, f"module global {name}")

    # -- blocking-under-lock (transitive through the call graph) --------
    blocking: Dict[FnKey, str] = {}
    for key, scan in scans.items():
        mod = modules[key[0]]
        cls = key[1].split(".", 1)[0] if "." in key[1] else None
        for site in scan.calls:
            prim = _blocking_primitive(site, inv, mod, cls)
            if prim is not None and not _held_lock_wait(
                    site, inv, mod, cls, held_at(key, site.held)):
                blocking.setdefault(key, prim)
                break
    for _ in range(8):
        changed = False
        for key, scan in scans.items():
            if key in blocking:
                continue
            mod = modules[key[0]]
            self_cls = key[1].split(".", 1)[0] if "." in key[1] else None
            for site in scan.calls:
                for callee in called_functions(site.node, mod, modules,
                                               self_cls):
                    if callee in blocking:
                        blocking[key] = \
                            f"{callee[1]} -> {blocking[callee]}"
                        changed = True
                        break
                if key in blocking:
                    break
        if not changed:
            break

    for key, scan in scans.items():
        mod = modules[key[0]]
        self_cls = key[1].split(".", 1)[0] if "." in key[1] else None
        for site in scan.calls:
            held = held_at(key, site.held)
            if not held:
                continue
            prim = _blocking_primitive(site, inv, mod, self_cls)
            if prim is not None:
                if not _held_lock_wait(site, inv, mod, self_cls, held):
                    add("blocking-under-lock", key, site.line,
                        f"{prim} while holding a lock "
                        f"(in {key[1]}, via {fn_desc[key]})")
                continue
            for callee in called_functions(site.node, mod, modules,
                                           self_cls):
                if callee in blocking:
                    add("blocking-under-lock", key, site.line,
                        f"call to {callee[1]} blocks "
                        f"({blocking[callee]}) while holding a lock "
                        f"(in {key[1]})")
                    break

    # -- unstamped contextvar reads in thread-entry domains -------------
    for domain, closed in closures.items():
        if domain == "api":
            continue  # caller context: the submitter's own stamps hold
        # name-level matching: a contextvar imported into another
        # module reads as `_var.get()` with the READER's module in the
        # key, so keying on (declaring_module, name) would blind the
        # rule to exactly the cross-module reads worker code makes
        cv_names = {n for _, n in inv.contextvars}
        sets_: Set[str] = set()
        reads: List[Tuple[FnKey, str, int]] = []
        for key in closed:
            scan = scans.get(key)
            if scan is None:
                continue
            for site in scan.calls:
                chain = site.chain
                if len(chain) == 2 and chain[0] in cv_names:
                    if chain[1] == "set":
                        sets_.add(chain[0])
                    elif chain[1] == "get":
                        reads.append((key, chain[0], site.line))
        for key, var, line in reads:
            if var not in sets_:
                add("unstamped-contextvar", key, line,
                    f"contextvar {var} read in thread domain "
                    f"{domain} whose closure never set()s it — a "
                    f"fresh thread sees the default, not the "
                    f"submitter's stamp (via {fn_desc[key]})")

    # -- finalizer hazards ----------------------------------------------
    for key in closures.get("finalizer", {}):
        scan = scans.get(key)
        if scan is None:
            continue
        mod = modules[key[0]]
        cls = key[1].split(".", 1)[0] if "." in key[1] else None
        for lock_key, line, _outer in scan.with_locks:
            if not inv.locks.get(lock_key, True):
                add("finalizer-hazard", key, line,
                    f"GC finalizer path acquires non-reentrant "
                    f"threading.Lock {lock_key[-1]} — a callback "
                    f"firing on a thread inside this critical section "
                    f"deadlocks against itself; use RLock "
                    f"(via {fn_desc[key]})")
        for site in scan.calls:
            chain = site.chain
            if chain[-1] == "acquire":
                lk = inv.lock_of(chain[:-1], mod.modname, cls)
                if lk is not None and not inv.locks.get(lk, True):
                    add("finalizer-hazard", key, site.line,
                        f"GC finalizer path acquires non-reentrant "
                        f"lock {lk[-1]} (via {fn_desc[key]})")
            elif chain[0] == "jax":
                add("finalizer-hazard", key, site.line,
                    f"jax dispatch {'.'.join(chain)} inside a GC "
                    f"finalizer — device work at arbitrary GC points "
                    f"(via {fn_desc[key]})")

    ctx.options.setdefault("notes", []).append(
        "concurrency: domains " + ", ".join(
            f"{d}={len(c)}" for d, c in sorted(closures.items())) +
        f"; {len(scans)} functions analyzed, "
        f"{len(inv.locks)} locks, {len(inv.contextvars)} contextvars")

    return [Finding(rule=r, path=p, line=ln, message=m)
            for r, p, ln, m in sorted(findings)]
