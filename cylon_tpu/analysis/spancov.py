"""Span-coverage lint: observability-bearing code paths must be spanned.

The telemetry layer is only as good as its coverage: a distributed
operator that runs outside any span is invisible to the phase log, the
Perfetto trace, `collect_phases` shuffle counting AND the per-query
EXPLAIN ANALYZE report — and the gap is silent, because nothing fails.
This checker makes the coverage contract static:

* every public ``distributed_*`` function in ``parallel/dist_ops.py``
  must contain at least one ``with``-span (``telemetry.span`` /
  ``telemetry.phase``, any alias);
* every executor lowering (``_do_*`` method in ``plan/executor.py``)
  must do the same — the lowering's span is what carries the
  ``plan.shuffle.*`` labels the shuffle-count acceptance tests pin.

A span "anywhere in the body" is deliberately the whole bar: several
operators open their spans conditionally (world-1 short circuits
return before any exchange), and requiring per-branch coverage would
force spans around no-op paths the label-honesty discipline
(executor docstring) explicitly keeps silent. What the lint catches is
the real failure mode — a NEW operator or lowering added with no
telemetry at all.

Fixture trees exercise it through the same scope table via
``options["span_scopes"]``.
"""
from __future__ import annotations

import ast
from typing import List, Tuple

from .core import AnalysisContext, Finding, register

# (package-relative file, kind, name-prefix); kind "function" scans
# module-level defs, "method" scans defs nested in classes
DEFAULT_SCOPES: Tuple[Tuple[str, str, str], ...] = (
    ("parallel/dist_ops.py", "function", "distributed_"),
    ("plan/executor.py", "method", "_do_"),
)

# call names that open a span: the telemetry API (span/phase) under the
# repo's import aliases (_span/_phase), as bare names or attributes
# (telemetry.span(...))
_SPAN_CALL_NAMES = frozenset({"span", "_span", "phase", "_phase"})


def _is_span_with(stmt: ast.stmt) -> bool:
    if not isinstance(stmt, (ast.With, ast.AsyncWith)):
        return False
    for item in stmt.items:
        call = item.context_expr
        if not isinstance(call, ast.Call):
            continue
        fn = call.func
        name = fn.id if isinstance(fn, ast.Name) else \
            fn.attr if isinstance(fn, ast.Attribute) else None
        if name in _SPAN_CALL_NAMES:
            return True
    return False


def _has_span(fn_node: ast.FunctionDef) -> bool:
    return any(_is_span_with(n) for n in ast.walk(fn_node))


def _targets(tree: ast.AST, kind: str, prefix: str):
    if kind == "function":
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name.startswith(prefix):
                yield node
        return
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)) \
                        and sub.name.startswith(prefix):
                    yield sub


@register("span-coverage")
def check_span_coverage(ctx: AnalysisContext) -> List[Finding]:
    scopes = ctx.options.get("span_scopes", DEFAULT_SCOPES)
    by_rel = {f.rel: f for f in ctx.files()}
    findings: List[Finding] = []
    for rel, kind, prefix in scopes:
        f = by_rel.get(rel)
        if f is None:
            continue
        for fn in _targets(f.tree, kind, prefix):
            if not _has_span(fn):
                what = "executor lowering" if kind == "method" \
                    else "distributed op"
                findings.append(Finding(
                    rule="span-coverage/missing-span", path=rel,
                    line=fn.lineno,
                    message=f"{what} {fn.name}() runs under no "
                            f"telemetry span: it is invisible to the "
                            f"phase log, collect_phases counting and "
                            f"EXPLAIN ANALYZE — wrap the operative "
                            f"path in telemetry.span/phase"))
    return findings
