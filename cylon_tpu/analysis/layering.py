"""Layering lints: declarative per-subsystem import contracts.

The paper's *local kernel + shuffle + local kernel* decomposition only
stays sound while each layer reaches the one below through its declared
seam (SURVEY §1): device kernels (`ops/`) are reached through
`parallel/dist_ops`, `data/table`, and `table_api` — the layers that
own key preparation, shuffle routing, witness semantics and capacity
policy. Each `LayerContract` below states one such seam as data; the
checker is a single AST pass that resolves every import (absolute and
relative) to a package-relative module path and matches it against the
contract table. `scripts/check_plan_imports.py` — the original ad-hoc
gate this generalizes — now delegates to the ``plan-no-ops`` rule.

Contracts are matched against the *package root* of the analysis
context, so the same checker runs against fixture trees with seeded
violations (tests/analysis_fixtures/).
"""
from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import List, Optional, Tuple

from .core import (AnalysisContext, Finding, importer_package, register,
                   resolve_import)


@dataclass(frozen=True)
class LayerContract:
    """One import ban: modules in ``scope`` must not import any module
    matching a ``forbid`` prefix (package-relative dotted paths).

    ``scope`` is a subsystem directory ("ops"), or a tuple of top-level
    module names for file-scoped contracts. ``exempt`` lists filenames
    inside the scope that are deliberately outside the contract — each
    with a reason in the table below, because an undocumented exemption
    is just a hole. ``allow`` lists prefixes carved OUT of ``forbid``:
    a leaf PACKAGE (telemetry/) forbids everything but must still
    import its own submodules."""

    name: str
    scope: Tuple[str, ...]
    forbid: Tuple[str, ...]
    reason: str
    exempt: Tuple[str, ...] = ()
    allow: Tuple[str, ...] = ()


# The cylon_tpu layer map. Order: kernels at the bottom, facades above.
DEFAULT_CONTRACTS: Tuple[LayerContract, ...] = (
    LayerContract(
        name="base-leaf",
        scope=("status.py", "dtypes.py", "util.py", "native.py",
               "memory.py"),
        forbid=("",),  # any intra-package import...
        allow=("telemetry.knobs",),
        # ...except the declared knob registry, itself a stdlib-only
        # leaf (memory.py reads CYLON_HBM_BYTES through it; telemetry
        # never imports back, so no cycle seed)
        reason="base-layer modules are leaves: everything imports them, "
               "so any import back into the package is a cycle seed "
               "(the stdlib-only knob registry telemetry.knobs is the "
               "one sanctioned exception)",
    ),
    LayerContract(
        name="telemetry-leaf",
        scope=("telemetry",),
        forbid=("",),            # any intra-package import...
        allow=("telemetry", "status"),
        # ...except telemetry's own submodules and the error taxonomy:
        # status.py is itself a pure stdlib leaf (base-leaf contract),
        # so telemetry -> status cannot seed a cycle — the statistics
        # warehouse quarantines corrupt snapshots with a typed
        # CylonDataError event instead of a stringly-typed one
        reason="telemetry is a base-layer LEAF grown into a package "
               "(spans/metrics/export): everything instruments through "
               "it, so any import back into the package is a cycle "
               "seed — gauges sample MemoryPool duck-typed, never by "
               "importing memory.py; the stdlib-only error taxonomy "
               "status.py is the one sanctioned sibling",
    ),
    LayerContract(
        name="ops-leaf",
        scope=("ops",),
        forbid=("parallel", "plan", "io", "table_api", "arrow_builder",
                "context"),
        reason="ops/ kernels are mesh-oblivious device code; sharding, "
               "exchange routing and registry policy live strictly above "
               "them",
    ),
    LayerContract(
        name="data-below-ops",
        scope=("data",),
        forbid=("ops", "parallel", "plan", "io", "table_api"),
        exempt=("table.py",),  # the eager operator facade: Table methods
        #        ARE the sanctioned seam that lowers onto ops/parallel
        reason="columnar storage (column/strings/row) must not reach "
               "into kernels or distribution — only the Table facade "
               "lowers",
    ),
    LayerContract(
        name="io-no-kernels",
        scope=("io",),
        forbid=("ops", "plan"),
        reason="ingest builds tables and may distribute them, but never "
               "invokes kernels or plans directly",
    ),
    LayerContract(
        name="parallel-no-plan",
        scope=("parallel",),
        forbid=("plan",),
        exempt=("task_plan.py",),  # legacy shim: absorbed as plan.tasks
        #        in PR 1, kept only to re-export the moved names
        reason="the plan subsystem lowers ONTO parallel/; an upward "
               "import would cycle the lowering contract",
    ),
    LayerContract(
        name="plan-no-ops",
        scope=("plan",),
        forbid=("ops",),
        reason="plan/ reaches device kernels only through dist_ops/"
               "table_api — a direct ops/ import would bypass lane "
               "pairing, witness semantics and emit-mask discipline and "
               "silently fork the execution paths the bit-identity "
               "tests compare",
    ),
    LayerContract(
        name="resilience-below-exec",
        scope=("resilience",),
        forbid=("",),                 # any intra-package import...
        allow=("resilience", "status", "telemetry"),
        # ...except its own submodules, the error taxonomy and the
        # telemetry leaf it records into
        reason="the resilience layer (inject/retry/admission) sits "
               "between the base leaves and the execution layers: "
               "parallel/, plan/ and io/ call INTO it — an import of "
               "the machinery it wraps would cycle the retry seam",
    ),
    LayerContract(
        name="service-top",
        scope=("service",),
        forbid=("",),                 # any intra-package import...
        allow=("service", "plan", "resilience", "telemetry", "status"),
        # ...except its own submodules and the seams it schedules
        # through: plans (optimize/execute/preflight), the admission/
        # retry machinery, the telemetry leaf and the error taxonomy
        reason="the service tier is the TOP of the stack: it submits "
               "plans and records decisions, but must never reach "
               "device machinery (ops/parallel/data/io) directly — "
               "execution goes through plan/'s executor seam only",
    ),
    LayerContract(
        name="below-service",
        scope=("ops", "data", "parallel", "plan", "io", "resilience",
               "telemetry", "analysis"),
        forbid=("service",),
        reason="everything below the service tier must stay importable "
               "without it; plan/ holds only a late-bound optimize-memo "
               "hook (lazy.set_plan_memo) that service/ registers — an "
               "upward import would cycle the scheduler's execution "
               "seam",
    ),
    LayerContract(
        name="analysis-read-only",
        scope=("analysis",),
        forbid=("data", "io", "table_api", "arrow_builder"),
        reason="the analysis suite inspects plans and traced programs; "
               "pulling in table storage or ingest would let checkers "
               "depend on the machinery they are supposed to check",
    ),
)

# Modules whose UNDERSCORE names are private to the module: importing or
# attribute-accessing them from elsewhere is a finding. telemetry's span
# internals (_collectors, _sinks, _current) are the motivating case — a
# second writer would race the identity-keyed unregistration discipline.
# Matching is by PREFIX: after the module→package split, "telemetry"
# covers telemetry.spans / telemetry.metrics / telemetry.export too
# (and any future submodule), and every file under telemetry/ is an
# owner allowed to touch its siblings' internals.
PRIVATE_MODULES: Tuple[str, ...] = ("telemetry",)


def _is_private_target(target: str, private_modules) -> Optional[str]:
    """The owning private module when ``target`` is one (or a submodule
    of one), else None."""
    for pm in private_modules:
        if target == pm or target.startswith(pm + "."):
            return pm
    return None


def _matches(target: str, prefix: str) -> bool:
    if prefix == "":
        return True
    return target == prefix or target.startswith(prefix + ".")


def _contract_for(rel: str, contracts) -> List[LayerContract]:
    """Contracts whose scope covers this package-relative file path."""
    out = []
    parts = rel.split("/")
    for c in contracts:
        if len(parts) == 1:
            if parts[0] in c.scope:
                out.append(c)
        elif parts[0] in c.scope and parts[-1] not in c.exempt:
            out.append(c)
    return out


def _iter_imports(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                yield node.lineno, alias.name, 0, (alias.name,)
        elif isinstance(node, ast.ImportFrom):
            names = tuple(a.name for a in node.names)
            yield node.lineno, node.module or "", node.level, names


@register("layering")
def check_layering(ctx: AnalysisContext) -> List[Finding]:
    contracts = ctx.options.get("contracts", DEFAULT_CONTRACTS)
    private_modules = ctx.options.get("private_modules", PRIVATE_MODULES)
    package = ctx.package_name
    findings: List[Finding] = []

    for f in ctx.files():
        mod = ctx.module_name(f)
        importer_pkg = importer_package(f.rel, ctx.module_name(f))
        active = _contract_for(f.rel, contracts)
        is_private_owner = _is_private_target(mod, private_modules) \
            is not None

        for lineno, module, level, names in _iter_imports(f.tree):
            target = resolve_import(module, level, importer_pkg, package)
            if target is None:
                continue
            # the imported name may itself be a submodule
            # ("from ..ops import join" targets ops.join)
            sub_targets = [target] + [
                (target + "." + n) if target else n for n in names]
            for c in active:
                hits = [t for t in sub_targets
                        if any(_matches(t, p) for p in c.forbid)
                        and not any(_matches(t, a) for a in c.allow)]
                if hits:
                    hit = max(hits, key=len)  # most specific module
                    dotted = f"{package}.{hit}" if hit else package
                    findings.append(Finding(
                        rule=f"layering/{c.name}", path=f.rel, line=lineno,
                        message=f"imports {dotted}: {c.reason}"))
                    break
            # private-name imports from privacy-owning modules (or any
            # of their submodules, post package split)
            pm = _is_private_target(target, private_modules)
            if pm is not None and not is_private_owner:
                for n in names:
                    if n.startswith("_"):
                        findings.append(Finding(
                            rule="layering/private-internals",
                            path=f.rel, line=lineno,
                            message=f"imports private name "
                                    f"{package}.{target}.{n}: only "
                                    f"{pm}'s own modules may touch "
                                    f"its internals"))

        if not is_private_owner:
            findings.extend(_private_attr_access(ctx, f, private_modules))
    return findings


def _private_attr_access(ctx: AnalysisContext, f, private_modules
                         ) -> List[Finding]:
    """Flag ``telemetry._collectors``-style attribute reads: find names
    bound to a privacy-owning module (or any of its submodules — the
    package form, ``telemetry.spans._collectors``) by import, then any
    ``name._attr`` access on them."""
    package = ctx.package_name
    importer_pkg = importer_package(f.rel, ctx.module_name(f))
    bound = {}  # local name -> package-relative module path
    for node in ast.walk(f.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                target = resolve_import(alias.name, 0, importer_pkg,
                                         package)
                if target is not None and \
                        _is_private_target(target, private_modules):
                    bound[alias.asname or alias.name.split(".")[-1]] = target
        elif isinstance(node, ast.ImportFrom):
            target = resolve_import(node.module or "", node.level,
                                     importer_pkg, package)
            if target is None:
                continue
            for alias in node.names:
                sub = (target + "." + alias.name) if target else alias.name
                if _is_private_target(sub, private_modules):
                    bound[alias.asname or alias.name] = sub
    if not bound:
        return []
    out = []
    for node in ast.walk(f.tree):
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id in bound and node.attr.startswith("_"):
            mod = bound[node.value.id]
            pm = _is_private_target(mod, private_modules)
            out.append(Finding(
                rule="layering/private-internals", path=f.rel,
                line=node.lineno,
                message=f"touches {package}.{mod}.{node.attr}: only "
                        f"{pm}'s own modules may touch its internals"))
    return out
