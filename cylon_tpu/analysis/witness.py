"""Plan-witness checker: drives `plan/verify.py` over a plan corpus.

The verifier itself (optimizer-independent witness re-derivation) lives
in `cylon_tpu/plan/verify.py` so the optimizer's debug assert can use
it without an upward import. This checker family gives it a standing
corpus to run against on every `python -m cylon_tpu.analysis`:

1. *Canonical pipelines* — symbolic plans (raw IR `Scan`s with schema /
   dtype / witness snapshots, no tables, no devices) covering the
   optimizer's rewrite space: elision via witnessed scans, string keys,
   promoting joins, filter pushdown, projection pruning, set ops. Each
   is optimized and must verify CLEAN — a violation here means the
   optimizer itself produced an unjustified elision.
2. *Randomized plans* — a seeded generator builds arbitrary deep
   pipelines (random dtypes, random witnesses, random operator mix);
   every optimizer output must verify clean. This is the property-test
   form of the soundness argument.
3. *Self-checks* — hand-mutated plans (a join-side `Shuffle` deleted
   with no witness to justify it; a witness snapshot stripped after
   elision) that the verifier MUST reject. If it accepts one, the
   verifier has gone blind and the checker fails the run — the suite
   checks itself.

Fixture modules (tests) may override the corpus via the
``witness_plan_module`` option: the module's ``build_plans()`` returns
``(name, root, world, expect_clean)`` tuples.
"""
from __future__ import annotations

import random
from typing import Callable, List, Optional, Tuple

from .core import AnalysisContext, Finding, register

_PATH = "plan/optimizer.py"     # findings anchor at the elision pass

_DTYPES = ["int32", "int64", "float32"]


def _scan(types, witness_cols=None, world: int = 4,
          name: str = "t"):
    from ..plan import ir

    schema = [f"c{i}" for i in range(len(types))]
    sig = None
    if witness_cols is not None:
        sig = (tuple(witness_cols),
               tuple(types[c] for c in witness_cols), world)
    return ir.Scan(name, schema, list(types), witness_sig=sig)


def canonical_plans(world: int = 4) -> List[Tuple[str, Callable]]:
    """(name, build()) pairs; build returns a LOGICAL plan root."""
    from ..plan import ir
    from ..plan.ir import col

    def join_groupby_same_keys():
        l = _scan(["int32", "float32", "int32"])
        r = _scan(["int32", "int32"], name="r")
        j = ir.Join(l, r, [0], [0])
        return ir.GroupBy(j, [0], [4], ["sum"])

    def witnessed_both_sides():
        l = _scan(["int32", "float32"], witness_cols=[0], world=world)
        r = _scan(["int32", "int32"], witness_cols=[0], world=world,
                  name="r")
        j = ir.Join(l, r, [0], [0])
        return ir.GroupBy(j, [0], [3], ["sum"])

    def string_keys_never_elide():
        l = _scan([ir.STR_TYPE, "int32"])
        r = _scan([ir.STR_TYPE, "int64"], name="r")
        return ir.Join(l, r, [0], [0])

    def promoting_join_witnessed_left():
        # left witnessed on int32 k; right key is int64: alignment
        # promotes, so the witness must NOT justify an elision
        l = _scan(["int32", "float32"], witness_cols=[0], world=world)
        r = _scan(["int64", "int32"], name="r")
        return ir.Join(l, r, [0], [0])

    def filter_pushdown_prune():
        l = _scan(["int32", "float32", "int32"])
        r = _scan(["int32", "int32"], name="r")
        f = ir.Filter(ir.Shuffle(l, [0]), (col(2) > 5).bind(lambda p: p))
        j = ir.Join(f, r, [0], [0])
        return ir.GroupBy(j, [0], [4], ["mean"])

    def user_shuffle_then_join():
        l = _scan(["int32", "int64"])
        r = _scan(["int32", "float32"], name="r")
        return ir.Join(ir.Shuffle(l, [0]), r, [0], [0])

    def setop_sort():
        a = _scan(["int32", "int32"])
        b = _scan(["int32", "int32"], name="b")
        return ir.Sort(ir.SetOp(a, b, "union"), [0], True)

    def groupby_after_witnessed_scan():
        t = _scan(["int32", "float32"], witness_cols=[0], world=world)
        return ir.GroupBy(t, [0], [1], ["sum"])

    return [(f.__name__, f) for f in (
        join_groupby_same_keys, witnessed_both_sides,
        string_keys_never_elide, promoting_join_witnessed_left,
        filter_pushdown_prune, user_shuffle_then_join, setop_sort,
        groupby_after_witnessed_scan)]


def random_plan(rng: random.Random, world: int):
    """One random logical plan: scans with random dtypes/witnesses under
    a random operator stack."""
    from ..plan import ir

    def scan():
        width = rng.randint(2, 4)
        types = [rng.choice(_DTYPES + [ir.STR_TYPE]) for _ in range(width)]
        witness = None
        hashable = [i for i, t in enumerate(types) if t != ir.STR_TYPE]
        if hashable and rng.random() < 0.5:
            k = rng.randint(1, min(2, len(hashable)))
            witness = rng.sample(hashable, k)
        return _scan(types, witness_cols=witness, world=world,
                     name=f"t{rng.randrange(1 << 16)}")

    def grow(node, depth):
        if depth <= 0:
            return node
        roll = rng.random()
        if roll < 0.35 and node.width >= 1:
            other = scan()
            li = rng.randrange(node.width)
            rj = rng.randrange(other.width)
            how = rng.choice(["inner", "left", "right"])
            node = ir.Join(node, other, [li], [rj], how)
        elif roll < 0.55:
            keys = [rng.randrange(node.width)]
            aggable = [i for i in range(node.width) if i not in keys]
            if aggable:
                node = ir.GroupBy(node, keys, [rng.choice(aggable)],
                                  [rng.choice(["sum", "count", "max"])])
        elif roll < 0.7:
            node = ir.Shuffle(node, [rng.randrange(node.width)])
        elif roll < 0.85:
            keep = sorted(rng.sample(range(node.width),
                                     rng.randint(1, node.width)))
            node = ir.Project(node, keep)
        else:
            node = ir.Sort(node, [rng.randrange(node.width)], True)
        return grow(node, depth - 1)

    return grow(scan(), rng.randint(1, 4))


def mutate_delete_shuffle(root, rng: Optional[random.Random] = None,
                          world: int = 4) -> bool:
    """Delete one join-side Shuffle whose input carries no witness —
    the canonical unjustified elision. Returns True when a mutation
    site existed."""
    from ..plan import ir
    from ..plan.verify import derive_witness

    sites = []
    for node in ir.walk(root):
        if isinstance(node, ir.Join):
            for side in (0, 1):
                c = node.children[side]
                if isinstance(c, ir.Shuffle) and \
                        derive_witness(c.children[0], world) is None:
                    sites.append((node, side))
    if not sites:
        return False
    node, side = sites[0] if rng is None else rng.choice(sites)
    node.children[side] = node.children[side].children[0]
    return True


@register("witness")
def check_witness(ctx: AnalysisContext) -> List[Finding]:
    from ..plan.ir import format_plan
    from ..plan.optimizer import optimize
    from ..plan.verify import verify_plan
    from ..status import CylonError

    world = int(ctx.options.get("world", 4))
    findings: List[Finding] = []
    notes: List[str] = ctx.options.setdefault("notes", [])

    plan_module = ctx.options.get("witness_plan_module")
    if plan_module is not None:
        # fixture mode: every verification problem IS a finding (the
        # seeded violation surfacing — non-zero exit), and a seeded-bad
        # plan the verifier ACCEPTS is a finding about the verifier
        for name, root, w, expect_clean in \
                _load_plan_module(plan_module):
            problems = verify_plan(root, w)
            for p in problems:
                findings.append(Finding(
                    rule="witness/unjustified-elision", path=_PATH,
                    line=1, message=f"{name}: {p}"))
            if not expect_clean and not problems:
                findings.append(Finding(
                    rule="witness/verifier-blind", path=_PATH, line=1,
                    message=f"{name}: verifier accepted a plan seeded "
                            f"with an unjustified elision"))
        return findings

    # 1. canonical pipelines: optimizer output must verify clean
    for name, build in canonical_plans(world):
        try:
            root, _stats = optimize(build(), world)
        except CylonError as e:
            findings.append(Finding(
                rule="witness/unjustified-elision", path=_PATH, line=1,
                message=f"canonical[{name}]: optimizer output failed "
                        f"verification: {e}"))
            continue
        problems = verify_plan(root, world)
        for p in problems:
            findings.append(Finding(
                rule="witness/unjustified-elision", path=_PATH, line=1,
                message=f"canonical[{name}]: {p}"))

    # 2. randomized property sweep (seeded — deterministic output)
    rng = random.Random(int(ctx.options.get("seed", 0xC11)))
    n_random = int(ctx.options.get("random_plans", 64))
    rejected = 0
    for i in range(n_random):
        logical = random_plan(rng, world)
        try:
            root, _stats = optimize(logical, world)
        except CylonError as e:
            findings.append(Finding(
                rule="witness/unjustified-elision", path=_PATH, line=1,
                message=f"random[{i}]: optimizer output failed "
                        f"verification: {e}"))
            continue
        problems = verify_plan(root, world)
        for p in problems:
            findings.append(Finding(
                rule="witness/unjustified-elision", path=_PATH, line=1,
                message=f"random[{i}]:\n{format_plan(root)}\n  {p}"))
        # 3. self-check: the same plan with one exchange deleted must
        # be REJECTED — otherwise the verifier has gone blind
        if not problems and mutate_delete_shuffle(root, rng, world):
            if not verify_plan(root, world):
                findings.append(Finding(
                    rule="witness/verifier-blind", path=_PATH, line=1,
                    message=f"random[{i}]: verifier accepted a plan "
                            f"whose join-side shuffle was deleted "
                            f"without a witness:\n{format_plan(root)}"))
            else:
                rejected += 1
    notes.append(f"witness: {len(canonical_plans(world))} canonical + "
                 f"{n_random} random plans verified; {rejected} "
                 f"mutations correctly rejected")
    return findings


def _load_plan_module(path: str):
    import importlib.util

    spec = importlib.util.spec_from_file_location("_cylint_plans", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.build_plans()
