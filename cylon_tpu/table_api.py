"""String-id table registry — the bindings-facing operator API.

Mirrors the reference's `table_api` (reference: cpp/src/cylon/
table_api.hpp:38-195, table_api.cpp:37-393): a global mutex-guarded
``map<string, Table>`` with id-keyed wrappers around every operator, kept
for parity with language bindings that pass handles rather than objects
(the reference's JNI layer, java/src/main/native/src/Table.cpp:37-46).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from .context import CylonContext
from .data.table import Table, join as _join_free, set_op as _set_op
from .ops import join as _join
from .ops import setops as _setops
from .status import Code, CylonError, Status

_tables: Dict[str, Table] = {}
_lock = threading.Lock()


def put_table(table_id: str, table: Table) -> None:
    """Reference: PutTable (table_api.cpp:40-47)."""
    with _lock:
        _tables[table_id] = table


def get_table(table_id: str) -> Table:
    """Reference: GetTable (table_api.cpp:49-57)."""
    with _lock:
        t = _tables.get(table_id)
    if t is None:
        raise CylonError(Code.KeyError, f"no table registered as {table_id!r}")
    return t


def remove_table(table_id: str) -> None:
    """Reference: RemoveTable (table_api.cpp:59-64)."""
    with _lock:
        _tables.pop(table_id, None)


def new_table_id(prefix: str = "t") -> str:
    """Fresh unique registry id (reference: util/uuid.hpp generate_uuid —
    the reference mints ids for intermediate JNI tables; callers here may
    also pass their own)."""
    import uuid

    return f"{prefix}-{uuid.uuid4().hex[:12]}"


def registered_ids() -> List[str]:
    with _lock:
        return sorted(_tables)


# ---------------------------------------------------------------------------
# id-keyed operator wrappers (table_api.hpp:38-195)
# ---------------------------------------------------------------------------

def read_csv(ctx: CylonContext, path: str, table_id: str,
             options=None) -> Status:
    from .io.csv import read_csv as _read

    put_table(table_id, _read(ctx, path, options))
    return Status.OK()


def write_csv(table_id: str, path: str, options=None) -> Status:
    from .io.csv import write_csv as _write

    _write(get_table(table_id), path, options)
    return Status.OK()


def join_tables(left_id: str, right_id: str, join_config: _join.JoinConfig,
                out_id: str) -> Status:
    """Reference: JoinTables (table_api.cpp:131-156)."""
    put_table(out_id, _join_free(get_table(left_id), get_table(right_id),
                                 join_config))
    return Status.OK()


def distributed_join_tables(left_id: str, right_id: str,
                            join_config: _join.JoinConfig,
                            out_id: str) -> Status:
    from .parallel.dist_ops import distributed_join

    put_table(out_id, distributed_join(get_table(left_id),
                                       get_table(right_id), join_config))
    return Status.OK()


def _setop_api(op: _setops.SetOp, distributed: bool):
    def fn(left_id: str, right_id: str, out_id: str) -> Status:
        left, right = get_table(left_id), get_table(right_id)
        if distributed:
            from .parallel.dist_ops import distributed_set_op

            put_table(out_id, distributed_set_op(left, right, op))
        else:
            put_table(out_id, _set_op(left, right, op))
        return Status.OK()
    return fn


union_tables = _setop_api(_setops.SetOp.UNION, False)
distributed_union_tables = _setop_api(_setops.SetOp.UNION, True)
subtract_tables = _setop_api(_setops.SetOp.SUBTRACT, False)
distributed_subtract_tables = _setop_api(_setops.SetOp.SUBTRACT, True)
intersect_tables = _setop_api(_setops.SetOp.INTERSECT, False)
distributed_intersect_tables = _setop_api(_setops.SetOp.INTERSECT, True)


def sort_table(table_id: str, out_id: str, column, ascending=True) -> Status:
    put_table(out_id, get_table(table_id).sort(column, ascending=ascending))
    return Status.OK()


def select_table(table_id: str, out_id: str, predicate) -> Status:
    put_table(out_id, get_table(table_id).select(predicate))
    return Status.OK()


def project_table(table_id: str, out_id: str, columns) -> Status:
    put_table(out_id, get_table(table_id).project(columns))
    return Status.OK()


def shuffle_table(table_id: str, hash_columns, out_id: str) -> Status:
    from .parallel.dist_ops import shuffle

    put_table(out_id, shuffle(get_table(table_id), hash_columns))
    return Status.OK()


def hash_partition_table(table_id: str, hash_columns, num_partitions: int,
                         out_prefix: str) -> Status:
    """Partitions registered as f"{out_prefix}{i}"."""
    from .parallel.dist_ops import hash_partition

    parts = hash_partition(get_table(table_id), hash_columns, num_partitions)
    for i, t in parts.items():
        put_table(f"{out_prefix}{i}", t)
    return Status.OK()


def merge_tables(table_ids: List[str], out_id: str,
                 ctx: Optional[CylonContext] = None) -> Status:
    from .data.table import concat_tables

    tables = [get_table(i) for i in table_ids]
    put_table(out_id, concat_tables(tables, ctx or tables[0].context))
    return Status.OK()


# ---------------------------------------------------------------------------
# lazy plan facade (cylon_tpu/plan) — id-keyed like every wrapper here
# ---------------------------------------------------------------------------

def lazy_table(table_id: str):
    """Start a lazy query plan over a registered table; build the
    pipeline with LazyTable methods and finish with
    ``execute(out_id=...)`` to register the result."""
    from .plan import scan

    return scan(table_id)


def execute_plan(lazy, out_id: str) -> Status:
    """Optimize + execute a `LazyTable` pipeline, registering the
    result under ``out_id``."""
    lazy.execute(out_id=out_id)
    return Status.OK()


def row_count(table_id: str) -> int:
    return get_table(table_id).row_count


def column_count(table_id: str) -> int:
    return get_table(table_id).column_count


def show_table(table_id: str, row1: int = 0, row2: int = -1,
               col1: int = 0, col2: int = -1) -> None:
    get_table(table_id).show(row1, row2, col1, col2)
