"""Device-native variable-length strings: (starts, lengths, words).

The reference handles arbitrary varlen binary through its whole stack
(reference: cpp/src/cylon/arrow/arrow_partition_kernels.hpp:94
`BinaryHashPartitionKernel`, arrow_kernels.hpp:101
`BinaryArraySplitKernel`, join/join.cpp:648-799 string/binary dispatch)
by pointer-walking Arrow (offsets, bytes) buffers per row. XLA has no
ragged type and per-row pointer walks are scalar-unit poison on TPU, so
the TPU-native design keeps the Arrow-style representation but makes
every operation a fixed set of whole-array passes:

* storage is WORD-ALIGNED: every row's bytes start at a 4-byte boundary
  of one dense ``uint32`` word buffer (tail-padded with zero bytes), so
  all content math runs on u32 vectors — no byte gathers;
* rows are TIGHTLY PACKED: ``starts == exclusive_cumsum(ceil(len/4))``.
  This invariant is what lets one unique-index scatter + cumsum recover
  the word→row map with no searchsorted / segment_sum / cummax (all
  measured TPU pathologies, see ops/join.py);
* per-row content identity is a family of independent 32-bit polynomial
  hashes computed with the prefix-sum range trick: contribution of word
  j is ``g^p * mix(w_j)`` with p = j − row_start, so a row's hash is a
  difference of two prefix sums — ONE cumsum per hash, zero per-row
  loops. Join/groupby/set-op equality on device is (h1, h2, h3, byte
  length): a false equality needs a 96-bit triple collision between
  same-length rows (< 2^-70 odds for a billion distinct keys). The
  reference compares bytes exactly; this is the deliberate TPU trade —
  documented, and the dictionary path remains available when exactness
  is demanded;
* varlen gather (``take``) builds the output layout from the gathered
  word counts and copies words through the same word→row map — two
  scatters, two cumsums, three gathers, independent of row lengths.

Dictionary encoding (data/column.py) remains the *optimization* for
low-cardinality columns; this module is the general path whose
vocabulary never materializes on host.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..util import capacity as _capacity

# ingest policy: dictionary-encode when the vocabulary is small (device
# codes sort faster and stay exact); otherwise varbytes
DICT_MAX_VOCAB = 1 << 14
DICT_MAX_RATIO = 0.5

# Table.sort prefix depth: varbytes sorts are exact up to this many words
# (4 bytes each); longer rows fall back to a host sort
SORT_PREFIX_WORDS = 16

# Word-lane fast paths (round 4). Short rows are just structs of a few
# u32 words, and TPU treats them best that way:
# * EXACT_KEY_WORDS: rows up to this many words join/group/set-op on
#   their RAW prefix words (+ byte length) as key lanes — byte-exact
#   equality (the reference's guarantee, join/join.cpp:648-799) with
#   ZERO hashing and zero varlen gathers; the lanes ride fused sorts as
#   operands. Longer rows keep the 96-bit content-hash identity.
# * LANE_WORDS_MAX: varlen takes (join/sort/filter outputs) for rows up
#   to this many words run as fixed-width lane gathers producing a
#   STRIDED layout (starts[r] = r*K) — no word→row map, no scatter, no
#   host sync. XLA's per-element gather costs ~15-30 ns on TPU, so the
#   packed-layout take of an M-row output costs ~3 passes over
#   cap_w≈M*avg_words elements (measured 4.6 s at M=16.8M, 3 words);
#   the lane route costs K gathers of M rows and nothing else.
# A strided layout is a VALID VarBytes everywhere: every kernel here
# reads rows via (starts, lengths) ranges and the prefix-sum hash
# differences cancel gap contributions, so only tightness of memory
# distinguishes it from the packed layout (waste ≤ K/avg_words, bounded
# by LANE_WORDS_MAX).
EXACT_KEY_WORDS = 5
LANE_WORDS_MAX = 8


def pair_k_words(a, b):
    """Shared lane count for two columns joined/compared as a key pair,
    or None when lane pairing does not apply. LOAD-BEARING: both sides
    of a key comparison must emit the same number of word lanes or the
    key arrays zip misaligned — every two-table key-building site must
    route through this."""
    if getattr(a, "is_varbytes", False) and getattr(b, "is_varbytes", False):
        return max(a.varbytes.max_words, b.varbytes.max_words)
    return None

# hash schemes: (g multiplier, seed, post-mix selector). g odd so g^p
# never collapses mod 2^32; three independent schemes give 96 id bits.
_G1, _G2, _G3 = np.uint32(31), np.uint32(0x01000193), np.uint32(0x9E3779B1)
_S1, _S2, _S3 = np.uint32(0x2545F491), np.uint32(0x85EBCA6B), np.uint32(0xC2B2AE35)


def _nwords(lengths: jnp.ndarray) -> jnp.ndarray:
    return (lengths + 3) >> 2


class VarBytes:
    """Word-aligned varlen byte storage (see module docstring).

    words:   jnp.uint32 [word_capacity], tightly packed rows then zeros
    starts:  jnp.int32 [n] — word index of each row's first word
    lengths: jnp.int32 [n] — byte length of each row
    max_words: static int ≥ 1 — max ceil(len/4) over rows (sort prefix
               bound; preserved through take/concat)
    total_words: static int — words actually occupied (packed prefix)
    shard_geom: None, or (rows_per_shard, words_per_shard) for a
               row-SHARDED column: each shard's starts are shard-relative
               so per-shard kernels stay self-contained; eager whole-
               array ops globalize via ``eff_starts`` (correct despite
               the inter-shard padding gaps — the hash/take range sums
               are gap-immune).
    """

    def __init__(self, words, starts, lengths, max_words: int,
                 total_words: int, shard_geom=None, stride=None):
        self.words = words
        self.starts = starts
        self.lengths = lengths
        self.max_words = max(int(max_words), 1)
        self.total_words = int(total_words)
        self.shard_geom = shard_geom
        # stride: None = packed; int K = strided layout starts[r] = r*K
        # (word_lanes become reshape slices instead of gathers)
        self.stride = stride
        self._hash_cache = None  # buffers are immutable; memoize hashes
        self._lane_cache = {}    # k_lim -> word lanes (immutable buffers)

    def __len__(self) -> int:
        return int(self.lengths.shape[0])

    @property
    def nrows(self) -> int:
        return int(self.lengths.shape[0])

    def eff_starts(self) -> jnp.ndarray:
        """Starts as GLOBAL word indices (identity when unsharded)."""
        if self.shard_geom is None:
            return self.starts
        rows, wstride = self.shard_geom
        sid = jnp.arange(self.starts.shape[0], dtype=jnp.int32) \
            // jnp.int32(rows)
        return self.starts + sid * jnp.int32(wstride)

    # ------------------------------------------------------------------
    # host <-> device
    # ------------------------------------------------------------------

    @staticmethod
    def from_host(values: Sequence, fill: bytes = b"") -> "VarBytes":
        """Build from a sequence of str/bytes (None/NaN rows become
        ``fill`` — validity is tracked by the owning Column)."""
        enc = []
        for v in values:
            if v is None or (isinstance(v, float) and v != v):
                enc.append(fill)
            elif isinstance(v, bytes):
                enc.append(v)
            else:
                enc.append(str(v).encode("utf-8"))
        n = len(enc)
        lengths = np.fromiter((len(b) for b in enc), np.int32, n) \
            if n else np.zeros(0, np.int32)
        src = b"".join(enc)
        return VarBytes._from_packed(src, lengths)

    @staticmethod
    def from_arrow_buffers(offsets: np.ndarray, data: bytes) -> "VarBytes":
        """Build from Arrow-style (offsets[n+1], bytes) — the zero-copy-
        adjacent ingest path (reference: Arrow binary array layout)."""
        offsets = np.asarray(offsets)
        lengths = np.diff(offsets).astype(np.int32)
        lo = int(offsets[0]) if offsets.size else 0
        hi = int(offsets[-1]) if offsets.size else 0
        return VarBytes._from_packed(bytes(data[lo:hi]),
                                     lengths, src_offsets=offsets - lo)

    @staticmethod
    def _from_packed(src: bytes, lengths: np.ndarray,
                     src_offsets: Optional[np.ndarray] = None) -> "VarBytes":
        """Vectorized host realignment: contiguous source bytes →
        word-aligned layout. All numpy, no per-row Python."""
        n = lengths.shape[0]
        nw = (lengths.astype(np.int64) + 3) // 4
        starts = np.concatenate([[0], np.cumsum(nw)])
        total_words = int(starts[-1])
        cap = _capacity(max(total_words, 1))
        out = np.zeros(cap * 4, np.uint8)
        if len(src):
            sbuf = np.frombuffer(src, np.uint8)
            if src_offsets is None:
                src_starts = np.concatenate(
                    [[0], np.cumsum(lengths.astype(np.int64))])[:-1]
            else:
                src_starts = np.asarray(src_offsets[:-1], np.int64)
            # dst position of source byte k (row r, in-row offset p):
            # starts[r]*4 + p
            rows_rep = np.repeat(np.arange(n), lengths)
            p = np.arange(len(rows_rep)) - np.repeat(
                np.cumsum(np.concatenate([[0], lengths.astype(np.int64)]))[:-1],
                lengths)
            dst = np.repeat(starts[:-1] * 4, lengths) + p
            out[dst] = sbuf[np.repeat(src_starts, lengths) + p]
        words = jnp.asarray(out.view("<u4"))
        return VarBytes(words, jnp.asarray(starts[:-1].astype(np.int32)),
                        jnp.asarray(lengths.astype(np.int32)),
                        int(nw.max()) if n else 1, total_words)

    def to_host(self, as_str: bool = True) -> np.ndarray:
        """Decode to a host object array of str (or bytes)."""
        words = np.asarray(jax.device_get(self.words))
        starts = np.asarray(jax.device_get(self.eff_starts()))
        lengths = np.asarray(jax.device_get(self.lengths))
        raw = words.view(np.uint8).tobytes()
        out = np.empty(len(starts), object)
        for i in range(len(starts)):
            b = raw[starts[i] * 4: starts[i] * 4 + lengths[i]]
            out[i] = b.decode("utf-8", errors="replace") if as_str else b
        return out

    # ------------------------------------------------------------------
    # device kernels
    # ------------------------------------------------------------------

    def hash_keys(self, validity=None) -> Tuple[jnp.ndarray, ...]:
        """(h1, h2, h3, len) uint32 arrays — the device identity of each
        row. Equal bytes ⇒ equal keys; unequal bytes collide only on a
        96-bit triple collision at equal length. ``validity`` (bool [n]
        or None) forces null rows to a shared tag so nulls group
        together (callers usually ALSO carry validity as its own key).

        PERF NOTE (v5e, 4M 12-byte rows): hash ≈ 0.57 s, varlen take of
        ~5M rows ≈ 1.6-5 s — the join-output takes dominate varbytes
        joins (bench string_join ~0.75M rows/s vs 53M numeric); a Pallas
        streaming varlen gather is the round-4 target."""
        if self._hash_cache is None:
            raw = _hash_rows(self.words, self.eff_starts(), self.lengths,
                             self.max_words)
            self._hash_cache = raw + (self.lengths.astype(jnp.uint32),)
        h1, h2, h3, ln = self._hash_cache
        if validity is not None:
            # masking layers ON TOP of the cached raw hashes (the raw
            # triple is validity-independent)
            tag = jnp.uint32(0x9E3779B9)
            h1 = jnp.where(validity, h1, tag)
            h2 = jnp.where(validity, h2, tag)
            h3 = jnp.where(validity, h3, tag)
            ln = jnp.where(validity, ln, jnp.uint32(0))
        return h1, h2, h3, ln

    def word_lanes(self, k_lim: Optional[int] = None) -> list:
        """Rows as ``k_lim`` fixed u32 lane arrays: lane k holds each
        row's word k, zero past the row's last word (matching the
        tail-zero storage invariant, so lane-tuple equality + the length
        lane IS byte equality). Strided layouts slice their word buffer;
        packed layouts gather once per lane (memoized)."""
        k_lim = int(self.max_words if k_lim is None else k_lim)
        cached = self._lane_cache.get(k_lim)
        if cached is not None:
            return list(cached)
        n = self.nrows
        nw = _nwords(self.lengths)
        if (self.stride is not None and self.shard_geom is None
                and int(self.words.shape[0]) >= n * self.stride):
            grid = self.words[:n * self.stride].reshape(n, self.stride)
            lanes = [jnp.where(k < nw, grid[:, k], jnp.uint32(0))
                     if k < self.stride else jnp.zeros(n, jnp.uint32)
                     for k in range(k_lim)]
        else:
            wcap = int(self.words.shape[0])
            estarts = self.eff_starts()
            lanes = []
            for k in range(k_lim):
                pos = jnp.clip(estarts + k, 0, wcap - 1)
                lanes.append(jnp.where(k < nw, jnp.take(self.words, pos),
                                       jnp.uint32(0)))
        self._lane_cache[k_lim] = tuple(lanes)
        return lanes

    @staticmethod
    def from_lanes(lanes: Sequence[jnp.ndarray], lengths,
                   shard_geom=None) -> "VarBytes":
        """Build a STRIDED VarBytes from word lanes + byte lengths (the
        join/take output path — words beyond each row's length are
        zeroed so the gap-zero invariant holds)."""
        K = max(len(lanes), 1)
        n = int(lengths.shape[0])
        nw = _nwords(lengths)
        masked = [jnp.where(k < nw, l, jnp.uint32(0))
                  for k, l in enumerate(lanes)] or \
            [jnp.zeros(n, jnp.uint32)]
        flat = jnp.stack(masked, axis=1).reshape(-1)
        cap = _capacity(max(n * K, 1))
        if cap > n * K:
            flat = jnp.concatenate(
                [flat, jnp.zeros(cap - n * K, jnp.uint32)])
        starts = jnp.arange(n, dtype=jnp.int32) * jnp.int32(K)
        vb = VarBytes(flat, starts, lengths, K, n * K,
                      shard_geom=shard_geom, stride=K)
        vb._lane_cache[K] = tuple(masked)
        return vb

    def take(self, indices) -> "VarBytes":
        """Varlen row gather; negative indices produce empty rows (the
        −1→null discipline — validity is the owning Column's job).
        Short rows (≤ LANE_WORDS_MAX words) gather as fixed lanes into a
        strided layout — no word→row map, no host sync; longer rows use
        the packed-layout program with one capacity sync."""
        idx = jnp.asarray(indices)
        if self.nrows == 0 or idx.shape[0] == 0:
            z = jnp.zeros(idx.shape[0], jnp.int32)
            return VarBytes(jnp.zeros(1, jnp.uint32), z, z, 1, 0)
        safe = jnp.maximum(idx, 0)
        hit = idx >= 0
        if self.max_words <= LANE_WORDS_MAX:
            lanes = self.word_lanes()
            out_lanes = [jnp.take(l, safe) for l in lanes]
            lens = jnp.where(hit, jnp.take(self.lengths, safe), 0)
            return VarBytes.from_lanes(out_lanes, lens)
        nw_src = _nwords(self.lengths)
        nw = jnp.where(idx >= 0, jnp.take(nw_src, safe), 0)
        total = int(nw.sum())  # the capacity decision (one scalar sync)
        cap_w = _capacity(max(total, 1))
        words, starts, lens = _take_program(
            self.words, self.eff_starts(), self.lengths, idx, cap_w)
        return VarBytes(words, starts, lens, self.max_words, total)

    def sort_prefix_keys(self) -> list:
        """Lexicographic sort keys: big-endian prefix words then byte
        length. EXACT when max_words ≤ SORT_PREFIX_WORDS (zero-padding +
        the length key order a true prefix first, which IS lexicographic
        order); longer rows need the host fallback — callers check
        ``sortable_on_device``."""
        nw = _nwords(self.lengths)
        keys = []
        k_lim = min(self.max_words, SORT_PREFIX_WORDS)
        wcap = self.words.shape[0]
        estarts = self.eff_starts()
        for k in range(k_lim):
            pos = jnp.clip(estarts + k, 0, wcap - 1)
            w = jnp.where(k < nw, jnp.take(self.words, pos), jnp.uint32(0))
            keys.append(_bswap32(w))
        keys.append(self.lengths.astype(jnp.uint32))
        return keys

    @property
    def sortable_on_device(self) -> bool:
        return self.max_words <= SORT_PREFIX_WORDS

    def equals_rows(self, other: "VarBytes") -> jnp.ndarray:
        """Exact per-row byte equality against another VarBytes of the
        same row count — the opt-in verification pass behind
        ``join(..., exact=True)`` for long keys whose join identity is
        the 96-bit content hash (short keys ≤ EXACT_KEY_WORDS are
        byte-exact by construction and never need this). Bounded loop
        over max(max_words) word positions; each step is two aligned
        gathers + a compare (reference bar: the hash-join kernel
        re-checks true keys after hash match,
        arrow_hash_kernels.hpp:110-185)."""
        eq = self.lengths == other.lengths
        nw = _nwords(self.lengths)
        sa, sb = self.eff_starts(), other.eff_starts()
        ca, cb = self.words.shape[0], other.words.shape[0]
        for k in range(max(self.max_words, other.max_words)):
            wa = jnp.take(self.words, jnp.clip(sa + k, 0, ca - 1))
            wb = jnp.take(other.words, jnp.clip(sb + k, 0, cb - 1))
            eq = eq & ((k >= nw) | (wa == wb))
        return eq

    def equals_literal(self, value) -> jnp.ndarray:
        """Exact per-row equality against one host literal (bounded loop
        over the literal's words)."""
        b = value.encode("utf-8") if isinstance(value, str) else bytes(value)
        pad = (-len(b)) % 4
        lw = np.frombuffer(b + b"\0" * pad, "<u4")
        eq = self.lengths == np.int32(len(b))
        wcap = self.words.shape[0]
        estarts = self.eff_starts()
        for k, w in enumerate(lw):
            pos = jnp.clip(estarts + k, 0, wcap - 1)
            eq = eq & (jnp.take(self.words, pos) == jnp.uint32(w))
        return eq

    def slice(self, start: int, stop: int) -> "VarBytes":
        # python-slice clamping semantics (match fixed-width columns)
        n = self.nrows
        start = max(0, min(int(start), n))
        stop = max(start, min(int(stop), n))
        return self.take(jnp.arange(start, stop, dtype=jnp.int32))


def concat_varbytes(parts: Sequence[VarBytes]) -> VarBytes:
    """Concatenate preserving the packed invariant: strip each part to
    its occupied prefix, shift starts, repad to capacity."""
    total = sum(p.total_words for p in parts)
    cap = _capacity(max(total, 1))
    bufs, starts, lens = [], [], []
    off = 0
    for p in parts:
        bufs.append(p.words[:p.total_words])
        starts.append(p.eff_starts() + jnp.int32(off))
        lens.append(p.lengths)
        off += p.total_words
    pad = cap - total
    if pad:
        bufs.append(jnp.zeros(pad, jnp.uint32))
    return VarBytes(jnp.concatenate(bufs), jnp.concatenate(starts),
                    jnp.concatenate(lens),
                    max(p.max_words for p in parts), total)


# ---------------------------------------------------------------------------
# traceable internals
# ---------------------------------------------------------------------------


def _bswap32(w: jnp.ndarray) -> jnp.ndarray:
    return ((w & 0xFF) << 24) | ((w & 0xFF00) << 8) \
        | ((w >> 8) & 0xFF00) | (w >> 24)


def _mix(w: jnp.ndarray, seed) -> jnp.ndarray:
    h = w ^ seed
    h = h ^ (h >> 16)
    h = h * np.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    return h


def _pow_vec(g: np.uint32, e: jnp.ndarray, max_e: int) -> jnp.ndarray:
    """g^e (mod 2^32) elementwise via bit decomposition — ceil(log2)
    vector multiplies, no per-row loops."""
    steps = max(int(max_e).bit_length(), 1)
    e = jnp.clip(e, 0, (1 << steps) - 1).astype(jnp.uint32)
    out = jnp.ones_like(e)
    acc = jnp.uint32(g)
    for b in range(steps):
        out = jnp.where((e >> b) & 1 == 1, out * acc, out)
        acc = acc * acc
    return out


def _word_row_map(starts, nw, W: int):
    """(row, p) for every word slot: the covering row and the slot's
    word offset within it. Requires tightly packed rows. Slots past the
    packed prefix return clamped garbage — callers mask or never read
    ranges that reach them."""
    n = starts.shape[0]
    iota_n = jnp.arange(n, dtype=jnp.int32)
    nz = nw > 0
    erank = jnp.cumsum(nz.astype(jnp.int32))
    slot = jnp.where(nz, erank - 1, n)
    nzrows = jnp.zeros(n, jnp.int32).at[slot].set(iota_n, mode="drop")
    # starts of nonzero-length rows are strictly increasing → unique slots
    mark = jnp.zeros(W, jnp.int32).at[
        jnp.where(nz, starts, W)].set(1, mode="drop")
    ridx = jnp.cumsum(mark) - 1
    row = jnp.take(nzrows, jnp.clip(ridx, 0, max(n - 1, 0)))
    p = jnp.arange(W, dtype=jnp.int32) - jnp.take(starts, row)
    return row, p


from functools import partial


@partial(jax.jit, static_argnames=("max_words",))
def _hash_rows(words, starts, lengths, max_words: int):
    """Three independent per-row 32-bit content hashes via the
    prefix-sum range trick (module docstring)."""
    W = words.shape[0]
    n = starts.shape[0]
    if n == 0:
        z = jnp.zeros(0, jnp.uint32)
        return z, z, z
    nw = _nwords(lengths)
    _, p = _word_row_map(starts, nw, W)
    end = jnp.clip(starts + nw - 1, 0, W - 1)
    prev = jnp.clip(starts - 1, 0, W - 1)
    has = nw > 0
    out = []
    for g, seed in ((_G1, _S1), (_G2, _S2), (_G3, _S3)):
        c = _mix(words, seed) * _pow_vec(g, p, max_words)
        P = jnp.cumsum(c)
        hi = jnp.take(P, end)
        lo = jnp.where(starts > 0, jnp.take(P, prev), jnp.uint32(0))
        h = jnp.where(has, hi - lo, jnp.uint32(0))
        h = h ^ (lengths.astype(jnp.uint32) * np.uint32(0x9E3779B1)) ^ seed
        h = h ^ (h >> 16)
        h = h * np.uint32(0x7FEB352D)
        h = h ^ (h >> 15)
        h = h * np.uint32(0x846CA68B)
        h = h ^ (h >> 16)
        out.append(h)
    return tuple(out)


@partial(jax.jit, static_argnames=("cap_w",))
def _take_program(words, starts, lengths, idx, cap_w: int):
    """Traceable varlen gather at static word capacity."""
    W_src = words.shape[0]
    safe = jnp.maximum(idx, 0)
    hit = idx >= 0
    nw_src = _nwords(lengths)
    nw = jnp.where(hit, jnp.take(nw_src, safe), 0)
    lens = jnp.where(hit, jnp.take(lengths, safe), 0)
    starts_out = jnp.cumsum(nw) - nw
    row, p = _word_row_map(starts_out, nw, cap_w)
    src_start = jnp.take(jnp.take(starts, safe), row)
    w = jnp.take(words, jnp.clip(src_start + p, 0, W_src - 1))
    total = starts_out[-1] + nw[-1] if nw.shape[0] else jnp.int32(0)
    valid = (jnp.arange(cap_w, dtype=jnp.int32) < total) \
        & (p < jnp.take(nw, row))
    return jnp.where(valid, w, jnp.uint32(0)), starts_out, lens
