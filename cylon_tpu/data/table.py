"""Table — the user-facing columnar table.

Mirrors the reference's `cylon::Table` + free-function operator API
(reference: cpp/src/cylon/table.hpp:43-387) and the pycylon surface
(python/pycylon/data/table.pyx:65-798), re-designed for the TPU execution
model:

* a Table is a GLOBAL view: a list of Columns whose arrays live in device
  HBM. On a distributed context the arrays are row-sharded over the 1-D
  mesh (jax.sharding.NamedSharding) — the reference's "one partition per
  MPI rank" becomes "one shard per chip", but the user holds ONE object,
  exactly like a global jax.Array.
* sharded tables carry a row-validity mask (`row_mask`): shards are padded
  to equal length (XLA static shapes), padding rows are masked out. This is
  the moral equivalent of Cylon's ragged per-rank partitions.
* every local op accepts the mask ("emit") so padded tables flow through
  kernels without host round-trips; compaction happens only at export.

Distributed ops (distributed_join & co) live in cylon_tpu/parallel and are
re-exported as methods here, following the reference's dual local/
distributed API (table.hpp:262-336).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtypes
from ..config import CSVWriteOptions
from ..context import CylonContext
from ..status import Code, CylonError
from .column import (Column, align_string_columns, as_varbytes,
                     string_key_arrays, unify_dictionaries)
from .strings import concat_varbytes, pair_k_words
from .. import telemetry as _telemetry
from ..ops import aggregates as _aggregates
from ..ops import groupby as _groupby
from ..ops import join as _join
from ..ops import order as _order
from ..ops import setops as _setops


class Table:
    def __init__(self, columns: List[Column], ctx: Optional[CylonContext] = None,
                 row_mask=None):
        self._columns = columns
        self._ctx = ctx or CylonContext.Init()
        self._row_count_cache: Optional[int] = None
        self._row_mask = row_mask  # bool [n] or None (all rows live)
        # co-partitioning witness: (key col idxs, key dtype sig, world) set
        # by shuffle/distribute_by_key; lets a later shuffle on the same
        # keys skip the exchange (parallel/dist_ops.shuffle)
        self._hash_partitioned = None
        if columns:
            n = len(columns[0])
            for c in columns:
                if len(c) != n:
                    raise CylonError(Code.Invalid, "ragged columns")

    # ------------------------------------------------------------------
    # properties (pycylon parity: table.pyx column_names/column_count/...)
    # ------------------------------------------------------------------

    @property
    def context(self) -> CylonContext:
        return self._ctx

    @property
    def column_names(self) -> List[str]:
        return [c.name for c in self._columns]

    @property
    def column_count(self) -> int:
        return len(self._columns)

    @property
    def row_mask(self):
        """Row-validity mask: bool [capacity] or None (all rows live)."""
        return self._row_mask

    @row_mask.setter
    def row_mask(self, mask) -> None:
        self._row_mask = mask
        self._row_count_cache = None

    @property
    def row_count(self) -> int:
        """Live row count. Masked tables sync ONE scalar to the host on
        first access; the result is cached (columns/mask never change
        after construction — mutators like clear() reset the cache)."""
        if not self._columns:
            return 0
        if self.row_mask is None:
            return len(self._columns[0])
        if self._row_count_cache is None:
            self._row_count_cache = int(self.row_mask.sum())
        return self._row_count_cache

    def columns(self) -> List[Column]:
        return self._columns

    def get_column(self, i: int) -> Column:
        return self._columns[i]

    def rows(self) -> int:
        """Reference: Table::Rows (table.hpp:134)."""
        return self.row_count

    def __len__(self) -> int:
        return self.row_count

    @property
    def capacity(self) -> int:
        """Physical (padded) row slots."""
        return len(self._columns[0]) if self._columns else 0

    def buffers(self) -> List:
        """Every device buffer this table references (data + validity +
        varbytes words/starts + row mask) — the canonical enumeration
        behind ``nbytes``, and the telemetry ledger's identity set for
        deduplicating shared-buffer views (zero-copy project/filter
        outputs must not double-count live bytes)."""
        out = [] if self.row_mask is None else [self.row_mask]
        for c in self._columns:
            out.append(c.data)
            if c.validity is not None:
                out.append(c.validity)
            if c.is_varbytes:
                vb = c.varbytes
                out.append(vb.words)
                out.append(vb.starts)
        return out

    @property
    def nbytes(self) -> int:
        """Device bytes this table's buffers span — shape × itemsize,
        computed on the host with NO device sync. The telemetry layer's
        ``bytes`` measurement for EXPLAIN ANALYZE reports."""
        return sum(int(np.dtype(a.dtype).itemsize) * int(np.prod(a.shape))
                   for a in self.buffers())

    def emit_mask(self) -> jnp.ndarray:
        if self.row_mask is None:
            return jnp.ones(self.capacity, dtype=bool)
        return self.row_mask

    # ------------------------------------------------------------------
    # constructors (pycylon: from_arrow/from_numpy/from_list/from_pydict/
    # from_pandas, table.pyx:556-624)
    # ------------------------------------------------------------------

    @staticmethod
    def from_arrow(ctx: CylonContext, pa_table) -> "Table":
        cols = [Column.from_pyarrow(pa_table.column(i), pa_table.column_names[i])
                for i in range(pa_table.num_columns)]
        return Table(cols, ctx)

    @staticmethod
    def from_pandas(ctx: CylonContext, df) -> "Table":
        cols = []
        for name in df.columns:
            s = df[name]
            validity = None
            if s.isna().any():
                validity = (~s.isna()).to_numpy()
            arr = s.to_numpy()
            cols.append(Column.from_numpy(arr, str(name), validity))
        return Table(cols, ctx)

    @staticmethod
    def from_numpy(ctx: CylonContext, col_names: Sequence[str],
                   arrays: Sequence[np.ndarray]) -> "Table":
        if len(col_names) != len(arrays):
            raise CylonError(Code.Invalid, "names/arrays length mismatch")
        cols = [Column.from_numpy(np.asarray(a), n)
                for n, a in zip(col_names, arrays)]
        return Table(cols, ctx)

    @staticmethod
    def from_pydict(ctx: CylonContext, data: Dict[str, Sequence]) -> "Table":
        return Table.from_numpy(ctx, list(data.keys()),
                                [np.asarray(v) for v in data.values()])

    @staticmethod
    def from_list(ctx: CylonContext, col_names: Sequence[str],
                  data: Sequence[Sequence]) -> "Table":
        return Table.from_numpy(ctx, col_names, [np.asarray(v) for v in data])

    # ------------------------------------------------------------------
    # exporters (table.pyx:626-693)
    # ------------------------------------------------------------------

    def _compact_indices(self) -> Optional[np.ndarray]:
        if self.row_mask is None:
            return None
        return np.flatnonzero(np.asarray(jax.device_get(self.row_mask)))

    def compact(self) -> "Table":
        """Drop masked rows; returns a dense table."""
        idx = self._compact_indices()
        if idx is None:
            return self
        cols = [c.take(jnp.asarray(idx)) for c in self._columns]
        return Table(cols, self._ctx)

    def _unique_names(self) -> List[str]:
        """Column names with duplicates suffixed (_2, _3, …) so dict
        exports can't silently drop columns (groupby emits one output
        per (column, op) pair — names repeat)."""
        seen: Dict[str, int] = {}
        used = set()
        out = []
        for c in self._columns:
            k = seen.get(c.name, 0) + 1
            name = c.name if k == 1 else f"{c.name}_{k}"
            # suffixes can still collide with literal column names
            while name in used:
                k += 1
                name = f"{c.name}_{k}"
            seen[c.name] = k
            used.add(name)
            out.append(name)
        return out

    def to_pydict(self) -> Dict[str, np.ndarray]:
        t = self.compact()
        return {n: c.to_numpy()
                for n, c in zip(t._unique_names(), t._columns)}

    def to_pydict_local(self) -> Dict[str, np.ndarray]:
        """THIS process's shards' live rows as host numpy — the
        per-process handoff for DDP-style training feeds (see
        parallel/shard.extract_process_local)."""
        from ..parallel import shard as _shard

        return _shard.extract_process_local(self, self._ctx)

    def to_numpy(self, order: str = "F") -> np.ndarray:
        t = self.compact()
        arrs = [c.to_numpy() for c in t._columns]
        return np.array(arrs).T.copy() if order == "F" else \
            np.ascontiguousarray(np.array(arrs).T)

    def to_pandas(self):
        import pandas as pd

        t = self.compact()
        # build positionally then rename: a dict would silently collapse
        # duplicate column names (groupby outputs repeat source names)
        df = pd.DataFrame({i: pd.Series(c.to_numpy())
                           for i, c in enumerate(t._columns)})
        df.columns = [c.name for c in t._columns]
        return df

    def to_arrow(self):
        import pyarrow as pa

        t = self.compact()
        return pa.table([c.to_pyarrow() for c in t._columns],
                        names=[c.name for c in t._columns])

    def to_csv(self, path: str, options: Optional[CSVWriteOptions] = None) -> None:
        from ..io.csv import write_csv

        write_csv(self, path, options)

    # reference: Table::WriteCSV (table.hpp:92)
    write_csv = to_csv

    def to_parquet(self, path: str) -> None:
        from ..io.parquet import write_parquet

        write_parquet(self, path)

    def show(self, row1: int = 0, row2: int = -1, col1: int = 0,
             col2: int = -1) -> None:
        """Print (pycylon table.pyx show/show_by_range)."""
        df = self.to_pandas()
        if row2 == -1:
            row2 = len(df)
        if col2 == -1:
            col2 = df.shape[1]
        print(df.iloc[row1:row2, col1:col2].to_string(index=False))

    print = show  # reference: Table::Print

    def clear(self) -> None:
        # free event: retire this table's ledger entry (if any) so
        # cylon_live_table_bytes drops and leak reports stay honest —
        # _free_if_unretained and finalize both route through here.
        # IDEMPOTENT under double-release: resilience retry/degrade
        # paths can re-enter cleanup (an op frees its non-retained
        # inputs, then the caller's error path finalizes again) — the
        # second call must be a no-op, never a second ledger event
        if getattr(self, "_cleared", False):
            return
        self._cleared = True
        _telemetry.ledger.release(self)
        self._columns = []
        self.row_mask = None
        self._row_count_cache = None

    def retain_memory(self, retain: bool = True) -> None:
        """Reference: Table::retainMemory (table.hpp:178) — free-after-use
        hint: with retain=False, the next operator that consumes this
        table clears its column references after use (reference: Shuffle
        frees non-retained inputs, table.cpp:207), letting the HBM return
        to the arena as soon as XLA's refcounts drop."""
        self._retain = bool(retain)

    def is_retain(self) -> bool:
        """Reference: Table::IsRetain (table.hpp:183)."""
        return getattr(self, "_retain", True)

    def _free_if_unretained(self) -> None:
        if not self.is_retain():
            self.clear()

    def finalize(self) -> None:
        self.clear()

    # ------------------------------------------------------------------
    # row selection / projection
    # ------------------------------------------------------------------

    def take(self, indices) -> "Table":
        """Gather rows by LOGICAL index (live rows in order); −1 produces
        null rows. Masked tables compact first so positional indexing
        never addresses filtered-out rows."""
        t = self.compact()
        idx = jnp.asarray(indices)
        cols = [c.take(idx) for c in t._columns]
        return Table(cols, self._ctx)

    def project(self, columns: Sequence[Union[int, str]]) -> "Table":
        """Zero-copy column subset (reference: Project, table.cpp:1066-1085).
        The hash-placement witness survives (positions remapped) when
        every witnessed key column is kept — projection never moves
        rows, so a later same-key shuffle can still skip."""
        idxs = [self._col_index(c) for c in columns]
        t = Table([self._columns[i] for i in idxs], self._ctx, self.row_mask)
        hp = self._hash_partitioned
        if hp is not None and all(k in idxs for k in hp[0]):
            t._hash_partitioned = (tuple(idxs.index(k) for k in hp[0]),
                                   ) + tuple(hp[1:])
        return t

    def select(self, predicate) -> "Table":
        """Row-lambda filter (reference: Select, table.cpp:698-727 — a host
        row loop in the reference too; prefer mask-based filtering for speed)."""
        t = self.compact()
        data = [c.to_numpy() for c in t._columns]
        n = len(data[0]) if data else 0
        mask = np.zeros(n, dtype=bool)
        from .row import Row

        for i in range(n):
            mask[i] = bool(predicate(Row(t, i, _cache=data)))
        return t.filter_mask(jnp.asarray(mask))

    def filter_mask(self, mask) -> "Table":
        """Filter by a boolean mask array/column. ZERO host syncs: the
        mask folds into ``row_mask`` (every kernel honors emit masks), so
        a filter inside an eager pipeline costs one elementwise AND —
        no count round-trip, no gather. Memory for the dead rows is
        reclaimed at the next shuffle/compact (both drop masked rows)."""
        mask = jnp.asarray(mask)
        keep = mask & self.emit_mask()
        t = Table(list(self._columns), self._ctx, keep)
        t._hash_partitioned = self._hash_partitioned
        return t

    def slice(self, start: int, stop: int) -> "Table":
        t = self.compact()
        return Table([c.slice(start, stop) for c in t._columns], self._ctx)

    def _col_index(self, c: Union[int, str]) -> int:
        if isinstance(c, (int, np.integer)):
            return int(c)
        try:
            return self.column_names.index(c)
        except ValueError:
            raise CylonError(Code.KeyError, f"no column named {c!r}")

    # ------------------------------------------------------------------
    # sort / merge
    # ------------------------------------------------------------------

    def sort(self, order_by: Union[int, str, Sequence],
             ascending: Union[bool, Sequence[bool]] = True) -> "Table":
        """Local sort (reference: Sort, table.cpp / util/arrow_utils.cpp:144-184
        — argsort the key column then gather every column)."""
        t = self.compact()
        cols_idx = [t._col_index(c) for c in
                    (order_by if isinstance(order_by, (list, tuple)) else [order_by])]
        asc = ascending if isinstance(ascending, (list, tuple)) \
            else [ascending] * len(cols_idx)
        keys = _sort_keys_mixed([t._columns[i] for i in cols_idx], asc)
        if keys is None:  # varbytes rows beyond the device prefix bound
            return t.take(_host_sort_perm(
                [t._columns[i] for i in cols_idx], asc))
        perm = _order.lexsort_indices(keys)
        return t.take(perm)

    def merge(self, other_or_list) -> "Table":
        """Concatenate tables (reference: Merge, table.hpp:250)."""
        others = other_or_list if isinstance(other_or_list, (list, tuple)) \
            else [other_or_list]
        tables = [self.compact()] + [o.compact() for o in others]
        return concat_tables(tables, self._ctx)

    # ------------------------------------------------------------------
    # joins
    # ------------------------------------------------------------------

    def join(self, table: "Table", join_type: str = "inner",
             algorithm: str = "auto", **kwargs) -> "Table":
        """Local join; self is the LEFT table (pycylon table.pyx:373-390).
        algorithm: "auto" (default — fastest applicable path), "sort", or
        "hash" (reference join_config.hpp:25)."""
        blk = kwargs.pop("probe_block_rows", None)
        cfg = self._make_join_config(table, join_type, algorithm, kwargs)
        if blk:
            return join_blocked(self, table, cfg, int(blk))
        return join(self, table, cfg)

    def distributed_join(self, table: "Table", join_type: str = "inner",
                         algorithm: str = "auto", **kwargs) -> "Table":
        """comm="shuffle" (default) repartitions both sides via all-to-all;
        comm="ring" streams the build side around the mesh ring
        (ArrowJoin-style overlap, best for a small build side);
        comm="broadcast" replicates ``build_side`` (0=left, 1=right;
        default right) to every shard and probes locally — zero
        all-to-all, the adaptive optimizer's rewrite target for a
        measured-small build side."""
        from ..parallel import dist_ops

        comm = kwargs.pop("comm", "shuffle")
        build_side = kwargs.pop("build_side", 1)
        cfg = self._make_join_config(table, join_type, algorithm, kwargs)
        if comm == "ring":
            return dist_ops.distributed_join_ring(self, table, cfg)
        if comm == "broadcast":
            return dist_ops.broadcast_hash_join(self, table, cfg,
                                                build_side=int(build_side))
        if comm != "shuffle":
            raise CylonError(Code.Invalid,
                             f"unknown comm mode {comm!r} "
                             "(expected 'shuffle', 'ring' or "
                             "'broadcast')")
        return dist_ops.distributed_join(self, table, cfg)

    def _make_join_config(self, table: "Table", join_type, algorithm, kwargs
                          ) -> _join.JoinConfig:
        exact = bool(kwargs.pop("exact", False))
        lidx, ridx = _resolve_join_columns(self, table, kwargs)
        jt = _JOIN_TYPES.get(join_type if not isinstance(join_type, _join.JoinType)
                             else join_type.name.lower())
        if isinstance(join_type, _join.JoinType):
            jt = join_type
        if jt is None:
            raise CylonError(Code.Invalid, f"Unsupported join type {join_type}")
        alg = _JOIN_ALGOS.get(algorithm, _join.JoinAlgorithm.SORT) \
            if isinstance(algorithm, str) else algorithm
        return _join.JoinConfig(jt, lidx, ridx, alg, exact=exact)

    # ------------------------------------------------------------------
    # set ops (pycylon table.pyx:411-457)
    # ------------------------------------------------------------------

    def union(self, table: "Table") -> "Table":
        return set_op(self, table, _setops.SetOp.UNION)

    def subtract(self, table: "Table") -> "Table":
        return set_op(self, table, _setops.SetOp.SUBTRACT)

    def intersect(self, table: "Table") -> "Table":
        return set_op(self, table, _setops.SetOp.INTERSECT)

    def distributed_union(self, table: "Table") -> "Table":
        from ..parallel import dist_ops

        return dist_ops.distributed_set_op(self, table, _setops.SetOp.UNION)

    def distributed_subtract(self, table: "Table") -> "Table":
        from ..parallel import dist_ops

        return dist_ops.distributed_set_op(self, table, _setops.SetOp.SUBTRACT)

    def distributed_intersect(self, table: "Table") -> "Table":
        from ..parallel import dist_ops

        return dist_ops.distributed_set_op(self, table, _setops.SetOp.INTERSECT)

    # ------------------------------------------------------------------
    # aggregates (pycylon table.pyx:485-522)
    # ------------------------------------------------------------------

    def _agg(self, column, op: str):
        i = self._col_index(column) if not isinstance(column, Column) else None
        col = self._columns[i] if i is not None else column
        if self.row_mask is not None:
            valid = col.valid_mask() & self.emit_mask()
            col = Column(col.data, col.dtype, valid, col.dictionary, col.name,
                         varbytes=col.varbytes)
        # a sharded column's reduction already spans all shards (XLA
        # inserts the cross-chip all-reduce) — no distributed branch needed
        value = _aggregates.agg_scalar(col, op)
        return Table.from_pydict(self._ctx, {col.name: [value]})

    def sum(self, column) -> "Table":
        return self._agg(column, "sum")

    def count(self, column) -> "Table":
        return self._agg(column, "count")

    def min(self, column) -> "Table":
        return self._agg(column, "min")

    def max(self, column) -> "Table":
        return self._agg(column, "max")

    def mean(self, column) -> "Table":
        return self._agg(column, "mean")

    # ------------------------------------------------------------------
    # groupby (pycylon table.pyx:524-554)
    # ------------------------------------------------------------------

    def groupby(self, index_col: int, aggregate_cols: Sequence,
                aggregate_ops: Sequence) -> "Table":
        ops = [_as_agg_op(o) for o in aggregate_ops]
        if self._ctx.is_distributed() and self._ctx.get_world_size() > 1:
            from ..parallel import dist_ops

            return dist_ops.distributed_groupby(self, index_col,
                                                list(aggregate_cols), ops)
        return groupby_local(self, index_col, list(aggregate_cols), ops)

    # ------------------------------------------------------------------
    # pandas-style sugar (pycylon table.pyx:749-798)
    # ------------------------------------------------------------------

    def __getitem__(self, key):
        if isinstance(key, Table):  # boolean mask table
            if key.column_count != 1:
                # full-table mask: AND across columns? pycylon uses filter result
                raise CylonError(Code.Invalid, "mask table must have one column")
            mask = key._columns[0].data.astype(bool) & key.emit_mask()
            return self.filter_mask(mask)
        if isinstance(key, slice):
            return self.slice(key.start or 0,
                              key.stop if key.stop is not None else self.row_count)
        if isinstance(key, int):
            return self.slice(key, key + 1)
        if isinstance(key, str):
            return self.project([key])
        if isinstance(key, (list, tuple)):
            return self.project(list(key))
        raise CylonError(Code.Invalid, f"unsupported key {key!r}")

    def _compare(self, other, op) -> "Table":
        # keep the padded capacity + row_mask (join/dist results are padded;
        # compacting here would break t[t["c"] > x] shape alignment)
        t = self
        out_cols = []
        for c in t._columns:
            if c.is_varbytes:
                if isinstance(other, str):
                    if op == "eq":
                        res = c.varbytes.equals_literal(other)
                    elif op == "ne":
                        res = ~c.varbytes.equals_literal(other)
                    else:
                        raise CylonError(
                            Code.TypeError,
                            "ordering vs str needs dictionary storage")
                else:
                    raise CylonError(Code.TypeError, "string col vs non-str")
            elif c.is_string:
                if isinstance(other, str):
                    code = np.searchsorted(c.dictionary, other)
                    hit = (code < len(c.dictionary)) and \
                        c.dictionary[code] == other
                    if op == "eq":
                        res = (c.data == int(code)) if hit else \
                            jnp.zeros(len(c), bool)
                    elif op == "ne":
                        res = (c.data != int(code)) if hit else \
                            jnp.ones(len(c), bool)
                    else:
                        raise CylonError(Code.TypeError,
                                         "ordering vs str uses dictionary order")
                else:
                    raise CylonError(Code.TypeError, "string col vs non-str")
            else:
                o = other
                res = _CMP[op](c.data, o)
            res = res & c.valid_mask()
            out_cols.append(Column(res, dtypes.Bool(), None, None, c.name))
        return Table(out_cols, self._ctx, t.row_mask)

    def __eq__(self, other):  # type: ignore[override]
        if isinstance(other, Table):
            return NotImplemented
        return self._compare(other, "eq")

    def __ne__(self, other):  # type: ignore[override]
        if isinstance(other, Table):
            return NotImplemented
        return self._compare(other, "ne")

    def __lt__(self, other):
        return self._compare(other, "lt")

    def __gt__(self, other):
        return self._compare(other, "gt")

    def __le__(self, other):
        return self._compare(other, "le")

    def __ge__(self, other):
        return self._compare(other, "ge")

    def __hash__(self):
        return id(self)

    def _bool_binop(self, other: "Table", fn) -> "Table":
        cols = [Column(fn(a.data.astype(bool), b.data.astype(bool)),
                       dtypes.Bool(), None, None, a.name)
                for a, b in zip(self._columns, other._columns)]
        return Table(cols, self._ctx, self.row_mask)

    def __and__(self, other: "Table") -> "Table":
        return self._bool_binop(other, jnp.logical_and)

    def __or__(self, other: "Table") -> "Table":
        return self._bool_binop(other, jnp.logical_or)

    def __invert__(self) -> "Table":
        cols = [Column(~c.data.astype(bool), dtypes.Bool(), None, None, c.name)
                for c in self._columns]
        return Table(cols, self._ctx)

    def __repr__(self) -> str:
        return f"Table({self.row_count}x{self.column_count} " \
               f"cols={self.column_names})"


_CMP = {
    "eq": lambda a, b: a == b,
    "ne": lambda a, b: a != b,
    "lt": lambda a, b: a < b,
    "gt": lambda a, b: a > b,
    "le": lambda a, b: a <= b,
    "ge": lambda a, b: a >= b,
}

_JOIN_TYPES = {
    "inner": _join.JoinType.INNER,
    "left": _join.JoinType.LEFT,
    "right": _join.JoinType.RIGHT,
    "outer": _join.JoinType.FULL_OUTER,
    "full_outer": _join.JoinType.FULL_OUTER,
}

_JOIN_ALGOS = {"sort": _join.JoinAlgorithm.SORT,
               "hash": _join.JoinAlgorithm.HASH,
               "auto": _join.JoinAlgorithm.AUTO}


def _as_agg_op(o) -> _groupby.AggregationOp:
    if isinstance(o, _groupby.AggregationOp):
        return o
    if isinstance(o, str):
        return _groupby.AggregationOp[o.upper()]
    return _groupby.AggregationOp(int(o))


from ..util import capacity as _capacity
from ..util import pow2 as _pow2  # shared capacity-rounding policy


def _sort_keys_mixed(cols: Sequence[Column], asc: Sequence[bool]):
    """Sort keys for a mix of plain and varbytes columns. Varbytes sort
    lexicographically via big-endian prefix words + length (exact up to
    strings.SORT_PREFIX_WORDS*4 bytes; longer → None, host fallback)."""
    keys = []
    for c, a in zip(cols, asc):
        if c.is_varbytes:
            if not c.varbytes.sortable_on_device:
                return None
            ks = c.varbytes.sort_prefix_keys()
            if not a:
                ks = [k ^ jnp.uint32(0xFFFFFFFF) for k in ks]
            if c.validity is not None:
                # nulls last: extreme on every prefix key
                ext = jnp.uint32(0xFFFFFFFF)
                ks = [jnp.where(c.validity, k, ext) for k in ks]
            keys.extend(ks)
        else:
            keys.extend(_order.sort_keys([c], [a]))
    return keys


def _host_sort_perm(cols: Sequence[Column], asc: Sequence[bool]):
    """Host lexsort fallback for varbytes rows past the device prefix
    bound (>64-byte strings): decode only the SORT columns."""
    import pandas as pd

    df = pd.DataFrame({str(i): c.to_numpy() for i, c in enumerate(cols)})
    perm = df.sort_values(by=[str(i) for i in range(len(cols))],
                          ascending=list(asc), kind="stable").index.to_numpy()
    return jnp.asarray(perm.astype(np.int32))


def _resolve_join_columns(left: Table, right: Table, kwargs
                          ) -> Tuple[List[int], List[int]]:
    """pycylon's on=/left_on=/right_on= resolution (table.pyx:228-266)."""
    on = kwargs.get("on")
    left_on = kwargs.get("left_on")
    right_on = kwargs.get("right_on")
    if on is not None:
        names = on if isinstance(on, (list, tuple)) else [on]
        li = [left._col_index(c) for c in names]
        ri = [right._col_index(c) for c in names]
        return li, ri
    if left_on is not None and right_on is not None:
        lo = left_on if isinstance(left_on, (list, tuple)) else [left_on]
        ro = right_on if isinstance(right_on, (list, tuple)) else [right_on]
        return ([left._col_index(c) for c in lo],
                [right._col_index(c) for c in ro])
    raise CylonError(Code.Invalid,
                     "kwargs 'on' or 'left_on' and 'right_on' must be provided")


# ---------------------------------------------------------------------------
# Key preparation shared by join/set ops/shuffle
# ---------------------------------------------------------------------------

def align_key_columns(left: Table, right: Table, lidx: List[int],
                      ridx: List[int]) -> Tuple[List[Column], List[Column]]:
    """Promote dtypes / unify string vocabularies so both sides' key columns
    are directly comparable on device."""
    lcols, rcols = [], []
    for li, ri in zip(lidx, ridx):
        a, b = left._columns[li], right._columns[ri]
        if a.is_string != b.is_string:
            raise CylonError(Code.TypeError,
                             f"join key type mismatch: {a.name} vs {b.name}")
        if a.is_string:
            a, b = align_string_columns(a, b)
        elif a.data.dtype != b.data.dtype:
            common = jnp.promote_types(a.data.dtype, b.data.dtype)
            a = Column(a.data.astype(common), a.dtype, a.validity, None, a.name)
            b = Column(b.data.astype(common), b.dtype, b.validity, None, b.name)
        lcols.append(a)
        rcols.append(b)
    return lcols, rcols


def _all_valid(cols: Sequence[Column]) -> jnp.ndarray:
    v = cols[0].valid_mask()
    for c in cols[1:]:
        v = v & c.valid_mask()
    return v


def row_gids(left: Table, right: Table) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Shared dense FULL-ROW ids for set ops; nulls compare equal (validity
    participates in the key, matching set-distinct semantics)."""
    if left.column_count != right.column_count:
        raise CylonError(Code.Invalid, "set ops need equal schemas")
    lidx = list(range(left.column_count))
    lcols, rcols = align_key_columns(left, right, lidx, lidx)
    keys_l, keys_r = [], []
    for a, b in zip(lcols, rcols):
        if a.is_varbytes:
            kw = pair_k_words(a, b)
            ka, _va, _fa = string_key_arrays(a, kw)
            kb, _vb, _fb = string_key_arrays(b, kw)
            keys_l.extend(ka)
            keys_r.extend(kb)
        else:
            keys_l.append(_order.sort_keys([a])[0])
            keys_r.append(_order.sort_keys([b])[0])
        if a.validity is not None or b.validity is not None:
            keys_l.append(a.valid_mask().astype(jnp.uint8))
            keys_r.append(b.valid_mask().astype(jnp.uint8))
    return _order.dense_ranks_two(keys_l, keys_r)


# ---------------------------------------------------------------------------
# Free-function operator API (reference: table.hpp:228-387)
# ---------------------------------------------------------------------------

def _expanded_keys(cols: Sequence[Column], paired: Sequence[Column] = None):
    """Key arrays for join/groupby kernels: one array per plain column;
    varbytes columns expand to raw word lanes (short rows, byte-exact)
    or (h1, h2, h3, len) content hashes (long rows) — data/strings.py.
    ``paired``: the other side's aligned key columns, so both sides
    emit the same lane count (max of the two max_words)."""
    keys, valids, flags = [], [], []
    for j, c in enumerate(cols):
        if c.is_varbytes:
            kw = pair_k_words(c, paired[j]) if paired is not None else None
            ks, vs, fs = string_key_arrays(c, kw)
            keys.extend(ks)
            valids.extend(vs)
            flags.extend(fs)
        else:
            keys.append(c.data)
            valids.append(c.validity)
            flags.append(c.is_string)
    return tuple(keys), tuple(valids), tuple(flags)


def _memo_refs(cols: Sequence[Column]) -> Tuple[Tuple, Tuple]:
    """(id-key, liveness refs) over EVERY buffer a count result depends
    on: data, validity, and varbytes words/starts (ADVICE r5 low —
    keying on id(data) alone would return stale counts for a column
    sharing a data buffer with different validity or string content,
    and weakref-anchoring only data would let a recycled id alias a
    dead entry). Shared by the join count memos here and the splitter
    memo in parallel/dist_ops."""
    ids, refs = [], []
    for c in cols:
        bufs = [c.data]
        if c.validity is not None:
            bufs.append(c.validity)
        if c.is_varbytes:
            bufs.append(c.varbytes.words)
            bufs.append(c.varbytes.starts)
        for b in bufs:
            ids.append(id(b))
            refs.append(b)
    return tuple(ids), tuple(refs)


def join(left: Table, right: Table, config: _join.JoinConfig) -> Table:
    """Local join (reference: cylon::Join, table.cpp:640-654). Exactly TWO
    compiled programs (count, then materialize) — only the 4 output-count
    scalars touch the host; the result keeps pow2 capacity with padding
    rows masked via row_mask. Varbytes key columns join on their
    content-hash identity; varbytes payload columns are re-gathered by
    the materialized row indices (one varlen gather per column).

    Working sets beyond HBM: when the estimated plan memory exceeds the
    pool's headroom, the probe side is processed in blocks
    (``join_blocked``); `Table.join(probe_block_rows=...)` forces it."""
    est = _join_plan_bytes_estimate(left, right)
    avail = left._ctx.memory_pool.available_bytes()
    probe_cap = right.capacity if config.type == _join.JoinType.RIGHT \
        else left.capacity
    if avail and est > avail // 2 and probe_cap > (1 << 20):
        blk = max((1 << 20),
                  probe_cap // max(2 * est // max(avail, 1), 2))
        return join_blocked(left, right, config, int(blk))
    return _join_once(left, right, config)


def _join_plan_bytes_estimate(left: Table, right: Table) -> int:
    """Rough plan+materialize working-set bytes: sort operands + payload
    gathers, ~6 u32-equivalents per row per column-ish; varbytes columns
    add their word-buffer bytes (the content dominates large strings)."""
    n = left.capacity + right.capacity
    width = sum(max(np.dtype(c.data.dtype).itemsize, 4) + 1
                for c in left._columns + right._columns)
    vb_bytes = sum(4 * int(c.varbytes.words.shape[0])
                   for c in left._columns + right._columns
                   if c.is_varbytes)
    return int(n) * (width + 24) + 2 * vb_bytes


def _join_once(left: Table, right: Table, config: _join.JoinConfig) -> Table:
    from ..data.strings import EXACT_KEY_WORDS, LANE_WORDS_MAX, VarBytes

    lcols, rcols = align_key_columns(left, right, config.left_column_idx,
                                     config.right_column_idx)
    # varbytes alignment may have lifted a dictionary key column: joins
    # read keys from the ALIGNED columns, payload from the originals
    lkeys, lkvalid, str_flags = _expanded_keys(lcols, rcols)
    rkeys, rkvalid, _ = _expanded_keys(rcols, lcols)
    lemit, remit = left.row_mask, right.row_mask

    lvb = [i for i, c in enumerate(left._columns) if c.is_varbytes]
    rvb = [i for i, c in enumerate(right._columns) if c.is_varbytes]
    # INNER joins on byte-exact (word-lane) string keys emit identical
    # bytes for both key columns — the right key's output aliases the
    # left's, skipping its lanes and its materialization entirely
    alias_rkeys = {}
    if config.type == _join.JoinType.INNER:
        for li, rj in zip(config.left_column_idx, config.right_column_idx):
            a, b = left._columns[li], right._columns[rj]
            if a.is_varbytes and b.is_varbytes:
                kp = max(a.varbytes.max_words, b.varbytes.max_words)
                if kp <= EXACT_KEY_WORDS:
                    alias_rkeys[rj] = li
    # short varbytes columns ride the materialize as fixed u32 word
    # lanes appended after the real columns (output = strided layout,
    # no varlen gather at all); long ones re-gather via VarBytes.take
    lvb_fast = [i for i in lvb
                if left._columns[i].varbytes.max_words <= LANE_WORDS_MAX]
    rvb_fast = [j for j in rvb
                if right._columns[j].varbytes.max_words <= LANE_WORDS_MAX
                and j not in alias_rkeys]
    ldat = tuple(c.data for c in left._columns)
    lval = tuple(c.validity for c in left._columns)
    rdat = tuple(c.data for c in right._columns)
    rval = tuple(c.validity for c in right._columns)
    l_lane_slots, r_lane_slots = {}, {}
    for i in lvb_fast:
        vb = left._columns[i].varbytes
        l_lane_slots[i] = (len(ldat), vb.max_words)
        ldat = ldat + tuple(vb.word_lanes())
        lval = lval + (None,) * vb.max_words
    for j in rvb_fast:
        vb = right._columns[j].varbytes
        r_lane_slots[j] = (len(rdat), vb.max_words)
        rdat = rdat + tuple(vb.word_lanes())
        rval = rval + (None,) * vb.max_words

    seq = left._ctx.get_next_sequence()
    # route: the sort-stream path handles single 4-byte keys; the
    # hash-stream path (JoinAlgorithm.HASH — reference hash join,
    # arrow_hash_kernels.hpp:48-225) covers multi-column/wide keys by
    # sorting a 2x32-bit row hash with exact collision fallback.
    # FULL_OUTER streams as LEFT + one unmatched-build membership tail
    # (_append_unmatched_right); the XLA plan remains the general
    # fallback (forced algorithms, collisions, non-streamable shapes).
    alg = config.algorithm
    if config.type == _join.JoinType.FULL_OUTER and \
            (_join.stream_plan_applicable(lkeys, rkeys, str_flags,
                                          _join.JoinType.LEFT)
             or _join.hash_stream_applicable(lkeys, rkeys, str_flags,
                                             _join.JoinType.LEFT)):
        sub = _join.JoinConfig(_join.JoinType.LEFT,
                               config.left_column_idx,
                               config.right_column_idx, alg,
                               exact=config.exact)
        out = _join_once(left, right, sub)
        return _append_unmatched_right(left, right, config, out,
                                       aligned=(lcols, rcols))
    use_stream = (alg != _join.JoinAlgorithm.HASH
                  and _join.stream_plan_applicable(lkeys, rkeys, str_flags,
                                                   config.type))
    use_hash = (not use_stream
                and alg in (_join.JoinAlgorithm.HASH,
                            _join.JoinAlgorithm.AUTO)
                and _join.hash_stream_applicable(lkeys, rkeys, str_flags,
                                                 config.type))

    def _stream_join(hash_mode: bool):
        from ..parallel.shuffle import _count_cached

        interp = jax.default_backend() != "tpu"
        a_desc, b_desc = _join.plan_lane_descs(ldat, lval, rdat, rval,
                                               config.type)
        br = _join.stream_block_rows(lkeys[0].shape[0], rkeys[0].shape[0])
        with _telemetry.phase("join.plan", seq):
            counts, a_streams, b_streams = _join.plan_program_stream(
                lkeys, lkvalid, lemit, rkeys, rkvalid, remit,
                ldat, lval, rdat, rval, str_flags, config.type,
                a_desc=a_desc, b_desc=b_desc, block_rows=br,
                hash_mode=hash_mode, interpret=interp)
            # the COUNT FETCH memoizes on the source buffers (weakref
            # identity — jax arrays are immutable): repeat joins of the
            # same tables skip this ~100 ms host sync; the device
            # `counts` still feeds materialize either way
            lids, lrefs = _memo_refs(lcols)
            rids, rrefs = _memo_refs(rcols)
            ck = ("join_counts", int(config.type), bool(hash_mode),
                  tuple(config.left_column_idx),
                  tuple(config.right_column_idx),
                  lids, rids, id(lemit), id(remit))
            refs = lrefs + rrefs \
                + tuple(x for x in (lemit, remit) if x is not None)
            host_counts = _count_cached(
                ck, refs, lambda: jax.device_get(counts))
            n_primary = int(host_counts[0])
        if hash_mode and int(host_counts[3]) > 0:
            return None  # hash collision — caller recomputes exactly
        if n_primary < 0:
            raise CylonError(Code.ExecutionError,
                             "join output exceeds 2^31 rows per shard; "
                             "repartition over more shards")
        cap_e = _join.stream_expand_capacity(n_primary, br)
        with _telemetry.phase("join.materialize", seq):
            return _join.materialize_program_stream(
                counts, a_streams, b_streams,
                ldat, lval, rdat, rval, config.type, cap_e,
                a_desc=a_desc, b_desc=b_desc, block_rows=br,
                interpret=interp)

    res = None
    if use_stream:
        res = _stream_join(hash_mode=False)
    elif use_hash:
        res = _stream_join(hash_mode=True)
    if res is not None:
        lod, lov, rod, rov, emit, lidx, ridx = res
    else:
        from ..parallel.shuffle import _count_cached

        with _telemetry.phase("join.plan", seq):
            counts2, lo, m, bperm, un_mask = _join.plan_program(
                lkeys, lkvalid, lemit, rkeys, rkvalid, remit, str_flags,
                config.type)
            # same memoization as the stream path: repeat joins of the
            # same tables skip the count host sync
            lids, lrefs = _memo_refs(lcols)
            rids, rrefs = _memo_refs(rcols)
            ck = ("join_counts_xla", int(config.type),
                  tuple(config.left_column_idx),
                  tuple(config.right_column_idx),
                  lids, rids, id(lemit), id(remit))
            refs = lrefs + rrefs \
                + tuple(x for x in (lemit, remit) if x is not None)
            n_primary, n_un = (int(v) for v in _count_cached(
                ck, refs, lambda: jax.device_get(counts2)))
        cap_p = _capacity(n_primary)
        cap_u = _capacity(n_un) \
            if config.type == _join.JoinType.FULL_OUTER else 0
        aemit = remit if config.type == _join.JoinType.RIGHT else lemit

        with _telemetry.phase("join.materialize", seq):
            lod, lov, rod, rov, emit, lidx, ridx = _join.materialize_program(
                lo, m, bperm, un_mask, aemit,
                ldat, lval, rdat, rval, config.type, cap_p, cap_u)

    nl = left.column_count
    cols = [Column(d, c.dtype, v, c.dictionary, f"lt-{i}")
            for i, (d, v, c) in enumerate(zip(lod, lov, left._columns))]
    cols += [Column(d, c.dtype, v, c.dictionary, f"rt-{nl + j}")
             for j, (d, v, c) in enumerate(zip(rod, rov, right._columns))]

    def lane_vb(od, slots, col_i, idx):
        off, k = slots[col_i]
        # miss/dead rows carry garbage lane values and lengths — zero
        # the lengths so the strided gap-zero/read-range invariants hold
        lens = jnp.where(idx >= 0, od[col_i], 0)
        return VarBytes.from_lanes([od[off + q] for q in range(k)], lens)

    for i in lvb:
        if i in l_lane_slots:
            vb = lane_vb(lod, l_lane_slots, i, lidx)
        else:
            vb = left._columns[i].varbytes.take(lidx)
        cols[i] = Column(vb.lengths, left._columns[i].dtype, cols[i].validity,
                         None, cols[i].name, varbytes=vb)
    for j in rvb:
        if j in alias_rkeys:
            src = cols[alias_rkeys[j]]
            cols[nl + j] = Column(src.data, right._columns[j].dtype,
                                  cols[nl + j].validity, None,
                                  cols[nl + j].name, varbytes=src.varbytes)
            continue
        if j in r_lane_slots:
            vb = lane_vb(rod, r_lane_slots, j, ridx)
        else:
            vb = right._columns[j].varbytes.take(ridx)
        cols[nl + j] = Column(vb.lengths, right._columns[j].dtype,
                              cols[nl + j].validity, None, cols[nl + j].name,
                              varbytes=vb)
    if config.exact:
        emit, collided = _exact_verify_keys(config, lcols, rcols,
                                            lidx, ridx, emit)
        if collided:
            # non-INNER collision: rows would need reclassification as
            # unmatched (and FULL_OUTER would need appended rows) —
            # redo the join on exact shared-vocabulary dictionary codes
            return _exact_dict_fallback_join(left, right, config)
    return Table(cols, left._ctx, emit)


def _exact_verify_keys(config, lcols, rcols, lidx, ridx, emit):
    """Opt-in byte verification of hash-identified varbytes join keys
    (VERDICT r03 #4). Short keys are byte-exact by construction; long
    keys join on the 96-bit content hash, so exact=True re-checks true
    bytes after the match, the way the reference's hash-join kernel
    re-checks true keys (arrow_hash_kernels.hpp:110-185). INNER joins
    filter collision rows out of the output; for outer joins a detected
    collision returns ``collided=True`` and the caller redoes the join
    on dictionary codes (exact by construction) — never raises
    (round-5: VERDICT r04 #8 closed the raise carve-out)."""
    from ..data.strings import EXACT_KEY_WORDS

    for a, b in zip(lcols, rcols):
        if not (a.is_varbytes and b.is_varbytes):
            continue
        if pair_k_words(a, b) <= EXACT_KEY_WORDS:
            continue  # word-lane keys: already byte-exact
        eq = a.varbytes.take(lidx).equals_rows(b.varbytes.take(ridx))
        matched = (lidx >= 0) & (ridx >= 0)
        if config.type == _join.JoinType.INNER:
            emit = emit & (~matched | eq)
            continue
        if bool(jax.device_get((emit & matched & ~eq).any())):
            return emit, True
    return emit, False


def _exact_dict_fallback_join(left: Table, right: Table,
                              config: _join.JoinConfig) -> Table:
    """Collision recovery for exact outer joins on long varbytes keys:
    re-encode each long key pair as dictionary columns over ONE shared
    sorted vocabulary (a host round trip — paid only when a 96-bit
    content-hash collision was actually detected, i.e. ~never), then
    redo the join on the int32 codes, which are exact by construction.
    Unmatched-row reclassification and FULL_OUTER appends come out right
    because the join itself now runs on collision-free keys. Reference
    bar: arrow_hash_kernels.hpp:110-185 verifies true keys inline."""
    from ..data.strings import EXACT_KEY_WORDS

    lcols2 = list(left._columns)
    rcols2 = list(right._columns)
    for li, rj in zip(config.left_column_idx, config.right_column_idx):
        a, b = left._columns[li], right._columns[rj]
        if not (a.is_varbytes and b.is_varbytes):
            continue
        if pair_k_words(a, b) <= EXACT_KEY_WORDS:
            continue
        lcols2[li], rcols2[rj] = _dict_encode_pair(a, b)
    cfg = _join.JoinConfig(config.type, config.left_column_idx,
                           config.right_column_idx, config.algorithm,
                           exact=False)
    return _join_once(Table(lcols2, left._ctx, left.row_mask),
                      Table(rcols2, right._ctx, right.row_mask), cfg)


def _dict_encode_pair(a: Column, b: Column) -> Tuple[Column, Column]:
    """Re-encode two varbytes key columns as dictionary columns over ONE
    shared sorted vocabulary — codes then compare exactly (collision
    recovery for exact=True; shared by the local and distributed
    fallbacks). Host round trip by design: only runs after an actual
    detected hash collision."""
    filler = b"" if a.dtype.type == dtypes.Type.BINARY else ""

    def _safe_host(c):
        return np.array([filler if v is None else v for v in c.to_numpy()],
                        dtype=object)

    sa, sb = _safe_host(a), _safe_host(b)
    vocab = np.unique(np.concatenate([sa, sb]))
    return (
        Column(jnp.asarray(np.searchsorted(vocab, sa).astype(np.int32)),
               a.dtype, a.validity, vocab, a.name),
        Column(jnp.asarray(np.searchsorted(vocab, sb).astype(np.int32)),
               b.dtype, b.validity, vocab, b.name),
    )


def join_blocked(left: Table, right: Table, config: _join.JoinConfig,
                 probe_block_rows: int) -> Table:
    """Chunked local join for working sets beyond HBM (SURVEY §5.7; the
    reference's analog is incremental buffer-at-a-time serialization,
    arrow_all_to_all.cpp:83-135): the PROBE side (left; right for RIGHT
    joins) is processed in row blocks of ``probe_block_rows``, each block
    joined against the resident build side at bounded capacity, results
    concatenated. Peak device memory ≈ build side + one block's join,
    instead of the full probe×build plan.

    FULL_OUTER runs blocked LEFT plus ONE key-membership pass that
    appends build rows whose key matches no probe row (keys-only memory,
    no payload blowup)."""
    jt = config.type
    if jt == _join.JoinType.RIGHT:
        probe, other = right, left
    else:
        probe, other = left, right
    n = probe.capacity
    blocks = []
    sub_type = _join.JoinType.LEFT if jt == _join.JoinType.FULL_OUTER \
        else jt
    for lo in range(0, max(n, 1), probe_block_rows):
        blk = probe.slice(lo, min(lo + probe_block_rows, n)) \
            if probe.row_mask is None else Table(
                [c.slice(lo, min(lo + probe_block_rows, n))
                 for c in probe._columns], probe._ctx,
                probe.row_mask[lo:min(lo + probe_block_rows, n)])
        if jt == _join.JoinType.RIGHT:
            blocks.append(_join_once(other, blk, config))
        else:
            cfg = _join.JoinConfig(sub_type, config.left_column_idx,
                                   config.right_column_idx,
                                   config.algorithm, exact=config.exact)
            blocks.append(_join_once(blk, other, cfg))
    out = concat_tables(blocks, left._ctx) if len(blocks) > 1 \
        else blocks[0]
    if jt != _join.JoinType.FULL_OUTER:
        return out
    return _append_unmatched_right(left, right, config, out)


def _append_unmatched_right(left: Table, right: Table,
                            config: _join.JoinConfig, out: Table,
                            aligned=None) -> Table:
    """FULL_OUTER = LEFT output + right rows whose key matches no left
    row (null keys never match): ONE keys-only membership pass appends
    the unmatched build rows — how both the blocked join and the
    streaming path lift their LEFT machinery to FULL_OUTER.
    ``aligned``: (lcols, rcols) already aligned by the caller (skips a
    repeat dictionary-unification / content-hash pass)."""
    lcols, rcols = aligned if aligned is not None else align_key_columns(
        left, right, config.left_column_idx, config.right_column_idx)
    # pairing is load-bearing: both sides must emit the same lane count
    # per varbytes key column or dense_ranks_two zips misaligned arrays
    lkeys, _lv_, _f = _expanded_keys(lcols, rcols)
    rkeys, _rv_, _f2 = _expanded_keys(rcols, lcols)
    lv = _all_valid(lcols) & left.emit_mask()
    rv = _all_valid(rcols) & right.emit_mask()
    gl, gr = _order.dense_ranks_two(
        [jnp.where(lv, jnp.asarray(k), jnp.asarray(k).dtype.type(0))
         for k in lkeys],
        [jnp.where(rv, jnp.asarray(k), jnp.asarray(k).dtype.type(0))
         for k in rkeys])
    from ..ops.setops import _isin

    in_l = _isin(jnp.where(rv, gr, -2), jnp.where(lv, gl, -1), None)
    un = right.emit_mask() & jnp.where(rv, ~in_l, True)
    # compact: the tail must carry only the unmatched rows (filter_mask
    # is a mask view, and the >HBM blocked path relies on the tail NOT
    # being build-side-capacity wide)
    r_unmatched = right.filter_mask(un).compact()

    def _null_col(c: Column, n: int) -> Column:
        if c.is_varbytes:
            from .strings import VarBytes

            z = jnp.zeros(n, jnp.int32)
            return Column.from_varbytes(
                VarBytes(jnp.zeros(1, jnp.uint32), z, z, 1, 0),
                jnp.zeros(n, bool), c.name, c.dtype)
        return Column(jnp.zeros(n, c.data.dtype), c.dtype,
                      jnp.zeros(n, bool), c.dictionary, c.name)

    ncap = r_unmatched.capacity
    tail = Table([_null_col(c, ncap) for c in left._columns]
                 + list(r_unmatched._columns), left._ctx,
                 r_unmatched.emit_mask())
    tail = Table([c.rename(nm) for c, nm in
                  zip(tail._columns, out.column_names)], left._ctx,
                 tail.row_mask)
    return concat_tables([out, tail], left._ctx)


def _aligned_setop_columns(left: Table, right: Table):
    """Schema-aligned column pairs for set ops: dtypes promoted,
    dictionaries unified."""
    lcols, rcols = [], []
    for ci in range(left.column_count):
        a, b = left._columns[ci], right._columns[ci]
        if a.is_string:
            a, b = align_string_columns(a, b)
        elif a.data.dtype != b.data.dtype:
            common = jnp.promote_types(a.data.dtype, b.data.dtype)
            a = a.astype(dtypes.from_np_dtype(common))
            b = b.astype(dtypes.from_np_dtype(common))
        lcols.append(a)
        rcols.append(b)
    return lcols, rcols


def set_op(left: Table, right: Table, op) -> Table:
    """Local union/subtract/intersect (reference: table.cpp:729-942).
    The streaming full-row-hash path handles lane-packable schemas in
    one sort + one Pallas pass; the dense-ranks path is the general
    (and collision) fallback."""
    if left.column_count != right.column_count:
        raise CylonError(Code.Invalid, "set ops need equal schemas")
    lcols, rcols = _aligned_setop_columns(left, right)
    out = _setops.setop_stream_table(left, right, lcols, rcols, op)
    if out is not None:
        return out

    gl, gr = row_gids(left, right)
    rows = _setops.setop_rows(gl, gr, left.emit_mask(), right.emit_mask(), op)
    out_cols = []
    for a, b in zip(lcols, rcols):
        validity = None
        if a.validity is not None or b.validity is not None:
            validity = jnp.concatenate([a.valid_mask(), b.valid_mask()])
        if a.is_varbytes:
            merged = Column.from_varbytes(
                concat_varbytes([a.varbytes, b.varbytes]), validity, a.name,
                a.dtype)
        else:
            data = jnp.concatenate([a.data, b.data])
            merged = Column(data, a.dtype, validity, a.dictionary, a.name)
        out_cols.append(merged.take(jnp.asarray(rows)))
    return Table(out_cols, left._ctx)


def concat_tables(tables: Sequence[Table], ctx: CylonContext) -> Table:
    """Reference: Merge (table.cpp:388-427) — schema-aligned concat."""
    first = tables[0]
    out_cols = []
    for ci in range(first.column_count):
        cs = [t._columns[ci] for t in tables]
        if any(c.is_varbytes for c in cs):
            cs = [as_varbytes(c) for c in cs]
            vb = concat_varbytes([c.varbytes for c in cs])
            has_null = any(c.validity is not None for c in cs)
            validity = jnp.concatenate([c.valid_mask() for c in cs]) \
                if has_null else None
            out_cols.append(Column.from_varbytes(vb, validity, cs[0].name,
                                                 cs[0].dtype))
            continue
        if cs[0].is_string:
            # unify all vocabularies pairwise-left-fold
            base = cs[0]
            unified = [base]
            for c in cs[1:]:
                base, c2 = unify_dictionaries(base, c)
                unified = [Column(u.data if u.dictionary is base.dictionary
                                  else jnp.take(jnp.asarray(
                                      np.searchsorted(base.dictionary,
                                                      u.dictionary).astype(np.int32)),
                                      u.data),
                                  u.dtype, u.validity, base.dictionary, u.name)
                           for u in unified]
                unified.append(c2)
            cs = unified
        data = jnp.concatenate([c.data for c in cs])
        has_null = any(c.validity is not None for c in cs)
        validity = jnp.concatenate([c.valid_mask() for c in cs]) if has_null \
            else None
        out_cols.append(Column(data, cs[0].dtype, validity, cs[0].dictionary,
                               cs[0].name))
    mask = None
    if any(t.row_mask is not None for t in tables):
        mask = jnp.concatenate([t.emit_mask() for t in tables])
    return Table(out_cols, ctx, mask)


def groupby_local(table: Table, index_col, aggregate_cols: List,
                  aggregate_ops: List, second_phase: bool = False) -> Table:
    """Local hash-groupby equivalent (reference: LocalHashGroupBy,
    groupby_hash.hpp:321-359). ``second_phase`` merges partials with the
    corrected ops (COUNT→SUM)."""
    idx_cols = index_col if isinstance(index_col, (list, tuple)) else [index_col]
    idx_cols = [table._col_index(c) for c in idx_cols]
    val_cols = [table._col_index(c) for c in aggregate_cols]
    ops = [(_groupby.second_phase_op(o) if second_phase else o)
           for o in aggregate_ops]

    for vi, op in zip(val_cols, ops):
        if table._columns[vi].is_varbytes and \
                op != _groupby.AggregationOp.COUNT:
            raise CylonError(
                Code.NotImplemented,
                "varbytes value columns support COUNT only (MIN/MAX need "
                "a total order the content-hash identity does not carry; "
                "dictionary-encode the column for string MIN/MAX)")
    key_columns = [table._columns[i] for i in idx_cols]
    keys = []
    for c in key_columns:
        if c.is_varbytes:
            # group identity = content hashes (grouping needs equality,
            # not order)
            ks, _vs, _fs = string_key_arrays(c)
            keys.extend(ks)
        else:
            keys.extend(_order.sort_keys([c]))
        if c.validity is not None:
            keys.append(c.valid_mask().astype(jnp.uint8))
    emit = table.emit_mask()
    values = tuple(table._columns[i].data for i in val_cols)
    # None for all-valid columns: the mask never rides the sort
    valids = tuple(table._columns[i].validity for i in val_cols)
    # ONE fused sort groups rows contiguously (dead rows last); the
    # n_groups fetch below is the op's single host sync, and every
    # segment reduction then runs on SORTED ids — see
    # ops/groupby.presort_groups (round-5 rework of the dense-rank +
    # scatter-back path; the old gid scatter cost ~15-30 ns/element)
    values_s, valids_s, emit_s, iota_s, gid_s, ng = \
        _groupby.presort_groups_jit(tuple(keys), emit, values, valids)
    num_groups = max(int(jax.device_get(ng)), 1)
    cap = _pow2(num_groups)

    rep, group_valid, results = _groupby.sorted_segment_aggregate_jit(
        gid_s, emit_s, iota_s, values_s, valids_s, cap, tuple(ops),
        tuple(val_cols),
        tuple(table._columns[i].validity is None for i in val_cols))

    # materialize at pow2 group capacity: dead slots (gid-space holes from
    # masked rows, pow2 padding) stay on device masked via row_mask —
    # num_groups above was the only host sync in this op
    safe = jnp.minimum(rep, max(table.capacity - 1, 0))
    out_cols = []
    for i in idx_cols:
        g = table._columns[i].take(safe)
        validity = None if g.validity is None else g.validity & group_valid
        out_cols.append(Column(g.data, g.dtype, validity, g.dictionary,
                               g.name, varbytes=g.varbytes))
    for (arr, avalid), vi, op in zip(results, val_cols, aggregate_ops):
        src = table._columns[vi]
        out_cols.append(Column(
            arr, _agg_dtype(src, op), avalid & group_valid,
            src.dictionary if op in (_groupby.AggregationOp.MIN,
                                     _groupby.AggregationOp.MAX)
            and src.is_string else None,
            src.name))
    return Table(out_cols, table._ctx, group_valid)


def _agg_dtype(src: Column, op) -> dtypes.DataType:
    if op == _groupby.AggregationOp.COUNT:
        return dtypes.Int64()
    if op == _groupby.AggregationOp.MEAN:
        return dtypes.Double()
    return src.dtype
