"""Row — a cursor over a Table with typed getters.

Reference: cpp/src/cylon/row.hpp:23-51 (`GetInt64/GetString/...`), used by
`Select`'s row lambda. Host-side by design: row-wise access is the slow
path on any columnar engine; vectorized masks are the fast path.
"""
from __future__ import annotations


class Row:
    def __init__(self, table, index: int, _cache=None):
        self._table = table
        self._index = index
        self._cache = _cache or [c.to_numpy() for c in table.columns()]

    def get(self, col: int):
        return self._cache[col][self._index]

    def __getitem__(self, col):
        if isinstance(col, str):
            col = self._table.column_names.index(col)
        return self.get(col)

    # typed getters (row.hpp parity)
    def get_bool(self, col: int) -> bool: return bool(self.get(col))
    def get_int8(self, col: int) -> int: return int(self.get(col))
    def get_uint8(self, col: int) -> int: return int(self.get(col))
    def get_int16(self, col: int) -> int: return int(self.get(col))
    def get_uint16(self, col: int) -> int: return int(self.get(col))
    def get_int32(self, col: int) -> int: return int(self.get(col))
    def get_uint32(self, col: int) -> int: return int(self.get(col))
    def get_int64(self, col: int) -> int: return int(self.get(col))
    def get_uint64(self, col: int) -> int: return int(self.get(col))
    def get_half_float(self, col: int) -> float: return float(self.get(col))
    def get_float(self, col: int) -> float: return float(self.get(col))
    def get_double(self, col: int) -> float: return float(self.get(col))
    def get_string(self, col: int) -> str: return str(self.get(col))
