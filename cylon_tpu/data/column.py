"""Column — a typed, device-resident column with optional validity mask.

Mirrors the reference's Column (reference: cpp/src/cylon/column.hpp:31-113 —
id + DataType + arrow::ChunkedArray) with a TPU-native representation:

* fixed-width data is ONE dense jax array in HBM (the reference's
  CombineChunks "one chunk per column" invariant, table.cpp:374-379, is
  structural here);
* nullability is a separate boolean mask array (Arrow validity-bitmap
  analog) — absent mask means "all valid";
* STRING/BINARY columns are dictionary-encoded: a *sorted* host-side
  vocabulary (numpy object array) + int32 codes in HBM. Because the vocab is
  sorted, code order == lexicographic order, so device-side sort/join/
  group-by on strings are integer ops on the MXU-friendly codes. Cross-table
  ops unify vocabularies host-side and re-map codes with one device gather
  (`unify_dictionaries`).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtypes
from ..dtypes import DataType, Type
from ..status import Code, CylonError


class Column:
    def __init__(self, data, dtype: DataType, validity=None, dictionary=None,
                 name: str = ""):
        self.data = data              # jnp array [n] (codes for STRING)
        self.dtype = dtype
        self.validity = validity      # jnp bool [n] (True=valid) or None
        self.dictionary = dictionary  # np.ndarray (sorted) for STRING/BINARY
        self.name = name

    # -- construction --

    @staticmethod
    def from_numpy(arr: np.ndarray, name: str = "",
                   validity: Optional[np.ndarray] = None) -> "Column":
        arr = np.asarray(arr)
        if arr.dtype.kind in ("U", "S", "O"):
            return Column._encode_strings(arr, name, validity)
        if arr.dtype.kind == "M":  # datetime64
            unit = np.datetime_data(arr.dtype)[0]
            dt = dtypes.Timestamp(_np_unit(unit))
            data = jnp.asarray(arr.astype("int64"))
            return Column(data, dt, _dev_mask(validity), None, name)
        if arr.dtype.kind == "m":
            unit = np.datetime_data(arr.dtype)[0]
            dt = dtypes.Duration(_np_unit(unit))
            return Column(jnp.asarray(arr.astype("int64")), dt,
                          _dev_mask(validity), None, name)
        if arr.dtype.kind == "f" and validity is None and np.isnan(arr).any():
            # pandas-style: NaN means null for float columns coming from host
            validity = ~np.isnan(arr)
        dt = dtypes.from_np_dtype(arr.dtype)
        return Column(jnp.asarray(arr), dt, _dev_mask(validity), None, name)

    @staticmethod
    def _encode_strings(arr: np.ndarray, name: str,
                        validity: Optional[np.ndarray]) -> "Column":
        obj = arr.astype(object)
        if validity is None:
            validity = np.array([v is not None and v == v for v in obj], dtype=bool)
        filler = ""
        safe = np.array([v if ok else filler for v, ok in zip(obj, validity)],
                        dtype=object)
        vocab, codes = np.unique(safe.astype(str), return_inverse=True)
        col = Column(jnp.asarray(codes.astype(np.int32)), dtypes.String(),
                     _dev_mask(validity if not validity.all() else None),
                     vocab, name)
        return col

    @staticmethod
    def from_pyarrow(pa_arr, name: str = "") -> "Column":
        """Build from a pyarrow Array/ChunkedArray (combines chunks)."""
        import pyarrow as pa

        if isinstance(pa_arr, pa.ChunkedArray):
            pa_arr = pa_arr.combine_chunks()
        if isinstance(pa_arr, pa.ChunkedArray):  # 0-chunk edge
            pa_arr = pa.concat_arrays(pa_arr.chunks) if pa_arr.num_chunks else \
                pa.array([], type=pa_arr.type)
        t = pa_arr.type
        nulls = pa_arr.null_count > 0
        if pa.types.is_string(t) or pa.types.is_large_string(t) or \
                pa.types.is_binary(t) or pa.types.is_large_binary(t):
            np_obj = pa_arr.to_numpy(zero_copy_only=False)
            validity = np.array([v is not None for v in np_obj]) if nulls else None
            return Column._encode_strings(np.asarray(np_obj, dtype=object), name, validity)
        if pa.types.is_dictionary(t):
            return Column.from_pyarrow(pa_arr.dictionary_decode(), name)
        np_arr = pa_arr.to_numpy(zero_copy_only=False)
        validity = None
        if nulls:
            validity = np.asarray(pa_arr.is_valid())
            if np_arr.dtype.kind == "f":
                np_arr = np.nan_to_num(np_arr)  # keep device data finite where null
            elif np_arr.dtype == object:
                fill = 0
                np_arr = np.array([v if ok else fill
                                   for v, ok in zip(np_arr, validity)])
        return Column.from_numpy(np_arr, name, validity)

    @staticmethod
    def Make(ctx, name, dtype, values) -> "Column":
        """Reference parity: Column::Make / VectorColumn::Make (column.hpp:84-113)."""
        del ctx
        c = Column.from_numpy(np.asarray(values), name)
        if c.dtype.type != dtype.type and not c.dtype.is_var_width():
            c = c.astype(dtype)
        return c

    # -- properties --

    def __len__(self) -> int:
        return int(self.data.shape[0])

    @property
    def is_string(self) -> bool:
        return self.dictionary is not None

    def null_count(self) -> int:
        if self.validity is None:
            return 0
        return int((~self.validity).sum())

    def valid_mask(self) -> jnp.ndarray:
        if self.validity is None:
            return jnp.ones(self.data.shape[0], dtype=bool)
        return self.validity

    # -- transforms --

    def astype(self, dtype: DataType) -> "Column":
        if self.is_string:
            raise CylonError(Code.TypeError, "cannot cast string column")
        return Column(self.data.astype(dtype.np_dtype), dtype, self.validity,
                      None, self.name)

    def take(self, indices, fill_invalid: bool = True) -> "Column":
        """Gather rows; negative indices produce NULL rows (the reference's
        −1→null gather, util/copy_arrray.cpp:16-287)."""
        idx = jnp.asarray(indices)
        if self.data.shape[0] == 0:
            data = jnp.zeros(idx.shape, self.data.dtype)
            return Column(data, self.dtype, jnp.zeros(idx.shape, bool),
                          self.dictionary, self.name)
        neg = idx < 0
        safe = jnp.where(neg, 0, idx)
        data = jnp.take(self.data, safe, axis=0)
        validity = None
        if fill_invalid or self.validity is not None:
            # NOTE: an all-True mask is NOT collapsed to None here — that
            # would force a device→host sync on every gather (deadly over a
            # tunneled TPU). Export paths collapse it instead.
            validity = jnp.take(self.valid_mask(), safe, axis=0) & ~neg
        return Column(data, self.dtype, validity, self.dictionary, self.name)

    def slice(self, start: int, stop: int) -> "Column":
        v = None if self.validity is None else self.validity[start:stop]
        return Column(self.data[start:stop], self.dtype, v, self.dictionary,
                      self.name)

    def rename(self, name: str) -> "Column":
        return Column(self.data, self.dtype, self.validity, self.dictionary, name)

    # -- export --

    def _host_mask(self) -> Optional[np.ndarray]:
        """Validity as a host array, collapsing all-True to None."""
        if self.validity is None:
            return None
        mask = np.asarray(jax.device_get(self.validity))
        return None if mask.all() else mask

    def to_numpy(self) -> np.ndarray:
        data = np.asarray(jax.device_get(self.data))
        mask = self._host_mask()
        if self.is_string:
            out = self.dictionary[data].astype(object)
            if mask is not None:
                out[~mask] = None
            return out
        if mask is not None:
            if data.dtype.kind == "f":
                out = data.astype(data.dtype, copy=True)
                out[~mask] = np.nan
                return out
            out = data.astype(object)
            out[~mask] = None
            return out
        if self.dtype.is_temporal():
            unit = {None: "us"}.get(self.dtype.unit, None)
            unit = _unit_str(self.dtype.unit)
            if self.dtype.type == Type.TIMESTAMP:
                return data.astype(f"datetime64[{unit}]")
            if self.dtype.type == Type.DURATION:
                return data.astype(f"timedelta64[{unit}]")
        return data

    def to_pyarrow(self):
        import pyarrow as pa

        data = np.asarray(jax.device_get(self.data))
        valid = self._host_mask()
        mask = None if valid is None else ~valid
        if self.is_string:
            vals = self.dictionary[data]
            return pa.array(vals, type=pa.string(),
                            mask=mask if mask is not None else None)
        return pa.array(data, mask=mask)


def unify_dictionaries(a: Column, b: Column) -> Tuple[Column, Column]:
    """Re-encode two string columns onto one shared *sorted* vocabulary so
    their codes are directly comparable on device. Host cost is O(|vocab|);
    device cost is one gather per column."""
    if not (a.is_string and b.is_string):
        raise CylonError(Code.TypeError, "unify_dictionaries needs string columns")
    if a.dictionary.shape == b.dictionary.shape and \
            (a.dictionary == b.dictionary).all():
        return a, b
    union = np.union1d(a.dictionary, b.dictionary)
    map_a = jnp.asarray(np.searchsorted(union, a.dictionary).astype(np.int32))
    map_b = jnp.asarray(np.searchsorted(union, b.dictionary).astype(np.int32))
    na = Column(jnp.take(map_a, a.data), a.dtype, a.validity, union, a.name)
    nb = Column(jnp.take(map_b, b.data), b.dtype, b.validity, union, b.name)
    return na, nb


def _dev_mask(validity: Optional[np.ndarray]):
    if validity is None:
        return None
    v = np.asarray(validity, dtype=bool)
    if v.all():
        return None
    return jnp.asarray(v)


def _np_unit(unit: str):
    from ..dtypes import TimeUnit

    return {"s": TimeUnit.SECOND, "ms": TimeUnit.MILLI,
            "us": TimeUnit.MICRO, "ns": TimeUnit.NANO}[unit]


def _unit_str(unit) -> str:
    from ..dtypes import TimeUnit

    if unit is None:
        return "us"
    return {TimeUnit.SECOND: "s", TimeUnit.MILLI: "ms",
            TimeUnit.MICRO: "us", TimeUnit.NANO: "ns"}[unit]
