"""Column — a typed, device-resident column with optional validity mask.

Mirrors the reference's Column (reference: cpp/src/cylon/column.hpp:31-113 —
id + DataType + arrow::ChunkedArray) with a TPU-native representation:

* fixed-width data is ONE dense jax array in HBM (the reference's
  CombineChunks "one chunk per column" invariant, table.cpp:374-379, is
  structural here);
* nullability is a separate boolean mask array (Arrow validity-bitmap
  analog) — absent mask means "all valid";
* STRING/BINARY columns are dictionary-encoded: a *sorted* host-side
  vocabulary (numpy object array) + int32 codes in HBM. Because the vocab is
  sorted, code order == lexicographic order, so device-side sort/join/
  group-by on strings are integer ops on the MXU-friendly codes. Cross-table
  ops unify vocabularies host-side and re-map codes with one device gather
  (`unify_dictionaries`).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import dtypes
from ..dtypes import DataType, Type
from ..status import Code, CylonError


class Column:
    def __init__(self, data, dtype: DataType, validity=None, dictionary=None,
                 name: str = "", varbytes=None):
        self.data = data              # jnp array [n] (codes for dict STRING,
        #                               byte lengths for varbytes STRING)
        self.dtype = dtype
        self.validity = validity      # jnp bool [n] (True=valid) or None
        self.dictionary = dictionary  # np.ndarray (sorted) for dict STRING
        self.varbytes = varbytes      # strings.VarBytes for varlen STRING
        self.name = name

    # -- construction --

    @staticmethod
    def from_numpy(arr: np.ndarray, name: str = "",
                   validity: Optional[np.ndarray] = None) -> "Column":
        arr = np.asarray(arr)
        if arr.dtype.kind in ("U", "S", "O"):
            return Column._encode_strings(arr, name, validity)
        if arr.dtype.kind == "M":  # datetime64
            unit = np.datetime_data(arr.dtype)[0]
            dt = dtypes.Timestamp(_np_unit(unit))
            data = jnp.asarray(arr.astype("int64"))
            return Column(data, dt, _dev_mask(validity), None, name)
        if arr.dtype.kind == "m":
            unit = np.datetime_data(arr.dtype)[0]
            dt = dtypes.Duration(_np_unit(unit))
            return Column(jnp.asarray(arr.astype("int64")), dt,
                          _dev_mask(validity), None, name)
        if arr.dtype.kind == "f" and validity is None and np.isnan(arr).any():
            # pandas-style: NaN means null for float columns coming from host
            validity = ~np.isnan(arr)
        dt = dtypes.from_np_dtype(arr.dtype)
        return Column(jnp.asarray(arr), dt, _dev_mask(validity), None, name)

    @staticmethod
    def _encode_strings(arr: np.ndarray, name: str,
                        validity: Optional[np.ndarray]) -> "Column":
        from .strings import DICT_MAX_RATIO, DICT_MAX_VOCAB, VarBytes

        obj = arr.astype(object)
        if validity is None:
            validity = np.array([v is not None and v == v for v in obj], dtype=bool)
        filler = ""
        safe = np.array([v if ok else filler for v, ok in zip(obj, validity)],
                        dtype=object)
        n = len(obj)
        thresh = min(DICT_MAX_VOCAB, max(16, int(n * DICT_MAX_RATIO)))
        # chunked distinct probe with early bail: the varbytes branch
        # (exactly the high-cardinality case) must not pay np.unique's
        # O(n log n) host string sort just to discard it. The same
        # chunked pass detects BINARY values (bytes must go straight to
        # varbytes — a str() decode corrupts non-UTF-8 payloads).
        seen: set = set()
        for lo in range(0, n, 1 << 16):
            chunk = safe[lo: lo + (1 << 16)]
            seen.update(chunk)
            if any(isinstance(v, bytes) for v in chunk):
                vb = VarBytes.from_host(safe)
                return Column.from_varbytes(
                    vb, _dev_mask(validity if not validity.all() else None),
                    name, dtypes.Binary())
            if len(seen) > thresh:
                # bailing early: later chunks may still hold BINARY
                # values — their scan is negligible next to from_host's
                # own full pass on this (varbytes) path
                is_bin = any(isinstance(v, bytes)
                             for v in safe[lo + (1 << 16):])
                vb = VarBytes.from_host(safe)
                return Column.from_varbytes(
                    vb, _dev_mask(validity if not validity.all() else None),
                    name, dtypes.Binary() if is_bin else None)
        vocab, codes = np.unique(safe.astype(str), return_inverse=True)
        col = Column(jnp.asarray(codes.astype(np.int32)), dtypes.String(),
                     _dev_mask(validity if not validity.all() else None),
                     vocab, name)
        return col

    @staticmethod
    def from_varbytes(vb, validity=None, name: str = "",
                      dtype: Optional[DataType] = None) -> "Column":
        """Wrap device-native varlen storage (data/strings.py). The
        Column's ``data`` array carries the byte lengths so generic
        shape/row plumbing works; content lives in ``varbytes``."""
        return Column(vb.lengths, dtype or dtypes.String(), validity,
                      None, name, varbytes=vb)

    @staticmethod
    def from_pyarrow(pa_arr, name: str = "") -> "Column":
        """Build from a pyarrow Array/ChunkedArray (combines chunks)."""
        import pyarrow as pa

        if isinstance(pa_arr, pa.ChunkedArray):
            pa_arr = pa_arr.combine_chunks()
        if isinstance(pa_arr, pa.ChunkedArray):  # 0-chunk edge
            pa_arr = pa.concat_arrays(pa_arr.chunks) if pa_arr.num_chunks else \
                pa.array([], type=pa_arr.type)
        t = pa_arr.type
        nulls = pa_arr.null_count > 0
        if pa.types.is_string(t) or pa.types.is_large_string(t) or \
                pa.types.is_binary(t) or pa.types.is_large_binary(t):
            import pyarrow.compute as pac

            from .strings import DICT_MAX_RATIO, DICT_MAX_VOCAB, VarBytes

            n = len(pa_arr)
            is_bin = pa.types.is_binary(t) or pa.types.is_large_binary(t)
            nuniq = pac.count_distinct(pa_arr).as_py() if n else 0
            if is_bin or \
                    nuniq > min(DICT_MAX_VOCAB, max(16, int(n * DICT_MAX_RATIO))):
                # high cardinality (or non-UTF8 binary, which the sorted-
                # str vocab can't represent) → varbytes straight from
                # Arrow buffers; nulls become empty rows under validity
                if nulls:
                    validity = np.asarray(pa_arr.is_valid())
                    pa_arr = pac.fill_null(pa_arr, b"" if is_bin else "")
                else:
                    validity = None
                bufs = pa_arr.buffers()
                odt = np.int64 if pa.types.is_large_string(t) or \
                    pa.types.is_large_binary(t) else np.int32
                offsets = np.frombuffer(bufs[1], odt)[
                    pa_arr.offset: pa_arr.offset + n + 1]
                data = bufs[2].to_pybytes() if bufs[2] is not None else b""
                vb = VarBytes.from_arrow_buffers(offsets, data)
                return Column.from_varbytes(
                    vb, _dev_mask(validity), name,
                    dtype=dtypes.Binary() if is_bin else None)
            np_obj = pa_arr.to_numpy(zero_copy_only=False)
            validity = np.array([v is not None for v in np_obj]) if nulls else None
            return Column._encode_strings(np.asarray(np_obj, dtype=object), name, validity)
        if pa.types.is_dictionary(t):
            return Column.from_pyarrow(pa_arr.dictionary_decode(), name)
        np_arr = pa_arr.to_numpy(zero_copy_only=False)
        validity = None
        if nulls:
            validity = np.asarray(pa_arr.is_valid())
            if np_arr.dtype.kind == "f":
                np_arr = np.nan_to_num(np_arr)  # keep device data finite where null
            elif np_arr.dtype == object:
                fill = 0
                np_arr = np.array([v if ok else fill
                                   for v, ok in zip(np_arr, validity)])
        return Column.from_numpy(np_arr, name, validity)

    @staticmethod
    def Make(ctx, name, dtype, values) -> "Column":
        """Reference parity: Column::Make / VectorColumn::Make (column.hpp:84-113)."""
        del ctx
        c = Column.from_numpy(np.asarray(values), name)
        if c.dtype.type != dtype.type and not c.dtype.is_var_width():
            c = c.astype(dtype)
        return c

    # -- properties --

    def __len__(self) -> int:
        return int(self.data.shape[0])

    @property
    def is_string(self) -> bool:
        return self.dictionary is not None or self.varbytes is not None

    @property
    def is_varbytes(self) -> bool:
        return self.varbytes is not None

    def null_count(self) -> int:
        if self.validity is None:
            return 0
        return int((~self.validity).sum())

    def valid_mask(self) -> jnp.ndarray:
        if self.validity is None:
            return jnp.ones(self.data.shape[0], dtype=bool)
        return self.validity

    # -- transforms --

    def astype(self, dtype: DataType) -> "Column":
        if self.is_string:
            raise CylonError(Code.TypeError, "cannot cast string column")
        return Column(self.data.astype(dtype.np_dtype), dtype, self.validity,
                      None, self.name)

    def take(self, indices, fill_invalid: bool = True) -> "Column":
        """Gather rows; negative indices produce NULL rows (the reference's
        −1→null gather, util/copy_arrray.cpp:16-287)."""
        idx = jnp.asarray(indices)
        if self.data.shape[0] == 0 and not self.is_varbytes:
            data = jnp.zeros(idx.shape, self.data.dtype)
            return Column(data, self.dtype, jnp.zeros(idx.shape, bool),
                          self.dictionary, self.name)
        neg = idx < 0
        safe = jnp.where(neg, 0, idx)
        validity = None
        if fill_invalid or self.validity is not None:
            # NOTE: an all-True mask is NOT collapsed to None here — that
            # would force a device→host sync on every gather (deadly over a
            # tunneled TPU). Export paths collapse it instead.
            if self.data.shape[0] == 0:
                validity = jnp.zeros(idx.shape, bool)
            else:
                validity = jnp.take(self.valid_mask(), safe, axis=0) & ~neg
        if self.is_varbytes:
            vb = self.varbytes.take(idx)  # negatives → empty rows
            return Column(vb.lengths, self.dtype, validity, None, self.name,
                          varbytes=vb)
        data = jnp.take(self.data, safe, axis=0)
        return Column(data, self.dtype, validity, self.dictionary, self.name)

    def slice(self, start: int, stop: int) -> "Column":
        v = None if self.validity is None else self.validity[start:stop]
        if self.is_varbytes:
            vb = self.varbytes.slice(start, stop)
            return Column(vb.lengths, self.dtype, v, None, self.name,
                          varbytes=vb)
        return Column(self.data[start:stop], self.dtype, v, self.dictionary,
                      self.name)

    def rename(self, name: str) -> "Column":
        return Column(self.data, self.dtype, self.validity, self.dictionary,
                      name, varbytes=self.varbytes)

    # -- export --

    def _host_mask(self) -> Optional[np.ndarray]:
        """Validity as a host array, collapsing all-True to None."""
        if self.validity is None:
            return None
        mask = np.asarray(jax.device_get(self.validity))
        return None if mask.all() else mask

    def to_numpy(self) -> np.ndarray:
        if self.is_varbytes:
            out = self.varbytes.to_host(
                as_str=self.dtype.type != Type.BINARY)
            mask = self._host_mask()
            if mask is not None:
                out[~mask] = None
            return out
        data = np.asarray(jax.device_get(self.data))
        mask = self._host_mask()
        if self.is_string:
            out = self.dictionary[data].astype(object)
            if mask is not None:
                out[~mask] = None
            return out
        if mask is not None:
            if data.dtype.kind == "f":
                out = data.astype(data.dtype, copy=True)
                out[~mask] = np.nan
                return out
            out = data.astype(object)
            out[~mask] = None
            return out
        if self.dtype.is_temporal():
            unit = {None: "us"}.get(self.dtype.unit, None)
            unit = _unit_str(self.dtype.unit)
            if self.dtype.type == Type.TIMESTAMP:
                return data.astype(f"datetime64[{unit}]")
            if self.dtype.type == Type.DURATION:
                return data.astype(f"timedelta64[{unit}]")
        return data

    def to_pyarrow(self):
        import pyarrow as pa

        valid = self._host_mask()
        mask = None if valid is None else ~valid
        if self.is_varbytes:
            if self.dtype.type == Type.BINARY:
                return pa.array(self.varbytes.to_host(as_str=False),
                                type=pa.binary(), mask=mask)
            return pa.array(self.varbytes.to_host(), type=pa.string(),
                            mask=mask)
        data = np.asarray(jax.device_get(self.data))
        if self.is_string:
            vals = self.dictionary[data]
            return pa.array(vals, type=pa.string(),
                            mask=mask if mask is not None else None)
        return pa.array(data, mask=mask)


def as_varbytes(col: Column) -> Column:
    """Lift a string column to device-native varbytes storage. Dictionary
    columns build the (small, host-resident by definition) vocab's
    VarBytes once, then ONE device varlen gather re-materializes rows —
    no per-row host work."""
    from .strings import VarBytes

    if col.is_varbytes:
        return col
    if not col.is_string:
        raise CylonError(Code.TypeError, "as_varbytes needs a string column")
    vocab_vb = VarBytes.from_host(col.dictionary)
    vb = vocab_vb.take(col.data)
    return Column(vb.lengths, col.dtype, col.validity, None, col.name,
                  varbytes=vb)


def align_string_columns(a: Column, b: Column) -> Tuple[Column, Column]:
    """Make two string columns directly comparable on device: if either
    side is varbytes, lift both (content hashes compare with no shared
    vocabulary); two dictionary columns unify vocabularies instead."""
    if a.is_varbytes or b.is_varbytes:
        return as_varbytes(a), as_varbytes(b)
    return unify_dictionaries(a, b)


def string_key_arrays(col: Column, k_words: int = None):
    """Device key arrays standing in for one string key column.

    varbytes, short (≤ EXACT_KEY_WORDS words): the raw prefix word lanes
    + byte length — byte-EXACT equality, matching the reference's
    guarantee (join/join.cpp:648-799) with zero hashing. ``k_words``
    forces the lane count so two joined columns emit aligned lanes
    (pass max of both sides' max_words).

    varbytes, long: (h1, h2, h3, len) 96-bit content-hash identity.
    dictionary: the (already rank-preserving) codes.
    Returns (keys, valids, flags) triples ready to extend a
    join/groupby key list."""
    from .strings import EXACT_KEY_WORDS

    if col.is_varbytes:
        vb = col.varbytes
        k = vb.max_words if k_words is None else max(int(k_words),
                                                     vb.max_words)
        if k <= EXACT_KEY_WORDS:
            ks = vb.word_lanes(k) + [vb.lengths.astype(jnp.uint32)]
        else:
            ks = list(vb.hash_keys())
        return (ks, [col.validity] + [None] * (len(ks) - 1),
                [False] * len(ks))
    return [col.data], [col.validity], [True]


def unify_dictionaries(a: Column, b: Column) -> Tuple[Column, Column]:
    """Re-encode two string columns onto one shared *sorted* vocabulary so
    their codes are directly comparable on device. Host cost is O(|vocab|);
    device cost is one gather per column."""
    if not (a.is_string and b.is_string):
        raise CylonError(Code.TypeError, "unify_dictionaries needs string columns")
    if a.dictionary.shape == b.dictionary.shape and \
            (a.dictionary == b.dictionary).all():
        return a, b
    union = np.union1d(a.dictionary, b.dictionary)
    map_a = jnp.asarray(np.searchsorted(union, a.dictionary).astype(np.int32))
    map_b = jnp.asarray(np.searchsorted(union, b.dictionary).astype(np.int32))
    na = Column(jnp.take(map_a, a.data), a.dtype, a.validity, union, a.name)
    nb = Column(jnp.take(map_b, b.data), b.dtype, b.validity, union, b.name)
    return na, nb


def _dev_mask(validity: Optional[np.ndarray]):
    if validity is None:
        return None
    v = np.asarray(validity, dtype=bool)
    if v.all():
        return None
    return jnp.asarray(v)


def _np_unit(unit: str):
    from ..dtypes import TimeUnit

    return {"s": TimeUnit.SECOND, "ms": TimeUnit.MILLI,
            "us": TimeUnit.MICRO, "ns": TimeUnit.NANO}[unit]


def _unit_str(unit) -> str:
    from ..dtypes import TimeUnit

    if unit is None:
        return "us"
    return {TimeUnit.SECOND: "s", TimeUnit.MILLI: "ms",
            TimeUnit.MICRO: "us", TimeUnit.NANO: "ns"}[unit]
