"""Data type system for cylon_tpu.

Mirrors the reference's stripped-down Arrow type system (reference:
cpp/src/cylon/data_types.hpp:25-175 — `Type::type` enum, `Layout`,
factory functions `Int64()`, `Double()`, ...), mapped onto device dtypes:

* fixed-width types map 1:1 to a ``jnp.dtype`` resident in HBM;
* STRING/BINARY are VARIABLE layout and are dictionary-encoded on device
  (int32 codes in HBM + host-side sorted vocabulary) because XLA has no
  variable-length array type — see data/column.py;
* temporal types carry their unit and are stored as int32/int64 lanes.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional

import numpy as np


class Type(enum.IntEnum):
    """Reference: cpp/src/cylon/data_types.hpp `Type::type` enum."""

    BOOL = 0
    UINT8 = 1
    INT8 = 2
    UINT16 = 3
    INT16 = 4
    UINT32 = 5
    INT32 = 6
    UINT64 = 7
    INT64 = 8
    HALF_FLOAT = 9
    FLOAT = 10
    DOUBLE = 11
    STRING = 12
    BINARY = 13
    FIXED_SIZE_BINARY = 14
    DATE32 = 15
    DATE64 = 16
    TIMESTAMP = 17
    TIME32 = 18
    TIME64 = 19
    INTERVAL = 20
    DECIMAL = 21
    LIST = 22
    EXTENSION = 23
    DURATION = 24


class Layout(enum.IntEnum):
    """Reference: data_types.hpp `Layout` (FIXED_WIDTH vs VARIABLE_WIDTH)."""

    FIXED_WIDTH = 1
    VARIABLE_WIDTH = 2


class TimeUnit(enum.IntEnum):
    SECOND = 0
    MILLI = 1
    MICRO = 2
    NANO = 3


_FIXED_NP: dict[Type, np.dtype] = {
    Type.BOOL: np.dtype(np.bool_),
    Type.UINT8: np.dtype(np.uint8),
    Type.INT8: np.dtype(np.int8),
    Type.UINT16: np.dtype(np.uint16),
    Type.INT16: np.dtype(np.int16),
    Type.UINT32: np.dtype(np.uint32),
    Type.INT32: np.dtype(np.int32),
    Type.UINT64: np.dtype(np.uint64),
    Type.INT64: np.dtype(np.int64),
    Type.HALF_FLOAT: np.dtype(np.float16),
    Type.FLOAT: np.dtype(np.float32),
    Type.DOUBLE: np.dtype(np.float64),
    # temporal lanes
    Type.DATE32: np.dtype(np.int32),
    Type.DATE64: np.dtype(np.int64),
    Type.TIMESTAMP: np.dtype(np.int64),
    Type.TIME32: np.dtype(np.int32),
    Type.TIME64: np.dtype(np.int64),
    Type.DURATION: np.dtype(np.int64),
}

_NP_TO_TYPE: dict[np.dtype, Type] = {
    np.dtype(np.bool_): Type.BOOL,
    np.dtype(np.uint8): Type.UINT8,
    np.dtype(np.int8): Type.INT8,
    np.dtype(np.uint16): Type.UINT16,
    np.dtype(np.int16): Type.INT16,
    np.dtype(np.uint32): Type.UINT32,
    np.dtype(np.int32): Type.INT32,
    np.dtype(np.uint64): Type.UINT64,
    np.dtype(np.int64): Type.INT64,
    np.dtype(np.float16): Type.HALF_FLOAT,
    np.dtype(np.float32): Type.FLOAT,
    np.dtype(np.float64): Type.DOUBLE,
}


@dataclass(frozen=True)
class DataType:
    """Reference: data_types.hpp `DataType::Make(type, layout)`."""

    type: Type
    layout: Layout = Layout.FIXED_WIDTH
    unit: Optional[TimeUnit] = field(default=None)  # temporal types only
    byte_width: int = -1  # FIXED_SIZE_BINARY only

    @staticmethod
    def Make(t: Type, layout: Layout = Layout.FIXED_WIDTH) -> "DataType":
        return DataType(t, layout)

    @property
    def np_dtype(self) -> np.dtype:
        """The numpy/jnp lane dtype backing this column on device."""
        if self.type in (Type.STRING, Type.BINARY):
            return np.dtype(np.int32)  # dictionary codes
        if self.type == Type.FIXED_SIZE_BINARY:
            return np.dtype(np.int32)  # dictionary codes
        try:
            return _FIXED_NP[self.type]
        except KeyError:
            raise TypeError(f"type {self.type.name} has no device lane dtype")

    def is_numeric(self) -> bool:
        return self.type in _FIXED_NP and self.type not in (
            Type.DATE32, Type.DATE64, Type.TIMESTAMP, Type.TIME32, Type.TIME64,
            Type.DURATION,
        )

    def is_temporal(self) -> bool:
        return self.type in (Type.DATE32, Type.DATE64, Type.TIMESTAMP,
                             Type.TIME32, Type.TIME64, Type.DURATION)

    def is_var_width(self) -> bool:
        return self.layout == Layout.VARIABLE_WIDTH


# Factory functions (reference: data_types.hpp TYPE_FACTORY macros).
def Bool() -> DataType: return DataType(Type.BOOL)
def UInt8() -> DataType: return DataType(Type.UINT8)
def Int8() -> DataType: return DataType(Type.INT8)
def UInt16() -> DataType: return DataType(Type.UINT16)
def Int16() -> DataType: return DataType(Type.INT16)
def UInt32() -> DataType: return DataType(Type.UINT32)
def Int32() -> DataType: return DataType(Type.INT32)
def UInt64() -> DataType: return DataType(Type.UINT64)
def Int64() -> DataType: return DataType(Type.INT64)
def HalfFloat() -> DataType: return DataType(Type.HALF_FLOAT)
def Float() -> DataType: return DataType(Type.FLOAT)
def Double() -> DataType: return DataType(Type.DOUBLE)
def String() -> DataType: return DataType(Type.STRING, Layout.VARIABLE_WIDTH)
def Binary() -> DataType: return DataType(Type.BINARY, Layout.VARIABLE_WIDTH)
def Date32() -> DataType: return DataType(Type.DATE32)
def Date64() -> DataType: return DataType(Type.DATE64)


def Timestamp(unit: TimeUnit = TimeUnit.MICRO) -> DataType:
    return DataType(Type.TIMESTAMP, Layout.FIXED_WIDTH, unit)


def Duration(unit: TimeUnit = TimeUnit.MICRO) -> DataType:
    return DataType(Type.DURATION, Layout.FIXED_WIDTH, unit)


def FixedSizeBinary(byte_width: int) -> DataType:
    return DataType(Type.FIXED_SIZE_BINARY, Layout.FIXED_WIDTH, None, byte_width)


def from_np_dtype(dt) -> DataType:
    """Infer a cylon DataType from a numpy dtype."""
    dt = np.dtype(dt)
    if dt in _NP_TO_TYPE:
        return DataType(_NP_TO_TYPE[dt])
    if dt.kind in ("U", "S", "O"):
        return String()
    if dt.kind == "M":
        return Timestamp(TimeUnit.NANO)
    if dt.kind == "m":
        return Duration(TimeUnit.NANO)
    raise TypeError(f"unsupported numpy dtype {dt}")
