"""cylon_tpu — a TPU-native distributed dataframe engine.

A from-scratch rebuild of the capabilities of Cylon (reference mounted at
/root/reference): Arrow-style columnar tables resident in TPU HBM,
relational kernels (join, union, intersect, subtract, groupby, sort) as
vectorized JAX/Pallas programs, and the distributed shuffle mapped onto XLA
collectives (`all_to_all`, `psum`) over ICI/DCN under `shard_map` SPMD —
no MPI, no per-rank processes, one controller driving a device mesh.
"""

from .config import (CommConfig, CommType, CSVReadOptions, CSVWriteOptions,
                     LocalConfig, MPIConfig, MultiHostConfig, ParquetOptions,
                     TPUConfig)
from .context import CylonContext
from . import telemetry
from .data.column import Column
from .data.row import Row
from .data.table import Table, concat_tables, join, set_op
from .dtypes import DataType, Layout, Type
from .io.csv import read_csv, read_csv_per_rank, write_csv
from .io.parquet import read_parquet, read_parquet_per_rank, write_parquet
from .ops.groupby import AggregationOp
from .ops.join import JoinAlgorithm, JoinConfig, JoinType
from . import native
from .parallel.dist_ops import (distributed_groupby, distributed_join,
                                distributed_join_ring, distributed_set_op,
                                distributed_sort, hash_partition,
                                repartition, shuffle)
from .parallel.shard import distribute_by_key
from . import plan
from .plan import LazyTable, col
from . import resilience
from . import service
from .service import QueryService, QueryTicket
from .status import (Code, CylonDataError, CylonError, CylonPlanError,
                     CylonResourceExhausted, CylonTimeoutError,
                     CylonTransientError, Status)

__version__ = "0.1.0"

__all__ = [
    "AggregationOp", "Code", "Column", "CommConfig", "CommType",
    "CSVReadOptions", "CSVWriteOptions", "CylonContext",
    "CylonDataError", "CylonError", "CylonPlanError",
    "CylonResourceExhausted", "CylonTimeoutError",
    "CylonTransientError",
    "DataType", "JoinAlgorithm", "JoinConfig", "JoinType", "Layout",
    "LazyTable", "LocalConfig", "MPIConfig", "MultiHostConfig",
    "ParquetOptions", "QueryService", "QueryTicket", "Row", "col",
    "plan", "resilience", "service",
    "Status", "TPUConfig", "Table", "Type", "concat_tables",
    "distribute_by_key", "distributed_groupby", "distributed_join",
    "distributed_join_ring", "distributed_set_op",
    "distributed_sort", "hash_partition", "join", "native", "read_csv",
    "read_csv_per_rank",
    "read_parquet", "read_parquet_per_rank", "repartition", "set_op",
    "shuffle", "telemetry",
    "write_csv", "write_parquet",
]
